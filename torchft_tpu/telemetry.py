"""Tracing, timing, metrics export, and the collective flight recorder.

TPU-native translation of the reference's observability subsystem:

- ``trace_span(name)``: the reference wraps every hot-path method in
  ``torch.profiler.record_function("torchft::manager::*")`` (reference:
  manager.py:379,430,574,586,600,650,671,705,760,786,793 and
  local_sgd.py:277,293,375,390,411). Here the same span names feed
  ``jax.profiler.TraceAnnotation`` so they appear in XLA/perfetto traces,
  and wall-time is accumulated in a process-local registry that tests and
  metrics lines can read without a trace viewer.
- ``timeit(name)``: checkpoint-transfer wall-time logging (reference:
  http_transport.py:31-36, pg_transport.py:80-85 ``_timeit``).
- ``MetricsLogger``: per-step scalar export as JSONL (the reference emits
  TensorBoard scalars incl. num_participants/current_step,
  train_diloco.py:219-232; TensorBoard isn't a dependency here so the
  sink is a plain JSONL file any plotter can consume).
- ``trace_window(step)``: scheduled profiler windows for train scripts
  (reference: train_ddp.py:169-174 runs torch.profiler.profile with a
  schedule exporting Chrome traces). Gated by env vars so production runs
  pay nothing.
- ``FlightRecorder``: ring buffer of recent collective ops dumped to disk
  on PG abort when ``TORCHFT_TRIGGER_FR_ON_ABORT=true`` (reference: the
  NCCL flight-recorder dump via named pipe, process_group.py:89-108,
  812-813).

Everything degrades to near-zero overhead: spans are two monotonic reads
and a dict update; the recorder is a deque append; metrics/trace windows
are off unless their env vars are set.
"""

from __future__ import annotations

import atexit
import bisect
import collections
import contextlib
import functools
import json
import os
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional

__all__ = [
    "trace_span",
    "traced",
    "span_stats",
    "span_percentiles",
    "reset_span_stats",
    "timeit",
    "timed",
    "MetricsLogger",
    "get_metrics_logger",
    "EventLog",
    "get_event_log",
    "reset_event_log",
    "set_default_replica_id",
    "trace_window",
    "reset_trace_window",
    "FlightRecorder",
    "flight_recorder",
]


# ----------------------------------------------------------------------
# Spans
# ----------------------------------------------------------------------

# Fixed log-spaced histogram boundaries shared by every span: 1µs doubling
# up to ~137s (28 finite buckets + one overflow). Precomputed once so the
# hot-path cost is a bisect over a tuple plus a list increment — no
# allocation per observation.
_HIST_BOUNDS: tuple = tuple(1e-6 * (2.0 ** i) for i in range(28))
_HIST_NBUCKETS = len(_HIST_BOUNDS) + 1


class _SpanStats:
    """Process-local span accounting: count + total/max wall seconds, plus a
    fixed-bucket latency histogram per span (log-spaced; p50/p95/p99 come
    from :func:`span_percentiles`)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._stats: Dict[str, Dict[str, float]] = {}
        self._hist: Dict[str, List[int]] = {}

    def add(self, name: str, dt: float) -> None:
        with self._lock:
            s = self._stats.get(name)
            if s is None:
                s = self._stats[name] = {
                    "count": 0, "total_s": 0.0, "max_s": 0.0
                }
                self._hist[name] = [0] * _HIST_NBUCKETS
            s["count"] += 1
            s["total_s"] += dt
            if dt > s["max_s"]:
                s["max_s"] = dt
            self._hist[name][bisect.bisect_left(_HIST_BOUNDS, dt)] += 1

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {k: dict(v) for k, v in self._stats.items()}

    def hist_snapshot(self) -> Dict[str, List[int]]:
        with self._lock:
            return {k: list(v) for k, v in self._hist.items()}

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()
            self._hist.clear()


_SPAN_STATS = _SpanStats()


def span_stats() -> Dict[str, Dict[str, float]]:
    """Snapshot of per-span {count, total_s, max_s} accumulated so far."""
    return _SPAN_STATS.snapshot()


def _hist_percentile(buckets: List[int], q: float) -> float:
    """Upper-bound estimate of the q-quantile from bucket counts.

    Edge cases (regression-tested): all-zero buckets -> 0.0 (no samples is
    not "the first boundary"); a run of empty leading buckets must never
    satisfy the target (``cum >= target`` holds vacuously at target <= 0,
    which used to report bucket 0's bound for q ~ 0 even when every sample
    sat in a much higher bucket); a single occupied bucket returns that
    bucket's upper bound for every q."""
    total = sum(buckets)
    if total == 0:
        return 0.0
    target = q * total
    cum = 0
    for i, c in enumerate(buckets):
        if c == 0:
            continue  # an empty prefix can't contain any quantile
        cum += c
        if cum >= target:
            if i < len(_HIST_BOUNDS):
                return _HIST_BOUNDS[i]
            # Overflow bucket: no upper bound; report the last boundary.
            return _HIST_BOUNDS[-1]
    return _HIST_BOUNDS[-1]


def span_percentiles(
    name: Optional[str] = None,
) -> Dict[str, Dict[str, float]]:
    """Per-span latency percentiles {p50, p95, p99} (seconds), estimated
    from the fixed log-spaced histogram (each value is the upper boundary
    of the bucket containing that quantile — an over-estimate within one
    2x bucket). Pass ``name`` to restrict to one span."""
    hist = _SPAN_STATS.hist_snapshot()
    if name is not None:
        hist = {name: hist[name]} if name in hist else {}
    return {
        k: {
            "p50": _hist_percentile(v, 0.50),
            "p95": _hist_percentile(v, 0.95),
            "p99": _hist_percentile(v, 0.99),
        }
        for k, v in hist.items()
    }


def reset_span_stats() -> None:
    _SPAN_STATS.reset()


def observe_span(name: str, dt: float) -> None:
    """Record an externally-timed duration into the span histogram.

    For call sites that already hold a wall-clock delta (e.g. a process
    group timing its own collective) and want it in the same
    ``span_stats``/``span_percentiles`` tables as ``span()``-wrapped
    regions, without nesting a context manager."""
    _SPAN_STATS.add(name, dt)


class _ByteCounters:
    """Process-local byte accounting (e.g. data-plane wire traffic).

    The quantized collectives exist to cut wire bytes; these counters
    make the cut MEASURABLE on any backend (the reference proves its
    codec the same way — by byte math, torchft/quantization.py) instead
    of inferring it from tunnel-bound wall times."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}

    def add(self, name: str, n: int) -> None:
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + int(n)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()


_BYTE_COUNTERS = _ByteCounters()


def add_bytes(name: str, n: int) -> None:
    """Accumulates ``n`` bytes under ``name`` (cheap; lock + dict add)."""
    _BYTE_COUNTERS.add(name, n)


def byte_stats() -> Dict[str, int]:
    """Snapshot of per-counter byte totals accumulated so far."""
    return _BYTE_COUNTERS.snapshot()


def reset_byte_stats() -> None:
    _BYTE_COUNTERS.reset()


def _jax_annotation(name: str) -> Any:
    """TraceAnnotation ctx if jax's profiler is importable, else None."""
    try:
        from jax.profiler import TraceAnnotation

        return TraceAnnotation(name)
    except Exception:
        return None


@contextlib.contextmanager
def trace_span(name: str) -> Iterator[None]:
    """Named hot-path span: shows up in jax profiler traces AND in
    :func:`span_stats`. Span names mirror the reference's
    ``torchft::manager::*`` convention so traces are comparable."""
    ann = _jax_annotation(name)
    t0 = time.monotonic()
    if ann is not None:
        try:
            ann.__enter__()
        except Exception:
            ann = None
    try:
        yield
    finally:
        if ann is not None:
            try:
                ann.__exit__(None, None, None)
            except Exception:
                pass
        _SPAN_STATS.add(name, time.monotonic() - t0)


def traced(name: str) -> Callable:
    """Decorator form of :func:`trace_span` — wraps the whole function body
    in the named span."""

    def deco(fn: Callable) -> Callable:
        @functools.wraps(fn)
        def wrapper(*args: Any, **kwargs: Any) -> Any:
            with trace_span(name):
                return fn(*args, **kwargs)

        return wrapper

    return deco


def timed(name: str) -> Callable:
    """Decorator form of :func:`timeit` — logs the function's wall-time."""

    def deco(fn: Callable) -> Callable:
        @functools.wraps(fn)
        def wrapper(*args: Any, **kwargs: Any) -> Any:
            with timeit(name):
                return fn(*args, **kwargs)

        return wrapper

    return deco


@contextlib.contextmanager
def timeit(name: str, logger: Optional[Any] = None) -> Iterator[dict]:
    """Logs the wall-time of a block (checkpoint transfers, heals).
    ``logger`` needs an ``info(msg)`` method; defaults to module logging.
    Exceptions from the block propagate (and are still timed).

    Yields a dict whose ``elapsed_s`` is filled when the block exits, so
    a caller needing the duration shares THIS clock instead of running a
    second one alongside."""
    t0 = time.monotonic()
    holder: dict = {"elapsed_s": None}
    try:
        yield holder
    finally:
        # No return/break in this finally: it would swallow in-flight
        # exceptions (PEP 601) — a failed heal must stay failed.
        dt = time.monotonic() - t0
        holder["elapsed_s"] = dt
        _SPAN_STATS.add(name, dt)
        msg = f"{name} took {dt:.3f}s"
        logged = False
        if logger is not None:
            try:
                logger.info(msg)
                logged = True
            except Exception:
                pass
        if not logged:
            import logging

            logging.getLogger("torchft_tpu").info(msg)


# ----------------------------------------------------------------------
# Metrics (JSONL scalar sink)
# ----------------------------------------------------------------------

class MetricsLogger:
    """Appends one JSON line per ``log`` call: {"step": N, "ts": ..., **scalars}.

    The reference exports TensorBoard scalars (num_participants,
    current_step, loss; train_diloco.py:219-232). JSONL keeps the same
    information with zero dependencies; `jq`/pandas/TensorBoard ingest it
    trivially.
    """

    def __init__(self, path: str) -> None:
        self._path = path
        self._lock = threading.Lock()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        # One append-mode handle for the logger's lifetime: reopening per
        # log() costs a syscall-heavy open/close on every train step.
        self._fh: Optional[Any] = open(path, "a")
        atexit.register(self.close)

    def log(self, step: int, **scalars: Any) -> None:
        rec: Dict[str, Any] = {"step": int(step), "ts": time.time()}
        for k, v in scalars.items():
            try:
                rec[k] = float(v)
            except (TypeError, ValueError):
                rec[k] = str(v)
        line = json.dumps(rec)
        with self._lock:
            if self._fh is None:  # closed: drop rather than raise mid-step
                return
            self._fh.write(line + "\n")
            self._fh.flush()

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                finally:
                    self._fh = None


_METRICS_LOGGER: Optional[MetricsLogger] = None
_METRICS_LOCK = threading.Lock()


def get_metrics_logger() -> Optional[MetricsLogger]:
    """Process-wide metrics sink, enabled by ``TORCHFT_METRICS_FILE``.
    Returns None (and costs one env read) when unset."""
    global _METRICS_LOGGER
    path = os.environ.get("TORCHFT_METRICS_FILE", "")
    if not path:
        return None
    with _METRICS_LOCK:
        if _METRICS_LOGGER is None or _METRICS_LOGGER._path != path:
            if _METRICS_LOGGER is not None:
                _METRICS_LOGGER.close()
            _METRICS_LOGGER = MetricsLogger(path)
        return _METRICS_LOGGER


# ----------------------------------------------------------------------
# Event journal (structured step-event JSONL)
# ----------------------------------------------------------------------

class EventLog:
    """Structured step-event journal: one JSON line per event,
    ``{ts, replica_id, step, event, **attrs}``.

    Where :class:`MetricsLogger` records per-step scalars, the journal
    records the *sequence* of control-plane events (quorum start/ready,
    heal start/done, allreduce issue/complete, commit verdicts, PG
    configure/abort, checkpoint send/recv) with enough attributes that
    ``tools/obs_report.py`` can merge journals from every replica into a
    step-aligned timeline. Lock-cheap: one json.dumps + one os.write per
    event, and events only fire at control-plane frequency (a handful per
    step), never per-microbatch.

    The journal file is opened ``O_APPEND`` and each record is a *single*
    ``os.write`` of one complete line: POSIX atomic appends mean several
    replica processes can share one journal file (``TORCHFT_JOURNAL_FILE``
    pointing everyone at the same path) without interleaving partial
    lines. The in-process lock still serializes threads sharing this
    EventLog instance.
    """

    def __init__(self, path: str, replica_id: Optional[str] = None) -> None:
        self._path = path
        self._lock = threading.Lock()
        if replica_id is None:
            replica_id = os.environ.get("TORCHFT_REPLICA_ID") or (
                _DEFAULT_REPLICA_ID
                or os.environ.get("REPLICA_GROUP_ID", f"pid{os.getpid()}")
            )
        self.replica_id = replica_id
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._fd: int = os.open(
            path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644
        )
        atexit.register(self.close)

    def emit(
        self,
        event: str,
        step: Optional[int] = None,
        replica_id: Optional[str] = None,
        trace: Optional[str] = None,
        **attrs: Any,
    ) -> None:
        rec: Dict[str, Any] = {
            "ts": time.time(),
            "replica_id": self.replica_id if replica_id is None else replica_id,
            "step": None if step is None else int(step),
            "event": event,
        }
        if trace:
            rec["trace"] = trace
        if attrs:
            rec["attrs"] = attrs
        try:
            line = json.dumps(rec, default=str)
        except Exception:
            return  # never let journaling break the train loop
        data = (line + "\n").encode("utf-8", errors="replace")
        with self._lock:
            if self._fd < 0:
                return
            try:
                os.write(self._fd, data)
            except Exception:
                pass

    def close(self) -> None:
        with self._lock:
            if self._fd >= 0:
                try:
                    os.close(self._fd)
                finally:
                    self._fd = -1


_EVENT_LOG: Optional[EventLog] = None
_EVENT_LOCK = threading.Lock()
_DEFAULT_REPLICA_ID: Optional[str] = None


def set_default_replica_id(replica_id: str) -> None:
    """Pins the ``replica_id`` stamped on journal events that don't pass
    one explicitly (process-group / transport call sites). The Manager
    calls this with its own id so every event from its process folds onto
    one timeline row in ``tools/obs_report.py`` — otherwise those events
    fall back to ``REPLICA_GROUP_ID``, which need not match the trainer's
    chosen manager id. ``TORCHFT_REPLICA_ID`` still wins."""
    global _DEFAULT_REPLICA_ID
    _DEFAULT_REPLICA_ID = replica_id
    with _EVENT_LOCK:
        if _EVENT_LOG is not None and not os.environ.get("TORCHFT_REPLICA_ID"):
            _EVENT_LOG.replica_id = replica_id


def _journal_path_from_env() -> str:
    """Journal destination: ``TORCHFT_JOURNAL_FILE`` wins; else
    ``TORCHFT_JOURNAL_DIR`` derives a per-process filename. Empty when
    neither is set (journal disabled)."""
    path = os.environ.get("TORCHFT_JOURNAL_FILE", "")
    if path:
        return path
    d = os.environ.get("TORCHFT_JOURNAL_DIR", "")
    if not d:
        return ""
    rid = os.environ.get("REPLICA_GROUP_ID", "x")
    rank = os.environ.get("RANK", "0")
    return os.path.join(d, f"journal_replica{rid}_rank{rank}_{os.getpid()}.jsonl")


def get_event_log() -> Optional[EventLog]:
    """Process-wide event journal, enabled by ``TORCHFT_JOURNAL_FILE`` or
    ``TORCHFT_JOURNAL_DIR``. Returns None (two env reads, no allocation)
    when neither is set — callers guard with ``if log is not None`` so the
    disabled hot path stays free."""
    global _EVENT_LOG
    path = _journal_path_from_env()
    if not path:
        return None
    with _EVENT_LOCK:
        if _EVENT_LOG is None or _EVENT_LOG._path != path:
            if _EVENT_LOG is not None:
                _EVENT_LOG.close()
            _EVENT_LOG = EventLog(path)
        return _EVENT_LOG


def reset_event_log() -> None:
    """Closes and forgets the cached journal and the pinned default
    replica id (tests / re-exec)."""
    global _EVENT_LOG, _DEFAULT_REPLICA_ID
    with _EVENT_LOCK:
        if _EVENT_LOG is not None:
            _EVENT_LOG.close()
        _EVENT_LOG = None
        _DEFAULT_REPLICA_ID = None


# ----------------------------------------------------------------------
# Scheduled profiler windows for train scripts
# ----------------------------------------------------------------------

_TRACE_STATE = {"active": False, "done": False, "stop_at": -1}
_TRACE_LOCK = threading.Lock()


def _trace_stop() -> None:
    try:
        import jax

        jax.profiler.stop_trace()
    except Exception:
        pass
    _TRACE_STATE["active"] = False
    _TRACE_STATE["done"] = True


def trace_window(step: int) -> None:
    """Call once per train step. When ``TORCHFT_TRACE_DIR`` is set, starts a
    ``jax.profiler`` trace once the step counter reaches
    ``TORCHFT_TRACE_START`` (default 5; ``>=`` so a heal that jumps the
    counter past it still records) and stops it ``TORCHFT_TRACE_COUNT``
    (default 3) steps later, writing a perfetto/XPlane trace under the dir.
    An atexit hook closes a window still open when the run ends early.
    No-op otherwise (reference: train_ddp.py:169-174 scheduled windows)."""
    trace_dir = os.environ.get("TORCHFT_TRACE_DIR", "")
    if not trace_dir:
        return
    start = int(os.environ.get("TORCHFT_TRACE_START", "5"))
    count = int(os.environ.get("TORCHFT_TRACE_COUNT", "3"))
    with _TRACE_LOCK:
        if (
            not _TRACE_STATE["active"]
            and not _TRACE_STATE["done"]
            and step >= start
        ):
            try:
                import atexit

                import jax

                jax.profiler.start_trace(trace_dir)
                _TRACE_STATE["active"] = True
                _TRACE_STATE["stop_at"] = step + count
                atexit.register(_trace_atexit)
            except Exception:
                _TRACE_STATE["done"] = True
        elif _TRACE_STATE["active"] and step >= _TRACE_STATE["stop_at"]:
            _trace_stop()


def _trace_atexit() -> None:
    with _TRACE_LOCK:
        if _TRACE_STATE["active"]:
            _trace_stop()


def reset_trace_window() -> None:
    """Re-arms the one-shot profiler window: stops a trace still running
    and clears the done flag so the next :func:`trace_window` call can
    schedule a fresh window (tests, multi-run processes)."""
    with _TRACE_LOCK:
        if _TRACE_STATE["active"]:
            _trace_stop()
        _TRACE_STATE["active"] = False
        _TRACE_STATE["done"] = False
        _TRACE_STATE["stop_at"] = -1


# ----------------------------------------------------------------------
# Flight recorder
# ----------------------------------------------------------------------

_DUMP_LOCK = threading.Lock()
_DUMP_COUNT = 0


class FlightRecorder:
    """Ring buffer of recent collective operations, dumped to a JSON file
    when the PG aborts and ``TORCHFT_TRIGGER_FR_ON_ABORT`` is truthy
    (reference: NCCL flight recorder, process_group.py:89-108,812-813).

    Each record: seq, op, tag, nbytes, rank, world, status
    (issued/ok/error), and wall timestamps. The dump answers "what was in
    flight when the ring wedged" without a debugger attached.
    """

    def __init__(self, capacity: int = 256) -> None:
        self._lock = threading.Lock()
        self._buf: collections.deque = collections.deque(maxlen=capacity)
        # seq -> record index alongside the deque so complete() is O(1)
        # instead of a reverse scan of the ring.
        self._by_seq: Dict[int, Dict[str, Any]] = {}
        self._seq = 0

    def record(
        self,
        op: str,
        tag: str = "",
        nbytes: int = 0,
        rank: int = -1,
        world: int = -1,
    ) -> int:
        """Records an issued op; returns its seq for later completion."""
        with self._lock:
            self._seq += 1
            seq = self._seq
            rec = {
                "seq": seq,
                "op": op,
                "tag": tag,
                "nbytes": int(nbytes),
                "rank": rank,
                "world": world,
                "status": "issued",
                "t_issued": time.time(),
            }
            if len(self._buf) == self._buf.maxlen:
                # Deque is full: the append below evicts the oldest record;
                # drop it from the index so the dict can't grow unbounded.
                self._by_seq.pop(self._buf[0]["seq"], None)
            self._buf.append(rec)
            self._by_seq[seq] = rec
            return seq

    def complete(self, seq: int, error: Optional[str] = None) -> None:
        with self._lock:
            rec = self._by_seq.get(seq)
            if rec is not None:
                rec["status"] = "error" if error else "ok"
                rec["t_done"] = time.time()
                if error:
                    rec["error"] = error[:500]

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(r) for r in self._buf]

    def dump(self, reason: str, path: Optional[str] = None) -> str:
        """Writes the buffer to ``path`` (default
        ``$TORCHFT_FR_DIR or /tmp/torchft_tpu_fr_<pid>.json``); returns the
        path written."""
        if path is None:
            d = os.environ.get("TORCHFT_FR_DIR", "/tmp")
            # Timestamp (unique across process restarts with recycled
            # PIDs, e.g. PID 1 in a container) + per-process counter
            # (unique within a millisecond): a later dump can never
            # overwrite the evidence from the abort that mattered.
            with _DUMP_LOCK:
                global _DUMP_COUNT
                _DUMP_COUNT += 1
                n = _DUMP_COUNT
            path = os.path.join(
                d,
                f"torchft_tpu_fr_{os.getpid()}_"
                f"{int(time.time() * 1000)}_{n:03d}.json",
            )
        payload = {
            "reason": reason,
            "pid": os.getpid(),
            "ts": time.time(),
            "ops": self.snapshot(),
        }
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            json.dump(payload, f, indent=1)
        return path

    def maybe_dump_on_abort(self, reason: str) -> Optional[str]:
        """Dump iff TORCHFT_TRIGGER_FR_ON_ABORT is truthy (the reference's
        exact gate, process_group.py:91)."""
        flag = os.environ.get("TORCHFT_TRIGGER_FR_ON_ABORT", "").lower()
        if flag not in ("1", "true", "yes", "on"):
            return None
        try:
            return self.dump(reason)
        except Exception:
            return None


flight_recorder = FlightRecorder()
