"""Timeout engine for futures, device arrays, and context blocks.

Analog of the reference's ``torchft/futures.py``: a singleton background
asyncio event loop schedules timeouts for pending futures
(``future_timeout``/``future_wait``), for blocks of host code
(``context_timeout``), and for in-flight JAX device work
(``array_timeout`` — the CUDA ``stream_timeout`` analog: fires a callback if
a set of arrays hasn't become ready in time). A watchdog thread kills the
process if the event loop itself wedges for more than
``TORCHFT_WATCHDOG_TIMEOUT_SEC`` (default 30s), mirroring futures.py:97-120.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import os
import sys
import threading
import time
from contextlib import contextmanager
from typing import Any, Callable, Generator, Optional, Sequence

from torchft_tpu import knobs

WATCHDOG_INTERVAL = 0.1


class _TimeoutManager:
    """Singleton scheduling engine (lazy-started)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._watchdog: Optional[threading.Thread] = None
        self._heartbeat = 0.0
        self._watchdog_enabled = False

    def _ensure_started(self) -> asyncio.AbstractEventLoop:
        with self._lock:
            if self._loop is None:
                self._loop = asyncio.new_event_loop()
                self._thread = threading.Thread(
                    target=self._run, name="torchft-timeout-manager", daemon=True
                )
                self._thread.start()
            return self._loop

    def _run(self) -> None:
        assert self._loop is not None
        asyncio.set_event_loop(self._loop)

        async def heartbeat() -> None:
            while True:
                self._heartbeat = time.monotonic()
                await asyncio.sleep(WATCHDOG_INTERVAL)

        self._loop.create_task(heartbeat())
        self._loop.run_forever()

    def start_watchdog(self) -> None:
        """Starts the thread that exits the process if the timeout loop is
        stuck (it is the last line of defense: if it can't run, nothing can
        cancel a wedged collective)."""
        self._ensure_started()  # the loop IS the heartbeat source
        with self._lock:
            if self._watchdog is not None:
                return
            self._watchdog_enabled = True
            self._heartbeat = time.monotonic()
            self._watchdog = threading.Thread(
                target=self._watchdog_loop, name="torchft-watchdog", daemon=True
            )
            self._watchdog.start()

    def _watchdog_loop(self) -> None:
        timeout = knobs.get_float("TORCHFT_WATCHDOG_TIMEOUT_SEC")
        while self._watchdog_enabled:
            time.sleep(timeout / 2)
            age = time.monotonic() - self._heartbeat
            if age > timeout:
                print(
                    f"torchft watchdog: timeout event loop stuck for {age:.1f}s; "
                    "exiting process",
                    file=sys.stderr,
                    flush=True,
                )
                os._exit(1)

    def stop_watchdog(self) -> None:
        self._watchdog_enabled = False
        self._watchdog = None

    def call_later(self, delay: float, fn: Callable[[], None]) -> Callable[[], None]:
        """Schedules fn on the engine loop; returns a cancel function."""
        loop = self._ensure_started()
        handle_box: list = []

        def _schedule() -> None:
            handle_box.append(loop.call_later(delay, fn))

        loop.call_soon_threadsafe(_schedule)

        def cancel() -> None:
            def _cancel() -> None:
                if handle_box:
                    handle_box[0].cancel()

            loop.call_soon_threadsafe(_cancel)

        return cancel


_TIMEOUT_MANAGER = _TimeoutManager()


def future_timeout(
    fut: concurrent.futures.Future, timeout: float
) -> concurrent.futures.Future:
    """Returns a future that mirrors ``fut`` but fails with TimeoutError if
    ``fut`` doesn't complete within ``timeout`` seconds (reference:
    futures.py ``future_timeout``)."""
    out: concurrent.futures.Future = concurrent.futures.Future()

    def on_timeout() -> None:
        if not out.done():
            out.set_exception(
                TimeoutError(f"future timed out after {timeout}s")
            )

    cancel = _TIMEOUT_MANAGER.call_later(timeout, on_timeout)

    def on_done(f: concurrent.futures.Future) -> None:
        cancel()
        if out.done():
            return
        exc = f.exception()
        if exc is not None:
            out.set_exception(exc)
        else:
            out.set_result(f.result())

    fut.add_done_callback(on_done)
    return out


def future_wait(fut: concurrent.futures.Future, timeout: float) -> Any:
    """Waits for ``fut`` up to ``timeout`` seconds; raises TimeoutError."""
    try:
        return fut.result(timeout)
    except concurrent.futures.TimeoutError as e:
        raise TimeoutError(f"future did not complete in {timeout}s") from e


@contextmanager
def context_timeout(
    callback: Callable[[], None], timeout: float
) -> Generator[None, None, None]:
    """Runs ``callback`` if the with-block doesn't finish within ``timeout``
    (reference: futures.py ``context_timeout``; used to abort a process group
    wedged inside a collective)."""
    cancel = _TIMEOUT_MANAGER.call_later(timeout, callback)
    try:
        yield
    finally:
        cancel()


def array_timeout(
    arrays: Sequence[Any], callback: Callable[[], None], timeout: float
) -> None:
    """Fires ``callback`` unless all JAX ``arrays`` become ready within
    ``timeout`` seconds — the analog of the reference's CUDA
    ``stream_timeout`` (futures.py:193-212): detect a device computation
    (e.g. a collective riding ICI) that will never complete, and abort at
    the transport layer rather than inside XLA."""
    done = threading.Event()

    def waiter() -> None:
        try:
            import jax

            jax.block_until_ready(list(arrays))
        except Exception:  # noqa: BLE001 - readiness probe only
            pass
        finally:
            done.set()

    threading.Thread(target=waiter, daemon=True).start()

    def on_timeout() -> None:
        if not done.is_set():
            callback()

    _TIMEOUT_MANAGER.call_later(timeout, on_timeout)


def start_watchdog() -> None:
    _TIMEOUT_MANAGER.start_watchdog()


def stop_watchdog() -> None:
    _TIMEOUT_MANAGER.stop_watchdog()
