"""Step-level MFU/roofline accounting — the one FLOP-counting module.

Three layers, shared by every consumer (bench.py, tools/mfu_sweep.py,
tools/mfu_cost_rank.py, the trainers under ``TORCHFT_PERF``):

- **Analytic estimate**: :func:`flops_per_step` is the standard 6ND
  dense estimate plus the causal-attention term — model-shape math, no
  compile needed (what bench.py's headline ``mfu_est`` always used).
- **Measured cost**: :func:`compiled_cost` reads XLA's own cost analysis
  (flops, bytes accessed) plus memory analysis (temp/arg/output bytes)
  off a lowered+compiled executable, tolerant of backends that return
  lists or partial keys. Known caveat (tools/mfu_cost_rank.py): XLA
  counts a ``lax.scan`` body ONCE, so scanned programs under-report; the
  rank tool applies its own correction.
- **Peaks/roofline**: bf16 peak TFLOP/s and HBM GB/s per TPU
  generation, and :func:`roofline` combining achieved FLOP/s with the
  program's arithmetic intensity into an MFU and an attainable-roofline
  fraction.

``record_jit_cost`` is the trainer entry point: gated on the
``TORCHFT_PERF`` knob, it lowers the jitted step once at compile time,
stores the cost in a process-local registry, and journals a
``perf_model`` event so tools/perf_report.py can put MFU next to ms.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from . import knobs
from .telemetry import get_event_log

__all__ = [
    "PEAK_BF16_TFLOPS",
    "PEAK_HBM_GBPS",
    "peak_tflops",
    "peak_hbm_gbps",
    "flops_per_step",
    "compiled_cost",
    "perf_enabled",
    "record_jit_cost",
    "step_metrics",
    "get_step_cost",
    "reset_step_costs",
    "roofline",
]

# Published bf16 peak per chip, by device_kind substring (first match
# wins, so "v5p" must precede "v5"). Same table bench.py shipped since
# r2; kept here so there is exactly one copy.
PEAK_BF16_TFLOPS = [
    ("v6", 918.0),  # Trillium
    ("v5p", 459.0),
    ("v5", 197.0),  # v5e / v5 lite
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 45.0),
]

# Published HBM bandwidth per chip (GB/s), for the roofline's memory
# ceiling. Same matching rules as the TFLOP table.
PEAK_HBM_GBPS = [
    ("v6", 1640.0),
    ("v5p", 2765.0),
    ("v5", 819.0),
    ("v4", 1228.0),
    ("v3", 900.0),
    ("v2", 700.0),
]


def _lookup(table, device_kind: str) -> Optional[float]:
    kind = (device_kind or "").lower()
    for key, val in table:
        if key in kind:
            return val
    return None


def peak_tflops(device_kind: str) -> Optional[float]:
    """bf16 peak TFLOP/s for a jax ``device_kind``; None off-TPU (CPU
    proxy runs report raw FLOP/s but no MFU — there is no honest peak)."""
    return _lookup(PEAK_BF16_TFLOPS, device_kind)


def peak_hbm_gbps(device_kind: str) -> Optional[float]:
    """HBM GB/s for a jax ``device_kind``; None off-TPU."""
    return _lookup(PEAK_HBM_GBPS, device_kind)


def flops_per_step(n_params: int, cfg, B: int, S: int) -> float:
    """Standard 6ND estimate + causal attention term (fwd+bwd)."""
    dense = 6.0 * n_params * B * S
    attn = 6.0 * cfg.num_layers * B * S * S * cfg.num_heads * cfg.head_dim
    return dense + attn


def compiled_cost(compiled) -> Dict[str, Any]:
    """flops/bytes from XLA cost analysis + temp bytes from memory
    analysis, tolerant of backends that return lists or partial keys."""
    out: Dict[str, Any] = {}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        out["flops"] = float(ca.get("flops", 0.0))
        out["bytes_accessed"] = float(ca.get("bytes accessed", 0.0))
    except Exception as e:  # noqa: BLE001 - record, don't die
        out["cost_error"] = str(e)[:120]
    try:
        ma = compiled.memory_analysis()
        out["temp_bytes"] = int(getattr(ma, "temp_size_in_bytes", 0))
        out["argument_bytes"] = int(
            getattr(ma, "argument_size_in_bytes", 0)
        )
        out["output_bytes"] = int(getattr(ma, "output_size_in_bytes", 0))
    except Exception as e:  # noqa: BLE001
        out["memory_error"] = str(e)[:120]
    return out


def roofline(
    flops: float,
    bytes_accessed: float,
    dt_s: float,
    device_kind: str,
    n_devices: int = 1,
) -> Dict[str, Any]:
    """Achieved FLOP/s vs the device roofline.

    ``mfu`` is achieved / bf16-peak. ``roofline_frac`` is achieved /
    min(peak_flops, AI * peak_bw) — 1.0 means the step runs at whichever
    ceiling (compute or memory) its arithmetic intensity allows, so a
    low MFU with a high roofline_frac says "memory-bound, not slow".
    Off-TPU both are None; tflops_per_s is always reported."""
    out: Dict[str, Any] = {
        "tflops_per_s": (flops / dt_s / 1e12) if dt_s > 0 else None,
        "mfu": None,
        "roofline_frac": None,
        "ai": (flops / bytes_accessed) if bytes_accessed > 0 else None,
    }
    peak_tf = peak_tflops(device_kind)
    if dt_s <= 0 or peak_tf is None:
        return out
    achieved = flops / dt_s  # flops/s
    peak_flops_s = peak_tf * 1e12 * n_devices
    out["mfu"] = achieved / peak_flops_s
    bw = peak_hbm_gbps(device_kind)
    if bw is not None and out["ai"] is not None:
        attainable = min(peak_flops_s, out["ai"] * bw * 1e9 * n_devices)
        if attainable > 0:
            out["roofline_frac"] = achieved / attainable
    return out


# Process-local registry of compile-time step costs, keyed by the name
# the trainer registered ("ddp_step", "diloco_inner_step", ...).
_COST_LOCK = threading.Lock()
_STEP_COSTS: Dict[str, Dict[str, Any]] = {}


def perf_enabled() -> bool:
    return knobs.get_bool("TORCHFT_PERF")


def get_step_cost(name: str) -> Optional[Dict[str, Any]]:
    with _COST_LOCK:
        rec = _STEP_COSTS.get(name)
        return dict(rec) if rec else None


def reset_step_costs() -> None:
    with _COST_LOCK:
        _STEP_COSTS.clear()


def record_jit_cost(
    name: str,
    jitted_fn,
    *args,
    tokens_per_step: Optional[int] = None,
    force: bool = False,
    **kwargs,
) -> Optional[Dict[str, Any]]:
    """Lower+compile ``jitted_fn`` on ``args`` once (the shapes the
    trainer warms up with, so XLA's compile cache absorbs the cost),
    record its FLOPs/bytes, and journal a ``perf_model`` event.

    No-op returning None unless the ``TORCHFT_PERF`` knob is set (or
    ``force``): drills and benches that don't ask for MFU pay nothing.
    Failures degrade to None — perf accounting must never kill a
    trainer."""
    if not (force or perf_enabled()):
        return None
    try:
        import jax

        compiled = jitted_fn.lower(*args, **kwargs).compile()
        cost = compiled_cost(compiled)
        devs = jax.devices()
        rec: Dict[str, Any] = {
            "name": name,
            "device_kind": devs[0].device_kind if devs else "unknown",
            "n_devices": len(devs),
            "tokens_per_step": tokens_per_step,
            **cost,
        }
    except Exception:  # noqa: BLE001 - accounting is best-effort
        return None
    with _COST_LOCK:
        _STEP_COSTS[name] = rec
    log = get_event_log()
    if log is not None:
        log.emit(
            "perf_model",
            name=name,
            flops=rec.get("flops"),
            bytes_accessed=rec.get("bytes_accessed"),
            temp_bytes=rec.get("temp_bytes"),
            device_kind=rec["device_kind"],
            n_devices=rec["n_devices"],
            tokens_per_step=tokens_per_step,
        )
    return rec


def step_metrics(name: str, dt_s: float) -> Optional[Dict[str, Any]]:
    """MFU/roofline for one wall-clock step of the registered program;
    None when the cost was never recorded (knob off, or lowering
    failed). CPU-proxy honesty: off-TPU ``mfu`` stays None and callers
    should print the raw TFLOP/s instead of inventing a peak."""
    rec = get_step_cost(name)
    if rec is None or dt_s <= 0:
        return None
    flops = float(rec.get("flops") or 0.0)
    out = roofline(
        flops,
        float(rec.get("bytes_accessed") or 0.0),
        dt_s,
        rec.get("device_kind", ""),
        int(rec.get("n_devices") or 1),
    )
    tok = rec.get("tokens_per_step")
    out["tokens_per_s"] = (tok / dt_s) if tok else None
    return out


def format_step_metrics(m: Optional[Dict[str, Any]]) -> str:
    """One-line suffix for trainer step logs: empty when accounting is
    off, else e.g. `` perf[0.42 TF/s mfu=1.2% roofline=3.4%]``."""
    if not m:
        return ""
    parts = []
    if m.get("tflops_per_s") is not None:
        parts.append(f"{m['tflops_per_s']:.3g} TF/s")
    if m.get("mfu") is not None:
        parts.append(f"mfu={m['mfu'] * 100:.2f}%")
    if m.get("roofline_frac") is not None:
        parts.append(f"roofline={m['roofline_frac'] * 100:.1f}%")
    if m.get("tokens_per_s"):
        parts.append(f"{m['tokens_per_s']:.0f} tok/s")
    return f" perf[{' '.join(parts)}]" if parts else ""
