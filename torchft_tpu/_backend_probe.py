"""Subprocess probe for jax backend liveness, with a cross-process cache.

A dead accelerator tunnel (e.g. the axon relay this dev box reaches its
TPU through) makes ``jax.devices()`` HANG forever rather than error, so
any entry point that must not wedge (bench.py, __graft_entry__) probes
backend init in a subprocess with a deadline first.

The probe result is cached in a temp file so that consecutive entry
points in one driver run (bench.py, then ``dryrun_multichip``) pay the
probe deadline at most once per boot rather than once per process.
Mirrors the reference's CI discipline of bounding every external wait
(reference pyproject.toml ``[tool.pytest.ini_options]`` 60s timeout).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
from typing import Optional

from torchft_tpu import knobs

# Verdict trust windows.  A CONFIRMED verdict (backend init returned —
# alive, or errored outright — dead) is trusted long enough that
# bench.py + dryrun_multichip in one driver round share a single probe.
# A TIMEOUT verdict (the dead-tunnel signature: jax.devices() hangs, it
# doesn't error) is trusted for the same window: driver phases (bench →
# entry → dryrun_multichip) can be many minutes apart, and re-paying a
# 30s probe on a known-dead tunnel burns the dryrun's own latency
# budget.  The residual risk — a loaded box pushing `import jax` past
# the deadline with a HEALTHY tunnel — only costs a CPU-fallback run,
# never a hang, so the cheap verdict is the safe one to cache.
_CACHE_TTL_S = 900.0
_TIMEOUT_TTL_S = 900.0

_DEFAULT_TIMEOUT_S = 30.0


def _cache_path() -> str:
    # Keyed by boot (stale verdicts can't leak across restarts) and uid
    # (no cross-user clobbering of a predictable world-shared /tmp name).
    try:
        with open("/proc/sys/kernel/random/boot_id") as f:
            boot = f.read().strip().replace("-", "")[:12]
    except OSError:
        boot = "noboot"
    uid = os.getuid() if hasattr(os, "getuid") else 0
    return os.path.join(
        tempfile.gettempdir(), f"torchft_tpu_probe_{uid}_{boot}.json"
    )


def _read_cache() -> Optional[dict]:
    try:
        with open(_cache_path()) as f:
            data = json.load(f)
        ttl = _TIMEOUT_TTL_S if data.get("timed_out") else _CACHE_TTL_S
        elapsed = time.time() - float(data["ts"])
        # Reject future timestamps too (clock step / crafted file), or a
        # bogus verdict would never expire.
        if 0.0 <= elapsed <= ttl:
            return data
    except (OSError, ValueError, KeyError):
        pass
    return None


def _write_cache(count: Optional[int], timed_out: bool) -> None:
    path = _cache_path()
    tmp = f"{path}.{os.getpid()}.tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(
                {"count": count, "ts": time.time(), "timed_out": timed_out},
                f,
            )
        os.replace(tmp, path)  # atomic vs concurrent probers
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass


def probe_device_count(
    timeout_s: float = _DEFAULT_TIMEOUT_S,
    use_cache: bool = True,
    distrust_timeout: bool = False,
) -> Optional[int]:
    """Returns the visible jax device count, or ``None`` when backend init
    fails or hangs past ``timeout_s`` (caller should fall back to CPU).

    ``TORCHFT_PROBE_TIMEOUT`` overrides the deadline;
    ``TORCHFT_PROBE_NO_CACHE=1`` forces a fresh probe.

    ``distrust_timeout``: re-probe instead of trusting a cached TIMEOUT
    verdict.  One 30s probe timeout on a loaded-but-healthy box would
    otherwise pin every phase to CPU fallback for the full TTL — callers
    about to spend minutes on a HEADLINE measurement should pay the
    fresh probe; cheap gate phases keep the cached verdict.
    """
    env_timeout = knobs.get_raw("TORCHFT_PROBE_TIMEOUT")
    if env_timeout:
        timeout_s = float(env_timeout)
    if knobs.get_bool("TORCHFT_PROBE_NO_CACHE"):
        use_cache = False

    if use_cache:
        cached = _read_cache()
        if cached is not None and not (
            distrust_timeout and cached.get("timed_out")
        ):
            count = cached["count"]
            return int(count) if count is not None else None

    code = "import jax; print(len(jax.devices()))"
    count: Optional[int]
    timed_out = False
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            timeout=timeout_s,
            capture_output=True,
        )
        if proc.returncode != 0:
            count = None
        else:
            count = int(proc.stdout.split()[-1])
    except subprocess.TimeoutExpired:
        count = None
        timed_out = True
    except (ValueError, IndexError):
        count = None

    if use_cache:
        _write_cache(count, timed_out)
    return count
