"""Subprocess probe for jax backend liveness.

A dead accelerator tunnel (e.g. the axon relay this dev box reaches its
TPU through) makes ``jax.devices()`` HANG forever rather than error, so
any entry point that must not wedge (bench.py, __graft_entry__) probes
backend init in a subprocess with a deadline first.
"""

from __future__ import annotations

import subprocess
import sys
from typing import Optional


def probe_device_count(timeout_s: float = 180.0) -> Optional[int]:
    """Returns the visible jax device count, or ``None`` when backend init
    fails or hangs past ``timeout_s`` (caller should fall back to CPU)."""
    code = "import jax; print(len(jax.devices()))"
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            timeout=timeout_s,
            capture_output=True,
        )
        if proc.returncode != 0:
            return None
        return int(proc.stdout.split()[-1])
    except (subprocess.TimeoutExpired, ValueError, IndexError):
        return None
