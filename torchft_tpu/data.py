"""Data sharding across the fault-tolerant replica axis.

Reference: ``torchft/data.py:24-77`` — a DistributedSampler that treats the
job as ``num_replica_groups x num_replicas`` workers with
``global_rank = group_rank + num_replicas * replica_rank``; documented as
lossy under faults (a failed group's shard for that step is simply dropped).

JAX translation: no torch DataLoader; the sampler yields index streams (or
shards a numpy array of indices) usable by any host data pipeline. For
replica-group-local determinism, pair with the Manager's
``batches_committed()`` to resume the stream after heal (the reference
recommends torchdata StatefulDataLoader for the same reason, data.py:13-14).
"""

from __future__ import annotations

from typing import Iterator, List, Optional

import numpy as np


class DistributedSampler:
    """Shards ``dataset_len`` indices over the global worker grid.

    Args:
        dataset_len: number of examples.
        replica_rank: this replica group's rank on the FT axis.
        num_replica_groups: total replica groups (the FT world size the job
            was *launched* with; membership changes drop shards, they don't
            reshuffle).
        group_rank / num_replicas: position inside the replica group (the
            inner DP axis), matching the reference's rank/num_replicas.
        shuffle / seed: epoch-deterministic shuffling shared by all workers.
    """

    def __init__(
        self,
        dataset_len: int,
        replica_rank: int,
        num_replica_groups: int,
        group_rank: int = 0,
        num_replicas: int = 1,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = True,
    ) -> None:
        if num_replica_groups < 1 or num_replicas < 1:
            raise ValueError("world dims must be >= 1")
        self._len = dataset_len
        self.global_rank = group_rank + num_replicas * replica_rank
        self.global_world_size = num_replicas * num_replica_groups
        if self.global_rank >= self.global_world_size:
            raise ValueError(
                f"global_rank {self.global_rank} >= world "
                f"{self.global_world_size}"
            )
        self._shuffle = shuffle
        self._seed = seed
        self._drop_last = drop_last
        self._epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch

    def __len__(self) -> int:
        if self._drop_last:
            return self._len // self.global_world_size
        return (self._len + self.global_world_size - 1) // self.global_world_size

    def indices(self) -> np.ndarray:
        order = np.arange(self._len)
        if self._shuffle:
            rng = np.random.default_rng(self._seed + self._epoch)
            rng.shuffle(order)
        if self._drop_last:
            usable = len(self) * self.global_world_size
            order = order[:usable]
        else:
            # Cyclic repeat covers pads larger than the dataset itself
            # (tiny datasets on large worlds), so every rank gets exactly
            # len(self) indices and loops stay in lockstep.
            order = np.resize(order, len(self) * self.global_world_size)
        return order[self.global_rank :: self.global_world_size]

    def __iter__(self) -> Iterator[int]:
        return iter(self.indices().tolist())


class StatefulDataIterator:
    """Resumable batch iterator over a :class:`DistributedSampler`.

    The reference points users at torchdata's ``StatefulDataLoader`` for
    per-replica-group dataloader state (torchft/data.py:13-14,
    train_ddp.py:67-70); this is the in-repo TPU-native equivalent: a
    batch-index stream whose position is a tiny ``state_dict`` that can be
    registered with the Manager so a healed replica resumes EXACTLY where
    the checkpoint source was (no repeated or skipped batches), and that
    durable checkpoints capture for full-job restarts.

    Wiring:

        it = StatefulDataIterator(sampler, batch_size=8)
        manager.register_state_dict_fn(
            "data", it.state_dict, it.load_state_dict)
        for batch_idx in it:   # yields np.ndarray of dataset indices
            ...
    """

    def __init__(self, sampler: DistributedSampler, batch_size: int) -> None:
        if batch_size < 1:
            raise ValueError("batch_size must be >= 1")
        if batch_size > len(sampler):
            raise ValueError(
                f"batch_size {batch_size} exceeds the per-rank shard "
                f"({len(sampler)} examples): every epoch would be empty"
            )
        self._sampler = sampler
        self._batch = batch_size
        self._pos = 0  # batches consumed within the current epoch
        self._cached_epoch: Optional[int] = None
        self._cached_indices: Optional[np.ndarray] = None

    def _indices(self) -> np.ndarray:
        """Epoch permutation, computed once per epoch (recomputing the
        full shuffle per batch would dominate the host input path)."""
        if self._cached_epoch != self._sampler._epoch:
            self._cached_indices = self._sampler.indices()
            self._cached_epoch = self._sampler._epoch
        return self._cached_indices

    def batches_per_epoch(self) -> int:
        return len(self._sampler) // self._batch

    def state_dict(self) -> dict:
        return {"epoch": self._sampler._epoch, "pos": self._pos}

    def load_state_dict(self, state: dict) -> None:
        self._sampler.set_epoch(int(state["epoch"]))
        self._pos = int(state["pos"])

    def __iter__(self) -> Iterator[np.ndarray]:
        return self

    def __next__(self) -> np.ndarray:
        if self._pos >= self.batches_per_epoch():
            # Epoch boundary: reshuffle deterministically, restart stream.
            self._sampler.set_epoch(self._sampler._epoch + 1)
            self._pos = 0
        idx = self._indices()
        start = self._pos * self._batch
        self._pos += 1
        return idx[start : start + self._batch]
