"""Data sharding across the fault-tolerant replica axis.

Reference: ``torchft/data.py:24-77`` — a DistributedSampler that treats the
job as ``num_replica_groups x num_replicas`` workers with
``global_rank = group_rank + num_replicas * replica_rank``; documented as
lossy under faults (a failed group's shard for that step is simply dropped).

JAX translation: no torch DataLoader; the sampler yields index streams (or
shards a numpy array of indices) usable by any host data pipeline. For
replica-group-local determinism, pair with the Manager's
``batches_committed()`` to resume the stream after heal (the reference
recommends torchdata StatefulDataLoader for the same reason, data.py:13-14).
"""

from __future__ import annotations

from typing import Iterator, List, Optional

import numpy as np


class DistributedSampler:
    """Shards ``dataset_len`` indices over the global worker grid.

    Args:
        dataset_len: number of examples.
        replica_rank: this replica group's rank on the FT axis.
        num_replica_groups: total replica groups (the FT world size the job
            was *launched* with; membership changes drop shards, they don't
            reshuffle).
        group_rank / num_replicas: position inside the replica group (the
            inner DP axis), matching the reference's rank/num_replicas.
        shuffle / seed: epoch-deterministic shuffling shared by all workers.
    """

    def __init__(
        self,
        dataset_len: int,
        replica_rank: int,
        num_replica_groups: int,
        group_rank: int = 0,
        num_replicas: int = 1,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = True,
    ) -> None:
        if num_replica_groups < 1 or num_replicas < 1:
            raise ValueError("world dims must be >= 1")
        self._len = dataset_len
        self.global_rank = group_rank + num_replicas * replica_rank
        self.global_world_size = num_replicas * num_replica_groups
        if self.global_rank >= self.global_world_size:
            raise ValueError(
                f"global_rank {self.global_rank} >= world "
                f"{self.global_world_size}"
            )
        self._shuffle = shuffle
        self._seed = seed
        self._drop_last = drop_last
        self._epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch

    def __len__(self) -> int:
        if self._drop_last:
            return self._len // self.global_world_size
        return (self._len + self.global_world_size - 1) // self.global_world_size

    def indices(self) -> np.ndarray:
        order = np.arange(self._len)
        if self._shuffle:
            rng = np.random.default_rng(self._seed + self._epoch)
            rng.shuffle(order)
        if self._drop_last:
            usable = len(self) * self.global_world_size
            order = order[:usable]
        else:
            # Cyclic repeat covers pads larger than the dataset itself
            # (tiny datasets on large worlds), so every rank gets exactly
            # len(self) indices and loops stay in lockstep.
            order = np.resize(order, len(self) * self.global_world_size)
        return order[self.global_rank :: self.global_world_size]

    def __iter__(self) -> Iterator[int]:
        return iter(self.indices().tolist())
