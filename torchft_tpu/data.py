"""Data sharding across the fault-tolerant replica axis.

Reference: ``torchft/data.py:24-77`` — a DistributedSampler that treats the
job as ``num_replica_groups x num_replicas`` workers with
``global_rank = group_rank + num_replicas * replica_rank``; documented as
lossy under faults (a failed group's shard for that step is simply dropped).

JAX translation: no torch DataLoader; the sampler yields index streams (or
shards a numpy array of indices) usable by any host data pipeline. For
replica-group-local determinism, pair with the Manager's
``batches_committed()`` to resume the stream after heal (the reference
recommends torchdata StatefulDataLoader for the same reason, data.py:13-14).
"""

from __future__ import annotations

from typing import Iterator, List, Optional

import numpy as np


class DistributedSampler:
    """Shards ``dataset_len`` indices over the global worker grid.

    Args:
        dataset_len: number of examples.
        replica_rank: this replica group's rank on the FT axis.
        num_replica_groups: total replica groups (the FT world size the job
            was *launched* with; membership changes drop shards, they don't
            reshuffle).
        group_rank / num_replicas: position inside the replica group (the
            inner DP axis), matching the reference's rank/num_replicas.
        shuffle / seed: epoch-deterministic shuffling shared by all workers.
    """

    def __init__(
        self,
        dataset_len: int,
        replica_rank: int,
        num_replica_groups: int,
        group_rank: int = 0,
        num_replicas: int = 1,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = True,
    ) -> None:
        if num_replica_groups < 1 or num_replicas < 1:
            raise ValueError("world dims must be >= 1")
        self._len = dataset_len
        self.global_rank = group_rank + num_replicas * replica_rank
        self.global_world_size = num_replicas * num_replica_groups
        if self.global_rank >= self.global_world_size:
            raise ValueError(
                f"global_rank {self.global_rank} >= world "
                f"{self.global_world_size}"
            )
        self._shuffle = shuffle
        self._seed = seed
        self._drop_last = drop_last
        self._epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch

    def reshard(
        self,
        replica_rank: int,
        num_replica_groups: int,
        group_rank: int = 0,
        num_replicas: int = 1,
    ) -> None:
        """Re-points this sampler at a new position in a RESIZED global
        worker grid (elastic scale-up/down at a quorum boundary).

        The epoch-level permutation (:meth:`global_order`) depends only on
        ``(seed, epoch, dataset_len)`` — never on the grid — so resharding
        just re-partitions it: every worker that calls ``reshard`` with the
        same new grid at the same global stream position keeps the
        exactly-once-per-epoch property (see :class:`ElasticDataIterator`,
        which tracks that position). Call at a step boundary, on every
        surviving worker, with the quorum's agreed grid."""
        if num_replica_groups < 1 or num_replicas < 1:
            raise ValueError("world dims must be >= 1")
        global_rank = group_rank + num_replicas * replica_rank
        global_world_size = num_replicas * num_replica_groups
        if global_rank >= global_world_size:
            raise ValueError(
                f"global_rank {global_rank} >= world {global_world_size}"
            )
        self.global_rank = global_rank
        self.global_world_size = global_world_size

    def __len__(self) -> int:
        if self._drop_last:
            return self._len // self.global_world_size
        return (self._len + self.global_world_size - 1) // self.global_world_size

    def indices(self) -> np.ndarray:
        order = self.global_order()
        if self._drop_last:
            usable = len(self) * self.global_world_size
            order = order[:usable]
        else:
            # Cyclic repeat covers pads larger than the dataset itself
            # (tiny datasets on large worlds), so every rank gets exactly
            # len(self) indices and loops stay in lockstep.
            order = np.resize(order, len(self) * self.global_world_size)
        return order[self.global_rank :: self.global_world_size]

    def global_order(self) -> np.ndarray:
        """The full epoch permutation, before any grid partitioning.

        World-size independent by construction (seed + epoch + length
        only): the anchor that makes elastic resharding deterministic —
        a worker that joins mid-epoch computes the IDENTICAL order as the
        incumbents and picks up its slice of the unconsumed tail."""
        order = np.arange(self._len)
        if self._shuffle:
            rng = np.random.default_rng(self._seed + self._epoch)
            rng.shuffle(order)
        return order

    def __iter__(self) -> Iterator[int]:
        return iter(self.indices().tolist())


class StatefulDataIterator:
    """Resumable batch iterator over a :class:`DistributedSampler`.

    The reference points users at torchdata's ``StatefulDataLoader`` for
    per-replica-group dataloader state (torchft/data.py:13-14,
    train_ddp.py:67-70); this is the in-repo TPU-native equivalent: a
    batch-index stream whose position is a tiny ``state_dict`` that can be
    registered with the Manager so a healed replica resumes EXACTLY where
    the checkpoint source was (no repeated or skipped batches), and that
    durable checkpoints capture for full-job restarts.

    Wiring:

        it = StatefulDataIterator(sampler, batch_size=8)
        manager.register_state_dict_fn(
            "data", it.state_dict, it.load_state_dict)
        for batch_idx in it:   # yields np.ndarray of dataset indices
            ...
    """

    def __init__(self, sampler: DistributedSampler, batch_size: int) -> None:
        if batch_size < 1:
            raise ValueError("batch_size must be >= 1")
        if batch_size > len(sampler):
            raise ValueError(
                f"batch_size {batch_size} exceeds the per-rank shard "
                f"({len(sampler)} examples): every epoch would be empty"
            )
        self._sampler = sampler
        self._batch = batch_size
        self._pos = 0  # batches consumed within the current epoch
        self._cached_epoch: Optional[int] = None
        self._cached_indices: Optional[np.ndarray] = None

    def _indices(self) -> np.ndarray:
        """Epoch permutation, computed once per epoch (recomputing the
        full shuffle per batch would dominate the host input path)."""
        if self._cached_epoch != self._sampler._epoch:
            self._cached_indices = self._sampler.indices()
            self._cached_epoch = self._sampler._epoch
        return self._cached_indices

    def batches_per_epoch(self) -> int:
        return len(self._sampler) // self._batch

    def state_dict(self) -> dict:
        return {"epoch": self._sampler._epoch, "pos": self._pos}

    def load_state_dict(self, state: dict) -> None:
        self._sampler.set_epoch(int(state["epoch"]))
        self._pos = int(state["pos"])

    def __iter__(self) -> Iterator[np.ndarray]:
        return self

    def __next__(self) -> np.ndarray:
        if self._pos >= self.batches_per_epoch():
            # Epoch boundary: reshuffle deterministically, restart stream.
            self._sampler.set_epoch(self._sampler._epoch + 1)
            self._pos = 0
        idx = self._indices()
        start = self._pos * self._batch
        self._pos += 1
        return idx[start : start + self._batch]


class ElasticDataIterator:
    """Reshard-aware batch iterator: exactly-once-per-epoch under any
    world-size walk (2 -> 8 -> 3, mid-epoch joins included).

    Where :class:`StatefulDataIterator` addresses the stream by per-rank
    batch position (fixed grid for the sampler's lifetime), this iterator
    addresses it by GLOBAL position: ``gpos`` counts indices of the
    epoch's :meth:`DistributedSampler.global_order` consumed by the whole
    fleet. Each ``__next__`` claims the next ``batch * world`` global
    indices as one lockstep fleet-batch and returns this rank's strided
    slice of it; the epoch's tail fleet-batch may be short (some ranks get
    fewer — or zero — indices rather than duplicating any).

    Elasticity contract: all participants advance in lockstep (one
    ``__next__`` per committed step), so ``gpos`` agrees fleet-wide at
    every step boundary. A resize is then just
    ``sampler.reshard(new_rank, new_world)`` between steps — the
    unconsumed tail ``order[gpos:]`` re-partitions across the new grid
    with no index lost or duplicated, and a joiner that heals
    ``state_dict()`` from an incumbent (epoch + gpos travel with the
    checkpoint) starts claiming its slice at exactly the fleet's
    position. Determinism: the yielded sequence is a pure function of
    (seed, epoch walk, reshard walk, gpos walk) — no wall clock, no
    process state."""

    def __init__(self, sampler: DistributedSampler, batch_size: int) -> None:
        if batch_size < 1:
            raise ValueError("batch_size must be >= 1")
        self._sampler = sampler
        self._batch = batch_size
        self._gpos = 0  # global indices consumed within the current epoch
        self._cached_epoch: Optional[int] = None
        self._cached_order: Optional[np.ndarray] = None

    def _order(self) -> np.ndarray:
        if self._cached_epoch != self._sampler._epoch:
            self._cached_order = self._sampler.global_order()
            self._cached_epoch = self._sampler._epoch
        return self._cached_order

    def epoch_len(self) -> int:
        return self._sampler._len

    def batches_left(self) -> int:
        """Fleet-batches remaining this epoch at the CURRENT world size
        (the tail short batch counts as one)."""
        left = self.epoch_len() - self._gpos
        stride = self._batch * self._sampler.global_world_size
        return (left + stride - 1) // stride

    def state_dict(self) -> dict:
        return {"epoch": self._sampler._epoch, "gpos": self._gpos}

    def load_state_dict(self, state: dict) -> None:
        self._sampler.set_epoch(int(state["epoch"]))
        self._gpos = int(state["gpos"])

    def __iter__(self) -> "ElasticDataIterator":
        return self

    def __next__(self) -> np.ndarray:
        if self._gpos >= self.epoch_len():
            # Epoch boundary: reshuffle deterministically, restart stream.
            self._sampler.set_epoch(self._sampler._epoch + 1)
            self._gpos = 0
        order = self._order()
        world = self._sampler.global_world_size
        take = min(self._batch * world, self.epoch_len() - self._gpos)
        segment = order[self._gpos : self._gpos + take]
        self._gpos += take
        return segment[self._sampler.global_rank :: world]
