// Tracks live per-connection handler threads by fd so a server can shut them
// all down promptly and wait for handlers to drain (connection threads are
// detached; without this, stop() would block up to the idle-frame timeout on
// every open connection, and the handle vector would grow unboundedly).
#pragma once

#include <sys/socket.h>

#include <chrono>
#include <condition_variable>
#include <mutex>
#include <set>

namespace tft {

class ConnTracker {
 public:
  // Registers a connection. Returns false if the server is shutting down
  // (caller should close the fd and bail).
  bool add(int fd) {
    std::lock_guard<std::mutex> lk(mu_);
    if (closed_) return false;
    fds_.insert(fd);
    return true;
  }

  void remove(int fd) {
    std::lock_guard<std::mutex> lk(mu_);
    fds_.erase(fd);
    cv_.notify_all();
  }

  // Interrupts every in-flight recv/send; handlers then exit on their own.
  void shutdown_all() {
    std::lock_guard<std::mutex> lk(mu_);
    closed_ = true;
    for (int fd : fds_) ::shutdown(fd, SHUT_RDWR);
  }

  // Waits for all handler threads to deregister. Returns false on timeout.
  bool wait_idle(int timeout_ms) {
    std::unique_lock<std::mutex> lk(mu_);
    return cv_.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                        [this] { return fds_.empty(); });
  }

 private:
  std::mutex mu_;
  std::condition_variable cv_;
  std::set<int> fds_;
  bool closed_ = false;
};

}  // namespace tft
