// Unit + in-process E2E tests for the torchft-tpu C++ control plane.
// Mirrors the reference's Rust test coverage (lighthouse.rs:612-1298,
// manager.rs:626-1217): quorum_compute corner cases, quorum_changed,
// compute_quorum_results matrices, live lighthouse E2E on an ephemeral port,
// should_commit barrier with concurrent clients, and heal planning.
#include <unistd.h>

#include <algorithm>
#include <cstdlib>
#include <cmath>
#include <cstdio>
#include <cstring>
#include <memory>
#include <thread>
#include <vector>

#include "collectives.hpp"
#include "json.hpp"
#include "lighthouse.hpp"
#include "manager_server.hpp"
#include "net.hpp"
#include "quorum.hpp"

using namespace tft;

static int g_failures = 0;
static int g_checks = 0;

#define CHECK(cond)                                                     \
  do {                                                                  \
    g_checks++;                                                         \
    if (!(cond)) {                                                      \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond);   \
      g_failures++;                                                     \
    }                                                                   \
  } while (0)

#define CHECK_EQ(a, b)                                                      \
  do {                                                                      \
    g_checks++;                                                             \
    auto va = (a);                                                          \
    auto vb = (b);                                                          \
    if (!(va == vb)) {                                                      \
      fprintf(stderr, "FAIL %s:%d: %s != %s\n", __FILE__, __LINE__, #a, #b); \
      g_failures++;                                                         \
    }                                                                       \
  } while (0)

static QuorumMember mk_member(const std::string& id, int64_t step = 0,
                              int64_t world = 1) {
  QuorumMember m;
  m.replica_id = id;
  m.address = "addr-" + id;
  m.store_address = "store-" + id;
  m.step = step;
  m.world_size = world;
  return m;
}

static void add_participant(LighthouseState* st, const QuorumMember& m,
                            int64_t now) {
  st->participants[m.replica_id] = {m, now};
  st->heartbeats[m.replica_id] = now;
}

static void test_json() {
  Json j;
  std::string err;
  CHECK(Json::parse("{\"a\":1,\"b\":[true,null,\"x\\n\"],\"c\":-2.5}", &j, &err));
  CHECK_EQ(j.get("a").as_int(), 1);
  CHECK_EQ(j.get("b").arr.size(), size_t(3));
  CHECK_EQ(j.get("b").arr[2].as_str(), std::string("x\n"));
  CHECK_EQ(j.get("c").as_double(), -2.5);
  Json round;
  CHECK(Json::parse(j.dump(), &round, &err));
  CHECK_EQ(round.dump(), j.dump());
  CHECK(!Json::parse("{", &j, &err));
  CHECK(!Json::parse("[1,]", &j, &err));
  // Unicode escapes.
  CHECK(Json::parse("\"\\u00e9\"", &j, &err));
  CHECK_EQ(j.as_str(), std::string("\xc3\xa9"));
}

static void test_quorum_compute_basic() {
  LighthouseOpts opt;
  opt.min_replicas = 2;
  opt.join_timeout_ms = 1000;
  opt.heartbeat_timeout_ms = 5000;
  LighthouseState st;
  int64_t now = 100000;
  std::string reason;

  // Not enough participants.
  add_participant(&st, mk_member("a"), now);
  CHECK(!quorum_compute(now, st, opt, &reason).has_value());

  // Two healthy participants, all healthy joined -> quorum forms immediately
  // even inside the join window.
  add_participant(&st, mk_member("b"), now);
  auto q = quorum_compute(now, st, opt, &reason);
  CHECK(q.has_value());
  CHECK_EQ(q->size(), size_t(2));
  CHECK_EQ((*q)[0].replica_id, std::string("a"));

  // A healthy straggler not yet joined blocks within the join window...
  st.heartbeats["c"] = now;
  CHECK(!quorum_compute(now, st, opt, &reason).has_value());
  // ...but after join_timeout the quorum proceeds without it.
  auto q2 = quorum_compute(now + 1500, st, opt, &reason);
  CHECK(q2.has_value());
  CHECK_EQ(q2->size(), size_t(2));
}

static void test_quorum_compute_heartbeat_expiry() {
  LighthouseOpts opt;
  opt.min_replicas = 2;
  opt.join_timeout_ms = 0;
  opt.heartbeat_timeout_ms = 1000;
  LighthouseState st;
  int64_t now = 50000;
  add_participant(&st, mk_member("a"), now);
  add_participant(&st, mk_member("b"), now);
  st.heartbeats["b"] = now - 2000;  // stale
  std::string reason;
  CHECK(!quorum_compute(now, st, opt, &reason).has_value());
  st.heartbeats["b"] = now;  // fresh again
  CHECK(quorum_compute(now, st, opt, &reason).has_value());
}

static void test_fast_quorum() {
  LighthouseOpts opt;
  opt.min_replicas = 2;
  opt.join_timeout_ms = 60000;  // long window; fast path must skip it
  opt.heartbeat_timeout_ms = 5000;
  LighthouseState st;
  int64_t now = 200000;
  Quorum prev;
  prev.quorum_id = 7;
  prev.participants = {mk_member("a", 5), mk_member("b", 5)};
  st.prev_quorum = prev;
  add_participant(&st, mk_member("a", 5), now);
  add_participant(&st, mk_member("b", 5), now);
  // A healthy straggler exists but fast quorum (all prev members present)
  // bypasses the join wait.
  st.heartbeats["c"] = now;
  std::string reason;
  auto q = quorum_compute(now, st, opt, &reason);
  CHECK(q.has_value());
  CHECK_EQ(q->size(), size_t(2));
}

static void test_split_brain_guard() {
  LighthouseOpts opt;
  opt.min_replicas = 1;
  opt.join_timeout_ms = 0;
  opt.heartbeat_timeout_ms = 5000;
  LighthouseState st;
  int64_t now = 300000;
  add_participant(&st, mk_member("a"), now);
  // Three healthy replicas exist; one participant is not a majority.
  st.heartbeats["b"] = now;
  st.heartbeats["c"] = now;
  std::string reason;
  CHECK(!quorum_compute(now, st, opt, &reason).has_value());
  // Two of three is a majority.
  add_participant(&st, mk_member("b"), now);
  CHECK(quorum_compute(now, st, opt, &reason).has_value());
}

static void test_shrink_only() {
  LighthouseOpts opt;
  opt.min_replicas = 1;
  opt.join_timeout_ms = 0;
  opt.heartbeat_timeout_ms = 5000;
  LighthouseState st;
  int64_t now = 400000;
  Quorum prev;
  prev.participants = {mk_member("a", 3), mk_member("b", 3)};
  st.prev_quorum = prev;
  auto a = mk_member("a", 3);
  a.shrink_only = true;
  add_participant(&st, a, now);
  add_participant(&st, mk_member("newcomer", 0), now);
  std::string reason;
  auto q = quorum_compute(now, st, opt, &reason);
  CHECK(q.has_value());
  // newcomer must be excluded while shrinking.
  CHECK_EQ(q->size(), size_t(1));
  CHECK_EQ((*q)[0].replica_id, std::string("a"));
}

static void test_quorum_changed() {
  std::vector<QuorumMember> a = {mk_member("x", 1), mk_member("y", 1)};
  std::vector<QuorumMember> b = {mk_member("y", 9), mk_member("x", 2)};
  CHECK(!quorum_changed(a, b));  // same ids, different steps/order
  std::vector<QuorumMember> c = {mk_member("x", 1)};
  CHECK(quorum_changed(a, c));
}

static void test_compute_quorum_results() {
  Quorum q;
  q.quorum_id = 3;
  q.participants = {mk_member("a", 10), mk_member("b", 10), mk_member("c", 7)};
  std::string err;

  // Up-to-date member "a" (rank 0) should be assigned recoverer "c" (rank 2).
  auto ra = compute_quorum_results(0, "a", q, true, &err);
  CHECK(ra.has_value());
  CHECK_EQ(ra->quorum_id, 3);
  CHECK_EQ(ra->replica_rank, 0);
  CHECK_EQ(ra->replica_world_size, 3);
  CHECK_EQ(ra->max_step, 10);
  CHECK_EQ(ra->max_world_size, 2);  // a and b at max step
  CHECK(!ra->heal);
  CHECK_EQ(ra->recover_dst_replica_ranks.size(), size_t(1));
  CHECK_EQ(ra->recover_dst_replica_ranks[0], 2);

  // Lagging member "c" heals from "a" (round-robin index 0 at group_rank 0).
  auto rc = compute_quorum_results(0, "c", q, true, &err);
  CHECK(rc.has_value());
  CHECK(rc->heal);
  CHECK(rc->recover_src_replica_rank.has_value());
  CHECK_EQ(*rc->recover_src_replica_rank, 0);
  CHECK_EQ(rc->recover_src_manager_address, std::string("addr-a"));

  // A different group_rank shifts the round-robin source to "b" (rank 1).
  auto rc1 = compute_quorum_results(1, "c", q, true, &err);
  CHECK(rc1.has_value());
  CHECK_EQ(*rc1->recover_src_replica_rank, 1);

  // Unknown replica -> error.
  CHECK(!compute_quorum_results(0, "zzz", q, true, &err).has_value());
}

static void test_force_recover_on_init() {
  // All at step 0 with init_sync: everyone except the primary heals so
  // weights start identical (manager.rs:537).
  Quorum q;
  q.participants = {mk_member("a", 0), mk_member("b", 0)};
  std::string err;
  auto ra = compute_quorum_results(0, "a", q, true, &err);
  auto rb = compute_quorum_results(0, "b", q, true, &err);
  CHECK(ra.has_value() && rb.has_value());
  CHECK_EQ(ra->heal + rb->heal, 1);  // exactly one heals
  // With init_sync=false nobody heals.
  auto na = compute_quorum_results(0, "a", q, false, &err);
  auto nb = compute_quorum_results(0, "b", q, false, &err);
  CHECK(!na->heal && !nb->heal);
}

static void test_commit_failures_propagate() {
  Quorum q;
  auto a = mk_member("a", 4);
  a.commit_failures = 2;
  q.participants = {a, mk_member("b", 4)};
  std::string err;
  auto rb = compute_quorum_results(0, "b", q, true, &err);
  CHECK_EQ(rb->commit_failures, 2);
}

// ---- E2E: live lighthouse + managers over loopback TCP ----

static Json lighthouse_call(const std::string& addr, const Json& req,
                            int64_t timeout_ms) {
  Json resp;
  bool ok = call_json_addr(addr, req, &resp, timeout_ms);
  if (!ok) {
    resp = Json::object();
    resp["ok"] = Json::of(false);
    resp["error"] = Json::of("transport failure");
  }
  return resp;
}

static void test_lighthouse_e2e() {
  LighthouseOpts opt;
  opt.min_replicas = 2;
  opt.join_timeout_ms = 100;
  opt.quorum_tick_ms = 20;
  opt.heartbeat_timeout_ms = 5000;
  Lighthouse lh("127.0.0.1", 0, opt);
  CHECK(lh.start());
  std::string addr = lh.address();

  auto quorum_req = [&](const std::string& id, int64_t step) {
    Json req = Json::object();
    req["type"] = Json::of("quorum");
    req["timeout_ms"] = Json::of(int64_t(5000));
    req["requester"] = mk_member(id, step).to_json();
    return lighthouse_call(addr, req, 6000);
  };

  Json ra, rb;
  std::thread ta([&] { ra = quorum_req("repA", 1); });
  std::thread tb([&] { rb = quorum_req("repB", 1); });
  ta.join();
  tb.join();
  CHECK(ra.get("ok").as_bool());
  CHECK(rb.get("ok").as_bool());
  CHECK_EQ(ra.get("quorum").get("participants").arr.size(), size_t(2));
  CHECK_EQ(ra.get("quorum").get("quorum_id").as_int(),
           rb.get("quorum").get("quorum_id").as_int());

  // Same membership again: quorum_id must NOT bump (fast quorum).
  int64_t qid = ra.get("quorum").get("quorum_id").as_int();
  std::thread tc([&] { ra = quorum_req("repA", 2); });
  std::thread td([&] { rb = quorum_req("repB", 2); });
  tc.join();
  td.join();
  CHECK(ra.get("ok").as_bool());
  CHECK_EQ(ra.get("quorum").get("quorum_id").as_int(), qid);

  // Status JSON over HTTP sniffing path is covered by the Python tests.
  Json sreq = Json::object();
  sreq["type"] = Json::of("status");
  Json s = lighthouse_call(addr, sreq, 2000);
  CHECK(s.get("ok").as_bool());
  CHECK_EQ(s.get("status").get("prev_quorum").get("participants").arr.size(),
           size_t(2));
  lh.stop();
}

static void test_lighthouse_leave() {
  // Graceful drain: a "leave" removes the member immediately, so survivors
  // re-quorum at tick speed instead of waiting for heartbeat expiry (set
  // deliberately huge here so only the leave can explain a fast shrink).
  LighthouseOpts opt;
  opt.min_replicas = 1;
  opt.join_timeout_ms = 2000;
  opt.quorum_tick_ms = 20;
  opt.heartbeat_timeout_ms = 60000;
  Lighthouse lh("127.0.0.1", 0, opt);
  CHECK(lh.start());
  std::string addr = lh.address();

  auto quorum_req = [&](const std::string& id, int64_t step) {
    Json req = Json::object();
    req["type"] = Json::of("quorum");
    req["timeout_ms"] = Json::of(int64_t(8000));
    req["requester"] = mk_member(id, step).to_json();
    return lighthouse_call(addr, req, 9000);
  };
  auto heartbeat = [&](const std::string& id) {
    Json req = Json::object();
    req["type"] = Json::of("heartbeat");
    req["replica_id"] = Json::of(id);
    return lighthouse_call(addr, req, 2000);
  };

  // Pre-heartbeat all three so the straggler wait holds the quorum open for
  // every member (min_replicas=1 would otherwise let the first registrant
  // form a singleton quorum before the other threads arrive).
  CHECK(heartbeat("repA").get("ok").as_bool());
  CHECK(heartbeat("repB").get("ok").as_bool());
  CHECK(heartbeat("repC").get("ok").as_bool());
  Json ra, rb, rc;
  std::thread ta([&] { ra = quorum_req("repA", 1); });
  std::thread tb([&] { rb = quorum_req("repB", 1); });
  std::thread tc([&] { rc = quorum_req("repC", 1); });
  ta.join();
  tb.join();
  tc.join();
  CHECK(ra.get("ok").as_bool());
  CHECK_EQ(ra.get("quorum").get("participants").arr.size(), size_t(3));
  int64_t qid = ra.get("quorum").get("quorum_id").as_int();

  Json lreq = Json::object();
  lreq["type"] = Json::of("leave");
  lreq["replica_id"] = Json::of(std::string("repC"));
  Json lresp = lighthouse_call(addr, lreq, 2000);
  CHECK(lresp.get("ok").as_bool());

  // State after leave: no heartbeat for repC, tombstone recorded.
  Json sreq = Json::object();
  sreq["type"] = Json::of("status");
  Json s = lighthouse_call(addr, sreq, 2000);
  CHECK(!s.get("status").get("heartbeat_ages_ms").obj.count("repC"));
  CHECK_EQ(s.get("status").get("left").arr.size(), size_t(1));

  // A heartbeat already in flight when the leave landed must not resurrect
  // the entry (would stall survivors on heartbeat expiry again).
  Json hreq = Json::object();
  hreq["type"] = Json::of("heartbeat");
  hreq["replica_id"] = Json::of(std::string("repC"));
  CHECK(lighthouse_call(addr, hreq, 2000).get("ok").as_bool());
  s = lighthouse_call(addr, sreq, 2000);
  CHECK(!s.get("status").get("heartbeat_ages_ms").obj.count("repC"));

  // Survivors re-quorum at tick speed: far below both the 60 s heartbeat
  // timeout and the 2 s join window a SIGKILLed member would cost them.
  int64_t t0 = now_ms();
  std::thread t2a([&] { ra = quorum_req("repA", 2); });
  std::thread t2b([&] { rb = quorum_req("repB", 2); });
  t2a.join();
  t2b.join();
  int64_t shrink_ms = now_ms() - t0;
  CHECK(ra.get("ok").as_bool());
  CHECK_EQ(ra.get("quorum").get("participants").arr.size(), size_t(2));
  CHECK(ra.get("quorum").get("quorum_id").as_int() > qid);
  CHECK(shrink_ms < 1000);

  // A relaunched drained replica rejoins via the normal quorum path (its
  // registration clears the tombstone — a tombstoned HEARTBEAT stays ignored
  // by design, else the stale-heartbeat race would reopen). Register repC
  // first so the survivors' round waits for it instead of forming a 2-quorum
  // underneath it.
  std::thread t3c([&] { rc = quorum_req("repC", 0); });
  // Wait until repC's registration has actually landed (a bare sleep could
  // lose the race under load, letting repA/repB form a 2-quorum underneath
  // the rejoiner and strand its RPC until timeout).
  for (int i = 0; i < 100; i++) {
    Json st = lighthouse_call(addr, sreq, 2000).get("status");
    bool registered = false;
    for (const auto& p : st.get("participants").arr)
      if (p.get("replica_id").as_str() == "repC") registered = true;
    if (registered) break;
    sleep_ms(50);
  }
  std::thread t3a([&] { ra = quorum_req("repA", 2); });
  std::thread t3b([&] { rb = quorum_req("repB", 2); });
  t3a.join();
  t3b.join();
  t3c.join();
  CHECK(rc.get("ok").as_bool());
  CHECK_EQ(rc.get("quorum").get("participants").arr.size(), size_t(3));
  s = lighthouse_call(addr, sreq, 2000);
  CHECK_EQ(s.get("status").get("left").arr.size(), size_t(0));
  lh.stop();
}

// ---- Lighthouse HA: durable state, fencing epoch, standby failover ----

static void test_lh_durable_state() {
  char tmpl[] = "/tmp/tft_lhstate_XXXXXX";
  CHECK(mkdtemp(tmpl) != nullptr);
  std::string dir = tmpl;

  // Missing file: load fails, output untouched semantics don't matter.
  LighthouseDurable d;
  CHECK(!lh_state_load(dir, &d));

  d.epoch = 3;
  d.quorum_id = 7;
  d.generation = 42;
  CHECK(lh_state_save(dir, d));
  LighthouseDurable r;
  CHECK(lh_state_load(dir, &r));
  CHECK_EQ(r.epoch, 3);
  CHECK_EQ(r.quorum_id, 7);
  CHECK_EQ(r.generation, 42);

  // Overwrite (the rename path must replace, not append).
  d.epoch = 4;
  d.quorum_id = 9;
  CHECK(lh_state_save(dir, d));
  CHECK(lh_state_load(dir, &r));
  CHECK_EQ(r.epoch, 4);
  CHECK_EQ(r.quorum_id, 9);

  // Garbage snapshot: load must fail cleanly (caller boots fresh), never
  // crash or half-apply.
  {
    FILE* f = fopen((dir + "/lighthouse_state.json").c_str(), "w");
    CHECK(f != nullptr);
    fputs("{not json", f);
    fclose(f);
  }
  CHECK(!lh_state_load(dir, &r));

  // Unwritable dir: save reports failure instead of silently dropping state.
  CHECK(!lh_state_save(dir + "/no/such/dir", d));
}

static void test_quorum_epoch_json_roundtrip() {
  Quorum q;
  q.quorum_id = 11;
  q.epoch = 5;
  q.generation = 9;
  q.participants.push_back(mk_member("repA", 3));
  Quorum r = Quorum::from_json(q.to_json());
  CHECK_EQ(r.quorum_id, 11);
  CHECK_EQ(r.epoch, 5);
  CHECK_EQ(r.generation, 9);

  // Pre-HA wire frames carry no epoch/generation: defaults must be 0 so a
  // mixed-version fleet doesn't spuriously trip the fence.
  Json j;
  std::string err;
  CHECK(Json::parse("{\"quorum_id\":2,\"participants\":[]}", &j, &err));
  Quorum old = Quorum::from_json(j);
  CHECK_EQ(old.epoch, 0);
  CHECK_EQ(old.generation, 0);
}

static void test_lighthouse_warm_restart() {
  char tmpl[] = "/tmp/tft_lhwarm_XXXXXX";
  CHECK(mkdtemp(tmpl) != nullptr);
  std::string dir = tmpl;

  LighthouseOpts opt;
  opt.min_replicas = 2;
  opt.join_timeout_ms = 100;
  opt.quorum_tick_ms = 20;
  opt.heartbeat_timeout_ms = 5000;
  opt.state_dir = dir;

  int64_t qid1 = 0, epoch1 = 0, gen1 = 0;
  {
    Lighthouse lh("127.0.0.1", 0, opt);
    CHECK(lh.start());
    std::string addr = lh.address();
    auto quorum_req = [&](const std::string& id, int64_t step) {
      Json req = Json::object();
      req["type"] = Json::of("quorum");
      req["timeout_ms"] = Json::of(int64_t(5000));
      req["requester"] = mk_member(id, step).to_json();
      return lighthouse_call(addr, req, 6000);
    };
    Json ra, rb;
    std::thread ta([&] { ra = quorum_req("repA", 1); });
    std::thread tb([&] { rb = quorum_req("repB", 1); });
    ta.join();
    tb.join();
    CHECK(ra.get("ok").as_bool());
    qid1 = ra.get("quorum").get("quorum_id").as_int();
    epoch1 = ra.get("quorum").get("epoch").as_int();
    gen1 = ra.get("quorum").get("generation").as_int();
    CHECK_EQ(epoch1, 1);  // fresh active boot
    CHECK(gen1 >= 1);
    lh.stop();
  }

  // Warm restart from the same state dir: the reign resumes (same epoch — no
  // takeover happened), but quorum ids and generations must stay strictly
  // monotone even though the generation counter was only persisted with
  // reserve headroom, never per broadcast.
  {
    Lighthouse lh("127.0.0.1", 0, opt);
    CHECK(lh.start());
    std::string addr = lh.address();
    auto quorum_req = [&](const std::string& id, int64_t step) {
      Json req = Json::object();
      req["type"] = Json::of("quorum");
      req["timeout_ms"] = Json::of(int64_t(5000));
      req["requester"] = mk_member(id, step).to_json();
      return lighthouse_call(addr, req, 6000);
    };
    Json ra, rb;
    std::thread ta([&] { ra = quorum_req("repA", 2); });
    std::thread tb([&] { rb = quorum_req("repB", 2); });
    ta.join();
    tb.join();
    CHECK(ra.get("ok").as_bool());
    CHECK_EQ(ra.get("quorum").get("epoch").as_int(), epoch1);
    CHECK(ra.get("quorum").get("quorum_id").as_int() > qid1);
    CHECK(ra.get("quorum").get("generation").as_int() > gen1);

    Json sreq = Json::object();
    sreq["type"] = Json::of("status");
    Json s = lighthouse_call(addr, sreq, 2000).get("status");
    CHECK_EQ(s.get("role").as_str(), std::string("active"));
    CHECK_EQ(s.get("epoch").as_int(), epoch1);
    lh.stop();
  }
}

static void test_lighthouse_standby_takeover() {
  // A standby absorbs heartbeats read-only; the first quorum request to
  // reach it means the fleet failed over, and it must take over with a
  // strictly higher epoch than anything it has observed.
  LighthouseOpts opt;
  opt.min_replicas = 2;
  opt.join_timeout_ms = 100;
  opt.quorum_tick_ms = 20;
  opt.heartbeat_timeout_ms = 5000;
  opt.standby = true;
  Lighthouse lh("127.0.0.1", 0, opt);
  CHECK(lh.start());
  std::string addr = lh.address();

  // Heartbeats carry the fleet's max accepted epoch (here: 3, stamped by
  // managers that accepted quorums from the dead primary).
  Json hreq = Json::object();
  hreq["type"] = Json::of("heartbeat");
  hreq["replica_id"] = Json::of(std::string("repA"));
  hreq["epoch"] = Json::of(int64_t(3));
  // ...and the max accepted quorum_id (7): the takeover must resume
  // numbering strictly above it, not restart from 1.
  hreq["quorum_id"] = Json::of(int64_t(7));
  CHECK(lighthouse_call(addr, hreq, 2000).get("ok").as_bool());

  Json sreq = Json::object();
  sreq["type"] = Json::of("status");
  Json s = lighthouse_call(addr, sreq, 2000).get("status");
  CHECK_EQ(s.get("role").as_str(), std::string("standby"));
  CHECK_EQ(s.get("observed_epoch").as_int(), 3);
  CHECK_EQ(s.get("observed_quorum_id").as_int(), 7);

  auto quorum_req = [&](const std::string& id, int64_t step) {
    Json req = Json::object();
    req["type"] = Json::of("quorum");
    req["timeout_ms"] = Json::of(int64_t(5000));
    req["requester"] = mk_member(id, step).to_json();
    return lighthouse_call(addr, req, 6000);
  };
  Json ra, rb;
  std::thread ta([&] { ra = quorum_req("repA", 1); });
  std::thread tb([&] { rb = quorum_req("repB", 1); });
  ta.join();
  tb.join();
  CHECK(ra.get("ok").as_bool());
  CHECK_EQ(ra.get("quorum").get("epoch").as_int(), 4);  // observed(3) + 1
  // Quorum ids continue past the dead primary's high-water mark.
  CHECK_EQ(ra.get("quorum").get("quorum_id").as_int(), 8);  // observed(7) + 1

  s = lighthouse_call(addr, sreq, 2000).get("status");
  CHECK_EQ(s.get("role").as_str(), std::string("active"));
  CHECK_EQ(s.get("takeovers").as_int(), 1);
  lh.stop();
}

static void test_lighthouse_demotion() {
  // A resurrected stale primary boots active, then sees heartbeats stamped
  // with the successor's higher epoch: it must fence itself out (demote to
  // standby), not compete for the fleet.
  LighthouseOpts opt;
  opt.min_replicas = 2;
  opt.join_timeout_ms = 100;
  opt.quorum_tick_ms = 20;
  opt.heartbeat_timeout_ms = 5000;
  Lighthouse lh("127.0.0.1", 0, opt);
  CHECK(lh.start());
  std::string addr = lh.address();

  Json sreq = Json::object();
  sreq["type"] = Json::of("status");
  Json s = lighthouse_call(addr, sreq, 2000).get("status");
  CHECK_EQ(s.get("role").as_str(), std::string("active"));
  CHECK_EQ(s.get("epoch").as_int(), 1);

  Json hreq = Json::object();
  hreq["type"] = Json::of("heartbeat");
  hreq["replica_id"] = Json::of(std::string("repA"));
  hreq["epoch"] = Json::of(int64_t(5));
  CHECK(lighthouse_call(addr, hreq, 2000).get("ok").as_bool());

  s = lighthouse_call(addr, sreq, 2000).get("status");
  CHECK_EQ(s.get("role").as_str(), std::string("standby"));
  CHECK_EQ(s.get("demotions").as_int(), 1);
  CHECK_EQ(s.get("observed_epoch").as_int(), 5);

  // If the fleet later fails over TO this instance (quorum request arrives),
  // it re-takes with epoch above everything observed — ids never go back.
  auto quorum_req = [&](const std::string& id, int64_t step) {
    Json req = Json::object();
    req["type"] = Json::of("quorum");
    req["timeout_ms"] = Json::of(int64_t(5000));
    req["requester"] = mk_member(id, step).to_json();
    return lighthouse_call(addr, req, 6000);
  };
  Json ra, rb;
  std::thread ta([&] { ra = quorum_req("repA", 1); });
  std::thread tb([&] { rb = quorum_req("repB", 1); });
  ta.join();
  tb.join();
  CHECK(ra.get("ok").as_bool());
  CHECK_EQ(ra.get("quorum").get("epoch").as_int(), 6);
  lh.stop();
}

static void test_manager_leave() {
  // Manager-level drain: "leave" stops the manager's heartbeat loop and
  // forwards the leave to the lighthouse, so the drained group ages out
  // instantly instead of looking healthy until heartbeat expiry.
  LighthouseOpts opt;
  opt.min_replicas = 1;
  opt.join_timeout_ms = 2000;
  opt.quorum_tick_ms = 20;
  opt.heartbeat_timeout_ms = 60000;
  Lighthouse lh("127.0.0.1", 0, opt);
  CHECK(lh.start());

  auto mk_opts = [&](const std::string& id) {
    ManagerOpts mo;
    mo.replica_id = id;
    mo.lighthouse_addr = lh.address();
    mo.store_address = "store-" + id;
    mo.world_size = 1;
    mo.heartbeat_interval_ms = 50;
    return mo;
  };
  ManagerServer mA(mk_opts("groupA"));
  ManagerServer mB(mk_opts("groupB"));
  CHECK(mA.start());
  CHECK(mB.start());
  // Let both heartbeat loops reach the lighthouse before the first quorum:
  // with min_replicas=1 an early registrant would otherwise form a singleton
  // quorum underneath the slower group.
  sleep_ms(300);

  auto quorum_req = [&](ManagerServer& m, int64_t step) {
    Json req = Json::object();
    req["type"] = Json::of("quorum");
    req["group_rank"] = Json::of(int64_t(0));
    req["step"] = Json::of(step);
    req["checkpoint_metadata"] = Json::of(std::string("meta"));
    req["init_sync"] = Json::of(false);
    req["timeout_ms"] = Json::of(int64_t(8000));
    return lighthouse_call(m.address(), req, 9000);
  };

  Json a, b;
  std::thread t0([&] { a = quorum_req(mA, 1); });
  std::thread t1([&] { b = quorum_req(mB, 1); });
  t0.join();
  t1.join();
  CHECK(a.get("ok").as_bool());
  CHECK_EQ(a.get("result").get("replica_world_size").as_int(), 2);

  Json lreq = Json::object();
  lreq["type"] = Json::of("leave");
  Json lresp = lighthouse_call(mB.address(), lreq, 3000);
  CHECK(lresp.get("ok").as_bool());
  CHECK(lresp.get("sent").as_bool());

  // mB's heartbeat loop is still running but drained: give it a few
  // intervals to prove no fresh heartbeat resurrects the entry.
  sleep_ms(200);
  Json sreq = Json::object();
  sreq["type"] = Json::of("status");
  Json s = lighthouse_call(lh.address(), sreq, 2000);
  CHECK(!s.get("status").get("heartbeat_ages_ms").obj.count("groupB"));

  // The survivor re-quorums alone at tick speed.
  int64_t t = now_ms();
  a = quorum_req(mA, 2);
  int64_t shrink_ms = now_ms() - t;
  CHECK(a.get("ok").as_bool());
  CHECK_EQ(a.get("result").get("replica_world_size").as_int(), 1);
  CHECK(shrink_ms < 1000);

  // A drained manager refuses quorum registrations (a late rank or stray
  // client must not clear the lighthouse tombstone while heartbeats stay
  // stopped), and fails FAST — no deadline wait.
  t = now_ms();
  b = quorum_req(mB, 2);
  CHECK(!b.get("ok").as_bool());
  CHECK(b.get("error").as_str().find("draining") != std::string::npos);
  CHECK(now_ms() - t < 1000);

  mA.stop();
  mB.stop();
  lh.stop();
}

static void test_operator_drain_request() {
  // Operator-initiated drain: the lighthouse "drain" RPC forwards a
  // request_drain to the member's manager; the flag rides every later
  // quorum response so the TRAINER can drain at a safe step boundary.
  LighthouseOpts opt;
  opt.min_replicas = 1;
  opt.join_timeout_ms = 2000;
  opt.quorum_tick_ms = 20;
  opt.heartbeat_timeout_ms = 60000;
  Lighthouse lh("127.0.0.1", 0, opt);
  CHECK(lh.start());

  ManagerOpts mo;
  mo.replica_id = "drainee";
  mo.lighthouse_addr = lh.address();
  mo.store_address = "store-x";
  mo.world_size = 1;
  mo.heartbeat_interval_ms = 50;
  ManagerServer m(mo);
  CHECK(m.start());
  sleep_ms(200);  // let the heartbeat register at the lighthouse

  auto quorum_req = [&](int64_t step) {
    Json req = Json::object();
    req["type"] = Json::of("quorum");
    req["group_rank"] = Json::of(int64_t(0));
    req["step"] = Json::of(step);
    req["checkpoint_metadata"] = Json::of(std::string("meta"));
    req["init_sync"] = Json::of(false);
    req["timeout_ms"] = Json::of(int64_t(8000));
    return lighthouse_call(m.address(), req, 9000);
  };

  Json a = quorum_req(1);
  CHECK(a.get("ok").as_bool());
  CHECK(!a.get("drain_requested").as_bool());

  // Operator drains via the lighthouse (the dashboard button's RPC).
  Json dreq = Json::object();
  dreq["type"] = Json::of("drain");
  dreq["replica_id"] = Json::of(std::string("drainee"));
  Json dresp = lighthouse_call(lh.address(), dreq, 3000);
  CHECK(dresp.get("ok").as_bool());
  CHECK(dresp.get("sent").as_bool());

  a = quorum_req(2);
  CHECK(a.get("ok").as_bool());
  CHECK(a.get("drain_requested").as_bool());

  // Out-of-band read (the failed-step fallback path): flag visible
  // without a successful quorum.
  Json sreq = Json::object();
  sreq["type"] = Json::of("drain_status");
  Json sresp = lighthouse_call(m.address(), sreq, 3000);
  CHECK(sresp.get("ok").as_bool());
  CHECK(sresp.get("drain_requested").as_bool());

  m.stop();
  lh.stop();
}

static void test_operator_drain_all() {
  // Whole-job operator drain: one drain_all RPC forwards request_drain
  // to EVERY registered member's manager; each member's flag rides its
  // next quorum response (the operator-triggered twin of a whole-pod
  // preemption — pairs with the trainers' durable final snapshots).
  LighthouseOpts opt;
  opt.min_replicas = 1;
  opt.join_timeout_ms = 2000;
  opt.quorum_tick_ms = 20;
  opt.heartbeat_timeout_ms = 60000;
  Lighthouse lh("127.0.0.1", 0, opt);
  CHECK(lh.start());

  auto mk = [&](const std::string& id) {
    ManagerOpts mo;
    mo.replica_id = id;
    mo.lighthouse_addr = lh.address();
    mo.store_address = "store-x";
    mo.world_size = 1;
    mo.heartbeat_interval_ms = 50;
    return new ManagerServer(mo);
  };
  ManagerServer* m0 = mk("job-a");
  ManagerServer* m1 = mk("job-b");
  CHECK(m0->start());
  CHECK(m1->start());

  auto quorum_req = [&](ManagerServer* m, int64_t step) {
    Json req = Json::object();
    req["type"] = Json::of("quorum");
    req["group_rank"] = Json::of(int64_t(0));
    req["step"] = Json::of(step);
    req["checkpoint_metadata"] = Json::of(std::string("meta"));
    req["init_sync"] = Json::of(false);
    req["timeout_ms"] = Json::of(int64_t(8000));
    return lighthouse_call(m->address(), req, 9000);
  };

  // Register BOTH members via a concurrent quorum round (drain_all
  // forwards to the lighthouse's participant map, which quorum
  // registration fills; the split-brain guard means each request waits
  // for the other, so they must be issued together).
  Json a0, a1;
  {
    std::thread t0([&] { a0 = quorum_req(m0, 1); });
    std::thread t1([&] { a1 = quorum_req(m1, 1); });
    t0.join();
    t1.join();
  }
  CHECK(a0.get("ok").as_bool());
  CHECK(a1.get("ok").as_bool());
  CHECK(!a0.get("drain_requested").as_bool());
  CHECK(!a1.get("drain_requested").as_bool());

  Json dreq = Json::object();
  dreq["type"] = Json::of("drain_all");
  Json dresp = lighthouse_call(lh.address(), dreq, 8000);
  CHECK(dresp.get("ok").as_bool());
  CHECK(dresp.get("n_sent").as_int() == 2);
  CHECK(dresp.get("n_members").as_int() == 2);
  CHECK(dresp.get("sent").get("job-a").as_bool());
  CHECK(dresp.get("sent").get("job-b").as_bool());

  {
    std::thread t0([&] { a0 = quorum_req(m0, 2); });
    std::thread t1([&] { a1 = quorum_req(m1, 2); });
    t0.join();
    t1.join();
  }
  CHECK(a0.get("drain_requested").as_bool());
  CHECK(a1.get("drain_requested").as_bool());

  m0->stop();
  m1->stop();
  delete m0;
  delete m1;
  lh.stop();
}

static void test_lighthouse_quorum_timeout() {
  LighthouseOpts opt;
  opt.min_replicas = 2;
  opt.join_timeout_ms = 50;
  opt.quorum_tick_ms = 20;
  Lighthouse lh("127.0.0.1", 0, opt);
  CHECK(lh.start());
  Json req = Json::object();
  req["type"] = Json::of("quorum");
  req["timeout_ms"] = Json::of(int64_t(300));
  req["requester"] = mk_member("lonely", 0).to_json();
  int64_t t0 = now_ms();
  Json resp = lighthouse_call(lh.address(), req, 5000);
  CHECK(!resp.get("ok").as_bool());
  CHECK(resp.get("timeout").as_bool());
  CHECK(now_ms() - t0 < 3000);
  lh.stop();
}

static void test_manager_e2e() {
  LighthouseOpts opt;
  opt.min_replicas = 2;
  opt.join_timeout_ms = 200;
  opt.quorum_tick_ms = 20;
  Lighthouse lh("127.0.0.1", 0, opt);
  CHECK(lh.start());

  auto mk_opts = [&](const std::string& id, int64_t world) {
    ManagerOpts mo;
    mo.replica_id = id;
    mo.lighthouse_addr = lh.address();
    mo.store_address = "store-" + id;
    mo.world_size = world;
    mo.heartbeat_interval_ms = 50;
    return mo;
  };
  ManagerServer mA(mk_opts("groupA", 2));
  ManagerServer mB(mk_opts("groupB", 1));
  CHECK(mA.start());
  CHECK(mB.start());

  auto quorum_req = [&](ManagerServer& m, int64_t rank, int64_t step,
                        const std::string& meta) {
    Json req = Json::object();
    req["type"] = Json::of("quorum");
    req["group_rank"] = Json::of(rank);
    req["step"] = Json::of(step);
    req["checkpoint_metadata"] = Json::of(meta);
    req["init_sync"] = Json::of(true);
    req["timeout_ms"] = Json::of(int64_t(5000));
    return lighthouse_call(m.address(), req, 6000);
  };

  // groupA has 2 local ranks, groupB has 1; groupB is ahead at step 4.
  Json a0, a1, b0;
  std::thread t0([&] { a0 = quorum_req(mA, 0, 0, "metaA0"); });
  std::thread t1([&] { a1 = quorum_req(mA, 1, 0, "metaA1"); });
  std::thread t2([&] { b0 = quorum_req(mB, 0, 4, "metaB0"); });
  t0.join();
  t1.join();
  t2.join();
  CHECK(a0.get("ok").as_bool());
  CHECK(a1.get("ok").as_bool());
  CHECK(b0.get("ok").as_bool());
  // groupA lags -> heals from groupB; groupB serves it.
  CHECK(a0.get("result").get("heal").as_bool());
  CHECK(a1.get("result").get("heal").as_bool());
  CHECK(!b0.get("result").get("heal").as_bool());
  CHECK_EQ(a0.get("result").get("max_step").as_int(), 4);
  CHECK_EQ(a0.get("result").get("recover_src_manager_address").as_str(),
           mB.address());
  CHECK_EQ(b0.get("result").get("recover_dst_replica_ranks").arr.size(),
           size_t(1));
  // Store address comes from the max-step primary (groupB).
  CHECK_EQ(a0.get("result").get("store_address").as_str(),
           std::string("store-groupB"));

  // Checkpoint metadata served to recovering peers.
  Json creq = Json::object();
  creq["type"] = Json::of("checkpoint_metadata");
  creq["rank"] = Json::of(int64_t(0));
  Json c = lighthouse_call(mB.address(), creq, 2000);
  CHECK(c.get("ok").as_bool());
  CHECK_EQ(c.get("checkpoint_metadata").as_str(), std::string("metaB0"));

  // should_commit barrier on groupA: one false vote fails everyone.
  auto commit_req = [&](ManagerServer& m, int64_t rank, bool vote) {
    Json req = Json::object();
    req["type"] = Json::of("should_commit");
    req["group_rank"] = Json::of(rank);
    req["step"] = Json::of(int64_t(1));
    req["should_commit"] = Json::of(vote);
    req["timeout_ms"] = Json::of(int64_t(5000));
    return lighthouse_call(m.address(), req, 6000);
  };
  Json ca, cb;
  std::thread c0([&] { ca = commit_req(mA, 0, true); });
  std::thread c1([&] { cb = commit_req(mA, 1, false); });
  c0.join();
  c1.join();
  CHECK(ca.get("ok").as_bool());
  CHECK(!ca.get("should_commit").as_bool());
  CHECK(!cb.get("should_commit").as_bool());
  // Next round with all-true votes succeeds (state reset between rounds).
  std::thread c2([&] { ca = commit_req(mA, 0, true); });
  std::thread c3([&] { cb = commit_req(mA, 1, true); });
  c2.join();
  c3.join();
  CHECK(ca.get("should_commit").as_bool());
  CHECK(cb.get("should_commit").as_bool());

  mA.stop();
  mB.stop();
  lh.stop();
}

static void test_split_host_port() {
  std::string host;
  int port = 0;
  CHECK(split_host_port("127.0.0.1:29510", &host, &port));
  CHECK_EQ(host, std::string("127.0.0.1"));
  CHECK_EQ(port, 29510);
  // Reference-style URL forms (TORCHFT_LIGHTHOUSE=http://host:port).
  CHECK(split_host_port("http://10.0.0.5:29510", &host, &port));
  CHECK_EQ(host, std::string("10.0.0.5"));
  CHECK_EQ(port, 29510);
  CHECK(split_host_port("http://localhost:80/", &host, &port));
  CHECK_EQ(host, std::string("localhost"));
  CHECK(split_host_port("[::1]:9", &host, &port));
  CHECK_EQ(port, 9);
  CHECK(!split_host_port("nocolon", &host, &port));
  CHECK(!split_host_port("http://", &host, &port));
}

static void test_drain_all_reaches_heartbeat_only_replica() {
  // The drain_all blind spot: a replica that heartbeats but never
  // registered a quorum appears in neither prev_quorum nor participants.
  // Heartbeats now carry the manager address, so drain_all reaches it.
  LighthouseOpts opt;
  opt.min_replicas = 1;
  opt.join_timeout_ms = 200;
  opt.quorum_tick_ms = 20;
  opt.heartbeat_timeout_ms = 60000;
  Lighthouse lh("127.0.0.1", 0, opt);
  CHECK(lh.start());

  auto mk = [&](const std::string& id) {
    ManagerOpts mo;
    mo.replica_id = id;
    mo.lighthouse_addr = lh.address();
    mo.store_address = "store-x";
    mo.world_size = 1;
    mo.heartbeat_interval_ms = 50;
    return new ManagerServer(mo);
  };
  ManagerServer* registered = mk("hb-registered");
  CHECK(registered->start());

  // Register one replica through a quorum round first (so the split-brain
  // guard doesn't count the unregistered heartbeat against it)...
  Json req = Json::object();
  req["type"] = Json::of("quorum");
  req["group_rank"] = Json::of(int64_t(0));
  req["step"] = Json::of(int64_t(1));
  req["checkpoint_metadata"] = Json::of(std::string("meta"));
  req["init_sync"] = Json::of(false);
  req["timeout_ms"] = Json::of(int64_t(8000));
  Json qresp = lighthouse_call(registered->address(), req, 9000);
  CHECK(qresp.get("ok").as_bool());

  // ...then bring up a second that only heartbeats (a trainer wedged before
  // its first quorum RPC).
  ManagerServer* hb_only = mk("hb-only");
  CHECK(hb_only->start());
  sleep_ms(300);  // several heartbeat intervals for hb-only

  Json dreq = Json::object();
  dreq["type"] = Json::of("drain_all");
  Json dresp = lighthouse_call(lh.address(), dreq, 8000);
  CHECK(dresp.get("ok").as_bool());
  CHECK_EQ(dresp.get("n_members").as_int(), 2);
  CHECK(dresp.get("sent").get("hb-registered").as_bool());
  CHECK(dresp.get("sent").get("hb-only").as_bool());

  // The heartbeat-only replica actually observed the drain request.
  Json sreq = Json::object();
  sreq["type"] = Json::of("drain_status");
  Json sresp = lighthouse_call(hb_only->address(), sreq, 3000);
  CHECK(sresp.get("ok").as_bool());
  CHECK(sresp.get("drain_requested").as_bool());

  registered->stop();
  hb_only->stop();
  delete registered;
  delete hb_only;
  lh.stop();
}

// --------------------------------------------------------------------------
// Native collective engine (collectives.cc)
// --------------------------------------------------------------------------

static std::vector<std::unique_ptr<CollectiveEngine>> engine_mesh(
    int ws, int streams, int64_t pipeline_bytes = 1 << 20,
    int fr_capacity = 0) {
  std::vector<std::unique_ptr<CollectiveEngine>> es;
  std::vector<std::string> addrs(ws);
  for (int i = 0; i < ws; ++i) {
    es.push_back(std::make_unique<CollectiveEngine>(streams, pipeline_bytes,
                                                    fr_capacity));
    int p = es[i]->listen("127.0.0.1");
    CHECK(p > 0);
    addrs[i] = "127.0.0.1:" + std::to_string(p);
  }
  std::vector<int> oks(ws, 0);
  std::vector<std::thread> ts;
  for (int i = 0; i < ws; ++i)
    ts.emplace_back([&, i] { oks[i] = es[i]->connect_mesh(i, ws, addrs, 8000); });
  for (auto& t : ts) t.join();
  for (int i = 0; i < ws; ++i) CHECK(oks[i]);
  return es;
}

static void test_native_ring_allreduce() {
  const int ws = 3;
  auto es = engine_mesh(ws, 2);
  // fp32 SUM over a count not divisible by ws or the stripe count; values
  // are small integers so the float sums are exact.
  const uint64_t n = 1000 + 7;
  std::vector<std::vector<float>> bufs(ws);
  for (int r = 0; r < ws; ++r) {
    bufs[r].resize(n);
    for (uint64_t i = 0; i < n; ++i)
      bufs[r][i] = static_cast<float>((r + 1) * static_cast<int>(i % 100));
  }
  std::vector<int> oks(ws, 0);
  std::vector<std::thread> ts;
  for (int r = 0; r < ws; ++r)
    ts.emplace_back([&, r] {
      oks[r] = es[r]->allreduce(bufs[r].data(), n, TFT_DT_F32, TFT_OP_SUM,
                                8000);
    });
  for (auto& t : ts) t.join();
  for (int r = 0; r < ws; ++r) CHECK(oks[r]);
  bool all_ok = true;
  for (int r = 0; r < ws; ++r)
    for (uint64_t i = 0; i < n; ++i)
      all_ok = all_ok &&
               bufs[r][i] == static_cast<float>(6 * static_cast<int>(i % 100));
  CHECK(all_ok);
  CHECK(es[0]->bytes_tx() > 0);
  CHECK(es[0]->bytes_rx() > 0);

  // i64 MAX.
  std::vector<std::vector<int64_t>> ib(ws);
  const uint64_t m = 97;
  for (int r = 0; r < ws; ++r) {
    ib[r].resize(m);
    for (uint64_t i = 0; i < m; ++i)
      ib[r][i] = static_cast<int64_t>(i) * (r == 1 ? -1 : 1) + r;
  }
  ts.clear();
  for (int r = 0; r < ws; ++r)
    ts.emplace_back([&, r] {
      oks[r] = es[r]->allreduce(ib[r].data(), m, TFT_DT_I64, TFT_OP_MAX, 8000);
    });
  for (auto& t : ts) t.join();
  for (int r = 0; r < ws; ++r) CHECK(oks[r]);
  bool max_ok = true;
  for (uint64_t i = 0; i < m; ++i) {
    int64_t want = std::max<int64_t>(
        {static_cast<int64_t>(i), -static_cast<int64_t>(i) + 1,
         static_cast<int64_t>(i) + 2});
    for (int r = 0; r < ws; ++r) max_ok = max_ok && ib[r][i] == want;
  }
  CHECK(max_ok);
}

static void test_native_q8_allreduce() {
  const int ws = 2;
  auto es = engine_mesh(ws, 2);
  // Big enough for the chunked path (blocks >= ws) and a ragged tail.
  const uint64_t n = 512 * 6 + 13;
  std::vector<std::vector<float>> bufs(ws), orig(ws);
  for (int r = 0; r < ws; ++r) {
    bufs[r].resize(n);
    for (uint64_t i = 0; i < n; ++i)
      bufs[r][i] = 0.01f * static_cast<float>((i * (r + 3)) % 257) -
                   1.2f * static_cast<float>(r);
    orig[r] = bufs[r];
  }
  std::vector<int> oks(ws, 0);
  std::vector<std::thread> ts;
  for (int r = 0; r < ws; ++r)
    ts.emplace_back(
        [&, r] { oks[r] = es[r]->allreduce_q8(bufs[r].data(), n, 8000); });
  for (auto& t : ts) t.join();
  for (int r = 0; r < ws; ++r) CHECK(oks[r]);
  // Cross-rank bitwise identical (everyone decodes the same bytes).
  CHECK(memcmp(bufs[0].data(), bufs[1].data(), n * sizeof(float)) == 0);
  // Within quantization tolerance of the true fp32 sum: two lossy steps,
  // each bounded by half a quantization step of its block absmax.
  bool tol_ok = true;
  for (uint64_t i = 0; i < n; ++i) {
    const float want = orig[0][i] + orig[1][i];
    tol_ok = tol_ok && std::abs(bufs[0][i] - want) < 0.08f;
  }
  CHECK(tol_ok);

  // Tiny payload (blocks < ws): allgather fallback, exact fp32 sum path
  // still within one quantize round trip of truth.
  const uint64_t tiny = 40;
  std::vector<std::vector<float>> tb(ws);
  for (int r = 0; r < ws; ++r) {
    tb[r].resize(tiny);
    for (uint64_t i = 0; i < tiny; ++i)
      tb[r][i] = static_cast<float>(r + 1) * 0.25f * static_cast<float>(i);
  }
  ts.clear();
  for (int r = 0; r < ws; ++r)
    ts.emplace_back(
        [&, r] { oks[r] = es[r]->allreduce_q8(tb[r].data(), tiny, 8000); });
  for (auto& t : ts) t.join();
  for (int r = 0; r < ws; ++r) CHECK(oks[r]);
  CHECK(memcmp(tb[0].data(), tb[1].data(), tiny * sizeof(float)) == 0);
  // One quantization per input, no requantize on this path: error bound is
  // one half-step of each rank's block absmax (~19.5/127/2 each).
  bool tiny_ok = true;
  for (uint64_t i = 0; i < tiny; ++i) {
    const float want = 3.f * 0.25f * static_cast<float>(i);
    tiny_ok = tiny_ok && std::abs(tb[0][i] - want) < 0.2f;
  }
  CHECK(tiny_ok);
}

static void test_native_allgather_broadcast() {
  const int ws = 3;
  auto es = engine_mesh(ws, 2);
  // Ragged allgather with opaque metadata.
  std::vector<std::string> payloads(ws), metas(ws);
  for (int r = 0; r < ws; ++r) {
    payloads[r] = std::string(100 + 37 * r, static_cast<char>('a' + r));
    metas[r] = "{\"rank\":" + std::to_string(r) + "}";
  }
  std::vector<int> oks(ws, 0);
  std::vector<std::thread> ts;
  for (int r = 0; r < ws; ++r)
    ts.emplace_back([&, r] {
      oks[r] = es[r]->allgather(metas[r], payloads[r].data(),
                                payloads[r].size(), 8000);
    });
  for (auto& t : ts) t.join();
  for (int r = 0; r < ws; ++r) CHECK(oks[r]);
  bool ag_ok = true;
  for (int r = 0; r < ws; ++r)
    for (int p = 0; p < ws; ++p) {
      if (p == r) continue;  // own slot is the caller's job
      ag_ok = ag_ok && es[r]->result_meta(p) == metas[p] &&
              es[r]->result_payload(p) == payloads[p];
    }
  CHECK(ag_ok);

  // Broadcast from a non-zero root.
  const int root = 1;
  std::string blob(4096 + 11, 'x');
  ts.clear();
  for (int r = 0; r < ws; ++r)
    ts.emplace_back([&, r] {
      if (r == root)
        oks[r] = es[r]->broadcast("bmeta", blob.data(), blob.size(), root,
                                  8000);
      else
        oks[r] = es[r]->broadcast("", nullptr, 0, root, 8000);
    });
  for (auto& t : ts) t.join();
  for (int r = 0; r < ws; ++r) CHECK(oks[r]);
  for (int r = 0; r < ws; ++r) {
    if (r == root) continue;
    CHECK(es[r]->result_meta(root) == std::string("bmeta"));
    CHECK(es[r]->result_payload(root) == blob);
  }
}

static void test_native_flight_recorder() {
  const int ws = 2;
  const int cap = 4;
  auto es = engine_mesh(ws, 2, 1 << 20, cap);
  for (int r = 0; r < ws; ++r) es[r]->set_trace("q1.s1|c0");
  // Run more collectives than the ring holds: the oldest must be evicted
  // (dropped counter), the newest cap records must survive with their seqs.
  const int n_ops = 6;
  const uint64_t n = 4096;
  for (int i = 0; i < n_ops; ++i) {
    std::vector<std::vector<float>> bufs(ws);
    std::vector<int> oks(ws, 0);
    std::vector<std::thread> ts;
    for (int r = 0; r < ws; ++r) bufs[r].assign(n, 1.0f * (r + 1));
    for (int r = 0; r < ws; ++r)
      ts.emplace_back([&, r] {
        oks[r] = es[r]->allreduce(bufs[r].data(), n, TFT_DT_F32, TFT_OP_SUM,
                                  8000);
      });
    for (auto& t : ts) t.join();
    for (int r = 0; r < ws; ++r) CHECK(oks[r]);
  }
  CHECK_EQ(static_cast<long long>(es[0]->fr_seq()), n_ops);
  CHECK_EQ(static_cast<long long>(es[0]->fr_dropped()), n_ops - cap);
  Json snap;
  CHECK(Json::parse(es[0]->fr_snapshot(0), &snap));
  CHECK_EQ(snap.get("seq").as_int(), n_ops);
  CHECK_EQ(snap.get("capacity").as_int(), cap);
  CHECK_EQ(snap.get("dropped").as_int(), n_ops - cap);
  const auto& recs = snap.get("records").arr;
  CHECK_EQ(static_cast<long long>(recs.size()), cap);
  for (size_t i = 0; i < recs.size(); ++i) {
    const Json& r = recs[i];
    // Surviving seqs are the newest `cap`: n_ops-cap+1 .. n_ops, in order.
    CHECK_EQ(r.get("seq").as_int(),
             static_cast<int64_t>(n_ops - cap + 1 + i));
    CHECK(r.get("op").as_str() == "allreduce");
    CHECK(r.get("status").as_str() == "ok");
    CHECK(r.get("tag").as_str() == "q1.s1|c0");
    CHECK_EQ(r.get("bytes").as_int(), static_cast<int64_t>(n * 4));
    CHECK(r.get("t_end_ns").as_int() >= r.get("t_start_ns").as_int());
    // ws=2 ring: 1 reduce-scatter + 1 allgather step stamp.
    CHECK_EQ(static_cast<long long>(r.get("step_ns").arr.size()), 2);
    CHECK(!r.get("lanes").arr.empty());
    bool saw_reduce = false;
    for (const auto& lane : r.get("lanes").arr) {
      CHECK_EQ(lane.get("peer").as_int(), 1);
      CHECK(lane.get("t1_ns").as_int() >= lane.get("t0_ns").as_int());
      if (lane.get("dir").as_str() == "recv_reduce") saw_reduce = true;
    }
    CHECK(saw_reduce);
  }
  // Per-peer counters present and plausible.
  CHECK_EQ(static_cast<long long>(snap.get("peers").arr.size()), ws - 1);
  CHECK(snap.get("peers").arr[0].get("tx_bytes").as_int() > 0);
  CHECK(snap.get("peers").arr[0].get("rx_bytes").as_int() > 0);
  // Incremental drain: since_seq = seq returns no records.
  Json empty_snap;
  CHECK(Json::parse(es[0]->fr_snapshot(es[0]->fr_seq()), &empty_snap));
  CHECK(empty_snap.get("records").arr.empty());

  // Snapshot is safe while a collective is in flight: hammer it from a
  // second thread during allreduces; every snapshot must stay parseable.
  std::atomic<bool> stop{false};
  std::atomic<int> parsed{0};
  std::thread sampler([&] {
    while (!stop.load()) {
      Json s;
      if (Json::parse(es[0]->fr_snapshot(0), &s)) parsed.fetch_add(1);
    }
  });
  for (int i = 0; i < 10; ++i) {
    std::vector<std::vector<float>> bufs(ws);
    std::vector<std::thread> ts;
    for (int r = 0; r < ws; ++r) bufs[r].assign(1 << 16, 2.0f);
    for (int r = 0; r < ws; ++r)
      ts.emplace_back([&, r] {
        es[r]->allreduce(bufs[r].data(), bufs[r].size(), TFT_DT_F32,
                         TFT_OP_SUM, 8000);
      });
    for (auto& t : ts) t.join();
  }
  stop.store(true);
  sampler.join();
  CHECK(parsed.load() > 0);

  // Recording off (capacity 0): no records, snapshot still well-formed.
  auto off = engine_mesh(ws, 2);
  std::vector<std::vector<float>> bufs(ws);
  std::vector<std::thread> ts;
  for (int r = 0; r < ws; ++r) bufs[r].assign(256, 1.0f);
  for (int r = 0; r < ws; ++r)
    ts.emplace_back([&, r] {
      off[r]->allreduce(bufs[r].data(), bufs[r].size(), TFT_DT_F32, TFT_OP_SUM,
                        8000);
    });
  for (auto& t : ts) t.join();
  CHECK_EQ(static_cast<long long>(off[0]->fr_seq()), 0);
  Json off_snap;
  CHECK(Json::parse(off[0]->fr_snapshot(0), &off_snap));
  CHECK(off_snap.get("records").arr.empty());
  // The always-on per-peer counters still tick with the ring off.
  CHECK(off_snap.get("peers").arr[0].get("tx_bytes").as_int() > 0);
}

static void test_native_abort_unblocks() {
  const int ws = 2;
  auto es = engine_mesh(ws, 2);
  // Rank 0 enters an allreduce alone; rank 1 never joins. Abort must
  // unblock it promptly (the socket-PG abort semantics, not a timeout).
  std::vector<float> buf(4096, 1.f);
  std::thread killer([&] {
    sleep_ms(200);
    es[0]->abort("test abort");
  });
  const int64_t t0 = now_ms();
  bool ok = es[0]->allreduce(buf.data(), buf.size(), TFT_DT_F32, TFT_OP_SUM,
                             60 * 1000);
  killer.join();
  CHECK(!ok);
  CHECK(now_ms() - t0 < 5000);  // did not wait out the 60s timeout
  CHECK(es[0]->last_error().find("aborted") != std::string::npos);
}

static void test_latency_hist() {
  // Bucket boundaries mirror telemetry._HIST_BOUNDS: bucket i covers
  // samples <= 2^i us, with bisect_left semantics (an exact power of two
  // lands in its own bucket, not the next).
  CHECK_EQ(LatencyHist::bucket_of(0), 0);
  CHECK_EQ(LatencyHist::bucket_of(1), 0);
  CHECK_EQ(LatencyHist::bucket_of(2), 1);
  CHECK_EQ(LatencyHist::bucket_of(3), 2);
  CHECK_EQ(LatencyHist::bucket_of(4), 2);
  CHECK_EQ(LatencyHist::bucket_of(5), 3);
  CHECK_EQ(LatencyHist::bucket_of(int64_t{1} << 27), 27);
  CHECK_EQ(LatencyHist::bucket_of((int64_t{1} << 27) + 1),
           LatencyHist::kFinite);  // overflow
  LatencyHist h;
  LatencyHist::Snap empty = h.snapshot();
  CHECK_EQ(LatencyHist::percentile_us(empty, 0.5), int64_t{0});
  // A single occupied bucket answers every quantile with its upper bound
  // (telemetry pins the same edge cases).
  h.observe_us(100);  // -> bucket 7 (2^7 = 128)
  LatencyHist::Snap one = h.snapshot();
  CHECK_EQ(one.count, int64_t{1});
  CHECK_EQ(LatencyHist::percentile_us(one, 0.0), int64_t{128});
  CHECK_EQ(LatencyHist::percentile_us(one, 0.5), int64_t{128});
  CHECK_EQ(LatencyHist::percentile_us(one, 0.99), int64_t{128});
  // 90 fast + 10 slow: p50 reports the fast bucket, p95+ the slow one.
  LatencyHist h2;
  for (int i = 0; i < 90; i++) h2.observe_us(3);    // bucket 2 (bound 4)
  for (int i = 0; i < 10; i++) h2.observe_us(5000);  // bucket 13 (8192)
  LatencyHist::Snap s2 = h2.snapshot();
  CHECK_EQ(s2.count, int64_t{100});
  CHECK_EQ(LatencyHist::percentile_us(s2, 0.50), int64_t{4});
  CHECK_EQ(LatencyHist::percentile_us(s2, 0.95), int64_t{8192});
  // Overflow samples report the last finite bound.
  LatencyHist h3;
  h3.observe_us(int64_t{1} << 30);
  CHECK_EQ(LatencyHist::percentile_us(h3.snapshot(), 0.5),
           int64_t{1} << (LatencyHist::kFinite - 1));
}

static void test_median_tracker() {
  // The incremental median must equal the old full-sort upper median
  // sorted[n/2] after every operation of a deterministic insert/erase churn.
  MedianTracker t;
  std::vector<double> live;
  uint64_t rng = 0x243f6a8885a308d3ull;  // fixed seed: deterministic test
  auto next = [&rng]() {
    rng ^= rng << 13;
    rng ^= rng >> 7;
    rng ^= rng << 17;
    return rng;
  };
  for (int op = 0; op < 2000; op++) {
    bool do_erase = !live.empty() && (next() % 3 == 0);
    if (do_erase) {
      size_t idx = next() % live.size();
      t.erase(live[idx]);
      live.erase(live.begin() + idx);
    } else {
      // Small value space so duplicates are common (the hard case).
      double v = static_cast<double>(next() % 37) * 0.25;
      t.insert(v);
      live.push_back(v);
    }
    CHECK_EQ(t.size(), live.size());
    if (!live.empty()) {
      std::vector<double> sorted = live;
      std::sort(sorted.begin(), sorted.end());
      CHECK_EQ(t.median(), sorted[sorted.size() / 2]);
    }
  }
  // Erasing an absent value is a no-op, not a crash.
  MedianTracker t2;
  t2.insert(1.0);
  t2.erase(99.0);
  CHECK_EQ(t2.size(), size_t(1));
  CHECK_EQ(t2.median(), 1.0);
}

static Json fleet_heartbeat(const std::string& addr, const std::string& id,
                            int64_t step, double rate) {
  Json req = Json::object();
  req["type"] = Json::of("heartbeat");
  req["replica_id"] = Json::of(id);
  req["hb_interval_ms"] = Json::of(int64_t(100));
  Json d = Json::object();
  d["v"] = Json::of(int64_t(1));
  d["step"] = Json::of(step);
  d["rate"] = Json::of(rate);
  d["gp"] = Json::of(0.9);
  d["cf"] = Json::of(int64_t(0));
  req["digest"] = d;
  return lighthouse_call(addr, req, 3000);
}

static Json fleet_fetch(const std::string& addr) {
  Json req = Json::object();
  req["type"] = Json::of("fleet");
  return lighthouse_call(addr, req, 3000);
}

static void test_fleet_snapshot_cache() {
  // fleet_snap_ms > 0: a mutation inside the staleness window is NOT
  // visible (cached snapshot, same gen + ts_ms); after the window expires
  // the next fetch rebuilds and the generation advances.
  LighthouseOpts opt;
  opt.min_replicas = 1;
  opt.join_timeout_ms = 100;
  opt.quorum_tick_ms = 50;
  opt.heartbeat_timeout_ms = 5000;
  opt.fleet_snap_ms = 200;
  Lighthouse lh("127.0.0.1", 0, opt);
  CHECK(lh.start());
  std::string addr = lh.address();

  CHECK(fleet_heartbeat(addr, "r0", 5, 1.0).get("ok").as_bool());
  Json f1 = fleet_fetch(addr).get("fleet");
  CHECK(f1.get("replicas").has("r0"));
  CHECK_EQ(f1.get("snap_ms").as_int(), int64_t{200});
  int64_t gen1 = f1.get("gen").as_int(-1);
  CHECK(gen1 >= 1);

  CHECK(fleet_heartbeat(addr, "r1", 5, 1.0).get("ok").as_bool());
  Json f2 = fleet_fetch(addr).get("fleet");
  // Served from cache: identical generation and build stamp, r1 invisible.
  CHECK_EQ(f2.get("gen").as_int(-1), gen1);
  CHECK_EQ(f2.get("ts_ms").as_int(), f1.get("ts_ms").as_int());
  CHECK(!f2.get("replicas").has("r1"));

  sleep_ms(250);  // let the staleness bound lapse
  Json f3 = fleet_fetch(addr).get("fleet");
  CHECK(f3.get("replicas").has("r1"));
  CHECK(f3.get("gen").as_int(-1) > gen1);
  CHECK_EQ(f3.get("agg").get("n").as_int(), int64_t{2});
  CHECK(f3.get("agg").has("anomalies_dropped"));

  // Hot-path histograms ride status.json: the heartbeats above must have
  // been observed, and every named path must export the full stat dict.
  Json sreq = Json::object();
  sreq["type"] = Json::of("status");
  Json st = lighthouse_call(addr, sreq, 3000).get("status");
  CHECK(st.has("hist"));
  Json hb = st.get("hist").get("heartbeat");
  CHECK(hb.get("count").as_int() >= 2);
  CHECK(hb.get("p95_us").as_int() >= 1);
  for (const char* path : {"heartbeat", "quorum_compute", "anomaly_eval",
                           "http", "fleet_snapshot"}) {
    Json hj = st.get("hist").get(path);
    CHECK(hj.has("count"));
    CHECK(hj.has("p50_us"));
    CHECK(hj.has("p99_us"));
  }
  lh.stop();

  // fleet_snap_ms == 0 (the embedder/test default): every fetch rebuilds,
  // so a write is visible on the very next read.
  LighthouseOpts opt0 = opt;
  opt0.fleet_snap_ms = 0;
  Lighthouse lh0("127.0.0.1", 0, opt0);
  CHECK(lh0.start());
  std::string addr0 = lh0.address();
  CHECK(fleet_heartbeat(addr0, "a", 1, 1.0).get("ok").as_bool());
  Json g1 = fleet_fetch(addr0).get("fleet");
  CHECK(g1.get("replicas").has("a"));
  CHECK(fleet_heartbeat(addr0, "b", 1, 1.0).get("ok").as_bool());
  Json g2 = fleet_fetch(addr0).get("fleet");
  CHECK(g2.get("replicas").has("b"));
  CHECK(g2.get("gen").as_int(-1) > g1.get("gen").as_int(-1));
  lh0.stop();
}

static void test_fleet_snapshot_concurrent() {
  // Pollers racing heartbeats across TTL expiries: the single-flight
  // rebuild must keep every served payload internally consistent —
  // agg.n and the replicas object are copied in one critical section,
  // so they must agree within any one payload even while the table
  // grows underneath. TSan exercises the rebuild_mu_/snap_mu_/mu_
  // ordering here; a 5 ms TTL forces many concurrent expiries.
  LighthouseOpts opt;
  opt.min_replicas = 1;
  opt.join_timeout_ms = 100;
  opt.quorum_tick_ms = 50;
  opt.heartbeat_timeout_ms = 5000;
  opt.fleet_snap_ms = 5;
  Lighthouse lh("127.0.0.1", 0, opt);
  CHECK(lh.start());
  std::string addr = lh.address();
  CHECK(fleet_heartbeat(addr, "w0", 1, 1.0).get("ok").as_bool());

  std::atomic<int> bad{0};
  std::atomic<int> fetched{0};
  std::vector<std::thread> ts;
  for (int w = 0; w < 2; w++) {
    ts.emplace_back([&, w] {
      for (int i = 0; i < 25; i++) {
        char id[16];
        std::snprintf(id, sizeof(id), "w%d_%d", w, i);
        fleet_heartbeat(addr, id, i, 1.0);
      }
    });
  }
  for (int p = 0; p < 4; p++) {
    ts.emplace_back([&] {
      for (int i = 0; i < 40; i++) {
        Json f = fleet_fetch(addr).get("fleet");
        if (!f.has("agg") || !f.has("replicas")) {
          bad.fetch_add(1);
          continue;
        }
        int64_t n = f.get("agg").get("n").as_int(-1);
        int64_t rows = static_cast<int64_t>(f.get("replicas").obj.size());
        if (n != rows) bad.fetch_add(1);
        fetched.fetch_add(1);
      }
    });
  }
  for (auto& t : ts) t.join();
  CHECK_EQ(bad.load(), 0);
  CHECK_EQ(fetched.load(), 4 * 40);
  // Everything the writers sent eventually lands: one more fetch after
  // the TTL lapses sees the full table.
  sleep_ms(10);
  Json last = fleet_fetch(addr).get("fleet");
  CHECK_EQ(last.get("agg").get("n").as_int(), int64_t{51});
  lh.stop();
}

static Json fleet_heartbeat_job(const std::string& addr, const std::string& job,
                                const std::string& id, int64_t step,
                                double rate) {
  Json req = Json::object();
  req["type"] = Json::of("heartbeat");
  req["replica_id"] = Json::of(id);
  req["job"] = Json::of(job);
  // Generous advertised interval: these tests heartbeat once and move on;
  // the hb_gap detector must not fire on its own mid-test.
  req["hb_interval_ms"] = Json::of(int64_t(60000));
  Json d = Json::object();
  d["v"] = Json::of(int64_t(1));
  d["step"] = Json::of(step);
  d["rate"] = Json::of(rate);
  d["gp"] = Json::of(0.9);
  d["cf"] = Json::of(int64_t(0));
  req["digest"] = d;
  return lighthouse_call(addr, req, 3000);
}

static Json fleet_fetch_job(const std::string& addr, const std::string& job) {
  Json req = Json::object();
  req["type"] = Json::of("fleet");
  req["job"] = Json::of(job);
  return lighthouse_call(addr, req, 3000);
}

static Json quorum_req_job(const std::string& addr, const std::string& job,
                           const std::string& id, int64_t step) {
  Json req = Json::object();
  req["type"] = Json::of("quorum");
  req["timeout_ms"] = Json::of(int64_t(5000));
  req["requester"] = mk_member(id, step).to_json();
  if (!job.empty()) req["job"] = Json::of(job);
  return lighthouse_call(addr, req, 6000);
}

static void test_job_namespace_isolation() {
  // Two job islands on one lighthouse: churn + anomalies in job alpha must
  // not bump job beta's quorum generation, quorum id, anomaly ring, or
  // fleet generation — the hard-isolation contract of the namespace plane.
  LighthouseOpts opt;
  opt.min_replicas = 1;
  opt.join_timeout_ms = 100;
  opt.quorum_tick_ms = 20;
  opt.heartbeat_timeout_ms = 5000;
  Lighthouse lh("127.0.0.1", 0, opt);
  CHECK(lh.start());
  std::string addr = lh.address();

  // Form one quorum in each namespace.
  Json qa = quorum_req_job(addr, "alpha", "a0", 1);
  Json qb = quorum_req_job(addr, "beta", "b0", 1);
  CHECK(qa.get("ok").as_bool());
  CHECK(qb.get("ok").as_bool());
  CHECK_EQ(qa.get("quorum").get("job").as_str(), std::string("alpha"));
  CHECK_EQ(qb.get("quorum").get("job").as_str(), std::string("beta"));
  // Ids are per-job: both namespaces start their numbering at 1.
  CHECK_EQ(qa.get("quorum").get("quorum_id").as_int(), int64_t{1});
  CHECK_EQ(qb.get("quorum").get("quorum_id").as_int(), int64_t{1});
  CHECK(fleet_heartbeat_job(addr, "beta", "b0", 1, 1.0).get("ok").as_bool());

  Json sreq = Json::object();
  sreq["type"] = Json::of("status");
  Json s0 = lighthouse_call(addr, sreq, 3000).get("status");
  Json b0 = s0.get("jobs").get("beta");
  int64_t beta_gen0 = b0.get("quorum_generation").as_int(-1);
  int64_t beta_qid0 = b0.get("quorum_id").as_int(-1);
  int64_t beta_aseq0 = b0.get("fleet").get("anomaly_seq").as_int(-1);
  CHECK(beta_gen0 >= 1);

  // Churn storm in alpha: memberships come and go, a straggler digest
  // raises an anomaly — all inside alpha's island.
  for (int round = 0; round < 3; round++) {
    Json q1 = quorum_req_job(addr, "alpha", "a0", round + 2);
    std::string extra = "a_extra_" + std::to_string(round);
    Json q2 = quorum_req_job(addr, "alpha", extra, 1);
    CHECK(q1.get("ok").as_bool() || q2.get("ok").as_bool());
    Json lv = Json::object();
    lv["type"] = Json::of("leave");
    lv["replica_id"] = Json::of(extra);
    lv["job"] = Json::of("alpha");
    CHECK(lighthouse_call(addr, lv, 3000).get("ok").as_bool());
  }
  // Anomaly in alpha: a commit-failure streak flags commit_stall.
  {
    Json req = Json::object();
    req["type"] = Json::of("heartbeat");
    req["replica_id"] = Json::of(std::string("a0"));
    req["job"] = Json::of(std::string("alpha"));
    req["hb_interval_ms"] = Json::of(int64_t(100));
    Json d = Json::object();
    d["v"] = Json::of(int64_t(1));
    d["step"] = Json::of(int64_t(5));
    d["rate"] = Json::of(1.0);
    d["gp"] = Json::of(0.9);
    d["cf"] = Json::of(int64_t(5));
    req["digest"] = d;
    CHECK(lighthouse_call(addr, req, 3000).get("ok").as_bool());
  }

  Json s1 = lighthouse_call(addr, sreq, 3000).get("status");
  Json a1 = s1.get("jobs").get("alpha");
  Json b1 = s1.get("jobs").get("beta");
  // Alpha saw churn + an anomaly...
  CHECK(a1.get("quorum_generation").as_int() > 1);
  CHECK(a1.get("fleet").get("anomaly_seq").as_int() >= 1);
  // ...beta is bit-exact untouched.
  CHECK_EQ(b1.get("quorum_generation").as_int(), beta_gen0);
  CHECK_EQ(b1.get("quorum_id").as_int(), beta_qid0);
  CHECK_EQ(b1.get("fleet").get("anomaly_seq").as_int(), beta_aseq0);
  // Per-job fleet tables: alpha's rows never leak into beta's payload.
  Json fb = fleet_fetch_job(addr, "beta").get("fleet");
  CHECK_EQ(fb.get("job").as_str(), std::string("beta"));
  CHECK(fb.get("replicas").has("b0"));
  CHECK(!fb.get("replicas").has("a0"));
  // Anomaly records carry their job tag.
  Json fa = fleet_fetch_job(addr, "alpha").get("fleet");
  CHECK(fa.get("anomalies").arr.size() >= 1);
  CHECK_EQ(fa.get("anomalies").arr[0].get("job").as_str(),
           std::string("alpha"));
  lh.stop();
}

static void test_job_wire_backcompat() {
  // Pre-namespace client against a namespaced lighthouse: frames without a
  // "job" key land in the default island, and the delivered quorum still
  // parses for a client that ignores the new field. Both directions of the
  // compat contract.
  LighthouseOpts opt;
  opt.min_replicas = 1;
  opt.join_timeout_ms = 100;
  opt.quorum_tick_ms = 20;
  opt.heartbeat_timeout_ms = 5000;
  Lighthouse lh("127.0.0.1", 0, opt);
  CHECK(lh.start());
  std::string addr = lh.address();

  // Old-style heartbeat + quorum (no job key anywhere).
  Json hreq = Json::object();
  hreq["type"] = Json::of("heartbeat");
  hreq["replica_id"] = Json::of(std::string("legacy"));
  CHECK(lighthouse_call(addr, hreq, 3000).get("ok").as_bool());
  Json q = quorum_req_job(addr, "", "legacy", 1);
  CHECK(q.get("ok").as_bool());
  // The namespaced lighthouse stamps the default namespace on the quorum.
  CHECK_EQ(q.get("quorum").get("job").as_str(), std::string("default"));
  // The legacy replica lives in the default island (top-level status view).
  Json sreq = Json::object();
  sreq["type"] = Json::of("status");
  Json s = lighthouse_call(addr, sreq, 3000).get("status");
  CHECK(s.get("heartbeat_ages_ms").has("legacy"));
  CHECK_EQ(s.get("jobs").get("default").get("members").as_int(), int64_t{1});
  // Old framed fleet fetch (no job key) serves the composite payload with
  // the pre-namespace top-level schema intact.
  Json f = fleet_fetch(addr).get("fleet");
  CHECK(f.get("replicas").has("legacy"));
  CHECK(f.has("agg"));
  CHECK(f.has("jobs"));
  lh.stop();

  // Other direction: a quorum dict from a PRE-namespace lighthouse (no job
  // key) parses into the default namespace.
  Json old_q = Json::object();
  old_q["quorum_id"] = Json::of(int64_t(7));
  old_q["created_ms"] = Json::of(int64_t(123));
  old_q["participants"] = Json::array();
  Quorum parsed = Quorum::from_json(old_q);
  CHECK_EQ(parsed.job, std::string("default"));
  CHECK_EQ(parsed.quorum_id, int64_t{7});
  // And a namespaced quorum round-trips its job tag.
  Quorum tagged;
  tagged.quorum_id = 9;
  tagged.job = "alpha";
  Quorum back = Quorum::from_json(tagged.to_json());
  CHECK_EQ(back.job, std::string("alpha"));
}

static void test_incremental_quorum_gate() {
  // The registration path must form quorums WITHOUT the periodic tick: a
  // huge quorum_tick_ms takes the timer out of the picture, so only the
  // O(1) gate firing the inline quorum_compute can complete these rounds.
  LighthouseOpts opt;
  opt.min_replicas = 2;
  opt.join_timeout_ms = 5000;
  opt.quorum_tick_ms = 60000;  // timer effectively disabled
  opt.heartbeat_timeout_ms = 5000;
  Lighthouse lh("127.0.0.1", 0, opt);
  CHECK(lh.start());
  std::string addr = lh.address();

  int64_t t0 = now_ms();
  Json ra, rb;
  std::thread ta([&] { ra = quorum_req_job(addr, "", "repA", 1); });
  std::thread tb([&] { rb = quorum_req_job(addr, "", "repB", 1); });
  ta.join();
  tb.join();
  CHECK(ra.get("ok").as_bool());
  CHECK(rb.get("ok").as_bool());
  CHECK_EQ(ra.get("quorum").get("participants").arr.size(), size_t(2));
  // Inline formation: far faster than the 60 s timer tick (allow wide CI
  // slack; the point is the ORDER of magnitude).
  CHECK(now_ms() - t0 < 5000);

  // Fast-quorum path through the gate: the same members re-register and
  // the previous-member counter completes the round inline again.
  int64_t qid = ra.get("quorum").get("quorum_id").as_int();
  int64_t t1 = now_ms();
  std::thread tc([&] { ra = quorum_req_job(addr, "", "repA", 2); });
  std::thread td([&] { rb = quorum_req_job(addr, "", "repB", 2); });
  tc.join();
  td.join();
  CHECK(ra.get("ok").as_bool());
  CHECK_EQ(ra.get("quorum").get("quorum_id").as_int(), qid);  // no bump
  CHECK(now_ms() - t1 < 5000);

  // Gate correctness under a grown membership: a new replica heartbeats
  // first (hb_not_joined goes up, holding the "all joined" condition open),
  // then registers; when the previous members return, the prev-member fast
  // path fires inline and the formed quorum includes all three.
  Json hreq = Json::object();
  hreq["type"] = Json::of("heartbeat");
  hreq["replica_id"] = Json::of(std::string("laggard"));
  CHECK(lighthouse_call(addr, hreq, 3000).get("ok").as_bool());
  int64_t t2 = now_ms();
  Json rl;
  std::thread tg([&] { rl = quorum_req_job(addr, "", "laggard", 1); });
  sleep_ms(300);  // laggard is registered before the prev members return
  std::thread te([&] { ra = quorum_req_job(addr, "", "repA", 3); });
  std::thread tf([&] { rb = quorum_req_job(addr, "", "repB", 3); });
  te.join();
  tf.join();
  tg.join();
  CHECK(ra.get("ok").as_bool());
  CHECK(rb.get("ok").as_bool());
  CHECK(rl.get("ok").as_bool());
  CHECK_EQ(ra.get("quorum").get("participants").arr.size(), size_t(3));
  CHECK(now_ms() - t2 < 10000);
  lh.stop();
}

static void test_district_federation() {
  // District -> root rollup over the heartbeat piggyback channel, with
  // per-district epoch fencing at the root.
  LighthouseOpts ropt;
  ropt.min_replicas = 1;
  ropt.join_timeout_ms = 100;
  ropt.quorum_tick_ms = 50;
  ropt.heartbeat_timeout_ms = 1500;
  Lighthouse root("127.0.0.1", 0, ropt);
  CHECK(root.start());

  LighthouseOpts dopt = ropt;
  dopt.district = "d1";
  dopt.root_addr = root.address();
  Lighthouse district("127.0.0.1", 0, dopt);
  CHECK(district.start());

  // Give the district a job's worth of state, then wait for a rollup.
  CHECK(fleet_heartbeat_job(district.address(), "alpha", "a0", 3, 1.0)
            .get("ok")
            .as_bool());
  Json sreq = Json::object();
  sreq["type"] = Json::of("status");
  Json d1;
  for (int i = 0; i < 50; i++) {
    Json s = lighthouse_call(root.address(), sreq, 3000).get("status");
    if (s.get("districts").has("d1")) {
      d1 = s.get("districts").get("d1");
      if (d1.get("jobs").has("alpha")) break;
    }
    sleep_ms(100);
  }
  CHECK(d1.get("jobs").has("alpha"));
  CHECK_EQ(d1.get("jobs").get("alpha").get("n").as_int(), int64_t{1});
  CHECK(d1.get("epoch").as_int() >= 1);
  CHECK(!d1.get("lost").as_bool());
  // The district's rollup frames must NOT create replica/fleet rows at the
  // root — they are control-plane metadata, not member liveness.
  Json rs = lighthouse_call(root.address(), sreq, 3000).get("status");
  CHECK(!rs.get("heartbeat_ages_ms").has("district:d1"));

  // Epoch fence: a rollup stamped with a LOWER epoch (the fenced old
  // primary after a failover) is dropped and counted; a HIGHER epoch is a
  // failover and bumps the counter.
  int64_t cur_epoch = d1.get("epoch").as_int();
  Json stale = Json::object();
  stale["type"] = Json::of("heartbeat");
  stale["replica_id"] = Json::of(std::string("district:d1"));
  stale["district"] = Json::of(std::string("d1"));
  stale["epoch"] = Json::of(cur_epoch - 1);
  Json rollup = Json::object();
  rollup["jobs"] = Json::object();
  stale["district_rollup"] = rollup;
  Json sresp = lighthouse_call(root.address(), stale, 3000);
  CHECK(!sresp.get("ok").as_bool());
  Json fresh = stale;
  fresh["epoch"] = Json::of(cur_epoch + 1);
  CHECK(lighthouse_call(root.address(), fresh, 3000).get("ok").as_bool());
  Json s2 = lighthouse_call(root.address(), sreq, 3000).get("status");
  Json d2 = s2.get("districts").get("d1");
  CHECK(d2.get("stale_dropped").as_int() >= 1);
  CHECK(d2.get("failovers").as_int() >= 1);
  CHECK_EQ(d2.get("epoch").as_int(), cur_epoch + 1);
  // Sibling districts are untouched by d1's failover: a second district's
  // row keeps its own epoch and counters.
  Json d3hb = Json::object();
  d3hb["type"] = Json::of("heartbeat");
  d3hb["replica_id"] = Json::of(std::string("district:d2"));
  d3hb["district"] = Json::of(std::string("d2"));
  d3hb["epoch"] = Json::of(int64_t(1));
  d3hb["district_rollup"] = rollup;
  CHECK(lighthouse_call(root.address(), d3hb, 3000).get("ok").as_bool());
  Json s3 = lighthouse_call(root.address(), sreq, 3000).get("status");
  CHECK_EQ(s3.get("districts").get("d2").get("failovers").as_int(),
           int64_t{0});
  CHECK_EQ(s3.get("districts").get("d2").get("stale_dropped").as_int(),
           int64_t{0});

  district.stop();
  // District loss: after the district stops reporting, the root marks it
  // lost within the heartbeat timeout.
  bool lost = false;
  for (int i = 0; i < 50; i++) {
    Json s = lighthouse_call(root.address(), sreq, 3000).get("status");
    if (s.get("districts").get("d1").get("lost").as_bool()) {
      lost = true;
      break;
    }
    sleep_ms(100);
  }
  CHECK(lost);
  root.stop();
}

static void test_fleet_snapshot_per_job_cache() {
  // The snapshot cache is keyed per job: job B's churn must not invalidate
  // job A's cached payload (no O(all-jobs) rebuilds), and job A must never
  // be served job B's gen.
  LighthouseOpts opt;
  opt.min_replicas = 1;
  opt.join_timeout_ms = 100;
  opt.quorum_tick_ms = 50;
  opt.heartbeat_timeout_ms = 5000;
  opt.fleet_snap_ms = 300;
  Lighthouse lh("127.0.0.1", 0, opt);
  CHECK(lh.start());
  std::string addr = lh.address();

  CHECK(fleet_heartbeat_job(addr, "jobA", "a0", 1, 1.0).get("ok").as_bool());
  CHECK(fleet_heartbeat_job(addr, "jobB", "b0", 1, 1.0).get("ok").as_bool());
  Json fa1 = fleet_fetch_job(addr, "jobA").get("fleet");
  Json fb1 = fleet_fetch_job(addr, "jobB").get("fleet");
  CHECK_EQ(fa1.get("job").as_str(), std::string("jobA"));
  CHECK_EQ(fb1.get("job").as_str(), std::string("jobB"));

  // Churn B hard; A's cached snapshot stays bit-identical (same gen, same
  // build stamp) while B's next post-TTL fetch advances.
  for (int i = 1; i <= 5; i++)
    CHECK(fleet_heartbeat_job(addr, "jobB", "b" + std::to_string(i), 2, 1.0)
              .get("ok")
              .as_bool());
  Json fa2 = fleet_fetch_job(addr, "jobA").get("fleet");
  CHECK_EQ(fa2.get("gen").as_int(-2), fa1.get("gen").as_int(-1));
  CHECK_EQ(fa2.get("ts_ms").as_int(), fa1.get("ts_ms").as_int());
  sleep_ms(350);
  Json fb2 = fleet_fetch_job(addr, "jobB").get("fleet");
  CHECK(fb2.get("gen").as_int(-1) > fb1.get("gen").as_int(-1));
  CHECK_EQ(fb2.get("agg").get("n").as_int(), int64_t{6});
  // A rebuilt after its own TTL still reports ITS table only.
  Json fa3 = fleet_fetch_job(addr, "jobA").get("fleet");
  CHECK_EQ(fa3.get("agg").get("n").as_int(), int64_t{1});
  CHECK(!fa3.get("replicas").has("b0"));
  lh.stop();
}

int main() {
  test_split_host_port();
  test_json();
  test_quorum_compute_basic();
  test_quorum_compute_heartbeat_expiry();
  test_fast_quorum();
  test_split_brain_guard();
  test_shrink_only();
  test_quorum_changed();
  test_compute_quorum_results();
  test_force_recover_on_init();
  test_commit_failures_propagate();
  test_latency_hist();
  test_median_tracker();
  test_fleet_snapshot_cache();
  test_fleet_snapshot_concurrent();
  test_job_namespace_isolation();
  test_job_wire_backcompat();
  test_incremental_quorum_gate();
  test_district_federation();
  test_fleet_snapshot_per_job_cache();
  test_lighthouse_e2e();
  test_lighthouse_leave();
  test_lh_durable_state();
  test_quorum_epoch_json_roundtrip();
  test_lighthouse_warm_restart();
  test_lighthouse_standby_takeover();
  test_lighthouse_demotion();
  test_manager_leave();
  test_operator_drain_request();
  test_operator_drain_all();
  test_drain_all_reaches_heartbeat_only_replica();
  test_lighthouse_quorum_timeout();
  test_manager_e2e();
  test_native_ring_allreduce();
  test_native_q8_allreduce();
  test_native_allgather_broadcast();
  test_native_flight_recorder();
  test_native_abort_unblocks();
  fprintf(stderr, "%d checks, %d failures\n", g_checks, g_failures);
  return g_failures == 0 ? 0 : 1;
}
