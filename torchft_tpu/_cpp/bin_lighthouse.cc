// Standalone lighthouse CLI (reference: src/bin/lighthouse.rs + the
// torchft_lighthouse console script). Prints "LISTENING <port>" on stdout once
// bound so wrappers can discover the ephemeral port.
#include <signal.h>

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>

#include "chaos.hpp"
#include "lighthouse.hpp"
#include "net.hpp"

static const char* kUsage =
    "usage: lighthouse --min-replicas N [--bind-host H] [--port P]\n"
    "                  [--join-timeout-ms N] [--quorum-tick-ms N]\n"
    "                  [--heartbeat-timeout-ms N] [--fleet-snap-ms N]\n"
    "                  [--state-dir DIR] [--standby]\n"
    "                  [--district NAME] [--root HOST:PORT]\n";

int main(int argc, char** argv) {
  std::string bind_host = "0.0.0.0";
  int port = 29510;
  tft::LighthouseOpts opts;
  // Served-snapshot staleness bound for /fleet.json (the flag wins over the
  // env knob; 0 disables caching and rebuilds per request).
  opts.fleet_snap_ms = 100;
  const char* snap_env = std::getenv("TORCHFT_FLEET_SNAP_MS");
  if (snap_env != nullptr && *snap_env != '\0')
    opts.fleet_snap_ms = std::stoll(snap_env);
  // Durable-state dir (epoch + quorum-id snapshot); the flag wins over the
  // env knob, empty disables persistence (the pre-HA behavior).
  const char* sd_env = std::getenv("TORCHFT_LH_STATE_DIR");
  if (sd_env != nullptr && *sd_env != '\0') opts.state_dir = sd_env;
  // Federation: district name + root lighthouse address. With both set, the
  // active instance reports per-job rollups upward; flags win over env.
  const char* di_env = std::getenv("TORCHFT_LH_DISTRICT");
  if (di_env != nullptr && *di_env != '\0') opts.district = di_env;
  const char* ro_env = std::getenv("TORCHFT_LH_ROOT");
  if (ro_env != nullptr && *ro_env != '\0') opts.root_addr = ro_env;
  // Failure-evidence plane: the reaction switch (signals are always
  // collected) plus the cadence-aware hb-lapse eviction budget.
  const char* ev_env = std::getenv("TORCHFT_LH_EVIDENCE");
  if (ev_env != nullptr && *ev_env != '\0')
    opts.evidence = std::stoll(ev_env) != 0;
  const char* em_env = std::getenv("TORCHFT_LH_EVICT_MULT");
  if (em_env != nullptr && *em_env != '\0')
    opts.evict_mult = std::stoll(em_env);
  const char* ef_env = std::getenv("TORCHFT_LH_EVICT_FLOOR_MS");
  if (ef_env != nullptr && *ef_env != '\0')
    opts.evict_floor_ms = std::stoll(ef_env);
  bool have_min = false;
  for (int i = 1; i < argc; i++) {
    std::string a = argv[i];
    auto next = [&]() -> std::string {
      if (i + 1 >= argc) {
        fprintf(stderr, "%s", kUsage);
        exit(2);
      }
      return argv[++i];
    };
    if (a == "--bind-host") {
      bind_host = next();
    } else if (a == "--port") {
      port = std::stoi(next());
    } else if (a == "--min-replicas") {
      opts.min_replicas = std::stoll(next());
      have_min = true;
    } else if (a == "--join-timeout-ms") {
      opts.join_timeout_ms = std::stoll(next());
    } else if (a == "--quorum-tick-ms") {
      opts.quorum_tick_ms = std::stoll(next());
    } else if (a == "--heartbeat-timeout-ms") {
      opts.heartbeat_timeout_ms = std::stoll(next());
    } else if (a == "--fleet-snap-ms") {
      opts.fleet_snap_ms = std::stoll(next());
    } else if (a == "--state-dir") {
      opts.state_dir = next();
    } else if (a == "--standby") {
      opts.standby = true;
    } else if (a == "--district") {
      opts.district = next();
    } else if (a == "--root") {
      opts.root_addr = next();
    } else if (a == "--parent-pid") {
      tft::watch_parent(std::stoll(next()));
    } else {
      fprintf(stderr, "unknown flag '%s'\n%s", a.c_str(), kUsage);
      return 2;
    }
  }
  if (!have_min) {
    fprintf(stderr, "--min-replicas is required\n%s", kUsage);
    return 2;
  }
  signal(SIGPIPE, SIG_IGN);
  // Seeded fault injection (TORCHFT_CHAOS, inherited from the spawning
  // trainer); off and free when the env var is unset.
  tft::chaos::init_from_env();
  tft::Lighthouse lh(bind_host, port, opts);
  if (!lh.start()) {
    fprintf(stderr, "failed to bind %s:%d\n", bind_host.c_str(), port);
    return 1;
  }
  printf("LISTENING %d\n", lh.port());
  fflush(stdout);
  while (true) tft::sleep_ms(1000);
  return 0;
}
