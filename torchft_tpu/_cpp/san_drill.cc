// Sanitizer drill: a short, race-hunting workload for TSan/ASan/UBSan
// builds (`make tsan-drill` etc.). Deliberately narrower than cpp_tests:
// it loops the two native-data-plane shapes where a data race or
// use-after-free would hide — concurrent pipelined allreduces with a
// flight-recorder reader on a second thread, and abort() racing a
// blocked collective — so the sanitizer sees each interleaving many
// times in a couple of seconds.

#include <atomic>
#include <cstdio>
#include <cstring>
#include <memory>
#include <string>
#include <thread>
#include <vector>

#include "chaos.hpp"
#include "collectives.hpp"
#include "json.hpp"
#include "net.hpp"

using namespace tft;

static int g_failures = 0;

#define REQUIRE(cond)                                                   \
  do {                                                                  \
    if (!(cond)) {                                                      \
      fprintf(stderr, "san_drill FAIL %s:%d: %s\n", __FILE__, __LINE__, \
              #cond);                                                   \
      ++g_failures;                                                     \
    }                                                                   \
  } while (0)

static std::vector<std::unique_ptr<CollectiveEngine>> mesh(int ws,
                                                           int streams,
                                                           int fr_cap) {
  std::vector<std::unique_ptr<CollectiveEngine>> es;
  std::vector<std::string> addrs(ws);
  for (int i = 0; i < ws; ++i) {
    es.push_back(
        std::make_unique<CollectiveEngine>(streams, int64_t(1) << 18, fr_cap));
    int p = es[i]->listen("127.0.0.1");
    REQUIRE(p > 0);
    addrs[i] = "127.0.0.1:" + std::to_string(p);
  }
  std::vector<int> oks(ws, 0);
  std::vector<std::thread> ts;
  for (int i = 0; i < ws; ++i)
    ts.emplace_back([&, i] { oks[i] = es[i]->connect_mesh(i, ws, addrs, 8000); });
  for (auto& t : ts) t.join();
  for (int i = 0; i < ws; ++i) REQUIRE(oks[i]);
  return es;
}

// Two replicas, multi-stream pipelined allreduces, while a sampler
// thread hammers the flight-recorder snapshot of rank 0. The ring
// buffer is written by the collective threads and read by the sampler
// — the exact shape TSan exists for.
static void drill_allreduce_with_sampler() {
  const int ws = 2;
  auto es = mesh(ws, 4, 128);
  std::atomic<bool> stop{false};
  std::thread sampler([&] {
    while (!stop.load()) {
      Json snap;
      if (!Json::parse(es[0]->fr_snapshot(0), &snap)) {
        fprintf(stderr, "san_drill FAIL: unparseable fr_snapshot\n");
        ++g_failures;
        return;
      }
    }
  });
  for (int iter = 0; iter < 20; ++iter) {
    std::vector<std::vector<float>> bufs(ws);
    for (int r = 0; r < ws; ++r) bufs[r].assign(1 << 15, float(r + 1));
    std::vector<std::thread> ts;
    std::vector<int> oks(ws, 0);
    for (int r = 0; r < ws; ++r)
      ts.emplace_back([&, r] {
        oks[r] = es[r]->allreduce(bufs[r].data(), bufs[r].size(), TFT_DT_F32,
                                  TFT_OP_SUM, 8000);
      });
    for (auto& t : ts) t.join();
    for (int r = 0; r < ws; ++r) {
      REQUIRE(oks[r]);
      REQUIRE(bufs[r][0] == 3.0f);  // 1 + 2
    }
  }
  stop.store(true);
  sampler.join();
}

// Abort racing a blocked collective, repeated with jittered delays so
// the abort lands before, during, and after the collective's socket
// waits. Each round tears the engines down while threads are winding
// up — the use-after-free window ASan watches.
static void drill_abort_race() {
  for (int round = 0; round < 10; ++round) {
    const int ws = 2;
    auto es = mesh(ws, 2, 32);
    std::vector<float> buf(4096, 1.f);
    std::thread killer([&, round] {
      sleep_ms(5 * round);  // sweep the abort across the collective's life
      es[0]->abort("san drill abort");
    });
    const int64_t t0 = now_ms();
    // Rank 1 never joins: rank 0 must be unblocked by abort, not timeout.
    bool ok = es[0]->allreduce(buf.data(), buf.size(), TFT_DT_F32, TFT_OP_SUM,
                               60 * 1000);
    killer.join();
    REQUIRE(!ok);
    REQUIRE(now_ms() - t0 < 10000);
    REQUIRE(es[0]->last_error().find("aborted") != std::string::npos);
  }
}

// Stripe tears racing live collectives: a seeded chaos rule pins resets
// to stripe 1's legs (the handoff context never matches, so every tear
// MUST be absorbed in-collective) while both ranks pump pipelined
// allreduces and a sampler hammers rank 0's flight recorder. Each tear
// exercises the failover machinery across threads — the leg epilogue
// clearing alive bits, the deterministic range handoff on the surviving
// sockets, the rejoin janitor redialing in the background and begin_op
// installing the staged fd — exactly the shared state the stripe-failover
// subsystem added. The inter-round sleep sweeps op start against the
// janitor's redial timing so rejoin activation lands at different points
// of the collective's life across rounds. Runs LAST: the armed schedule
// is process-global.
static void drill_stripe_tear_race() {
  std::string err;
  if (!chaos::init_from_spec("seed:9,spec:reset@data:match=s1:every=5:count=8",
                             &err)) {
    fprintf(stderr, "san_drill FAIL: chaos arm: %s\n", err.c_str());
    ++g_failures;
    return;
  }
  const int ws = 2;
  auto es = mesh(ws, 4, 128);
  std::atomic<bool> stop{false};
  std::thread sampler([&] {
    while (!stop.load()) {
      Json snap;
      if (!Json::parse(es[0]->fr_snapshot(0), &snap)) {
        fprintf(stderr, "san_drill FAIL: unparseable fr_snapshot\n");
        ++g_failures;
        return;
      }
    }
  });
  for (int iter = 0; iter < 30; ++iter) {
    sleep_ms(iter % 5);
    std::vector<std::vector<float>> bufs(ws);
    for (int r = 0; r < ws; ++r) bufs[r].assign(1 << 15, float(r + 1));
    std::vector<std::thread> ts;
    std::vector<int> oks(ws, 0);
    for (int r = 0; r < ws; ++r)
      ts.emplace_back([&, r] {
        oks[r] = es[r]->allreduce(bufs[r].data(), bufs[r].size(), TFT_DT_F32,
                                  TFT_OP_SUM, 8000);
      });
    for (auto& t : ts) t.join();
    for (int r = 0; r < ws; ++r) {
      REQUIRE(oks[r]);
      REQUIRE(bufs[r][0] == 3.0f);  // tears absorbed, result still exact
    }
  }
  stop.store(true);
  sampler.join();
  Json snap;
  REQUIRE(Json::parse(es[0]->fr_snapshot(0), &snap));
  REQUIRE(snap.get("failovers").is_array() &&
          !snap.get("failovers").arr.empty());
}

int main() {
  drill_allreduce_with_sampler();
  drill_abort_race();
  drill_stripe_tear_race();
  fprintf(stderr, "san_drill: %s (%d failure(s))\n",
          g_failures == 0 ? "PASS" : "FAIL", g_failures);
  return g_failures == 0 ? 0 : 1;
}
