#include "quorum.hpp"

#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

#include <algorithm>
#include <cstdio>
#include <fstream>
#include <set>
#include <sstream>

namespace tft {

Json QuorumMember::to_json() const {
  Json j = Json::object();
  j["replica_id"] = Json::of(replica_id);
  j["address"] = Json::of(address);
  j["store_address"] = Json::of(store_address);
  j["step"] = Json::of(step);
  j["world_size"] = Json::of(world_size);
  j["shrink_only"] = Json::of(shrink_only);
  j["commit_failures"] = Json::of(commit_failures);
  j["data"] = data;
  return j;
}

QuorumMember QuorumMember::from_json(const Json& j) {
  QuorumMember m;
  m.replica_id = j.get("replica_id").as_str();
  m.address = j.get("address").as_str();
  m.store_address = j.get("store_address").as_str();
  m.step = j.get("step").as_int();
  m.world_size = j.get("world_size").as_int(1);
  m.shrink_only = j.get("shrink_only").as_bool();
  m.commit_failures = j.get("commit_failures").as_int();
  m.data = j.get("data");
  return m;
}

Json Quorum::to_json() const {
  Json j = Json::object();
  j["quorum_id"] = Json::of(quorum_id);
  j["created_ms"] = Json::of(created_ms);
  j["epoch"] = Json::of(epoch);
  j["generation"] = Json::of(generation);
  j["job"] = Json::of(job);
  Json parts = Json::array();
  for (const auto& p : participants) parts.push(p.to_json());
  j["participants"] = parts;
  return j;
}

Quorum Quorum::from_json(const Json& j) {
  Quorum q;
  q.quorum_id = j.get("quorum_id").as_int();
  q.created_ms = j.get("created_ms").as_int();
  q.epoch = j.get("epoch").as_int(0);
  q.generation = j.get("generation").as_int(0);
  // Wire back-compat: a quorum from a pre-namespace lighthouse carries no
  // job field — it belongs to the default namespace.
  q.job = j.get("job").as_str();
  if (q.job.empty()) q.job = "default";
  for (const auto& p : j.get("participants").arr)
    q.participants.push_back(QuorumMember::from_json(p));
  return q;
}

std::optional<std::vector<QuorumMember>> quorum_compute(
    int64_t now, const LighthouseState& state, const LighthouseOpts& opt,
    std::string* reason) {
  // shrink_only: if any participant requests it and we have a previous quorum,
  // candidates are restricted to previous members (lighthouse.rs:172-200).
  bool shrink_only = false;
  for (const auto& kv : state.participants) {
    if (kv.second.first.shrink_only) shrink_only = true;
  }
  std::set<std::string> prev_ids;
  if (state.prev_quorum) {
    for (const auto& m : state.prev_quorum->participants)
      prev_ids.insert(m.replica_id);
  }
  bool restrict_to_prev = shrink_only && state.prev_quorum.has_value();

  // (1) healthy = replicas whose heartbeat is fresh (lighthouse.rs:147-156).
  // Under shrink_only, newcomers' heartbeats are ignored entirely — they
  // neither join nor count toward the majority guard.
  std::set<std::string> healthy;
  for (const auto& kv : state.heartbeats) {
    if (restrict_to_prev && !prev_ids.count(kv.first)) continue;
    if (now - kv.second < opt.heartbeat_timeout_ms) healthy.insert(kv.first);
  }

  // met = healthy participants (restricted to prev members if shrinking).
  std::vector<QuorumMember> met;
  int64_t first_joined = -1;
  for (const auto& kv : state.participants) {
    const QuorumMember& m = kv.second.first;
    int64_t joined_at = kv.second.second;
    if (first_joined < 0 || joined_at < first_joined) first_joined = joined_at;
    if (!healthy.count(m.replica_id)) continue;
    if (shrink_only && state.prev_quorum && !prev_ids.count(m.replica_id))
      continue;
    met.push_back(m);
  }

  // (2) fast quorum: every member of the previous quorum is a healthy
  // participant again — no need to wait for the join window
  // (lighthouse.rs:202-214).
  bool fast = false;
  if (state.prev_quorum && !prev_ids.empty()) {
    std::set<std::string> met_ids;
    for (const auto& m : met) met_ids.insert(m.replica_id);
    fast = std::all_of(prev_ids.begin(), prev_ids.end(),
                       [&](const std::string& id) { return met_ids.count(id); });
  }

  if (!fast) {
    // (3) min_replicas floor (lighthouse.rs:218-228).
    if (static_cast<int64_t>(met.size()) < opt.min_replicas) {
      if (reason)
        *reason = "need at least " + std::to_string(opt.min_replicas) +
                  " participants, have " + std::to_string(met.size());
      return std::nullopt;
    }
    // (4) split-brain guard: participants must exceed half of all heartbeating
    // replicas (lighthouse.rs:231-241).
    if (met.size() * 2 <= healthy.size()) {
      if (reason)
        *reason = "split-brain guard: " + std::to_string(met.size()) +
                  " participants <= half of " + std::to_string(healthy.size()) +
                  " healthy replicas";
      return std::nullopt;
    }
    // (5) give healthy stragglers up to join_timeout_ms (measured from the
    // first joiner of this round) to participate (lighthouse.rs:243-263).
    bool all_healthy_joined = true;
    for (const auto& id : healthy) {
      if (shrink_only && state.prev_quorum && !prev_ids.count(id)) continue;
      if (!state.participants.count(id)) all_healthy_joined = false;
    }
    if (!all_healthy_joined && first_joined >= 0 &&
        now - first_joined < opt.join_timeout_ms) {
      if (reason)
        *reason = "waiting up to join_timeout for healthy stragglers";
      return std::nullopt;
    }
  }

  if (met.empty()) {
    if (reason) *reason = "no healthy participants";
    return std::nullopt;
  }

  std::sort(met.begin(), met.end(),
            [](const QuorumMember& a, const QuorumMember& b) {
              return a.replica_id < b.replica_id;
            });
  return met;
}

bool quorum_changed(const std::vector<QuorumMember>& a,
                    const std::vector<QuorumMember>& b) {
  std::vector<std::string> ia, ib;
  for (const auto& m : a) ia.push_back(m.replica_id);
  for (const auto& m : b) ib.push_back(m.replica_id);
  std::sort(ia.begin(), ia.end());
  std::sort(ib.begin(), ib.end());
  return ia != ib;
}

Json ManagerQuorumResult::to_json() const {
  Json j = Json::object();
  j["quorum_id"] = Json::of(quorum_id);
  j["recover_src_manager_address"] = Json::of(recover_src_manager_address);
  j["recover_src_replica_rank"] = recover_src_replica_rank
                                      ? Json::of(*recover_src_replica_rank)
                                      : Json::null();
  Json dsts = Json::array();
  for (int64_t r : recover_dst_replica_ranks) dsts.push(Json::of(r));
  j["recover_dst_replica_ranks"] = dsts;
  j["store_address"] = Json::of(store_address);
  j["max_step"] = Json::of(max_step);
  j["max_replica_rank"] =
      max_replica_rank ? Json::of(*max_replica_rank) : Json::null();
  j["max_world_size"] = Json::of(max_world_size);
  j["replica_rank"] = Json::of(replica_rank);
  j["replica_world_size"] = Json::of(replica_world_size);
  j["heal"] = Json::of(heal);
  j["commit_failures"] = Json::of(commit_failures);
  return j;
}

static std::string lh_state_path(const std::string& state_dir) {
  return state_dir + "/lighthouse_state.json";
}

bool lh_state_save(const std::string& state_dir, const LighthouseDurable& d) {
  if (state_dir.empty()) return false;
  Json j = Json::object();
  j["schema"] = Json::of(static_cast<int64_t>(1));
  j["epoch"] = Json::of(d.epoch);
  j["quorum_id"] = Json::of(d.quorum_id);
  j["generation"] = Json::of(d.generation);
  const std::string body = j.dump();
  // Best-effort single-level mkdir: operators point --state-dir at a fresh
  // per-instance path (the drill does too), so create it rather than fail.
  ::mkdir(state_dir.c_str(), 0777);
  const std::string tmp = lh_state_path(state_dir) + ".tmp";
  int fd = ::open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return false;
  size_t off = 0;
  while (off < body.size()) {
    ssize_t n = ::write(fd, body.data() + off, body.size() - off);
    if (n <= 0) {
      ::close(fd);
      std::remove(tmp.c_str());
      return false;
    }
    off += static_cast<size_t>(n);
  }
  // fsync before rename: the snapshot is the fence's source of truth — a
  // torn write that survives a crash could hand a resurrected lighthouse a
  // lower epoch than the fleet has already accepted.
  if (::fsync(fd) != 0 || ::close(fd) != 0) {
    std::remove(tmp.c_str());
    return false;
  }
  if (std::rename(tmp.c_str(), lh_state_path(state_dir).c_str()) != 0) {
    std::remove(tmp.c_str());
    return false;
  }
  return true;
}

bool lh_state_load(const std::string& state_dir, LighthouseDurable* d) {
  if (state_dir.empty() || d == nullptr) return false;
  std::ifstream f(lh_state_path(state_dir));
  if (!f) return false;
  std::stringstream ss;
  ss << f.rdbuf();
  Json j;
  if (!Json::parse(ss.str(), &j)) return false;
  d->epoch = j.get("epoch").as_int(0);
  d->quorum_id = j.get("quorum_id").as_int(0);
  d->generation = j.get("generation").as_int(0);
  return true;
}

std::optional<ManagerQuorumResult> compute_quorum_results(
    int64_t group_rank, const std::string& my_replica_id, const Quorum& quorum,
    bool init_sync, std::string* error) {
  // Sort by replica_id -> replica_rank (manager.rs:495-496).
  std::vector<QuorumMember> parts = quorum.participants;
  std::sort(parts.begin(), parts.end(),
            [](const QuorumMember& a, const QuorumMember& b) {
              return a.replica_id < b.replica_id;
            });

  if (group_rank < 0) {
    if (error) *error = "group_rank must be non-negative";
    return std::nullopt;
  }
  int64_t my_rank = -1;
  for (size_t k = 0; k < parts.size(); k++) {
    if (parts[k].replica_id == my_replica_id) my_rank = static_cast<int64_t>(k);
  }
  if (my_rank < 0) {
    if (error)
      *error = "replica " + my_replica_id + " not in quorum " +
               std::to_string(quorum.quorum_id);
    return std::nullopt;
  }

  // Max step and the set of members at it (manager.rs:519-528).
  int64_t max_step = 0;
  for (const auto& p : parts) max_step = std::max(max_step, p.step);
  std::vector<int64_t> max_idx;  // replica ranks at max_step
  for (size_t k = 0; k < parts.size(); k++) {
    if (parts[k].step == max_step) max_idx.push_back(static_cast<int64_t>(k));
  }

  // Store primary spread across local ranks (manager.rs:532-533).
  int64_t primary_idx = max_idx[group_rank % static_cast<int64_t>(max_idx.size())];
  const QuorumMember& primary = parts[primary_idx];

  // Everyone recovers from the primary at step 0 when init_sync is requested
  // (manager.rs:537) so all replicas start from identical weights.
  bool force_recover = init_sync && max_step == 0;

  // Recovering set (manager.rs:542-552).
  std::vector<int64_t> recovering;  // replica ranks
  std::vector<int64_t> up_to_date;
  for (size_t k = 0; k < parts.size(); k++) {
    bool rec = parts[k].step != max_step ||
               (force_recover && parts[k].replica_id != primary.replica_id);
    if (rec)
      recovering.push_back(static_cast<int64_t>(k));
    else
      up_to_date.push_back(static_cast<int64_t>(k));
  }

  ManagerQuorumResult res;
  res.quorum_id = quorum.quorum_id;
  res.store_address = primary.store_address;
  res.max_step = max_step;
  res.max_replica_rank = primary_idx;
  res.max_world_size = static_cast<int64_t>(max_idx.size());
  res.replica_rank = my_rank;
  res.replica_world_size = static_cast<int64_t>(parts.size());
  for (const auto& p : parts)
    res.commit_failures = std::max(res.commit_failures, p.commit_failures);

  // Round-robin recovery-source assignment, offset by group_rank so different
  // local ranks of the same recovering group pull from different sources
  // (manager.rs:569-585).
  for (size_t k = 0; k < recovering.size(); k++) {
    int64_t src = up_to_date[(static_cast<int64_t>(k) + group_rank) %
                             static_cast<int64_t>(up_to_date.size())];
    if (recovering[k] == my_rank) {
      res.heal = true;
      res.recover_src_replica_rank = src;
      res.recover_src_manager_address = parts[src].address;
    }
    if (src == my_rank) res.recover_dst_replica_ranks.push_back(recovering[k]);
  }
  return res;
}

}  // namespace tft
