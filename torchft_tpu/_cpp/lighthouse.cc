#include "lighthouse.hpp"

#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <cstdio>
#include <cstring>
#include <map>
#include <sstream>
#include <vector>

#include "chaos.hpp"
#include "net.hpp"

namespace tft {

Lighthouse::Lighthouse(const std::string& bind_host, int port,
                       LighthouseOpts opts)
    : bind_host_(bind_host), port_(port), opts_(opts) {}

Lighthouse::~Lighthouse() { stop(); }

bool Lighthouse::start() {
  listen_fd_ = tcp_listen(bind_host_, port_);
  if (listen_fd_ < 0) return false;
  port_ = bound_port(listen_fd_);
  running_ = true;
  accept_thread_ = std::thread([this] { accept_loop(); });
  tick_thread_ = std::thread([this] { tick_loop(); });
  return true;
}

void Lighthouse::stop() {
  if (!running_.exchange(false)) return;
  cv_.notify_all();
  conns_.shutdown_all();  // interrupt in-flight frames so handlers drain fast
  // shutdown() unblocks the accept loop; close() + reset must wait until
  // the thread is joined — accept_loop reads listen_fd_ until then.
  if (listen_fd_ >= 0) shutdown(listen_fd_, SHUT_RDWR);
  if (accept_thread_.joinable()) accept_thread_.join();
  if (tick_thread_.joinable()) tick_thread_.join();
  if (listen_fd_ >= 0) {
    close(listen_fd_);
    listen_fd_ = -1;
  }
  conns_.wait_idle(10000);
}

std::string Lighthouse::address() const {
  return "127.0.0.1:" + std::to_string(port_);
}

void Lighthouse::accept_loop() {
  while (running_) {
    int fd = tcp_accept(listen_fd_, 200);
    if (fd < 0) continue;
    if (!conns_.add(fd)) {
      close(fd);
      continue;
    }
    std::thread([this, fd] {
      handle_conn(fd);
      conns_.remove(fd);
    }).detach();
  }
}

void Lighthouse::tick_loop() {
  while (running_) {
    tick();
    sleep_ms(opts_.quorum_tick_ms);
  }
}

void Lighthouse::tick() {
  std::unique_lock<std::mutex> lk(mu_);
  // Time-based anomaly rules (open heartbeat gaps, digest staleness) ride
  // the tick so a wedged replica is flagged while it is STILL wedged —
  // before its step completes or its heartbeat resumes.
  fleet_scan_locked(now_ms());
  std::string reason;
  auto members = quorum_compute(now_ms(), state_, opts_, &reason);
  if (!members) {
    if (reason != last_reason_ && !state_.participants.empty()) {
      fprintf(stderr, "[lighthouse] no quorum: %s\n", reason.c_str());
    }
    last_reason_ = reason;
    return;
  }
  // Bump quorum_id only when membership changed or a member reported commit
  // failures (lighthouse.rs:305-325) — a changed id forces process groups to
  // reconfigure, so we avoid it when the world is stable.
  bool bump = false;
  if (!state_.prev_quorum) {
    bump = true;
  } else if (quorum_changed(state_.prev_quorum->participants, *members)) {
    bump = true;
  } else {
    for (const auto& m : *members)
      if (m.commit_failures > 0) bump = true;
  }
  if (bump) state_.quorum_id += 1;

  // Participant churn across quorum transitions (surfaced via status +
  // /metrics): a member present now but not in the previous quorum is a
  // join; one gone is a leave. Covers crash, kill, and graceful drain
  // uniformly at the granularity monitoring cares about.
  {
    std::set<std::string> prev_ids;
    if (state_.prev_quorum)
      for (const auto& m : state_.prev_quorum->participants)
        prev_ids.insert(m.replica_id);
    std::set<std::string> new_ids;
    for (const auto& m : *members) new_ids.insert(m.replica_id);
    for (const auto& id : new_ids)
      if (!prev_ids.count(id)) joins_total_ += 1;
    for (const auto& id : prev_ids)
      if (!new_ids.count(id)) leaves_total_ += 1;
  }

  Quorum q;
  q.quorum_id = state_.quorum_id;
  q.participants = *members;
  q.created_ms = now_ms();
  state_.prev_quorum = q;
  state_.participants.clear();  // next round starts fresh (lighthouse.rs:336)
  last_quorum_ = q;
  quorum_gen_ += 1;
  last_reason_.clear();
  fprintf(stderr, "[lighthouse] quorum %lld formed with %zu members\n",
          static_cast<long long>(q.quorum_id), q.participants.size());
  if (std::getenv("TORCHFT_LH_DEBUG") != nullptr) {
    std::string ids;
    for (const auto& m : q.participants) ids += m.replica_id + " ";
    fprintf(stderr, "[lighthouse] +%lld formed gen=%lld members: %s\n",
            static_cast<long long>(now_ms() % 1000000),
            static_cast<long long>(quorum_gen_), ids.c_str());
  }
  lk.unlock();
  cv_.notify_all();
}

void Lighthouse::handle_conn(int fd) {
  // Sniff: framed requests begin with a 4-byte big-endian length whose first
  // byte is 0 for any sane control message; HTTP begins with ASCII letters.
  char peek[4] = {0};
  int n = peek_bytes(fd, peek, 4, 30000);
  if (n <= 0) {
    close(fd);
    return;
  }
  if (n >= 3 && (memcmp(peek, "GET", 3) == 0 || memcmp(peek, "POS", 3) == 0 ||
                 memcmp(peek, "HEA", 3) == 0)) {
    handle_http(fd);
    close(fd);
    return;
  }
  // Persistent framed connection: serve requests until the peer closes.
  while (running_) {
    std::string payload;
    if (!recv_frame(fd, &payload, 3600 * 1000)) break;
    Json req;
    std::string err;
    Json resp;
    if (!Json::parse(payload, &req, &err)) {
      resp["ok"] = Json::of(false);
      resp["error"] = Json::of("bad json: " + err);
    } else {
      // Server-side chaos (rpc_delay sleeps; rpc_drop/reset tear the
      // connection without replying — the client sees a torn RPC and must
      // absorb it through its retry policy).
      if (!chaos::server_rpc(req.get("type").as_str())) break;
      int64_t timeout = req.get("timeout_ms").as_int(60000);
      resp = handle_request(req, now_ms() + timeout);
      // Echo the caller's trace id so both planes of a step share one id
      // (the Python Manager mints it; responses carry it for correlation).
      if (req.has("trace_id")) resp["trace_id"] = req.get("trace_id");
    }
    if (!send_frame(fd, resp.dump(), 30000)) break;
  }
  close(fd);
}

Json Lighthouse::handle_request(const Json& req, int64_t deadline_ms) {
  const std::string type = req.get("type").as_str();
  Json resp = Json::object();
  if (type == "heartbeat") {
    std::lock_guard<std::mutex> lk(mu_);
    const std::string replica_id = req.get("replica_id").as_str();
    // A drained replica's manager may have one heartbeat in flight when its
    // leave lands; the tombstone keeps it from resurrecting the entry (which
    // would stall the survivors' next quorum until heartbeat expiry).
    if (!state_.left.count(replica_id)) {
      int64_t now = now_ms();
      state_.heartbeats[replica_id] = now;
      // Heartbeats carry the manager address so drain_all can reach a
      // replica that heartbeats but never registered a quorum.
      const std::string addr = req.get("address").as_str();
      if (!addr.empty()) state_.heartbeat_addrs[replica_id] = addr;
      // Live fleet plane: fold the optional digest + declared cadence into
      // the fleet table and run the digest-driven anomaly rules. Old
      // clients send neither field; the row simply stays digest-less.
      fleet_note_heartbeat(replica_id, req, now);
    }
    resp["ok"] = Json::of(true);
    return resp;
  }
  if (type == "fleet") {
    std::lock_guard<std::mutex> lk(mu_);
    resp["ok"] = Json::of(true);
    resp["fleet"] = fleet_json_locked(now_ms());
    return resp;
  }
  if (type == "leave") {
    // Graceful drain (no reference analog; the reference only has Kill →
    // exit(1), so survivors always pay the heartbeat-expiry stall). Removing
    // the member's heartbeat + registration lets the very next tick form the
    // shrunken quorum: ~quorum_tick_ms of stall instead of
    // ~heartbeat_timeout_ms.
    const std::string replica_id = req.get("replica_id").as_str();
    {
      std::lock_guard<std::mutex> lk(mu_);
      state_.heartbeats.erase(replica_id);
      state_.heartbeat_addrs.erase(replica_id);
      state_.participants.erase(replica_id);
      state_.left.insert(replica_id);
      // A drained replica must not linger in the fleet table looking like
      // a straggler whose heartbeats stopped.
      fleet_.erase(replica_id);
    }
    fprintf(stderr, "[lighthouse] replica %s left gracefully\n",
            replica_id.c_str());
    // Proactive tick: survivors already blocked in a quorum RPC see the
    // shrunken membership now, not at the next timer tick.
    tick();
    resp["ok"] = Json::of(true);
    return resp;
  }
  if (type == "quorum") {
    return quorum_rpc(req, deadline_ms);
  }
  if (type == "status") {
    resp["ok"] = Json::of(true);
    resp["status"] = status_json();
    return resp;
  }
  if (type == "kill" || type == "drain") {
    // Forward to the member's manager address (kill: lighthouse.rs:454-479;
    // drain: no reference analog — asks the trainer to leave gracefully at
    // its next step boundary instead of exit(1)).
    std::string replica_id = req.get("replica_id").as_str();
    std::string addr;
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (state_.prev_quorum) {
        for (const auto& m : state_.prev_quorum->participants)
          if (m.replica_id == replica_id) addr = m.address;
      }
      for (const auto& kv : state_.participants)
        if (kv.first == replica_id) addr = kv.second.first.address;
    }
    if (addr.empty()) {
      resp["ok"] = Json::of(false);
      resp["error"] = Json::of("unknown replica " + replica_id);
      return resp;
    }
    Json fwd = Json::object();
    if (type == "kill") {
      fwd["type"] = Json::of("kill");
      fwd["msg"] = Json::of("killed via lighthouse");
    } else {
      fwd["type"] = Json::of("request_drain");
    }
    Json ignored;
    bool ok = call_json_addr(addr, fwd, &ignored, 5000);
    // A kill victim exits without replying; treat connection-level failure
    // after send as success-ish.
    resp["ok"] = Json::of(true);
    resp["sent"] = Json::of(ok);
    return resp;
  }
  if (type == "drain_all") {
    // Operator-initiated FULL-job drain: forward request_drain to every
    // registered member's manager. Each trainer drains at its own safe
    // boundary (with --durable-dir that includes a final durable
    // snapshot), so the whole job can be stopped cleanly and relaunched
    // later — the operator-triggered twin of a whole-pod preemption.
    // No reference analog (the reference's only job-wide stop is
    // killing each replica). The flag rides the next quorum response
    // per member (manager_server.cc request_drain), so for sync-quorum
    // trainers every group learns it at the SAME sync — no group can
    // drain a boundary ahead and strand the others' quorum.
    // Union of the last formed quorum and any currently-registering
    // members (same lookup the single-replica drain uses: registration
    // empties into prev_quorum when a quorum forms, and a drain must
    // reach members in either place). Live registrations overwrite
    // stale prev_quorum addresses; tombstoned (already-left) members
    // are excluded.
    std::map<std::string, std::string> members;
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (state_.prev_quorum) {
        for (const auto& m : state_.prev_quorum->participants)
          if (!state_.left.count(m.replica_id))
            members[m.replica_id] = m.address;
      }
      for (const auto& kv : state_.participants)
        members[kv.first] = kv.second.first.address;
      // Heartbeat-only replicas (heartbeating but never registered a
      // quorum) were a drain_all blind spot: they appear in neither
      // prev_quorum nor participants. Their heartbeat-carried addresses
      // close it; registered addresses win when both exist.
      for (const auto& kv : state_.heartbeat_addrs)
        if (!members.count(kv.first) && !state_.left.count(kv.first))
          members[kv.first] = kv.second;
    }
    Json sent = Json::object();
    int n_sent = 0;
    for (const auto& m : members) {
      Json fwd = Json::object();
      fwd["type"] = Json::of("request_drain");
      Json ignored;
      // Bound each forward by the request's remaining deadline (capped
      // at 5 s): a job with several unreachable members (stale
      // prev_quorum addresses after crashes — exactly when an operator
      // reaches for drain ALL) must still return the per-member send
      // report to the caller instead of timing out the whole RPC.
      int64_t remaining = deadline_ms - now_ms();
      if (remaining < 200) {
        sent[m.first] = Json::of(false);
        continue;
      }
      int64_t budget = remaining < 5000 ? remaining : 5000;
      bool ok = call_json_addr(m.second, fwd, &ignored,
                               static_cast<int>(budget));
      sent[m.first] = Json::of(ok);
      if (ok) n_sent++;
    }
    resp["ok"] = Json::of(true);
    resp["sent"] = sent;
    resp["n_sent"] = Json::of(static_cast<int64_t>(n_sent));
    resp["n_members"] = Json::of(static_cast<int64_t>(members.size()));
    return resp;
  }
  resp["ok"] = Json::of(false);
  resp["error"] = Json::of("unknown request type '" + type + "'");
  return resp;
}

Json Lighthouse::quorum_rpc(const Json& req, int64_t deadline_ms) {
  QuorumMember me = QuorumMember::from_json(req.get("requester"));
  Json resp = Json::object();
  if (me.replica_id.empty()) {
    resp["ok"] = Json::of(false);
    resp["error"] = Json::of("quorum request missing requester.replica_id");
    return resp;
  }
  const bool debug = std::getenv("TORCHFT_LH_DEBUG") != nullptr;
  std::unique_lock<std::mutex> lk(mu_);
  // Joining is an implicit heartbeat (lighthouse.rs:502-512) and clears any
  // graceful-leave tombstone (a drained replica relaunching to rejoin).
  state_.left.erase(me.replica_id);
  state_.heartbeats[me.replica_id] = now_ms();
  state_.participants[me.replica_id] = {me, now_ms()};
  int64_t my_gen = quorum_gen_;
  if (debug) {
    fprintf(stderr, "[lighthouse] +%lld register %s step=%lld gen=%lld pool=%zu\n",
            static_cast<long long>(now_ms() % 1000000),
            me.replica_id.c_str(), static_cast<long long>(me.step),
            static_cast<long long>(my_gen), state_.participants.size());
  }
  lk.unlock();
  // Proactive tick so a completing quorum doesn't wait for the next timer
  // tick (lighthouse.rs:516-518).
  tick();
  lk.lock();

  while (running_) {
    // Wait for a fresh quorum broadcast.
    while (running_ && quorum_gen_ == my_gen) {
      if (cv_.wait_until(lk, std::chrono::system_clock::time_point(
                                 std::chrono::milliseconds(deadline_ms))) ==
          std::cv_status::timeout) {
        if (now_ms() >= deadline_ms) {
          resp["ok"] = Json::of(false);
          resp["error"] = Json::of("timed out waiting for quorum");
          resp["timeout"] = Json::of(true);
          return resp;
        }
      }
    }
    if (!running_) break;
    my_gen = quorum_gen_;
    if (last_quorum_) {
      bool in_quorum = false;
      for (const auto& m : last_quorum_->participants)
        if (m.replica_id == me.replica_id) in_quorum = true;
      if (in_quorum) {
        resp["ok"] = Json::of(true);
        resp["quorum"] = last_quorum_->to_json();
        return resp;
      }
      // Delivered quorum doesn't include us (we joined too late): rejoin and
      // wait for the next one (lighthouse.rs:523-544).
      state_.left.erase(me.replica_id);
      state_.heartbeats[me.replica_id] = now_ms();
      state_.participants[me.replica_id] = {me, now_ms()};
    }
  }
  resp["ok"] = Json::of(false);
  resp["error"] = Json::of("lighthouse shutting down");
  return resp;
}

Json Lighthouse::status_json() {
  std::lock_guard<std::mutex> lk(mu_);
  Json s = Json::object();
  s["quorum_id"] = Json::of(state_.quorum_id);
  s["quorum_generation"] = Json::of(quorum_gen_);
  s["joins_total"] = Json::of(joins_total_);
  s["leaves_total"] = Json::of(leaves_total_);
  int64_t now = now_ms();
  Json hb = Json::object();
  for (const auto& kv : state_.heartbeats)
    hb[kv.first] = Json::of(now - kv.second);
  s["heartbeat_ages_ms"] = hb;
  Json parts = Json::array();
  for (const auto& kv : state_.participants)
    parts.push(kv.second.first.to_json());
  s["participants"] = parts;
  s["prev_quorum"] =
      state_.prev_quorum ? state_.prev_quorum->to_json() : Json::null();
  Json left = Json::array();
  for (const auto& id : state_.left) left.push(Json::of(id));
  s["left"] = left;
  s["reason"] = Json::of(last_reason_);
  // Live-plane summary rides along so a status poller sees fleet health
  // without a second RPC; the full table stays on /fleet.json.
  s["fleet"] = fleet_summary_locked(now);
  return s;
}

// ---------------------------------------------------------------------------
// Live fleet health plane
// ---------------------------------------------------------------------------

namespace {
constexpr size_t kFleetAnomalyRing = 64;     // rise-edge records kept
constexpr int64_t kFleetStickyMs = 10000;    // straggler display hold
constexpr int64_t kFleetCommitStall = 3;     // cf streak that flags
constexpr double kFleetSlowRateFrac = 0.5;   // rate < frac*median flags
constexpr int64_t kFleetStepLag = 2;         // step < median-lag flags
constexpr int64_t kFleetJitterMult = 8;      // budget = mult * cadence
constexpr int64_t kFleetJitterFloorMs = 1000;
constexpr int64_t kFleetEwmaWarmup = 5;      // gaps before EWMA budget counts

// Upper median: with two replicas this is the HEALTHY one's value, which is
// the right baseline for "relative slowdown vs the fleet".
double fleet_median(std::vector<double> v) {
  std::sort(v.begin(), v.end());
  return v[v.size() / 2];
}
}  // namespace

int64_t Lighthouse::fleet_jitter_budget_ms(const FleetEntry& e) const {
  // Deterministic when the sender declared its cadence; EWMA of observed
  // inter-arrival gaps as the old-client fallback. The floor absorbs GC /
  // scheduler hiccups that are noise at any cadence.
  int64_t base = e.hb_interval_ms > 0
                     ? e.hb_interval_ms * kFleetJitterMult
                     : static_cast<int64_t>(e.hb_gap_ewma_ms) * kFleetJitterMult;
  return base < kFleetJitterFloorMs ? kFleetJitterFloorMs : base;
}

void Lighthouse::fleet_set_flag(const std::string& replica_id, FleetEntry& e,
                                const std::string& kind, int64_t now,
                                Json detail) {
  e.straggler_until_ms = now + kFleetStickyMs;
  if (e.flags.count(kind)) return;  // only the RISE edge is an anomaly
  e.flags.insert(kind);
  anomaly_seq_ += 1;
  Json a = Json::object();
  a["seq"] = Json::of(anomaly_seq_);
  a["ts_ms"] = Json::of(now);
  a["replica_id"] = Json::of(replica_id);
  a["kind"] = Json::of(kind);
  a["detail"] = detail;
  anomalies_.push_back(a);
  while (anomalies_.size() > kFleetAnomalyRing) anomalies_.pop_front();
  fprintf(stderr, "[lighthouse] anomaly #%lld: %s on %s %s\n",
          static_cast<long long>(anomaly_seq_), kind.c_str(),
          replica_id.c_str(), detail.dump().c_str());
}

void Lighthouse::fleet_note_heartbeat(const std::string& replica_id,
                                      const Json& req, int64_t now) {
  FleetEntry& e = fleet_[replica_id];
  if (e.hb_count > 0) {
    int64_t gap = now - e.last_hb_ms;
    // Judge the gap against the budget BEFORE folding it into the EWMA —
    // a jittered gap must not raise its own threshold.
    bool budget_valid =
        e.hb_interval_ms > 0 || e.hb_count >= kFleetEwmaWarmup;
    if (budget_valid && gap > fleet_jitter_budget_ms(e)) {
      Json d = Json::object();
      d["gap_ms"] = Json::of(gap);
      d["budget_ms"] = Json::of(fleet_jitter_budget_ms(e));
      fleet_set_flag(replica_id, e, "hb_jitter", now, d);
      e.last_jitter_ms = now;
    }
    e.hb_gap_ewma_ms = e.hb_gap_ewma_ms == 0.0
                           ? static_cast<double>(gap)
                           : 0.8 * e.hb_gap_ewma_ms + 0.2 * gap;
  }
  e.last_hb_ms = now;
  e.hb_count += 1;
  int64_t declared = req.get("hb_interval_ms").as_int(0);
  if (declared > 0) e.hb_interval_ms = declared;
  if (!req.has("digest") || !req.get("digest").is_object()) return;

  // Digest-driven rules run at ARRIVAL, against the fleet table as of this
  // heartbeat: given the same global digest sequence the flag/anomaly
  // sequence is identical, so a chaos replay reproduces its alerts.
  e.digest = req.get("digest");
  e.has_digest = true;
  e.digest_ms = now;

  int64_t cf = e.digest.get("cf").as_int(0);
  if (cf >= kFleetCommitStall) {
    Json d = Json::object();
    d["cf"] = Json::of(cf);
    fleet_set_flag(replica_id, e, "commit_stall", now, d);
  } else {
    e.flags.erase("commit_stall");
  }

  std::vector<double> rates, steps;
  for (const auto& kv : fleet_) {
    if (!kv.second.has_digest) continue;
    double r = kv.second.digest.get("rate").as_double(0.0);
    if (r > 0.0) rates.push_back(r);
    steps.push_back(
        static_cast<double>(kv.second.digest.get("step").as_int(0)));
  }
  double own_rate = e.digest.get("rate").as_double(0.0);
  if (rates.size() >= 2) {
    double med = fleet_median(rates);
    if (own_rate < kFleetSlowRateFrac * med) {
      Json d = Json::object();
      d["rate"] = Json::of(own_rate);
      d["median_rate"] = Json::of(med);
      fleet_set_flag(replica_id, e, "slow_rate", now, d);
    } else {
      e.flags.erase("slow_rate");
    }
  }
  int64_t own_step = e.digest.get("step").as_int(0);
  if (steps.size() >= 2) {
    int64_t med = static_cast<int64_t>(fleet_median(steps));
    if (own_step < med - kFleetStepLag) {
      Json d = Json::object();
      d["step"] = Json::of(own_step);
      d["median_step"] = Json::of(med);
      fleet_set_flag(replica_id, e, "step_lag", now, d);
    } else {
      e.flags.erase("step_lag");
    }
  }
}

void Lighthouse::fleet_scan_locked(int64_t now) {
  // Time-based rules only: an OPEN heartbeat gap (the replica is wedged
  // RIGHT NOW — arrival-side checks can't see it because nothing arrives)
  // plus expiry of a jitter flag whose evidence has aged out.
  for (auto& kv : fleet_) {
    FleetEntry& e = kv.second;
    bool budget_valid =
        e.hb_interval_ms > 0 || e.hb_count >= kFleetEwmaWarmup;
    int64_t open_gap = now - e.last_hb_ms;
    if (budget_valid && open_gap > fleet_jitter_budget_ms(e)) {
      Json d = Json::object();
      d["gap_ms"] = Json::of(open_gap);
      d["budget_ms"] = Json::of(fleet_jitter_budget_ms(e));
      d["open"] = Json::of(true);
      fleet_set_flag(kv.first, e, "hb_jitter", now, d);
      e.last_jitter_ms = now;
    } else if (e.flags.count("hb_jitter") &&
               now - e.last_jitter_ms > kFleetStickyMs) {
      e.flags.erase("hb_jitter");
    }
  }
}

Json Lighthouse::fleet_json_locked(int64_t now) {
  Json f = Json::object();
  f["ts_ms"] = Json::of(now);
  Json reps = Json::object();
  std::vector<double> rates, steps, gps;
  int64_t max_cf = 0;
  int64_t n_digest = 0, n_straggler = 0;
  for (const auto& kv : fleet_) {
    const FleetEntry& e = kv.second;
    Json r = Json::object();
    r["last_hb_age_ms"] = Json::of(now - e.last_hb_ms);
    r["hb_interval_ms"] = Json::of(e.hb_interval_ms);
    // Old client (no digest ever): fields render as null, row stays —
    // the forward-compat contract the tests pin.
    r["digest"] = e.has_digest ? e.digest : Json::null();
    r["digest_age_ms"] =
        e.has_digest ? Json::of(now - e.digest_ms) : Json::null();
    Json fl = Json::array();
    for (const auto& k : e.flags) fl.push(Json::of(k));
    if (now - e.last_hb_ms > opts_.heartbeat_timeout_ms)
      fl.push(Json::of("stale"));  // view-only: presence, not an anomaly
    r["flags"] = fl;
    bool straggler = !e.flags.empty() || now < e.straggler_until_ms;
    r["straggler"] = Json::of(straggler);
    if (straggler) n_straggler += 1;
    if (e.has_digest) {
      n_digest += 1;
      double rt = e.digest.get("rate").as_double(0.0);
      if (rt > 0.0) rates.push_back(rt);
      steps.push_back(
          static_cast<double>(e.digest.get("step").as_int(0)));
      gps.push_back(e.digest.get("gp").as_double(0.0));
      int64_t cf = e.digest.get("cf").as_int(0);
      if (cf > max_cf) max_cf = cf;
    }
    reps[kv.first] = r;
  }
  f["replicas"] = reps;
  Json agg = Json::object();
  agg["n"] = Json::of(static_cast<int64_t>(fleet_.size()));
  agg["n_digest"] = Json::of(n_digest);
  agg["stragglers"] = Json::of(n_straggler);
  agg["median_rate"] =
      rates.empty() ? Json::null() : Json::of(fleet_median(rates));
  agg["median_step"] =
      steps.empty() ? Json::null()
                    : Json::of(static_cast<int64_t>(fleet_median(steps)));
  agg["median_goodput"] =
      gps.empty() ? Json::null() : Json::of(fleet_median(gps));
  agg["max_commit_failures"] = Json::of(max_cf);
  f["agg"] = agg;
  Json an = Json::array();
  for (const auto& a : anomalies_) an.push(a);
  f["anomalies"] = an;
  f["anomaly_seq"] = Json::of(anomaly_seq_);
  return f;
}

Json Lighthouse::fleet_summary_locked(int64_t now) {
  Json fj = fleet_json_locked(now);
  Json s = fj.get("agg");
  s["anomaly_seq"] = fj.get("anomaly_seq");
  return s;
}

std::string Lighthouse::render_status_html() {
  Json s = status_json();
  std::ostringstream html;
  html << "<!doctype html><html><head><title>torchft-tpu lighthouse</title>"
       << "<style>body{font-family:monospace;margin:2em}table{border-collapse:"
          "collapse}td,th{border:1px solid #999;padding:4px 8px}</style>"
       << "</head><body><h1>torchft-tpu lighthouse</h1>"
       << "<p>quorum_id: " << s.get("quorum_id").as_int() << "</p>";
  html << "<h2>heartbeats</h2><table><tr><th>replica</th><th>age (ms)</th>"
       << "<th></th></tr>";
  for (const auto& kv : s.get("heartbeat_ages_ms").obj) {
    html << "<tr><td>" << kv.first << "</td><td>" << kv.second.as_int()
         << "</td><td><form method=post action=\"/replica/" << kv.first
         << "/kill\" style=\"display:inline\"><button>kill</button></form> "
         << "<form method=post action=\"/replica/" << kv.first
         << "/drain\" style=\"display:inline\"><button>drain</button></form>"
         << "</td></tr>";
  }
  html << "</table><p><form method=post action=\"/drain_all\" "
          "style=\"display:inline\"><button>drain ALL (stop job "
          "cleanly)</button></form></p>";
  html << "<h2>previous quorum</h2><table><tr><th>replica</th>"
       << "<th>address</th><th>step</th><th>world</th></tr>";
  if (s.get("prev_quorum").is_object()) {
    for (const auto& p : s.get("prev_quorum").get("participants").arr) {
      html << "<tr><td>" << p.get("replica_id").as_str() << "</td><td>"
           << p.get("address").as_str() << "</td><td>"
           << p.get("step").as_int() << "</td><td>"
           << p.get("world_size").as_int() << "</td></tr>";
    }
  }
  html << "</table>";
  if (!s.get("reason").as_str().empty())
    html << "<p>waiting: " << s.get("reason").as_str() << "</p>";
  html << "</body></html>";
  return html.str();
}

static std::string prom_escape(const std::string& s) {
  // Prometheus label values must escape backslash, double-quote, and
  // newline — replica ids are client-supplied strings.
  std::string out;
  out.reserve(s.size());
  for (char c : s) {
    if (c == '\\') out += "\\\\";
    else if (c == '"') out += "\\\"";
    else if (c == '\n') out += "\\n";
    else out += c;
  }
  return out;
}

std::string Lighthouse::render_metrics() {
  // Prometheus text exposition (the reference lighthouse has only an HTML
  // dashboard; a scrapeable endpoint is what production monitoring needs).
  std::lock_guard<std::mutex> lk(mu_);
  int64_t now = now_ms();
  std::ostringstream m;
  m << "# HELP torchft_lighthouse_quorum_id Current quorum id.\n"
    << "# TYPE torchft_lighthouse_quorum_id gauge\n"
    << "torchft_lighthouse_quorum_id " << state_.quorum_id << "\n";
  m << "# HELP torchft_lighthouse_quorum_generation Quorum broadcasts since "
       "boot.\n"
    << "# TYPE torchft_lighthouse_quorum_generation counter\n"
    << "torchft_lighthouse_quorum_generation " << quorum_gen_ << "\n";
  m << "# HELP torchft_lighthouse_joins_total Members added across quorum "
       "transitions.\n"
    << "# TYPE torchft_lighthouse_joins_total counter\n"
    << "torchft_lighthouse_joins_total " << joins_total_ << "\n";
  m << "# HELP torchft_lighthouse_leaves_total Members gone across quorum "
       "transitions.\n"
    << "# TYPE torchft_lighthouse_leaves_total counter\n"
    << "torchft_lighthouse_leaves_total " << leaves_total_ << "\n";
  m << "# HELP torchft_lighthouse_participants Replicas currently waiting in "
       "the next quorum.\n"
    << "# TYPE torchft_lighthouse_participants gauge\n"
    << "torchft_lighthouse_participants " << state_.participants.size()
    << "\n";
  m << "# HELP torchft_lighthouse_quorum_members Members of the last "
       "delivered quorum.\n"
    << "# TYPE torchft_lighthouse_quorum_members gauge\n"
    << "torchft_lighthouse_quorum_members "
    << (state_.prev_quorum ? state_.prev_quorum->participants.size() : 0)
    << "\n";
  m << "# HELP torchft_lighthouse_heartbeat_age_ms Milliseconds since each "
       "replica's last heartbeat.\n"
    << "# TYPE torchft_lighthouse_heartbeat_age_ms gauge\n";
  for (const auto& kv : state_.heartbeats)
    m << "torchft_lighthouse_heartbeat_age_ms{replica=\""
      << prom_escape(kv.first) << "\"} " << (now - kv.second) << "\n";
  if (state_.prev_quorum) {
    m << "# HELP torchft_lighthouse_member_step Training step each quorum "
         "member reported.\n"
      << "# TYPE torchft_lighthouse_member_step gauge\n";
    for (const auto& mem : state_.prev_quorum->participants)
      m << "torchft_lighthouse_member_step{replica=\""
        << prom_escape(mem.replica_id) << "\"} " << mem.step << "\n";
  }
  // Live-plane alert gauges: straggler flags + the anomaly counter are
  // what a pager rule fires on; per-replica step rate + the fleet median
  // give the rule its denominator.
  m << "# HELP torchft_lighthouse_anomalies_total Anomaly rise-edges "
       "detected since boot.\n"
    << "# TYPE torchft_lighthouse_anomalies_total counter\n"
    << "torchft_lighthouse_anomalies_total " << anomaly_seq_ << "\n";
  if (!fleet_.empty()) {
    m << "# HELP torchft_lighthouse_straggler Replica currently flagged "
         "as a straggler (1) or healthy (0).\n"
      << "# TYPE torchft_lighthouse_straggler gauge\n";
    for (const auto& kv : fleet_) {
      bool straggler =
          !kv.second.flags.empty() || now < kv.second.straggler_until_ms;
      m << "torchft_lighthouse_straggler{replica=\""
        << prom_escape(kv.first) << "\"} " << (straggler ? 1 : 0) << "\n";
    }
    std::vector<double> rates;
    std::ostringstream per_replica;
    for (const auto& kv : fleet_) {
      if (!kv.second.has_digest) continue;
      double r = kv.second.digest.get("rate").as_double(0.0);
      per_replica << "torchft_lighthouse_replica_step_rate{replica=\""
                  << prom_escape(kv.first) << "\"} " << r << "\n";
      if (r > 0.0) rates.push_back(r);
    }
    std::string per = per_replica.str();
    if (!per.empty()) {
      m << "# HELP torchft_lighthouse_replica_step_rate Committed steps "
           "per second each replica reported in its digest.\n"
        << "# TYPE torchft_lighthouse_replica_step_rate gauge\n"
        << per;
    }
    if (!rates.empty()) {
      m << "# HELP torchft_lighthouse_fleet_median_step_rate Fleet median "
           "of reported step rates.\n"
        << "# TYPE torchft_lighthouse_fleet_median_step_rate gauge\n"
        << "torchft_lighthouse_fleet_median_step_rate "
        << fleet_median(rates) << "\n";
    }
  }
  return m.str();
}

void Lighthouse::handle_http(int fd) {
  std::string req = read_http_request(fd, 10000);
  std::string path = "/";
  std::string method;
  {
    size_t sp1 = req.find(' ');
    size_t sp2 = req.find(' ', sp1 + 1);
    if (sp1 != std::string::npos && sp2 != std::string::npos) {
      method = req.substr(0, sp1);
      path = req.substr(sp1 + 1, sp2 - sp1 - 1);
    }
  }
  // Side-effecting endpoints (kill / drain / drain_all) are POST-only:
  // a GET must never stop a replica — browsers prefetch URLs and
  // monitoring scrapers walk dashboard paths. The dashboard forms
  // declare method=post already.
  const bool side_effecting =
      path == "/drain_all" || path.rfind("/replica/", 0) == 0;
  if (side_effecting && method != "POST") {
    std::string body405 = "method not allowed (POST required)";
    std::ostringstream hdr;
    hdr << "HTTP/1.1 405 Method Not Allowed\r\nContent-Type: text/plain"
        << "\r\nAllow: POST\r\nContent-Length: " << body405.size()
        << "\r\nConnection: close\r\n\r\n";
    std::string out405 = hdr.str() + body405;
    write_all(fd, out405.data(), out405.size(), 10000);
    return;
  }
  std::string body;
  std::string ctype = "text/html";
  int code = 200;
  if (path == "/" || path == "/status") {
    body = render_status_html();
  } else if (path == "/status.json") {
    body = status_json().dump();
    ctype = "application/json";
  } else if (path == "/fleet.json") {
    std::lock_guard<std::mutex> lk(mu_);
    body = fleet_json_locked(now_ms()).dump();
    ctype = "application/json";
  } else if (path == "/metrics") {
    body = render_metrics();
    ctype = "text/plain; version=0.0.4";
  } else if (path.rfind("/replica/", 0) == 0 && path.size() > 14 &&
             (path.compare(path.size() - 5, 5, "/kill") == 0 ||
              path.compare(path.size() - 6, 6, "/drain") == 0)) {
    bool is_kill = path.compare(path.size() - 5, 5, "/kill") == 0;
    size_t suffix = is_kill ? 5 : 6;
    std::string replica_id = path.substr(9, path.size() - 9 - suffix);
    Json kreq = Json::object();
    kreq["type"] = Json::of(is_kill ? "kill" : "drain");
    kreq["replica_id"] = Json::of(replica_id);
    Json kresp = handle_request(kreq, now_ms() + 5000);
    body = kresp.dump();
    ctype = "application/json";
    if (!kresp.get("ok").as_bool()) code = 404;
  } else if (path == "/drain_all") {
    Json dreq = Json::object();
    dreq["type"] = Json::of("drain_all");
    Json dresp = handle_request(dreq, now_ms() + 15000);
    body = dresp.dump();
    ctype = "application/json";
  } else {
    code = 404;
    body = "not found";
    ctype = "text/plain";
  }
  std::ostringstream hdr;
  hdr << "HTTP/1.1 " << code << (code == 200 ? " OK" : " Not Found")
      << "\r\nContent-Type: " << ctype
      << "\r\nContent-Length: " << body.size()
      << "\r\nConnection: close\r\n\r\n";
  std::string out = hdr.str() + body;
  write_all(fd, out.data(), out.size(), 10000);
}

}  // namespace tft
