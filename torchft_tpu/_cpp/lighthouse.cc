#include "lighthouse.hpp"

#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <sstream>
#include <vector>

#include "chaos.hpp"
#include "net.hpp"

namespace tft {

namespace {
// Steady-clock microseconds for the hot-path histograms (wall clock can
// step; a latency sample must not).
int64_t now_us_steady() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// Wire back-compat: pre-namespace clients send no "job" field; an absent or
// empty value maps to the default namespace on every frame type.
std::string job_of(const Json& req) {
  std::string j = req.get("job").as_str();
  return j.empty() ? "default" : j;
}

// Closed failure-evidence source enum — positionally mirrors
// telemetry.SIGNAL_SOURCES on the Python side (lint rule signal-sources).
const char* const kSignalSourceNames[] = {
    "hb_lapse",       "lease_expiry", "digest_anomaly",
    "rpc_error",      "native_abort", "proc_death",
};

bool known_signal_source(const std::string& s) {
  for (const char* n : kSignalSourceNames)
    if (s == n) return true;
  return false;
}

// Closed badput taxonomy — positionally mirrors telemetry.BADPUT_KINDS on
// the Python side (lint rule badput-kinds). The digest's "acct" array is
// indexed by this order; index 1 ("compute") is the goodput numerator.
const char* const kBadputKindNames[] = {
    "init_compile",   "compute",        "exposed_comm",
    "quorum_wait",    "heal",           "discarded_step",
    "replay_catchup", "straggler_idle", "drain",
    "down",
};
static_assert(sizeof(kBadputKindNames) / sizeof(kBadputKindNames[0]) ==
                  static_cast<size_t>(kNumBadputKinds),
              "kBadputKindNames must match kNumBadputKinds");
constexpr int kBadputComputeIdx = 1;

// Hard failure evidence (same set the trainer's _EvidenceWatcher acts on):
// these rise edges count as faults for MTBF and open an ETTR episode.
bool hard_signal_source(const std::string& s) {
  return s == "hb_lapse" || s == "proc_death" || s == "native_abort";
}

// A digest's acct vector, when complete: pre-namespace digests (or ones
// from a client older than the taxonomy) simply don't contribute.
bool digest_acct(const Json& digest, double out[kNumBadputKinds]) {
  const Json& a = digest.get("acct");
  if (!a.is_array() || a.arr.size() < static_cast<size_t>(kNumBadputKinds))
    return false;
  for (int i = 0; i < kNumBadputKinds; i++)
    out[i] = a.arr[i].as_double(0.0);
  return true;
}
}  // namespace

Lighthouse::Lighthouse(const std::string& bind_host, int port,
                       LighthouseOpts opts)
    : bind_host_(bind_host), port_(port), opts_(opts) {
  // Shared with tools/obs_export.py (same knob, same default): above this
  // many replicas, per-replica /metrics series collapse to aggregates +
  // anomalous rows only, so a 1024-replica scrape stays bounded.
  const char* em = std::getenv("TORCHFT_EXPORT_MAX_REPLICAS");
  if (em != nullptr && *em != '\0') export_max_replicas_ = std::atoll(em);
  if (export_max_replicas_ < 0) export_max_replicas_ = 0;
  // SLO burn-rate knobs. A target >= 1.0 disarms the evaluator (the burn
  // denominator would be <= 0 — there is no error budget to spend).
  const char* sg = std::getenv("TORCHFT_LH_SLO_GOODPUT");
  if (sg != nullptr && *sg != '\0') slo_goodput_ = std::atof(sg);
  const char* sb = std::getenv("TORCHFT_LH_SLO_BURN");
  if (sb != nullptr && *sb != '\0') slo_burn_ = std::atof(sb);
  const char* sm = std::getenv("TORCHFT_LH_SLO_MIN_S");
  if (sm != nullptr && *sm != '\0') slo_min_s_ = std::atof(sm);
}

Lighthouse::~Lighthouse() { stop(); }

// Reserve this much generation headroom on every durable save: generations
// bump on every broadcast but are only persisted on (rare) quorum_id/epoch
// changes, so a reload must jump past anything possibly handed out since
// the last fsync to keep (epoch, generation) strictly monotone.
static constexpr int64_t kGenReserve = 1 << 20;

void Lighthouse::persist_locked(int64_t job_qid, int64_t job_gen) {
  if (opts_.state_dir.empty()) return;
  // The durable snapshot stores the MAX ids across every job island: a warm
  // restart (or takeover) must resume each job's numbering strictly above
  // anything any job ever published, and a single fsync'd file is the
  // cheapest shape that guarantees it.
  if (job_qid > dur_quorum_id_) dur_quorum_id_ = job_qid;
  if (job_gen > dur_gen_) dur_gen_ = job_gen;
  LighthouseDurable d;
  d.epoch = epoch_.load();
  d.quorum_id = dur_quorum_id_;
  d.generation = dur_gen_ + kGenReserve;
  if (!lh_state_save(opts_.state_dir, d)) {
    fprintf(stderr, "[lighthouse] WARNING: failed to persist state to %s\n",
            opts_.state_dir.c_str());
  }
}

void Lighthouse::persist(int64_t job_qid, int64_t job_gen) {
  std::lock_guard<std::mutex> lk(persist_mu_);
  persist_locked(job_qid, job_gen);
}

Lighthouse::JobState& Lighthouse::job_state(const std::string& job) {
  std::lock_guard<std::mutex> lk(jobs_mu_);
  auto it = jobs_.find(job);
  if (it == jobs_.end()) {
    it = jobs_.try_emplace(job).first;
    JobState& js = it->second;
    js.name = job;
    // Seed from the restored durable maxima so a job island created after a
    // warm restart (or a job first seen post-restart) continues its quorum
    // numbering monotonically. restored_* are written once in start()
    // before any thread runs, so the unlocked read is safe.
    js.state.quorum_id = restored_quorum_id_;
    js.quorum_gen = restored_gen_;
  }
  return it->second;
}

std::vector<Lighthouse::JobState*> Lighthouse::all_jobs() {
  std::lock_guard<std::mutex> lk(jobs_mu_);
  std::vector<JobState*> out;
  out.reserve(jobs_.size());
  // std::map nodes are stable and islands are never erased, so the pointers
  // stay valid after jobs_mu_ is dropped.
  for (auto& kv : jobs_) out.push_back(&kv.second);
  return out;
}

bool Lighthouse::start() {
  listen_fd_ = tcp_listen(bind_host_, port_);
  if (listen_fd_ < 0) return false;
  port_ = bound_port(listen_fd_);
  {
    std::lock_guard<std::mutex> lk(persist_mu_);
    active_ = !opts_.standby;
    LighthouseDurable d;
    if (!opts_.state_dir.empty() && lh_state_load(opts_.state_dir, &d)) {
      // Warm restart: resume the persisted reign — same epoch (we may still
      // be the rightful owner), quorum ids continue strictly monotone, and
      // generations jump past the reserved headroom. Participant/fleet
      // tables rebuild from the live heartbeat stream.
      epoch_ = d.epoch;
      restored_quorum_id_ = dur_quorum_id_ = d.quorum_id;
      restored_gen_ = dur_gen_ = d.generation;
      fprintf(stderr,
              "[lighthouse] warm restart from %s: epoch=%lld quorum_id=%lld "
              "gen=%lld%s\n",
              opts_.state_dir.c_str(), static_cast<long long>(epoch_.load()),
              static_cast<long long>(restored_quorum_id_),
              static_cast<long long>(restored_gen_),
              active_ ? "" : " (standby)");
    }
    if (active_ && epoch_ == 0) epoch_ = 1;  // fresh active boot
    if (active_) persist_locked(dur_quorum_id_, dur_gen_);
  }
  // The default namespace island always exists (pre-namespace clients and
  // the composite /fleet.json land there).
  job_state("default");
  running_ = true;
  accept_thread_ = std::thread([this] { accept_loop(); });
  tick_thread_ = std::thread([this] { tick_loop(); });
  // Federation sender: a lighthouse configured with a district name and a
  // root address reports per-job rollups upward.
  if (!opts_.root_addr.empty() && !opts_.district.empty())
    district_thread_ = std::thread([this] { district_loop(); });
  return true;
}

void Lighthouse::stop() {
  if (!running_.exchange(false)) return;
  for (JobState* js : all_jobs()) {
    std::lock_guard<std::mutex> lk(js->mu);
    js->cv.notify_all();
  }
  conns_.shutdown_all();  // interrupt in-flight frames so handlers drain fast
  // shutdown() unblocks the accept loop; close() + reset must wait until
  // the thread is joined — accept_loop reads listen_fd_ until then.
  if (listen_fd_ >= 0) shutdown(listen_fd_, SHUT_RDWR);
  if (accept_thread_.joinable()) accept_thread_.join();
  if (tick_thread_.joinable()) tick_thread_.join();
  if (district_thread_.joinable()) district_thread_.join();
  if (listen_fd_ >= 0) {
    close(listen_fd_);
    listen_fd_ = -1;
  }
  conns_.wait_idle(10000);
}

std::string Lighthouse::address() const {
  return "127.0.0.1:" + std::to_string(port_);
}

void Lighthouse::accept_loop() {
  while (running_) {
    int fd = tcp_accept(listen_fd_, 200);
    if (fd < 0) continue;
    if (!conns_.add(fd)) {
      close(fd);
      continue;
    }
    std::thread([this, fd] {
      handle_conn(fd);
      conns_.remove(fd);
    }).detach();
  }
}

void Lighthouse::tick_loop() {
  while (running_) {
    tick();
    sleep_ms(opts_.quorum_tick_ms);
  }
}

void Lighthouse::tick() {
  // The periodic tick is the time-driven fallback of the incremental gate:
  // it catches everything only the clock can decide (heartbeat expiry,
  // join-timeout straggler cutoff, open heartbeat gaps) plus any formation
  // a conservative gate miss deferred. Jobs tick independently under their
  // own locks — one job's slow scan never blocks another's heartbeats.
  int64_t now = now_ms();
  for (JobState* js : all_jobs()) {
    std::lock_guard<std::mutex> lk(js->mu);
    fleet_scan_locked(*js, now);
    job_tick_locked(*js, now);
  }
  district_scan(now);
}

void Lighthouse::district_loop() {
  // District -> root rollup sender, piggybacking on the heartbeat frame
  // type. Only the ACTIVE instance reports: a standby stays silent, and
  // after a takeover the new primary reports with its higher epoch — the
  // root observes the epoch advance as a district failover while the fenced
  // old primary's late rollups are dropped by the per-district fence.
  int64_t interval = opts_.heartbeat_timeout_ms / 4;
  if (interval < 250) interval = 250;
  if (interval > 1000) interval = 1000;
  std::string host;
  int port = 0;
  const bool addr_ok = split_host_port(opts_.root_addr, &host, &port);
  if (!addr_ok) {
    fprintf(stderr, "[lighthouse] bad root address '%s'; federation off\n",
            opts_.root_addr.c_str());
    return;
  }
  int fd = -1;
  while (running_) {
    if (active_.load()) {
      Json jobs = Json::object();
      int64_t now = now_ms();
      for (JobState* js : all_jobs()) {
        std::lock_guard<std::mutex> lk(js->mu);
        jobs[js->name] = fleet_summary_locked(*js, now);
      }
      Json rollup = Json::object();
      rollup["jobs"] = jobs;
      Json req = Json::object();
      req["type"] = Json::of(std::string("heartbeat"));
      req["replica_id"] = Json::of("district:" + opts_.district);
      req["district"] = Json::of(opts_.district);
      req["epoch"] = Json::of(epoch_.load());
      req["district_rollup"] = rollup;
      if (fd < 0) fd = tcp_connect(host, port, 2000);
      if (fd >= 0) {
        Json resp;
        if (!call_json(fd, req, &resp, 5000)) {
          close(fd);
          fd = -1;  // reconnect next round
        }
      }
    }
    sleep_ms(interval);
  }
  if (fd >= 0) close(fd);
}

void Lighthouse::handle_conn(int fd) {
  // Sniff: framed requests begin with a 4-byte big-endian length whose first
  // byte is 0 for any sane control message; HTTP begins with ASCII letters.
  char peek[4] = {0};
  int n = peek_bytes(fd, peek, 4, 30000);
  if (n <= 0) {
    close(fd);
    return;
  }
  if (n >= 3 && (memcmp(peek, "GET", 3) == 0 || memcmp(peek, "POS", 3) == 0 ||
                 memcmp(peek, "HEA", 3) == 0)) {
    handle_http(fd);
    close(fd);
    return;
  }
  // Persistent framed connection: serve requests until the peer closes.
  while (running_) {
    std::string payload;
    if (!recv_frame(fd, &payload, 3600 * 1000)) break;
    Json req;
    std::string err;
    Json resp;
    if (!Json::parse(payload, &req, &err)) {
      resp["ok"] = Json::of(false);
      resp["error"] = Json::of("bad json: " + err);
    } else {
      // Server-side chaos (rpc_delay sleeps; rpc_drop/reset tear the
      // connection without replying — the client sees a torn RPC and must
      // absorb it through its retry policy).
      if (!chaos::server_rpc(req.get("type").as_str())) break;
      int64_t timeout = req.get("timeout_ms").as_int(60000);
      std::shared_ptr<const std::string> raw;
      resp = handle_request(req, now_ms() + timeout, &raw);
      if (raw) {
        // Prebuilt shared quorum broadcast: send the bytes as-is. (No
        // trace echo on this path — the manager's quorum client reads only
        // ok/quorum and stamps its own trace on the step events.)
        if (!send_frame(fd, *raw, 30000)) break;
        continue;
      }
      // Echo the caller's trace id so both planes of a step share one id
      // (the Python Manager mints it; responses carry it for correlation).
      if (req.has("trace_id")) resp["trace_id"] = req.get("trace_id");
    }
    if (!send_frame(fd, resp.dump(), 30000)) break;
  }
  close(fd);
}

Json Lighthouse::handle_request(const Json& req, int64_t deadline_ms,
                                std::shared_ptr<const std::string>* raw) {
  const std::string type = req.get("type").as_str();
  Json resp = Json::object();
  if (type == "heartbeat") {
    // District rollups ride the heartbeat frame type (piggyback channel)
    // but are control-plane metadata, not replica liveness: divert them
    // BEFORE the job tables so a district never appears as a fleet row or
    // quorum participant.
    if (req.has("district_rollup")) return district_note(req);
    // Timed from before the lock: the histogram must show contention (the
    // wait behind a /fleet.json rebuild was exactly the bug), not just the
    // work done once inside.
    int64_t hb_t0 = now_us_steady();
    JobState& js = job_state(job_of(req));
    {
      std::lock_guard<std::mutex> lk(js.mu);
      const std::string replica_id = req.get("replica_id").as_str();
      // Managers stamp the max quorum epoch they have accepted into every
      // heartbeat: this is how a standby (or a resurrected stale primary)
      // learns the fleet's current owner without any lighthouse-to-
      // lighthouse channel. An active instance seeing a higher epoch has
      // been superseded by a takeover — it fences itself out (demotes to
      // standby) instead of competing for the fleet.
      int64_t hb_epoch = req.get("epoch").as_int(0);
      int64_t seen = observed_epoch_.load();
      while (hb_epoch > seen &&
             !observed_epoch_.compare_exchange_weak(seen, hb_epoch)) {
      }
      // Max accepted quorum_id rides the same frames, tracked PER JOB: a
      // standby resumes each job's numbering above what that job's fleet
      // accepted (a global max would inflate job B's ids from job A's).
      int64_t hb_qid = req.get("quorum_id").as_int(0);
      if (hb_qid > js.observed_quorum_id) js.observed_quorum_id = hb_qid;
      if (active_.load() && observed_epoch_.load() > epoch_.load()) {
        std::lock_guard<std::mutex> plk(persist_mu_);
        if (active_.load() && observed_epoch_.load() > epoch_.load()) {
          active_ = false;
          demotions_ += 1;
          js.last_reason = "fenced: observed epoch " +
                           std::to_string(observed_epoch_.load()) +
                           " > own epoch " + std::to_string(epoch_.load());
          fprintf(stderr,
                  "[lighthouse] demoting to standby: fleet is on epoch %lld, "
                  "ours is %lld (stale primary fenced out)\n",
                  static_cast<long long>(observed_epoch_.load()),
                  static_cast<long long>(epoch_.load()));
        }
      }
      // A drained replica's manager may have one heartbeat in flight when
      // its leave lands; the tombstone keeps it from resurrecting the entry
      // (which would stall the survivors' next quorum until heartbeat
      // expiry).
      if (!js.state.left.count(replica_id)) {
        int64_t now = now_ms();
        // Gate counter: a replica heartbeating but not (yet) registered
        // holds the "all healthy joined" condition open.
        if (!js.state.heartbeats.count(replica_id) &&
            !js.state.participants.count(replica_id))
          js.hb_not_joined += 1;
        js.state.heartbeats[replica_id] = now;
        // Heartbeats carry the manager address so drain_all can reach a
        // replica that heartbeats but never registered a quorum.
        const std::string addr = req.get("address").as_str();
        if (!addr.empty()) js.state.heartbeat_addrs[replica_id] = addr;
        // Live fleet plane: fold the optional digest + declared cadence into
        // the fleet table and run the digest-driven anomaly rules. Old
        // clients send neither field; the row simply stays digest-less.
        fleet_note_heartbeat(js, replica_id, req, now);
      }
      // Failure-evidence ingest: manager-observed signals (rpc_error,
      // native_abort, proc_death, lease_expiry) piggyback on the heartbeat
      // frame. Old clients never send the key (wire back-compat); unknown
      // sources are dropped rather than poisoning the closed enum.
      if (req.has("signals") && req.get("signals").is_array()) {
        int64_t now = now_ms();
        bool ingested = false;
        for (const auto& sg : req.get("signals").arr) {
          const std::string src = sg.get("source").as_str();
          if (!known_signal_source(src)) continue;
          std::string subject = sg.get("replica_id").as_str();
          if (subject.empty()) subject = replica_id;
          std::string site = sg.get("site").as_str();
          if (site.empty()) site = "manager:" + replica_id;
          signal_note_locked(js, src, subject, site, sg.get("detail"), now);
          ingested = true;
        }
        // Evidence tick: fresh evidence re-evaluates the quorum NOW (the
        // periodic tick and vote-timeout landing stay as the fallback).
        if (ingested && opts_.evidence) job_tick_locked(js, now_ms());
      }
      // The ACK carries the job's signal cursor + last signal so every
      // manager's evidence_status view advances at heartbeat cadence with
      // zero extra RPCs. Old managers ignore both keys.
      resp["signal_seq"] = Json::of(js.signal_seq);
      if (!js.signals.empty()) resp["signal"] = js.signals.back();
    }
    resp["ok"] = Json::of(true);
    hist_heartbeat_.observe_us(now_us_steady() - hb_t0);
    return resp;
  }
  if (type == "fleet") {
    // Served from the generation-tagged cached snapshot — the framed twin
    // of GET /fleet.json no longer rebuilds O(N) JSON under the job lock.
    // No/empty job = the composite (default + cross-job summary) view.
    auto snap = fleet_snapshot(req.get("job").as_str(), now_ms());
    resp["ok"] = Json::of(true);
    resp["fleet"] = snap->json;
    return resp;
  }
  if (type == "leave") {
    // Graceful drain (no reference analog; the reference only has Kill →
    // exit(1), so survivors always pay the heartbeat-expiry stall). Removing
    // the member's heartbeat + registration lets the very next evaluation
    // form the shrunken quorum: ~quorum_tick_ms of stall instead of
    // ~heartbeat_timeout_ms.
    const std::string replica_id = req.get("replica_id").as_str();
    const std::string reason = req.get("reason").as_str();
    JobState& js = job_state(job_of(req));
    {
      std::lock_guard<std::mutex> lk(js.mu);
      bool was_part = js.state.participants.count(replica_id) > 0;
      bool was_hb = js.state.heartbeats.count(replica_id) > 0;
      // A leave on the DEAD replica's behalf (the manager binary's
      // parent-death watchdog) is failure evidence, not a planned drain:
      // signal proc_death so peers wedged mid-collective with the corpse
      // abort at heartbeat speed instead of their collective timeout.
      if ((was_part || was_hb) && reason == "trainer died") {
        Json d = Json::object();
        d["reason"] = Json::of(reason);
        signal_note_locked(js, "proc_death", replica_id, "lighthouse.leave",
                           std::move(d), now_ms());
      }
      js.state.heartbeats.erase(replica_id);
      js.state.heartbeat_addrs.erase(replica_id);
      js.state.participants.erase(replica_id);
      js.state.left.insert(replica_id);
      if (was_hb && !was_part) js.hb_not_joined -= 1;
      if (was_part && js.prev_ids.count(replica_id)) js.prev_present -= 1;
      // A drained replica must not linger in the fleet table looking like
      // a straggler whose heartbeats stopped.
      fleet_erase(js, replica_id);
      // Proactive evaluation for THIS job only: survivors already blocked
      // in a quorum RPC see the shrunken membership now, not at the next
      // timer tick — and sibling jobs are untouched.
      job_tick_locked(js, now_ms());
    }
    fprintf(stderr, "[lighthouse] replica %s left gracefully (job %s)\n",
            replica_id.c_str(), js.name.c_str());
    resp["ok"] = Json::of(true);
    return resp;
  }
  if (type == "quorum") {
    return quorum_rpc(req, deadline_ms, raw);
  }
  if (type == "status") {
    resp["ok"] = Json::of(true);
    resp["status"] = status_json();
    return resp;
  }
  if (type == "kill" || type == "drain") {
    // Forward to the member's manager address (kill: lighthouse.rs:454-479;
    // drain: no reference analog — asks the trainer to leave gracefully at
    // its next step boundary instead of exit(1)). Lookup is scoped to the
    // frame's job namespace.
    std::string replica_id = req.get("replica_id").as_str();
    JobState& js = job_state(job_of(req));
    std::string addr;
    {
      std::lock_guard<std::mutex> lk(js.mu);
      if (js.state.prev_quorum) {
        for (const auto& m : js.state.prev_quorum->participants)
          if (m.replica_id == replica_id) addr = m.address;
      }
      for (const auto& kv : js.state.participants)
        if (kv.first == replica_id) addr = kv.second.first.address;
    }
    if (addr.empty()) {
      resp["ok"] = Json::of(false);
      resp["error"] = Json::of("unknown replica " + replica_id);
      return resp;
    }
    Json fwd = Json::object();
    if (type == "kill") {
      fwd["type"] = Json::of("kill");
      fwd["msg"] = Json::of("killed via lighthouse");
    } else {
      fwd["type"] = Json::of("request_drain");
    }
    Json ignored;
    bool ok = call_json_addr(addr, fwd, &ignored, 5000);
    // A kill victim exits without replying; treat connection-level failure
    // after send as success-ish.
    resp["ok"] = Json::of(true);
    resp["sent"] = Json::of(ok);
    return resp;
  }
  if (type == "drain_all") {
    // Operator-initiated FULL drain: forward request_drain to every
    // registered member's manager. Each trainer drains at its own safe
    // boundary (with --durable-dir that includes a final durable
    // snapshot), so a whole job can be stopped cleanly and relaunched
    // later — the operator-triggered twin of a whole-pod preemption.
    // A frame with a "job" drains that namespace only; without one it
    // drains EVERY namespace (the pre-namespace whole-instance semantics).
    // Union of the last formed quorum and any currently-registering
    // members per job (registration empties into prev_quorum when a quorum
    // forms, and a drain must reach members in either place). Live
    // registrations overwrite stale prev_quorum addresses; tombstoned
    // (already-left) members are excluded; heartbeat-only replicas are
    // reached through their heartbeat-carried addresses.
    std::vector<JobState*> targets;
    if (req.has("job") && !req.get("job").as_str().empty()) {
      targets.push_back(&job_state(job_of(req)));
    } else {
      targets = all_jobs();
    }
    std::map<std::string, std::string> members;
    for (JobState* jsp : targets) {
      std::lock_guard<std::mutex> lk(jsp->mu);
      if (jsp->state.prev_quorum) {
        for (const auto& m : jsp->state.prev_quorum->participants)
          if (!jsp->state.left.count(m.replica_id))
            members[m.replica_id] = m.address;
      }
      for (const auto& kv : jsp->state.participants)
        members[kv.first] = kv.second.first.address;
      for (const auto& kv : jsp->state.heartbeat_addrs)
        if (!members.count(kv.first) && !jsp->state.left.count(kv.first))
          members[kv.first] = kv.second;
    }
    Json sent = Json::object();
    int n_sent = 0;
    for (const auto& m : members) {
      Json fwd = Json::object();
      fwd["type"] = Json::of("request_drain");
      Json ignored;
      // Bound each forward by the request's remaining deadline (capped
      // at 5 s): a job with several unreachable members (stale
      // prev_quorum addresses after crashes — exactly when an operator
      // reaches for drain ALL) must still return the per-member send
      // report to the caller instead of timing out the whole RPC.
      int64_t remaining = deadline_ms - now_ms();
      if (remaining < 200) {
        sent[m.first] = Json::of(false);
        continue;
      }
      int64_t budget = remaining < 5000 ? remaining : 5000;
      bool ok = call_json_addr(m.second, fwd, &ignored,
                               static_cast<int>(budget));
      sent[m.first] = Json::of(ok);
      if (ok) n_sent++;
    }
    resp["ok"] = Json::of(true);
    resp["sent"] = sent;
    resp["n_sent"] = Json::of(static_cast<int64_t>(n_sent));
    resp["n_members"] = Json::of(static_cast<int64_t>(members.size()));
    return resp;
  }
  resp["ok"] = Json::of(false);
  resp["error"] = Json::of("unknown request type '" + type + "'");
  return resp;
}

void Lighthouse::register_participant_locked(JobState& js,
                                             const QuorumMember& me) {
  // Joining is an implicit heartbeat (lighthouse.rs:502-512) and clears any
  // graceful-leave tombstone (a drained replica relaunching to rejoin).
  int64_t now = now_ms();
  js.state.left.erase(me.replica_id);
  const bool was_part = js.state.participants.count(me.replica_id) > 0;
  const bool was_hb = js.state.heartbeats.count(me.replica_id) > 0;
  js.state.heartbeats[me.replica_id] = now;
  js.state.participants[me.replica_id] = {me, now};
  if (!was_part) {
    if (was_hb) js.hb_not_joined -= 1;
    if (js.prev_ids.count(me.replica_id)) js.prev_present += 1;
  }
}

bool Lighthouse::quorum_gate_locked(const JobState& js) const {
  // O(1) decision: can a quorum POSSIBLY form right now? The gate is
  // deliberately one-sided — a pass pays the full quorum_compute (which
  // remains the single source of truth and can still say no); a miss defers
  // to the periodic tick. A counter bug can therefore only delay a
  // formation by one tick, never form a wrong quorum.
  if (!active_.load()) return false;
  if (js.state.participants.empty()) return false;
  // Fast-quorum certain: every member of the previous quorum has
  // re-registered (their registration doubled as a fresh heartbeat).
  if (js.state.prev_quorum && !js.prev_ids.empty() &&
      js.prev_present == static_cast<int64_t>(js.prev_ids.size()))
    return true;
  // Everyone heartbeating has registered and the floor is met: no straggler
  // the join-timeout wait would hold the door for.
  if (static_cast<int64_t>(js.state.participants.size()) >=
          opts_.min_replicas &&
      js.hb_not_joined == 0)
    return true;
  return false;
}

void Lighthouse::job_tick_locked(JobState& js, int64_t now) {
  // A standby absorbs heartbeats (keeping fleet/participant tables warm)
  // but must not form quorums — there is exactly one epoch owner, and it is
  // not us until a manager fails over and its quorum request promotes us.
  if (!active_.load()) {
    js.last_reason = "standby (not forming quorums)";
    return;
  }
  std::string reason;
  int64_t q_t0 = now_us_steady();
  auto members = quorum_compute(now, js.state, opts_, &reason);
  hist_quorum_.observe_us(now_us_steady() - q_t0);
  if (!members) {
    if (reason != js.last_reason && !js.state.participants.empty()) {
      fprintf(stderr, "[lighthouse] no quorum (job %s): %s\n",
              js.name.c_str(), reason.c_str());
    }
    js.last_reason = reason;
    return;
  }
  // Bump quorum_id only when membership changed or a member reported commit
  // failures (lighthouse.rs:305-325) — a changed id forces process groups to
  // reconfigure, so we avoid it when the world is stable.
  bool bump = false;
  if (!js.state.prev_quorum) {
    bump = true;
  } else if (quorum_changed(js.state.prev_quorum->participants, *members)) {
    bump = true;
  } else {
    for (const auto& m : *members)
      if (m.commit_failures > 0) bump = true;
  }
  if (bump) {
    // Resume numbering above anything this job's fleet already accepted
    // (relevant on a takeover or a stateless warm restart).
    if (js.observed_quorum_id > js.state.quorum_id)
      js.state.quorum_id = js.observed_quorum_id;
    js.state.quorum_id += 1;
    // Fsync the new id BEFORE publishing the quorum: a crash between
    // publish and persist could otherwise let a warm restart re-issue an id
    // the fleet has already seen.
    persist(js.state.quorum_id, js.quorum_gen);
  }

  // Participant churn across quorum transitions (surfaced via status +
  // /metrics): a member present now but not in the previous quorum is a
  // join; one gone is a leave. Covers crash, kill, and graceful drain
  // uniformly at the granularity monitoring cares about.
  std::set<std::string> new_ids;
  for (const auto& m : *members) new_ids.insert(m.replica_id);
  {
    std::set<std::string> old_ids;
    if (js.state.prev_quorum)
      for (const auto& m : js.state.prev_quorum->participants)
        old_ids.insert(m.replica_id);
    for (const auto& id : new_ids)
      if (!old_ids.count(id)) js.joins_total += 1;
    for (const auto& id : old_ids)
      if (!new_ids.count(id)) js.leaves_total += 1;
  }

  Quorum q;
  q.quorum_id = js.state.quorum_id;
  q.participants = *members;
  q.created_ms = now;
  q.epoch = epoch_.load();
  q.generation = js.quorum_gen + 1;
  q.job = js.name;
  js.state.prev_quorum = q;
  js.state.participants.clear();  // next round starts fresh (lighthouse.rs:336)
  // Reset the gate counters for the next round: nobody from the new quorum
  // has re-registered yet, and with participants cleared every heartbeating
  // replica is momentarily unregistered.
  js.prev_ids = new_ids;
  js.prev_present = 0;
  js.hb_not_joined = static_cast<int64_t>(js.state.heartbeats.size());
  js.last_quorum = q;
  // Serialize the broadcast ONCE: every in-quorum waiter (and its
  // connection loop) sends these exact bytes, turning the O(N^2)
  // per-waiter to_json+dump fan-out into a single O(N) build.
  {
    Json bresp = Json::object();
    bresp["ok"] = Json::of(true);
    bresp["quorum"] = q.to_json();
    js.quorum_payload = std::make_shared<const std::string>(bresp.dump());
  }
  js.quorum_gen += 1;
  js.last_reason.clear();
  fprintf(stderr, "[lighthouse] quorum %lld formed with %zu members (job %s)\n",
          static_cast<long long>(q.quorum_id), q.participants.size(),
          js.name.c_str());
  if (std::getenv("TORCHFT_LH_DEBUG") != nullptr) {
    std::string ids;
    for (const auto& m : q.participants) ids += m.replica_id + " ";
    fprintf(stderr, "[lighthouse] +%lld formed gen=%lld job=%s members: %s\n",
            static_cast<long long>(now_ms() % 1000000),
            static_cast<long long>(js.quorum_gen), js.name.c_str(),
            ids.c_str());
  }
  js.cv.notify_all();
}

Json Lighthouse::quorum_rpc(const Json& req, int64_t deadline_ms,
                            std::shared_ptr<const std::string>* raw) {
  QuorumMember me = QuorumMember::from_json(req.get("requester"));
  Json resp = Json::object();
  if (me.replica_id.empty()) {
    resp["ok"] = Json::of(false);
    resp["error"] = Json::of("quorum request missing requester.replica_id");
    return resp;
  }
  const bool debug = std::getenv("TORCHFT_LH_DEBUG") != nullptr;
  JobState& js = job_state(job_of(req));
  std::unique_lock<std::mutex> lk(js.mu);
  // Warm-standby takeover: managers only send quorum RPCs to their active
  // target, so a quorum request arriving at a standby means the fleet's
  // lease on the old primary lapsed and failover chose us. Claim the reign
  // with a strictly higher epoch than anything observed (fencing out the
  // old primary) and persist it before serving a single quorum.
  if (!active_.load()) {
    std::lock_guard<std::mutex> plk(persist_mu_);
    if (!active_.load()) {
      epoch_ = std::max(epoch_.load(), observed_epoch_.load()) + 1;
      // Resume this job's quorum numbering above anything its fleet
      // accepted from the old primary: each quorum_id must have exactly
      // one (epoch) owner.
      js.state.quorum_id =
          std::max(js.state.quorum_id, js.observed_quorum_id);
      active_ = true;
      takeovers_ += 1;
      persist_locked(js.state.quorum_id, js.quorum_gen);
      fprintf(stderr,
              "[lighthouse] standby takeover: now active with epoch %lld "
              "(first quorum request from %s, job %s)\n",
              static_cast<long long>(epoch_.load()), me.replica_id.c_str(),
              js.name.c_str());
    }
  }
  register_participant_locked(js, me);
  int64_t my_gen = js.quorum_gen;
  if (debug) {
    fprintf(stderr,
            "[lighthouse] +%lld register %s job=%s step=%lld gen=%lld "
            "pool=%zu\n",
            static_cast<long long>(now_ms() % 1000000), me.replica_id.c_str(),
            js.name.c_str(), static_cast<long long>(me.step),
            static_cast<long long>(my_gen), js.state.participants.size());
  }
  // Incremental quorum: the O(1) gate decides whether this registration
  // could complete a quorum; only then does the full quorum_compute run —
  // inline, still under the job lock, replacing the per-registration
  // unconditional full tick (the O(N^2) storm behind the 4 s formations at
  // N=1024). A gate miss is covered by the periodic tick.
  if (quorum_gate_locked(js)) job_tick_locked(js, now_ms());

  while (running_) {
    // Wait for a fresh quorum broadcast.
    while (running_ && js.quorum_gen == my_gen) {
      if (js.cv.wait_until(lk, std::chrono::system_clock::time_point(
                                   std::chrono::milliseconds(deadline_ms))) ==
          std::cv_status::timeout) {
        if (now_ms() >= deadline_ms) {
          resp["ok"] = Json::of(false);
          resp["error"] = Json::of("timed out waiting for quorum");
          resp["timeout"] = Json::of(true);
          return resp;
        }
      }
    }
    if (!running_) break;
    my_gen = js.quorum_gen;
    if (js.last_quorum) {
      // prev_ids is exactly the broadcast quorum's member set (assigned
      // together with last_quorum at formation): O(log N) membership
      // instead of a per-waiter linear scan.
      if (js.prev_ids.count(me.replica_id)) {
        if (raw && js.quorum_payload) {
          *raw = js.quorum_payload;  // shared prebuilt bytes, no re-dump
          return resp;
        }
        resp["ok"] = Json::of(true);
        resp["quorum"] = js.last_quorum->to_json();
        return resp;
      }
      // Delivered quorum doesn't include us (we joined too late): rejoin and
      // wait for the next one (lighthouse.rs:523-544).
      register_participant_locked(js, me);
      if (quorum_gate_locked(js)) job_tick_locked(js, now_ms());
      if (js.quorum_gen != my_gen) continue;  // formed inline; re-check
    }
  }
  resp["ok"] = Json::of(false);
  resp["error"] = Json::of("lighthouse shutting down");
  return resp;
}

Json Lighthouse::status_json() {
  int64_t now = now_ms();
  Json s = Json::object();
  // Top-level keys keep the pre-namespace schema, reporting the DEFAULT
  // job's island (what old dashboards and tests read); the per-job map
  // below carries every namespace including default.
  {
    JobState& js = job_state("default");
    std::lock_guard<std::mutex> lk(js.mu);
    s["quorum_id"] = Json::of(js.state.quorum_id);
    s["quorum_generation"] = Json::of(js.quorum_gen);
    s["joins_total"] = Json::of(js.joins_total);
    s["leaves_total"] = Json::of(js.leaves_total);
    s["epoch"] = Json::of(epoch_.load());
    s["observed_epoch"] = Json::of(observed_epoch_.load());
    s["observed_quorum_id"] = Json::of(js.observed_quorum_id);
    s["role"] = Json::of(std::string(active_.load() ? "active" : "standby"));
    s["takeovers"] = Json::of(takeovers_.load());
    s["demotions"] = Json::of(demotions_.load());
    Json hb = Json::object();
    for (const auto& kv : js.state.heartbeats)
      hb[kv.first] = Json::of(now - kv.second);
    s["heartbeat_ages_ms"] = hb;
    Json parts = Json::array();
    for (const auto& kv : js.state.participants)
      parts.push(kv.second.first.to_json());
    s["participants"] = parts;
    s["prev_quorum"] =
        js.state.prev_quorum ? js.state.prev_quorum->to_json() : Json::null();
    Json left = Json::array();
    for (const auto& id : js.state.left) left.push(Json::of(id));
    s["left"] = left;
    s["reason"] = Json::of(js.last_reason);
    // Live-plane summary rides along so a status poller sees fleet health
    // without a second RPC; the full table stays on /fleet.json.
    s["fleet"] = fleet_summary_locked(js, now);
  }
  // Per-job sections: one summary per namespace island, gathered by
  // locking each island one at a time (never two job locks at once).
  Json jobs = Json::object();
  for (JobState* jsp : all_jobs()) {
    std::lock_guard<std::mutex> lk(jsp->mu);
    Json j = Json::object();
    j["quorum_id"] = Json::of(jsp->state.quorum_id);
    j["quorum_generation"] = Json::of(jsp->quorum_gen);
    j["participants"] =
        Json::of(static_cast<int64_t>(jsp->state.participants.size()));
    j["members"] = Json::of(
        jsp->state.prev_quorum
            ? static_cast<int64_t>(jsp->state.prev_quorum->participants.size())
            : int64_t{0});
    j["heartbeats"] =
        Json::of(static_cast<int64_t>(jsp->state.heartbeats.size()));
    j["joins_total"] = Json::of(jsp->joins_total);
    j["leaves_total"] = Json::of(jsp->leaves_total);
    j["reason"] = Json::of(jsp->last_reason);
    j["fleet"] = fleet_summary_locked(*jsp, now);
    jobs[jsp->name] = j;
  }
  s["jobs"] = jobs;
  s["districts"] = districts_json(now);
  // Hot-path latency histograms (p50/p95/p99 in microseconds, upper-bound
  // estimates from the log buckets — same semantics as telemetry
  // span_percentiles on the Python side).
  s["hist"] = hist_json();
  return s;
}

Json Lighthouse::hist_json() const {
  struct Named {
    const char* name;
    const LatencyHist* h;
  };
  const Named hists[] = {
      {"heartbeat", &hist_heartbeat_},   {"quorum_compute", &hist_quorum_},
      {"anomaly_eval", &hist_anomaly_},  {"http", &hist_http_},
      {"fleet_snapshot", &hist_snapshot_},
  };
  Json out = Json::object();
  for (const auto& nh : hists) {
    LatencyHist::Snap s = nh.h->snapshot();
    Json h = Json::object();
    h["count"] = Json::of(s.count);
    h["sum_us"] = Json::of(s.sum_us);
    h["p50_us"] = Json::of(LatencyHist::percentile_us(s, 0.50));
    h["p95_us"] = Json::of(LatencyHist::percentile_us(s, 0.95));
    h["p99_us"] = Json::of(LatencyHist::percentile_us(s, 0.99));
    out[nh.name] = h;
  }
  return out;
}

// ---------------------------------------------------------------------------
// Federation: root-side district table
// ---------------------------------------------------------------------------

Json Lighthouse::district_note(const Json& req) {
  const std::string name = req.get("district").as_str();
  const int64_t ep = req.get("epoch").as_int(0);
  Json resp = Json::object();
  if (name.empty()) {
    resp["ok"] = Json::of(false);
    resp["error"] = Json::of("district rollup missing district name");
    return resp;
  }
  std::lock_guard<std::mutex> lk(districts_mu_);
  DistrictEntry& e = districts_[name];
  // Per-district fence: a rollup stamped with an epoch below the highest
  // this district has reported is the fenced old primary still talking
  // after a failover — drop it so the root's view can't flap backwards.
  if (ep < e.epoch) {
    e.stale_dropped += 1;
    resp["ok"] = Json::of(false);
    resp["error"] = Json::of("stale district epoch");
    return resp;
  }
  if (ep > e.epoch && e.hb_count > 0) {
    // Epoch advance from a district we already knew = its lighthouse
    // failed over (standby takeover bumps the epoch). Only this district's
    // row changes; siblings and other jobs' tables are untouched.
    e.failovers += 1;
    fprintf(stderr,
            "[lighthouse] district %s failed over: epoch %lld -> %lld\n",
            name.c_str(), static_cast<long long>(e.epoch),
            static_cast<long long>(ep));
  }
  e.epoch = ep;
  e.last_hb_ms = now_ms();
  e.hb_count += 1;
  e.lost = false;
  e.rollup = req.get("district_rollup");
  resp["ok"] = Json::of(true);
  return resp;
}

void Lighthouse::district_scan(int64_t now) {
  std::lock_guard<std::mutex> lk(districts_mu_);
  for (auto& kv : districts_) {
    DistrictEntry& e = kv.second;
    if (!e.lost && now - e.last_hb_ms > opts_.heartbeat_timeout_ms) {
      e.lost = true;
      district_losses_ += 1;
      fprintf(stderr,
              "[lighthouse] district %s lost: no rollup for %lld ms\n",
              kv.first.c_str(), static_cast<long long>(now - e.last_hb_ms));
    }
  }
}

Json Lighthouse::districts_json(int64_t now) {
  std::lock_guard<std::mutex> lk(districts_mu_);
  Json out = Json::object();
  for (const auto& kv : districts_) {
    const DistrictEntry& e = kv.second;
    Json d = Json::object();
    d["age_ms"] = Json::of(now - e.last_hb_ms);
    d["epoch"] = Json::of(e.epoch);
    d["hb_count"] = Json::of(e.hb_count);
    d["failovers"] = Json::of(e.failovers);
    d["stale_dropped"] = Json::of(e.stale_dropped);
    d["lost"] = Json::of(e.lost);
    d["jobs"] = e.rollup.get("jobs");
    out[kv.first] = d;
  }
  return out;
}

// ---------------------------------------------------------------------------
// Live fleet health plane (per job)
// ---------------------------------------------------------------------------

namespace {
constexpr size_t kFleetAnomalyRing = 64;     // rise-edge records kept
constexpr int64_t kFleetStickyMs = 10000;    // straggler display hold
constexpr int64_t kFleetCommitStall = 3;     // cf streak that flags
constexpr double kFleetSlowRateFrac = 0.5;   // rate < frac*median flags
constexpr int64_t kFleetStepLag = 2;         // step < median-lag flags
constexpr int64_t kFleetJitterMult = 8;      // budget = mult * cadence
constexpr int64_t kFleetJitterFloorMs = 1000;
constexpr int64_t kFleetEwmaWarmup = 5;      // gaps before EWMA budget counts
constexpr size_t kFleetSignalRing = 64;      // failure signals kept
// (The old full-sort fleet_median lived here; the MedianTracker members in
// lighthouse.hpp maintain the identical upper median incrementally.)
}  // namespace

int64_t Lighthouse::fleet_jitter_budget_ms(const FleetEntry& e) const {
  // Deterministic when the sender declared its cadence; EWMA of observed
  // inter-arrival gaps as the old-client fallback. The floor absorbs GC /
  // scheduler hiccups that are noise at any cadence.
  int64_t base = e.hb_interval_ms > 0
                     ? e.hb_interval_ms * kFleetJitterMult
                     : static_cast<int64_t>(e.hb_gap_ewma_ms) * kFleetJitterMult;
  return base < kFleetJitterFloorMs ? kFleetJitterFloorMs : base;
}

void Lighthouse::fleet_set_flag(JobState& js, const std::string& replica_id,
                                FleetEntry& e, const std::string& kind,
                                int64_t now, Json detail) {
  e.straggler_until_ms = now + kFleetStickyMs;
  js.fleet_gen += 1;  // sticky-window extension alone changes the table view
  if (e.flags.count(kind)) return;  // only the RISE edge is an anomaly
  if (e.flags.empty()) js.flagged += 1;
  e.flags.insert(kind);
  js.anomaly_seq += 1;
  Json a = Json::object();
  a["seq"] = Json::of(js.anomaly_seq);
  a["ts_ms"] = Json::of(now);
  a["replica_id"] = Json::of(replica_id);
  a["kind"] = Json::of(kind);
  a["job"] = Json::of(js.name);
  a["detail"] = detail;
  js.anomalies.push_back(a);
  while (js.anomalies.size() > kFleetAnomalyRing) {
    // At fleet scale the ring overflows routinely; a silent pop would make
    // the anomaly feed look complete when it is not. The drop count rides
    // /fleet.json + /metrics, and obs_export journals the rise edge.
    js.anomalies.pop_front();
    js.anomalies_dropped += 1;
  }
  fprintf(stderr, "[lighthouse] anomaly #%lld: %s on %s (job %s) %s\n",
          static_cast<long long>(js.anomaly_seq), kind.c_str(),
          replica_id.c_str(), js.name.c_str(), detail.dump().c_str());
  // Digest-driven anomaly rise-edges double as failure evidence (the
  // heartbeat-gap rules have their own cadence-aware hb_lapse source in
  // the scan/eviction path, so they are excluded here).
  if (kind != "hb_jitter") {
    Json d = Json::object();
    d["kind"] = Json::of(kind);
    d["anomaly_seq"] = Json::of(js.anomaly_seq);
    signal_note_locked(js, "digest_anomaly", replica_id, "lighthouse.digest",
                       d, now);
  }
}

void Lighthouse::fleet_clear_flag(JobState& js, FleetEntry& e,
                                  const std::string& kind) {
  if (e.flags.erase(kind) == 0) return;
  if (e.flags.empty()) js.flagged -= 1;
  js.fleet_gen += 1;
}

void Lighthouse::signal_note_locked(JobState& js, const std::string& source,
                                    const std::string& replica_id,
                                    const std::string& site, Json detail,
                                    int64_t now) {
  // One failure signal into the job's ring — same discipline as the anomaly
  // ring: monotonic seq (the consumers' cursor), bounded ring, overflow pops
  // the oldest and bumps the drop counter so the feed can't silently look
  // complete.
  js.signal_seq += 1;
  js.signal_counts[source] += 1;
  // Fault bookkeeping off the evidence plane: hard sources are faults for
  // MTBF, and the first one with no open episode starts the ETTR clock —
  // recovery is "done" when any digest step passes the fleet max as of
  // now (forward progress resumed; see fleet_note_heartbeat).
  if (hard_signal_source(source)) {
    js.hard_signals += 1;
    if (!js.ettr_open) {
      int64_t max_step = 0;
      for (const auto& kv : js.fleet)
        if (kv.second.has_digest) {
          int64_t st = kv.second.digest.get("step").as_int(0);
          if (st > max_step) max_step = st;
        }
      js.ettr_open = true;
      js.ettr_open_ms = now;
      js.ettr_open_step = max_step;
    }
  }
  Json sgn = Json::object();
  sgn["seq"] = Json::of(js.signal_seq);
  sgn["ts_ms"] = Json::of(now);
  sgn["replica_id"] = Json::of(replica_id);
  sgn["source"] = Json::of(source);
  sgn["site"] = Json::of(site);
  sgn["job"] = Json::of(js.name);
  sgn["detail"] = detail;
  js.signals.push_back(sgn);
  while (js.signals.size() > kFleetSignalRing) {
    js.signals.pop_front();
    js.signals_dropped += 1;
  }
  // Stamp the fleet row (never CREATE one: a signal about a replica the
  // fleet never saw must not fabricate a liveness row).
  auto it = js.fleet.find(replica_id);
  if (it != js.fleet.end()) {
    it->second.last_signal = source;
    it->second.last_signal_ms = now;
  }
  js.fleet_gen += 1;
  fprintf(stderr, "[lighthouse] signal #%lld: %s on %s via %s (job %s)\n",
          static_cast<long long>(js.signal_seq), source.c_str(),
          replica_id.c_str(), site.c_str(), js.name.c_str());
}

void Lighthouse::evidence_evict_locked(JobState& js,
                                       const std::string& replica_id,
                                       int64_t now) {
  // Evidence says this replica is dead: drop it from the quorum tables NOW
  // so the next evaluation forms the shrunken quorum, instead of waiting
  // out heartbeat_timeout_ms. Same gate fixups as a graceful leave, but NO
  // tombstone — evidence can be wrong, and the replica's next heartbeat or
  // registration re-admits it with zero ceremony. The fleet row stays
  // (flags, digest, last_signal intact) as detection forensics.
  (void)now;
  const bool was_part = js.state.participants.count(replica_id) > 0;
  const bool was_hb = js.state.heartbeats.count(replica_id) > 0;
  if (!was_part && !was_hb) return;
  js.state.heartbeats.erase(replica_id);
  js.state.heartbeat_addrs.erase(replica_id);
  js.state.participants.erase(replica_id);
  if (was_hb && !was_part) js.hb_not_joined -= 1;
  if (was_part && js.prev_ids.count(replica_id)) js.prev_present -= 1;
}

// Retire / fold one entry's digest contributions. Together these keep the
// running aggregates exactly equal to a full-table recompute: every digest
// row contributes its step and goodput, its rate only when > 0 (matching
// the old scan's filter), and its commit-failure streak to the max-tracker.
void Lighthouse::fleet_agg_remove(JobState& js, const FleetEntry& e) {
  if (!e.has_digest) return;
  double r = e.digest.get("rate").as_double(0.0);
  if (r > 0.0) js.agg_rates.erase(r);
  js.agg_steps.erase(static_cast<double>(e.digest.get("step").as_int(0)));
  js.agg_gps.erase(e.digest.get("gp").as_double(0.0));
  auto it = js.agg_cfs.find(e.digest.get("cf").as_int(0));
  if (it != js.agg_cfs.end()) js.agg_cfs.erase(it);
  js.n_digest -= 1;
  double acct[kNumBadputKinds];
  if (digest_acct(e.digest, acct)) {
    for (int i = 0; i < kNumBadputKinds; i++) js.agg_badput[i] -= acct[i];
    js.n_acct -= 1;
  }
}

void Lighthouse::fleet_agg_insert(JobState& js, const FleetEntry& e) {
  if (!e.has_digest) return;
  double r = e.digest.get("rate").as_double(0.0);
  if (r > 0.0) js.agg_rates.insert(r);
  js.agg_steps.insert(static_cast<double>(e.digest.get("step").as_int(0)));
  js.agg_gps.insert(e.digest.get("gp").as_double(0.0));
  js.agg_cfs.insert(e.digest.get("cf").as_int(0));
  js.n_digest += 1;
  double acct[kNumBadputKinds];
  if (digest_acct(e.digest, acct)) {
    for (int i = 0; i < kNumBadputKinds; i++) js.agg_badput[i] += acct[i];
    js.n_acct += 1;
  }
}

void Lighthouse::fleet_erase(JobState& js, const std::string& replica_id) {
  auto it = js.fleet.find(replica_id);
  if (it == js.fleet.end()) return;
  fleet_agg_remove(js, it->second);
  if (!it->second.flags.empty()) js.flagged -= 1;
  js.fleet.erase(it);
  js.fleet_gen += 1;
}

void Lighthouse::fleet_note_heartbeat(JobState& js,
                                      const std::string& replica_id,
                                      const Json& req, int64_t now) {
  FleetEntry& e = js.fleet[replica_id];
  if (e.hb_count > 0) {
    int64_t gap = now - e.last_hb_ms;
    // Judge the gap against the budget BEFORE folding it into the EWMA —
    // a jittered gap must not raise its own threshold.
    bool budget_valid =
        e.hb_interval_ms > 0 || e.hb_count >= kFleetEwmaWarmup;
    if (budget_valid && gap > fleet_jitter_budget_ms(e)) {
      Json d = Json::object();
      d["gap_ms"] = Json::of(gap);
      d["budget_ms"] = Json::of(fleet_jitter_budget_ms(e));
      fleet_set_flag(js, replica_id, e, "hb_jitter", now, d);
      e.last_jitter_ms = now;
    }
    e.hb_gap_ewma_ms = e.hb_gap_ewma_ms == 0.0
                           ? static_cast<double>(gap)
                           : 0.8 * e.hb_gap_ewma_ms + 0.2 * gap;
  }
  e.last_hb_ms = now;
  e.hb_count += 1;
  js.fleet_gen += 1;
  if (js.first_seen_ms == 0) js.first_seen_ms = now;
  int64_t declared = req.get("hb_interval_ms").as_int(0);
  if (declared > 0) e.hb_interval_ms = declared;
  if (!req.has("digest") || !req.get("digest").is_object()) return;

  // Digest-driven rules run at ARRIVAL, against the job's fleet table as of
  // this heartbeat: given the same per-job digest sequence the flag/anomaly
  // sequence is identical, so a chaos replay reproduces its alerts — and a
  // sibling job's digests can never perturb it.
  // Bounded-cost contract: everything below is O(log N) — the medians the
  // rules compare against come from the running trackers, never from a
  // full-table rescan (tests/test_fleet.py pins tracker == recompute).
  int64_t an_t0 = now_us_steady();
  fleet_agg_remove(js, e);  // retire the previous digest's contributions
  e.digest = req.get("digest");
  e.has_digest = true;
  e.digest_ms = now;
  fleet_agg_insert(js, e);

  int64_t cf = e.digest.get("cf").as_int(0);
  if (cf >= kFleetCommitStall) {
    Json d = Json::object();
    d["cf"] = Json::of(cf);
    fleet_set_flag(js, replica_id, e, "commit_stall", now, d);
  } else {
    fleet_clear_flag(js, e, "commit_stall");
  }

  double own_rate = e.digest.get("rate").as_double(0.0);
  if (js.agg_rates.size() >= 2) {
    double med = js.agg_rates.median();
    if (own_rate < kFleetSlowRateFrac * med) {
      Json d = Json::object();
      d["rate"] = Json::of(own_rate);
      d["median_rate"] = Json::of(med);
      fleet_set_flag(js, replica_id, e, "slow_rate", now, d);
    } else {
      fleet_clear_flag(js, e, "slow_rate");
    }
  }
  int64_t own_step = e.digest.get("step").as_int(0);
  if (js.agg_steps.size() >= 2) {
    int64_t med = static_cast<int64_t>(js.agg_steps.median());
    if (own_step < med - kFleetStepLag) {
      Json d = Json::object();
      d["step"] = Json::of(own_step);
      d["median_step"] = Json::of(med);
      fleet_set_flag(js, replica_id, e, "step_lag", now, d);
    } else {
      fleet_clear_flag(js, e, "step_lag");
    }
  }

  // ETTR close: training moved past the fleet max step recorded when the
  // fault's hard evidence arrived — the job has recovered.
  if (js.ettr_open && own_step > js.ettr_open_step) {
    js.ettr_sum_s += static_cast<double>(now - js.ettr_open_ms) / 1000.0;
    js.ettr_n += 1;
    js.ettr_open = false;
  }

  // SLO burn-rate evaluator: burn = (1 - goodput) / (1 - target) — how
  // many times faster than allotted the job spends its error budget.
  // Rise-edge only (the ring is the pager feed), armed after slo_min_s_
  // accounted seconds so compile/startup can't page, disarmed entirely
  // when target >= 1 (no budget to spend).
  if (js.n_acct > 0 && slo_goodput_ < 1.0) {
    double acct_total = 0.0;
    for (int i = 0; i < kNumBadputKinds; i++)
      acct_total += js.agg_badput[i] > 0.0 ? js.agg_badput[i] : 0.0;
    if (acct_total >= slo_min_s_) {
      double gp = std::max(js.agg_badput[kBadputComputeIdx], 0.0) / acct_total;
      double burn = (1.0 - gp) / (1.0 - slo_goodput_);
      if (burn >= slo_burn_) {
        if (!js.slo_burning) {
          js.slo_burning = true;
          js.slo_seq += 1;
          Json b = Json::object();
          b["seq"] = Json::of(js.slo_seq);
          b["ts_ms"] = Json::of(now);
          b["job"] = Json::of(js.name);
          b["goodput"] = Json::of(gp);
          b["target"] = Json::of(slo_goodput_);
          b["burn"] = Json::of(burn);
          js.slo_burns.push_back(b);
          while (js.slo_burns.size() > kFleetAnomalyRing) {
            js.slo_burns.pop_front();
            js.slo_dropped += 1;
          }
          js.fleet_gen += 1;
          fprintf(stderr,
                  "[lighthouse] slo_burn #%lld: job %s goodput %.4f vs "
                  "target %.4f (burn %.2fx)\n",
                  static_cast<long long>(js.slo_seq), js.name.c_str(), gp,
                  slo_goodput_, burn);
        }
      } else if (js.slo_burning) {
        js.slo_burning = false;  // fall edge: budget spend back in bounds
        js.fleet_gen += 1;
      }
    }
  }
  hist_anomaly_.observe_us(now_us_steady() - an_t0);
}

void Lighthouse::fleet_scan_locked(JobState& js, int64_t now) {
  // Time-based rules only: an OPEN heartbeat gap (the replica is wedged
  // RIGHT NOW — arrival-side checks can't see it because nothing arrives)
  // plus expiry of a jitter flag whose evidence has aged out.
  for (auto& kv : js.fleet) {
    FleetEntry& e = kv.second;
    bool budget_valid =
        e.hb_interval_ms > 0 || e.hb_count >= kFleetEwmaWarmup;
    int64_t open_gap = now - e.last_hb_ms;
    if (budget_valid && open_gap > fleet_jitter_budget_ms(e)) {
      Json d = Json::object();
      d["gap_ms"] = Json::of(open_gap);
      d["budget_ms"] = Json::of(fleet_jitter_budget_ms(e));
      d["open"] = Json::of(true);
      fleet_set_flag(js, kv.first, e, "hb_jitter", now, d);
      e.last_jitter_ms = now;
    } else if (e.flags.count("hb_jitter") &&
               now - e.last_jitter_ms > kFleetStickyMs) {
      fleet_clear_flag(js, e, "hb_jitter");
    }
  }
  // Evidence-driven hb-lapse eviction: a replica whose OPEN gap blew the
  // cadence-aware budget is dead on evidence — signal it and drop it from
  // the quorum tables immediately, so the shrunken quorum forms at tick
  // speed instead of heartbeat_timeout_ms. Only replicas that DECLARED a
  // cadence qualify (old clients keep the timeout path: wire back-compat),
  // and only while they still hold a quorum-plane heartbeat entry — which
  // also makes the signal naturally rise-edge-only.
  if (opts_.evidence) {
    std::vector<std::string> evict;
    for (const auto& kv : js.fleet) {
      const FleetEntry& e = kv.second;
      if (e.hb_interval_ms <= 0) continue;
      int64_t budget = e.hb_interval_ms * opts_.evict_mult;
      if (budget < opts_.evict_floor_ms) budget = opts_.evict_floor_ms;
      if (now - e.last_hb_ms <= budget) continue;
      if (!js.state.heartbeats.count(kv.first)) continue;
      evict.push_back(kv.first);
    }
    for (const auto& id : evict) {
      const FleetEntry& e = js.fleet[id];
      Json d = Json::object();
      d["gap_ms"] = Json::of(now - e.last_hb_ms);
      d["budget_ms"] =
          Json::of(std::max(e.hb_interval_ms * opts_.evict_mult,
                            opts_.evict_floor_ms));
      signal_note_locked(js, "hb_lapse", id, "lighthouse.fleet_scan", d, now);
      evidence_evict_locked(js, id, now);
    }
    // Evidence tick: fresh evidence re-evaluates the quorum NOW; the
    // periodic tick and the timeout landing stay as the fallback.
    if (!evict.empty()) job_tick_locked(js, now);
  }
}

// Aggregate dict straight from the running trackers — O(1) medians/max plus
// one allocation-free pass for the time-dependent straggler count. This is
// the "agg" the property tests compare against a full recompute from the
// row dicts in the same payload.
Json Lighthouse::fleet_agg_locked(JobState& js, int64_t now) {
  int64_t n_straggler = 0;
  for (const auto& kv : js.fleet)
    if (!kv.second.flags.empty() || now < kv.second.straggler_until_ms)
      n_straggler += 1;
  Json agg = Json::object();
  agg["n"] = Json::of(static_cast<int64_t>(js.fleet.size()));
  agg["n_digest"] = Json::of(js.n_digest);
  agg["stragglers"] = Json::of(n_straggler);
  agg["median_rate"] = js.agg_rates.size() == 0
                           ? Json::null()
                           : Json::of(js.agg_rates.median());
  agg["median_step"] =
      js.agg_steps.size() == 0
          ? Json::null()
          : Json::of(static_cast<int64_t>(js.agg_steps.median()));
  agg["median_goodput"] =
      js.agg_gps.size() == 0 ? Json::null() : Json::of(js.agg_gps.median());
  agg["max_commit_failures"] =
      Json::of(js.agg_cfs.empty() ? int64_t{0} : *js.agg_cfs.rbegin());
  agg["anomalies_dropped"] = Json::of(js.anomalies_dropped);
  agg["signals_dropped"] = Json::of(js.signals_dropped);
  // Elastic-membership view: current quorum size plus cumulative
  // join/leave churn, so obs_top's WORLD column tracks capacity changes
  // (deliberate scale-up/down AND crash churn) from the same counters
  // /metrics exports.
  agg["quorum_world"] = Json::of(
      js.last_quorum ? static_cast<int64_t>(js.last_quorum->participants.size())
                     : int64_t{0});
  agg["joins_total"] = Json::of(js.joins_total);
  agg["leaves_total"] = Json::of(js.leaves_total);
  // Control-plane ownership view: the fencing epoch this instance stamps on
  // quorums (obs_top's EPOCH column). A jump means a standby takeover; a
  // reader comparing two lighthouses can tell owner from fenced stale
  // primary by it.
  agg["epoch"] = Json::of(epoch_.load());
  // Time-accounting rollup: per-kind badput seconds summed over every row
  // whose digest carries an acct vector (clamped at 0 — the running sums
  // can drift a few ulps negative), the job goodput fraction (compute
  // share of all accounted seconds), and the fault metrics derived from
  // the evidence plane. Null until any acct digest / fault arrives.
  double acct_total = 0.0;
  for (int i = 0; i < kNumBadputKinds; i++)
    acct_total += js.agg_badput[i] > 0.0 ? js.agg_badput[i] : 0.0;
  if (js.n_acct > 0 && acct_total > 0.0) {
    Json bp = Json::object();
    for (int i = 0; i < kNumBadputKinds; i++)
      bp[kBadputKindNames[i]] = Json::of(std::max(js.agg_badput[i], 0.0));
    agg["badput_s"] = bp;
    agg["goodput_frac"] =
        Json::of(std::max(js.agg_badput[kBadputComputeIdx], 0.0) / acct_total);
  } else {
    agg["badput_s"] = Json::null();
    agg["goodput_frac"] = Json::null();
  }
  agg["mtbf_s"] =
      js.hard_signals > 0 && js.first_seen_ms > 0
          ? Json::of(static_cast<double>(now - js.first_seen_ms) / 1000.0 /
                     static_cast<double>(js.hard_signals))
          : Json::null();
  agg["ettr_s"] = js.ettr_n > 0 ? Json::of(js.ettr_sum_s /
                                           static_cast<double>(js.ettr_n))
                                : Json::null();
  agg["slo_burning"] = Json::of(js.slo_burning);
  agg["slo_dropped"] = Json::of(js.slo_dropped);
  return agg;
}

std::shared_ptr<const Lighthouse::FleetSnapshot> Lighthouse::fleet_snapshot(
    const std::string& job, int64_t now) {
  // Empty job = the composite view: served FROM the default island's cache
  // slot (its payload extended with the cross-job summary + districts), so
  // pre-namespace consumers keep the old top-level schema while each job's
  // full table stays per-job. Keyed per island: one job's content change
  // never rebuilds, or serves a stale gen to, another job.
  const std::string jname = job.empty() ? "default" : job;
  const bool composite = jname == "default";
  JobState& js = job_state(jname);
  // Bounded staleness: any cached payload younger than fleet_snap_ms is
  // served as-is (fleet_snap_ms == 0 disables caching — the "before" mode
  // the fleet_load harness benchmarks against).
  if (opts_.fleet_snap_ms > 0) {
    std::lock_guard<std::mutex> lk(js.snap_mu);
    if (js.snap && now >= js.snap->built_ms &&
        now - js.snap->built_ms <= opts_.fleet_snap_ms)
      return js.snap;
  }
  // Single-flight rebuild: concurrent readers that all see a stale (or
  // absent) snapshot would otherwise each pay the O(N) rebuild at once —
  // a thundering herd that turns the cache off exactly when load peaks.
  // One caller rebuilds; the rest block here, then re-check and serve the
  // winner's result.
  std::lock_guard<std::mutex> rebuild_lk(js.rebuild_mu);
  if (opts_.fleet_snap_ms > 0) {
    std::lock_guard<std::mutex> lk(js.snap_mu);
    if (js.snap && now >= js.snap->built_ms &&
        now - js.snap->built_ms <= opts_.fleet_snap_ms)
      return js.snap;
  }
  int64_t t0 = now_us_steady();
  // Copy raw state under the hot lock; build + dump the JSON off it. The
  // copy is the cheap part (row structs + small digest dicts); the O(N)
  // string formatting that used to stall heartbeats happens unlocked.
  std::vector<std::pair<std::string, FleetEntry>> rows;
  std::deque<Json> anomalies;
  std::deque<Json> signals;
  std::deque<Json> slo_burns;
  std::map<std::string, int64_t> signal_counts;
  Json agg;
  int64_t gen, aseq, sseq, slseq;
  {
    std::lock_guard<std::mutex> lk(js.mu);
    rows.assign(js.fleet.begin(), js.fleet.end());
    anomalies = js.anomalies;
    signals = js.signals;
    slo_burns = js.slo_burns;
    signal_counts = js.signal_counts;
    agg = fleet_agg_locked(js, now);
    gen = js.fleet_gen;
    aseq = js.anomaly_seq;
    sseq = js.signal_seq;
    slseq = js.slo_seq;
  }
  auto snap = std::make_shared<FleetSnapshot>();
  snap->gen = gen;
  snap->built_ms = now;
  Json f = Json::object();
  f["ts_ms"] = Json::of(now);
  f["gen"] = Json::of(gen);
  f["snap_ms"] = Json::of(opts_.fleet_snap_ms);
  f["job"] = Json::of(jname);
  Json reps = Json::object();
  for (const auto& kv : rows) {
    const FleetEntry& e = kv.second;
    Json r = Json::object();
    r["last_hb_age_ms"] = Json::of(now - e.last_hb_ms);
    r["hb_interval_ms"] = Json::of(e.hb_interval_ms);
    // Old client (no digest ever): fields render as null, row stays —
    // the forward-compat contract the tests pin.
    r["digest"] = e.has_digest ? e.digest : Json::null();
    r["digest_age_ms"] =
        e.has_digest ? Json::of(now - e.digest_ms) : Json::null();
    Json fl = Json::array();
    for (const auto& k : e.flags) fl.push(Json::of(k));
    if (now - e.last_hb_ms > opts_.heartbeat_timeout_ms)
      fl.push(Json::of("stale"));  // view-only: presence, not an anomaly
    r["flags"] = fl;
    r["straggler"] =
        Json::of(!e.flags.empty() || now < e.straggler_until_ms);
    // Failure-evidence view: last signal source recorded about this
    // replica and its age (null until any evidence arrives).
    r["signal"] =
        e.last_signal.empty() ? Json::null() : Json::of(e.last_signal);
    r["signal_age_ms"] = e.last_signal.empty()
                             ? Json::null()
                             : Json::of(now - e.last_signal_ms);
    reps[kv.first] = r;
  }
  f["replicas"] = reps;
  f["agg"] = agg;
  Json an = Json::array();
  for (const auto& a : anomalies) an.push(a);
  f["anomalies"] = an;
  f["anomaly_seq"] = Json::of(aseq);
  Json sg = Json::array();
  for (const auto& s : signals) sg.push(s);
  f["signals"] = sg;
  f["signal_seq"] = Json::of(sseq);
  Json sb = Json::array();
  for (const auto& b : slo_burns) sb.push(b);
  f["slo_burns"] = sb;
  f["slo_seq"] = Json::of(slseq);
  Json scnt = Json::object();
  for (const auto& kv : signal_counts) scnt[kv.first] = Json::of(kv.second);
  f["signal_counts"] = scnt;
  if (composite) {
    // Cross-job summary map + district table ride the composite payload
    // only — SUMMARIES, not full tables, so the default payload stays O(N
    // of default) + O(jobs) and per-job readers use ?job=<id>. Each
    // sibling island is locked one at a time, off this island's hot path.
    Json jobs = Json::object();
    for (JobState* oj : all_jobs()) {
      std::lock_guard<std::mutex> olk(oj->mu);
      jobs[oj->name] = fleet_summary_locked(*oj, now);
    }
    f["jobs"] = jobs;
    f["districts"] = districts_json(now);
  }
  snap->json = f;
  snap->body = f.dump();
  hist_snapshot_.observe_us(now_us_steady() - t0);
  std::lock_guard<std::mutex> lk(js.snap_mu);
  js.snap = snap;
  return js.snap;
}

Json Lighthouse::fleet_summary_locked(JobState& js, int64_t now) {
  Json s = fleet_agg_locked(js, now);
  s["anomaly_seq"] = Json::of(js.anomaly_seq);
  s["signal_seq"] = Json::of(js.signal_seq);
  s["slo_seq"] = Json::of(js.slo_seq);
  s["gen"] = Json::of(js.fleet_gen);
  return s;
}

std::string Lighthouse::render_status_html() {
  Json s = status_json();
  std::ostringstream html;
  html << "<!doctype html><html><head><title>torchft-tpu lighthouse</title>"
       << "<style>body{font-family:monospace;margin:2em}table{border-collapse:"
          "collapse}td,th{border:1px solid #999;padding:4px 8px}</style>"
       << "</head><body><h1>torchft-tpu lighthouse</h1>"
       << "<p>quorum_id: " << s.get("quorum_id").as_int() << "</p>";
  html << "<h2>heartbeats</h2><table><tr><th>replica</th><th>age (ms)</th>"
       << "<th></th></tr>";
  for (const auto& kv : s.get("heartbeat_ages_ms").obj) {
    html << "<tr><td>" << kv.first << "</td><td>" << kv.second.as_int()
         << "</td><td><form method=post action=\"/replica/" << kv.first
         << "/kill\" style=\"display:inline\"><button>kill</button></form> "
         << "<form method=post action=\"/replica/" << kv.first
         << "/drain\" style=\"display:inline\"><button>drain</button></form>"
         << "</td></tr>";
  }
  html << "</table><p><form method=post action=\"/drain_all\" "
          "style=\"display:inline\"><button>drain ALL (stop job "
          "cleanly)</button></form></p>";
  // Namespace overview: one row per job island (quorum + fleet summary).
  html << "<h2>jobs</h2><table><tr><th>job</th><th>quorum_id</th>"
       << "<th>members</th><th>participants</th><th>heartbeats</th></tr>";
  for (const auto& kv : s.get("jobs").obj) {
    html << "<tr><td>" << kv.first << "</td><td>"
         << kv.second.get("quorum_id").as_int() << "</td><td>"
         << kv.second.get("members").as_int() << "</td><td>"
         << kv.second.get("participants").as_int() << "</td><td>"
         << kv.second.get("heartbeats").as_int() << "</td></tr>";
  }
  html << "</table>";
  if (!s.get("districts").obj.empty()) {
    html << "<h2>districts</h2><table><tr><th>district</th><th>epoch</th>"
         << "<th>age (ms)</th><th>failovers</th><th>lost</th></tr>";
    for (const auto& kv : s.get("districts").obj) {
      html << "<tr><td>" << kv.first << "</td><td>"
           << kv.second.get("epoch").as_int() << "</td><td>"
           << kv.second.get("age_ms").as_int() << "</td><td>"
           << kv.second.get("failovers").as_int() << "</td><td>"
           << (kv.second.get("lost").as_bool() ? "LOST" : "up")
           << "</td></tr>";
    }
    html << "</table>";
  }
  html << "<h2>previous quorum</h2><table><tr><th>replica</th>"
       << "<th>address</th><th>step</th><th>world</th></tr>";
  if (s.get("prev_quorum").is_object()) {
    for (const auto& p : s.get("prev_quorum").get("participants").arr) {
      html << "<tr><td>" << p.get("replica_id").as_str() << "</td><td>"
           << p.get("address").as_str() << "</td><td>"
           << p.get("step").as_int() << "</td><td>"
           << p.get("world_size").as_int() << "</td></tr>";
    }
  }
  html << "</table>";
  if (!s.get("reason").as_str().empty())
    html << "<p>waiting: " << s.get("reason").as_str() << "</p>";
  html << "</body></html>";
  return html.str();
}

static std::string prom_escape(const std::string& s) {
  // Prometheus label values must escape backslash, double-quote, and
  // newline — replica ids are client-supplied strings.
  std::string out;
  out.reserve(s.size());
  for (char c : s) {
    if (c == '\\') out += "\\\\";
    else if (c == '"') out += "\\\"";
    else if (c == '\n') out += "\\n";
    else out += c;
  }
  return out;
}

std::string Lighthouse::render_metrics() {
  // Prometheus text exposition (the reference lighthouse has only an HTML
  // dashboard; a scrapeable endpoint is what production monitoring needs).
  // Unlabeled gauges keep the pre-namespace series names and report the
  // DEFAULT job (existing alert rules keep firing); job-labeled gauges
  // cover every namespace. Scalars and minimal per-replica tuples are
  // copied under each job's lock one island at a time; all string
  // formatting happens off the hot locks, so a scrape never stalls the
  // heartbeat path behind O(N) text building.
  struct FleetRow {
    std::string id;
    bool straggler = false;
    bool has_rate = false;
    double rate = 0.0;
  };
  struct JobRow {
    std::string name;
    int64_t quorum_id = 0, quorum_gen = 0, joins = 0, leaves = 0;
    int64_t aseq = 0, adropped = 0, gen = 0;
    int64_t sseq = 0, sdropped = 0;
    size_t n_participants = 0, n_members = 0, n_fleet = 0;
    int64_t n_straggler = 0;
    // Time-accounting plane (valid when has_acct).
    bool has_acct = false;
    double badput[kNumBadputKinds] = {};
    double goodput = 0.0;
    int64_t slo_seq = 0;
    bool slo_burning = false;
    double mtbf_s = -1.0, ettr_s = -1.0;  // <0 = no fault observed yet
  };
  int64_t now = now_ms();
  const int64_t epoch = epoch_.load();
  const int64_t takeovers = takeovers_.load();
  const int64_t demotions = demotions_.load();
  const bool is_active = active_.load();
  std::vector<std::pair<std::string, int64_t>> hb_ages;
  std::vector<std::pair<std::string, int64_t>> member_steps;
  std::vector<FleetRow> rows;
  bool have_median = false;
  double median_rate = 0.0;
  std::vector<JobRow> job_rows;
  std::map<std::string, int64_t> def_signal_counts;
  JobRow def;
  for (JobState* jsp : all_jobs()) {
    std::lock_guard<std::mutex> lk(jsp->mu);
    JobRow j;
    j.name = jsp->name;
    j.quorum_id = jsp->state.quorum_id;
    j.quorum_gen = jsp->quorum_gen;
    j.joins = jsp->joins_total;
    j.leaves = jsp->leaves_total;
    j.aseq = jsp->anomaly_seq;
    j.adropped = jsp->anomalies_dropped;
    j.sseq = jsp->signal_seq;
    j.sdropped = jsp->signals_dropped;
    j.gen = jsp->fleet_gen;
    j.n_participants = jsp->state.participants.size();
    j.n_members = jsp->state.prev_quorum
                      ? jsp->state.prev_quorum->participants.size()
                      : 0;
    j.n_fleet = jsp->fleet.size();
    for (const auto& kv : jsp->fleet)
      if (!kv.second.flags.empty() || now < kv.second.straggler_until_ms)
        j.n_straggler += 1;
    double acct_total = 0.0;
    for (int i = 0; i < kNumBadputKinds; i++) {
      j.badput[i] = std::max(jsp->agg_badput[i], 0.0);
      acct_total += j.badput[i];
    }
    if (jsp->n_acct > 0 && acct_total > 0.0) {
      j.has_acct = true;
      j.goodput = j.badput[kBadputComputeIdx] / acct_total;
    }
    j.slo_seq = jsp->slo_seq;
    j.slo_burning = jsp->slo_burning;
    if (jsp->hard_signals > 0 && jsp->first_seen_ms > 0)
      j.mtbf_s = static_cast<double>(now - jsp->first_seen_ms) / 1000.0 /
                 static_cast<double>(jsp->hard_signals);
    if (jsp->ettr_n > 0)
      j.ettr_s = jsp->ettr_sum_s / static_cast<double>(jsp->ettr_n);
    if (jsp->name == "default") {
      def = j;
      hb_ages.reserve(jsp->state.heartbeats.size());
      for (const auto& kv : jsp->state.heartbeats)
        hb_ages.emplace_back(kv.first, now - kv.second);
      if (jsp->state.prev_quorum)
        for (const auto& mem : jsp->state.prev_quorum->participants)
          member_steps.emplace_back(mem.replica_id, mem.step);
      rows.reserve(jsp->fleet.size());
      for (const auto& kv : jsp->fleet) {
        FleetRow r;
        r.id = kv.first;
        r.straggler =
            !kv.second.flags.empty() || now < kv.second.straggler_until_ms;
        if (kv.second.has_digest) {
          r.rate = kv.second.digest.get("rate").as_double(0.0);
          r.has_rate = true;
        }
        rows.push_back(std::move(r));
      }
      if (jsp->agg_rates.size() > 0) {
        have_median = true;
        median_rate = jsp->agg_rates.median();
      }
      def_signal_counts = jsp->signal_counts;
    }
    job_rows.push_back(std::move(j));
  }
  struct DistrictRow {
    std::string name;
    int64_t epoch = 0, failovers = 0, stale_dropped = 0;
    bool lost = false;
  };
  std::vector<DistrictRow> dist_rows;
  int64_t district_losses;
  {
    std::lock_guard<std::mutex> lk(districts_mu_);
    district_losses = district_losses_;
    for (const auto& kv : districts_) {
      DistrictRow d;
      d.name = kv.first;
      d.epoch = kv.second.epoch;
      d.failovers = kv.second.failovers;
      d.stale_dropped = kv.second.stale_dropped;
      d.lost = kv.second.lost;
      dist_rows.push_back(std::move(d));
    }
  }
  // Label-cardinality bound (TORCHFT_EXPORT_MAX_REPLICAS, shared with
  // obs_export): above the cap, per-replica series are emitted only for
  // anomalous/straggler replicas; healthy rows collapse into the aggregate
  // gauges plus a suppressed-count so the scrape stays O(cap), not O(N).
  const size_t cap = static_cast<size_t>(export_max_replicas_);
  const bool capped = rows.size() > cap;
  int64_t suppressed = 0;
  std::ostringstream m;
  m << "# HELP torchft_lighthouse_quorum_id Current quorum id.\n"
    << "# TYPE torchft_lighthouse_quorum_id gauge\n"
    << "torchft_lighthouse_quorum_id " << def.quorum_id << "\n";
  m << "# HELP torchft_lighthouse_quorum_generation Quorum broadcasts since "
       "boot.\n"
    << "# TYPE torchft_lighthouse_quorum_generation counter\n"
    << "torchft_lighthouse_quorum_generation " << def.quorum_gen << "\n";
  m << "# HELP torchft_lighthouse_epoch Fencing epoch stamped on quorums.\n"
    << "# TYPE torchft_lighthouse_epoch gauge\n"
    << "torchft_lighthouse_epoch " << epoch << "\n";
  m << "# HELP torchft_lighthouse_active 1 when this instance owns the "
       "fleet (forms quorums); 0 when standby/fenced.\n"
    << "# TYPE torchft_lighthouse_active gauge\n"
    << "torchft_lighthouse_active " << (is_active ? 1 : 0) << "\n";
  m << "# HELP torchft_lighthouse_takeovers_total Standby->active "
       "transitions.\n"
    << "# TYPE torchft_lighthouse_takeovers_total counter\n"
    << "torchft_lighthouse_takeovers_total " << takeovers << "\n";
  m << "# HELP torchft_lighthouse_demotions_total Active->standby fences "
       "(superseded by a higher epoch).\n"
    << "# TYPE torchft_lighthouse_demotions_total counter\n"
    << "torchft_lighthouse_demotions_total " << demotions << "\n";
  m << "# HELP torchft_lighthouse_joins_total Members added across quorum "
       "transitions.\n"
    << "# TYPE torchft_lighthouse_joins_total counter\n"
    << "torchft_lighthouse_joins_total " << def.joins << "\n";
  m << "# HELP torchft_lighthouse_leaves_total Members gone across quorum "
       "transitions.\n"
    << "# TYPE torchft_lighthouse_leaves_total counter\n"
    << "torchft_lighthouse_leaves_total " << def.leaves << "\n";
  m << "# HELP torchft_lighthouse_participants Replicas currently waiting in "
       "the next quorum.\n"
    << "# TYPE torchft_lighthouse_participants gauge\n"
    << "torchft_lighthouse_participants " << def.n_participants << "\n";
  m << "# HELP torchft_lighthouse_quorum_members Members of the last "
       "delivered quorum.\n"
    << "# TYPE torchft_lighthouse_quorum_members gauge\n"
    << "torchft_lighthouse_quorum_members " << def.n_members << "\n";
  int64_t max_hb_age = 0;
  for (const auto& kv : hb_ages)
    if (kv.second > max_hb_age) max_hb_age = kv.second;
  m << "# HELP torchft_lighthouse_heartbeat_age_max_ms Oldest replica "
       "heartbeat age.\n"
    << "# TYPE torchft_lighthouse_heartbeat_age_max_ms gauge\n"
    << "torchft_lighthouse_heartbeat_age_max_ms " << max_hb_age << "\n";
  if (!capped) {
    m << "# HELP torchft_lighthouse_heartbeat_age_ms Milliseconds since "
         "each replica's last heartbeat.\n"
      << "# TYPE torchft_lighthouse_heartbeat_age_ms gauge\n";
    for (const auto& kv : hb_ages)
      m << "torchft_lighthouse_heartbeat_age_ms{replica=\""
        << prom_escape(kv.first) << "\"} " << kv.second << "\n";
  }
  if (!member_steps.empty() && !capped) {
    m << "# HELP torchft_lighthouse_member_step Training step each quorum "
         "member reported.\n"
      << "# TYPE torchft_lighthouse_member_step gauge\n";
    for (const auto& kv : member_steps)
      m << "torchft_lighthouse_member_step{replica=\""
        << prom_escape(kv.first) << "\"} " << kv.second << "\n";
  }
  // Live-plane alert gauges: straggler flags + the anomaly counter are
  // what a pager rule fires on; per-replica step rate + the fleet median
  // give the rule its denominator.
  m << "# HELP torchft_lighthouse_anomalies_total Anomaly rise-edges "
       "detected since boot.\n"
    << "# TYPE torchft_lighthouse_anomalies_total counter\n"
    << "torchft_lighthouse_anomalies_total " << def.aseq << "\n";
  m << "# HELP torchft_lighthouse_anomalies_dropped Anomaly records evicted "
       "from the bounded ring (feed incomplete when > 0).\n"
    << "# TYPE torchft_lighthouse_anomalies_dropped counter\n"
    << "torchft_lighthouse_anomalies_dropped " << def.adropped << "\n";
  // Failure-evidence counters: per-source totals (bounded: the source enum
  // is closed at SIGNAL_SOURCES size, never per-replica) plus the ring-drop
  // counter — the same incompleteness alarm the anomaly ring has.
  m << "# HELP torchft_lighthouse_signals_total Failure signals recorded "
       "since boot, by evidence source.\n"
    << "# TYPE torchft_lighthouse_signals_total counter\n"
    << "torchft_lighthouse_signals_total " << def.sseq << "\n";
  for (const auto& kv : def_signal_counts)
    m << "torchft_lighthouse_signals_total{source=\"" << prom_escape(kv.first)
      << "\"} " << kv.second << "\n";
  m << "# HELP torchft_lighthouse_signals_dropped Failure-signal records "
       "evicted from the bounded ring (feed incomplete when > 0).\n"
    << "# TYPE torchft_lighthouse_signals_dropped counter\n"
    << "torchft_lighthouse_signals_dropped " << def.sdropped << "\n";
  m << "# HELP torchft_lighthouse_fleet_gen Fleet-table content generation "
       "(bumped on every mutation; tags /fleet.json snapshots).\n"
    << "# TYPE torchft_lighthouse_fleet_gen counter\n"
    << "torchft_lighthouse_fleet_gen " << def.gen << "\n";
  m << "# HELP torchft_lighthouse_fleet_replicas Replicas in the fleet "
       "table.\n"
    << "# TYPE torchft_lighthouse_fleet_replicas gauge\n"
    << "torchft_lighthouse_fleet_replicas " << rows.size() << "\n";
  m << "# HELP torchft_lighthouse_fleet_stragglers Replicas currently "
       "flagged or inside the sticky straggler window.\n"
    << "# TYPE torchft_lighthouse_fleet_stragglers gauge\n"
    << "torchft_lighthouse_fleet_stragglers " << def.n_straggler << "\n";
  if (!rows.empty()) {
    std::ostringstream strag, per_replica;
    for (const auto& r : rows) {
      if (capped && !r.straggler) {
        suppressed += 1;
        continue;
      }
      strag << "torchft_lighthouse_straggler{replica=\""
            << prom_escape(r.id) << "\"} " << (r.straggler ? 1 : 0) << "\n";
      if (r.has_rate)
        per_replica << "torchft_lighthouse_replica_step_rate{replica=\""
                    << prom_escape(r.id) << "\"} " << r.rate << "\n";
    }
    std::string st = strag.str();
    if (!st.empty()) {
      m << "# HELP torchft_lighthouse_straggler Replica currently flagged "
           "as a straggler (1) or healthy (0).\n"
        << "# TYPE torchft_lighthouse_straggler gauge\n"
        << st;
    }
    std::string per = per_replica.str();
    if (!per.empty()) {
      m << "# HELP torchft_lighthouse_replica_step_rate Committed steps "
           "per second each replica reported in its digest.\n"
        << "# TYPE torchft_lighthouse_replica_step_rate gauge\n"
        << per;
    }
    if (have_median) {
      m << "# HELP torchft_lighthouse_fleet_median_step_rate Fleet median "
           "of reported step rates.\n"
        << "# TYPE torchft_lighthouse_fleet_median_step_rate gauge\n"
        << "torchft_lighthouse_fleet_median_step_rate " << median_rate
        << "\n";
    }
  }
  m << "# HELP torchft_lighthouse_replicas_suppressed Healthy replicas "
       "whose per-replica series were collapsed into aggregates "
       "(TORCHFT_EXPORT_MAX_REPLICAS).\n"
    << "# TYPE torchft_lighthouse_replicas_suppressed gauge\n"
    << "torchft_lighthouse_replicas_suppressed " << suppressed << "\n";
  // Per-job series: every namespace island, keyed by the job label. The
  // cardinality here is O(jobs), not O(replicas) — bounded by how many
  // jobs the fleet actually runs.
  m << "# HELP torchft_lighthouse_job_quorum_id Current quorum id per job "
       "namespace.\n"
    << "# TYPE torchft_lighthouse_job_quorum_id gauge\n";
  for (const auto& j : job_rows)
    m << "torchft_lighthouse_job_quorum_id{job=\"" << prom_escape(j.name)
      << "\"} " << j.quorum_id << "\n";
  m << "# HELP torchft_lighthouse_job_quorum_generation Quorum broadcasts "
       "per job namespace.\n"
    << "# TYPE torchft_lighthouse_job_quorum_generation counter\n";
  for (const auto& j : job_rows)
    m << "torchft_lighthouse_job_quorum_generation{job=\""
      << prom_escape(j.name) << "\"} " << j.quorum_gen << "\n";
  m << "# HELP torchft_lighthouse_job_participants Replicas waiting in the "
       "next quorum per job namespace.\n"
    << "# TYPE torchft_lighthouse_job_participants gauge\n";
  for (const auto& j : job_rows)
    m << "torchft_lighthouse_job_participants{job=\"" << prom_escape(j.name)
      << "\"} " << j.n_participants << "\n";
  m << "# HELP torchft_lighthouse_job_fleet_replicas Fleet-table rows per "
       "job namespace.\n"
    << "# TYPE torchft_lighthouse_job_fleet_replicas gauge\n";
  for (const auto& j : job_rows)
    m << "torchft_lighthouse_job_fleet_replicas{job=\"" << prom_escape(j.name)
      << "\"} " << j.n_fleet << "\n";
  m << "# HELP torchft_lighthouse_job_stragglers Flagged/sticky replicas "
       "per job namespace.\n"
    << "# TYPE torchft_lighthouse_job_stragglers gauge\n";
  for (const auto& j : job_rows)
    m << "torchft_lighthouse_job_stragglers{job=\"" << prom_escape(j.name)
      << "\"} " << j.n_straggler << "\n";
  m << "# HELP torchft_lighthouse_job_anomalies_total Anomaly rise-edges "
       "per job namespace.\n"
    << "# TYPE torchft_lighthouse_job_anomalies_total counter\n";
  for (const auto& j : job_rows)
    m << "torchft_lighthouse_job_anomalies_total{job=\"" << prom_escape(j.name)
      << "\"} " << j.aseq << "\n";
  // Time-accounting series. Cardinality stays bounded by construction:
  // goodput/SLO gauges are O(jobs); the badput family is O(jobs x the
  // CLOSED kind enum), never per-replica.
  m << "# HELP torchft_lighthouse_job_goodput_fraction Compute share of "
       "all accounted replica-seconds per job namespace.\n"
    << "# TYPE torchft_lighthouse_job_goodput_fraction gauge\n";
  for (const auto& j : job_rows)
    if (j.has_acct)
      m << "torchft_lighthouse_job_goodput_fraction{job=\""
        << prom_escape(j.name) << "\"} " << j.goodput << "\n";
  m << "# HELP torchft_lighthouse_job_badput_seconds Accounted "
       "replica-seconds per badput kind per job namespace (closed enum).\n"
    << "# TYPE torchft_lighthouse_job_badput_seconds gauge\n";
  for (const auto& j : job_rows)
    if (j.has_acct)
      for (int i = 0; i < kNumBadputKinds; i++)
        m << "torchft_lighthouse_job_badput_seconds{job=\""
          << prom_escape(j.name) << "\",kind=\"" << kBadputKindNames[i]
          << "\"} " << j.badput[i] << "\n";
  m << "# HELP torchft_lighthouse_job_slo_burns_total SLO burn-rate rise "
       "edges per job namespace.\n"
    << "# TYPE torchft_lighthouse_job_slo_burns_total counter\n";
  for (const auto& j : job_rows)
    m << "torchft_lighthouse_job_slo_burns_total{job=\"" << prom_escape(j.name)
      << "\"} " << j.slo_seq << "\n";
  m << "# HELP torchft_lighthouse_job_slo_burning Job currently burning "
       "its goodput error budget faster than the threshold (1) or not (0).\n"
    << "# TYPE torchft_lighthouse_job_slo_burning gauge\n";
  for (const auto& j : job_rows)
    m << "torchft_lighthouse_job_slo_burning{job=\"" << prom_escape(j.name)
      << "\"} " << (j.slo_burning ? 1 : 0) << "\n";
  m << "# HELP torchft_lighthouse_job_mtbf_seconds Mean time between "
       "hard-evidence faults per job namespace.\n"
    << "# TYPE torchft_lighthouse_job_mtbf_seconds gauge\n";
  for (const auto& j : job_rows)
    if (j.mtbf_s >= 0.0)
      m << "torchft_lighthouse_job_mtbf_seconds{job=\"" << prom_escape(j.name)
        << "\"} " << j.mtbf_s << "\n";
  m << "# HELP torchft_lighthouse_job_ettr_seconds Mean evidence-to-"
       "training-resumption time per job namespace.\n"
    << "# TYPE torchft_lighthouse_job_ettr_seconds gauge\n";
  for (const auto& j : job_rows)
    if (j.ettr_s >= 0.0)
      m << "torchft_lighthouse_job_ettr_seconds{job=\"" << prom_escape(j.name)
        << "\"} " << j.ettr_s << "\n";
  // District (federation) series, present on a root lighthouse.
  m << "# HELP torchft_lighthouse_districts Districts reporting rollups.\n"
    << "# TYPE torchft_lighthouse_districts gauge\n"
    << "torchft_lighthouse_districts " << dist_rows.size() << "\n";
  m << "# HELP torchft_lighthouse_district_losses_total Districts that "
       "went silent past the heartbeat timeout (cumulative).\n"
    << "# TYPE torchft_lighthouse_district_losses_total counter\n"
    << "torchft_lighthouse_district_losses_total " << district_losses << "\n";
  if (!dist_rows.empty()) {
    m << "# HELP torchft_lighthouse_district_up District currently "
         "reporting (1) or lost (0).\n"
      << "# TYPE torchft_lighthouse_district_up gauge\n";
    for (const auto& d : dist_rows)
      m << "torchft_lighthouse_district_up{district=\"" << prom_escape(d.name)
        << "\"} " << (d.lost ? 0 : 1) << "\n";
    m << "# HELP torchft_lighthouse_district_epoch Max fencing epoch seen "
         "from each district.\n"
      << "# TYPE torchft_lighthouse_district_epoch gauge\n";
    for (const auto& d : dist_rows)
      m << "torchft_lighthouse_district_epoch{district=\""
        << prom_escape(d.name) << "\"} " << d.epoch << "\n";
    m << "# HELP torchft_lighthouse_district_failovers_total Epoch advances "
         "observed per district (its lighthouse failed over).\n"
      << "# TYPE torchft_lighthouse_district_failovers_total counter\n";
    for (const auto& d : dist_rows)
      m << "torchft_lighthouse_district_failovers_total{district=\""
        << prom_escape(d.name) << "\"} " << d.failovers << "\n";
    m << "# HELP torchft_lighthouse_district_stale_dropped_total Rollups "
         "fenced out per district (old primary after failover).\n"
      << "# TYPE torchft_lighthouse_district_stale_dropped_total counter\n";
    for (const auto& d : dist_rows)
      m << "torchft_lighthouse_district_stale_dropped_total{district=\""
        << prom_escape(d.name) << "\"} " << d.stale_dropped << "\n";
  }
  // Hot-path latency histograms: upper-bound percentile gauges per path
  // (log buckets, telemetry._hist_percentile semantics).
  struct Named {
    const char* name;
    const LatencyHist* h;
  };
  const Named hists[] = {
      {"heartbeat", &hist_heartbeat_},   {"quorum_compute", &hist_quorum_},
      {"anomaly_eval", &hist_anomaly_},  {"http", &hist_http_},
      {"fleet_snapshot", &hist_snapshot_},
  };
  m << "# HELP torchft_lighthouse_hotpath_p50_us Hot-path latency p50 "
       "(upper-bound log-bucket estimate, microseconds).\n"
    << "# TYPE torchft_lighthouse_hotpath_p50_us gauge\n"
    << "# HELP torchft_lighthouse_hotpath_p95_us Hot-path latency p95.\n"
    << "# TYPE torchft_lighthouse_hotpath_p95_us gauge\n"
    << "# HELP torchft_lighthouse_hotpath_count Hot-path samples observed.\n"
    << "# TYPE torchft_lighthouse_hotpath_count counter\n";
  for (const auto& nh : hists) {
    LatencyHist::Snap s = nh.h->snapshot();
    m << "torchft_lighthouse_hotpath_p50_us{path=\"" << nh.name << "\"} "
      << LatencyHist::percentile_us(s, 0.50) << "\n"
      << "torchft_lighthouse_hotpath_p95_us{path=\"" << nh.name << "\"} "
      << LatencyHist::percentile_us(s, 0.95) << "\n"
      << "torchft_lighthouse_hotpath_count{path=\"" << nh.name << "\"} "
      << s.count << "\n";
  }
  return m.str();
}

void Lighthouse::handle_http(int fd) {
  int64_t t0 = now_us_steady();
  std::string req = read_http_request(fd, 10000);
  std::string path = "/";
  std::string method;
  {
    size_t sp1 = req.find(' ');
    size_t sp2 = req.find(' ', sp1 + 1);
    if (sp1 != std::string::npos && sp2 != std::string::npos) {
      method = req.substr(0, sp1);
      path = req.substr(sp1 + 1, sp2 - sp1 - 1);
    }
  }
  // Query-string split: /fleet.json?job=<id> selects one namespace island
  // (only the "job" key is recognized; anything else is ignored).
  std::string query;
  {
    size_t qpos = path.find('?');
    if (qpos != std::string::npos) {
      query = path.substr(qpos + 1);
      path = path.substr(0, qpos);
    }
  }
  std::string q_job;
  {
    size_t pos = 0;
    while (pos < query.size()) {
      size_t amp = query.find('&', pos);
      std::string kv = query.substr(
          pos, amp == std::string::npos ? std::string::npos : amp - pos);
      if (kv.rfind("job=", 0) == 0) q_job = kv.substr(4);
      if (amp == std::string::npos) break;
      pos = amp + 1;
    }
  }
  // Side-effecting endpoints (kill / drain / drain_all) are POST-only:
  // a GET must never stop a replica — browsers prefetch URLs and
  // monitoring scrapers walk dashboard paths. The dashboard forms
  // declare method=post already.
  const bool side_effecting =
      path == "/drain_all" || path.rfind("/replica/", 0) == 0;
  if (side_effecting && method != "POST") {
    std::string body405 = "method not allowed (POST required)";
    std::ostringstream hdr;
    hdr << "HTTP/1.1 405 Method Not Allowed\r\nContent-Type: text/plain"
        << "\r\nAllow: POST\r\nContent-Length: " << body405.size()
        << "\r\nConnection: close\r\n\r\n";
    std::string out405 = hdr.str() + body405;
    write_all(fd, out405.data(), out405.size(), 10000);
    hist_http_.observe_us(now_us_steady() - t0);
    return;
  }
  std::string body;
  std::string ctype = "text/html";
  int code = 200;
  if (path == "/" || path == "/status") {
    body = render_status_html();
  } else if (path == "/status.json") {
    body = status_json().dump();
    ctype = "application/json";
  } else if (path == "/fleet.json") {
    // Pre-dumped cached snapshot: serving is a string copy, not an O(N)
    // JSON build under the job lock (the contention the fleet_load harness
    // measures). ?job=<id> selects that namespace; bare = composite.
    body = fleet_snapshot(q_job, now_ms())->body;
    ctype = "application/json";
  } else if (path == "/metrics") {
    body = render_metrics();
    ctype = "text/plain; version=0.0.4";
  } else if (path.rfind("/replica/", 0) == 0 && path.size() > 14 &&
             (path.compare(path.size() - 5, 5, "/kill") == 0 ||
              path.compare(path.size() - 6, 6, "/drain") == 0)) {
    bool is_kill = path.compare(path.size() - 5, 5, "/kill") == 0;
    size_t suffix = is_kill ? 5 : 6;
    std::string replica_id = path.substr(9, path.size() - 9 - suffix);
    Json kreq = Json::object();
    kreq["type"] = Json::of(is_kill ? "kill" : "drain");
    kreq["replica_id"] = Json::of(replica_id);
    if (!q_job.empty()) kreq["job"] = Json::of(q_job);
    Json kresp = handle_request(kreq, now_ms() + 5000);
    body = kresp.dump();
    ctype = "application/json";
    if (!kresp.get("ok").as_bool()) code = 404;
  } else if (path == "/drain_all") {
    Json dreq = Json::object();
    dreq["type"] = Json::of("drain_all");
    if (!q_job.empty()) dreq["job"] = Json::of(q_job);
    Json dresp = handle_request(dreq, now_ms() + 15000);
    body = dresp.dump();
    ctype = "application/json";
  } else {
    code = 404;
    body = "not found";
    ctype = "text/plain";
  }
  std::ostringstream hdr;
  hdr << "HTTP/1.1 " << code << (code == 200 ? " OK" : " Not Found")
      << "\r\nContent-Type: " << ctype
      << "\r\nContent-Length: " << body.size()
      << "\r\nConnection: close\r\n\r\n";
  std::string out = hdr.str() + body;
  write_all(fd, out.data(), out.size(), 10000);
  hist_http_.observe_us(now_us_steady() - t0);
}

}  // namespace tft
