#include "lighthouse.hpp"

#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <sstream>
#include <vector>

#include "chaos.hpp"
#include "net.hpp"

namespace tft {

namespace {
// Steady-clock microseconds for the hot-path histograms (wall clock can
// step; a latency sample must not).
int64_t now_us_steady() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}
}  // namespace

Lighthouse::Lighthouse(const std::string& bind_host, int port,
                       LighthouseOpts opts)
    : bind_host_(bind_host), port_(port), opts_(opts) {
  // Shared with tools/obs_export.py (same knob, same default): above this
  // many replicas, per-replica /metrics series collapse to aggregates +
  // anomalous rows only, so a 1024-replica scrape stays bounded.
  const char* em = std::getenv("TORCHFT_EXPORT_MAX_REPLICAS");
  if (em != nullptr && *em != '\0') export_max_replicas_ = std::atoll(em);
  if (export_max_replicas_ < 0) export_max_replicas_ = 0;
}

Lighthouse::~Lighthouse() { stop(); }

// Reserve this much generation headroom on every durable save: generations
// bump on every broadcast but are only persisted on (rare) quorum_id/epoch
// changes, so a reload must jump past anything possibly handed out since
// the last fsync to keep (epoch, generation) strictly monotone.
static constexpr int64_t kGenReserve = 1 << 20;

void Lighthouse::persist_locked() {
  if (opts_.state_dir.empty()) return;
  LighthouseDurable d;
  d.epoch = epoch_;
  d.quorum_id = state_.quorum_id;
  d.generation = quorum_gen_ + kGenReserve;
  if (!lh_state_save(opts_.state_dir, d)) {
    fprintf(stderr, "[lighthouse] WARNING: failed to persist state to %s\n",
            opts_.state_dir.c_str());
  }
}

bool Lighthouse::start() {
  listen_fd_ = tcp_listen(bind_host_, port_);
  if (listen_fd_ < 0) return false;
  port_ = bound_port(listen_fd_);
  {
    std::lock_guard<std::mutex> lk(mu_);
    active_ = !opts_.standby;
    LighthouseDurable d;
    if (!opts_.state_dir.empty() && lh_state_load(opts_.state_dir, &d)) {
      // Warm restart: resume the persisted reign — same epoch (we may still
      // be the rightful owner), quorum ids continue strictly monotone, and
      // generations jump past the reserved headroom. Participant/fleet
      // tables rebuild from the live heartbeat stream.
      epoch_ = d.epoch;
      state_.quorum_id = d.quorum_id;
      quorum_gen_ = d.generation;
      fprintf(stderr,
              "[lighthouse] warm restart from %s: epoch=%lld quorum_id=%lld "
              "gen=%lld%s\n",
              opts_.state_dir.c_str(), static_cast<long long>(epoch_),
              static_cast<long long>(state_.quorum_id),
              static_cast<long long>(quorum_gen_),
              active_ ? "" : " (standby)");
    }
    if (active_ && epoch_ == 0) epoch_ = 1;  // fresh active boot
    if (active_) persist_locked();
  }
  running_ = true;
  accept_thread_ = std::thread([this] { accept_loop(); });
  tick_thread_ = std::thread([this] { tick_loop(); });
  return true;
}

void Lighthouse::stop() {
  if (!running_.exchange(false)) return;
  cv_.notify_all();
  conns_.shutdown_all();  // interrupt in-flight frames so handlers drain fast
  // shutdown() unblocks the accept loop; close() + reset must wait until
  // the thread is joined — accept_loop reads listen_fd_ until then.
  if (listen_fd_ >= 0) shutdown(listen_fd_, SHUT_RDWR);
  if (accept_thread_.joinable()) accept_thread_.join();
  if (tick_thread_.joinable()) tick_thread_.join();
  if (listen_fd_ >= 0) {
    close(listen_fd_);
    listen_fd_ = -1;
  }
  conns_.wait_idle(10000);
}

std::string Lighthouse::address() const {
  return "127.0.0.1:" + std::to_string(port_);
}

void Lighthouse::accept_loop() {
  while (running_) {
    int fd = tcp_accept(listen_fd_, 200);
    if (fd < 0) continue;
    if (!conns_.add(fd)) {
      close(fd);
      continue;
    }
    std::thread([this, fd] {
      handle_conn(fd);
      conns_.remove(fd);
    }).detach();
  }
}

void Lighthouse::tick_loop() {
  while (running_) {
    tick();
    sleep_ms(opts_.quorum_tick_ms);
  }
}

void Lighthouse::tick() {
  std::unique_lock<std::mutex> lk(mu_);
  // Time-based anomaly rules (open heartbeat gaps, digest staleness) ride
  // the tick so a wedged replica is flagged while it is STILL wedged —
  // before its step completes or its heartbeat resumes.
  fleet_scan_locked(now_ms());
  // A standby absorbs heartbeats (keeping fleet/participant tables warm)
  // but must not form quorums — there is exactly one epoch owner, and it is
  // not us until a manager fails over and its quorum request promotes us.
  if (!active_) {
    last_reason_ = "standby (not forming quorums)";
    return;
  }
  std::string reason;
  int64_t q_t0 = now_us_steady();
  auto members = quorum_compute(now_ms(), state_, opts_, &reason);
  hist_quorum_.observe_us(now_us_steady() - q_t0);
  if (!members) {
    if (reason != last_reason_ && !state_.participants.empty()) {
      fprintf(stderr, "[lighthouse] no quorum: %s\n", reason.c_str());
    }
    last_reason_ = reason;
    return;
  }
  // Bump quorum_id only when membership changed or a member reported commit
  // failures (lighthouse.rs:305-325) — a changed id forces process groups to
  // reconfigure, so we avoid it when the world is stable.
  bool bump = false;
  if (!state_.prev_quorum) {
    bump = true;
  } else if (quorum_changed(state_.prev_quorum->participants, *members)) {
    bump = true;
  } else {
    for (const auto& m : *members)
      if (m.commit_failures > 0) bump = true;
  }
  if (bump) {
    state_.quorum_id += 1;
    // Fsync the new id BEFORE publishing the quorum: a crash between
    // publish and persist could otherwise let a warm restart re-issue an id
    // the fleet has already seen.
    persist_locked();
  }

  // Participant churn across quorum transitions (surfaced via status +
  // /metrics): a member present now but not in the previous quorum is a
  // join; one gone is a leave. Covers crash, kill, and graceful drain
  // uniformly at the granularity monitoring cares about.
  {
    std::set<std::string> prev_ids;
    if (state_.prev_quorum)
      for (const auto& m : state_.prev_quorum->participants)
        prev_ids.insert(m.replica_id);
    std::set<std::string> new_ids;
    for (const auto& m : *members) new_ids.insert(m.replica_id);
    for (const auto& id : new_ids)
      if (!prev_ids.count(id)) joins_total_ += 1;
    for (const auto& id : prev_ids)
      if (!new_ids.count(id)) leaves_total_ += 1;
  }

  Quorum q;
  q.quorum_id = state_.quorum_id;
  q.participants = *members;
  q.created_ms = now_ms();
  q.epoch = epoch_;
  q.generation = quorum_gen_ + 1;
  state_.prev_quorum = q;
  state_.participants.clear();  // next round starts fresh (lighthouse.rs:336)
  last_quorum_ = q;
  quorum_gen_ += 1;
  last_reason_.clear();
  fprintf(stderr, "[lighthouse] quorum %lld formed with %zu members\n",
          static_cast<long long>(q.quorum_id), q.participants.size());
  if (std::getenv("TORCHFT_LH_DEBUG") != nullptr) {
    std::string ids;
    for (const auto& m : q.participants) ids += m.replica_id + " ";
    fprintf(stderr, "[lighthouse] +%lld formed gen=%lld members: %s\n",
            static_cast<long long>(now_ms() % 1000000),
            static_cast<long long>(quorum_gen_), ids.c_str());
  }
  lk.unlock();
  cv_.notify_all();
}

void Lighthouse::handle_conn(int fd) {
  // Sniff: framed requests begin with a 4-byte big-endian length whose first
  // byte is 0 for any sane control message; HTTP begins with ASCII letters.
  char peek[4] = {0};
  int n = peek_bytes(fd, peek, 4, 30000);
  if (n <= 0) {
    close(fd);
    return;
  }
  if (n >= 3 && (memcmp(peek, "GET", 3) == 0 || memcmp(peek, "POS", 3) == 0 ||
                 memcmp(peek, "HEA", 3) == 0)) {
    handle_http(fd);
    close(fd);
    return;
  }
  // Persistent framed connection: serve requests until the peer closes.
  while (running_) {
    std::string payload;
    if (!recv_frame(fd, &payload, 3600 * 1000)) break;
    Json req;
    std::string err;
    Json resp;
    if (!Json::parse(payload, &req, &err)) {
      resp["ok"] = Json::of(false);
      resp["error"] = Json::of("bad json: " + err);
    } else {
      // Server-side chaos (rpc_delay sleeps; rpc_drop/reset tear the
      // connection without replying — the client sees a torn RPC and must
      // absorb it through its retry policy).
      if (!chaos::server_rpc(req.get("type").as_str())) break;
      int64_t timeout = req.get("timeout_ms").as_int(60000);
      resp = handle_request(req, now_ms() + timeout);
      // Echo the caller's trace id so both planes of a step share one id
      // (the Python Manager mints it; responses carry it for correlation).
      if (req.has("trace_id")) resp["trace_id"] = req.get("trace_id");
    }
    if (!send_frame(fd, resp.dump(), 30000)) break;
  }
  close(fd);
}

Json Lighthouse::handle_request(const Json& req, int64_t deadline_ms) {
  const std::string type = req.get("type").as_str();
  Json resp = Json::object();
  if (type == "heartbeat") {
    // Timed from before the lock: the histogram must show contention (the
    // wait behind a /fleet.json rebuild was exactly the bug), not just the
    // work done once inside.
    int64_t hb_t0 = now_us_steady();
    {
      std::lock_guard<std::mutex> lk(mu_);
      const std::string replica_id = req.get("replica_id").as_str();
      // Managers stamp the max quorum epoch they have accepted into every
      // heartbeat: this is how a standby (or a resurrected stale primary)
      // learns the fleet's current owner without any lighthouse-to-
      // lighthouse channel. An active instance seeing a higher epoch has
      // been superseded by a takeover — it fences itself out (demotes to
      // standby) instead of competing for the fleet.
      int64_t hb_epoch = req.get("epoch").as_int(0);
      if (hb_epoch > observed_epoch_) observed_epoch_ = hb_epoch;
      // Max accepted quorum_id rides the same frames: a standby resumes
      // numbering above it on takeover (strict monotonicity across
      // failover, where no disk snapshot is available to restore from).
      int64_t hb_qid = req.get("quorum_id").as_int(0);
      if (hb_qid > observed_quorum_id_) observed_quorum_id_ = hb_qid;
      if (active_ && observed_epoch_ > epoch_) {
        active_ = false;
        demotions_ += 1;
        last_reason_ = "fenced: observed epoch " +
                       std::to_string(observed_epoch_) + " > own epoch " +
                       std::to_string(epoch_);
        fprintf(stderr,
                "[lighthouse] demoting to standby: fleet is on epoch %lld, "
                "ours is %lld (stale primary fenced out)\n",
                static_cast<long long>(observed_epoch_),
                static_cast<long long>(epoch_));
      }
      // A drained replica's manager may have one heartbeat in flight when
      // its leave lands; the tombstone keeps it from resurrecting the entry
      // (which would stall the survivors' next quorum until heartbeat
      // expiry).
      if (!state_.left.count(replica_id)) {
        int64_t now = now_ms();
        state_.heartbeats[replica_id] = now;
        // Heartbeats carry the manager address so drain_all can reach a
        // replica that heartbeats but never registered a quorum.
        const std::string addr = req.get("address").as_str();
        if (!addr.empty()) state_.heartbeat_addrs[replica_id] = addr;
        // Live fleet plane: fold the optional digest + declared cadence into
        // the fleet table and run the digest-driven anomaly rules. Old
        // clients send neither field; the row simply stays digest-less.
        fleet_note_heartbeat(replica_id, req, now);
      }
    }
    resp["ok"] = Json::of(true);
    hist_heartbeat_.observe_us(now_us_steady() - hb_t0);
    return resp;
  }
  if (type == "fleet") {
    // Served from the generation-tagged cached snapshot — the framed twin
    // of GET /fleet.json no longer rebuilds O(N) JSON under mu_.
    auto snap = fleet_snapshot(now_ms());
    resp["ok"] = Json::of(true);
    resp["fleet"] = snap->json;
    return resp;
  }
  if (type == "leave") {
    // Graceful drain (no reference analog; the reference only has Kill →
    // exit(1), so survivors always pay the heartbeat-expiry stall). Removing
    // the member's heartbeat + registration lets the very next tick form the
    // shrunken quorum: ~quorum_tick_ms of stall instead of
    // ~heartbeat_timeout_ms.
    const std::string replica_id = req.get("replica_id").as_str();
    {
      std::lock_guard<std::mutex> lk(mu_);
      state_.heartbeats.erase(replica_id);
      state_.heartbeat_addrs.erase(replica_id);
      state_.participants.erase(replica_id);
      state_.left.insert(replica_id);
      // A drained replica must not linger in the fleet table looking like
      // a straggler whose heartbeats stopped.
      fleet_erase(replica_id);
    }
    fprintf(stderr, "[lighthouse] replica %s left gracefully\n",
            replica_id.c_str());
    // Proactive tick: survivors already blocked in a quorum RPC see the
    // shrunken membership now, not at the next timer tick.
    tick();
    resp["ok"] = Json::of(true);
    return resp;
  }
  if (type == "quorum") {
    return quorum_rpc(req, deadline_ms);
  }
  if (type == "status") {
    resp["ok"] = Json::of(true);
    resp["status"] = status_json();
    return resp;
  }
  if (type == "kill" || type == "drain") {
    // Forward to the member's manager address (kill: lighthouse.rs:454-479;
    // drain: no reference analog — asks the trainer to leave gracefully at
    // its next step boundary instead of exit(1)).
    std::string replica_id = req.get("replica_id").as_str();
    std::string addr;
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (state_.prev_quorum) {
        for (const auto& m : state_.prev_quorum->participants)
          if (m.replica_id == replica_id) addr = m.address;
      }
      for (const auto& kv : state_.participants)
        if (kv.first == replica_id) addr = kv.second.first.address;
    }
    if (addr.empty()) {
      resp["ok"] = Json::of(false);
      resp["error"] = Json::of("unknown replica " + replica_id);
      return resp;
    }
    Json fwd = Json::object();
    if (type == "kill") {
      fwd["type"] = Json::of("kill");
      fwd["msg"] = Json::of("killed via lighthouse");
    } else {
      fwd["type"] = Json::of("request_drain");
    }
    Json ignored;
    bool ok = call_json_addr(addr, fwd, &ignored, 5000);
    // A kill victim exits without replying; treat connection-level failure
    // after send as success-ish.
    resp["ok"] = Json::of(true);
    resp["sent"] = Json::of(ok);
    return resp;
  }
  if (type == "drain_all") {
    // Operator-initiated FULL-job drain: forward request_drain to every
    // registered member's manager. Each trainer drains at its own safe
    // boundary (with --durable-dir that includes a final durable
    // snapshot), so the whole job can be stopped cleanly and relaunched
    // later — the operator-triggered twin of a whole-pod preemption.
    // No reference analog (the reference's only job-wide stop is
    // killing each replica). The flag rides the next quorum response
    // per member (manager_server.cc request_drain), so for sync-quorum
    // trainers every group learns it at the SAME sync — no group can
    // drain a boundary ahead and strand the others' quorum.
    // Union of the last formed quorum and any currently-registering
    // members (same lookup the single-replica drain uses: registration
    // empties into prev_quorum when a quorum forms, and a drain must
    // reach members in either place). Live registrations overwrite
    // stale prev_quorum addresses; tombstoned (already-left) members
    // are excluded.
    std::map<std::string, std::string> members;
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (state_.prev_quorum) {
        for (const auto& m : state_.prev_quorum->participants)
          if (!state_.left.count(m.replica_id))
            members[m.replica_id] = m.address;
      }
      for (const auto& kv : state_.participants)
        members[kv.first] = kv.second.first.address;
      // Heartbeat-only replicas (heartbeating but never registered a
      // quorum) were a drain_all blind spot: they appear in neither
      // prev_quorum nor participants. Their heartbeat-carried addresses
      // close it; registered addresses win when both exist.
      for (const auto& kv : state_.heartbeat_addrs)
        if (!members.count(kv.first) && !state_.left.count(kv.first))
          members[kv.first] = kv.second;
    }
    Json sent = Json::object();
    int n_sent = 0;
    for (const auto& m : members) {
      Json fwd = Json::object();
      fwd["type"] = Json::of("request_drain");
      Json ignored;
      // Bound each forward by the request's remaining deadline (capped
      // at 5 s): a job with several unreachable members (stale
      // prev_quorum addresses after crashes — exactly when an operator
      // reaches for drain ALL) must still return the per-member send
      // report to the caller instead of timing out the whole RPC.
      int64_t remaining = deadline_ms - now_ms();
      if (remaining < 200) {
        sent[m.first] = Json::of(false);
        continue;
      }
      int64_t budget = remaining < 5000 ? remaining : 5000;
      bool ok = call_json_addr(m.second, fwd, &ignored,
                               static_cast<int>(budget));
      sent[m.first] = Json::of(ok);
      if (ok) n_sent++;
    }
    resp["ok"] = Json::of(true);
    resp["sent"] = sent;
    resp["n_sent"] = Json::of(static_cast<int64_t>(n_sent));
    resp["n_members"] = Json::of(static_cast<int64_t>(members.size()));
    return resp;
  }
  resp["ok"] = Json::of(false);
  resp["error"] = Json::of("unknown request type '" + type + "'");
  return resp;
}

Json Lighthouse::quorum_rpc(const Json& req, int64_t deadline_ms) {
  QuorumMember me = QuorumMember::from_json(req.get("requester"));
  Json resp = Json::object();
  if (me.replica_id.empty()) {
    resp["ok"] = Json::of(false);
    resp["error"] = Json::of("quorum request missing requester.replica_id");
    return resp;
  }
  const bool debug = std::getenv("TORCHFT_LH_DEBUG") != nullptr;
  std::unique_lock<std::mutex> lk(mu_);
  // Warm-standby takeover: managers only send quorum RPCs to their active
  // target, so a quorum request arriving at a standby means the fleet's
  // lease on the old primary lapsed and failover chose us. Claim the reign
  // with a strictly higher epoch than anything observed (fencing out the
  // old primary) and persist it before serving a single quorum.
  if (!active_) {
    epoch_ = std::max(epoch_, observed_epoch_) + 1;
    // Resume quorum numbering above anything the fleet accepted from the
    // old primary: each quorum_id must have exactly one (epoch) owner.
    state_.quorum_id = std::max(state_.quorum_id, observed_quorum_id_);
    active_ = true;
    takeovers_ += 1;
    persist_locked();
    fprintf(stderr,
            "[lighthouse] standby takeover: now active with epoch %lld "
            "(first quorum request from %s)\n",
            static_cast<long long>(epoch_), me.replica_id.c_str());
  }
  // Joining is an implicit heartbeat (lighthouse.rs:502-512) and clears any
  // graceful-leave tombstone (a drained replica relaunching to rejoin).
  state_.left.erase(me.replica_id);
  state_.heartbeats[me.replica_id] = now_ms();
  state_.participants[me.replica_id] = {me, now_ms()};
  int64_t my_gen = quorum_gen_;
  if (debug) {
    fprintf(stderr, "[lighthouse] +%lld register %s step=%lld gen=%lld pool=%zu\n",
            static_cast<long long>(now_ms() % 1000000),
            me.replica_id.c_str(), static_cast<long long>(me.step),
            static_cast<long long>(my_gen), state_.participants.size());
  }
  lk.unlock();
  // Proactive tick so a completing quorum doesn't wait for the next timer
  // tick (lighthouse.rs:516-518).
  tick();
  lk.lock();

  while (running_) {
    // Wait for a fresh quorum broadcast.
    while (running_ && quorum_gen_ == my_gen) {
      if (cv_.wait_until(lk, std::chrono::system_clock::time_point(
                                 std::chrono::milliseconds(deadline_ms))) ==
          std::cv_status::timeout) {
        if (now_ms() >= deadline_ms) {
          resp["ok"] = Json::of(false);
          resp["error"] = Json::of("timed out waiting for quorum");
          resp["timeout"] = Json::of(true);
          return resp;
        }
      }
    }
    if (!running_) break;
    my_gen = quorum_gen_;
    if (last_quorum_) {
      bool in_quorum = false;
      for (const auto& m : last_quorum_->participants)
        if (m.replica_id == me.replica_id) in_quorum = true;
      if (in_quorum) {
        resp["ok"] = Json::of(true);
        resp["quorum"] = last_quorum_->to_json();
        return resp;
      }
      // Delivered quorum doesn't include us (we joined too late): rejoin and
      // wait for the next one (lighthouse.rs:523-544).
      state_.left.erase(me.replica_id);
      state_.heartbeats[me.replica_id] = now_ms();
      state_.participants[me.replica_id] = {me, now_ms()};
    }
  }
  resp["ok"] = Json::of(false);
  resp["error"] = Json::of("lighthouse shutting down");
  return resp;
}

Json Lighthouse::status_json() {
  std::lock_guard<std::mutex> lk(mu_);
  Json s = Json::object();
  s["quorum_id"] = Json::of(state_.quorum_id);
  s["quorum_generation"] = Json::of(quorum_gen_);
  s["joins_total"] = Json::of(joins_total_);
  s["leaves_total"] = Json::of(leaves_total_);
  s["epoch"] = Json::of(epoch_);
  s["observed_epoch"] = Json::of(observed_epoch_);
  s["observed_quorum_id"] = Json::of(observed_quorum_id_);
  s["role"] = Json::of(std::string(active_ ? "active" : "standby"));
  s["takeovers"] = Json::of(takeovers_);
  s["demotions"] = Json::of(demotions_);
  int64_t now = now_ms();
  Json hb = Json::object();
  for (const auto& kv : state_.heartbeats)
    hb[kv.first] = Json::of(now - kv.second);
  s["heartbeat_ages_ms"] = hb;
  Json parts = Json::array();
  for (const auto& kv : state_.participants)
    parts.push(kv.second.first.to_json());
  s["participants"] = parts;
  s["prev_quorum"] =
      state_.prev_quorum ? state_.prev_quorum->to_json() : Json::null();
  Json left = Json::array();
  for (const auto& id : state_.left) left.push(Json::of(id));
  s["left"] = left;
  s["reason"] = Json::of(last_reason_);
  // Live-plane summary rides along so a status poller sees fleet health
  // without a second RPC; the full table stays on /fleet.json.
  s["fleet"] = fleet_summary_locked(now);
  // Hot-path latency histograms (p50/p95/p99 in microseconds, upper-bound
  // estimates from the log buckets — same semantics as telemetry
  // span_percentiles on the Python side).
  s["hist"] = hist_json();
  return s;
}

Json Lighthouse::hist_json() const {
  struct Named {
    const char* name;
    const LatencyHist* h;
  };
  const Named hists[] = {
      {"heartbeat", &hist_heartbeat_},   {"quorum_compute", &hist_quorum_},
      {"anomaly_eval", &hist_anomaly_},  {"http", &hist_http_},
      {"fleet_snapshot", &hist_snapshot_},
  };
  Json out = Json::object();
  for (const auto& nh : hists) {
    LatencyHist::Snap s = nh.h->snapshot();
    Json h = Json::object();
    h["count"] = Json::of(s.count);
    h["sum_us"] = Json::of(s.sum_us);
    h["p50_us"] = Json::of(LatencyHist::percentile_us(s, 0.50));
    h["p95_us"] = Json::of(LatencyHist::percentile_us(s, 0.95));
    h["p99_us"] = Json::of(LatencyHist::percentile_us(s, 0.99));
    out[nh.name] = h;
  }
  return out;
}

// ---------------------------------------------------------------------------
// Live fleet health plane
// ---------------------------------------------------------------------------

namespace {
constexpr size_t kFleetAnomalyRing = 64;     // rise-edge records kept
constexpr int64_t kFleetStickyMs = 10000;    // straggler display hold
constexpr int64_t kFleetCommitStall = 3;     // cf streak that flags
constexpr double kFleetSlowRateFrac = 0.5;   // rate < frac*median flags
constexpr int64_t kFleetStepLag = 2;         // step < median-lag flags
constexpr int64_t kFleetJitterMult = 8;      // budget = mult * cadence
constexpr int64_t kFleetJitterFloorMs = 1000;
constexpr int64_t kFleetEwmaWarmup = 5;      // gaps before EWMA budget counts
// (The old full-sort fleet_median lived here; the MedianTracker members in
// lighthouse.hpp maintain the identical upper median incrementally.)
}  // namespace

int64_t Lighthouse::fleet_jitter_budget_ms(const FleetEntry& e) const {
  // Deterministic when the sender declared its cadence; EWMA of observed
  // inter-arrival gaps as the old-client fallback. The floor absorbs GC /
  // scheduler hiccups that are noise at any cadence.
  int64_t base = e.hb_interval_ms > 0
                     ? e.hb_interval_ms * kFleetJitterMult
                     : static_cast<int64_t>(e.hb_gap_ewma_ms) * kFleetJitterMult;
  return base < kFleetJitterFloorMs ? kFleetJitterFloorMs : base;
}

void Lighthouse::fleet_set_flag(const std::string& replica_id, FleetEntry& e,
                                const std::string& kind, int64_t now,
                                Json detail) {
  e.straggler_until_ms = now + kFleetStickyMs;
  fleet_gen_ += 1;  // sticky-window extension alone changes the table view
  if (e.flags.count(kind)) return;  // only the RISE edge is an anomaly
  if (e.flags.empty()) flagged_ += 1;
  e.flags.insert(kind);
  anomaly_seq_ += 1;
  Json a = Json::object();
  a["seq"] = Json::of(anomaly_seq_);
  a["ts_ms"] = Json::of(now);
  a["replica_id"] = Json::of(replica_id);
  a["kind"] = Json::of(kind);
  a["detail"] = detail;
  anomalies_.push_back(a);
  while (anomalies_.size() > kFleetAnomalyRing) {
    // At fleet scale the ring overflows routinely; a silent pop would make
    // the anomaly feed look complete when it is not. The drop count rides
    // /fleet.json + /metrics, and obs_export journals the rise edge.
    anomalies_.pop_front();
    anomalies_dropped_ += 1;
  }
  fprintf(stderr, "[lighthouse] anomaly #%lld: %s on %s %s\n",
          static_cast<long long>(anomaly_seq_), kind.c_str(),
          replica_id.c_str(), detail.dump().c_str());
}

void Lighthouse::fleet_clear_flag(FleetEntry& e, const std::string& kind) {
  if (e.flags.erase(kind) == 0) return;
  if (e.flags.empty()) flagged_ -= 1;
  fleet_gen_ += 1;
}

// Retire / fold one entry's digest contributions. Together these keep the
// running aggregates exactly equal to a full-table recompute: every digest
// row contributes its step and goodput, its rate only when > 0 (matching
// the old scan's filter), and its commit-failure streak to the max-tracker.
void Lighthouse::fleet_agg_remove(const FleetEntry& e) {
  if (!e.has_digest) return;
  double r = e.digest.get("rate").as_double(0.0);
  if (r > 0.0) agg_rates_.erase(r);
  agg_steps_.erase(static_cast<double>(e.digest.get("step").as_int(0)));
  agg_gps_.erase(e.digest.get("gp").as_double(0.0));
  auto it = agg_cfs_.find(e.digest.get("cf").as_int(0));
  if (it != agg_cfs_.end()) agg_cfs_.erase(it);
  n_digest_ -= 1;
}

void Lighthouse::fleet_agg_insert(const FleetEntry& e) {
  if (!e.has_digest) return;
  double r = e.digest.get("rate").as_double(0.0);
  if (r > 0.0) agg_rates_.insert(r);
  agg_steps_.insert(static_cast<double>(e.digest.get("step").as_int(0)));
  agg_gps_.insert(e.digest.get("gp").as_double(0.0));
  agg_cfs_.insert(e.digest.get("cf").as_int(0));
  n_digest_ += 1;
}

void Lighthouse::fleet_erase(const std::string& replica_id) {
  auto it = fleet_.find(replica_id);
  if (it == fleet_.end()) return;
  fleet_agg_remove(it->second);
  if (!it->second.flags.empty()) flagged_ -= 1;
  fleet_.erase(it);
  fleet_gen_ += 1;
}

void Lighthouse::fleet_note_heartbeat(const std::string& replica_id,
                                      const Json& req, int64_t now) {
  FleetEntry& e = fleet_[replica_id];
  if (e.hb_count > 0) {
    int64_t gap = now - e.last_hb_ms;
    // Judge the gap against the budget BEFORE folding it into the EWMA —
    // a jittered gap must not raise its own threshold.
    bool budget_valid =
        e.hb_interval_ms > 0 || e.hb_count >= kFleetEwmaWarmup;
    if (budget_valid && gap > fleet_jitter_budget_ms(e)) {
      Json d = Json::object();
      d["gap_ms"] = Json::of(gap);
      d["budget_ms"] = Json::of(fleet_jitter_budget_ms(e));
      fleet_set_flag(replica_id, e, "hb_jitter", now, d);
      e.last_jitter_ms = now;
    }
    e.hb_gap_ewma_ms = e.hb_gap_ewma_ms == 0.0
                           ? static_cast<double>(gap)
                           : 0.8 * e.hb_gap_ewma_ms + 0.2 * gap;
  }
  e.last_hb_ms = now;
  e.hb_count += 1;
  fleet_gen_ += 1;
  int64_t declared = req.get("hb_interval_ms").as_int(0);
  if (declared > 0) e.hb_interval_ms = declared;
  if (!req.has("digest") || !req.get("digest").is_object()) return;

  // Digest-driven rules run at ARRIVAL, against the fleet table as of this
  // heartbeat: given the same global digest sequence the flag/anomaly
  // sequence is identical, so a chaos replay reproduces its alerts.
  // Bounded-cost contract: everything below is O(log N) — the medians the
  // rules compare against come from the running trackers, never from a
  // full-table rescan (tests/test_fleet.py pins tracker == recompute).
  int64_t an_t0 = now_us_steady();
  fleet_agg_remove(e);  // retire the previous digest's contributions
  e.digest = req.get("digest");
  e.has_digest = true;
  e.digest_ms = now;
  fleet_agg_insert(e);

  int64_t cf = e.digest.get("cf").as_int(0);
  if (cf >= kFleetCommitStall) {
    Json d = Json::object();
    d["cf"] = Json::of(cf);
    fleet_set_flag(replica_id, e, "commit_stall", now, d);
  } else {
    fleet_clear_flag(e, "commit_stall");
  }

  double own_rate = e.digest.get("rate").as_double(0.0);
  if (agg_rates_.size() >= 2) {
    double med = agg_rates_.median();
    if (own_rate < kFleetSlowRateFrac * med) {
      Json d = Json::object();
      d["rate"] = Json::of(own_rate);
      d["median_rate"] = Json::of(med);
      fleet_set_flag(replica_id, e, "slow_rate", now, d);
    } else {
      fleet_clear_flag(e, "slow_rate");
    }
  }
  int64_t own_step = e.digest.get("step").as_int(0);
  if (agg_steps_.size() >= 2) {
    int64_t med = static_cast<int64_t>(agg_steps_.median());
    if (own_step < med - kFleetStepLag) {
      Json d = Json::object();
      d["step"] = Json::of(own_step);
      d["median_step"] = Json::of(med);
      fleet_set_flag(replica_id, e, "step_lag", now, d);
    } else {
      fleet_clear_flag(e, "step_lag");
    }
  }
  hist_anomaly_.observe_us(now_us_steady() - an_t0);
}

void Lighthouse::fleet_scan_locked(int64_t now) {
  // Time-based rules only: an OPEN heartbeat gap (the replica is wedged
  // RIGHT NOW — arrival-side checks can't see it because nothing arrives)
  // plus expiry of a jitter flag whose evidence has aged out.
  for (auto& kv : fleet_) {
    FleetEntry& e = kv.second;
    bool budget_valid =
        e.hb_interval_ms > 0 || e.hb_count >= kFleetEwmaWarmup;
    int64_t open_gap = now - e.last_hb_ms;
    if (budget_valid && open_gap > fleet_jitter_budget_ms(e)) {
      Json d = Json::object();
      d["gap_ms"] = Json::of(open_gap);
      d["budget_ms"] = Json::of(fleet_jitter_budget_ms(e));
      d["open"] = Json::of(true);
      fleet_set_flag(kv.first, e, "hb_jitter", now, d);
      e.last_jitter_ms = now;
    } else if (e.flags.count("hb_jitter") &&
               now - e.last_jitter_ms > kFleetStickyMs) {
      fleet_clear_flag(e, "hb_jitter");
    }
  }
}

// Aggregate dict straight from the running trackers — O(1) medians/max plus
// one allocation-free pass for the time-dependent straggler count. This is
// the "agg" the property tests compare against a full recompute from the
// row dicts in the same payload.
Json Lighthouse::fleet_agg_locked(int64_t now) {
  int64_t n_straggler = 0;
  for (const auto& kv : fleet_)
    if (!kv.second.flags.empty() || now < kv.second.straggler_until_ms)
      n_straggler += 1;
  Json agg = Json::object();
  agg["n"] = Json::of(static_cast<int64_t>(fleet_.size()));
  agg["n_digest"] = Json::of(n_digest_);
  agg["stragglers"] = Json::of(n_straggler);
  agg["median_rate"] = agg_rates_.size() == 0
                           ? Json::null()
                           : Json::of(agg_rates_.median());
  agg["median_step"] =
      agg_steps_.size() == 0
          ? Json::null()
          : Json::of(static_cast<int64_t>(agg_steps_.median()));
  agg["median_goodput"] =
      agg_gps_.size() == 0 ? Json::null() : Json::of(agg_gps_.median());
  agg["max_commit_failures"] =
      Json::of(agg_cfs_.empty() ? int64_t{0} : *agg_cfs_.rbegin());
  agg["anomalies_dropped"] = Json::of(anomalies_dropped_);
  // Elastic-membership view: current quorum size plus cumulative
  // join/leave churn, so obs_top's WORLD column tracks capacity changes
  // (deliberate scale-up/down AND crash churn) from the same counters
  // /metrics exports.
  agg["quorum_world"] = Json::of(
      last_quorum_ ? static_cast<int64_t>(last_quorum_->participants.size())
                   : int64_t{0});
  agg["joins_total"] = Json::of(joins_total_);
  agg["leaves_total"] = Json::of(leaves_total_);
  // Control-plane ownership view: the fencing epoch this instance stamps on
  // quorums (obs_top's EPOCH column). A jump means a standby takeover; a
  // reader comparing two lighthouses can tell owner from fenced stale
  // primary by it.
  agg["epoch"] = Json::of(epoch_);
  return agg;
}

std::shared_ptr<const Lighthouse::FleetSnapshot> Lighthouse::fleet_snapshot(
    int64_t now) {
  // Bounded staleness: any cached payload younger than fleet_snap_ms is
  // served as-is (fleet_snap_ms == 0 disables caching — the "before" mode
  // the fleet_load harness benchmarks against).
  if (opts_.fleet_snap_ms > 0) {
    std::lock_guard<std::mutex> lk(snap_mu_);
    if (snap_ && now >= snap_->built_ms &&
        now - snap_->built_ms <= opts_.fleet_snap_ms)
      return snap_;
  }
  // Single-flight rebuild: concurrent readers that all see a stale (or
  // absent) snapshot would otherwise each pay the O(N) rebuild at once —
  // a thundering herd that turns the cache off exactly when load peaks.
  // One caller rebuilds; the rest block here, then re-check and serve the
  // winner's result.
  std::lock_guard<std::mutex> rebuild_lk(rebuild_mu_);
  if (opts_.fleet_snap_ms > 0) {
    std::lock_guard<std::mutex> lk(snap_mu_);
    if (snap_ && now >= snap_->built_ms &&
        now - snap_->built_ms <= opts_.fleet_snap_ms)
      return snap_;
  }
  int64_t t0 = now_us_steady();
  // Copy raw state under the hot lock; build + dump the JSON off it. The
  // copy is the cheap part (row structs + small digest dicts); the O(N)
  // string formatting that used to stall heartbeats happens unlocked.
  std::vector<std::pair<std::string, FleetEntry>> rows;
  std::deque<Json> anomalies;
  Json agg;
  int64_t gen, aseq;
  {
    std::lock_guard<std::mutex> lk(mu_);
    rows.assign(fleet_.begin(), fleet_.end());
    anomalies = anomalies_;
    agg = fleet_agg_locked(now);
    gen = fleet_gen_;
    aseq = anomaly_seq_;
  }
  auto snap = std::make_shared<FleetSnapshot>();
  snap->gen = gen;
  snap->built_ms = now;
  Json f = Json::object();
  f["ts_ms"] = Json::of(now);
  f["gen"] = Json::of(gen);
  f["snap_ms"] = Json::of(opts_.fleet_snap_ms);
  Json reps = Json::object();
  for (const auto& kv : rows) {
    const FleetEntry& e = kv.second;
    Json r = Json::object();
    r["last_hb_age_ms"] = Json::of(now - e.last_hb_ms);
    r["hb_interval_ms"] = Json::of(e.hb_interval_ms);
    // Old client (no digest ever): fields render as null, row stays —
    // the forward-compat contract the tests pin.
    r["digest"] = e.has_digest ? e.digest : Json::null();
    r["digest_age_ms"] =
        e.has_digest ? Json::of(now - e.digest_ms) : Json::null();
    Json fl = Json::array();
    for (const auto& k : e.flags) fl.push(Json::of(k));
    if (now - e.last_hb_ms > opts_.heartbeat_timeout_ms)
      fl.push(Json::of("stale"));  // view-only: presence, not an anomaly
    r["flags"] = fl;
    r["straggler"] =
        Json::of(!e.flags.empty() || now < e.straggler_until_ms);
    reps[kv.first] = r;
  }
  f["replicas"] = reps;
  f["agg"] = agg;
  Json an = Json::array();
  for (const auto& a : anomalies) an.push(a);
  f["anomalies"] = an;
  f["anomaly_seq"] = Json::of(aseq);
  snap->json = f;
  snap->body = f.dump();
  hist_snapshot_.observe_us(now_us_steady() - t0);
  std::lock_guard<std::mutex> lk(snap_mu_);
  snap_ = snap;
  return snap_;
}

Json Lighthouse::fleet_summary_locked(int64_t now) {
  Json s = fleet_agg_locked(now);
  s["anomaly_seq"] = Json::of(anomaly_seq_);
  s["gen"] = Json::of(fleet_gen_);
  return s;
}

std::string Lighthouse::render_status_html() {
  Json s = status_json();
  std::ostringstream html;
  html << "<!doctype html><html><head><title>torchft-tpu lighthouse</title>"
       << "<style>body{font-family:monospace;margin:2em}table{border-collapse:"
          "collapse}td,th{border:1px solid #999;padding:4px 8px}</style>"
       << "</head><body><h1>torchft-tpu lighthouse</h1>"
       << "<p>quorum_id: " << s.get("quorum_id").as_int() << "</p>";
  html << "<h2>heartbeats</h2><table><tr><th>replica</th><th>age (ms)</th>"
       << "<th></th></tr>";
  for (const auto& kv : s.get("heartbeat_ages_ms").obj) {
    html << "<tr><td>" << kv.first << "</td><td>" << kv.second.as_int()
         << "</td><td><form method=post action=\"/replica/" << kv.first
         << "/kill\" style=\"display:inline\"><button>kill</button></form> "
         << "<form method=post action=\"/replica/" << kv.first
         << "/drain\" style=\"display:inline\"><button>drain</button></form>"
         << "</td></tr>";
  }
  html << "</table><p><form method=post action=\"/drain_all\" "
          "style=\"display:inline\"><button>drain ALL (stop job "
          "cleanly)</button></form></p>";
  html << "<h2>previous quorum</h2><table><tr><th>replica</th>"
       << "<th>address</th><th>step</th><th>world</th></tr>";
  if (s.get("prev_quorum").is_object()) {
    for (const auto& p : s.get("prev_quorum").get("participants").arr) {
      html << "<tr><td>" << p.get("replica_id").as_str() << "</td><td>"
           << p.get("address").as_str() << "</td><td>"
           << p.get("step").as_int() << "</td><td>"
           << p.get("world_size").as_int() << "</td></tr>";
    }
  }
  html << "</table>";
  if (!s.get("reason").as_str().empty())
    html << "<p>waiting: " << s.get("reason").as_str() << "</p>";
  html << "</body></html>";
  return html.str();
}

static std::string prom_escape(const std::string& s) {
  // Prometheus label values must escape backslash, double-quote, and
  // newline — replica ids are client-supplied strings.
  std::string out;
  out.reserve(s.size());
  for (char c : s) {
    if (c == '\\') out += "\\\\";
    else if (c == '"') out += "\\\"";
    else if (c == '\n') out += "\\n";
    else out += c;
  }
  return out;
}

std::string Lighthouse::render_metrics() {
  // Prometheus text exposition (the reference lighthouse has only an HTML
  // dashboard; a scrapeable endpoint is what production monitoring needs).
  // Scalars and minimal per-replica tuples are copied under mu_; all string
  // formatting happens off the hot lock, so a scrape never stalls the
  // heartbeat path behind O(N) text building.
  struct FleetRow {
    std::string id;
    bool straggler = false;
    bool has_rate = false;
    double rate = 0.0;
  };
  int64_t now, quorum_id, quorum_gen, joins, leaves, aseq, adropped, gen;
  int64_t epoch, takeovers, demotions;
  bool is_active;
  size_t n_participants, n_members;
  std::vector<std::pair<std::string, int64_t>> hb_ages;
  std::vector<std::pair<std::string, int64_t>> member_steps;
  std::vector<FleetRow> rows;
  int64_t n_straggler = 0;
  bool have_median = false;
  double median_rate = 0.0;
  {
    std::lock_guard<std::mutex> lk(mu_);
    now = now_ms();
    quorum_id = state_.quorum_id;
    quorum_gen = quorum_gen_;
    joins = joins_total_;
    leaves = leaves_total_;
    epoch = epoch_;
    takeovers = takeovers_;
    demotions = demotions_;
    is_active = active_;
    aseq = anomaly_seq_;
    adropped = anomalies_dropped_;
    gen = fleet_gen_;
    n_participants = state_.participants.size();
    n_members =
        state_.prev_quorum ? state_.prev_quorum->participants.size() : 0;
    hb_ages.reserve(state_.heartbeats.size());
    for (const auto& kv : state_.heartbeats)
      hb_ages.emplace_back(kv.first, now - kv.second);
    if (state_.prev_quorum)
      for (const auto& mem : state_.prev_quorum->participants)
        member_steps.emplace_back(mem.replica_id, mem.step);
    rows.reserve(fleet_.size());
    for (const auto& kv : fleet_) {
      FleetRow r;
      r.id = kv.first;
      r.straggler =
          !kv.second.flags.empty() || now < kv.second.straggler_until_ms;
      if (r.straggler) n_straggler += 1;
      if (kv.second.has_digest) {
        r.rate = kv.second.digest.get("rate").as_double(0.0);
        r.has_rate = true;
      }
      rows.push_back(std::move(r));
    }
    if (agg_rates_.size() > 0) {
      have_median = true;
      median_rate = agg_rates_.median();
    }
  }
  // Label-cardinality bound (TORCHFT_EXPORT_MAX_REPLICAS, shared with
  // obs_export): above the cap, per-replica series are emitted only for
  // anomalous/straggler replicas; healthy rows collapse into the aggregate
  // gauges plus a suppressed-count so the scrape stays O(cap), not O(N).
  const size_t cap = static_cast<size_t>(export_max_replicas_);
  const bool capped = rows.size() > cap;
  int64_t suppressed = 0;
  std::ostringstream m;
  m << "# HELP torchft_lighthouse_quorum_id Current quorum id.\n"
    << "# TYPE torchft_lighthouse_quorum_id gauge\n"
    << "torchft_lighthouse_quorum_id " << quorum_id << "\n";
  m << "# HELP torchft_lighthouse_quorum_generation Quorum broadcasts since "
       "boot.\n"
    << "# TYPE torchft_lighthouse_quorum_generation counter\n"
    << "torchft_lighthouse_quorum_generation " << quorum_gen << "\n";
  m << "# HELP torchft_lighthouse_epoch Fencing epoch stamped on quorums.\n"
    << "# TYPE torchft_lighthouse_epoch gauge\n"
    << "torchft_lighthouse_epoch " << epoch << "\n";
  m << "# HELP torchft_lighthouse_active 1 when this instance owns the "
       "fleet (forms quorums); 0 when standby/fenced.\n"
    << "# TYPE torchft_lighthouse_active gauge\n"
    << "torchft_lighthouse_active " << (is_active ? 1 : 0) << "\n";
  m << "# HELP torchft_lighthouse_takeovers_total Standby->active "
       "transitions.\n"
    << "# TYPE torchft_lighthouse_takeovers_total counter\n"
    << "torchft_lighthouse_takeovers_total " << takeovers << "\n";
  m << "# HELP torchft_lighthouse_demotions_total Active->standby fences "
       "(superseded by a higher epoch).\n"
    << "# TYPE torchft_lighthouse_demotions_total counter\n"
    << "torchft_lighthouse_demotions_total " << demotions << "\n";
  m << "# HELP torchft_lighthouse_joins_total Members added across quorum "
       "transitions.\n"
    << "# TYPE torchft_lighthouse_joins_total counter\n"
    << "torchft_lighthouse_joins_total " << joins << "\n";
  m << "# HELP torchft_lighthouse_leaves_total Members gone across quorum "
       "transitions.\n"
    << "# TYPE torchft_lighthouse_leaves_total counter\n"
    << "torchft_lighthouse_leaves_total " << leaves << "\n";
  m << "# HELP torchft_lighthouse_participants Replicas currently waiting in "
       "the next quorum.\n"
    << "# TYPE torchft_lighthouse_participants gauge\n"
    << "torchft_lighthouse_participants " << n_participants << "\n";
  m << "# HELP torchft_lighthouse_quorum_members Members of the last "
       "delivered quorum.\n"
    << "# TYPE torchft_lighthouse_quorum_members gauge\n"
    << "torchft_lighthouse_quorum_members " << n_members << "\n";
  int64_t max_hb_age = 0;
  for (const auto& kv : hb_ages)
    if (kv.second > max_hb_age) max_hb_age = kv.second;
  m << "# HELP torchft_lighthouse_heartbeat_age_max_ms Oldest replica "
       "heartbeat age.\n"
    << "# TYPE torchft_lighthouse_heartbeat_age_max_ms gauge\n"
    << "torchft_lighthouse_heartbeat_age_max_ms " << max_hb_age << "\n";
  if (!capped) {
    m << "# HELP torchft_lighthouse_heartbeat_age_ms Milliseconds since "
         "each replica's last heartbeat.\n"
      << "# TYPE torchft_lighthouse_heartbeat_age_ms gauge\n";
    for (const auto& kv : hb_ages)
      m << "torchft_lighthouse_heartbeat_age_ms{replica=\""
        << prom_escape(kv.first) << "\"} " << kv.second << "\n";
  }
  if (!member_steps.empty() && !capped) {
    m << "# HELP torchft_lighthouse_member_step Training step each quorum "
         "member reported.\n"
      << "# TYPE torchft_lighthouse_member_step gauge\n";
    for (const auto& kv : member_steps)
      m << "torchft_lighthouse_member_step{replica=\""
        << prom_escape(kv.first) << "\"} " << kv.second << "\n";
  }
  // Live-plane alert gauges: straggler flags + the anomaly counter are
  // what a pager rule fires on; per-replica step rate + the fleet median
  // give the rule its denominator.
  m << "# HELP torchft_lighthouse_anomalies_total Anomaly rise-edges "
       "detected since boot.\n"
    << "# TYPE torchft_lighthouse_anomalies_total counter\n"
    << "torchft_lighthouse_anomalies_total " << aseq << "\n";
  m << "# HELP torchft_lighthouse_anomalies_dropped Anomaly records evicted "
       "from the bounded ring (feed incomplete when > 0).\n"
    << "# TYPE torchft_lighthouse_anomalies_dropped counter\n"
    << "torchft_lighthouse_anomalies_dropped " << adropped << "\n";
  m << "# HELP torchft_lighthouse_fleet_gen Fleet-table content generation "
       "(bumped on every mutation; tags /fleet.json snapshots).\n"
    << "# TYPE torchft_lighthouse_fleet_gen counter\n"
    << "torchft_lighthouse_fleet_gen " << gen << "\n";
  m << "# HELP torchft_lighthouse_fleet_replicas Replicas in the fleet "
       "table.\n"
    << "# TYPE torchft_lighthouse_fleet_replicas gauge\n"
    << "torchft_lighthouse_fleet_replicas " << rows.size() << "\n";
  m << "# HELP torchft_lighthouse_fleet_stragglers Replicas currently "
       "flagged or inside the sticky straggler window.\n"
    << "# TYPE torchft_lighthouse_fleet_stragglers gauge\n"
    << "torchft_lighthouse_fleet_stragglers " << n_straggler << "\n";
  if (!rows.empty()) {
    std::ostringstream strag, per_replica;
    for (const auto& r : rows) {
      if (capped && !r.straggler) {
        suppressed += 1;
        continue;
      }
      strag << "torchft_lighthouse_straggler{replica=\""
            << prom_escape(r.id) << "\"} " << (r.straggler ? 1 : 0) << "\n";
      if (r.has_rate)
        per_replica << "torchft_lighthouse_replica_step_rate{replica=\""
                    << prom_escape(r.id) << "\"} " << r.rate << "\n";
    }
    std::string st = strag.str();
    if (!st.empty()) {
      m << "# HELP torchft_lighthouse_straggler Replica currently flagged "
           "as a straggler (1) or healthy (0).\n"
        << "# TYPE torchft_lighthouse_straggler gauge\n"
        << st;
    }
    std::string per = per_replica.str();
    if (!per.empty()) {
      m << "# HELP torchft_lighthouse_replica_step_rate Committed steps "
           "per second each replica reported in its digest.\n"
        << "# TYPE torchft_lighthouse_replica_step_rate gauge\n"
        << per;
    }
    if (have_median) {
      m << "# HELP torchft_lighthouse_fleet_median_step_rate Fleet median "
           "of reported step rates.\n"
        << "# TYPE torchft_lighthouse_fleet_median_step_rate gauge\n"
        << "torchft_lighthouse_fleet_median_step_rate " << median_rate
        << "\n";
    }
  }
  m << "# HELP torchft_lighthouse_replicas_suppressed Healthy replicas "
       "whose per-replica series were collapsed into aggregates "
       "(TORCHFT_EXPORT_MAX_REPLICAS).\n"
    << "# TYPE torchft_lighthouse_replicas_suppressed gauge\n"
    << "torchft_lighthouse_replicas_suppressed " << suppressed << "\n";
  // Hot-path latency histograms: upper-bound percentile gauges per path
  // (log buckets, telemetry._hist_percentile semantics).
  struct Named {
    const char* name;
    const LatencyHist* h;
  };
  const Named hists[] = {
      {"heartbeat", &hist_heartbeat_},   {"quorum_compute", &hist_quorum_},
      {"anomaly_eval", &hist_anomaly_},  {"http", &hist_http_},
      {"fleet_snapshot", &hist_snapshot_},
  };
  m << "# HELP torchft_lighthouse_hotpath_p50_us Hot-path latency p50 "
       "(upper-bound log-bucket estimate, microseconds).\n"
    << "# TYPE torchft_lighthouse_hotpath_p50_us gauge\n"
    << "# HELP torchft_lighthouse_hotpath_p95_us Hot-path latency p95.\n"
    << "# TYPE torchft_lighthouse_hotpath_p95_us gauge\n"
    << "# HELP torchft_lighthouse_hotpath_count Hot-path samples observed.\n"
    << "# TYPE torchft_lighthouse_hotpath_count counter\n";
  for (const auto& nh : hists) {
    LatencyHist::Snap s = nh.h->snapshot();
    m << "torchft_lighthouse_hotpath_p50_us{path=\"" << nh.name << "\"} "
      << LatencyHist::percentile_us(s, 0.50) << "\n"
      << "torchft_lighthouse_hotpath_p95_us{path=\"" << nh.name << "\"} "
      << LatencyHist::percentile_us(s, 0.95) << "\n"
      << "torchft_lighthouse_hotpath_count{path=\"" << nh.name << "\"} "
      << s.count << "\n";
  }
  return m.str();
}

void Lighthouse::handle_http(int fd) {
  int64_t t0 = now_us_steady();
  std::string req = read_http_request(fd, 10000);
  std::string path = "/";
  std::string method;
  {
    size_t sp1 = req.find(' ');
    size_t sp2 = req.find(' ', sp1 + 1);
    if (sp1 != std::string::npos && sp2 != std::string::npos) {
      method = req.substr(0, sp1);
      path = req.substr(sp1 + 1, sp2 - sp1 - 1);
    }
  }
  // Side-effecting endpoints (kill / drain / drain_all) are POST-only:
  // a GET must never stop a replica — browsers prefetch URLs and
  // monitoring scrapers walk dashboard paths. The dashboard forms
  // declare method=post already.
  const bool side_effecting =
      path == "/drain_all" || path.rfind("/replica/", 0) == 0;
  if (side_effecting && method != "POST") {
    std::string body405 = "method not allowed (POST required)";
    std::ostringstream hdr;
    hdr << "HTTP/1.1 405 Method Not Allowed\r\nContent-Type: text/plain"
        << "\r\nAllow: POST\r\nContent-Length: " << body405.size()
        << "\r\nConnection: close\r\n\r\n";
    std::string out405 = hdr.str() + body405;
    write_all(fd, out405.data(), out405.size(), 10000);
    hist_http_.observe_us(now_us_steady() - t0);
    return;
  }
  std::string body;
  std::string ctype = "text/html";
  int code = 200;
  if (path == "/" || path == "/status") {
    body = render_status_html();
  } else if (path == "/status.json") {
    body = status_json().dump();
    ctype = "application/json";
  } else if (path == "/fleet.json") {
    // Pre-dumped cached snapshot: serving is a string copy, not an O(N)
    // JSON build under mu_ (the contention the fleet_load harness measures).
    body = fleet_snapshot(now_ms())->body;
    ctype = "application/json";
  } else if (path == "/metrics") {
    body = render_metrics();
    ctype = "text/plain; version=0.0.4";
  } else if (path.rfind("/replica/", 0) == 0 && path.size() > 14 &&
             (path.compare(path.size() - 5, 5, "/kill") == 0 ||
              path.compare(path.size() - 6, 6, "/drain") == 0)) {
    bool is_kill = path.compare(path.size() - 5, 5, "/kill") == 0;
    size_t suffix = is_kill ? 5 : 6;
    std::string replica_id = path.substr(9, path.size() - 9 - suffix);
    Json kreq = Json::object();
    kreq["type"] = Json::of(is_kill ? "kill" : "drain");
    kreq["replica_id"] = Json::of(replica_id);
    Json kresp = handle_request(kreq, now_ms() + 5000);
    body = kresp.dump();
    ctype = "application/json";
    if (!kresp.get("ok").as_bool()) code = 404;
  } else if (path == "/drain_all") {
    Json dreq = Json::object();
    dreq["type"] = Json::of("drain_all");
    Json dresp = handle_request(dreq, now_ms() + 15000);
    body = dresp.dump();
    ctype = "application/json";
  } else {
    code = 404;
    body = "not found";
    ctype = "text/plain";
  }
  std::ostringstream hdr;
  hdr << "HTTP/1.1 " << code << (code == 200 ? " OK" : " Not Found")
      << "\r\nContent-Type: " << ctype
      << "\r\nContent-Length: " << body.size()
      << "\r\nConnection: close\r\n\r\n";
  std::string out = hdr.str() + body;
  write_all(fd, out.data(), out.size(), 10000);
  hist_http_.observe_us(now_us_steady() - t0);
}

}  // namespace tft
