// TCP helpers for the torchft-tpu control plane: listen/connect with timeouts,
// length-prefixed JSON frames, and exponential-backoff connect retry.
//
// Capability parity with the reference's src/net.rs:10-36 (keep-alive connect
// with exponential backoff 100ms -> 10s x1.5) and src/retry.rs, minus gRPC:
// the wire format here is [u32 big-endian length][JSON payload].
#pragma once

#include <cstdint>
#include <functional>
#include <string>

#include "json.hpp"

namespace tft {

// Returns ms since epoch (steady for intervals where it matters we use the
// same clock consistently).
int64_t now_ms();

// Wall-clock nanoseconds (CLOCK_REALTIME), chosen over CLOCK_MONOTONIC so
// timestamps recorded in the data plane align with the Python journal's
// time.time() records for cross-plane trace assembly.
uint64_t now_realtime_ns();

// Count of MSG_DONTWAIT misses (EAGAIN -> poll waits) taken by the calling
// thread inside write_all/read_exact since thread start. Thread-local so a
// transfer job can delta it around one stripe without synchronization.
uint64_t net_spin_count();

// Starts a detached watchdog thread that _exit(2)s this process as soon as
// getppid() != parent_pid (poll every 500 ms). Used by the control-plane
// binaries (--parent-pid): a server orphaned by `kill -9` of its trainer
// would keep heartbeating and wedge the lighthouse's split-brain majority
// guard. Polling the ppid is immune to the PR_SET_PDEATHSIG pitfalls
// (fires on spawning-*thread* exit; exec-window race under subreapers) —
// if the parent died before this call, getppid() already differs and the
// first poll exits. `on_death` (optional) runs before the exit — the
// manager binary uses it to send a lighthouse leave on behalf of its dead
// trainer, cutting the survivors' stall from heartbeat expiry (~5 s) to
// one watchdog poll (~0.5 s).
void watch_parent(int64_t parent_pid, std::function<void()> on_death = nullptr);

// Sleep helper.
void sleep_ms(int64_t ms);

// Creates a listening socket bound to `host` (empty or "0.0.0.0" = any) and
// `port` (0 = ephemeral). Returns fd >= 0 or -1 on error (errno set).
int tcp_listen(const std::string& host, int port, int backlog = 128);

// Port a listening fd is bound to, or -1.
int bound_port(int fd);

// Accept with timeout. Returns client fd, -1 on timeout/error.
int tcp_accept(int listen_fd, int timeout_ms);

// Connect to host:port with a timeout. Returns fd or -1.
int tcp_connect(const std::string& host, int port, int64_t timeout_ms);

// Connect with exponential backoff retries until deadline, mirroring the
// reference's net.rs connect(): 100ms initial, x1.5, max 10s interval —
// with seeded full jitter on each sleep (chaos::backoff_unit) so mass
// reconnects after a partition heal don't stampede in lockstep.
// `attempt_ms` clamps each individual connect attempt (link-policy budget:
// WAN links legitimately need more than the old hardcoded 5000, local
// links much less).
int tcp_connect_retry(const std::string& host, int port, int64_t timeout_ms,
                      int64_t attempt_ms = 5000);

// Splits "host:port" (also accepts "[v6]:port"). Returns false on parse error.
bool split_host_port(const std::string& addr, std::string* host, int* port);

// Sends a length-prefixed frame. Returns false on error/timeout.
bool send_frame(int fd, const std::string& payload, int64_t timeout_ms);

// Receives a length-prefixed frame into *out. Returns false on error/timeout.
bool recv_frame(int fd, std::string* out, int64_t timeout_ms);

// Convenience: send `req` JSON, receive one JSON reply. False on any failure.
bool call_json(int fd, const Json& req, Json* resp, int64_t timeout_ms);

// One-shot: connect, call, close. False on any failure.
bool call_json_addr(const std::string& addr, const Json& req, Json* resp,
                    int64_t timeout_ms);

// Peeks at up to n bytes without consuming (for HTTP-vs-frame sniffing).
// Returns number of bytes peeked, or -1.
int peek_bytes(int fd, char* buf, int n, int timeout_ms);

// Reads until the socket closes or `max` bytes (for HTTP requests).
std::string read_http_request(int fd, int timeout_ms);

// Writes all bytes. Returns false on error.
bool write_all(int fd, const char* data, size_t len, int64_t timeout_ms);

// Reads exactly `len` bytes (raw, no framing). Returns false on
// error/timeout/peer close. The bulk-transfer twin of write_all, used by the
// collective engine for striped tensor payloads whose sizes both sides
// already know (no per-chunk frame header on the hot path).
bool read_exact(int fd, char* data, size_t len, int64_t timeout_ms);

}  // namespace tft
