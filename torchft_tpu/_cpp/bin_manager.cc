// Standalone per-replica-group manager server CLI. Spawned by the Python
// Manager on group rank 0 (the reference boots its Rust ManagerServer
// in-process via pyo3; we isolate it in a subprocess so a wedged trainer
// can't take the control plane down with it).
#include <signal.h>

#include <cstdio>
#include <cstdlib>
#include <string>

#include "chaos.hpp"
#include "manager_server.hpp"
#include "net.hpp"

static const char* kUsage =
    "usage: torchft_manager --replica-id ID --lighthouse HOST:PORT[,...]\n"
    "         --store-address HOST:PORT --world-size N\n"
    "         [--advertise-host H] [--bind-host H] [--port P]\n"
    "         [--heartbeat-interval-ms N] [--connect-timeout-ms N]\n"
    "         [--quorum-retries N] [--lh-lease-ms N] [--job NAME]\n"
    "         [--evidence-streak N]\n";

int main(int argc, char** argv) {
  tft::ManagerOpts opts;
  // Active-lighthouse lease before failing over down the --lighthouse list;
  // the flag wins over the env knob.
  const char* lease_env = std::getenv("TORCHFT_LH_LEASE_MS");
  if (lease_env != nullptr && *lease_env != '\0')
    opts.lighthouse_lease_ms = std::stoll(lease_env);
  // Job namespace this replica group belongs to (stamped on every frame to
  // the lighthouse); the flag wins over the env knob.
  const char* job_env = std::getenv("TORCHFT_JOB");
  if (job_env != nullptr && *job_env != '\0') opts.job = job_env;
  // Hard-evidence failover streak (0 = lease lapse only); flag wins.
  const char* es_env = std::getenv("TORCHFT_MGR_EVIDENCE_STREAK");
  if (es_env != nullptr && *es_env != '\0')
    opts.evidence_streak = std::stoll(es_env);
  int64_t parent_pid = 0;
  for (int i = 1; i < argc; i++) {
    std::string a = argv[i];
    auto next = [&]() -> std::string {
      if (i + 1 >= argc) {
        fprintf(stderr, "%s", kUsage);
        exit(2);
      }
      return argv[++i];
    };
    if (a == "--replica-id") {
      opts.replica_id = next();
    } else if (a == "--lighthouse") {
      opts.lighthouse_addr = next();
    } else if (a == "--advertise-host") {
      opts.advertise_host = next();
    } else if (a == "--bind-host") {
      opts.bind_host = next();
    } else if (a == "--port") {
      opts.port = std::stoi(next());
    } else if (a == "--store-address") {
      opts.store_address = next();
    } else if (a == "--world-size") {
      opts.world_size = std::stoll(next());
    } else if (a == "--heartbeat-interval-ms") {
      opts.heartbeat_interval_ms = std::stoll(next());
    } else if (a == "--connect-timeout-ms") {
      opts.connect_timeout_ms = std::stoll(next());
    } else if (a == "--quorum-retries") {
      opts.quorum_retries = std::stoll(next());
    } else if (a == "--lh-lease-ms") {
      opts.lighthouse_lease_ms = std::stoll(next());
    } else if (a == "--evidence-streak") {
      opts.evidence_streak = std::stoll(next());
    } else if (a == "--job") {
      opts.job = next();
    } else if (a == "--parent-pid") {
      parent_pid = std::stoll(next());
    } else {
      fprintf(stderr, "unknown flag '%s'\n%s", a.c_str(), kUsage);
      return 2;
    }
  }
  if (opts.replica_id.empty() || opts.lighthouse_addr.empty()) {
    fprintf(stderr, "--replica-id and --lighthouse are required\n%s", kUsage);
    return 2;
  }
  signal(SIGPIPE, SIG_IGN);
  // Seeded fault injection (TORCHFT_CHAOS, inherited from the spawning
  // trainer); off and free when the env var is unset.
  tft::chaos::init_from_env();
  tft::ManagerServer server(opts);
  if (!server.start()) {
    fprintf(stderr, "failed to bind manager server\n");
    return 1;
  }
  printf("LISTENING %d\n", server.port());
  fflush(stdout);
  if (parent_pid > 0) {
    // Armed after start() so the on-death hook has a live server. If the
    // trainer already died during startup, the first poll fires at once.
    // Leaving on the trainer's behalf cuts the survivors' stall for an
    // abrupt trainer death from heartbeat expiry (~5 s) to one watchdog
    // poll (~0.5 s); heartbeat expiry remains the backstop for
    // whole-machine loss, where nobody is left to send the leave.
    // Small budget: if the lighthouse is unreachable too (machine or
    // partition loss — where the leave is moot and heartbeat expiry is
    // the designed backstop), the orphan must still exit within ~1.5 s,
    // not hang out the full connect timeout holding its port.
    tft::watch_parent(parent_pid, [&server] {
      server.leave("trainer died", /*budget_ms=*/1500);
    });
  }
  while (true) tft::sleep_ms(1000);
  return 0;
}
