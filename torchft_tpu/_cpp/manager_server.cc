#include "manager_server.hpp"

#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <cstdio>
#include <cstdlib>

#include "chaos.hpp"
#include "net.hpp"

namespace tft {

ManagerServer::ManagerServer(ManagerOpts opts) : opts_(std::move(opts)) {
  if (opts_.bind_host.empty()) opts_.bind_host = "0.0.0.0";
  if (opts_.advertise_host.empty()) opts_.advertise_host = "127.0.0.1";
  // Parse the ordered lighthouse list once; the vector is read-only after
  // construction so both the heartbeat thread and quorum path can index it
  // with only the atomic active index.
  std::string rest = opts_.lighthouse_addr;
  while (!rest.empty()) {
    size_t comma = rest.find(',');
    std::string one = rest.substr(0, comma);
    size_t b = one.find_first_not_of(" \t");
    size_t e = one.find_last_not_of(" \t");
    if (b != std::string::npos) lh_addrs_.push_back(one.substr(b, e - b + 1));
    if (comma == std::string::npos) break;
    rest = rest.substr(comma + 1);
  }
  if (lh_addrs_.empty()) lh_addrs_.push_back(opts_.lighthouse_addr);
}

ManagerServer::~ManagerServer() { stop(); }

bool ManagerServer::start() {
  listen_fd_ = tcp_listen(opts_.bind_host, opts_.port);
  if (listen_fd_ < 0) return false;
  port_ = bound_port(listen_fd_);
  running_ = true;
  accept_thread_ = std::thread([this] { accept_loop(); });
  heartbeat_thread_ = std::thread([this] { heartbeat_loop(); });
  return true;
}

void ManagerServer::stop() {
  if (!running_.exchange(false)) return;
  cv_.notify_all();
  conns_.shutdown_all();  // interrupt in-flight frames so handlers drain fast
  // shutdown() unblocks the accept loop; close() + reset must wait until
  // the thread is joined — accept_loop reads listen_fd_ until then.
  if (listen_fd_ >= 0) shutdown(listen_fd_, SHUT_RDWR);
  if (accept_thread_.joinable()) accept_thread_.join();
  if (heartbeat_thread_.joinable()) heartbeat_thread_.join();
  if (listen_fd_ >= 0) {
    close(listen_fd_);
    listen_fd_ = -1;
  }
  conns_.wait_idle(10000);
}

void ManagerServer::accept_loop() {
  while (running_) {
    int fd = tcp_accept(listen_fd_, 200);
    if (fd < 0) continue;
    if (!conns_.add(fd)) {
      close(fd);
      continue;
    }
    std::thread([this, fd] {
      handle_conn(fd);
      conns_.remove(fd);
    }).detach();
  }
}

void ManagerServer::heartbeat_loop() {
  // Pings EVERY lighthouse in the ordered list each round over persistent
  // connections (manager.rs:194-216, extended for HA): the active entry's
  // ack renews its lease; standbys receive the same heartbeats read-only so
  // their fleet/participant tables stay warm for takeover. When the active
  // entry's lease lapses (no ack for lighthouse_lease_ms) we fail over
  // deterministically to the next address down the list, with the shared
  // seeded-jitter backoff so a fleet of managers doesn't storm the standby
  // in lockstep.
  const size_t n = lh_addrs_.size();
  std::vector<std::string> hosts(n);
  std::vector<int> ports(n, -1);
  size_t n_ok = 0;
  for (size_t i = 0; i < n; i++) {
    if (split_host_port(lh_addrs_[i], &hosts[i], &ports[i])) {
      n_ok++;
    } else {
      ports[i] = -1;
      fprintf(stderr, "[manager %s] bad lighthouse addr '%s' (entry %zu)\n",
              opts_.replica_id.c_str(), lh_addrs_[i].c_str(), i);
    }
  }
  if (n_ok == 0) return;
  std::vector<int> fds(n, -1);
  // Per-address reconnect backoff: a dead standby must not stall every
  // round behind its connect timeout, and the active entry's connect budget
  // must stay well inside the lease so a down primary is detected in time.
  std::vector<int64_t> next_try_ms(n, 0);
  std::vector<uint64_t> fail_streak(n, 0);
  int64_t last_active_ok_ms = now_ms();
  uint64_t failover_streak = 0;  // consecutive failovers without any ack
  // Consecutive TRANSPORT failures (connect refused/reset — not a live
  // lighthouse saying no) on the active entry: hard evidence the process is
  // gone, consumed by the evidence failover below.
  uint64_t active_fail_streak = 0;
  while (running_) {
    if (draining_) {
      // Graceful drain in progress: no more heartbeats (a fresh heartbeat
      // would make the lighthouse wait for us after we announced our leave).
      sleep_ms(opts_.heartbeat_interval_ms);
      continue;
    }
    const int active = lh_active_.load() % static_cast<int>(n);
    // Shared failover tail for both triggers (lease lapse / hard evidence):
    // advance down the list, record detection attribution for lh_failover
    // journaling, and queue a failure signal for the NEW active lighthouse.
    auto fail_over = [&](int kind, const char* label, int64_t detect_ms) {
      failover_streak += 1;
      int next = (active + 1) % static_cast<int>(n);
      lh_active_.store(next);
      lh_failovers_.fetch_add(1);
      lh_detect_ms_.store(detect_ms);
      lh_failover_kind_.store(kind);
      Json d = Json::object();
      d["detect_ms"] = Json::of(detect_ms);
      d["failed_addr"] = Json::of(lh_addrs_[active]);
      d["next_addr"] = Json::of(lh_addrs_[next]);
      queue_signal(kind == 2 ? "rpc_error" : "lease_expiry",
                   "lighthouse:" + lh_addrs_[active],
                   "manager:" + opts_.replica_id + ":hb_loop", std::move(d));
      last_active_ok_ms = now_ms();
      active_fail_streak = 0;
      fprintf(stderr,
              "[manager %s] lighthouse %s on %s (detect %lld ms): failing "
              "over to %s (failover #%lld)\n",
              opts_.replica_id.c_str(), label, lh_addrs_[active].c_str(),
              static_cast<long long>(detect_ms), lh_addrs_[next].c_str(),
              static_cast<long long>(lh_failovers_.load()));
      // Seeded full-jitter pause (shared PR-7 backoff) so the whole fleet
      // doesn't re-register against the standby in the same instant.
      double unit = chaos::backoff_unit(opts_.replica_id + "|lh_failover",
                                        failover_streak);
      sleep_ms(static_cast<int64_t>(unit * 500.0));
    };
    for (size_t i = 0; i < n && running_ && !draining_; i++) {
      if (ports[i] < 0) continue;
      const bool is_active = static_cast<int>(i) == active;
      int64_t now = now_ms();
      if (!is_active && fds[i] < 0 && now < next_try_ms[i]) continue;
      // Attribute heartbeat I/O to (ctrl, lighthouse-host, "heartbeat") for
      // the chaos plane: a stall@ctrl:match=heartbeat spec can delay THIS
      // replica's heartbeats (the fleet lane's straggler signal) without
      // touching quorum or data traffic.
      chaos::ScopedCtx chaos_ctx("ctrl", hosts[i], "heartbeat");
      if (fds[i] < 0) {
        // Connect budget: a third of the lease for the active entry (a dead
        // primary must be detected within the lease, not behind a 10 s
        // connect), a short probe for standbys.
        int64_t budget = is_active
                             ? std::max<int64_t>(
                                   50, std::min(opts_.lighthouse_lease_ms / 3,
                                                opts_.connect_timeout_ms))
                             : 250;
        fds[i] = tcp_connect(hosts[i], ports[i], budget);
      }
      bool acked = false;
      if (fds[i] >= 0) {
        Json req = Json::object();
        req["type"] = Json::of("heartbeat");
        req["replica_id"] = Json::of(opts_.replica_id);
        // Job namespace: routes this heartbeat to our job's isolated island
        // on a namespaced lighthouse; an old lighthouse ignores the key.
        req["job"] = Json::of(opts_.job);
        // Carry our address: lets the lighthouse drain_all reach us even if
        // we never managed to register a quorum (drain_all blind spot).
        req["address"] = Json::of(address());
        // Our nominal cadence: lets the lighthouse derive a deterministic
        // jitter threshold instead of guessing from arrival statistics.
        req["hb_interval_ms"] = Json::of(opts_.heartbeat_interval_ms);
        // The max quorum epoch we have accepted: the heartbeat stream is how
        // standbys learn the fleet's current owner (for a fenced takeover
        // epoch) and how a resurrected stale primary learns it has been
        // superseded (self-demotes).
        req["epoch"] = Json::of(lh_epoch_.load());
        // Max accepted quorum_id rides along so a takeover standby can
        // resume numbering strictly above the old primary's quorums.
        req["quorum_id"] = Json::of(lh_quorum_id_.load());
        req["lh_index"] = Json::of(static_cast<int64_t>(active));
        {
          // Piggyback the latest health digest (if the trainer pushed one).
          // Old lighthouses read only the keys they know, so this is free
          // to send unconditionally.
          std::lock_guard<std::mutex> lk(digest_mu_);
          if (has_digest_) req["digest"] = digest_;
        }
        // Piggyback queued failure signals on the ACTIVE entry (the island
        // that forms quorums is the one that must ingest evidence). The
        // outbox is only drained on ack, so a torn send re-delivers — the
        // lighthouse ring tolerates duplicates, losing evidence is worse.
        size_t attached = 0;
        if (is_active) {
          std::lock_guard<std::mutex> lk(signal_mu_);
          if (!signal_outbox_.empty()) {
            Json arr = Json::array();
            for (const auto& s : signal_outbox_) arr.push(s);
            attached = signal_outbox_.size();
            req["signals"] = std::move(arr);
          }
        }
        Json resp;
        if (call_json(fds[i], req, &resp, 5000)) {
          acked = resp.get("ok").as_bool();
          if (acked && is_active) {
            // Evidence cursor: the ack carries the island's failure-signal
            // seq + last signal; the trainer's watcher polls these via the
            // "evidence_status" RPC to react to peer death in ~one
            // heartbeat instead of a full collective timeout.
            int64_t sseq = resp.get("signal_seq").as_int(-1);
            if (sseq >= 0) {
              int64_t cur = lh_signal_seq_.load();
              while (sseq > cur &&
                     !lh_signal_seq_.compare_exchange_weak(cur, sseq)) {
              }
              std::lock_guard<std::mutex> lk(signal_mu_);
              if (resp.has("signal")) last_signal_ = resp.get("signal");
            }
            if (attached > 0) {
              std::lock_guard<std::mutex> lk(signal_mu_);
              for (size_t k = 0; k < attached && !signal_outbox_.empty(); k++)
                signal_outbox_.pop_front();
            }
          }
        } else {
          close(fds[i]);
          fds[i] = -1;
        }
      }
      if (acked) {
        fail_streak[i] = 0;
        next_try_ms[i] = 0;
        if (is_active) {
          last_active_ok_ms = now_ms();
          failover_streak = 0;
          active_fail_streak = 0;
        }
      } else if (fds[i] < 0) {
        if (is_active) active_fail_streak += 1;
        fail_streak[i] += 1;
        double unit = chaos::backoff_unit(
            opts_.replica_id + "|hb|" + lh_addrs_[i], fail_streak[i]);
        next_try_ms[i] =
            now_ms() + static_cast<int64_t>(unit * 2000.0);  // cap 2 s
      }
    }
    if (!draining_ && n > 1 && opts_.evidence_streak > 0 &&
        active_fail_streak >= static_cast<uint64_t>(opts_.evidence_streak)) {
      // Hard-evidence failover: N consecutive transport failures against
      // the active entry (connect refused/reset — the process is GONE, not
      // merely slow) fail over at heartbeat-cadence speed instead of
      // waiting out the rest of the lease.
      fail_over(2, "transport-dead (hard evidence)",
                now_ms() - last_active_ok_ms);
    } else if (!draining_ &&
               now_ms() - last_active_ok_ms > opts_.lighthouse_lease_ms) {
      // Lease lapsed: deterministic failover down the list (wrapping, so a
      // resurrected earlier entry can be re-adopted if everything later
      // also dies — it will take over with a freshly fenced epoch). The
      // soft-evidence fallback: covers hangs/partitions where connects
      // still land but acks never do.
      fail_over(1, "lease lapsed", now_ms() - last_active_ok_ms);
    }
    sleep_ms(opts_.heartbeat_interval_ms);
  }
  for (size_t i = 0; i < n; i++)
    if (fds[i] >= 0) close(fds[i]);
}

void ManagerServer::handle_conn(int fd) {
  while (running_) {
    std::string payload;
    if (!recv_frame(fd, &payload, 3600 * 1000)) break;
    Json req;
    std::string err;
    Json resp;
    if (!Json::parse(payload, &req, &err)) {
      resp["ok"] = Json::of(false);
      resp["error"] = Json::of("bad json: " + err);
    } else {
      // Server-side chaos: delay or drop this RPC (see lighthouse.cc).
      if (!chaos::server_rpc(req.get("type").as_str())) break;
      int64_t timeout = req.get("timeout_ms").as_int(60000);
      resp = handle_request(req, now_ms() + timeout);
      // Echo the caller's trace id so both planes of a step share one id
      // (the Python Manager mints it; responses carry it for correlation).
      if (req.has("trace_id")) resp["trace_id"] = req.get("trace_id");
    }
    if (!send_frame(fd, resp.dump(), 30000)) break;
  }
  close(fd);
}

Json ManagerServer::handle_request(const Json& req, int64_t deadline_ms) {
  const std::string type = req.get("type").as_str();
  Json resp = Json::object();
  if (type == "quorum") return quorum_rpc(req, deadline_ms);
  if (type == "should_commit") return should_commit_rpc(req, deadline_ms);
  if (type == "checkpoint_metadata") {
    int64_t rank = req.get("rank").as_int();
    std::lock_guard<std::mutex> lk(mu_);
    auto it = checkpoint_metadata_.find(rank);
    if (it == checkpoint_metadata_.end()) {
      resp["ok"] = Json::of(false);
      resp["error"] =
          Json::of("no checkpoint metadata for rank " + std::to_string(rank));
    } else {
      resp["ok"] = Json::of(true);
      resp["checkpoint_metadata"] = Json::of(it->second);
    }
    return resp;
  }
  if (type == "kill") {
    fprintf(stderr, "[manager %s] kill requested: %s\n",
            opts_.replica_id.c_str(), req.get("msg").as_str().c_str());
    fflush(stderr);
    // _exit, not exit: static destructors would try to join live server
    // threads and delay the death the caller is counting on
    // (reference kills the whole process too, manager.rs:481-486).
    _exit(1);
  }
  if (type == "leave") {
    bool sent = leave("graceful drain",
                      std::max<int64_t>(500, deadline_ms - now_ms()));
    resp["ok"] = Json::of(true);
    resp["sent"] = Json::of(sent);
    return resp;
  }
  if (type == "request_drain") {
    // Only sets the flag — the trainer sees it on its next quorum
    // response and drains at a step boundary it knows is safe.
    drain_requested_ = true;
    fprintf(stderr, "[manager %s] drain requested (operator)\n",
            opts_.replica_id.c_str());
    resp["ok"] = Json::of(true);
    return resp;
  }
  if (type == "drain_status") {
    // Out-of-band read of the flag: the piggyback on quorum responses
    // only delivers on quorum SUCCESS, so a trainer whose peers drained
    // a beat earlier (its quorums now fail) polls this after a failed
    // step instead of retrying quorums it can never win.
    resp["ok"] = Json::of(true);
    resp["drain_requested"] = Json::of(drain_requested_.load());
    return resp;
  }
  if (type == "set_digest") {
    // Cache the trainer's latest health digest; the heartbeat loop
    // attaches it to every lighthouse ping until replaced. Advisory
    // telemetry only — no validation beyond "is an object" (the
    // lighthouse tolerates anything), and dropping it is never an error.
    {
      std::lock_guard<std::mutex> lk(digest_mu_);
      digest_ = req.get("digest");
      has_digest_ = digest_.is_object();
    }
    resp["ok"] = Json::of(true);
    return resp;
  }
  if (type == "signal") {
    // Trainer/runner-observed failure evidence: queue for heartbeat
    // piggyback to the active lighthouse. Source must be one of
    // telemetry.SIGNAL_SOURCES; the lighthouse drops unknown sources, so
    // here we only refuse the obviously malformed (empty) case.
    const std::string source = req.get("source").as_str();
    if (source.empty()) {
      resp["ok"] = Json::of(false);
      resp["error"] = Json::of("signal requires a non-empty 'source'");
      return resp;
    }
    queue_signal(source, req.get("replica_id").as_str(opts_.replica_id),
                 req.get("site").as_str(""), req.get("detail"));
    resp["ok"] = Json::of(true);
    return resp;
  }
  if (type == "evidence_status") {
    // Lock-cheap poll for the trainer's evidence watcher: the island-wide
    // failure-signal cursor plus the last signal seen in an active ack. A
    // seq rise with a hard source on a PEER is grounds to abort a wedged
    // collective now instead of waiting out its timeout.
    resp["ok"] = Json::of(true);
    resp["signal_seq"] = Json::of(lh_signal_seq_.load());
    {
      std::lock_guard<std::mutex> lk(signal_mu_);
      resp["signal"] = last_signal_;
      resp["outbox"] = Json::of(static_cast<int64_t>(signal_outbox_.size()));
      resp["outbox_dropped"] = Json::of(signal_outbox_dropped_);
    }
    resp["lh"] = lh_info_json();
    return resp;
  }
  if (type == "info") {
    resp["ok"] = Json::of(true);
    resp["replica_id"] = Json::of(opts_.replica_id);
    resp["address"] = Json::of(address());
    resp["world_size"] = Json::of(opts_.world_size);
    resp["lh"] = lh_info_json();
    return resp;
  }
  resp["ok"] = Json::of(false);
  resp["error"] = Json::of("unknown request type '" + type + "'");
  return resp;
}

Json ManagerServer::lh_info_json() const {
  Json lh = Json::object();
  int idx = lh_active_.load() % static_cast<int>(lh_addrs_.size());
  lh["active"] = Json::of(static_cast<int64_t>(idx));
  lh["addr"] = Json::of(lh_addrs_[idx]);
  lh["failovers"] = Json::of(lh_failovers_.load());
  lh["epoch"] = Json::of(lh_epoch_.load());
  lh["stale_rejected"] = Json::of(lh_stale_rejected_.load());
  lh["unreachable_retries"] = Json::of(lh_unreachable_retries_.load());
  lh["job"] = Json::of(opts_.job);
  // Detection attribution of the LAST failover: how long the dead active
  // entry went unacked before we moved ("detect_ms"), and which trigger won
  // — hard transport evidence or the lease timeout fallback.
  lh["detect_ms"] = Json::of(lh_detect_ms_.load());
  int k = lh_failover_kind_.load();
  lh["evidence"] = Json::of(k == 2 ? "evidence" : (k == 1 ? "lease" : ""));
  lh["signal_seq"] = Json::of(lh_signal_seq_.load());
  return lh;
}

void ManagerServer::queue_signal(const std::string& source,
                                 const std::string& subject,
                                 const std::string& site, Json detail) {
  Json s = Json::object();
  s["source"] = Json::of(source);
  s["replica_id"] = Json::of(subject.empty() ? opts_.replica_id : subject);
  s["site"] =
      Json::of(site.empty() ? "manager:" + opts_.replica_id : site);
  s["ts_ms"] = Json::of(now_ms());
  if (!detail.is_null()) s["detail"] = std::move(detail);
  std::lock_guard<std::mutex> lk(signal_mu_);
  signal_outbox_.push_back(std::move(s));
  // Bounded like the lighthouse rings: drop the OLDEST — fresh evidence is
  // what unblocks survivors.
  while (signal_outbox_.size() > 16) {
    signal_outbox_.pop_front();
    signal_outbox_dropped_ += 1;
  }
}

std::optional<Quorum> ManagerServer::lighthouse_quorum(
    const QuorumMember& me, int64_t deadline_ms, const std::string& trace_id,
    std::string* error) {
  // Retry with per-attempt deadline slices (manager.rs:250-306): each attempt
  // gets total/(retries+1). A connect-level failure (lighthouse unreachable —
  // a transient blip or a dead primary mid-failover) is absorbed with the
  // shared seeded full-jitter backoff rather than failing the step; a live
  // lighthouse's explicit refusal is a different error. The active target is
  // re-read every attempt: the heartbeat thread's lease may fail over
  // mid-retry and the next attempt must follow it down the list.
  int64_t attempts = std::max<int64_t>(1, opts_.quorum_retries + 1);
  int64_t total = std::max<int64_t>(1, deadline_ms - now_ms());
  int64_t slice = std::max<int64_t>(100, total / attempts);
  int64_t unreachable = 0;
  std::string last_addr;
  std::string denied;
  // Follow-the-failover retries: when the heartbeat thread fails over WHILE
  // an attempt is burning its connect budget against the dead target, the
  // next try against the new active is free (not counted against the
  // budgeted attempts, no backoff). Bounded so a flapping list can't loop.
  int64_t free_retries = static_cast<int64_t>(lh_addrs_.size()) * 2;

  for (int64_t a = 0; a < attempts && running_; a++) {
    const int active_at_start = lh_active_.load();
    const std::string addr =
        lh_addrs_[active_at_start % static_cast<int>(lh_addrs_.size())];
    last_addr = addr;
    std::string host;
    int port = 0;
    int fd = -1;
    bool transport_fail = false;
    int64_t attempt_deadline = std::min(deadline_ms, now_ms() + slice);
    if (split_host_port(addr, &host, &port)) {
      // Per-attempt connect budget. With standbys configured, cap it near
      // the lease: a SIGKILLed primary must not eat the whole slice (the
      // full quorum timeout when quorum_retries=0) when the heartbeat
      // thread will have failed over at evidence speed long before — the
      // free retry below follows it. Single-lighthouse deployments keep
      // the full budget (nowhere else to go).
      int64_t cbudget = std::min<int64_t>(slice, opts_.connect_timeout_ms);
      if (lh_addrs_.size() > 1)
        cbudget = std::min(
            cbudget, std::max<int64_t>(250, opts_.lighthouse_lease_ms));
      fd = tcp_connect_retry(host, port, cbudget);
    }
    if (fd < 0) {
      transport_fail = true;
      unreachable += 1;
      lh_unreachable_retries_.fetch_add(1);
    } else {
      Json req = Json::object();
      req["type"] = Json::of("quorum");
      req["job"] = Json::of(opts_.job);
      req["timeout_ms"] = Json::of(attempt_deadline - now_ms());
      req["requester"] = me.to_json();
      if (!trace_id.empty()) req["trace_id"] = Json::of(trace_id);
      Json resp;
      bool ok = call_json(fd, req, &resp, attempt_deadline - now_ms());
      close(fd);
      if (!ok) {
        // Torn mid-RPC (connection reset / partition): same bucket as
        // unreachable — retry, don't latch.
        transport_fail = true;
        unreachable += 1;
        lh_unreachable_retries_.fetch_add(1);
      } else if (!resp.get("ok").as_bool()) {
        denied = resp.get("error").as_str("quorum denied");
      } else {
        Quorum q = Quorum::from_json(resp.get("quorum"));
        int64_t fence = lh_epoch_.load();
        if (q.epoch < fence) {
          // Split-brain fence: a resurrected stale primary can answer
          // quorums, but its epoch is below what the fleet has already
          // accepted from the takeover. Never deliver it to the trainer.
          lh_stale_rejected_.fetch_add(1);
          denied = "stale quorum fenced: epoch " + std::to_string(q.epoch) +
                   " < " + std::to_string(fence) + " (from " + addr + ")";
          fprintf(stderr, "[manager %s] %s\n", opts_.replica_id.c_str(),
                  denied.c_str());
        } else {
          while (q.epoch > fence &&
                 !lh_epoch_.compare_exchange_weak(fence, q.epoch)) {
          }
          int64_t qid = lh_quorum_id_.load();
          while (q.quorum_id > qid &&
                 !lh_quorum_id_.compare_exchange_weak(qid, q.quorum_id)) {
          }
          return q;
        }
      }
    }
    if (now_ms() >= deadline_ms) break;
    if (transport_fail && free_retries > 0 &&
        lh_active_.load() != active_at_start) {
      // The heartbeat thread failed over mid-attempt: follow it now.
      free_retries -= 1;
      a -= 1;
      continue;
    }
    if (a + 1 < attempts) {
      // Seeded full-jitter between attempts (chaos.backoff_jitter's C++
      // twin, keyed per replica so retries across the fleet decorrelate).
      double unit = chaos::backoff_unit(
          opts_.replica_id + "|lh_quorum|" + addr, static_cast<uint64_t>(a + 1));
      int64_t cap = std::min<int64_t>(1000, deadline_ms - now_ms());
      sleep_ms(std::max<int64_t>(10, static_cast<int64_t>(unit * cap)));
    }
  }
  if (error) {
    if (!denied.empty()) {
      *error = "lighthouse quorum denied: " + denied;
    } else {
      *error = "lighthouse unreachable after " + std::to_string(unreachable) +
               " attempts (last: " + last_addr + ")";
    }
  }
  return std::nullopt;
}

bool ManagerServer::leave(const std::string& reason, int64_t budget_ms) {
  // Stop our lighthouse heartbeats FIRST so a racing ping can't resurrect
  // the entry, then tell the lighthouse to drop us (its tombstone covers
  // the one heartbeat that may already be in flight). A repeat call (e.g.
  // a second local rank's leave RPC, or the RPC racing the parent-death
  // watchdog) short-circuits only once the lighthouse has CONFIRMED —
  // otherwise it retries the send, so a transient connect failure on the
  // first attempt can't latch a false "sent" while survivors stall out
  // the heartbeat expiry. Concurrent duplicate sends are harmless (the
  // lighthouse leave is idempotent).
  draining_ = true;
  if (left_sent_) return true;
  bool sent = false;
  // One budget for the WHOLE attempt (connect + RPC, across however many
  // list entries we manage to try): the parent-death watchdog passes a
  // small budget so an unreachable lighthouse (whole-machine / partition
  // loss, where the leave is moot anyway) can't hold the orphaned binary
  // alive — a slow connect must not let the RPC wait spend the full budget
  // again on top. Starting at the ACTIVE entry (and walking down the list
  // on failure) covers a drain racing a failover: the leave must land on
  // whichever lighthouse will form the survivors' next quorum.
  int64_t deadline = now_ms() + budget_ms;
  const size_t n = lh_addrs_.size();
  const int start = lh_active_.load() % static_cast<int>(n);
  for (size_t k = 0; k < n && !sent; k++) {
    const std::string& addr = lh_addrs_[(start + k) % n];
    std::string host;
    int port = 0;
    if (!split_host_port(addr, &host, &port)) continue;
    int64_t remaining = deadline - now_ms();
    if (remaining < 100 && k > 0) break;
    int fd = tcp_connect(
        host, port,
        std::max<int64_t>(100, std::min<int64_t>(
                                   remaining, opts_.connect_timeout_ms)));
    if (fd >= 0) {
      remaining = std::max<int64_t>(200, deadline - now_ms());
      Json lv = Json::object();
      lv["type"] = Json::of("leave");
      lv["replica_id"] = Json::of(opts_.replica_id);
      lv["job"] = Json::of(opts_.job);
      // Why we left: "trainer died" (the parent-death watchdog leaving on
      // the corpse's behalf) is failure evidence the lighthouse turns into
      // a proc_death signal; planned drains stay signal-free.
      lv["reason"] = Json::of(reason);
      Json lresp;
      sent = call_json(fd, lv, &lresp, remaining) && lresp.get("ok").as_bool();
      close(fd);
    }
  }
  if (sent) left_sent_ = true;
  fprintf(stderr, "[manager %s] leaving quorum (%s, sent=%d)\n",
          opts_.replica_id.c_str(), reason.c_str(), sent ? 1 : 0);
  return sent;
}

Json ManagerServer::quorum_rpc(const Json& req, int64_t deadline_ms) {
  int64_t rank = req.get("group_rank").as_int();
  bool init_sync = req.get("init_sync").as_bool(true);
  const std::string trace_id = req.get("trace_id").as_str();
  Json resp = Json::object();
  if (draining_) {
    // A post-leave quorum registration would clear our lighthouse tombstone
    // while our heartbeats stay stopped — recreating the heartbeat-expiry
    // stall the drain exists to remove. All ranks and clients share this
    // layer, so the refusal is enforced here, not just in the Python
    // Manager's _drained flag (which is per-object).
    resp["ok"] = Json::of(false);
    resp["error"] = Json::of(
        "manager is draining (leave() called); relaunch the process to rejoin");
    return resp;
  }
  if (rank < 0 || rank >= opts_.world_size) {
    resp["ok"] = Json::of(false);
    resp["error"] = Json::of("group_rank " + std::to_string(rank) +
                             " out of range [0, " +
                             std::to_string(opts_.world_size) + ")");
    return resp;
  }

  std::unique_lock<std::mutex> lk(mu_);
  RankInfo info;
  info.step = req.get("step").as_int();
  info.shrink_only = req.get("shrink_only").as_bool();
  info.commit_failures = req.get("commit_failures").as_int();
  participants_[rank] = info;
  checkpoint_metadata_[rank] = req.get("checkpoint_metadata").as_str();
  int64_t my_round = quorum_round_;

  if (static_cast<int64_t>(participants_.size()) >= opts_.world_size &&
      !quorum_inflight_) {
    // Last local rank in: this thread performs the lighthouse round
    // (manager.rs:332-402).
    quorum_inflight_ = true;
    QuorumMember me;
    me.replica_id = opts_.replica_id;
    me.address = address();
    me.store_address = opts_.store_address;
    me.world_size = opts_.world_size;
    for (const auto& kv : participants_) {
      me.step = std::max(me.step, kv.second.step);
      me.shrink_only = me.shrink_only || kv.second.shrink_only;
      me.commit_failures = std::max(me.commit_failures, kv.second.commit_failures);
    }
    lk.unlock();
    std::string lherr;
    auto q = lighthouse_quorum(me, deadline_ms, trace_id, &lherr);
    lk.lock();
    if (q) {
      current_quorum_ = q;
      quorum_error_.clear();
    } else {
      current_quorum_.reset();
      quorum_error_ = lherr.empty()
                          ? "lighthouse quorum failed (timeout or unreachable)"
                          : lherr;
    }
    quorum_round_ += 1;
    participants_.clear();
    quorum_inflight_ = false;
    lk.unlock();
    cv_.notify_all();
    lk.lock();
  } else {
    while (running_ && quorum_round_ == my_round) {
      if (cv_.wait_until(lk, std::chrono::system_clock::time_point(
                                 std::chrono::milliseconds(deadline_ms))) ==
              std::cv_status::timeout &&
          now_ms() >= deadline_ms) {
        participants_.erase(rank);
        resp["ok"] = Json::of(false);
        resp["error"] = Json::of("timed out waiting for local ranks / quorum");
        resp["timeout"] = Json::of(true);
        return resp;
      }
    }
  }

  if (!current_quorum_) {
    resp["ok"] = Json::of(false);
    resp["error"] = Json::of(
        quorum_error_.empty() ? "no quorum delivered" : quorum_error_);
    resp["lh"] = lh_info_json();
    return resp;
  }
  std::string err;
  auto result = compute_quorum_results(rank, opts_.replica_id, *current_quorum_,
                                       init_sync, &err);
  if (!result) {
    resp["ok"] = Json::of(false);
    resp["error"] = Json::of(err);
    return resp;
  }
  resp["ok"] = Json::of(true);
  resp["result"] = result->to_json();
  resp["quorum"] = current_quorum_->to_json();
  resp["drain_requested"] = Json::of(drain_requested_.load());
  // HA telemetry: epoch/failover/retry counters so the Python Manager can
  // journal lh_epoch / lh_failover / rpc_retry transitions per step.
  resp["lh"] = lh_info_json();
  return resp;
}

Json ManagerServer::should_commit_rpc(const Json& req, int64_t deadline_ms) {
  int64_t rank = req.get("group_rank").as_int();
  bool vote = req.get("should_commit").as_bool();
  Json resp = Json::object();
  if (rank < 0 || rank >= opts_.world_size) {
    resp["ok"] = Json::of(false);
    resp["error"] = Json::of("group_rank " + std::to_string(rank) +
                             " out of range [0, " +
                             std::to_string(opts_.world_size) + ")");
    return resp;
  }

  std::unique_lock<std::mutex> lk(mu_);
  commit_votes_[rank] = vote;
  int64_t my_round = commit_round_;
  if (static_cast<int64_t>(commit_votes_.size()) >= opts_.world_size) {
    // Barrier complete: commit iff no rank voted false (manager.rs:423-479).
    bool all = true;
    for (const auto& kv : commit_votes_) all = all && kv.second;
    commit_result_ = all;
    commit_votes_.clear();
    commit_round_ += 1;
    lk.unlock();
    cv_.notify_all();
    lk.lock();
  } else {
    while (running_ && commit_round_ == my_round) {
      if (cv_.wait_until(lk, std::chrono::system_clock::time_point(
                                 std::chrono::milliseconds(deadline_ms))) ==
              std::cv_status::timeout &&
          now_ms() >= deadline_ms) {
        commit_votes_.erase(rank);
        resp["ok"] = Json::of(false);
        resp["error"] = Json::of("timed out waiting for should_commit barrier");
        resp["timeout"] = Json::of(true);
        return resp;
      }
    }
  }
  resp["ok"] = Json::of(true);
  resp["should_commit"] = Json::of(commit_result_);
  return resp;
}

}  // namespace tft
