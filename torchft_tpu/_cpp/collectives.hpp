// Native DCN data plane: a C++ pipelined collective engine.
//
// The fault-tolerant replica axis moves every gradient byte host-side over
// DCN TCP. ProcessGroupSocket drives that ring from Python — one connection
// per peer, one chunk in flight, the interpreter on the copy path — which
// caps throughput far below the NIC. This engine is the native data plane
// behind ProcessGroupNative (process_group.py): the same framed-TCP net
// layer underneath (net.hpp), but
//
//  - multi-connection striping: n_streams sockets per peer, each carrying a
//    contiguous slice of every transfer, so one TCP window / one core never
//    bounds a transfer;
//  - chunked ring allreduce with pipelined receive-reduce: each stripe
//    reader consumes the wire in pipeline_bytes sub-blocks and reduces
//    sub-block k into the destination while k+1 is still in flight (the
//    kernel socket buffer is the second half of the double buffer);
//  - optional int8 blockwise wire compression (allreduce_q8) that
//    round-trips through the exact quantize_blockwise layout of
//    torchft_tpu/collectives.py + ops/quantization.py: BLOCK=512 values per
//    float32 scale, scale = absmax/127 (1.0 for all-zero blocks),
//    round-half-even, clip to ±127 — quantize once, alltoall owner chunks,
//    fp32 local reduce, requantize, allgather, so every rank decodes the
//    same bytes and results stay cross-replica bitwise identical;
//  - ragged allgather / broadcast carrying an opaque metadata string per
//    payload (the Python side stores dtype/shape there; the engine only
//    relays it).
//
// Numerics: the fp32/f64/i32/i64 ring uses np.array_split chunking and the
// same per-element accumulation (dst = dst OP incoming, left-neighbor
// contributions in ring order) as ProcessGroupSocket._ring_allreduce_flat,
// so uncompressed results are bitwise identical to the socket backend.
//
// Exposed to Python through the C ABI at the bottom (ctypes over
// libtftcollectives.so, see torchft_tpu/_native.py). One collective at a
// time per engine (the Python PG already serializes ops on one executor
// thread); abort() may be called concurrently from any thread and shuts
// down every socket so blocked calls fail fast instead of timing out.
//
// Degraded-network survival (per-peer link policy + stripe failover):
//
//  - Every peer link carries a LinkPolicy (class local|dcn|wan, per-attempt
//    connect clamp, optional per-leg I/O budget, stripe count, wire
//    preference), pushed from TORCHFT_LINKS before connect_mesh. Policies
//    must be configured symmetrically: rank A's policy for B and B's for A
//    agree on stripe count, or the mesh handshake fails.
//  - A striped transfer no longer aborts the collective on one socket
//    error: the stripes of one (peer, direction) leg group report into the
//    group, and the last leg to finish re-assigns every failed stripe's
//    byte range to the lowest-indexed surviving stripe (both ends compute
//    the identical handoff from the shared alive mask + split logic, so no
//    extra control round-trip is needed). Dead stripes are excluded from
//    later transfers via a per-peer alive bitmask; only when ALL stripes to
//    a peer are dead (or the deadline is already spent) does the engine
//    fall back to the abort/poison path. Failovers are recorded in a ring
//    exposed by fr_snapshot ("failovers") and journaled by the Python PG as
//    stripe_failover events.
//  - Failover relies on SYMMETRIC detection (a reset/shutdown propagates to
//    the peer mid-leg, so both ends fail the same stripe in the same leg
//    group). An asymmetric failure — receiver errors while the sender's
//    bytes all fit in the kernel socket buffer — leaves the ends with
//    different masks and falls back to deadline -> abort -> heal.
//  - A background janitor reconnects dead stripes (seeded jittered backoff,
//    original connect direction) and stages the new socket on both ends
//    with an activation collective number negotiated in the rejoin
//    handshake, so both ends swap the fd in before the same collective.
#pragma once

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <queue>
#include <string>
#include <thread>
#include <vector>

namespace tft {

// Codes shared with the ctypes bindings (_native.py). Keep in sync.
enum : int32_t {
  TFT_DT_F32 = 0,
  TFT_DT_F64 = 1,
  TFT_DT_I32 = 2,
  TFT_DT_I64 = 3,
};
enum : int32_t {
  TFT_OP_SUM = 0,
  TFT_OP_MAX = 1,
  TFT_OP_MIN = 2,
};

// ---------------------------------------------------------------------------
// Flight recorder: a fixed-size ring of per-collective records written on the
// hot path with no allocation and no locks. One collective runs at a time per
// engine, so a record has a single writer for its scalar fields; the striped
// transfer jobs claim disjoint lane slots via one fetch_add each. Snapshots
// (fr_snapshot) read the ring concurrently: records whose seq no longer
// matches their slot (wrapped mid-read) are skipped, in-flight records are
// reported as such — a torn lane costs a garbage number in a diagnostic
// record, never memory unsafety.
// ---------------------------------------------------------------------------

constexpr int kFrTagLen = 64;
constexpr int kFrCauseLen = 96;
constexpr int kFrMaxLanes = 32;  // (peer, stripe, direction) legs per record
constexpr int kFrMaxSteps = 16;  // ring-step completion stamps per record

// One striped transfer leg. Written by exactly one pool job.
struct FlightLane {
  int16_t peer = -1;
  int8_t stripe = 0;
  int8_t dir = 0;          // 0 = send, 1 = recv, 2 = recv-reduce
  uint32_t spins = 0;      // MSG_DONTWAIT misses (EAGAIN -> poll) in this leg
  uint64_t bytes = 0;
  uint64_t t0_ns = 0;      // CLOCK_REALTIME, aligns with journal time.time()
  uint64_t t1_ns = 0;
  uint64_t reduce_ns = 0;  // recv-reduce only: ns folding blocks into dst
};

struct FlightRec {
  std::atomic<uint64_t> seq{0};    // 1-based; 0 = slot never written
  int32_t op = 0;                  // 0 allreduce 1 q8 2 allgather 3 broadcast
  int32_t dtype = -1;
  int32_t red_op = -1;
  std::atomic<int32_t> status{0};  // 0 in-flight 1 ok 2 error 3 timeout 4 abort
  uint64_t bytes = 0;
  uint64_t t_start_ns = 0;
  uint64_t t_end_ns = 0;
  char tag[kFrTagLen] = {0};       // trace tag in force when the op started
  char cause[kFrCauseLen] = {0};   // abort/poison/error cause on failure
  std::atomic<uint32_t> nsteps{0};
  uint64_t step_ns[kFrMaxSteps] = {0};  // per-chunk ring-step completion
  std::atomic<uint32_t> lane_n{0};      // lanes claimed (may exceed kFrMaxLanes)
  FlightLane lanes[kFrMaxLanes];
};

// Cumulative per-peer link counters, always on (plain atomic adds): feed the
// Prometheus exporter's per-peer bandwidth gauges even when the ring is off.
struct PeerCounters {
  std::atomic<uint64_t> tx_bytes{0};
  std::atomic<uint64_t> rx_bytes{0};
  std::atomic<uint64_t> tx_busy_ns{0};  // summed over stripe jobs (overlapping)
  std::atomic<uint64_t> rx_busy_ns{0};
  std::atomic<uint64_t> spins{0};
};

// Per-peer link policy, pushed from TORCHFT_LINKS (knobs.py) before
// connect_mesh. Both ends of a link must agree on n_streams (the mesh
// handshake validates stripe indices against the local policy). `q8` is
// consumed by the Python wire-format selection, not the engine; it rides
// here so one registry owns the whole policy.
struct LinkPolicy {
  std::string cls = "dcn";     // local | dcn | wan (chaos link:<class> scope)
  int64_t connect_ms = 5000;   // per-attempt clamp inside tcp_connect_retry
  int64_t io_ms = 0;           // per-leg I/O budget; 0 = collective deadline.
                               // A stripe stalled past this fails early enough
                               // for the leg group to hand its range over.
  int n_streams = 0;           // stripes on this link; 0 = engine default
  bool q8 = false;             // prefer int8 wire compression on this link
};

// Fixed-size worker pool for concurrent striped send/recv jobs. Sized so
// every stripe to and from every peer can progress at once — a smaller pool
// could fill up with blocked senders and deadlock the mesh.
class TaskPool {
 public:
  explicit TaskPool(int n_threads);
  ~TaskPool();
  void submit(std::function<void()> fn);

 private:
  void worker();
  std::vector<std::thread> threads_;
  std::queue<std::function<void()>> queue_;
  std::mutex mu_;
  std::condition_variable cv_;
  bool stop_ = false;
};

class CollectiveEngine {
 public:
  // fr_capacity: flight-recorder ring slots; 0 disables recording (the
  // per-peer counters stay on either way).
  CollectiveEngine(int n_streams, int64_t pipeline_bytes, int fr_capacity = 0);
  ~CollectiveEngine();

  // Binds the data-plane listener. Returns the port, or -1 (last_error set).
  int listen(const std::string& host);
  // Full-mesh rendezvous: connect n_streams sockets to every lower rank,
  // accept n_streams from every higher rank. peers[i] is rank i's
  // "host:port" (peers[rank] ignored). False on failure.
  bool connect_mesh(int rank, int world, const std::vector<std::string>& peers,
                    int64_t timeout_ms);
  // Shuts down every socket (listener included). Safe from any thread while
  // a collective is blocked; that collective returns an error promptly.
  void abort(const std::string& why);

  // Installs the link policy for `peer` (-1 = default for unlisted peers).
  // Must be called before connect_mesh; ignored afterwards (the janitor
  // reads policies without a lock once the mesh is up).
  void set_link_policy(int peer, const LinkPolicy& pol);

  // In-place ring allreduce over `count` elements of `dtype`. AVG is the
  // caller's job (SUM then divide), matching ProcessGroupSocket.
  bool allreduce(void* data, uint64_t count, int32_t dtype, int32_t op,
                 int64_t timeout_ms);
  // In-place int8-compressed fp32 SUM allreduce (blockwise layout above).
  bool allreduce_q8(float* data, uint64_t count, int64_t timeout_ms);
  // Ragged allgather of (meta, payload); results land in slots [0, world).
  bool allgather(const std::string& meta, const void* data, uint64_t nbytes,
                 int64_t timeout_ms);
  // Broadcast from root; non-root ranks find (meta, payload) in slot `root`.
  bool broadcast(const std::string& meta, const void* data, uint64_t nbytes,
                 int root, int64_t timeout_ms);

  const std::string& result_meta(int slot) const { return results_[slot].first; }
  const std::string& result_payload(int slot) const {
    return results_[slot].second;
  }
  int world() const { return world_; }
  int port() const { return port_; }
  uint64_t bytes_tx() const { return bytes_tx_.load(); }
  uint64_t bytes_rx() const { return bytes_rx_.load(); }
  std::string last_error() const;

  // -- flight recorder ----------------------------------------------------
  // Tag stamped onto every subsequent record (trace id + collective tag,
  // e.g. "q3.s17|c4"). Callable between collectives from any thread.
  void set_trace(const std::string& tag);
  // Highest record seq allocated so far (0 if recording is off/idle).
  uint64_t fr_seq() const { return fr_seq_.load(); }
  // Records evicted by ring wrap since creation.
  uint64_t fr_dropped() const { return fr_dropped_.load(); }
  // JSON snapshot of records with seq > since_seq plus cumulative counters.
  // Safe to call from any thread while a collective is in flight.
  std::string fr_snapshot(uint64_t since_seq) const;

 private:
  struct Waiter;
  struct LegGroup;

  void set_error(const std::string& msg);
  bool fail(const std::string& msg);  // set_error + return false
  void close_all();

  // Effective policy / stripe count for a peer (clamped to the 32-bit alive
  // mask; both ends must agree — symmetric TORCHFT_LINKS configuration).
  LinkPolicy link_policy(int peer) const;
  int stripes_for(int peer) const;
  // Lowest-indexed live stripe to `peer` (header/metadata traffic), or -1.
  int first_alive(int peer) const;

  // Enqueue striped transfer jobs against `peer`; the stripes of one call
  // form a leg group that reports ONE completion into *w — individual
  // stripe failures are handled inside the group (handoff to a surviving
  // stripe) before the group resolves. `esize` keeps stripe boundaries on
  // element boundaries (both ends must pass the same esize or the slices
  // would interleave mid-element). `rec` (nullable) collects per-stripe
  // flight-recorder lanes.
  void send_stripes(int peer, const char* data, uint64_t nbytes,
                    uint64_t esize, int64_t deadline_ms, Waiter* w,
                    FlightRec* rec = nullptr);
  void recv_stripes(int peer, char* data, uint64_t nbytes, uint64_t esize,
                    int64_t deadline_ms, Waiter* w, FlightRec* rec = nullptr);
  // Striped receive that reduces into dst in pipeline_bytes sub-blocks
  // (dst[i] = dst[i] OP incoming[i]) instead of storing raw bytes.
  void recv_reduce_stripes(int peer, void* dst, uint64_t count, int32_t dtype,
                           int32_t op, int64_t deadline_ms, Waiter* w,
                           FlightRec* rec = nullptr);

  // Partitions [0, units) over the live stripes of g->peer and submits one
  // pool job per leg; the group resolves g->w exactly once (leg_epilogue).
  void launch_group(std::shared_ptr<LegGroup> g, uint64_t units);
  // One stripe leg: transfer, flight-recorder lane, group bookkeeping.
  void run_leg(std::shared_ptr<LegGroup> g, size_t li);
  // Runs on the pool thread of the LAST stripe job of a group to finish:
  // re-assigns every failed stripe's byte range to survivors (or fails the
  // group), then resolves the group's Waiter slot exactly once.
  void leg_epilogue(std::shared_ptr<LegGroup> g);
  // Re-runs failed leg `li` in full over surviving stripe `to` (16-byte
  // {magic, stripe, ulen} header so both ends can detect disagreement).
  bool handoff_leg(LegGroup& g, size_t li, int to);
  // One rejoin dial for a dead stripe (janitor). Stages the socket with the
  // activation number the acceptor picked. False = retry next sweep.
  bool try_rejoin(int peer, int stripe);
  // Records one handoff in the failover ring (fr_snapshot "failovers").
  void record_failover(int peer, int stripe, int to_stripe, int dir,
                       uint64_t moved_bytes, const char* tag);

  // Collective entry: bumps op_seq_ and installs janitor-staged rejoin
  // sockets whose negotiated activation number has arrived (both ends
  // install before the same collective, so stripe partitions agree).
  void begin_op();
  void janitor_loop();   // connector side: redial dead stripes to lower ranks
  void acceptor_loop();  // acceptor side: absorb rejoin dials from higher ranks

  template <typename T>
  bool ring_allreduce_t(T* data, uint64_t count, int32_t dtype, int32_t op,
                        int64_t deadline_ms, FlightRec* rec);

  bool allreduce_q8_inner(float* data, uint64_t count, int64_t timeout_ms,
                          FlightRec* rec);
  bool allgather_inner(const std::string& meta, const void* data,
                       uint64_t nbytes, int64_t timeout_ms, FlightRec* rec);
  bool broadcast_inner(const std::string& meta, const void* data,
                       uint64_t nbytes, int root, int64_t timeout_ms,
                       FlightRec* rec);

  // Flight-recorder plumbing (all no-ops when recording is off / rec null).
  FlightRec* fr_begin(int32_t op_code, int32_t dtype, int32_t red_op,
                      uint64_t bytes);
  void fr_end(FlightRec* rec, bool ok);
  void fr_step(FlightRec* rec);  // stamp the next ring-step completion
  // Completion of one stripe job: updates the per-peer counters and, when
  // recording, claims a lane on `rec`.
  void fr_job(FlightRec* rec, int peer, int stripe, int dir, uint64_t bytes,
              uint64_t t0_ns, uint64_t spins_before, uint64_t reduce_ns);

  int n_streams_;
  int64_t pipeline_bytes_;
  int rank_ = -1;
  int world_ = 0;
  int listen_fd_ = -1;
  int port_ = -1;
  std::vector<std::vector<int>> peer_fds_;  // [peer][stripe]; self empty
  std::unique_ptr<TaskPool> pool_;

  // -- link policy / stripe health ----------------------------------------
  LinkPolicy default_policy_;
  std::map<int, LinkPolicy> link_policies_;  // frozen once connect_mesh runs
  std::vector<std::string> peer_addrs_;      // "host:port" per rank (janitor)
  // Bit s set = stripe s to that peer is usable. Cleared by leg groups on
  // symmetric failure detection, restored by the rejoin janitor. 32 bits
  // bounds stripes per link at 32 (ctor clamps).
  std::unique_ptr<std::atomic<uint32_t>[]> alive_mask_;
  // alive_mask_ snapshot frozen at begin_op: the partition mask every group
  // launched during one collective uses, so mid-op leg deaths (observed at
  // different times on the two ends) cannot desynchronize the byte ranges.
  // Written in begin_op (under reconn_mu_) and read by launch_group /
  // first_alive on the same caller thread that ran begin_op.
  std::vector<uint32_t> op_mask_;
  // Per-(peer, stripe) throughput EWMA in GiB/s, updated per leg (fr_job).
  mutable std::mutex health_mu_;
  std::vector<std::vector<double>> stripe_gibs_;

  // -- failover ring (fr_snapshot "failovers") ----------------------------
  struct FailoverEvent {
    int64_t seq;
    int16_t peer;
    int8_t stripe;     // stripe whose range moved (or rejoined)
    int8_t to_stripe;  // surviving carrier; -1 for a rejoin event
    int8_t dir;        // 0 send 1 recv 2 recv-reduce 3 rejoin
    uint64_t bytes;
    uint64_t t_ns;
    char tag[kFrTagLen];
  };
  mutable std::mutex fo_mu_;
  std::deque<FailoverEvent> failovers_;  // capped; Python drains by seq
  int64_t fo_seq_ = 0;

  // -- rejoin janitor -----------------------------------------------------
  // Lock order: reconn_mu_ is a leaf (never held across I/O or other locks).
  std::mutex reconn_mu_;
  uint64_t op_seq_ = 0;  // collectives started; rejoin activation unit
  struct Staged {
    int peer;
    int stripe;
    int fd;
    uint64_t activate_at;  // install when op_seq_ reaches this
  };
  std::vector<Staged> staged_;
  // fds replaced by a rejoin: already shut down, kept open until the
  // destructor so a stripe job blocked on one fails instead of touching a
  // recycled descriptor (same lifetime rule as peer_fds_).
  std::vector<int> retired_fds_;
  std::thread janitor_;
  std::thread acceptor_;
  std::atomic<bool> stopping_{false};
  std::vector<std::pair<std::string, std::string>> results_;  // meta, payload
  std::atomic<bool> aborted_{false};
  std::atomic<uint64_t> bytes_tx_{0};
  std::atomic<uint64_t> bytes_rx_{0};
  mutable std::mutex err_mu_;
  std::string last_error_;

  // Flight recorder state. The ring is a raw array (not std::vector) because
  // FlightRec holds atomics and is neither copyable nor movable.
  int fr_cap_ = 0;
  std::unique_ptr<FlightRec[]> fr_ring_;
  std::atomic<uint64_t> fr_seq_{0};
  std::atomic<uint64_t> fr_dropped_{0};
  std::atomic<uint64_t> spin_total_{0};
  std::unique_ptr<PeerCounters[]> peer_counters_;  // sized world_ at connect
  // Serializes ring-record field mutation (fr_begin/end/step/job) against
  // fr_snapshot. The per-record seq/status/nsteps/lane_n atomics stay for
  // wrap detection and slot claiming; the mutex covers the plain fields a
  // snapshot would otherwise read torn. Held for ns — collective jobs spend
  // their time in socket I/O, not here.
  mutable std::mutex fr_mu_;
  mutable std::mutex trace_mu_;
  char trace_tag_[kFrTagLen] = {0};
};

}  // namespace tft

// ---------------------------------------------------------------------------
// C ABI for the ctypes bindings (torchft_tpu/_native.py). Return codes:
// 0 = ok, 1 = error (see tft_coll_last_error), 2 = timeout.
// ---------------------------------------------------------------------------
extern "C" {
// fr_capacity: flight-recorder ring slots (0 = recording off).
void* tft_coll_create(int32_t n_streams, int64_t pipeline_bytes,
                      int32_t fr_capacity);
void tft_coll_destroy(void* h);
int32_t tft_coll_listen(void* h, const char* host);  // port or -1
// peers_json: JSON array of "host:port", one per rank (self ignored).
int32_t tft_coll_connect(void* h, int32_t rank, int32_t world,
                         const char* peers_json, int64_t timeout_ms);
void tft_coll_abort(void* h, const char* why);
// Link policy for `peer` (-1 = default). cls: "local"|"dcn"|"wan".
// n_streams 0 = engine default; q8 nonzero = prefer int8 wire. Call before
// tft_coll_connect; ignored afterwards.
void tft_coll_set_link(void* h, int32_t peer, const char* cls,
                       int64_t connect_ms, int64_t io_ms, int32_t n_streams,
                       int32_t q8);
int32_t tft_coll_allreduce(void* h, void* data, uint64_t count, int32_t dtype,
                           int32_t op, int64_t timeout_ms);
int32_t tft_coll_allreduce_q8(void* h, float* data, uint64_t count,
                              int64_t timeout_ms);
int32_t tft_coll_allgather(void* h, const char* meta, const void* data,
                           uint64_t nbytes, int64_t timeout_ms);
int32_t tft_coll_broadcast(void* h, const char* meta, const void* data,
                           uint64_t nbytes, int32_t root, int64_t timeout_ms);
int64_t tft_coll_result_meta_len(void* h, int32_t slot);
int32_t tft_coll_result_meta(void* h, int32_t slot, char* out, int64_t cap);
int64_t tft_coll_result_size(void* h, int32_t slot);
int32_t tft_coll_result_copy(void* h, int32_t slot, void* out, int64_t cap);
uint64_t tft_coll_bytes_tx(void* h);
uint64_t tft_coll_bytes_rx(void* h);
// Copies the last error into out (NUL-terminated, truncated to cap).
void tft_coll_last_error(void* h, char* out, int64_t cap);
// Tag stamped onto subsequent flight records (trace id + collective tag).
void tft_coll_set_trace(void* h, const char* tag);
// Highest flight-record seq allocated so far.
uint64_t tft_coll_fr_seq(void* h);
// JSON snapshot of flight records with seq > since_seq plus engine counters.
// Returns the full serialized length (excluding NUL); writes up to cap-1
// bytes plus a NUL when cap > 0 — callers re-call with a larger buffer when
// the return value >= cap. Safe concurrently with an in-flight collective.
int64_t tft_coll_fr_snapshot(void* h, uint64_t since_seq, char* out,
                             int64_t cap);
}
