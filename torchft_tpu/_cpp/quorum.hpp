// Pure quorum logic for the torchft-tpu control plane.
//
// Capability parity with the reference (tushar00jain/torchft):
//  - quorum_compute: src/lighthouse.rs:141-269 (heartbeat filter, fast quorum,
//    min_replicas floor, split-brain majority guard, join-timeout straggler
//    wait, shrink_only restriction).
//  - quorum_changed: src/lighthouse.rs:133-138 (sorted replica_ids compare).
//  - compute_quorum_results: src/manager.rs:489-624 (replica ranks, max-step
//    set, store primary selection, force_recover on init_sync, round-robin
//    recovery-source assignment offset by group rank).
// Pure functions; unit-tested in cpp_tests.cc.
#pragma once

#include <cstdint>
#include <map>
#include <optional>
#include <set>
#include <string>
#include <vector>

#include "json.hpp"

namespace tft {

struct QuorumMember {
  std::string replica_id;
  std::string address;        // manager control-plane address host:port
  std::string store_address;  // rendezvous store address host:port
  int64_t step = 0;
  int64_t world_size = 1;
  bool shrink_only = false;
  int64_t commit_failures = 0;
  Json data;  // opaque user payload (reference: QuorumMember.data JSON)

  Json to_json() const;
  static QuorumMember from_json(const Json& j);
};

struct Quorum {
  int64_t quorum_id = 0;
  std::vector<QuorumMember> participants;
  int64_t created_ms = 0;
  // Fencing epoch of the lighthouse instance that formed this quorum. A
  // warm-restarted primary keeps its epoch; a standby takeover bumps it, so
  // managers can reject quorums from a resurrected stale primary by
  // comparing against the max epoch they have ever accepted.
  int64_t epoch = 0;
  // Broadcast counter of the forming lighthouse (monotone across restarts
  // via the durable snapshot). (epoch, generation) orders every quorum the
  // fleet has ever seen, even across lighthouse identities.
  int64_t generation = 0;
  // Job namespace this quorum belongs to. Absent/empty on the wire maps to
  // "default" (back-compat with pre-namespace lighthouses and clients).
  std::string job = "default";

  Json to_json() const;
  static Quorum from_json(const Json& j);
};

struct LighthouseOpts {
  int64_t min_replicas = 1;
  int64_t join_timeout_ms = 60000;
  int64_t quorum_tick_ms = 100;
  int64_t heartbeat_timeout_ms = 5000;
  // /fleet.json staleness bound: a cached snapshot younger than this is
  // served without touching the fleet table; 0 rebuilds on every request
  // (the pre-caching behavior). The bin default comes from
  // TORCHFT_FLEET_SNAP_MS / --fleet-snap-ms; direct embedders (tests)
  // default to uncached for read-after-write determinism.
  int64_t fleet_snap_ms = 0;
  // Durable-state directory. When non-empty the lighthouse persists a tiny
  // fsync'd snapshot {epoch, quorum_id, generation} and restores it on boot,
  // so quorum ids and the fencing epoch stay strictly monotone across
  // restarts. Empty = fully in-memory (the pre-HA behavior).
  std::string state_dir;
  // Boot as a warm standby: absorb heartbeats (keeping fleet/participant
  // tables warm) but do not form or serve quorums until the first quorum
  // request arrives — managers only send quorum RPCs to their active
  // target, so a request here means the fleet failed over to us and we take
  // over with epoch = max(observed) + 1.
  bool standby = false;
  // Federation: this lighthouse's district name. With root_addr set, the
  // ACTIVE instance periodically reports a per-job rollup to the root over
  // the heartbeat piggyback channel, tagged with this name and its fencing
  // epoch. Both empty = federation off (the default, standalone behavior).
  std::string district;
  // Root lighthouse address ("host:port") the district rollups go to.
  std::string root_addr;
  // ---- failure-evidence plane ----
  // Master switch for the evidence-driven REACTION: cadence-aware hb-lapse
  // eviction plus signal-triggered quorum re-evaluation. Signals themselves
  // are always collected/journaled/exported; this only gates acting on
  // them (TORCHFT_LH_EVIDENCE / --evidence).
  bool evidence = true;
  // Cadence-aware hb-lapse eviction budget: a replica whose OPEN heartbeat
  // gap exceeds max(evict_floor_ms, evict_mult * declared cadence) is
  // treated as dead on evidence — dropped from the quorum tables so the
  // next quorum forms immediately, instead of waiting out the full
  // heartbeat_timeout_ms. Replicas that never declared a cadence (old
  // clients) are NEVER evicted early (wire back-compat).
  // (TORCHFT_LH_EVICT_MULT / TORCHFT_LH_EVICT_FLOOR_MS)
  int64_t evict_mult = 12;
  int64_t evict_floor_ms = 1000;
};

// Durable lighthouse snapshot: the only state that must survive a restart.
// Participant/fleet tables are rebuilt from the live heartbeat stream.
struct LighthouseDurable {
  int64_t epoch = 0;
  int64_t quorum_id = 0;
  int64_t generation = 0;
};

// Atomic (tmp + fsync + rename) snapshot save/load under state_dir. Load
// returns false when no snapshot exists or it cannot be parsed; save returns
// false on I/O failure. Pure file-format helpers, unit-tested in
// cpp_tests.cc; the threading/ownership policy lives in lighthouse.cc.
bool lh_state_save(const std::string& state_dir, const LighthouseDurable& d);
bool lh_state_load(const std::string& state_dir, LighthouseDurable* d);

// Mutable lighthouse state operated on by the tick loop.
struct LighthouseState {
  // replica_id -> (member info, joined_at ms)
  std::map<std::string, std::pair<QuorumMember, int64_t>> participants;
  // replica_id -> last heartbeat ms
  std::map<std::string, int64_t> heartbeats;
  // replica_id -> manager address carried by heartbeat messages. A replica
  // that heartbeats but never registered a quorum (e.g. wedged before its
  // first quorum RPC) is invisible in participants/prev_quorum; this map is
  // what lets an operator drain_all still reach it.
  std::map<std::string, std::string> heartbeat_addrs;
  // Replicas that drained via a graceful "leave": a tombstone so a heartbeat
  // already in flight when the leave landed can't resurrect the entry and
  // stall the survivors' next quorum on heartbeat expiry. Cleared when the
  // replica re-registers through a quorum request (a relaunch rejoining).
  std::set<std::string> left;
  std::optional<Quorum> prev_quorum;
  int64_t quorum_id = 0;
};

// Returns the members of a newly formed quorum, or nullopt (with a
// human-readable reason in *reason) if no quorum can form yet.
std::optional<std::vector<QuorumMember>> quorum_compute(
    int64_t now, const LighthouseState& state, const LighthouseOpts& opt,
    std::string* reason);

// True iff membership differs (compares sorted replica_ids only, like the
// reference — step/address changes alone don't bump the quorum id).
bool quorum_changed(const std::vector<QuorumMember>& a,
                    const std::vector<QuorumMember>& b);

// Per-rank recovery plan computed from a delivered quorum.
struct ManagerQuorumResult {
  int64_t quorum_id = 0;
  std::string recover_src_manager_address;  // empty if not healing
  std::optional<int64_t> recover_src_replica_rank;
  std::vector<int64_t> recover_dst_replica_ranks;
  std::string store_address;
  int64_t max_step = 0;
  std::optional<int64_t> max_replica_rank;
  int64_t max_world_size = 0;
  int64_t replica_rank = 0;
  int64_t replica_world_size = 0;
  bool heal = false;
  int64_t commit_failures = 0;

  Json to_json() const;
};

// group_rank: the caller's local rank inside its replica group (used to spread
// store-primary choice and recovery sources across local ranks).
// Returns nullopt if my_replica_id is not in the quorum.
std::optional<ManagerQuorumResult> compute_quorum_results(
    int64_t group_rank, const std::string& my_replica_id, const Quorum& quorum,
    bool init_sync, std::string* error);

}  // namespace tft
