#include "collectives.hpp"

#include <math.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <cstdio>

#include "chaos.hpp"
#include "net.hpp"

namespace tft {

namespace {

// Matches _net.set_buffer_sizes (Python side): 4 MiB socket buffers so a
// single DCN stream can keep a large window in flight.
constexpr int kSockBuf = 16 * 1024 * 1024;

void set_data_plane_opts(int fd) {
  setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &kSockBuf, sizeof(kSockBuf));
  setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &kSockBuf, sizeof(kSockBuf));
}

// ---------------------------------------------------------------------------
// Blockwise int8 quantization, numerically identical to
// torchft_tpu/collectives.py quantize_blockwise / dequantize_blockwise
// (bits=8): BLOCK=512 values per float32 scale, scale = absmax/127 (1.0 for
// all-zero blocks), round-half-even, clip to ±127, zero-padded tail block.
// All arithmetic stays in fp32 with the same operation order as the numpy
// path, so quantized wire bytes and reduced results agree bit-for-bit with
// the Python codec.
// ---------------------------------------------------------------------------

constexpr uint64_t kQBlock = 512;

void q8_quantize(const float* x, uint64_t n, uint64_t blocks, int8_t* q,
                 float* scales) {
  for (uint64_t b = 0; b < blocks; ++b) {
    const uint64_t lo = b * kQBlock;
    float absmax = 0.f;
    for (uint64_t j = 0; j < kQBlock; ++j) {
      const uint64_t idx = lo + j;
      const float v = idx < n ? x[idx] : 0.f;
      const float a = fabsf(v);
      if (a > absmax) absmax = a;
    }
    float s = absmax / 127.0f;
    if (absmax == 0.f) s = 1.0f;
    scales[b] = s;
    for (uint64_t j = 0; j < kQBlock; ++j) {
      const uint64_t idx = lo + j;
      const float v = idx < n ? x[idx] : 0.f;
      float t = nearbyintf(v / s);  // FE_TONEAREST = ties-to-even = np.rint
      if (t > 127.f) t = 127.f;
      if (t < -127.f) t = -127.f;
      q[lo + j] = static_cast<int8_t>(t);
    }
  }
}

// acc[i] += (float)q[i] * scale[block], same two fp32 roundings as the numpy
// dequantize-then-accumulate (mat *= scales; acc += mat).
void q8_accumulate(float* acc, const int8_t* q, const float* scales,
                   uint64_t blocks) {
  for (uint64_t b = 0; b < blocks; ++b) {
    const float s = scales[b];
    const uint64_t lo = b * kQBlock;
    for (uint64_t j = 0; j < kQBlock; ++j) {
      const float t = static_cast<float>(q[lo + j]) * s;
      acc[lo + j] += t;
    }
  }
}

template <typename T>
void reduce_into(T* dst, const T* src, uint64_t n, int32_t op) {
  if (op == TFT_OP_SUM) {
    for (uint64_t i = 0; i < n; ++i) dst[i] += src[i];
  } else if (op == TFT_OP_MAX) {
    for (uint64_t i = 0; i < n; ++i)
      dst[i] = dst[i] > src[i] ? dst[i] : src[i];
  } else {
    for (uint64_t i = 0; i < n; ++i)
      dst[i] = dst[i] < src[i] ? dst[i] : src[i];
  }
}

uint64_t dtype_size(int32_t dtype) {
  switch (dtype) {
    case TFT_DT_F32:
    case TFT_DT_I32:
      return 4;
    case TFT_DT_F64:
    case TFT_DT_I64:
      return 8;
  }
  return 0;
}

// np.array_split semantics over `n` units across `parts`: the first n%parts
// chunks get one extra unit. Identical to ProcessGroupSocket's chunking, so
// the uncompressed ring reduces the exact same slices.
uint64_t split_size(uint64_t n, int parts, int i) {
  return n / parts + (static_cast<uint64_t>(i) < n % parts ? 1 : 0);
}
uint64_t split_off(uint64_t n, int parts, int i) {
  const uint64_t base = n / parts;
  const uint64_t rem = n % parts;
  const uint64_t extra =
      std::min<uint64_t>(static_cast<uint64_t>(i), rem);
  return base * static_cast<uint64_t>(i) + extra;
}

}  // namespace

// ---------------------------------------------------------------------------
// TaskPool
// ---------------------------------------------------------------------------

TaskPool::TaskPool(int n_threads) {
  threads_.reserve(n_threads);
  for (int i = 0; i < n_threads; ++i)
    threads_.emplace_back([this] { worker(); });
}

TaskPool::~TaskPool() {
  {
    std::lock_guard<std::mutex> lk(mu_);
    stop_ = true;
  }
  cv_.notify_all();
  for (auto& t : threads_) t.join();
}

void TaskPool::submit(std::function<void()> fn) {
  {
    std::lock_guard<std::mutex> lk(mu_);
    queue_.push(std::move(fn));
  }
  cv_.notify_one();
}

void TaskPool::worker() {
  while (true) {
    std::function<void()> fn;
    {
      std::unique_lock<std::mutex> lk(mu_);
      cv_.wait(lk, [this] { return stop_ || !queue_.empty(); });
      // Drain remaining jobs even when stopping: queued jobs carry Waiter
      // pointers someone may still be blocked on; with the sockets shut
      // down they fail fast rather than hang.
      if (queue_.empty()) return;
      fn = std::move(queue_.front());
      queue_.pop();
    }
    fn();
  }
}

// ---------------------------------------------------------------------------
// Waiter: completion barrier for a batch of striped transfer jobs.
// ---------------------------------------------------------------------------

struct CollectiveEngine::Waiter {
  std::mutex mu;
  std::condition_variable cv;
  int pending = 0;
  bool ok = true;
  bool timed_out = false;
  std::string err;

  void add(int n) {
    std::lock_guard<std::mutex> lk(mu);
    pending += n;
  }
  void done(bool job_ok, bool job_timeout, const char* what) {
    std::lock_guard<std::mutex> lk(mu);
    if (!job_ok && ok) {
      ok = false;
      timed_out = job_timeout;
      err = what;
    }
    if (--pending == 0) cv.notify_all();
  }
  bool wait_all() {
    std::unique_lock<std::mutex> lk(mu);
    cv.wait(lk, [this] { return pending == 0; });
    return ok;
  }
};

// ---------------------------------------------------------------------------
// CollectiveEngine
// ---------------------------------------------------------------------------

CollectiveEngine::CollectiveEngine(int n_streams, int64_t pipeline_bytes,
                                   int fr_capacity)
    // 32 stripes bounds the per-peer alive bitmask (failover bookkeeping).
    : n_streams_(std::min(32, std::max(1, n_streams))),
      pipeline_bytes_(std::max<int64_t>(64 * 1024, pipeline_bytes)),
      fr_cap_(std::max(0, fr_capacity)) {
  if (fr_cap_ > 0) fr_ring_ = std::make_unique<FlightRec[]>(fr_cap_);
}

CollectiveEngine::~CollectiveEngine() {
  stopping_.store(true);
  abort("engine destroyed");
  if (janitor_.joinable()) janitor_.join();
  if (acceptor_.joinable()) acceptor_.join();
  pool_.reset();  // joins workers; queued jobs fail fast on shut-down fds
  close_all();
}

void CollectiveEngine::set_link_policy(int peer, const LinkPolicy& pol) {
  // Frozen once connect_mesh ran: the janitor and leg jobs read policies
  // without a lock.
  if (world_ != 0) return;
  LinkPolicy p = pol;
  if (p.n_streams > 32) p.n_streams = 32;
  if (p.connect_ms <= 0) p.connect_ms = 5000;
  if (peer < 0)
    default_policy_ = p;
  else
    link_policies_[peer] = p;
}

LinkPolicy CollectiveEngine::link_policy(int peer) const {
  auto it = link_policies_.find(peer);
  return it != link_policies_.end() ? it->second : default_policy_;
}

int CollectiveEngine::stripes_for(int peer) const {
  const int n = link_policy(peer).n_streams;
  return n > 0 ? std::min(n, 32) : n_streams_;
}

int CollectiveEngine::first_alive(int peer) const {
  // Header frames must ride a stripe both ends agree on, so this consults
  // the per-op frozen mask (see begin_op), like launch_group's partition.
  if (peer < 0 || peer >= static_cast<int>(op_mask_.size())) return 0;
  const uint32_t mask = op_mask_[peer];
  if (mask == 0) return -1;
  return __builtin_ctz(mask);
}

void CollectiveEngine::set_error(const std::string& msg) {
  std::lock_guard<std::mutex> lk(err_mu_);
  last_error_ = msg;
}

bool CollectiveEngine::fail(const std::string& msg) {
  // An abort reason beats the downstream I/O error it caused.
  if (!aborted_.load()) set_error(msg);
  return false;
}

std::string CollectiveEngine::last_error() const {
  std::lock_guard<std::mutex> lk(err_mu_);
  return last_error_;
}

int CollectiveEngine::listen(const std::string& host) {
  listen_fd_ = tcp_listen(host, 0, 256);
  if (listen_fd_ < 0) {
    set_error("data plane listen failed");
    return -1;
  }
  // Accepted sockets inherit the buffer sizes; must precede accept.
  set_data_plane_opts(listen_fd_);
  port_ = bound_port(listen_fd_);
  return port_;
}

bool CollectiveEngine::connect_mesh(int rank, int world,
                                    const std::vector<std::string>& peers,
                                    int64_t timeout_ms) {
  rank_ = rank;
  world_ = world;
  results_.assign(world, {});
  peer_fds_.assign(world, {});
  peer_addrs_ = peers;
  peer_counters_ = std::make_unique<PeerCounters[]>(world);
  alive_mask_ = std::make_unique<std::atomic<uint32_t>[]>(world);
  op_mask_.assign(world, 0);
  stripe_gibs_.assign(world, {});
  for (int p = 0; p < world; ++p) {
    const int ns = p == rank ? 0 : stripes_for(p);
    alive_mask_[p].store(ns >= 32 ? ~0u : ((1u << ns) - 1));
    op_mask_[p] = alive_mask_[p].load();
    stripe_gibs_[p].assign(ns, 0.0);
  }
  if (world <= 1) {
    pool_ = std::make_unique<TaskPool>(1);
    return true;
  }
  if (static_cast<int>(peers.size()) != world)
    return fail("connect_mesh: need one address per rank");
  const int64_t deadline = now_ms() + timeout_ms;
  // Deterministic full mesh (same shape as ProcessGroupSocket.configure):
  // connect the link's stripe count to every lower rank, accept from higher
  // ranks. Per-peer counts come from the link policy; both ends must be
  // configured symmetrically (the acceptor validates against ITS policy).
  for (int p = 0; p < rank; ++p) {
    std::string host;
    int port = 0;
    if (!split_host_port(peers[p], &host, &port))
      return fail("connect_mesh: bad peer address " + peers[p]);
    const LinkPolicy pol = link_policy(p);
    const int ns = stripes_for(p);
    peer_fds_[p].assign(ns, -1);
    for (int s = 0; s < ns; ++s) {
      const int64_t remaining = deadline - now_ms();
      if (remaining <= 0 || aborted_.load())
        return fail("timeout: data plane connect to rank " +
                    std::to_string(p));
      chaos::ScopedCtx cctx("data", std::to_string(p), "configure");
      int fd = tcp_connect_retry(host, port, remaining, pol.connect_ms);
      if (fd < 0)
        return fail("timeout: data plane connect to rank " +
                    std::to_string(p));
      set_data_plane_opts(fd);
      Json hello = Json::object();
      hello["rank"] = Json::of(static_cast<int64_t>(rank));
      hello["stripe"] = Json::of(static_cast<int64_t>(s));
      if (!send_frame(fd, hello.dump(), deadline - now_ms())) {
        close(fd);
        return fail("connect_mesh: hello to rank " + std::to_string(p) +
                    " failed");
      }
      peer_fds_[p][s] = fd;
    }
  }
  int expected = 0;
  for (int p = rank + 1; p < world; ++p) expected += stripes_for(p);
  for (int i = 0; i < expected; ++i) {
    const int64_t remaining = deadline - now_ms();
    if (remaining <= 0 || aborted_.load())
      return fail("timeout: data plane accept (" + std::to_string(i) + "/" +
                  std::to_string(expected) + ")");
    int fd = tcp_accept(listen_fd_, static_cast<int>(remaining));
    if (fd < 0)
      return fail("timeout: data plane accept (" + std::to_string(i) + "/" +
                  std::to_string(expected) + ")");
    set_data_plane_opts(fd);
    std::string raw;
    Json hello;
    if (!recv_frame(fd, &raw, std::max<int64_t>(1, deadline - now_ms())) ||
        !Json::parse(raw, &hello)) {
      close(fd);
      return fail("connect_mesh: bad hello frame");
    }
    // A janitor of an already-meshed higher rank can dial while we are
    // still collecting mesh sockets; don't let its rejoin hello consume a
    // mesh slot (the dial self-heals: no reply arrives, it retries later).
    if (hello.get("rejoin").as_int(0) != 0) {
      close(fd);
      --i;
      continue;
    }
    const int p = static_cast<int>(hello.get("rank").as_int(-1));
    const int s = static_cast<int>(hello.get("stripe").as_int(-1));
    if (p <= rank || p >= world || s < 0 || s >= stripes_for(p)) {
      close(fd);
      return fail("connect_mesh: hello from unexpected rank/stripe");
    }
    if (peer_fds_[p].empty()) peer_fds_[p].assign(stripes_for(p), -1);
    peer_fds_[p][s] = fd;
  }
  // Worst concurrent job count: the compressed alltoall runs two striped
  // sends + two striped recvs per peer at once. Undersizing the pool could
  // fill every worker with blocked senders and deadlock the mesh.
  int total_stripes = 0;
  for (int p = 0; p < world; ++p)
    if (p != rank) total_stripes += stripes_for(p);
  const int n_threads = std::min(64, std::max(2, 4 * total_stripes));
  pool_ = std::make_unique<TaskPool>(n_threads);
  // Stripe-rejoin plumbing: the connector side redials dead stripes, the
  // acceptor side absorbs those dials after the mesh is up.
  janitor_ = std::thread([this] { janitor_loop(); });
  acceptor_ = std::thread([this] { acceptor_loop(); });
  return true;
}

void CollectiveEngine::abort(const std::string& why) {
  if (aborted_.exchange(true)) return;
  set_error("aborted: " + why);
  // Shut down (not close) every socket: blocked reads/writes in pool jobs
  // and any caller mid-collective fail immediately; fds stay valid until
  // the destructor so no job can race a close/reuse.
  if (listen_fd_ >= 0) ::shutdown(listen_fd_, SHUT_RDWR);
  // reconn_mu_ also orders this against begin_op's fd installs so the scan
  // below never reads a peer_fds_ slot mid-write.
  std::lock_guard<std::mutex> lk(reconn_mu_);
  for (auto& fds : peer_fds_)
    for (int fd : fds)
      if (fd >= 0) ::shutdown(fd, SHUT_RDWR);
  for (const Staged& st : staged_) ::shutdown(st.fd, SHUT_RDWR);
  for (int fd : retired_fds_) ::shutdown(fd, SHUT_RDWR);
}

void CollectiveEngine::close_all() {
  if (listen_fd_ >= 0) close(listen_fd_);
  listen_fd_ = -1;
  for (auto& fds : peer_fds_)
    for (int fd : fds)
      if (fd >= 0) close(fd);
  peer_fds_.clear();
  for (const Staged& st : staged_) close(st.fd);
  staged_.clear();
  for (int fd : retired_fds_) close(fd);
  retired_fds_.clear();
}

// ---------------------------------------------------------------------------
// Stripe rejoin: janitor (connector side), acceptor, and activation
// ---------------------------------------------------------------------------

void CollectiveEngine::begin_op() {
  std::lock_guard<std::mutex> lk(reconn_mu_);
  ++op_seq_;
  for (auto it = staged_.begin(); it != staged_.end();) {
    if (it->activate_at > op_seq_) {
      ++it;
      continue;
    }
    // Both ends negotiated the same activation number, so (barring a dial
    // racing >8 collectives ahead — then the masks diverge and the next
    // transfer fails back into the abort/heal path) they swap the fd in
    // before the same collective and the stripe partitions agree again.
    const int old = peer_fds_[it->peer][it->stripe];
    if (old >= 0) {
      ::shutdown(old, SHUT_RDWR);
      retired_fds_.push_back(old);
    }
    peer_fds_[it->peer][it->stripe] = it->fd;
    alive_mask_[it->peer].fetch_or(1u << it->stripe);
    record_failover(it->peer, it->stripe, -1, /*dir=*/3, 0, "rejoin");
    it = staged_.erase(it);
  }
  // Freeze the partition mask for this collective. Groups launched during
  // the op must NOT re-read alive_mask_: a leg death observed by one
  // direction's epilogue mid-collective would repartition the other
  // direction's (or the next step's) launch on this end only, while the
  // peer — which observes the death on its own schedule — still partitions
  // over the old stripe set, desynchronizing the byte ranges. With a frozen
  // mask both ends keep launching legs on the dead stripe for the rest of
  // the op; those fail instantly (the fd is shut down) and the handoff
  // protocol re-routes them — identically on both ends.
  for (int p = 0; p < world_; ++p)
    op_mask_[p] = alive_mask_[p].load(std::memory_order_acquire);
}

bool CollectiveEngine::try_rejoin(int peer, int stripe) {
  if (peer < 0 || peer >= static_cast<int>(peer_addrs_.size())) return false;
  std::string host;
  int port = 0;
  if (!split_host_port(peer_addrs_[peer], &host, &port)) return false;
  const LinkPolicy pol = link_policy(peer);
  chaos::ScopedCtx cctx("data", std::to_string(peer), "rejoin");
  int fd = tcp_connect(host, port, std::max<int64_t>(1, pol.connect_ms));
  if (fd < 0) return false;
  set_data_plane_opts(fd);
  uint64_t my_seq;
  {
    std::lock_guard<std::mutex> lk(reconn_mu_);
    my_seq = op_seq_;
  }
  Json hello = Json::object();
  hello["rank"] = Json::of(static_cast<int64_t>(rank_));
  hello["stripe"] = Json::of(static_cast<int64_t>(stripe));
  hello["rejoin"] = Json::of(static_cast<int64_t>(1));
  hello["op_seq"] = Json::of(static_cast<int64_t>(my_seq));
  std::string raw;
  Json reply;
  if (!send_frame(fd, hello.dump(), 2000) || !recv_frame(fd, &raw, 5000) ||
      !Json::parse(raw, &reply)) {
    close(fd);  // never shared: safe to close directly
    return false;
  }
  const int64_t act = reply.get("op_seq").as_int(-1);
  if (act < 0) {
    close(fd);
    return false;
  }
  std::lock_guard<std::mutex> lk(reconn_mu_);
  staged_.push_back({peer, stripe, fd, static_cast<uint64_t>(act)});
  return true;
}

void CollectiveEngine::janitor_loop() {
  uint64_t attempt = 0;
  const std::string key = "stripe_rejoin:" + std::to_string(rank_);
  while (!stopping_.load() && !aborted_.load()) {
    // Seeded full-jitter backoff (~50ms..2s): deterministic under a chaos
    // seed, desynchronized across ranks by the key.
    const int64_t cap =
        std::min<int64_t>(2000, 200 << std::min<uint64_t>(attempt, 4));
    int64_t pause =
        50 + static_cast<int64_t>(chaos::backoff_unit(key, attempt) *
                                  static_cast<double>(cap));
    while (pause > 0 && !stopping_.load() && !aborted_.load()) {
      const int64_t step = std::min<int64_t>(50, pause);
      sleep_ms(step);
      pause -= step;
    }
    bool any_dead = false;
    for (int p = 0; p < rank_ && !stopping_.load() && !aborted_.load(); ++p) {
      const int ns = stripes_for(p);
      const uint32_t full = ns >= 32 ? ~0u : ((1u << ns) - 1);
      uint32_t dead = full & ~alive_mask_[p].load(std::memory_order_acquire);
      {
        std::lock_guard<std::mutex> lk(reconn_mu_);
        for (const Staged& st : staged_)
          if (st.peer == p) dead &= ~(1u << st.stripe);
      }
      while (dead != 0 && !stopping_.load() && !aborted_.load()) {
        const int s = __builtin_ctz(dead);
        dead &= ~(1u << s);
        any_dead = true;
        try_rejoin(p, s);
      }
    }
    attempt = any_dead ? attempt + 1 : 0;
  }
}

void CollectiveEngine::acceptor_loop() {
  while (!stopping_.load() && !aborted_.load()) {
    int fd = tcp_accept(listen_fd_, 250);
    if (fd < 0) continue;
    set_data_plane_opts(fd);
    std::string raw;
    Json hello;
    if (!recv_frame(fd, &raw, 2000) || !Json::parse(raw, &hello)) {
      close(fd);
      continue;
    }
    const int p = static_cast<int>(hello.get("rank").as_int(-1));
    const int s = static_cast<int>(hello.get("stripe").as_int(-1));
    if (hello.get("rejoin").as_int(0) != 1 || p <= rank_ || p >= world_ ||
        s < 0 || s >= stripes_for(p) ||
        (alive_mask_[p].load(std::memory_order_acquire) & (1u << s)) != 0) {
      close(fd);
      continue;
    }
    bool staged_ok = false;
    uint64_t act = 0;
    {
      std::lock_guard<std::mutex> lk(reconn_mu_);
      bool dup = false;
      for (const Staged& st : staged_)
        if (st.peer == p && st.stripe == s) {
          dup = true;
          break;
        }
      if (!dup) {
        const uint64_t theirs = static_cast<uint64_t>(
            std::max<int64_t>(0, hello.get("op_seq").as_int(0)));
        // +8 gives the reply a few collectives of headroom to cross the
        // wire before either end reaches the activation number.
        act = std::max(theirs, op_seq_) + 8;
        staged_.push_back({p, s, fd, act});
        staged_ok = true;
      }
    }
    if (!staged_ok) {
      close(fd);
      continue;
    }
    Json reply = Json::object();
    reply["op_seq"] = Json::of(static_cast<int64_t>(act));
    // A lost reply self-heals: the stripe activates here, comes up dead on
    // the next transfer, and fails over again.
    send_frame(fd, reply.dump(), 2000);
  }
}

void CollectiveEngine::record_failover(int peer, int stripe, int to_stripe,
                                       int dir, uint64_t moved_bytes,
                                       const char* tag) {
  std::lock_guard<std::mutex> lk(fo_mu_);
  FailoverEvent ev{};
  ev.seq = ++fo_seq_;
  ev.peer = static_cast<int16_t>(peer);
  ev.stripe = static_cast<int8_t>(stripe);
  ev.to_stripe = static_cast<int8_t>(to_stripe);
  ev.dir = static_cast<int8_t>(dir);
  ev.bytes = moved_bytes;
  ev.t_ns = now_realtime_ns();
  const size_t n = std::min(strlen(tag), sizeof(ev.tag) - 1);
  memcpy(ev.tag, tag, n);
  ev.tag[n] = '\0';
  failovers_.push_back(ev);
  if (failovers_.size() > 256) failovers_.pop_front();
}

// ---------------------------------------------------------------------------
// Flight recorder
// ---------------------------------------------------------------------------

void CollectiveEngine::set_trace(const std::string& tag) {
  std::lock_guard<std::mutex> lk(trace_mu_);
  const size_t n = std::min(tag.size(), sizeof(trace_tag_) - 1);
  memcpy(trace_tag_, tag.data(), n);
  trace_tag_[n] = '\0';
}

FlightRec* CollectiveEngine::fr_begin(int32_t op_code, int32_t dtype,
                                      int32_t red_op, uint64_t bytes) {
  if (fr_cap_ <= 0) return nullptr;
  const uint64_t seq = fr_seq_.fetch_add(1, std::memory_order_relaxed) + 1;
  if (seq > static_cast<uint64_t>(fr_cap_))
    fr_dropped_.fetch_add(1, std::memory_order_relaxed);
  FlightRec* rec = &fr_ring_[(seq - 1) % fr_cap_];
  std::lock_guard<std::mutex> fr_lk(fr_mu_);
  // seq=0 marks the slot torn while we reset it; a concurrent snapshot
  // skips it instead of reporting a half-old half-new record.
  rec->seq.store(0, std::memory_order_release);
  rec->op = op_code;
  rec->dtype = dtype;
  rec->red_op = red_op;
  rec->bytes = bytes;
  rec->t_start_ns = now_realtime_ns();
  rec->t_end_ns = 0;
  rec->cause[0] = '\0';
  {
    std::lock_guard<std::mutex> lk(trace_mu_);
    memcpy(rec->tag, trace_tag_, sizeof(rec->tag));
  }
  memset(rec->step_ns, 0, sizeof(rec->step_ns));
  rec->nsteps.store(0, std::memory_order_relaxed);
  rec->lane_n.store(0, std::memory_order_relaxed);
  rec->status.store(0, std::memory_order_relaxed);
  rec->seq.store(seq, std::memory_order_release);
  return rec;
}

void CollectiveEngine::fr_end(FlightRec* rec, bool ok) {
  if (rec == nullptr) return;
  const std::string err = ok ? std::string() : last_error();
  std::lock_guard<std::mutex> fr_lk(fr_mu_);
  rec->t_end_ns = now_realtime_ns();
  int32_t st = 1;
  if (!ok) {
    const size_t n = std::min(err.size(), sizeof(rec->cause) - 1);
    memcpy(rec->cause, err.data(), n);
    rec->cause[n] = '\0';
    if (aborted_.load())
      st = 4;
    else if (err.rfind("timeout", 0) == 0)
      st = 3;
    else
      st = 2;
  }
  rec->status.store(st, std::memory_order_release);
}

void CollectiveEngine::fr_step(FlightRec* rec) {
  if (rec == nullptr) return;
  const uint32_t i = rec->nsteps.fetch_add(1, std::memory_order_relaxed);
  if (i < kFrMaxSteps) {
    std::lock_guard<std::mutex> fr_lk(fr_mu_);
    rec->step_ns[i] = now_realtime_ns();
  }
}

void CollectiveEngine::fr_job(FlightRec* rec, int peer, int stripe, int dir,
                              uint64_t bytes, uint64_t t0_ns,
                              uint64_t spins_before, uint64_t reduce_ns) {
  const uint64_t t1 = now_realtime_ns();
  const uint64_t spins = net_spin_count() - spins_before;
  spin_total_.fetch_add(spins, std::memory_order_relaxed);
  if (peer_counters_ && peer >= 0 && peer < world_) {
    PeerCounters& pc = peer_counters_[peer];
    if (dir == 0) {
      pc.tx_bytes.fetch_add(bytes, std::memory_order_relaxed);
      pc.tx_busy_ns.fetch_add(t1 - t0_ns, std::memory_order_relaxed);
    } else {
      pc.rx_bytes.fetch_add(bytes, std::memory_order_relaxed);
      pc.rx_busy_ns.fetch_add(t1 - t0_ns, std::memory_order_relaxed);
    }
    pc.spins.fetch_add(spins, std::memory_order_relaxed);
  }
  // Per-stripe throughput EWMA (fr_snapshot "stripes"): slow-decaying so a
  // WAN drill can read steady-state per-link-class GiB/s off one snapshot.
  if (bytes > 0 && t1 > t0_ns && peer >= 0 &&
      peer < static_cast<int>(stripe_gibs_.size())) {
    const double gibs = static_cast<double>(bytes) /
                        (static_cast<double>(t1 - t0_ns) / 1e9) /
                        static_cast<double>(1ull << 30);
    std::lock_guard<std::mutex> lk(health_mu_);
    if (stripe >= 0 && stripe < static_cast<int>(stripe_gibs_[peer].size())) {
      double& e = stripe_gibs_[peer][stripe];
      e = e == 0.0 ? gibs : 0.8 * e + 0.2 * gibs;
    }
  }
  if (rec == nullptr) return;
  const uint32_t li = rec->lane_n.fetch_add(1, std::memory_order_relaxed);
  if (li >= static_cast<uint32_t>(kFrMaxLanes)) return;
  std::lock_guard<std::mutex> fr_lk(fr_mu_);
  FlightLane& L = rec->lanes[li];
  L.peer = static_cast<int16_t>(peer);
  L.stripe = static_cast<int8_t>(stripe);
  L.dir = static_cast<int8_t>(dir);
  L.spins = static_cast<uint32_t>(spins);
  L.bytes = bytes;
  L.t0_ns = t0_ns;
  L.t1_ns = t1;
  L.reduce_ns = reduce_ns;
}

namespace {

// Snapshot reads are serialized with writers by fr_mu_, but the strings are
// still caller-supplied byte buffers: keep only printable ASCII so the
// emitted JSON always parses.
std::string fr_sanitize(const char* s, size_t cap) {
  std::string out;
  for (size_t i = 0; i < cap && s[i] != '\0'; ++i) {
    const unsigned char c = static_cast<unsigned char>(s[i]);
    out += (c >= 0x20 && c < 0x7f) ? static_cast<char>(c) : '?';
  }
  return out;
}

const char* fr_op_name(int32_t op) {
  switch (op) {
    case 0:
      return "allreduce";
    case 1:
      return "allreduce_q8";
    case 2:
      return "allgather";
    case 3:
      return "broadcast";
  }
  return "unknown";
}

const char* fr_status_name(int32_t st) {
  switch (st) {
    case 0:
      return "in_flight";
    case 1:
      return "ok";
    case 2:
      return "error";
    case 3:
      return "timeout";
    case 4:
      return "aborted";
  }
  return "unknown";
}

const char* fr_dir_name(int8_t dir) {
  return dir == 0 ? "send" : (dir == 1 ? "recv" : "recv_reduce");
}

Json fr_u64(uint64_t v) { return Json::of(static_cast<int64_t>(v)); }

}  // namespace

std::string CollectiveEngine::fr_snapshot(uint64_t since_seq) const {
  Json root = Json::object();
  const uint64_t hi = fr_seq_.load(std::memory_order_acquire);
  root["seq"] = fr_u64(hi);
  root["capacity"] = Json::of(fr_cap_);
  root["dropped"] = fr_u64(fr_dropped_.load(std::memory_order_relaxed));
  root["spin_total"] = fr_u64(spin_total_.load(std::memory_order_relaxed));
  root["bytes_tx"] = fr_u64(bytes_tx_.load());
  root["bytes_rx"] = fr_u64(bytes_rx_.load());
  root["world"] = Json::of(world_);
  root["n_streams"] = Json::of(n_streams_);
  Json peers = Json::array();
  if (peer_counters_) {
    for (int p = 0; p < world_; ++p) {
      if (p == rank_) continue;
      const PeerCounters& pc = peer_counters_[p];
      Json jp = Json::object();
      jp["peer"] = Json::of(p);
      jp["tx_bytes"] = fr_u64(pc.tx_bytes.load(std::memory_order_relaxed));
      jp["rx_bytes"] = fr_u64(pc.rx_bytes.load(std::memory_order_relaxed));
      jp["tx_busy_ns"] = fr_u64(pc.tx_busy_ns.load(std::memory_order_relaxed));
      jp["rx_busy_ns"] = fr_u64(pc.rx_busy_ns.load(std::memory_order_relaxed));
      jp["spins"] = fr_u64(pc.spins.load(std::memory_order_relaxed));
      jp["link"] = Json::of(link_policy(p).cls);
      if (alive_mask_) {
        const uint32_t mask =
            alive_mask_[p].load(std::memory_order_relaxed);
        jp["alive_mask"] = Json::of(static_cast<int64_t>(mask));
        Json stripes = Json::array();
        const int ns = p < static_cast<int>(stripe_gibs_.size())
                           ? static_cast<int>(stripe_gibs_[p].size())
                           : 0;
        std::lock_guard<std::mutex> hl(health_mu_);
        for (int s = 0; s < ns; ++s) {
          Json js = Json::object();
          js["stripe"] = Json::of(s);
          js["alive"] = Json::of(static_cast<int64_t>((mask >> s) & 1));
          js["gibs"] = Json::of(stripe_gibs_[p][s]);
          stripes.push(std::move(js));
        }
        jp["stripes"] = std::move(stripes);
      }
      peers.push(std::move(jp));
    }
  }
  root["peers"] = std::move(peers);
  // Failover ring: every in-collective stripe handoff plus janitor rejoins.
  // Python drains by the monotonic per-event seq (journal stripe_failover).
  Json fos = Json::array();
  {
    std::lock_guard<std::mutex> fo_lk(fo_mu_);
    for (const auto& ev : failovers_) {
      Json je = Json::object();
      je["seq"] = Json::of(ev.seq);
      je["peer"] = Json::of(static_cast<int>(ev.peer));
      je["stripe"] = Json::of(static_cast<int>(ev.stripe));
      je["to_stripe"] = Json::of(static_cast<int>(ev.to_stripe));
      je["dir"] = Json::of(ev.dir == 3 ? "rejoin" : fr_dir_name(ev.dir));
      je["bytes"] = fr_u64(ev.bytes);
      je["t_ns"] = fr_u64(ev.t_ns);
      je["tag"] = Json::of(fr_sanitize(ev.tag, sizeof(ev.tag)));
      fos.push(std::move(je));
    }
  }
  root["failovers"] = std::move(fos);
  Json recs = Json::array();
  std::lock_guard<std::mutex> fr_lk(fr_mu_);
  if (fr_cap_ > 0 && hi > 0) {
    const uint64_t lo0 = hi > static_cast<uint64_t>(fr_cap_)
                             ? hi - static_cast<uint64_t>(fr_cap_)
                             : 0;
    for (uint64_t s = std::max(since_seq, lo0) + 1; s <= hi; ++s) {
      const FlightRec& r = fr_ring_[(s - 1) % fr_cap_];
      if (r.seq.load(std::memory_order_acquire) != s) continue;  // wrapped
      Json jr = Json::object();
      jr["seq"] = fr_u64(s);
      jr["op"] = Json::of(fr_op_name(r.op));
      jr["dtype"] = Json::of(r.dtype);
      jr["red_op"] = Json::of(r.red_op);
      jr["status"] =
          Json::of(fr_status_name(r.status.load(std::memory_order_acquire)));
      jr["bytes"] = fr_u64(r.bytes);
      jr["t_start_ns"] = fr_u64(r.t_start_ns);
      jr["t_end_ns"] = fr_u64(r.t_end_ns);
      jr["tag"] = Json::of(fr_sanitize(r.tag, sizeof(r.tag)));
      jr["cause"] = Json::of(fr_sanitize(r.cause, sizeof(r.cause)));
      const uint32_t nsteps = std::min<uint32_t>(
          r.nsteps.load(std::memory_order_relaxed), kFrMaxSteps);
      Json steps = Json::array();
      for (uint32_t i = 0; i < nsteps; ++i) steps.push(fr_u64(r.step_ns[i]));
      jr["step_ns"] = std::move(steps);
      const uint32_t claimed = r.lane_n.load(std::memory_order_relaxed);
      const uint32_t nlanes = std::min<uint32_t>(claimed, kFrMaxLanes);
      jr["lanes_dropped"] = Json::of(static_cast<int64_t>(claimed - nlanes));
      Json lanes = Json::array();
      for (uint32_t i = 0; i < nlanes; ++i) {
        const FlightLane& L = r.lanes[i];
        Json jl = Json::object();
        jl["peer"] = Json::of(static_cast<int>(L.peer));
        jl["stripe"] = Json::of(static_cast<int>(L.stripe));
        jl["dir"] = Json::of(fr_dir_name(L.dir));
        jl["spins"] = Json::of(static_cast<int64_t>(L.spins));
        jl["bytes"] = fr_u64(L.bytes);
        jl["t0_ns"] = fr_u64(L.t0_ns);
        jl["t1_ns"] = fr_u64(L.t1_ns);
        jl["reduce_ns"] = fr_u64(L.reduce_ns);
        lanes.push(std::move(jl));
      }
      jr["lanes"] = std::move(lanes);
      recs.push(std::move(jr));
    }
  }
  root["records"] = std::move(recs);
  return root.dump();
}

// ---------------------------------------------------------------------------
// Leg groups: striped transfer with in-collective failover
// ---------------------------------------------------------------------------

// All stripes of one (peer, direction) transfer. The group resolves its
// Waiter slot exactly once, from whichever pool thread finishes last; that
// thread also runs the failover epilogue inline (its group-mates are done,
// so the survivor sockets are quiescent and handoff bytes follow the
// normal stripe bytes in order).
struct CollectiveEngine::LegGroup {
  int peer = -1;
  int dir = 0;  // 0 send, 1 recv, 2 recv-reduce
  uint64_t esize = 1;
  int64_t deadline_ms = 0;
  Waiter* w = nullptr;
  FlightRec* rec = nullptr;
  // Transfer base. Send legs only read through it (the const_cast at
  // construction is confined to this struct).
  char* base = nullptr;
  // recv-reduce only.
  int32_t dtype = -1;
  int32_t op = -1;
  uint64_t block_elems = 0;
  uint32_t mask0 = 0;  // alive-mask snapshot the partition was built on
  std::mutex mu;
  int remaining = 0;
  struct Leg {
    int stripe = -1;
    int fd = -1;
    uint64_t uoff = 0;
    uint64_t ulen = 0;
    uint64_t done_units = 0;  // recv-reduce: units already folded into dst
    bool ok = false;
  };
  std::vector<Leg> legs;  // ascending stripe order (failover determinism)
};

namespace {

// Handoff frame: {magic u32, original stripe u32, ulen u64}. Lets the
// receiving end detect asymmetric failure detection (the ends disagreeing
// about which stripe died) instead of misparsing payload bytes.
constexpr uint32_t kHandoffMagic = 0x46414F56;  // "VOAF"

// Pipelined receive-reduce over one contiguous element span: consume the
// wire in sub-blocks and fold each into dst while the peer (and the kernel
// socket buffer) keeps the next sub-block in flight. `skip_elems` consumes
// but does not reduce the leading elements (handoff resends a failed
// stripe's FULL range; the live end must not re-reduce what it already
// folded). `done_out` reports consumed-and-folded progress even on failure
// so a later handoff knows where to resume reducing.
template <typename T>
bool recv_reduce_span(int fd, T* dst, uint64_t elems, int32_t op,
                      uint64_t block_elems, int64_t deadline_ms,
                      std::atomic<uint64_t>* bytes_rx, uint64_t skip_elems,
                      uint64_t* done_out, uint64_t* reduce_ns_out) {
  std::vector<T> scratch(std::min(elems, block_elems));
  uint64_t done = 0;
  uint64_t reduce_ns = 0;
  bool ok = true;
  while (done < elems) {
    const uint64_t m = std::min(block_elems, elems - done);
    const int64_t remaining = deadline_ms - now_ms();
    if (remaining <= 0 ||
        !read_exact(fd, reinterpret_cast<char*>(scratch.data()),
                    m * sizeof(T), remaining)) {
      ok = false;
      break;
    }
    *bytes_rx += m * sizeof(T);
    const uint64_t lo = std::max(done, skip_elems);
    if (lo < done + m) {
      // Per-chunk wire-vs-reduce split for the flight recorder: the lane's
      // total minus reduce_ns is time blocked on the wire.
      const uint64_t r0 = now_realtime_ns();
      reduce_into<T>(dst + lo, scratch.data() + (lo - done), done + m - lo,
                     op);
      reduce_ns += now_realtime_ns() - r0;
    }
    done += m;
  }
  if (done_out != nullptr) *done_out = done;
  if (reduce_ns_out != nullptr) *reduce_ns_out = reduce_ns;
  return ok;
}

bool recv_reduce_dispatch(int32_t dtype, int fd, char* base, uint64_t uoff,
                          uint64_t ulen, int32_t op, uint64_t block_elems,
                          int64_t deadline_ms,
                          std::atomic<uint64_t>* bytes_rx, uint64_t skip,
                          uint64_t* done_out, uint64_t* reduce_ns_out) {
  switch (dtype) {
    case TFT_DT_F32:
      return recv_reduce_span<float>(fd, reinterpret_cast<float*>(base) + uoff,
                                     ulen, op, block_elems, deadline_ms,
                                     bytes_rx, skip, done_out, reduce_ns_out);
    case TFT_DT_F64:
      return recv_reduce_span<double>(
          fd, reinterpret_cast<double*>(base) + uoff, ulen, op, block_elems,
          deadline_ms, bytes_rx, skip, done_out, reduce_ns_out);
    case TFT_DT_I32:
      return recv_reduce_span<int32_t>(
          fd, reinterpret_cast<int32_t*>(base) + uoff, ulen, op, block_elems,
          deadline_ms, bytes_rx, skip, done_out, reduce_ns_out);
    case TFT_DT_I64:
      return recv_reduce_span<int64_t>(
          fd, reinterpret_cast<int64_t*>(base) + uoff, ulen, op, block_elems,
          deadline_ms, bytes_rx, skip, done_out, reduce_ns_out);
  }
  return false;
}

}  // namespace

void CollectiveEngine::launch_group(std::shared_ptr<LegGroup> g,
                                    uint64_t units) {
  const int peer = g->peer;
  const int ns = stripes_for(peer);
  // Partition over the mask FROZEN at begin_op, not the live alive_mask_ —
  // see begin_op for why (mid-op repartitioning desyncs the two ends).
  const uint32_t mask = peer < static_cast<int>(op_mask_.size())
                            ? op_mask_[peer]
                            : (ns >= 32 ? ~0u : ((1u << ns) - 1));
  if (mask == 0) {
    g->w->add(1);
    g->w->done(false, false, "all stripes to peer dead");
    return;
  }
  g->mask0 = mask;
  // Partition over the LIVE stripes only (np.array_split semantics over the
  // survivor count). Both ends hold the same mask after a symmetric
  // failure, so their partitions agree without a control round-trip.
  std::vector<int> alive;
  alive.reserve(ns);
  for (int s = 0; s < ns; ++s)
    if (mask & (1u << s)) alive.push_back(s);
  const int parts = static_cast<int>(alive.size());
  for (int i = 0; i < parts; ++i) {
    const uint64_t ulen = split_size(units, parts, i);
    if (ulen == 0) continue;
    LegGroup::Leg leg;
    leg.stripe = alive[i];
    leg.fd = peer_fds_[peer][alive[i]];
    leg.uoff = split_off(units, parts, i);
    leg.ulen = ulen;
    g->legs.push_back(leg);
  }
  if (g->legs.empty()) return;
  g->remaining = static_cast<int>(g->legs.size());
  g->w->add(1);
  for (size_t i = 0; i < g->legs.size(); ++i)
    pool_->submit([this, g, i] { run_leg(g, i); });
}

void CollectiveEngine::run_leg(std::shared_ptr<LegGroup> g, size_t li) {
  LegGroup::Leg& leg = g->legs[li];
  const uint64_t t0 = now_realtime_ns();
  const uint64_t sp0 = net_spin_count();
  // Chaos scope: stall/partial_write/reset/throttle rules fire inside
  // write_all/read_exact, attributed to (peer rank, collective tag). The
  // "|s<stripe>" suffix lets a rule pin one stripe (match=|s2).
  chaos::ScopedCtx cctx(
      "data", std::to_string(g->peer),
      (g->rec != nullptr ? std::string(g->rec->tag) : std::string()) + "|s" +
          std::to_string(leg.stripe));
  // An io_ms budget fails a stalled stripe early enough for the group to
  // hand its range over; without one a stall rides to the collective
  // deadline and can only abort.
  const LinkPolicy pol = link_policy(g->peer);
  int64_t leg_deadline = g->deadline_ms;
  if (pol.io_ms > 0)
    leg_deadline = std::min(leg_deadline, now_ms() + pol.io_ms);
  const uint64_t len = leg.ulen * g->esize;
  uint64_t reduce_ns = 0;
  uint64_t done_units = 0;
  bool ok = false;
  const int64_t remaining = leg_deadline - now_ms();
  if (remaining > 0 && !aborted_.load()) {
    switch (g->dir) {
      case 0:
        ok = write_all(leg.fd, g->base + leg.uoff * g->esize, len, remaining);
        if (ok) bytes_tx_ += len;
        break;
      case 1:
        ok = read_exact(leg.fd, g->base + leg.uoff * g->esize, len,
                        remaining);
        if (ok) bytes_rx_ += len;
        break;
      default:
        ok = recv_reduce_dispatch(g->dtype, leg.fd, g->base, leg.uoff,
                                  leg.ulen, g->op, g->block_elems,
                                  leg_deadline, &bytes_rx_, /*skip=*/0,
                                  &done_units, &reduce_ns);
        break;
    }
  }
  fr_job(g->rec, g->peer, leg.stripe, g->dir, ok ? len : 0, t0, sp0,
         reduce_ns);
  bool last;
  {
    std::lock_guard<std::mutex> lk(g->mu);
    leg.ok = ok;
    leg.done_units = done_units;
    last = --g->remaining == 0;
  }
  if (last) leg_epilogue(std::move(g));
}

bool CollectiveEngine::handoff_leg(LegGroup& g, size_t li, int to) {
  LegGroup::Leg& leg = g.legs[li];
  const int fd = peer_fds_[g.peer][to];
  int64_t remaining = g.deadline_ms - now_ms();
  if (fd < 0 || remaining <= 0) return false;
  chaos::ScopedCtx cctx(
      "data", std::to_string(g.peer),
      (g.rec != nullptr ? std::string(g.rec->tag) : std::string()) +
          "|handoff");
  const uint64_t t0 = now_realtime_ns();
  const uint64_t sp0 = net_spin_count();
  const uint64_t len = leg.ulen * g.esize;
  char hdr[16];
  const uint32_t magic = kHandoffMagic;
  uint64_t reduce_ns = 0;
  bool ok = false;
  if (g.dir == 0) {
    const uint32_t s32 = static_cast<uint32_t>(leg.stripe);
    memcpy(hdr, &magic, 4);
    memcpy(hdr + 4, &s32, 4);
    memcpy(hdr + 8, &leg.ulen, 8);
    ok = write_all(fd, hdr, 16, remaining);
    remaining = g.deadline_ms - now_ms();
    ok = ok && remaining > 0 &&
         write_all(fd, g.base + leg.uoff * g.esize, len, remaining);
    if (ok) bytes_tx_ += len;
  } else {
    ok = read_exact(fd, hdr, 16, remaining);
    if (ok) {
      uint32_t m2 = 0, s2 = 0;
      uint64_t ul = 0;
      memcpy(&m2, hdr, 4);
      memcpy(&s2, hdr + 4, 4);
      memcpy(&ul, hdr + 8, 8);
      ok = m2 == magic && s2 == static_cast<uint32_t>(leg.stripe) &&
           ul == leg.ulen;
    }
    remaining = g.deadline_ms - now_ms();
    ok = ok && remaining > 0;
    if (ok) {
      if (g.dir == 1) {
        ok = read_exact(fd, g.base + leg.uoff * g.esize, len, remaining);
        if (ok) bytes_rx_ += len;
      } else {
        uint64_t done2 = 0;
        ok = recv_reduce_dispatch(g.dtype, fd, g.base, leg.uoff, leg.ulen,
                                  g.op, g.block_elems, g.deadline_ms,
                                  &bytes_rx_, /*skip=*/leg.done_units, &done2,
                                  &reduce_ns);
      }
    }
  }
  // The handoff shows up as a lane on the carrier stripe, so obs_trace
  // recovery lanes render it next to the leg it replaced.
  fr_job(g.rec, g.peer, to, g.dir, ok ? len : 0, t0, sp0, reduce_ns);
  return ok;
}

void CollectiveEngine::leg_epilogue(std::shared_ptr<LegGroup> g) {
  // No lock needed: remaining hit 0 under g->mu, publishing every leg.
  std::vector<size_t> failed;
  for (size_t i = 0; i < g->legs.size(); ++i)
    if (!g->legs[i].ok) failed.push_back(i);
  if (failed.empty()) {
    g->w->done(true, false, "");
    return;
  }
  uint32_t mask = g->mask0;
  for (size_t i : failed) {
    mask &= ~(1u << g->legs[i].stripe);
    alive_mask_[g->peer].fetch_and(~(1u << g->legs[i].stripe));
  }
  auto give_up = [&](const char* what) {
    g->w->done(false, now_ms() >= g->deadline_ms && !aborted_.load(), what);
  };
  if (aborted_.load()) {
    give_up("stripe transfer aborted");
    return;
  }
  if (mask == 0) {
    give_up("all stripes to peer dead");
    return;
  }
  if (g->deadline_ms - now_ms() <= 0) {
    give_up("timeout: stripe failover budget spent");
    return;
  }
  // Hand each failed leg's FULL range to the lowest live stripe. Both ends
  // walk their failed legs in ascending stripe order over the same mask, so
  // the carrier choice needs no control round-trip; if a carrier dies too,
  // both ends see it (symmetric detection) and cascade identically.
  const char* tag = g->rec != nullptr ? g->rec->tag : "";
  for (size_t i : failed) {
    bool moved = false;
    while (mask != 0) {
      const int to = __builtin_ctz(mask);
      if (handoff_leg(*g, i, to)) {
        record_failover(g->peer, g->legs[i].stripe, to, g->dir,
                        g->legs[i].ulen * g->esize, tag);
        moved = true;
        break;
      }
      mask &= ~(1u << to);
      alive_mask_[g->peer].fetch_and(~(1u << to));
    }
    if (!moved) {
      give_up(mask == 0 ? "all stripes to peer dead"
                        : "stripe handoff failed");
      return;
    }
  }
  g->w->done(true, false, "");
}

void CollectiveEngine::send_stripes(int peer, const char* data,
                                    uint64_t nbytes, uint64_t esize,
                                    int64_t deadline_ms, Waiter* w,
                                    FlightRec* rec) {
  if (nbytes == 0) return;
  auto g = std::make_shared<LegGroup>();
  g->peer = peer;
  g->dir = 0;
  g->esize = esize;
  g->deadline_ms = deadline_ms;
  g->w = w;
  g->rec = rec;
  g->base = const_cast<char*>(data);  // send legs never write through base
  launch_group(std::move(g), nbytes / esize);
}

void CollectiveEngine::recv_stripes(int peer, char* data, uint64_t nbytes,
                                    uint64_t esize, int64_t deadline_ms,
                                    Waiter* w, FlightRec* rec) {
  if (nbytes == 0) return;
  auto g = std::make_shared<LegGroup>();
  g->peer = peer;
  g->dir = 1;
  g->esize = esize;
  g->deadline_ms = deadline_ms;
  g->w = w;
  g->rec = rec;
  g->base = data;
  launch_group(std::move(g), nbytes / esize);
}

void CollectiveEngine::recv_reduce_stripes(int peer, void* dst, uint64_t count,
                                           int32_t dtype, int32_t op,
                                           int64_t deadline_ms, Waiter* w,
                                           FlightRec* rec) {
  if (count == 0) return;
  const uint64_t esize = dtype_size(dtype);
  auto g = std::make_shared<LegGroup>();
  g->peer = peer;
  g->dir = 2;
  g->esize = esize;
  g->deadline_ms = deadline_ms;
  g->w = w;
  g->rec = rec;
  g->base = static_cast<char*>(dst);
  g->dtype = dtype;
  g->op = op;
  g->block_elems =
      std::max<uint64_t>(1, static_cast<uint64_t>(pipeline_bytes_) / esize);
  launch_group(std::move(g), count);
}

template <typename T>
bool CollectiveEngine::ring_allreduce_t(T* data, uint64_t count, int32_t dtype,
                                        int32_t op, int64_t deadline_ms,
                                        FlightRec* rec) {
  const int ws = world_, r = rank_;
  const int right = (r + 1) % ws;
  const int left = (r - 1 + ws) % ws;
  auto coff = [&](int i) { return split_off(count, ws, i); };
  auto clen = [&](int i) { return split_size(count, ws, i); };
  auto ring_idx = [&](int i) { return ((i % ws) + ws) % ws; };
  // Reduce-scatter: after step k, chunk (r - k - 1) holds the partial
  // reduction of k+2 ranks; after ws-1 steps rank r owns the full reduction
  // of chunk (r + 1) % ws. Same schedule (and therefore the same
  // per-element accumulation order) as _ring_allreduce_flat.
  for (int step = 0; step < ws - 1; ++step) {
    const int si = ring_idx(r - step);
    const int ri = ring_idx(r - step - 1);
    Waiter w;
    send_stripes(right, reinterpret_cast<const char*>(data + coff(si)),
                 clen(si) * sizeof(T), sizeof(T), deadline_ms, &w, rec);
    recv_reduce_stripes(left, data + coff(ri), clen(ri), dtype, op,
                        deadline_ms, &w, rec);
    if (!w.wait_all())
      return fail((w.timed_out ? "timeout: " : "") + std::string(
                      "allreduce reduce-scatter step ") +
                  std::to_string(step) + ": " + w.err);
    fr_step(rec);
  }
  // Allgather: circulate the fully reduced chunks.
  for (int step = 0; step < ws - 1; ++step) {
    const int si = ring_idx(r - step + 1);
    const int ri = ring_idx(r - step);
    Waiter w;
    send_stripes(right, reinterpret_cast<const char*>(data + coff(si)),
                 clen(si) * sizeof(T), sizeof(T), deadline_ms, &w, rec);
    recv_stripes(left, reinterpret_cast<char*>(data + coff(ri)),
                 clen(ri) * sizeof(T), sizeof(T), deadline_ms, &w, rec);
    if (!w.wait_all())
      return fail((w.timed_out ? "timeout: " : "") +
                  std::string("allreduce allgather step ") +
                  std::to_string(step) + ": " + w.err);
    fr_step(rec);
  }
  return true;
}

bool CollectiveEngine::allreduce(void* data, uint64_t count, int32_t dtype,
                                 int32_t op, int64_t timeout_ms) {
  if (world_ <= 1) return true;
  if (aborted_.load()) return false;
  if (pool_ == nullptr) return fail("engine not connected");
  begin_op();
  const int64_t deadline = now_ms() + timeout_ms;
  FlightRec* rec = fr_begin(0, dtype, op, count * dtype_size(dtype));
  bool ok = false;
  switch (dtype) {
    case TFT_DT_F32:
      ok = ring_allreduce_t<float>(static_cast<float*>(data), count, dtype,
                                   op, deadline, rec);
      break;
    case TFT_DT_F64:
      ok = ring_allreduce_t<double>(static_cast<double*>(data), count, dtype,
                                    op, deadline, rec);
      break;
    case TFT_DT_I32:
      ok = ring_allreduce_t<int32_t>(static_cast<int32_t*>(data), count,
                                     dtype, op, deadline, rec);
      break;
    case TFT_DT_I64:
      ok = ring_allreduce_t<int64_t>(static_cast<int64_t*>(data), count,
                                     dtype, op, deadline, rec);
      break;
    default:
      ok = fail("allreduce: unsupported dtype code " + std::to_string(dtype));
      break;
  }
  fr_end(rec, ok);
  return ok;
}

bool CollectiveEngine::allreduce_q8(float* data, uint64_t count,
                                    int64_t timeout_ms) {
  if (world_ <= 1) return true;
  if (aborted_.load()) return false;
  if (pool_ == nullptr) return fail("engine not connected");
  begin_op();
  FlightRec* rec = fr_begin(1, TFT_DT_F32, TFT_OP_SUM, count * sizeof(float));
  const bool ok = allreduce_q8_inner(data, count, timeout_ms, rec);
  fr_end(rec, ok);
  return ok;
}

bool CollectiveEngine::allreduce_q8_inner(float* data, uint64_t count,
                                          int64_t timeout_ms, FlightRec* rec) {
  const int64_t deadline = now_ms() + timeout_ms;
  const int ws = world_, me = rank_;
  const uint64_t blocks = (count + kQBlock - 1) / kQBlock;

  // Quantize the full payload exactly once (collectives.py:586).
  std::vector<int8_t> q(blocks * kQBlock);
  std::vector<float> scales(blocks);
  q8_quantize(data, count, blocks, q.data(), scales.data());

  if (blocks < static_cast<uint64_t>(ws)) {
    // Tiny payload (fewer blocks than ranks): allgather-all fallback, no
    // chunking — mirrors _quantized_wire_pipeline's blocks < ws branch.
    std::string payload(reinterpret_cast<const char*>(scales.data()),
                        blocks * sizeof(float));
    payload.append(reinterpret_cast<const char*>(q.data()), q.size());
    if (!allgather("", payload.data(), payload.size(), timeout_ms))
      return false;
    std::vector<float> acc(blocks * kQBlock, 0.f);
    for (int p = 0; p < ws; ++p) {
      const char* src = p == me ? payload.data() : results_[p].second.data();
      q8_accumulate(acc.data(),
                    reinterpret_cast<const int8_t*>(src +
                                                    blocks * sizeof(float)),
                    reinterpret_cast<const float*>(src), blocks);
    }
    memcpy(data, acc.data(), count * sizeof(float));
    return true;
  }

  // Owner chunks: contiguous block-aligned np.array_split over blocks, so
  // each chunk owns whole scales (collectives.py:543).
  auto boff = [&](int i) { return split_off(blocks, ws, i); };
  auto blen = [&](int i) { return split_size(blocks, ws, i); };
  const uint64_t my_blocks = blen(me);

  // Each direction of each peer exchange must be one contiguous transfer:
  // two concurrent send_stripes to the same peer would race on the shared
  // per-stripe fds and interleave bytes. Wire layout per chunk of b blocks:
  // [b fp32 scales][b * kQBlock int8 codes].
  auto pack = [](const float* s, const int8_t* qv, uint64_t nb) {
    std::vector<char> buf(nb * (sizeof(float) + kQBlock));
    memcpy(buf.data(), s, nb * sizeof(float));
    memcpy(buf.data() + nb * sizeof(float), qv, nb * kQBlock);
    return buf;
  };
  auto unpack_s = [](const std::vector<char>& buf) {
    return reinterpret_cast<const float*>(buf.data());
  };
  auto unpack_q = [](const std::vector<char>& buf, uint64_t nb) {
    return reinterpret_cast<const int8_t*>(buf.data() + nb * sizeof(float));
  };

  // Phase 1: alltoall — send rank p its chunk of my quantized payload,
  // receive every peer's slice of MY chunk.
  std::vector<std::vector<char>> out(ws), in(ws);
  {
    Waiter w;
    for (int p = 0; p < ws; ++p) {
      if (p == me) continue;
      out[p] = pack(scales.data() + boff(p), q.data() + boff(p) * kQBlock,
                    blen(p));
      send_stripes(p, out[p].data(), out[p].size(), 1, deadline, &w, rec);
      in[p].resize(my_blocks * (sizeof(float) + kQBlock));
      recv_stripes(p, in[p].data(), in[p].size(), 1, deadline, &w, rec);
    }
    if (!w.wait_all())
      return fail((w.timed_out ? "timeout: " : "") +
                  std::string("q8 alltoall: ") + w.err);
    fr_step(rec);
  }

  // Local fp32 reduce of my chunk, rank order 0..ws-1 (alltoall output
  // order in _alltoall_chunk_reduce) — cross-replica bitwise identical.
  std::vector<float> acc(my_blocks * kQBlock, 0.f);
  for (int p = 0; p < ws; ++p) {
    const int8_t* src_q = p == me ? q.data() + boff(me) * kQBlock
                                  : unpack_q(in[p], my_blocks);
    const float* src_s = p == me ? scales.data() + boff(me) : unpack_s(in[p]);
    q8_accumulate(acc.data(), src_q, src_s, my_blocks);
  }

  // Requantize my reduced chunk (the second and final lossy step), then
  // allgather every rank's chunk.
  std::vector<int8_t> q2(my_blocks * kQBlock);
  std::vector<float> s2(my_blocks);
  q8_quantize(acc.data(), acc.size(), my_blocks, q2.data(), s2.data());
  const std::vector<char> mine = pack(s2.data(), q2.data(), my_blocks);
  std::vector<std::vector<char>> gathered(ws);
  {
    Waiter w;
    for (int p = 0; p < ws; ++p) {
      if (p == me) continue;
      send_stripes(p, mine.data(), mine.size(), 1, deadline, &w, rec);
      gathered[p].resize(blen(p) * (sizeof(float) + kQBlock));
      recv_stripes(p, gathered[p].data(), gathered[p].size(), 1, deadline, &w,
                   rec);
    }
    if (!w.wait_all())
      return fail((w.timed_out ? "timeout: " : "") +
                  std::string("q8 allgather: ") + w.err);
    fr_step(rec);
  }

  // Decode the assembled (q_final, s_final) straight into the caller's
  // buffer: data[i] = (float)q * scale, trimmed to count.
  for (int p = 0; p < ws; ++p) {
    const uint64_t nb = blen(p);
    const int8_t* fq = p == me ? q2.data() : unpack_q(gathered[p], nb);
    const float* fs = p == me ? s2.data() : unpack_s(gathered[p]);
    const uint64_t lo = boff(p) * kQBlock;
    for (uint64_t b = 0; b < nb; ++b) {
      const float s = fs[b];
      for (uint64_t j = 0; j < kQBlock; ++j) {
        const uint64_t idx = lo + b * kQBlock + j;
        if (idx >= count) break;
        data[idx] = static_cast<float>(fq[b * kQBlock + j]) * s;
      }
    }
  }
  return true;
}

bool CollectiveEngine::allgather(const std::string& meta, const void* data,
                                 uint64_t nbytes, int64_t timeout_ms) {
  for (auto& r : results_) r = {};
  if (world_ <= 1) return true;
  if (aborted_.load()) return false;
  if (pool_ == nullptr) return fail("engine not connected");
  begin_op();
  FlightRec* rec = fr_begin(2, -1, -1, nbytes);
  const bool ok = allgather_inner(meta, data, nbytes, timeout_ms, rec);
  fr_end(rec, ok);
  return ok;
}

bool CollectiveEngine::allgather_inner(const std::string& meta,
                                       const void* data, uint64_t nbytes,
                                       int64_t timeout_ms, FlightRec* rec) {
  const int64_t deadline = now_ms() + timeout_ms;
  // Phase A: fixed-size headers + meta on the first LIVE stripe of every
  // peer link (both ends agree on the alive mask, so they pick the same
  // one). The barrier before phase B guarantees the header precedes that
  // stripe's payload bytes on the same socket, and that every receive
  // buffer is sized.
  char hdr[12];
  const uint32_t mlen = static_cast<uint32_t>(meta.size());
  memcpy(hdr, &mlen, 4);
  memcpy(hdr + 4, &nbytes, 8);
  std::string hdr_full(hdr, 12);
  hdr_full += meta;
  {
    Waiter w;
    for (int p = 0; p < world_; ++p) {
      if (p == rank_) continue;
      const int fa = first_alive(p);
      const int fd0 = peer_fds_[p][fa < 0 ? 0 : fa];
      w.add(2);
      pool_->submit([this, fd0, &hdr_full, deadline, w_ptr = &w] {
        const int64_t remaining = deadline - now_ms();
        const bool ok = remaining > 0 && !aborted_.load() &&
                        write_all(fd0, hdr_full.data(), hdr_full.size(),
                                  remaining);
        if (ok) bytes_tx_ += hdr_full.size();
        w_ptr->done(ok, !ok && now_ms() >= deadline && !aborted_.load(),
                    "allgather header send failed");
      });
      pool_->submit([this, p, fd0, deadline, w_ptr = &w] {
        char h[12];
        int64_t remaining = deadline - now_ms();
        bool ok = remaining > 0 && !aborted_.load() &&
                  read_exact(fd0, h, 12, remaining);
        uint32_t peer_mlen = 0;
        uint64_t peer_nbytes = 0;
        if (ok) {
          memcpy(&peer_mlen, h, 4);
          memcpy(&peer_nbytes, h + 4, 8);
          ok = peer_mlen <= (64u << 20) && peer_nbytes <= (1ull << 40);
        }
        if (ok && peer_mlen > 0) {
          results_[p].first.resize(peer_mlen);
          remaining = deadline - now_ms();
          ok = remaining > 0 &&
               read_exact(fd0, &results_[p].first[0], peer_mlen, remaining);
        }
        if (ok) {
          results_[p].second.resize(peer_nbytes);
          bytes_rx_ += 12 + peer_mlen;
        }
        w_ptr->done(ok, !ok && now_ms() >= deadline && !aborted_.load(),
                    "allgather header recv failed");
      });
    }
    if (!w.wait_all())
      return fail((w.timed_out ? "timeout: " : "") +
                  std::string("allgather headers: ") + w.err);
    fr_step(rec);
  }
  // Phase B: striped payloads, all peers in full flight.
  {
    Waiter w;
    for (int p = 0; p < world_; ++p) {
      if (p == rank_) continue;
      send_stripes(p, static_cast<const char*>(data), nbytes, 1, deadline,
                   &w, rec);
      recv_stripes(p, results_[p].second.empty() ? nullptr
                                                 : &results_[p].second[0],
                   results_[p].second.size(), 1, deadline, &w, rec);
    }
    if (!w.wait_all())
      return fail((w.timed_out ? "timeout: " : "") +
                  std::string("allgather payloads: ") + w.err);
    fr_step(rec);
  }
  return true;
}

bool CollectiveEngine::broadcast(const std::string& meta, const void* data,
                                 uint64_t nbytes, int root,
                                 int64_t timeout_ms) {
  for (auto& r : results_) r = {};
  if (world_ <= 1) return true;
  if (aborted_.load()) return false;
  if (pool_ == nullptr) return fail("engine not connected");
  if (root < 0 || root >= world_)
    return fail("broadcast: bad root " + std::to_string(root));
  begin_op();
  FlightRec* rec = fr_begin(3, -1, -1, nbytes);
  const bool ok = broadcast_inner(meta, data, nbytes, root, timeout_ms, rec);
  fr_end(rec, ok);
  return ok;
}

bool CollectiveEngine::broadcast_inner(const std::string& meta,
                                       const void* data, uint64_t nbytes,
                                       int root, int64_t timeout_ms,
                                       FlightRec* rec) {
  const int64_t deadline = now_ms() + timeout_ms;
  if (rank_ == root) {
    char hdr[12];
    const uint32_t mlen = static_cast<uint32_t>(meta.size());
    const uint64_t pn = nbytes;
    memcpy(hdr, &mlen, 4);
    memcpy(hdr + 4, &pn, 8);
    std::string hdr_full(hdr, 12);
    hdr_full += meta;
    {
      // Headers first (barrier keeps them ahead of stripe-0 payload).
      Waiter w;
      for (int p = 0; p < world_; ++p) {
        if (p == rank_) continue;
        const int fa = first_alive(p);
        const int fd0 = peer_fds_[p][fa < 0 ? 0 : fa];
        w.add(1);
        pool_->submit([this, fd0, &hdr_full, deadline, w_ptr = &w] {
          const int64_t remaining = deadline - now_ms();
          const bool ok = remaining > 0 && !aborted_.load() &&
                          write_all(fd0, hdr_full.data(), hdr_full.size(),
                                    remaining);
          if (ok) bytes_tx_ += hdr_full.size();
          w_ptr->done(ok, !ok && now_ms() >= deadline && !aborted_.load(),
                      "broadcast header send failed");
        });
      }
      if (!w.wait_all())
        return fail((w.timed_out ? "timeout: " : "") +
                    std::string("broadcast headers: ") + w.err);
    }
    Waiter w;
    for (int p = 0; p < world_; ++p) {
      if (p == rank_) continue;
      send_stripes(p, static_cast<const char*>(data), nbytes, 1, deadline,
                   &w, rec);
    }
    if (!w.wait_all())
      return fail((w.timed_out ? "timeout: " : "") +
                  std::string("broadcast payload: ") + w.err);
    return true;
  }
  // Non-root: header from root on its first live stripe (caller thread),
  // then striped payload into the result slot.
  const int fa = first_alive(root);
  const int fd0 = peer_fds_[root][fa < 0 ? 0 : fa];
  char h[12];
  int64_t remaining = deadline - now_ms();
  if (remaining <= 0 || !read_exact(fd0, h, 12, remaining))
    return fail(now_ms() >= deadline && !aborted_.load()
                    ? "timeout: broadcast header"
                    : "broadcast header recv failed");
  uint32_t peer_mlen = 0;
  uint64_t peer_nbytes = 0;
  memcpy(&peer_mlen, h, 4);
  memcpy(&peer_nbytes, h + 4, 8);
  if (peer_mlen > (64u << 20) || peer_nbytes > (1ull << 40))
    return fail("broadcast: implausible header");
  if (peer_mlen > 0) {
    results_[root].first.resize(peer_mlen);
    remaining = deadline - now_ms();
    if (remaining <= 0 ||
        !read_exact(fd0, &results_[root].first[0], peer_mlen, remaining))
      return fail("broadcast meta recv failed");
  }
  bytes_rx_ += 12 + peer_mlen;
  results_[root].second.resize(peer_nbytes);
  Waiter w;
  recv_stripes(root,
               results_[root].second.empty() ? nullptr
                                             : &results_[root].second[0],
               peer_nbytes, 1, deadline, &w, rec);
  if (!w.wait_all())
    return fail((w.timed_out ? "timeout: " : "") +
                std::string("broadcast payload: ") + w.err);
  return true;
}

}  // namespace tft

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

namespace {

tft::CollectiveEngine* eng(void* h) {
  return static_cast<tft::CollectiveEngine*>(h);
}

int32_t rc_for(tft::CollectiveEngine* e, bool ok) {
  if (ok) return 0;
  return e->last_error().rfind("timeout", 0) == 0 ? 2 : 1;
}

}  // namespace

extern "C" {

void* tft_coll_create(int32_t n_streams, int64_t pipeline_bytes,
                      int32_t fr_capacity) {
  return new tft::CollectiveEngine(n_streams, pipeline_bytes, fr_capacity);
}

void tft_coll_destroy(void* h) { delete eng(h); }

int32_t tft_coll_listen(void* h, const char* host) {
  return eng(h)->listen(host ? host : "");
}

int32_t tft_coll_connect(void* h, int32_t rank, int32_t world,
                         const char* peers_json, int64_t timeout_ms) {
  tft::Json peers;
  std::vector<std::string> addrs;
  if (peers_json && tft::Json::parse(peers_json, &peers) &&
      peers.is_array()) {
    for (const auto& p : peers.arr) addrs.push_back(p.as_str());
  }
  return rc_for(eng(h),
                eng(h)->connect_mesh(rank, world, addrs, timeout_ms));
}

void tft_coll_abort(void* h, const char* why) {
  eng(h)->abort(why ? why : "abort");
}

void tft_coll_set_link(void* h, int32_t peer, const char* cls,
                       int64_t connect_ms, int64_t io_ms, int32_t n_streams,
                       int32_t q8) {
  tft::LinkPolicy pol;
  if (cls != nullptr && cls[0] != '\0') pol.cls = cls;
  pol.connect_ms = connect_ms;
  pol.io_ms = io_ms;
  pol.n_streams = n_streams;
  pol.q8 = q8 != 0;
  eng(h)->set_link_policy(peer, pol);
}

int32_t tft_coll_allreduce(void* h, void* data, uint64_t count, int32_t dtype,
                           int32_t op, int64_t timeout_ms) {
  return rc_for(eng(h), eng(h)->allreduce(data, count, dtype, op, timeout_ms));
}

int32_t tft_coll_allreduce_q8(void* h, float* data, uint64_t count,
                              int64_t timeout_ms) {
  return rc_for(eng(h), eng(h)->allreduce_q8(data, count, timeout_ms));
}

int32_t tft_coll_allgather(void* h, const char* meta, const void* data,
                           uint64_t nbytes, int64_t timeout_ms) {
  return rc_for(eng(h), eng(h)->allgather(meta ? meta : "", data, nbytes,
                                          timeout_ms));
}

int32_t tft_coll_broadcast(void* h, const char* meta, const void* data,
                           uint64_t nbytes, int32_t root, int64_t timeout_ms) {
  return rc_for(eng(h), eng(h)->broadcast(meta ? meta : "", data, nbytes,
                                          root, timeout_ms));
}

int64_t tft_coll_result_meta_len(void* h, int32_t slot) {
  auto* e = eng(h);
  if (slot < 0 || slot >= e->world()) return -1;
  return static_cast<int64_t>(e->result_meta(slot).size());
}

int32_t tft_coll_result_meta(void* h, int32_t slot, char* out, int64_t cap) {
  auto* e = eng(h);
  if (slot < 0 || slot >= e->world()) return 1;
  const std::string& m = e->result_meta(slot);
  if (static_cast<int64_t>(m.size()) > cap) return 1;
  memcpy(out, m.data(), m.size());
  return 0;
}

int64_t tft_coll_result_size(void* h, int32_t slot) {
  auto* e = eng(h);
  if (slot < 0 || slot >= e->world()) return -1;
  return static_cast<int64_t>(e->result_payload(slot).size());
}

int32_t tft_coll_result_copy(void* h, int32_t slot, void* out, int64_t cap) {
  auto* e = eng(h);
  if (slot < 0 || slot >= e->world()) return 1;
  const std::string& p = e->result_payload(slot);
  if (static_cast<int64_t>(p.size()) > cap) return 1;
  memcpy(out, p.data(), p.size());
  return 0;
}

uint64_t tft_coll_bytes_tx(void* h) { return eng(h)->bytes_tx(); }
uint64_t tft_coll_bytes_rx(void* h) { return eng(h)->bytes_rx(); }

void tft_coll_last_error(void* h, char* out, int64_t cap) {
  if (cap <= 0) return;
  const std::string e = eng(h)->last_error();
  const int64_t n = std::min<int64_t>(cap - 1, e.size());
  memcpy(out, e.data(), n);
  out[n] = '\0';
}

void tft_coll_set_trace(void* h, const char* tag) {
  eng(h)->set_trace(tag ? tag : "");
}

uint64_t tft_coll_fr_seq(void* h) { return eng(h)->fr_seq(); }

int64_t tft_coll_fr_snapshot(void* h, uint64_t since_seq, char* out,
                             int64_t cap) {
  const std::string snap = eng(h)->fr_snapshot(since_seq);
  if (out != nullptr && cap > 0) {
    const int64_t n = std::min<int64_t>(cap - 1, snap.size());
    memcpy(out, snap.data(), n);
    out[n] = '\0';
  }
  return static_cast<int64_t>(snap.size());
}

}  // extern "C"
