#include "collectives.hpp"

#include <math.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <cstdio>

#include "chaos.hpp"
#include "net.hpp"

namespace tft {

namespace {

// Matches _net.set_buffer_sizes (Python side): 4 MiB socket buffers so a
// single DCN stream can keep a large window in flight.
constexpr int kSockBuf = 16 * 1024 * 1024;

void set_data_plane_opts(int fd) {
  setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &kSockBuf, sizeof(kSockBuf));
  setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &kSockBuf, sizeof(kSockBuf));
}

// ---------------------------------------------------------------------------
// Blockwise int8 quantization, numerically identical to
// torchft_tpu/collectives.py quantize_blockwise / dequantize_blockwise
// (bits=8): BLOCK=512 values per float32 scale, scale = absmax/127 (1.0 for
// all-zero blocks), round-half-even, clip to ±127, zero-padded tail block.
// All arithmetic stays in fp32 with the same operation order as the numpy
// path, so quantized wire bytes and reduced results agree bit-for-bit with
// the Python codec.
// ---------------------------------------------------------------------------

constexpr uint64_t kQBlock = 512;

void q8_quantize(const float* x, uint64_t n, uint64_t blocks, int8_t* q,
                 float* scales) {
  for (uint64_t b = 0; b < blocks; ++b) {
    const uint64_t lo = b * kQBlock;
    float absmax = 0.f;
    for (uint64_t j = 0; j < kQBlock; ++j) {
      const uint64_t idx = lo + j;
      const float v = idx < n ? x[idx] : 0.f;
      const float a = fabsf(v);
      if (a > absmax) absmax = a;
    }
    float s = absmax / 127.0f;
    if (absmax == 0.f) s = 1.0f;
    scales[b] = s;
    for (uint64_t j = 0; j < kQBlock; ++j) {
      const uint64_t idx = lo + j;
      const float v = idx < n ? x[idx] : 0.f;
      float t = nearbyintf(v / s);  // FE_TONEAREST = ties-to-even = np.rint
      if (t > 127.f) t = 127.f;
      if (t < -127.f) t = -127.f;
      q[lo + j] = static_cast<int8_t>(t);
    }
  }
}

// acc[i] += (float)q[i] * scale[block], same two fp32 roundings as the numpy
// dequantize-then-accumulate (mat *= scales; acc += mat).
void q8_accumulate(float* acc, const int8_t* q, const float* scales,
                   uint64_t blocks) {
  for (uint64_t b = 0; b < blocks; ++b) {
    const float s = scales[b];
    const uint64_t lo = b * kQBlock;
    for (uint64_t j = 0; j < kQBlock; ++j) {
      const float t = static_cast<float>(q[lo + j]) * s;
      acc[lo + j] += t;
    }
  }
}

template <typename T>
void reduce_into(T* dst, const T* src, uint64_t n, int32_t op) {
  if (op == TFT_OP_SUM) {
    for (uint64_t i = 0; i < n; ++i) dst[i] += src[i];
  } else if (op == TFT_OP_MAX) {
    for (uint64_t i = 0; i < n; ++i)
      dst[i] = dst[i] > src[i] ? dst[i] : src[i];
  } else {
    for (uint64_t i = 0; i < n; ++i)
      dst[i] = dst[i] < src[i] ? dst[i] : src[i];
  }
}

uint64_t dtype_size(int32_t dtype) {
  switch (dtype) {
    case TFT_DT_F32:
    case TFT_DT_I32:
      return 4;
    case TFT_DT_F64:
    case TFT_DT_I64:
      return 8;
  }
  return 0;
}

// np.array_split semantics over `n` units across `parts`: the first n%parts
// chunks get one extra unit. Identical to ProcessGroupSocket's chunking, so
// the uncompressed ring reduces the exact same slices.
uint64_t split_size(uint64_t n, int parts, int i) {
  return n / parts + (static_cast<uint64_t>(i) < n % parts ? 1 : 0);
}
uint64_t split_off(uint64_t n, int parts, int i) {
  const uint64_t base = n / parts;
  const uint64_t rem = n % parts;
  const uint64_t extra =
      std::min<uint64_t>(static_cast<uint64_t>(i), rem);
  return base * static_cast<uint64_t>(i) + extra;
}

}  // namespace

// ---------------------------------------------------------------------------
// TaskPool
// ---------------------------------------------------------------------------

TaskPool::TaskPool(int n_threads) {
  threads_.reserve(n_threads);
  for (int i = 0; i < n_threads; ++i)
    threads_.emplace_back([this] { worker(); });
}

TaskPool::~TaskPool() {
  {
    std::lock_guard<std::mutex> lk(mu_);
    stop_ = true;
  }
  cv_.notify_all();
  for (auto& t : threads_) t.join();
}

void TaskPool::submit(std::function<void()> fn) {
  {
    std::lock_guard<std::mutex> lk(mu_);
    queue_.push(std::move(fn));
  }
  cv_.notify_one();
}

void TaskPool::worker() {
  while (true) {
    std::function<void()> fn;
    {
      std::unique_lock<std::mutex> lk(mu_);
      cv_.wait(lk, [this] { return stop_ || !queue_.empty(); });
      // Drain remaining jobs even when stopping: queued jobs carry Waiter
      // pointers someone may still be blocked on; with the sockets shut
      // down they fail fast rather than hang.
      if (queue_.empty()) return;
      fn = std::move(queue_.front());
      queue_.pop();
    }
    fn();
  }
}

// ---------------------------------------------------------------------------
// Waiter: completion barrier for a batch of striped transfer jobs.
// ---------------------------------------------------------------------------

struct CollectiveEngine::Waiter {
  std::mutex mu;
  std::condition_variable cv;
  int pending = 0;
  bool ok = true;
  bool timed_out = false;
  std::string err;

  void add(int n) {
    std::lock_guard<std::mutex> lk(mu);
    pending += n;
  }
  void done(bool job_ok, bool job_timeout, const char* what) {
    std::lock_guard<std::mutex> lk(mu);
    if (!job_ok && ok) {
      ok = false;
      timed_out = job_timeout;
      err = what;
    }
    if (--pending == 0) cv.notify_all();
  }
  bool wait_all() {
    std::unique_lock<std::mutex> lk(mu);
    cv.wait(lk, [this] { return pending == 0; });
    return ok;
  }
};

// ---------------------------------------------------------------------------
// CollectiveEngine
// ---------------------------------------------------------------------------

CollectiveEngine::CollectiveEngine(int n_streams, int64_t pipeline_bytes,
                                   int fr_capacity)
    : n_streams_(std::max(1, n_streams)),
      pipeline_bytes_(std::max<int64_t>(64 * 1024, pipeline_bytes)),
      fr_cap_(std::max(0, fr_capacity)) {
  if (fr_cap_ > 0) fr_ring_ = std::make_unique<FlightRec[]>(fr_cap_);
}

CollectiveEngine::~CollectiveEngine() {
  abort("engine destroyed");
  pool_.reset();  // joins workers; queued jobs fail fast on shut-down fds
  close_all();
}

void CollectiveEngine::set_error(const std::string& msg) {
  std::lock_guard<std::mutex> lk(err_mu_);
  last_error_ = msg;
}

bool CollectiveEngine::fail(const std::string& msg) {
  // An abort reason beats the downstream I/O error it caused.
  if (!aborted_.load()) set_error(msg);
  return false;
}

std::string CollectiveEngine::last_error() const {
  std::lock_guard<std::mutex> lk(err_mu_);
  return last_error_;
}

int CollectiveEngine::listen(const std::string& host) {
  listen_fd_ = tcp_listen(host, 0, 256);
  if (listen_fd_ < 0) {
    set_error("data plane listen failed");
    return -1;
  }
  // Accepted sockets inherit the buffer sizes; must precede accept.
  set_data_plane_opts(listen_fd_);
  port_ = bound_port(listen_fd_);
  return port_;
}

bool CollectiveEngine::connect_mesh(int rank, int world,
                                    const std::vector<std::string>& peers,
                                    int64_t timeout_ms) {
  rank_ = rank;
  world_ = world;
  results_.assign(world, {});
  peer_fds_.assign(world, {});
  peer_counters_ = std::make_unique<PeerCounters[]>(world);
  if (world <= 1) {
    pool_ = std::make_unique<TaskPool>(1);
    return true;
  }
  if (static_cast<int>(peers.size()) != world)
    return fail("connect_mesh: need one address per rank");
  const int64_t deadline = now_ms() + timeout_ms;
  // Deterministic full mesh (same shape as ProcessGroupSocket.configure):
  // connect n_streams sockets to every lower rank, accept from higher ranks.
  for (int p = 0; p < rank; ++p) {
    std::string host;
    int port = 0;
    if (!split_host_port(peers[p], &host, &port))
      return fail("connect_mesh: bad peer address " + peers[p]);
    peer_fds_[p].assign(n_streams_, -1);
    for (int s = 0; s < n_streams_; ++s) {
      const int64_t remaining = deadline - now_ms();
      if (remaining <= 0 || aborted_.load())
        return fail("timeout: data plane connect to rank " +
                    std::to_string(p));
      chaos::ScopedCtx cctx("data", std::to_string(p), "configure");
      int fd = tcp_connect_retry(host, port, remaining);
      if (fd < 0)
        return fail("timeout: data plane connect to rank " +
                    std::to_string(p));
      set_data_plane_opts(fd);
      Json hello = Json::object();
      hello["rank"] = Json::of(static_cast<int64_t>(rank));
      hello["stripe"] = Json::of(static_cast<int64_t>(s));
      if (!send_frame(fd, hello.dump(), deadline - now_ms())) {
        close(fd);
        return fail("connect_mesh: hello to rank " + std::to_string(p) +
                    " failed");
      }
      peer_fds_[p][s] = fd;
    }
  }
  const int expected = (world - 1 - rank) * n_streams_;
  for (int i = 0; i < expected; ++i) {
    const int64_t remaining = deadline - now_ms();
    if (remaining <= 0 || aborted_.load())
      return fail("timeout: data plane accept (" + std::to_string(i) + "/" +
                  std::to_string(expected) + ")");
    int fd = tcp_accept(listen_fd_, static_cast<int>(remaining));
    if (fd < 0)
      return fail("timeout: data plane accept (" + std::to_string(i) + "/" +
                  std::to_string(expected) + ")");
    set_data_plane_opts(fd);
    std::string raw;
    Json hello;
    if (!recv_frame(fd, &raw, std::max<int64_t>(1, deadline - now_ms())) ||
        !Json::parse(raw, &hello)) {
      close(fd);
      return fail("connect_mesh: bad hello frame");
    }
    const int p = static_cast<int>(hello.get("rank").as_int(-1));
    const int s = static_cast<int>(hello.get("stripe").as_int(-1));
    if (p <= rank || p >= world || s < 0 || s >= n_streams_) {
      close(fd);
      return fail("connect_mesh: hello from unexpected rank/stripe");
    }
    if (peer_fds_[p].empty()) peer_fds_[p].assign(n_streams_, -1);
    peer_fds_[p][s] = fd;
  }
  // Worst concurrent job count: the compressed alltoall runs two striped
  // sends + two striped recvs per peer at once. Undersizing the pool could
  // fill every worker with blocked senders and deadlock the mesh.
  const int n_threads =
      std::min(64, std::max(2, 4 * n_streams_ * (world - 1)));
  pool_ = std::make_unique<TaskPool>(n_threads);
  return true;
}

void CollectiveEngine::abort(const std::string& why) {
  if (aborted_.exchange(true)) return;
  set_error("aborted: " + why);
  // Shut down (not close) every socket: blocked reads/writes in pool jobs
  // and any caller mid-collective fail immediately; fds stay valid until
  // the destructor so no job can race a close/reuse.
  if (listen_fd_ >= 0) ::shutdown(listen_fd_, SHUT_RDWR);
  for (auto& fds : peer_fds_)
    for (int fd : fds)
      if (fd >= 0) ::shutdown(fd, SHUT_RDWR);
}

void CollectiveEngine::close_all() {
  if (listen_fd_ >= 0) close(listen_fd_);
  listen_fd_ = -1;
  for (auto& fds : peer_fds_)
    for (int fd : fds)
      if (fd >= 0) close(fd);
  peer_fds_.clear();
}

void CollectiveEngine::stripe_range(uint64_t units, int s, uint64_t* off,
                                    uint64_t* len) const {
  *off = split_off(units, n_streams_, s);
  *len = split_size(units, n_streams_, s);
}

// ---------------------------------------------------------------------------
// Flight recorder
// ---------------------------------------------------------------------------

void CollectiveEngine::set_trace(const std::string& tag) {
  std::lock_guard<std::mutex> lk(trace_mu_);
  const size_t n = std::min(tag.size(), sizeof(trace_tag_) - 1);
  memcpy(trace_tag_, tag.data(), n);
  trace_tag_[n] = '\0';
}

FlightRec* CollectiveEngine::fr_begin(int32_t op_code, int32_t dtype,
                                      int32_t red_op, uint64_t bytes) {
  if (fr_cap_ <= 0) return nullptr;
  const uint64_t seq = fr_seq_.fetch_add(1, std::memory_order_relaxed) + 1;
  if (seq > static_cast<uint64_t>(fr_cap_))
    fr_dropped_.fetch_add(1, std::memory_order_relaxed);
  FlightRec* rec = &fr_ring_[(seq - 1) % fr_cap_];
  std::lock_guard<std::mutex> fr_lk(fr_mu_);
  // seq=0 marks the slot torn while we reset it; a concurrent snapshot
  // skips it instead of reporting a half-old half-new record.
  rec->seq.store(0, std::memory_order_release);
  rec->op = op_code;
  rec->dtype = dtype;
  rec->red_op = red_op;
  rec->bytes = bytes;
  rec->t_start_ns = now_realtime_ns();
  rec->t_end_ns = 0;
  rec->cause[0] = '\0';
  {
    std::lock_guard<std::mutex> lk(trace_mu_);
    memcpy(rec->tag, trace_tag_, sizeof(rec->tag));
  }
  memset(rec->step_ns, 0, sizeof(rec->step_ns));
  rec->nsteps.store(0, std::memory_order_relaxed);
  rec->lane_n.store(0, std::memory_order_relaxed);
  rec->status.store(0, std::memory_order_relaxed);
  rec->seq.store(seq, std::memory_order_release);
  return rec;
}

void CollectiveEngine::fr_end(FlightRec* rec, bool ok) {
  if (rec == nullptr) return;
  const std::string err = ok ? std::string() : last_error();
  std::lock_guard<std::mutex> fr_lk(fr_mu_);
  rec->t_end_ns = now_realtime_ns();
  int32_t st = 1;
  if (!ok) {
    const size_t n = std::min(err.size(), sizeof(rec->cause) - 1);
    memcpy(rec->cause, err.data(), n);
    rec->cause[n] = '\0';
    if (aborted_.load())
      st = 4;
    else if (err.rfind("timeout", 0) == 0)
      st = 3;
    else
      st = 2;
  }
  rec->status.store(st, std::memory_order_release);
}

void CollectiveEngine::fr_step(FlightRec* rec) {
  if (rec == nullptr) return;
  const uint32_t i = rec->nsteps.fetch_add(1, std::memory_order_relaxed);
  if (i < kFrMaxSteps) {
    std::lock_guard<std::mutex> fr_lk(fr_mu_);
    rec->step_ns[i] = now_realtime_ns();
  }
}

void CollectiveEngine::fr_job(FlightRec* rec, int peer, int stripe, int dir,
                              uint64_t bytes, uint64_t t0_ns,
                              uint64_t spins_before, uint64_t reduce_ns) {
  const uint64_t t1 = now_realtime_ns();
  const uint64_t spins = net_spin_count() - spins_before;
  spin_total_.fetch_add(spins, std::memory_order_relaxed);
  if (peer_counters_ && peer >= 0 && peer < world_) {
    PeerCounters& pc = peer_counters_[peer];
    if (dir == 0) {
      pc.tx_bytes.fetch_add(bytes, std::memory_order_relaxed);
      pc.tx_busy_ns.fetch_add(t1 - t0_ns, std::memory_order_relaxed);
    } else {
      pc.rx_bytes.fetch_add(bytes, std::memory_order_relaxed);
      pc.rx_busy_ns.fetch_add(t1 - t0_ns, std::memory_order_relaxed);
    }
    pc.spins.fetch_add(spins, std::memory_order_relaxed);
  }
  if (rec == nullptr) return;
  const uint32_t li = rec->lane_n.fetch_add(1, std::memory_order_relaxed);
  if (li >= static_cast<uint32_t>(kFrMaxLanes)) return;
  std::lock_guard<std::mutex> fr_lk(fr_mu_);
  FlightLane& L = rec->lanes[li];
  L.peer = static_cast<int16_t>(peer);
  L.stripe = static_cast<int8_t>(stripe);
  L.dir = static_cast<int8_t>(dir);
  L.spins = static_cast<uint32_t>(spins);
  L.bytes = bytes;
  L.t0_ns = t0_ns;
  L.t1_ns = t1;
  L.reduce_ns = reduce_ns;
}

namespace {

// Snapshot reads are serialized with writers by fr_mu_, but the strings are
// still caller-supplied byte buffers: keep only printable ASCII so the
// emitted JSON always parses.
std::string fr_sanitize(const char* s, size_t cap) {
  std::string out;
  for (size_t i = 0; i < cap && s[i] != '\0'; ++i) {
    const unsigned char c = static_cast<unsigned char>(s[i]);
    out += (c >= 0x20 && c < 0x7f) ? static_cast<char>(c) : '?';
  }
  return out;
}

const char* fr_op_name(int32_t op) {
  switch (op) {
    case 0:
      return "allreduce";
    case 1:
      return "allreduce_q8";
    case 2:
      return "allgather";
    case 3:
      return "broadcast";
  }
  return "unknown";
}

const char* fr_status_name(int32_t st) {
  switch (st) {
    case 0:
      return "in_flight";
    case 1:
      return "ok";
    case 2:
      return "error";
    case 3:
      return "timeout";
    case 4:
      return "aborted";
  }
  return "unknown";
}

const char* fr_dir_name(int8_t dir) {
  return dir == 0 ? "send" : (dir == 1 ? "recv" : "recv_reduce");
}

Json fr_u64(uint64_t v) { return Json::of(static_cast<int64_t>(v)); }

}  // namespace

std::string CollectiveEngine::fr_snapshot(uint64_t since_seq) const {
  Json root = Json::object();
  const uint64_t hi = fr_seq_.load(std::memory_order_acquire);
  root["seq"] = fr_u64(hi);
  root["capacity"] = Json::of(fr_cap_);
  root["dropped"] = fr_u64(fr_dropped_.load(std::memory_order_relaxed));
  root["spin_total"] = fr_u64(spin_total_.load(std::memory_order_relaxed));
  root["bytes_tx"] = fr_u64(bytes_tx_.load());
  root["bytes_rx"] = fr_u64(bytes_rx_.load());
  root["world"] = Json::of(world_);
  root["n_streams"] = Json::of(n_streams_);
  Json peers = Json::array();
  if (peer_counters_) {
    for (int p = 0; p < world_; ++p) {
      if (p == rank_) continue;
      const PeerCounters& pc = peer_counters_[p];
      Json jp = Json::object();
      jp["peer"] = Json::of(p);
      jp["tx_bytes"] = fr_u64(pc.tx_bytes.load(std::memory_order_relaxed));
      jp["rx_bytes"] = fr_u64(pc.rx_bytes.load(std::memory_order_relaxed));
      jp["tx_busy_ns"] = fr_u64(pc.tx_busy_ns.load(std::memory_order_relaxed));
      jp["rx_busy_ns"] = fr_u64(pc.rx_busy_ns.load(std::memory_order_relaxed));
      jp["spins"] = fr_u64(pc.spins.load(std::memory_order_relaxed));
      peers.push(std::move(jp));
    }
  }
  root["peers"] = std::move(peers);
  Json recs = Json::array();
  std::lock_guard<std::mutex> fr_lk(fr_mu_);
  if (fr_cap_ > 0 && hi > 0) {
    const uint64_t lo0 = hi > static_cast<uint64_t>(fr_cap_)
                             ? hi - static_cast<uint64_t>(fr_cap_)
                             : 0;
    for (uint64_t s = std::max(since_seq, lo0) + 1; s <= hi; ++s) {
      const FlightRec& r = fr_ring_[(s - 1) % fr_cap_];
      if (r.seq.load(std::memory_order_acquire) != s) continue;  // wrapped
      Json jr = Json::object();
      jr["seq"] = fr_u64(s);
      jr["op"] = Json::of(fr_op_name(r.op));
      jr["dtype"] = Json::of(r.dtype);
      jr["red_op"] = Json::of(r.red_op);
      jr["status"] =
          Json::of(fr_status_name(r.status.load(std::memory_order_acquire)));
      jr["bytes"] = fr_u64(r.bytes);
      jr["t_start_ns"] = fr_u64(r.t_start_ns);
      jr["t_end_ns"] = fr_u64(r.t_end_ns);
      jr["tag"] = Json::of(fr_sanitize(r.tag, sizeof(r.tag)));
      jr["cause"] = Json::of(fr_sanitize(r.cause, sizeof(r.cause)));
      const uint32_t nsteps = std::min<uint32_t>(
          r.nsteps.load(std::memory_order_relaxed), kFrMaxSteps);
      Json steps = Json::array();
      for (uint32_t i = 0; i < nsteps; ++i) steps.push(fr_u64(r.step_ns[i]));
      jr["step_ns"] = std::move(steps);
      const uint32_t claimed = r.lane_n.load(std::memory_order_relaxed);
      const uint32_t nlanes = std::min<uint32_t>(claimed, kFrMaxLanes);
      jr["lanes_dropped"] = Json::of(static_cast<int64_t>(claimed - nlanes));
      Json lanes = Json::array();
      for (uint32_t i = 0; i < nlanes; ++i) {
        const FlightLane& L = r.lanes[i];
        Json jl = Json::object();
        jl["peer"] = Json::of(static_cast<int>(L.peer));
        jl["stripe"] = Json::of(static_cast<int>(L.stripe));
        jl["dir"] = Json::of(fr_dir_name(L.dir));
        jl["spins"] = Json::of(static_cast<int64_t>(L.spins));
        jl["bytes"] = fr_u64(L.bytes);
        jl["t0_ns"] = fr_u64(L.t0_ns);
        jl["t1_ns"] = fr_u64(L.t1_ns);
        jl["reduce_ns"] = fr_u64(L.reduce_ns);
        lanes.push(std::move(jl));
      }
      jr["lanes"] = std::move(lanes);
      recs.push(std::move(jr));
    }
  }
  root["records"] = std::move(recs);
  return root.dump();
}

void CollectiveEngine::send_stripes(int peer, const char* data,
                                    uint64_t nbytes, uint64_t esize,
                                    int64_t deadline_ms, Waiter* w,
                                    FlightRec* rec) {
  if (nbytes == 0) return;
  const uint64_t units = nbytes / esize;
  for (int s = 0; s < n_streams_; ++s) {
    uint64_t uoff, ulen;
    stripe_range(units, s, &uoff, &ulen);
    if (ulen == 0) continue;
    const int fd = peer_fds_[peer][s];
    const char* p = data + uoff * esize;
    const uint64_t len = ulen * esize;
    w->add(1);
    pool_->submit([this, peer, s, fd, p, len, deadline_ms, w, rec] {
      const uint64_t t0 = now_realtime_ns();
      const uint64_t sp0 = net_spin_count();
      // Chaos scope: stall/partial_write/reset rules fire inside write_all,
      // attributed to (peer rank, collective tag).
      chaos::ScopedCtx cctx(
          "data", std::to_string(peer),
          rec != nullptr ? std::string(rec->tag) : std::string());
      const int64_t remaining = deadline_ms - now_ms();
      const bool ok = remaining > 0 && !aborted_.load() &&
                      write_all(fd, p, len, remaining);
      if (ok) bytes_tx_ += len;
      fr_job(rec, peer, s, /*dir=*/0, ok ? len : 0, t0, sp0, 0);
      w->done(ok, !ok && now_ms() >= deadline_ms && !aborted_.load(),
              "stripe send failed");
    });
  }
}

void CollectiveEngine::recv_stripes(int peer, char* data, uint64_t nbytes,
                                    uint64_t esize, int64_t deadline_ms,
                                    Waiter* w, FlightRec* rec) {
  if (nbytes == 0) return;
  const uint64_t units = nbytes / esize;
  for (int s = 0; s < n_streams_; ++s) {
    uint64_t uoff, ulen;
    stripe_range(units, s, &uoff, &ulen);
    if (ulen == 0) continue;
    const int fd = peer_fds_[peer][s];
    char* p = data + uoff * esize;
    const uint64_t len = ulen * esize;
    w->add(1);
    pool_->submit([this, peer, s, fd, p, len, deadline_ms, w, rec] {
      const uint64_t t0 = now_realtime_ns();
      const uint64_t sp0 = net_spin_count();
      chaos::ScopedCtx cctx(
          "data", std::to_string(peer),
          rec != nullptr ? std::string(rec->tag) : std::string());
      const int64_t remaining = deadline_ms - now_ms();
      const bool ok = remaining > 0 && !aborted_.load() &&
                      read_exact(fd, p, len, remaining);
      if (ok) bytes_rx_ += len;
      fr_job(rec, peer, s, /*dir=*/1, ok ? len : 0, t0, sp0, 0);
      w->done(ok, !ok && now_ms() >= deadline_ms && !aborted_.load(),
              "stripe recv failed");
    });
  }
}

namespace {

// Pipelined receive-reduce for one stripe: consume the wire in sub-blocks
// and fold each into dst while the peer (and the kernel socket buffer)
// keeps the next sub-block in flight — the "reduce chunk k while chunk k+1
// is on the wire" half of the double buffer.
template <typename T>
bool recv_reduce_stripe(int fd, T* dst, uint64_t elems, int32_t op,
                        uint64_t block_elems, int64_t deadline_ms,
                        std::atomic<uint64_t>* bytes_rx,
                        uint64_t* reduce_ns_out) {
  std::vector<T> scratch(std::min(elems, block_elems));
  uint64_t done = 0;
  uint64_t reduce_ns = 0;
  while (done < elems) {
    const uint64_t m = std::min(block_elems, elems - done);
    const int64_t remaining = deadline_ms - now_ms();
    if (remaining <= 0) return false;
    if (!read_exact(fd, reinterpret_cast<char*>(scratch.data()),
                    m * sizeof(T), remaining))
      return false;
    *bytes_rx += m * sizeof(T);
    // Per-chunk wire-vs-reduce split for the flight recorder: the lane's
    // total minus reduce_ns is time blocked on the wire.
    const uint64_t r0 = now_realtime_ns();
    reduce_into<T>(dst + done, scratch.data(), m, op);
    reduce_ns += now_realtime_ns() - r0;
    done += m;
  }
  if (reduce_ns_out != nullptr) *reduce_ns_out = reduce_ns;
  return true;
}

}  // namespace

void CollectiveEngine::recv_reduce_stripes(int peer, void* dst, uint64_t count,
                                           int32_t dtype, int32_t op,
                                           int64_t deadline_ms, Waiter* w,
                                           FlightRec* rec) {
  if (count == 0) return;
  const uint64_t esize = dtype_size(dtype);
  const uint64_t block_elems =
      std::max<uint64_t>(1, static_cast<uint64_t>(pipeline_bytes_) / esize);
  for (int s = 0; s < n_streams_; ++s) {
    uint64_t uoff, ulen;
    stripe_range(count, s, &uoff, &ulen);
    if (ulen == 0) continue;
    const int fd = peer_fds_[peer][s];
    w->add(1);
    pool_->submit([this, peer, s, fd, dst, uoff, ulen, esize, dtype, op,
                   block_elems, deadline_ms, w, rec] {
      const uint64_t t0 = now_realtime_ns();
      const uint64_t sp0 = net_spin_count();
      chaos::ScopedCtx cctx(
          "data", std::to_string(peer),
          rec != nullptr ? std::string(rec->tag) : std::string());
      uint64_t reduce_ns = 0;
      bool ok = false;
      if (!aborted_.load()) {
        switch (dtype) {
          case TFT_DT_F32:
            ok = recv_reduce_stripe<float>(fd, static_cast<float*>(dst) + uoff,
                                           ulen, op, block_elems, deadline_ms,
                                           &bytes_rx_, &reduce_ns);
            break;
          case TFT_DT_F64:
            ok = recv_reduce_stripe<double>(
                fd, static_cast<double*>(dst) + uoff, ulen, op, block_elems,
                deadline_ms, &bytes_rx_, &reduce_ns);
            break;
          case TFT_DT_I32:
            ok = recv_reduce_stripe<int32_t>(
                fd, static_cast<int32_t*>(dst) + uoff, ulen, op, block_elems,
                deadline_ms, &bytes_rx_, &reduce_ns);
            break;
          case TFT_DT_I64:
            ok = recv_reduce_stripe<int64_t>(
                fd, static_cast<int64_t*>(dst) + uoff, ulen, op, block_elems,
                deadline_ms, &bytes_rx_, &reduce_ns);
            break;
        }
      }
      fr_job(rec, peer, s, /*dir=*/2, ok ? ulen * esize : 0, t0, sp0,
             reduce_ns);
      w->done(ok, !ok && now_ms() >= deadline_ms && !aborted_.load(),
              "stripe recv-reduce failed");
    });
  }
}

template <typename T>
bool CollectiveEngine::ring_allreduce_t(T* data, uint64_t count, int32_t dtype,
                                        int32_t op, int64_t deadline_ms,
                                        FlightRec* rec) {
  const int ws = world_, r = rank_;
  const int right = (r + 1) % ws;
  const int left = (r - 1 + ws) % ws;
  auto coff = [&](int i) { return split_off(count, ws, i); };
  auto clen = [&](int i) { return split_size(count, ws, i); };
  auto ring_idx = [&](int i) { return ((i % ws) + ws) % ws; };
  // Reduce-scatter: after step k, chunk (r - k - 1) holds the partial
  // reduction of k+2 ranks; after ws-1 steps rank r owns the full reduction
  // of chunk (r + 1) % ws. Same schedule (and therefore the same
  // per-element accumulation order) as _ring_allreduce_flat.
  for (int step = 0; step < ws - 1; ++step) {
    const int si = ring_idx(r - step);
    const int ri = ring_idx(r - step - 1);
    Waiter w;
    send_stripes(right, reinterpret_cast<const char*>(data + coff(si)),
                 clen(si) * sizeof(T), sizeof(T), deadline_ms, &w, rec);
    recv_reduce_stripes(left, data + coff(ri), clen(ri), dtype, op,
                        deadline_ms, &w, rec);
    if (!w.wait_all())
      return fail((w.timed_out ? "timeout: " : "") + std::string(
                      "allreduce reduce-scatter step ") +
                  std::to_string(step) + ": " + w.err);
    fr_step(rec);
  }
  // Allgather: circulate the fully reduced chunks.
  for (int step = 0; step < ws - 1; ++step) {
    const int si = ring_idx(r - step + 1);
    const int ri = ring_idx(r - step);
    Waiter w;
    send_stripes(right, reinterpret_cast<const char*>(data + coff(si)),
                 clen(si) * sizeof(T), sizeof(T), deadline_ms, &w, rec);
    recv_stripes(left, reinterpret_cast<char*>(data + coff(ri)),
                 clen(ri) * sizeof(T), sizeof(T), deadline_ms, &w, rec);
    if (!w.wait_all())
      return fail((w.timed_out ? "timeout: " : "") +
                  std::string("allreduce allgather step ") +
                  std::to_string(step) + ": " + w.err);
    fr_step(rec);
  }
  return true;
}

bool CollectiveEngine::allreduce(void* data, uint64_t count, int32_t dtype,
                                 int32_t op, int64_t timeout_ms) {
  if (world_ <= 1) return true;
  if (aborted_.load()) return false;
  if (pool_ == nullptr) return fail("engine not connected");
  const int64_t deadline = now_ms() + timeout_ms;
  FlightRec* rec = fr_begin(0, dtype, op, count * dtype_size(dtype));
  bool ok = false;
  switch (dtype) {
    case TFT_DT_F32:
      ok = ring_allreduce_t<float>(static_cast<float*>(data), count, dtype,
                                   op, deadline, rec);
      break;
    case TFT_DT_F64:
      ok = ring_allreduce_t<double>(static_cast<double*>(data), count, dtype,
                                    op, deadline, rec);
      break;
    case TFT_DT_I32:
      ok = ring_allreduce_t<int32_t>(static_cast<int32_t*>(data), count,
                                     dtype, op, deadline, rec);
      break;
    case TFT_DT_I64:
      ok = ring_allreduce_t<int64_t>(static_cast<int64_t*>(data), count,
                                     dtype, op, deadline, rec);
      break;
    default:
      ok = fail("allreduce: unsupported dtype code " + std::to_string(dtype));
      break;
  }
  fr_end(rec, ok);
  return ok;
}

bool CollectiveEngine::allreduce_q8(float* data, uint64_t count,
                                    int64_t timeout_ms) {
  if (world_ <= 1) return true;
  if (aborted_.load()) return false;
  if (pool_ == nullptr) return fail("engine not connected");
  FlightRec* rec = fr_begin(1, TFT_DT_F32, TFT_OP_SUM, count * sizeof(float));
  const bool ok = allreduce_q8_inner(data, count, timeout_ms, rec);
  fr_end(rec, ok);
  return ok;
}

bool CollectiveEngine::allreduce_q8_inner(float* data, uint64_t count,
                                          int64_t timeout_ms, FlightRec* rec) {
  const int64_t deadline = now_ms() + timeout_ms;
  const int ws = world_, me = rank_;
  const uint64_t blocks = (count + kQBlock - 1) / kQBlock;

  // Quantize the full payload exactly once (collectives.py:586).
  std::vector<int8_t> q(blocks * kQBlock);
  std::vector<float> scales(blocks);
  q8_quantize(data, count, blocks, q.data(), scales.data());

  if (blocks < static_cast<uint64_t>(ws)) {
    // Tiny payload (fewer blocks than ranks): allgather-all fallback, no
    // chunking — mirrors _quantized_wire_pipeline's blocks < ws branch.
    std::string payload(reinterpret_cast<const char*>(scales.data()),
                        blocks * sizeof(float));
    payload.append(reinterpret_cast<const char*>(q.data()), q.size());
    if (!allgather("", payload.data(), payload.size(), timeout_ms))
      return false;
    std::vector<float> acc(blocks * kQBlock, 0.f);
    for (int p = 0; p < ws; ++p) {
      const char* src = p == me ? payload.data() : results_[p].second.data();
      q8_accumulate(acc.data(),
                    reinterpret_cast<const int8_t*>(src +
                                                    blocks * sizeof(float)),
                    reinterpret_cast<const float*>(src), blocks);
    }
    memcpy(data, acc.data(), count * sizeof(float));
    return true;
  }

  // Owner chunks: contiguous block-aligned np.array_split over blocks, so
  // each chunk owns whole scales (collectives.py:543).
  auto boff = [&](int i) { return split_off(blocks, ws, i); };
  auto blen = [&](int i) { return split_size(blocks, ws, i); };
  const uint64_t my_blocks = blen(me);

  // Each direction of each peer exchange must be one contiguous transfer:
  // two concurrent send_stripes to the same peer would race on the shared
  // per-stripe fds and interleave bytes. Wire layout per chunk of b blocks:
  // [b fp32 scales][b * kQBlock int8 codes].
  auto pack = [](const float* s, const int8_t* qv, uint64_t nb) {
    std::vector<char> buf(nb * (sizeof(float) + kQBlock));
    memcpy(buf.data(), s, nb * sizeof(float));
    memcpy(buf.data() + nb * sizeof(float), qv, nb * kQBlock);
    return buf;
  };
  auto unpack_s = [](const std::vector<char>& buf) {
    return reinterpret_cast<const float*>(buf.data());
  };
  auto unpack_q = [](const std::vector<char>& buf, uint64_t nb) {
    return reinterpret_cast<const int8_t*>(buf.data() + nb * sizeof(float));
  };

  // Phase 1: alltoall — send rank p its chunk of my quantized payload,
  // receive every peer's slice of MY chunk.
  std::vector<std::vector<char>> out(ws), in(ws);
  {
    Waiter w;
    for (int p = 0; p < ws; ++p) {
      if (p == me) continue;
      out[p] = pack(scales.data() + boff(p), q.data() + boff(p) * kQBlock,
                    blen(p));
      send_stripes(p, out[p].data(), out[p].size(), 1, deadline, &w, rec);
      in[p].resize(my_blocks * (sizeof(float) + kQBlock));
      recv_stripes(p, in[p].data(), in[p].size(), 1, deadline, &w, rec);
    }
    if (!w.wait_all())
      return fail((w.timed_out ? "timeout: " : "") +
                  std::string("q8 alltoall: ") + w.err);
    fr_step(rec);
  }

  // Local fp32 reduce of my chunk, rank order 0..ws-1 (alltoall output
  // order in _alltoall_chunk_reduce) — cross-replica bitwise identical.
  std::vector<float> acc(my_blocks * kQBlock, 0.f);
  for (int p = 0; p < ws; ++p) {
    const int8_t* src_q = p == me ? q.data() + boff(me) * kQBlock
                                  : unpack_q(in[p], my_blocks);
    const float* src_s = p == me ? scales.data() + boff(me) : unpack_s(in[p]);
    q8_accumulate(acc.data(), src_q, src_s, my_blocks);
  }

  // Requantize my reduced chunk (the second and final lossy step), then
  // allgather every rank's chunk.
  std::vector<int8_t> q2(my_blocks * kQBlock);
  std::vector<float> s2(my_blocks);
  q8_quantize(acc.data(), acc.size(), my_blocks, q2.data(), s2.data());
  const std::vector<char> mine = pack(s2.data(), q2.data(), my_blocks);
  std::vector<std::vector<char>> gathered(ws);
  {
    Waiter w;
    for (int p = 0; p < ws; ++p) {
      if (p == me) continue;
      send_stripes(p, mine.data(), mine.size(), 1, deadline, &w, rec);
      gathered[p].resize(blen(p) * (sizeof(float) + kQBlock));
      recv_stripes(p, gathered[p].data(), gathered[p].size(), 1, deadline, &w,
                   rec);
    }
    if (!w.wait_all())
      return fail((w.timed_out ? "timeout: " : "") +
                  std::string("q8 allgather: ") + w.err);
    fr_step(rec);
  }

  // Decode the assembled (q_final, s_final) straight into the caller's
  // buffer: data[i] = (float)q * scale, trimmed to count.
  for (int p = 0; p < ws; ++p) {
    const uint64_t nb = blen(p);
    const int8_t* fq = p == me ? q2.data() : unpack_q(gathered[p], nb);
    const float* fs = p == me ? s2.data() : unpack_s(gathered[p]);
    const uint64_t lo = boff(p) * kQBlock;
    for (uint64_t b = 0; b < nb; ++b) {
      const float s = fs[b];
      for (uint64_t j = 0; j < kQBlock; ++j) {
        const uint64_t idx = lo + b * kQBlock + j;
        if (idx >= count) break;
        data[idx] = static_cast<float>(fq[b * kQBlock + j]) * s;
      }
    }
  }
  return true;
}

bool CollectiveEngine::allgather(const std::string& meta, const void* data,
                                 uint64_t nbytes, int64_t timeout_ms) {
  for (auto& r : results_) r = {};
  if (world_ <= 1) return true;
  if (aborted_.load()) return false;
  if (pool_ == nullptr) return fail("engine not connected");
  FlightRec* rec = fr_begin(2, -1, -1, nbytes);
  const bool ok = allgather_inner(meta, data, nbytes, timeout_ms, rec);
  fr_end(rec, ok);
  return ok;
}

bool CollectiveEngine::allgather_inner(const std::string& meta,
                                       const void* data, uint64_t nbytes,
                                       int64_t timeout_ms, FlightRec* rec) {
  const int64_t deadline = now_ms() + timeout_ms;
  // Phase A: fixed-size headers + meta on stripe 0 of every peer link. The
  // barrier before phase B guarantees the header precedes stripe-0 payload
  // bytes on the same socket, and that every receive buffer is sized.
  char hdr[12];
  const uint32_t mlen = static_cast<uint32_t>(meta.size());
  memcpy(hdr, &mlen, 4);
  memcpy(hdr + 4, &nbytes, 8);
  std::string hdr_full(hdr, 12);
  hdr_full += meta;
  {
    Waiter w;
    for (int p = 0; p < world_; ++p) {
      if (p == rank_) continue;
      const int fd0 = peer_fds_[p][0];
      w.add(2);
      pool_->submit([this, fd0, &hdr_full, deadline, w_ptr = &w] {
        const int64_t remaining = deadline - now_ms();
        const bool ok = remaining > 0 && !aborted_.load() &&
                        write_all(fd0, hdr_full.data(), hdr_full.size(),
                                  remaining);
        if (ok) bytes_tx_ += hdr_full.size();
        w_ptr->done(ok, !ok && now_ms() >= deadline && !aborted_.load(),
                    "allgather header send failed");
      });
      pool_->submit([this, p, fd0, deadline, w_ptr = &w] {
        char h[12];
        int64_t remaining = deadline - now_ms();
        bool ok = remaining > 0 && !aborted_.load() &&
                  read_exact(fd0, h, 12, remaining);
        uint32_t peer_mlen = 0;
        uint64_t peer_nbytes = 0;
        if (ok) {
          memcpy(&peer_mlen, h, 4);
          memcpy(&peer_nbytes, h + 4, 8);
          ok = peer_mlen <= (64u << 20) && peer_nbytes <= (1ull << 40);
        }
        if (ok && peer_mlen > 0) {
          results_[p].first.resize(peer_mlen);
          remaining = deadline - now_ms();
          ok = remaining > 0 &&
               read_exact(fd0, &results_[p].first[0], peer_mlen, remaining);
        }
        if (ok) {
          results_[p].second.resize(peer_nbytes);
          bytes_rx_ += 12 + peer_mlen;
        }
        w_ptr->done(ok, !ok && now_ms() >= deadline && !aborted_.load(),
                    "allgather header recv failed");
      });
    }
    if (!w.wait_all())
      return fail((w.timed_out ? "timeout: " : "") +
                  std::string("allgather headers: ") + w.err);
    fr_step(rec);
  }
  // Phase B: striped payloads, all peers in full flight.
  {
    Waiter w;
    for (int p = 0; p < world_; ++p) {
      if (p == rank_) continue;
      send_stripes(p, static_cast<const char*>(data), nbytes, 1, deadline,
                   &w, rec);
      recv_stripes(p, results_[p].second.empty() ? nullptr
                                                 : &results_[p].second[0],
                   results_[p].second.size(), 1, deadline, &w, rec);
    }
    if (!w.wait_all())
      return fail((w.timed_out ? "timeout: " : "") +
                  std::string("allgather payloads: ") + w.err);
    fr_step(rec);
  }
  return true;
}

bool CollectiveEngine::broadcast(const std::string& meta, const void* data,
                                 uint64_t nbytes, int root,
                                 int64_t timeout_ms) {
  for (auto& r : results_) r = {};
  if (world_ <= 1) return true;
  if (aborted_.load()) return false;
  if (pool_ == nullptr) return fail("engine not connected");
  if (root < 0 || root >= world_)
    return fail("broadcast: bad root " + std::to_string(root));
  FlightRec* rec = fr_begin(3, -1, -1, nbytes);
  const bool ok = broadcast_inner(meta, data, nbytes, root, timeout_ms, rec);
  fr_end(rec, ok);
  return ok;
}

bool CollectiveEngine::broadcast_inner(const std::string& meta,
                                       const void* data, uint64_t nbytes,
                                       int root, int64_t timeout_ms,
                                       FlightRec* rec) {
  const int64_t deadline = now_ms() + timeout_ms;
  if (rank_ == root) {
    char hdr[12];
    const uint32_t mlen = static_cast<uint32_t>(meta.size());
    const uint64_t pn = nbytes;
    memcpy(hdr, &mlen, 4);
    memcpy(hdr + 4, &pn, 8);
    std::string hdr_full(hdr, 12);
    hdr_full += meta;
    {
      // Headers first (barrier keeps them ahead of stripe-0 payload).
      Waiter w;
      for (int p = 0; p < world_; ++p) {
        if (p == rank_) continue;
        const int fd0 = peer_fds_[p][0];
        w.add(1);
        pool_->submit([this, fd0, &hdr_full, deadline, w_ptr = &w] {
          const int64_t remaining = deadline - now_ms();
          const bool ok = remaining > 0 && !aborted_.load() &&
                          write_all(fd0, hdr_full.data(), hdr_full.size(),
                                    remaining);
          if (ok) bytes_tx_ += hdr_full.size();
          w_ptr->done(ok, !ok && now_ms() >= deadline && !aborted_.load(),
                      "broadcast header send failed");
        });
      }
      if (!w.wait_all())
        return fail((w.timed_out ? "timeout: " : "") +
                    std::string("broadcast headers: ") + w.err);
    }
    Waiter w;
    for (int p = 0; p < world_; ++p) {
      if (p == rank_) continue;
      send_stripes(p, static_cast<const char*>(data), nbytes, 1, deadline,
                   &w, rec);
    }
    if (!w.wait_all())
      return fail((w.timed_out ? "timeout: " : "") +
                  std::string("broadcast payload: ") + w.err);
    return true;
  }
  // Non-root: header from root on stripe 0 (caller thread), then striped
  // payload into the result slot.
  const int fd0 = peer_fds_[root][0];
  char h[12];
  int64_t remaining = deadline - now_ms();
  if (remaining <= 0 || !read_exact(fd0, h, 12, remaining))
    return fail(now_ms() >= deadline && !aborted_.load()
                    ? "timeout: broadcast header"
                    : "broadcast header recv failed");
  uint32_t peer_mlen = 0;
  uint64_t peer_nbytes = 0;
  memcpy(&peer_mlen, h, 4);
  memcpy(&peer_nbytes, h + 4, 8);
  if (peer_mlen > (64u << 20) || peer_nbytes > (1ull << 40))
    return fail("broadcast: implausible header");
  if (peer_mlen > 0) {
    results_[root].first.resize(peer_mlen);
    remaining = deadline - now_ms();
    if (remaining <= 0 ||
        !read_exact(fd0, &results_[root].first[0], peer_mlen, remaining))
      return fail("broadcast meta recv failed");
  }
  bytes_rx_ += 12 + peer_mlen;
  results_[root].second.resize(peer_nbytes);
  Waiter w;
  recv_stripes(root,
               results_[root].second.empty() ? nullptr
                                             : &results_[root].second[0],
               peer_nbytes, 1, deadline, &w, rec);
  if (!w.wait_all())
    return fail((w.timed_out ? "timeout: " : "") +
                std::string("broadcast payload: ") + w.err);
  return true;
}

}  // namespace tft

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

namespace {

tft::CollectiveEngine* eng(void* h) {
  return static_cast<tft::CollectiveEngine*>(h);
}

int32_t rc_for(tft::CollectiveEngine* e, bool ok) {
  if (ok) return 0;
  return e->last_error().rfind("timeout", 0) == 0 ? 2 : 1;
}

}  // namespace

extern "C" {

void* tft_coll_create(int32_t n_streams, int64_t pipeline_bytes,
                      int32_t fr_capacity) {
  return new tft::CollectiveEngine(n_streams, pipeline_bytes, fr_capacity);
}

void tft_coll_destroy(void* h) { delete eng(h); }

int32_t tft_coll_listen(void* h, const char* host) {
  return eng(h)->listen(host ? host : "");
}

int32_t tft_coll_connect(void* h, int32_t rank, int32_t world,
                         const char* peers_json, int64_t timeout_ms) {
  tft::Json peers;
  std::vector<std::string> addrs;
  if (peers_json && tft::Json::parse(peers_json, &peers) &&
      peers.is_array()) {
    for (const auto& p : peers.arr) addrs.push_back(p.as_str());
  }
  return rc_for(eng(h),
                eng(h)->connect_mesh(rank, world, addrs, timeout_ms));
}

void tft_coll_abort(void* h, const char* why) {
  eng(h)->abort(why ? why : "abort");
}

int32_t tft_coll_allreduce(void* h, void* data, uint64_t count, int32_t dtype,
                           int32_t op, int64_t timeout_ms) {
  return rc_for(eng(h), eng(h)->allreduce(data, count, dtype, op, timeout_ms));
}

int32_t tft_coll_allreduce_q8(void* h, float* data, uint64_t count,
                              int64_t timeout_ms) {
  return rc_for(eng(h), eng(h)->allreduce_q8(data, count, timeout_ms));
}

int32_t tft_coll_allgather(void* h, const char* meta, const void* data,
                           uint64_t nbytes, int64_t timeout_ms) {
  return rc_for(eng(h), eng(h)->allgather(meta ? meta : "", data, nbytes,
                                          timeout_ms));
}

int32_t tft_coll_broadcast(void* h, const char* meta, const void* data,
                           uint64_t nbytes, int32_t root, int64_t timeout_ms) {
  return rc_for(eng(h), eng(h)->broadcast(meta ? meta : "", data, nbytes,
                                          root, timeout_ms));
}

int64_t tft_coll_result_meta_len(void* h, int32_t slot) {
  auto* e = eng(h);
  if (slot < 0 || slot >= e->world()) return -1;
  return static_cast<int64_t>(e->result_meta(slot).size());
}

int32_t tft_coll_result_meta(void* h, int32_t slot, char* out, int64_t cap) {
  auto* e = eng(h);
  if (slot < 0 || slot >= e->world()) return 1;
  const std::string& m = e->result_meta(slot);
  if (static_cast<int64_t>(m.size()) > cap) return 1;
  memcpy(out, m.data(), m.size());
  return 0;
}

int64_t tft_coll_result_size(void* h, int32_t slot) {
  auto* e = eng(h);
  if (slot < 0 || slot >= e->world()) return -1;
  return static_cast<int64_t>(e->result_payload(slot).size());
}

int32_t tft_coll_result_copy(void* h, int32_t slot, void* out, int64_t cap) {
  auto* e = eng(h);
  if (slot < 0 || slot >= e->world()) return 1;
  const std::string& p = e->result_payload(slot);
  if (static_cast<int64_t>(p.size()) > cap) return 1;
  memcpy(out, p.data(), p.size());
  return 0;
}

uint64_t tft_coll_bytes_tx(void* h) { return eng(h)->bytes_tx(); }
uint64_t tft_coll_bytes_rx(void* h) { return eng(h)->bytes_rx(); }

void tft_coll_last_error(void* h, char* out, int64_t cap) {
  if (cap <= 0) return;
  const std::string e = eng(h)->last_error();
  const int64_t n = std::min<int64_t>(cap - 1, e.size());
  memcpy(out, e.data(), n);
  out[n] = '\0';
}

void tft_coll_set_trace(void* h, const char* tag) {
  eng(h)->set_trace(tag ? tag : "");
}

uint64_t tft_coll_fr_seq(void* h) { return eng(h)->fr_seq(); }

int64_t tft_coll_fr_snapshot(void* h, uint64_t since_seq, char* out,
                             int64_t cap) {
  const std::string snap = eng(h)->fr_snapshot(since_seq);
  if (out != nullptr && cap > 0) {
    const int64_t n = std::min<int64_t>(cap - 1, snap.size());
    memcpy(out, snap.data(), n);
    out[n] = '\0';
  }
  return static_cast<int64_t>(snap.size());
}

}  // extern "C"
