// Deterministic seeded fault injection for the C++ side of the system —
// the exact mirror of torchft_tpu/chaos.py. Consumes the same
// TORCHFT_CHAOS="seed:<u64>,spec:<kind>@<plane>[:k=v]...[;...]" grammar and
// the same decision function (FNV-1a-64 site hash folded through splitmix64
// with per-(rule, site) visit counters), so a schedule replays bit-for-bit
// across both planes from one seed.
//
// Wiring:
// - net.cc's write_all/read_exact/tcp_connect consult a thread-local context
//   (plane, peer, match) set via ScopedCtx; no context == no injection, so
//   unrelated I/O (store traffic, HTTP status) is never perturbed.
// - collectives.cc stripe jobs set the context around each transfer
//   (plane "data", peer rank, flight-record tag).
// - lighthouse.cc / manager_server.cc call server_rpc() per dispatched
//   request (plane "ctrl", match = RPC type) for rpc_delay / rpc_drop.
// - Every injection is recorded in a bounded ring; tft_chaos_snapshot
//   exposes it as JSON so ProcessGroupNative can journal engine-side
//   injections as chaos_inject events; server binaries log to stderr.
//
// Off is free: every hook starts with a relaxed atomic load of a bool.
#pragma once

#include <cstdint>
#include <string>

namespace tft {
namespace chaos {

// Fault kinds (codes shared with the event ring).
enum Kind : int32_t {
  kConnectRefuse = 0,
  kReset = 1,
  kStall = 2,
  kPartialWrite = 3,
  kRpcDelay = 4,
  kRpcDrop = 5,
  kAbortHeal = 6,
  kCkptTruncate = 7,
  kThrottle = 8,
  kPreempt = 9,
};

// Parses `spec` (TORCHFT_CHAOS grammar) and arms the global schedule.
// Empty/absent spec leaves chaos off. Returns false (and fills *err) on a
// malformed spec — callers should fail loudly, a typo'd schedule must not
// silently inject nothing.
bool init_from_spec(const std::string& spec, std::string* err);

// init_from_spec(getenv("TORCHFT_CHAOS")). Parse errors go to stderr and
// abort the arming (servers keep running un-injected).
void init_from_env();

// True when a schedule is armed (relaxed load; the universal fast gate).
bool armed();

// Pins the current training step for step=a-b rule windows (mirrors
// chaos.py set_step; forwarded from Python via tft_chaos_set_step).
void set_step(int64_t step);

// What a fired rule tells the hook to do.
struct Decision {
  int32_t kind = -1;  // -1: nothing fired
  int64_t ms = 0;
  double frac = 0.0;
  int64_t rate = 0;    // throttle: sustained bytes/second
  int64_t bucket = 0;  // throttle: burst bytes
  int64_t grace = 0;   // preempt: drain window ms before hard kill
};

// One eligible visit at `site` for `kind` under the current thread context.
// Bumps matching rules' visit counters; returns the first firing rule's
// decision (kind == -1 otherwise). Records the injection in the event ring.
Decision pick(int32_t kind, const std::string& site);

// RAII thread-local context: attributes I/O inside the scope to
// (plane, peer, match). Nesting restores the outer context.
class ScopedCtx {
 public:
  ScopedCtx(const char* plane, const std::string& peer,
            const std::string& match);
  ~ScopedCtx();

 private:
  std::string prev_plane_, prev_peer_, prev_match_;
  bool prev_set_;
  uint64_t prev_gen_;
  bool prev_maybe_;
};

// Hook for net.cc write_all: throttle paces (sleeps, token bucket), stall
// sleeps in place; returns a Decision whose kind is kReset or kPartialWrite
// when the write should be torn.
Decision on_write(int fd, size_t len);

// Hook for net.cc read_all/read_exact: throttle paces, stall sleeps;
// kReset tears. `len` is the expected read size (throttle accounting).
Decision on_read(int fd, size_t len);

// Hook for net.cc tcp_connect: true == refuse (caller returns -1).
bool on_connect(const std::string& host, int port);

// Server dispatch hook (lighthouse/manager_server handle_conn): applies
// rpc_delay (sleeps) and rpc_drop/reset (returns false: drop the
// connection without replying — the client sees a torn RPC).
bool server_rpc(const std::string& rpc_type);

// Tags `peer` with a link class so `link=<class>` rules apply to it
// (mirrors chaos.py set_link_class; fed from TORCHFT_LINKS by the process
// group via tft_chaos_set_link).
void set_link_class(const std::string& peer, const std::string& cls);

// Seeded full-jitter unit in [0, 1) for backoff delays, deterministic in
// (chaos seed, key, attempt); seed 0 when no schedule is armed. Mirrors
// chaos.py backoff_jitter (which multiplies by the caller's cap).
double backoff_unit(const std::string& key, uint64_t attempt);

// Decision hash primitives (exposed for cpp_tests parity checks against
// the Python implementation).
uint64_t fnv1a64(const std::string& s);
uint64_t splitmix64(uint64_t x);
uint64_t decision_hash(uint64_t seed, uint64_t rule_idx, uint64_t site_hash,
                       uint64_t visit);

}  // namespace chaos
}  // namespace tft

// C ABI for ctypes (_native.py) — lives in libtftcollectives.so.
extern "C" {
// Arms the global schedule from `spec` (empty string reads TORCHFT_CHAOS).
// Returns 0 ok / -1 parse error.
int32_t tft_chaos_init(const char* spec);
// 1 when a schedule is armed.
int32_t tft_chaos_armed();
// Mirrors chaos.py set_step for step-windowed rules on this plane.
void tft_chaos_set_step(int64_t step);
// Mirrors chaos.py set_link_class for link=<class> rule scoping.
void tft_chaos_set_link(const char* peer, const char* cls);
// Monotonic count of injections fired so far.
int64_t tft_chaos_seq();
// JSON {"seq": N, "events": [{seq, kind, plane, site, rule, visit, step,
// ms, frac, ts_ns}, ...]} with events whose seq > since_seq. Returns bytes
// written, or -needed when `cap` is too small (caller grows and retries —
// same contract as tft_coll_fr_snapshot).
int64_t tft_chaos_snapshot(int64_t since_seq, char* buf, int64_t cap);
}
