// Minimal JSON value type + parser/serializer for the torchft-tpu control plane.
//
// The reference control plane (src/lighthouse.rs, src/manager.rs in
// tushar00jain/torchft) speaks protobuf/gRPC; this TPU-native build uses
// length-prefixed JSON frames over TCP instead (no external deps in the image),
// with identical message capability (see proto/torchft.proto in the reference
// for the fields each message carries).
#pragma once

#include <cmath>
#include <cstdint>
#include <map>
#include <memory>
#include <sstream>
#include <string>
#include <vector>

namespace tft {

class Json {
 public:
  enum class Type { Null, Bool, Int, Double, Str, Array, Object };

  Type type = Type::Null;
  bool b = false;
  int64_t i = 0;
  double d = 0.0;
  std::string s;
  std::vector<Json> arr;
  std::map<std::string, Json> obj;

  Json() = default;
  static Json null() { return Json(); }
  static Json of(bool v) {
    Json j;
    j.type = Type::Bool;
    j.b = v;
    return j;
  }
  static Json of(int64_t v) {
    Json j;
    j.type = Type::Int;
    j.i = v;
    return j;
  }
  static Json of(int v) { return of(static_cast<int64_t>(v)); }
  static Json of(double v) {
    Json j;
    j.type = Type::Double;
    j.d = v;
    return j;
  }
  static Json of(const std::string& v) {
    Json j;
    j.type = Type::Str;
    j.s = v;
    return j;
  }
  static Json of(const char* v) { return of(std::string(v)); }
  static Json array() {
    Json j;
    j.type = Type::Array;
    return j;
  }
  static Json object() {
    Json j;
    j.type = Type::Object;
    return j;
  }

  bool is_null() const { return type == Type::Null; }
  bool is_object() const { return type == Type::Object; }
  bool is_array() const { return type == Type::Array; }

  // Accessors with defaults (lenient: wrong type returns the default).
  bool as_bool(bool dflt = false) const {
    if (type == Type::Bool) return b;
    if (type == Type::Int) return i != 0;
    return dflt;
  }
  int64_t as_int(int64_t dflt = 0) const {
    if (type == Type::Int) return i;
    if (type == Type::Double) return static_cast<int64_t>(d);
    if (type == Type::Bool) return b ? 1 : 0;
    return dflt;
  }
  double as_double(double dflt = 0.0) const {
    if (type == Type::Double) return d;
    if (type == Type::Int) return static_cast<double>(i);
    return dflt;
  }
  std::string as_str(const std::string& dflt = "") const {
    return type == Type::Str ? s : dflt;
  }

  bool has(const std::string& key) const {
    return type == Type::Object && obj.count(key) > 0;
  }
  const Json& get(const std::string& key) const {
    static Json kNull;
    auto it = obj.find(key);
    return it == obj.end() ? kNull : it->second;
  }
  Json& operator[](const std::string& key) {
    type = Type::Object;
    return obj[key];
  }
  void push(Json v) {
    type = Type::Array;
    arr.push_back(std::move(v));
  }

  std::string dump() const {
    std::string out;
    dump_to(out);
    return out;
  }

  void dump_to(std::string& out) const {
    switch (type) {
      case Type::Null:
        out += "null";
        break;
      case Type::Bool:
        out += b ? "true" : "false";
        break;
      case Type::Int:
        out += std::to_string(i);
        break;
      case Type::Double: {
        if (std::isfinite(d)) {
          std::ostringstream ss;
          ss.precision(17);
          ss << d;
          out += ss.str();
        } else {
          out += "null";
        }
        break;
      }
      case Type::Str:
        escape_to(s, out);
        break;
      case Type::Array: {
        out += '[';
        bool first = true;
        for (const auto& v : arr) {
          if (!first) out += ',';
          first = false;
          v.dump_to(out);
        }
        out += ']';
        break;
      }
      case Type::Object: {
        out += '{';
        bool first = true;
        for (const auto& kv : obj) {
          if (!first) out += ',';
          first = false;
          escape_to(kv.first, out);
          out += ':';
          kv.second.dump_to(out);
        }
        out += '}';
        break;
      }
    }
  }

  // Parses `in` into `out`. Returns false and sets *err on malformed input.
  static bool parse(const std::string& in, Json* out, std::string* err = nullptr) {
    size_t pos = 0;
    std::string e;
    if (!parse_value(in, pos, out, &e)) {
      if (err) *err = e;
      return false;
    }
    skip_ws(in, pos);
    if (pos != in.size()) {
      if (err) *err = "trailing characters at " + std::to_string(pos);
      return false;
    }
    return true;
  }

 private:
  static void escape_to(const std::string& v, std::string& out) {
    out += '"';
    for (unsigned char c : v) {
      switch (c) {
        case '"':
          out += "\\\"";
          break;
        case '\\':
          out += "\\\\";
          break;
        case '\n':
          out += "\\n";
          break;
        case '\r':
          out += "\\r";
          break;
        case '\t':
          out += "\\t";
          break;
        default:
          if (c < 0x20) {
            char buf[8];
            snprintf(buf, sizeof(buf), "\\u%04x", c);
            out += buf;
          } else {
            out += static_cast<char>(c);
          }
      }
    }
    out += '"';
  }

  static void skip_ws(const std::string& in, size_t& pos) {
    while (pos < in.size() && (in[pos] == ' ' || in[pos] == '\t' ||
                               in[pos] == '\n' || in[pos] == '\r'))
      pos++;
  }

  static bool fail(std::string* err, const std::string& msg, size_t pos) {
    if (err) *err = msg + " at " + std::to_string(pos);
    return false;
  }

  static bool parse_value(const std::string& in, size_t& pos, Json* out,
                          std::string* err) {
    skip_ws(in, pos);
    if (pos >= in.size()) return fail(err, "unexpected end", pos);
    char c = in[pos];
    if (c == '{') return parse_object(in, pos, out, err);
    if (c == '[') return parse_array(in, pos, out, err);
    if (c == '"') {
      out->type = Type::Str;
      return parse_string(in, pos, &out->s, err);
    }
    if (c == 't') {
      if (in.compare(pos, 4, "true") != 0) return fail(err, "bad literal", pos);
      pos += 4;
      *out = Json::of(true);
      return true;
    }
    if (c == 'f') {
      if (in.compare(pos, 5, "false") != 0) return fail(err, "bad literal", pos);
      pos += 5;
      *out = Json::of(false);
      return true;
    }
    if (c == 'n') {
      if (in.compare(pos, 4, "null") != 0) return fail(err, "bad literal", pos);
      pos += 4;
      *out = Json::null();
      return true;
    }
    return parse_number(in, pos, out, err);
  }

  static bool parse_string(const std::string& in, size_t& pos, std::string* out,
                           std::string* err) {
    pos++;  // opening quote
    out->clear();
    while (pos < in.size()) {
      char c = in[pos];
      if (c == '"') {
        pos++;
        return true;
      }
      if (c == '\\') {
        pos++;
        if (pos >= in.size()) return fail(err, "bad escape", pos);
        char e = in[pos];
        switch (e) {
          case '"':
            *out += '"';
            break;
          case '\\':
            *out += '\\';
            break;
          case '/':
            *out += '/';
            break;
          case 'b':
            *out += '\b';
            break;
          case 'f':
            *out += '\f';
            break;
          case 'n':
            *out += '\n';
            break;
          case 'r':
            *out += '\r';
            break;
          case 't':
            *out += '\t';
            break;
          case 'u': {
            if (pos + 4 >= in.size()) return fail(err, "bad \\u escape", pos);
            unsigned int cp = 0;
            for (int k = 1; k <= 4; k++) {
              char h = in[pos + k];
              cp <<= 4;
              if (h >= '0' && h <= '9')
                cp |= h - '0';
              else if (h >= 'a' && h <= 'f')
                cp |= h - 'a' + 10;
              else if (h >= 'A' && h <= 'F')
                cp |= h - 'A' + 10;
              else
                return fail(err, "bad hex", pos + k);
            }
            pos += 4;
            // UTF-8 encode (surrogate pairs not combined; rare in control msgs).
            if (cp < 0x80) {
              *out += static_cast<char>(cp);
            } else if (cp < 0x800) {
              *out += static_cast<char>(0xC0 | (cp >> 6));
              *out += static_cast<char>(0x80 | (cp & 0x3F));
            } else {
              *out += static_cast<char>(0xE0 | (cp >> 12));
              *out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
              *out += static_cast<char>(0x80 | (cp & 0x3F));
            }
            break;
          }
          default:
            return fail(err, "bad escape char", pos);
        }
        pos++;
      } else {
        *out += c;
        pos++;
      }
    }
    return fail(err, "unterminated string", pos);
  }

  static bool parse_number(const std::string& in, size_t& pos, Json* out,
                           std::string* err) {
    size_t start = pos;
    if (pos < in.size() && (in[pos] == '-' || in[pos] == '+')) pos++;
    bool is_double = false;
    while (pos < in.size()) {
      char c = in[pos];
      if (c >= '0' && c <= '9') {
        pos++;
      } else if (c == '.' || c == 'e' || c == 'E' || c == '-' || c == '+') {
        if (c == '.' || c == 'e' || c == 'E') is_double = true;
        pos++;
      } else {
        break;
      }
    }
    if (pos == start) return fail(err, "bad number", pos);
    std::string tok = in.substr(start, pos - start);
    try {
      if (is_double) {
        *out = Json::of(std::stod(tok));
      } else {
        *out = Json::of(static_cast<int64_t>(std::stoll(tok)));
      }
    } catch (...) {
      return fail(err, "unparseable number '" + tok + "'", start);
    }
    return true;
  }

  static bool parse_array(const std::string& in, size_t& pos, Json* out,
                          std::string* err) {
    pos++;  // '['
    *out = Json::array();
    skip_ws(in, pos);
    if (pos < in.size() && in[pos] == ']') {
      pos++;
      return true;
    }
    while (true) {
      Json v;
      if (!parse_value(in, pos, &v, err)) return false;
      out->arr.push_back(std::move(v));
      skip_ws(in, pos);
      if (pos >= in.size()) return fail(err, "unterminated array", pos);
      if (in[pos] == ',') {
        pos++;
        continue;
      }
      if (in[pos] == ']') {
        pos++;
        return true;
      }
      return fail(err, "expected ',' or ']'", pos);
    }
  }

  static bool parse_object(const std::string& in, size_t& pos, Json* out,
                           std::string* err) {
    pos++;  // '{'
    *out = Json::object();
    skip_ws(in, pos);
    if (pos < in.size() && in[pos] == '}') {
      pos++;
      return true;
    }
    while (true) {
      skip_ws(in, pos);
      if (pos >= in.size() || in[pos] != '"')
        return fail(err, "expected object key", pos);
      std::string key;
      if (!parse_string(in, pos, &key, err)) return false;
      skip_ws(in, pos);
      if (pos >= in.size() || in[pos] != ':')
        return fail(err, "expected ':'", pos);
      pos++;
      Json v;
      if (!parse_value(in, pos, &v, err)) return false;
      out->obj[key] = std::move(v);
      skip_ws(in, pos);
      if (pos >= in.size()) return fail(err, "unterminated object", pos);
      if (in[pos] == ',') {
        pos++;
        continue;
      }
      if (in[pos] == '}') {
        pos++;
        return true;
      }
      return fail(err, "expected ',' or '}'", pos);
    }
  }
};

}  // namespace tft
