// ManagerServer: per-replica-group coordinator for torchft-tpu.
//
// Capability parity with the reference's src/manager.rs:68-487: local ranks
// of one replica group check in via a Quorum request; when the last of
// `world_size` ranks arrives the server forwards a single QuorumMember to the
// Lighthouse (with retry/reconnect, manager.rs:250-306), broadcasts the
// delivered quorum to all waiting ranks, and each rank's reply carries its
// recovery plan from compute_quorum_results. Also: a ShouldCommit barrier
// (commit iff zero ranks voted false, manager.rs:423-479), CheckpointMetadata
// lookup for recovering peers (manager.rs:404-421), a Kill request that exits
// the process (manager.rs:481-486), and a heartbeat loop pinging the
// Lighthouse (manager.rs:194-216).
//
// Requests (length-prefixed JSON frames):
//   {"type":"quorum","group_rank":r,"step":s,"checkpoint_metadata":m,
//    "shrink_only":b,"init_sync":b,"commit_failures":n,"timeout_ms":N}
//   {"type":"should_commit","group_rank":r,"step":s,"should_commit":b,
//    "timeout_ms":N}
//   {"type":"checkpoint_metadata","rank":r}
//   {"type":"kill","msg":...}
//   {"type":"leave"}   (graceful drain: stop heartbeats, tell the lighthouse)
//   {"type":"request_drain"}   (operator asks the TRAINER to drain: sets a
//       flag piggybacked on every quorum response as "drain_requested";
//       the trainer drains at its next step boundary via "leave")
//   {"type":"set_digest","digest":{...}}   (trainer hands over its latest
//       StepDigest wire dict; the heartbeat loop attaches it to every
//       lighthouse heartbeat until replaced — the live fleet-health feed)
//   {"type":"info"}
#pragma once

#include <atomic>
#include <condition_variable>
#include <deque>
#include <map>
#include <mutex>
#include <optional>
#include <string>
#include <thread>
#include <vector>

#include "conn_tracker.hpp"
#include "quorum.hpp"

namespace tft {

struct ManagerOpts {
  std::string replica_id;
  // Ordered comma list "host:port[,host:port...]": first entry is the
  // primary lighthouse, the rest are warm standbys. Managers heartbeat every
  // entry (standbys stay warm, read-only) and fail over down the list when
  // the active entry's lease lapses.
  std::string lighthouse_addr;
  std::string advertise_host;      // host other processes can reach us at
  int port = 0;                    // 0 = ephemeral
  std::string bind_host;           // default 0.0.0.0
  std::string store_address;       // rendezvous store this group advertises
  int64_t world_size = 1;          // local ranks in this replica group
  int64_t heartbeat_interval_ms = 100;
  int64_t connect_timeout_ms = 10000;
  int64_t quorum_retries = 0;
  // Lease on the active lighthouse: no successful heartbeat ack for this
  // long => deterministically advance to the next address in the list
  // (TORCHFT_LH_LEASE_MS / --lh-lease-ms).
  int64_t lighthouse_lease_ms = 3000;
  // Job namespace this replica group belongs to (TORCHFT_JOB / --job).
  // Stamped on every heartbeat/quorum/leave frame; the lighthouse keeps a
  // fully isolated control-plane island per job. "default" matches the
  // pre-namespace wire behavior (the key is still sent; an old lighthouse
  // ignores unknown keys).
  std::string job = "default";
  // Failure-evidence failover: this many CONSECUTIVE transport failures on
  // the ACTIVE lighthouse entry (connect refused/reset — hard evidence the
  // process is gone) fail over immediately instead of waiting out the full
  // lease. 0 disables: lease lapse stays the only failover trigger
  // (TORCHFT_MGR_EVIDENCE_STREAK / --evidence-streak).
  int64_t evidence_streak = 3;
};

class ManagerServer {
 public:
  explicit ManagerServer(ManagerOpts opts);
  ~ManagerServer();

  bool start();
  void stop();

  int port() const { return port_; }
  std::string address() const {
    return opts_.advertise_host + ":" + std::to_string(port_);
  }

  // Graceful drain: stop heartbeating, tell the lighthouse to drop this
  // replica. Idempotent; returns whether the lighthouse confirmed. Called
  // by the "leave" RPC (trainer-initiated drain) and by the parent-death
  // watchdog (trainer crashed — leave on its behalf so survivors shrink at
  // watchdog-poll speed instead of heartbeat expiry).
  bool leave(const std::string& reason, int64_t budget_ms = 5000);

 private:
  void accept_loop();
  void heartbeat_loop();
  void handle_conn(int fd);
  Json handle_request(const Json& req, int64_t deadline_ms);
  Json quorum_rpc(const Json& req, int64_t deadline_ms);
  Json should_commit_rpc(const Json& req, int64_t deadline_ms);
  // Calls the lighthouse Quorum RPC with retries; returns nullopt on failure
  // with a human-readable reason in *error that distinguishes "lighthouse
  // unreachable" (connect-level, retried with the shared seeded-jitter
  // backoff) from "quorum denied" (a live lighthouse said no) from "stale
  // quorum fenced" (epoch below the fence). `trace_id` (may be empty) is
  // forwarded so the lighthouse leg of the step's control-plane path carries
  // the same correlation id.
  std::optional<Quorum> lighthouse_quorum(const QuorumMember& me,
                                          int64_t deadline_ms,
                                          const std::string& trace_id,
                                          std::string* error);
  // HA counters snapshot attached to quorum/info responses so the Python
  // Manager can journal lh_failover / lh_epoch / rpc_retry events.
  Json lh_info_json() const;
  // Enqueue a failure signal for heartbeat piggyback (bounded outbox; oldest
  // dropped). Used by the "signal" RPC and by manager-side evidence (lease
  // lapse / transport-fail failover observations).
  void queue_signal(const std::string& source, const std::string& subject,
                    const std::string& site, Json detail);

  ManagerOpts opts_;
  // ---- lighthouse HA state ----
  // Parsed ordered address list (set in the constructor, then read-only).
  std::vector<std::string> lh_addrs_;
  std::atomic<int> lh_active_{0};       // index of the current active target
  std::atomic<int64_t> lh_failovers_{0};
  // Max quorum epoch ever accepted: the split-brain fence. Any delivered
  // quorum with a lower epoch (a resurrected stale primary) is rejected.
  std::atomic<int64_t> lh_epoch_{0};
  // Max quorum_id ever accepted; heartbeat-carried so a takeover standby
  // resumes numbering above it (strict quorum-id monotonicity w/o a
  // lighthouse-to-lighthouse channel).
  std::atomic<int64_t> lh_quorum_id_{0};
  std::atomic<int64_t> lh_stale_rejected_{0};
  // Connect-level quorum retries absorbed before latching quorum_error_.
  std::atomic<int64_t> lh_unreachable_retries_{0};
  // ---- failure-evidence state ----
  // Max failure-signal seq seen in ACTIVE-entry heartbeat ACKs: the local
  // evidence cursor the trainer's watcher polls via "evidence_status".
  std::atomic<int64_t> lh_signal_seq_{0};
  // Detection latency of the last failover: ms from the last successful
  // active ack to the failover decision (-1 before any failover), plus
  // which trigger won the race (0 none, 1 lease lapse, 2 hard evidence).
  std::atomic<int64_t> lh_detect_ms_{-1};
  std::atomic<int> lh_failover_kind_{0};
  // Last signal object from an active ACK (signal_mu_), and the bounded
  // outbox of trainer-emitted signals awaiting heartbeat piggyback.
  std::mutex signal_mu_;
  Json last_signal_ = Json::null();
  std::deque<Json> signal_outbox_;
  int64_t signal_outbox_dropped_ = 0;
  int port_ = 0;
  int listen_fd_ = -1;
  std::atomic<bool> running_{false};
  // Set by a "leave" request: the heartbeat loop stops pinging the lighthouse
  // so the drained replica ages out instead of looking healthy forever.
  std::atomic<bool> draining_{false};
  // Whether the lighthouse actually confirmed our leave: a repeat leave()
  // call retries the send if the first attempt failed (a false "sent" would
  // hide that survivors are stuck waiting out the heartbeat expiry).
  std::atomic<bool> left_sent_{false};
  // Operator-requested drain (dashboard/RPC): surfaced to the trainer on
  // every quorum response; the trainer owns the actual drain (finish the
  // step, leave, exit) because only it knows a safe boundary.
  std::atomic<bool> drain_requested_{false};
  std::thread accept_thread_;
  std::thread heartbeat_thread_;
  ConnTracker conns_;

  // Latest StepDigest handed over via set_digest, attached verbatim to every
  // heartbeat frame. Own mutex: the heartbeat loop must never contend with a
  // quorum round holding mu_ across a lighthouse RPC.
  std::mutex digest_mu_;
  Json digest_ = Json::null();
  bool has_digest_ = false;

  std::mutex mu_;
  std::condition_variable cv_;

  // Quorum round state (reset after each broadcast).
  struct RankInfo {
    int64_t step = 0;
    bool shrink_only = false;
    int64_t commit_failures = 0;
  };
  std::map<int64_t, RankInfo> participants_;
  std::map<int64_t, std::string> checkpoint_metadata_;  // persists across rounds
  std::optional<Quorum> current_quorum_;
  int64_t quorum_round_ = 0;
  bool quorum_inflight_ = false;
  std::string quorum_error_;

  // should_commit round state.
  std::map<int64_t, bool> commit_votes_;
  bool commit_result_ = false;
  int64_t commit_round_ = 0;
};

}  // namespace tft
