// Implementation of the C++ half of the deterministic chaos plane.
// See chaos.hpp for the contract and torchft_tpu/chaos.py for the Python
// twin — the grammar, the decision hash, and the visit-counter semantics
// here MUST stay bit-identical to the Python implementation (the parity is
// regression-tested from tests/test_chaos.py via ctypes).

#include "chaos.hpp"

#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include <atomic>
#include <deque>
#include <map>
#include <mutex>
#include <stdexcept>
#include <unordered_map>
#include <vector>

#include "json.hpp"
#include "net.hpp"

namespace tft {
namespace chaos {

namespace {

constexpr int64_t kStepMax = int64_t(1) << 62;
constexpr size_t kEventRing = 1024;

const char* kKindNames[] = {
    "connect_refuse", "reset",    "stall",      "partial_write", "rpc_delay",
    "rpc_drop",       "abort_heal", "ckpt_truncate", "throttle", "preempt",
};
constexpr int32_t kNumKinds = 10;

struct Rule {
  int32_t kind = -1;
  std::string plane;  // ctrl | data | heal | srv | any
  int32_t index = 0;
  bool has_peer = false, has_match = false, has_link = false;
  std::string peer, match, link;
  int64_t step_lo = -1, step_hi = kStepMax;
  double p = 1.0;
  int64_t after = 0, every = 1, count = -1;  // count -1 = unlimited
  int64_t ms = 100;
  double frac = 0.5;
  int64_t rate = int64_t(1) << 20;    // throttle: bytes/second sustained
  int64_t bucket = int64_t(1) << 16;  // throttle: burst bytes
  int64_t grace = 0;  // preempt: drain window ms (0 = TORCHFT_DRAIN_GRACE_S)
};

struct Event {
  int64_t seq = 0;
  int32_t kind = -1;
  std::string plane, site;
  int32_t rule = 0;
  int64_t visit = 0, step = -1, ms = 0;
  double frac = 0.0;
  int64_t rate = 0, bucket = 0, grace = 0;
  uint64_t ts_ns = 0;
};

// Wall-clock token bucket pacing an activated throttle site. Which visit
// activates it is the seeded pick(); the pacing itself (like a stall's
// sleep) is not part of the replayed decision sequence.
struct Bucket {
  int64_t rate = 0;  // 0 == not yet configured
  int64_t cap = 1;
  double tokens = 0.0;
  int64_t t_last_ms = 0;
};

struct State {
  uint64_t seed = 0;
  std::vector<Rule> rules;
  std::mutex mu;
  std::map<std::pair<int32_t, std::string>, uint64_t> visits;
  std::map<int32_t, int64_t> fired;
  int64_t seq = 0;
  std::deque<Event> events;
  std::unordered_map<std::string, uint64_t> site_hash;
  std::map<std::string, Bucket> buckets;  // site -> active throttle
  bool has_throttle = false;              // any kThrottle rule in `rules`
};

// Never freed once armed: hooks on detached threads may outlive main.
State* g_state = nullptr;
std::atomic<bool> g_armed{false};
std::atomic<int64_t> g_step{-1};
std::mutex g_init_mu;

// Peer -> link class, fed from TORCHFT_LINKS via tft_chaos_set_link.
// Own mutex: written at configure time, read in pick() only for rules that
// carry a link filter.
std::map<std::string, std::string>* g_links = nullptr;  // never freed
std::mutex g_links_mu;

// Serializes throttle activation (bucket check + pick + create) so
// concurrent stripe threads at one site produce a deterministic number of
// activation visits; also guards State::buckets and pacing math.
std::mutex g_throttle_mu;

// True when the rule's link filter matches the current thread's peer.
bool link_matches(const Rule& r, const std::string& peer) {
  if (!r.has_link) return true;
  std::lock_guard<std::mutex> lk(g_links_mu);
  if (g_links == nullptr) return false;
  auto it = g_links->find(peer);
  return it != g_links->end() && it->second == r.link;
}

struct Ctx {
  bool set = false;
  std::string plane, peer, match;
  // Cached "could any armed rule ever match this ctx" verdict, valid while
  // gen matches g_gen (bumped on every re-arm/disarm).
  uint64_t gen = 0;
  bool maybe = false;
};
thread_local Ctx t_ctx;

// Schedule generation: starts at 1 so a fresh ctx (gen 0) always
// recomputes; install()/disarm bump it so cached verdicts expire.
std::atomic<uint64_t> g_gen{1};

// Rules are immutable once armed and a ctx's (plane, peer, match) are
// fixed for its lifetime, so the filter scan runs once per
// (ctx, generation) instead of on every I/O call — the armed-but-inert
// fast path is then two relaxed loads and a TLS read. Step windows are
// treated as always matchable here (the step can change mid-ctx); the
// per-visit scan in pick() still applies them.
bool ctx_maybe(const State& st) {
  const uint64_t gen = g_gen.load(std::memory_order_relaxed);
  if (t_ctx.gen != gen) {
    bool m = false;
    for (const Rule& r : st.rules) {
      if (r.plane != "any" && r.plane != t_ctx.plane) continue;
      if (r.has_peer && t_ctx.peer.find(r.peer) == std::string::npos)
        continue;
      if (r.has_match && t_ctx.match.find(r.match) == std::string::npos)
        continue;
      m = true;
      break;
    }
    t_ctx.maybe = m;
    t_ctx.gen = gen;
  }
  return t_ctx.maybe;
}

int32_t kind_code(const std::string& name) {
  for (int32_t i = 0; i < kNumKinds; ++i)
    if (name == kKindNames[i]) return i;
  return -1;
}

bool valid_plane(const std::string& p) {
  return p == "ctrl" || p == "data" || p == "heal" || p == "srv" ||
         p == "any";
}

bool parse_rule(const std::string& text, int32_t index, Rule* out,
                std::string* err) {
  std::vector<std::string> parts;
  size_t start = 0;
  while (start <= text.size()) {
    size_t colon = text.find(':', start);
    if (colon == std::string::npos) colon = text.size();
    std::string piece = text.substr(start, colon - start);
    if (!piece.empty()) parts.push_back(piece);
    start = colon + 1;
  }
  if (parts.empty()) {
    *err = "empty rule";
    return false;
  }
  size_t at = parts[0].find('@');
  if (at == std::string::npos) {
    *err = "rule '" + text + "': expected <kind>@<plane>";
    return false;
  }
  Rule r;
  r.index = index;
  std::string kind = parts[0].substr(0, at);
  r.plane = parts[0].substr(at + 1);
  r.kind = kind_code(kind);
  if (r.kind < 0) {
    *err = "rule '" + text + "': unknown kind '" + kind + "'";
    return false;
  }
  if (!valid_plane(r.plane)) {
    *err = "rule '" + text + "': unknown plane '" + r.plane + "'";
    return false;
  }
  for (size_t i = 1; i < parts.size(); ++i) {
    size_t eq = parts[i].find('=');
    if (eq == std::string::npos) {
      *err = "rule '" + text + "': bad param '" + parts[i] + "'";
      return false;
    }
    std::string k = parts[i].substr(0, eq);
    std::string v = parts[i].substr(eq + 1);
    try {
      if (k == "peer") {
        r.has_peer = true;
        r.peer = v;
      } else if (k == "match") {
        r.has_match = true;
        r.match = v;
      } else if (k == "link") {
        r.has_link = true;
        r.link = v;
      } else if (k == "step") {
        size_t dash = v.find('-');
        std::string lo = dash == std::string::npos ? v : v.substr(0, dash);
        std::string hi =
            dash == std::string::npos ? "" : v.substr(dash + 1);
        r.step_lo = lo.empty() ? 0 : std::stoll(lo);
        r.step_hi = hi.empty() ? kStepMax : std::stoll(hi);
      } else if (k == "p") {
        r.p = std::stod(v);
        if (r.p < 0.0 || r.p > 1.0) throw std::runtime_error("p");
      } else if (k == "after") {
        r.after = std::stoll(v);
      } else if (k == "every") {
        r.every = std::max<int64_t>(1, std::stoll(v));
      } else if (k == "count") {
        r.count = std::stoll(v);
      } else if (k == "ms") {
        r.ms = std::stoll(v);
      } else if (k == "frac") {
        r.frac = std::stod(v);
        if (r.frac < 0.0 || r.frac > 1.0) throw std::runtime_error("frac");
      } else if (k == "rate") {
        r.rate = std::stoll(v);
        if (r.rate <= 0) throw std::runtime_error("rate");
      } else if (k == "bucket") {
        r.bucket = std::stoll(v);
        if (r.bucket <= 0) throw std::runtime_error("bucket");
      } else if (k == "grace") {
        r.grace = std::stoll(v);
        if (r.grace < 0) throw std::runtime_error("grace");
      } else {
        *err = "rule '" + text + "': unknown param '" + k + "'";
        return false;
      }
    } catch (const std::exception&) {
      *err = "rule '" + text + "': bad value in '" + parts[i] + "'";
      return false;
    }
  }
  *out = r;
  return true;
}

void log_event(const Event& ev) {
  fprintf(stderr,
          "[chaos] inject seq=%lld kind=%s plane=%s site=%s rule=%d "
          "visit=%lld step=%lld\n",
          static_cast<long long>(ev.seq), kKindNames[ev.kind],
          ev.plane.c_str(), ev.site.c_str(), ev.rule,
          static_cast<long long>(ev.visit),
          static_cast<long long>(ev.step));
}

}  // namespace

uint64_t fnv1a64(const std::string& s) {
  uint64_t h = 0xcbf29ce484222325ull;
  for (unsigned char c : s) {
    h ^= c;
    h *= 0x100000001b3ull;
  }
  return h;
}

uint64_t splitmix64(uint64_t x) {
  uint64_t z = x + 0x9E3779B97F4A7C15ull;
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
  return z ^ (z >> 31);
}

uint64_t decision_hash(uint64_t seed, uint64_t rule_idx, uint64_t site_hash,
                       uint64_t visit) {
  uint64_t x = seed ^ site_hash ^ (rule_idx * 0x9E3779B97F4A7C15ull) ^
               (visit * 0xBF58476D1CE4E5B9ull);
  return splitmix64(x);
}

bool init_from_spec(const std::string& spec, std::string* err) {
  std::string trimmed = spec;
  while (!trimmed.empty() && (trimmed.back() == ' ' || trimmed.back() == '\n'))
    trimmed.pop_back();
  if (trimmed.empty()) {
    g_armed.store(false, std::memory_order_relaxed);
    g_gen.fetch_add(1, std::memory_order_relaxed);
    return true;
  }
  if (trimmed.rfind("seed:", 0) != 0) {
    *err = "TORCHFT_CHAOS must start with 'seed:<int>,spec:'";
    return false;
  }
  std::string rest = trimmed.substr(5);
  size_t comma = rest.find(',');
  if (comma == std::string::npos || rest.compare(comma + 1, 5, "spec:") != 0) {
    *err = "TORCHFT_CHAOS must be 'seed:<int>,spec:<rules>'";
    return false;
  }
  uint64_t seed = 0;
  try {
    seed = static_cast<uint64_t>(std::stoull(rest.substr(0, comma)));
  } catch (const std::exception&) {
    *err = "bad seed '" + rest.substr(0, comma) + "'";
    return false;
  }
  std::string body = rest.substr(comma + 6);
  auto st = new State();
  st->seed = seed;
  size_t start = 0;
  int32_t index = 0;
  while (start <= body.size()) {
    size_t semi = body.find(';', start);
    if (semi == std::string::npos) semi = body.size();
    std::string rtext = body.substr(start, semi - start);
    start = semi + 1;
    // Trim spaces.
    while (!rtext.empty() && rtext.front() == ' ') rtext.erase(0, 1);
    while (!rtext.empty() && rtext.back() == ' ') rtext.pop_back();
    if (rtext.empty()) continue;
    Rule r;
    if (!parse_rule(rtext, index, &r, err)) {
      delete st;
      return false;
    }
    if (r.kind == kThrottle) st->has_throttle = true;
    st->rules.push_back(r);
    ++index;
  }
  if (st->rules.empty()) {
    delete st;
    *err = "TORCHFT_CHAOS spec has no rules";
    return false;
  }
  std::lock_guard<std::mutex> lk(g_init_mu);
  delete g_state;  // safe: callers only hold g_state under armed checks
  g_state = st;
  g_armed.store(true, std::memory_order_release);
  g_gen.fetch_add(1, std::memory_order_release);
  return true;
}

void init_from_env() {
  const char* v = getenv("TORCHFT_CHAOS");
  if (v == nullptr || v[0] == '\0') return;
  std::string err;
  if (!init_from_spec(v, &err))
    fprintf(stderr, "[chaos] bad TORCHFT_CHAOS (ignored): %s\n", err.c_str());
}

bool armed() { return g_armed.load(std::memory_order_relaxed); }

void set_step(int64_t step) {
  g_step.store(step, std::memory_order_relaxed);
}

ScopedCtx::ScopedCtx(const char* plane, const std::string& peer,
                     const std::string& match)
    : prev_plane_(t_ctx.plane),
      prev_peer_(t_ctx.peer),
      prev_match_(t_ctx.match),
      prev_set_(t_ctx.set),
      prev_gen_(t_ctx.gen),
      prev_maybe_(t_ctx.maybe) {
  t_ctx.set = true;
  t_ctx.plane = plane;
  t_ctx.peer = peer;
  t_ctx.match = match;
  t_ctx.gen = 0;  // new filters: force ctx_maybe to recompute
}

ScopedCtx::~ScopedCtx() {
  t_ctx.set = prev_set_;
  t_ctx.plane = prev_plane_;
  t_ctx.peer = prev_peer_;
  t_ctx.match = prev_match_;
  t_ctx.gen = prev_gen_;
  t_ctx.maybe = prev_maybe_;
}

Decision pick(int32_t kind, const std::string& site) {
  Decision d;
  if (!g_armed.load(std::memory_order_acquire) || !t_ctx.set) return d;
  State& st = *g_state;
  if (!ctx_maybe(st)) return d;
  const int64_t step = g_step.load(std::memory_order_relaxed);
  // Lock-free pre-scan over the (immutable once armed) rule filters: if
  // nothing can match this visit, no counter moves — so skip the schedule
  // mutex entirely. Keeps an armed-but-narrowly-scoped schedule from
  // serializing every unrelated stripe thread on one global lock (the
  // bench_pg chaos A/B measures this path).
  bool any = false;
  for (const Rule& r : st.rules) {
    if (r.kind != kind) continue;
    if (r.plane != "any" && r.plane != t_ctx.plane) continue;
    if (r.has_peer && t_ctx.peer.find(r.peer) == std::string::npos) continue;
    if (r.has_match && t_ctx.match.find(r.match) == std::string::npos)
      continue;
    if (!link_matches(r, t_ctx.peer)) continue;
    if (r.step_lo >= 0 &&
        (step < 0 || step < r.step_lo || step > r.step_hi))
      continue;
    any = true;
    break;
  }
  if (!any) return d;
  Event ev;
  {
    std::lock_guard<std::mutex> lk(st.mu);
    for (const Rule& r : st.rules) {
      if (r.kind != kind) continue;
      if (r.plane != "any" && r.plane != t_ctx.plane) continue;
      if (r.has_peer && t_ctx.peer.find(r.peer) == std::string::npos)
        continue;
      if (r.has_match && t_ctx.match.find(r.match) == std::string::npos)
        continue;
      if (!link_matches(r, t_ctx.peer)) continue;
      if (r.step_lo >= 0) {  // windowed rule: needs a known step
        if (step < 0 || step < r.step_lo || step > r.step_hi) continue;
      }
      // Bump the visit counter of EVERY matching rule (mirrors chaos.py):
      // rule order must not change later rules' counters.
      auto key = std::make_pair(r.index, site);
      uint64_t visit = st.visits[key]++;
      if (d.kind >= 0) continue;  // already fired this visit
      if (static_cast<int64_t>(visit) < r.after) continue;
      uint64_t k = visit - static_cast<uint64_t>(r.after);
      if (k % static_cast<uint64_t>(r.every) != 0) continue;
      if (r.count >= 0 && st.fired[r.index] >= r.count) continue;
      if (r.p < 1.0) {
        auto it = st.site_hash.find(site);
        uint64_t sh;
        if (it != st.site_hash.end()) {
          sh = it->second;
        } else {
          sh = fnv1a64(site);
          st.site_hash.emplace(site, sh);
        }
        uint64_t h = decision_hash(st.seed, r.index, sh, visit);
        // Top 53 bits as a unit float, same as chaos.py _hash_unit.
        double unit = static_cast<double>(h >> 11) / 9007199254740992.0;
        if (unit >= r.p) continue;
      }
      st.fired[r.index]++;
      st.seq++;
      d.kind = kind;
      d.ms = r.ms;
      d.frac = r.frac;
      if (kind == kThrottle) {
        d.rate = r.rate;
        d.bucket = r.bucket;
      }
      if (kind == kPreempt) d.grace = r.grace;
      ev.seq = st.seq;
      ev.kind = kind;
      ev.plane = t_ctx.plane;
      ev.site = site;
      ev.rule = r.index;
      ev.visit = static_cast<int64_t>(visit);
      ev.step = step;
      ev.ms = r.ms;
      ev.frac = r.frac;
      ev.rate = d.rate;
      ev.bucket = d.bucket;
      ev.grace = d.grace;
      ev.ts_ns = now_realtime_ns();
      st.events.push_back(ev);
      if (st.events.size() > kEventRing) st.events.pop_front();
    }
  }
  if (d.kind >= 0) log_event(ev);
  return d;
}

namespace {

// Milliseconds a paced I/O of `len` bytes must sleep under the bucket.
int64_t bucket_consume(Bucket& b, size_t len) {
  const int64_t now = now_ms();
  b.tokens = std::min(static_cast<double>(b.cap),
                      b.tokens + static_cast<double>(now - b.t_last_ms) *
                                     static_cast<double>(b.rate) / 1000.0);
  b.t_last_ms = now;
  b.tokens -= static_cast<double>(len);
  if (b.tokens >= 0.0) return 0;
  // Cap per-call sleeps so one huge buffered write can't wedge a
  // deadline-driven transfer longer than a stall rule could.
  return std::min<int64_t>(
      static_cast<int64_t>(-b.tokens * 1000.0 / b.rate), 2000);
}

// Throttle hook body: once a seeded throttle pick fires for `site`, a token
// bucket paces every later I/O there without further picks (one journaled
// activation, visit-deterministic because activation is serialized under
// g_throttle_mu).
int64_t throttle_ms(State& st, const std::string& site, size_t len) {
  if (!st.has_throttle) return 0;  // schedules without throttle: lock-free
  std::lock_guard<std::mutex> lk(g_throttle_mu);
  auto it = st.buckets.find(site);
  if (it == st.buckets.end()) {
    Decision t = pick(kThrottle, site);
    if (t.kind < 0) return 0;
    Bucket b;
    b.rate = std::max<int64_t>(1, t.rate);
    b.cap = std::max<int64_t>(1, t.bucket);
    b.tokens = static_cast<double>(b.cap);
    b.t_last_ms = now_ms();
    it = st.buckets.emplace(site, b).first;
  }
  return bucket_consume(it->second, len);
}

}  // namespace

Decision on_write(int fd, size_t len) {
  (void)fd;
  Decision none;
  if (!g_armed.load(std::memory_order_acquire) || !t_ctx.set) return none;
  // Skip the site-string allocation and the pick() scans when the armed
  // schedule cannot touch this ctx (bench_pg --chaos-ab measures exactly
  // this path).
  if (!ctx_maybe(*g_state)) return none;
  const std::string site =
      "send:" + (t_ctx.peer.empty() ? std::string("?") : t_ctx.peer);
  int64_t tms = throttle_ms(*g_state, site, len);
  if (tms > 0) sleep_ms(tms);
  Decision s = pick(kStall, site);
  if (s.kind == kStall) sleep_ms(s.ms);
  Decision pw = pick(kPartialWrite, site);
  if (pw.kind >= 0) return pw;
  return pick(kReset, site);
}

Decision on_read(int fd, size_t len) {
  (void)fd;
  Decision none;
  if (!g_armed.load(std::memory_order_acquire) || !t_ctx.set) return none;
  if (!ctx_maybe(*g_state)) return none;
  const std::string site =
      "recv:" + (t_ctx.peer.empty() ? std::string("?") : t_ctx.peer);
  int64_t tms = throttle_ms(*g_state, site, len);
  if (tms > 0) sleep_ms(tms);
  Decision s = pick(kStall, site);
  if (s.kind == kStall) sleep_ms(s.ms);
  return pick(kReset, site);
}

bool on_connect(const std::string& host, int port) {
  if (!g_armed.load(std::memory_order_relaxed) || !t_ctx.set) return false;
  std::string peer = t_ctx.peer.empty()
                         ? host + ":" + std::to_string(port)
                         : t_ctx.peer;
  const std::string site = "connect:" + peer;
  return pick(kConnectRefuse, site).kind >= 0;
}

void set_link_class(const std::string& peer, const std::string& cls) {
  std::lock_guard<std::mutex> lk(g_links_mu);
  if (g_links == nullptr) g_links = new std::map<std::string, std::string>();
  (*g_links)[peer] = cls;
}

double backoff_unit(const std::string& key, uint64_t attempt) {
  uint64_t seed = 0;
  if (g_armed.load(std::memory_order_acquire)) seed = g_state->seed;
  uint64_t h =
      splitmix64(seed ^ fnv1a64(key) ^ (attempt * 0x9E3779B97F4A7C15ull));
  // Top 53 bits as a unit float, same as chaos.py _hash_unit.
  return static_cast<double>(h >> 11) / 9007199254740992.0;
}

bool server_rpc(const std::string& rpc_type) {
  if (!g_armed.load(std::memory_order_relaxed)) return true;
  ScopedCtx ctx("srv", "", rpc_type);
  const std::string site = "srv:" + rpc_type;
  Decision d = pick(kRpcDelay, site);
  if (d.kind == kRpcDelay) sleep_ms(d.ms);
  if (pick(kRpcDrop, site).kind >= 0) return false;
  if (pick(kReset, site).kind >= 0) return false;
  return true;
}

}  // namespace chaos
}  // namespace tft

extern "C" {

int32_t tft_chaos_init(const char* spec) {
  std::string err;
  std::string s = spec == nullptr ? "" : spec;
  if (s.empty()) {
    tft::chaos::init_from_env();
    return tft::chaos::armed() ? 0 : 0;
  }
  if (!tft::chaos::init_from_spec(s, &err)) {
    fprintf(stderr, "[chaos] bad spec: %s\n", err.c_str());
    return -1;
  }
  return 0;
}

int32_t tft_chaos_armed() { return tft::chaos::armed() ? 1 : 0; }

void tft_chaos_set_step(int64_t step) { tft::chaos::set_step(step); }

void tft_chaos_set_link(const char* peer, const char* cls) {
  if (peer == nullptr || cls == nullptr) return;
  tft::chaos::set_link_class(peer, cls);
}

int64_t tft_chaos_seq() {
  using namespace tft::chaos;
  if (!armed()) return 0;
  // g_state is stable once armed (re-init replaces the pointer under
  // g_init_mu; hooks read the old or the new — both valid objects).
  State* st = g_state;
  std::lock_guard<std::mutex> lk(st->mu);
  return st->seq;
}

int64_t tft_chaos_snapshot(int64_t since_seq, char* buf, int64_t cap) {
  using namespace tft;
  using namespace tft::chaos;
  Json root;
  Json events = Json::array();
  int64_t seq = 0;
  if (armed()) {
    State* st = g_state;
    std::lock_guard<std::mutex> lk(st->mu);
    seq = st->seq;
    for (const Event& ev : st->events) {
      if (ev.seq <= since_seq) continue;
      Json je;
      je["seq"] = Json::of(ev.seq);
      je["kind"] = Json::of(kKindNames[ev.kind]);
      je["plane"] = Json::of(ev.plane);
      je["site"] = Json::of(ev.site);
      je["rule"] = Json::of(static_cast<int64_t>(ev.rule));
      je["visit"] = Json::of(ev.visit);
      je["step"] = Json::of(ev.step);
      je["ms"] = Json::of(ev.ms);
      je["frac"] = Json::of(ev.frac);
      je["rate"] = Json::of(ev.rate);
      je["bucket"] = Json::of(ev.bucket);
      je["grace"] = Json::of(ev.grace);
      je["ts_ns"] = Json::of(static_cast<int64_t>(ev.ts_ns));
      events.push(std::move(je));
    }
  }
  root["seq"] = Json::of(seq);
  root["events"] = std::move(events);
  std::string out = root.dump();
  int64_t need = static_cast<int64_t>(out.size()) + 1;
  if (need > cap) return -need;
  memcpy(buf, out.data(), out.size());
  buf[out.size()] = '\0';
  return static_cast<int64_t>(out.size());
}

}  // extern "C"
