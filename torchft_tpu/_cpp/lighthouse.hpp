// Lighthouse: the global quorum coordinator for torchft-tpu.
//
// Capability parity with the reference's src/lighthouse.rs:68-480:
// heartbeats + participants maps, a tick loop running quorum_compute every
// quorum_tick_ms, quorum_id bumps on membership change or commit failures,
// blocking Quorum requests answered via broadcast, an HTTP status dashboard
// served on the same port (sniffed by first bytes), and a kill endpoint that
// forwards a Kill message to a member's manager address.
//
// Wire protocol: length-prefixed JSON frames (see net.hpp). Requests:
//   {"type":"heartbeat","replica_id":...}
//   {"type":"quorum","timeout_ms":N,"requester":{QuorumMember}}
//   {"type":"status"}
//   {"type":"kill","replica_id":...}
// HTTP: GET / or /status (dashboard), GET/POST /replica/<id>/kill.
#pragma once

#include <atomic>
#include <condition_variable>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "conn_tracker.hpp"
#include "quorum.hpp"

namespace tft {

class Lighthouse {
 public:
  Lighthouse(const std::string& bind_host, int port, LighthouseOpts opts);
  ~Lighthouse();

  // Starts listener + tick threads. Returns false if bind failed.
  bool start();
  void stop();

  int port() const { return port_; }
  std::string address() const;

  // Exposed for tests: runs one tick synchronously.
  void tick();

 private:
  void accept_loop();
  void tick_loop();
  void handle_conn(int fd);
  void handle_frame_conn(int fd, const std::string& first_payload);
  void handle_http(int fd);
  Json handle_request(const Json& req, int64_t deadline_ms);
  Json quorum_rpc(const Json& req, int64_t deadline_ms);
  std::string render_status_html();
  std::string render_metrics();
  Json status_json();

  std::string bind_host_;
  int port_;
  LighthouseOpts opts_;

  std::mutex mu_;
  std::condition_variable cv_;
  LighthouseState state_;
  std::optional<Quorum> last_quorum_;  // most recently broadcast quorum
  int64_t quorum_gen_ = 0;             // bumped on every broadcast
  int64_t joins_total_ = 0;   // members added across quorum transitions
  int64_t leaves_total_ = 0;  // members gone across quorum transitions
  std::string last_reason_;            // why no quorum yet (for status page)

  int listen_fd_ = -1;
  std::atomic<bool> running_{false};
  std::thread accept_thread_;
  std::thread tick_thread_;
  ConnTracker conns_;
};

}  // namespace tft
