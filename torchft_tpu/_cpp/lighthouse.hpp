// Lighthouse: the global quorum coordinator for torchft-tpu.
//
// Capability parity with the reference's src/lighthouse.rs:68-480:
// heartbeats + participants maps, a tick loop running quorum_compute every
// quorum_tick_ms, quorum_id bumps on membership change or commit failures,
// blocking Quorum requests answered via broadcast, an HTTP status dashboard
// served on the same port (sniffed by first bytes), and a kill endpoint that
// forwards a Kill message to a member's manager address.
//
// Wire protocol: length-prefixed JSON frames (see net.hpp). Requests:
//   {"type":"heartbeat","replica_id":...[,"digest":{...},"hb_interval_ms":N]}
//   {"type":"quorum","timeout_ms":N,"requester":{QuorumMember}}
//   {"type":"status"}
//   {"type":"fleet"}   (live fleet-health table, the framed twin of
//       GET /fleet.json: per-replica digest rows + aggregates + anomalies)
//   {"type":"kill","replica_id":...}
// HTTP: GET / or /status (dashboard), GET /fleet.json (live health table),
// GET/POST /replica/<id>/kill.
//
// Live fleet plane: heartbeats optionally carry a StepDigest (compact
// per-replica health summary built by telemetry.StepDigest). The lighthouse
// keeps a rolling per-replica fleet table, runs an online straggler/anomaly
// detector (relative step-rate slowdown vs the fleet median, heartbeat-gap
// jitter against the sender-declared cadence, commit-failure streaks), and
// serves it all at /fleet.json. Digest-driven rules evaluate at heartbeat
// ARRIVAL (same digest sequence => same anomaly sequence, so chaos replays
// reproduce alerts); only the time-based rules (open heartbeat gaps,
// staleness) live in the tick scan.
#pragma once

#include <atomic>
#include <condition_variable>
#include <deque>
#include <map>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <vector>

#include "conn_tracker.hpp"
#include "quorum.hpp"

namespace tft {

class Lighthouse {
 public:
  Lighthouse(const std::string& bind_host, int port, LighthouseOpts opts);
  ~Lighthouse();

  // Starts listener + tick threads. Returns false if bind failed.
  bool start();
  void stop();

  int port() const { return port_; }
  std::string address() const;

  // Exposed for tests: runs one tick synchronously.
  void tick();

 private:
  void accept_loop();
  void tick_loop();
  void handle_conn(int fd);
  void handle_frame_conn(int fd, const std::string& first_payload);
  void handle_http(int fd);
  Json handle_request(const Json& req, int64_t deadline_ms);
  Json quorum_rpc(const Json& req, int64_t deadline_ms);
  std::string render_status_html();
  std::string render_metrics();
  Json status_json();

  // ---- live fleet health plane ----
  struct FleetEntry {
    Json digest;                     // last StepDigest wire dict
    bool has_digest = false;
    int64_t digest_ms = 0;           // arrival time of that digest
    int64_t last_hb_ms = 0;          // last heartbeat arrival
    int64_t hb_interval_ms = 0;      // sender-declared cadence (0 = unknown)
    double hb_gap_ewma_ms = 0.0;     // inter-arrival EWMA (old-client fallback)
    int64_t hb_count = 0;
    int64_t last_jitter_ms = 0;      // when a closed gap last blew the budget
    std::set<std::string> flags;     // active anomaly flags
    int64_t straggler_until_ms = 0;  // sticky display flag
  };
  // All fleet_* helpers run with mu_ held by the caller.
  void fleet_note_heartbeat(const std::string& replica_id, const Json& req,
                            int64_t now);
  void fleet_scan_locked(int64_t now);  // time-based rules (gaps, staleness)
  void fleet_set_flag(const std::string& replica_id, FleetEntry& e,
                      const std::string& kind, int64_t now, Json detail);
  int64_t fleet_jitter_budget_ms(const FleetEntry& e) const;
  Json fleet_json_locked(int64_t now);
  Json fleet_summary_locked(int64_t now);  // the slice merged into status.json

  std::map<std::string, FleetEntry> fleet_;
  std::deque<Json> anomalies_;  // rise-edge anomaly ring (capped)
  int64_t anomaly_seq_ = 0;     // total anomalies ever (ring drops old ones)

  std::string bind_host_;
  int port_;
  LighthouseOpts opts_;

  std::mutex mu_;
  std::condition_variable cv_;
  LighthouseState state_;
  std::optional<Quorum> last_quorum_;  // most recently broadcast quorum
  int64_t quorum_gen_ = 0;             // bumped on every broadcast
  int64_t joins_total_ = 0;   // members added across quorum transitions
  int64_t leaves_total_ = 0;  // members gone across quorum transitions
  std::string last_reason_;            // why no quorum yet (for status page)

  int listen_fd_ = -1;
  std::atomic<bool> running_{false};
  std::thread accept_thread_;
  std::thread tick_thread_;
  ConnTracker conns_;
};

}  // namespace tft
