// Lighthouse: the global quorum coordinator for torchft-tpu.
//
// Capability parity with the reference's src/lighthouse.rs:68-480:
// heartbeats + participants maps, a tick loop running quorum_compute every
// quorum_tick_ms, quorum_id bumps on membership change or commit failures,
// blocking Quorum requests answered via broadcast, an HTTP status dashboard
// served on the same port (sniffed by first bytes), and a kill endpoint that
// forwards a Kill message to a member's manager address.
//
// Wire protocol: length-prefixed JSON frames (see net.hpp). Requests:
//   {"type":"heartbeat","replica_id":...[,"job":J,"digest":{...},
//       "hb_interval_ms":N]}
//   {"type":"quorum","timeout_ms":N,"requester":{QuorumMember}[,"job":J]}
//   {"type":"status"}
//   {"type":"fleet"[,"job":J]}   (live fleet-health table, the framed twin of
//       GET /fleet.json: per-replica digest rows + aggregates + anomalies)
//   {"type":"kill","replica_id":...[,"job":J]}
// HTTP: GET / or /status (dashboard), GET /fleet.json[?job=J] (live health
// table), GET/POST /replica/<id>/kill.
//
// Multi-tenant namespaces: every frame may carry a "job" id; an absent or
// empty field maps to "default" (wire back-compat with pre-namespace
// clients). Each job owns a fully isolated control-plane island — its own
// participant/heartbeat/quorum tables, fleet-health table, anomaly detectors
// and ring, aggregate trackers, and /fleet.json snapshot cache — under its
// OWN mutex, so one job's churn or quorum storm cannot stall another job's
// heartbeat/quorum hot path or bump its quorum generation.
//
// Incremental quorum compute: registrations no longer trigger a full
// O(N log N) quorum_compute each (the O(N^2) registration storm that put
// quorum formation at ~4 s for N=1024). Each join/leave maintains O(1) gate
// counters (previous members re-registered; heartbeating replicas not yet
// registered); the full quorum_compute — still the single source of truth —
// only runs when the gate says a quorum CAN form, plus on the periodic tick
// as the time-driven (heartbeat expiry, join timeout) fallback. A gate bug
// can therefore only delay a formation by one tick, never form a wrong one.
//
// Federation: a lighthouse started with a root address periodically reports
// a per-job rollup upward over the SAME heartbeat frame type (piggyback
// channel), tagged with its district name and fencing epoch. The root keeps
// a per-district table with per-district epoch fencing — after a district
// failover the old primary's rollups are dropped, and a district's loss or
// failover never perturbs sibling districts or other jobs' tables.
//
// Live fleet plane: heartbeats optionally carry a StepDigest (compact
// per-replica health summary built by telemetry.StepDigest). The lighthouse
// keeps a rolling per-replica fleet table PER JOB, runs an online
// straggler/anomaly detector (relative step-rate slowdown vs the job median,
// heartbeat-gap jitter against the sender-declared cadence, commit-failure
// streaks), and serves it all at /fleet.json. Digest-driven rules evaluate
// at heartbeat ARRIVAL (same digest sequence => same anomaly sequence, so
// chaos replays reproduce alerts); only the time-based rules (open heartbeat
// gaps, staleness) live in the tick scan.
#pragma once

#include <atomic>
#include <condition_variable>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <vector>

#include "conn_tracker.hpp"
#include "quorum.hpp"

namespace tft {

// Lock-free log-bucket latency histogram: the C++ twin of
// telemetry._HIST_BOUNDS / _hist_percentile. Bucket i (i in 0..27) holds
// samples with latency <= 2^i microseconds (1 us doubling up to ~134 s);
// bucket 28 is overflow. Percentiles report the UPPER bound of the bucket
// containing the quantile, so they over-estimate within one power of two —
// identical semantics to the Python side, which keeps dashboards comparable
// across both planes.
class LatencyHist {
 public:
  static constexpr int kFinite = 28;
  static constexpr int kBuckets = kFinite + 1;

  struct Snap {
    int64_t count = 0;
    int64_t sum_us = 0;
    int64_t buckets[kBuckets] = {0};
  };

  // First bucket whose upper bound (2^i us) covers the sample; matches
  // bisect.bisect_left(_HIST_BOUNDS, dt) on the Python side.
  static int bucket_of(int64_t us) {
    if (us <= 1) return 0;
    for (int i = 1; i < kFinite; i++)
      if ((int64_t{1} << i) >= us) return i;
    return kFinite;  // overflow
  }

  void observe_us(int64_t us) {
    if (us < 0) us = 0;
    buckets_[bucket_of(us)].fetch_add(1, std::memory_order_relaxed);
    count_.fetch_add(1, std::memory_order_relaxed);
    sum_us_.fetch_add(us, std::memory_order_relaxed);
  }

  Snap snapshot() const {
    Snap s;
    s.count = count_.load(std::memory_order_relaxed);
    s.sum_us = sum_us_.load(std::memory_order_relaxed);
    for (int i = 0; i < kBuckets; i++)
      s.buckets[i] = buckets_[i].load(std::memory_order_relaxed);
    return s;
  }

  // Upper-bound quantile from bucket counts (telemetry._hist_percentile):
  // 0 with no samples; an empty bucket prefix never satisfies the target;
  // the overflow bucket reports the last finite bound.
  static int64_t percentile_us(const Snap& s, double q) {
    int64_t total = 0;
    for (int i = 0; i < kBuckets; i++) total += s.buckets[i];
    if (total == 0) return 0;
    double target = q * static_cast<double>(total);
    int64_t cum = 0;
    for (int i = 0; i < kBuckets; i++) {
      if (s.buckets[i] == 0) continue;
      cum += s.buckets[i];
      if (static_cast<double>(cum) >= target)
        return int64_t{1} << (i < kFinite ? i : kFinite - 1);
    }
    return int64_t{1} << (kFinite - 1);
  }

 private:
  std::atomic<int64_t> buckets_[kBuckets] = {};
  std::atomic<int64_t> count_{0};
  std::atomic<int64_t> sum_us_{0};
};

// Exact running median over a multiset of doubles with O(log N)
// insert/erase, replacing the per-heartbeat full-table sort. Maintains the
// same "upper median" the old fleet_median(sort) returned: lo_ holds the
// smaller floor(n/2) values, hi_ the larger ceil(n/2), so
// median() == sorted[n/2] bit-for-bit (the property tests in
// tests/test_fleet.py pin this equality against a full recompute).
class MedianTracker {
 public:
  void insert(double v) {
    if (hi_.empty() || v >= *hi_.begin())
      hi_.insert(v);
    else
      lo_.insert(v);
    rebalance();
  }

  // No-op if v is not present (defensive: an aggregate drift bug should
  // surface as a wrong median in the property test, not a crash).
  void erase(double v) {
    auto it = hi_.find(v);
    if (it != hi_.end()) {
      hi_.erase(it);
    } else {
      auto lo = lo_.find(v);
      if (lo == lo_.end()) return;
      lo_.erase(lo);
    }
    rebalance();
  }

  size_t size() const { return lo_.size() + hi_.size(); }
  double median() const { return hi_.empty() ? 0.0 : *hi_.begin(); }

 private:
  void rebalance() {
    while (hi_.size() > lo_.size() + 1) {
      lo_.insert(*hi_.begin());
      hi_.erase(hi_.begin());
    }
    while (lo_.size() > hi_.size()) {
      auto it = std::prev(lo_.end());
      hi_.insert(*it);
      lo_.erase(it);
    }
  }

  std::multiset<double> lo_, hi_;
};

// Size of the closed badput taxonomy (telemetry.BADPUT_KINDS); the names
// live in lighthouse.cc (kBadputKindNames, lint-mirrored positionally
// against the Python tuple). The digest's "acct" array is indexed by it.
constexpr int kNumBadputKinds = 10;

class Lighthouse {
 public:
  Lighthouse(const std::string& bind_host, int port, LighthouseOpts opts);
  ~Lighthouse();

  // Starts listener + tick threads. Returns false if bind failed.
  bool start();
  void stop();

  int port() const { return port_; }
  std::string address() const;

  // Exposed for tests: runs one tick synchronously (all jobs).
  void tick();

 private:
  // ---- live fleet health plane (per job) ----
  struct FleetEntry {
    Json digest;                     // last StepDigest wire dict
    bool has_digest = false;
    int64_t digest_ms = 0;           // arrival time of that digest
    int64_t last_hb_ms = 0;          // last heartbeat arrival
    int64_t hb_interval_ms = 0;      // sender-declared cadence (0 = unknown)
    double hb_gap_ewma_ms = 0.0;     // inter-arrival EWMA (old-client fallback)
    int64_t hb_count = 0;
    int64_t last_jitter_ms = 0;      // when a closed gap last blew the budget
    std::set<std::string> flags;     // active anomaly flags
    int64_t straggler_until_ms = 0;  // sticky display flag
    std::string last_signal;         // last failure-signal source (evidence)
    int64_t last_signal_ms = 0;      // when that signal was recorded
  };

  // Generation-tagged cached fleet snapshot (per job). The full /fleet.json
  // payload is only O(N)-rebuilt when the cached copy is older than
  // fleet_snap_ms; the rebuild copies raw rows under the job's hot lock
  // (cheap) and does the JSON build + dump OFF it, so heartbeats never wait
  // behind serialization. Keyed per job: one job's content change never
  // forces a rebuild of (or serves a stale gen to) another job.
  struct FleetSnapshot {
    int64_t gen = -1;       // job fleet_gen at build
    int64_t built_ms = 0;   // wall time at build (== payload ts_ms)
    Json json;              // the /fleet.json object
    std::string body;       // pre-dumped body served verbatim over HTTP
  };

  // One fully isolated control-plane island per job namespace. Everything
  // here is guarded by the island's OWN mu (snap by snap_mu, rebuilds by
  // rebuild_mu — same ordering discipline as the old instance-wide locks:
  // rebuild_mu strictly outside snap_mu and mu; snap_mu never held with
  // mu; never two jobs' mu held at once).
  struct JobState {
    std::string name;
    std::mutex mu;
    std::condition_variable cv;

    // ---- quorum plane ----
    LighthouseState state;
    std::optional<Quorum> last_quorum;  // most recently broadcast quorum
    int64_t quorum_gen = 0;             // bumped on every broadcast
    // Serialized {"ok":true,"quorum":...} built ONCE per formation and
    // shared by every waiter: with N waiters each dumping an O(N)
    // participant list the broadcast is O(N^2) — at N=1024 that was ~3.7 s
    // of lighthouse CPU per formation.
    std::shared_ptr<const std::string> quorum_payload;
    int64_t joins_total = 0;   // members added across quorum transitions
    int64_t leaves_total = 0;  // members gone across quorum transitions
    std::string last_reason;   // why no quorum yet (for status page)
    // Max quorum_id seen in this job's manager heartbeats. A takeover
    // standby resumes the job's numbering above it (strict monotonicity
    // across failover without a lighthouse-to-lighthouse channel).
    int64_t observed_quorum_id = 0;

    // ---- incremental-quorum gate counters (see quorum_gate_locked) ----
    std::set<std::string> prev_ids;  // ids of prev_quorum members
    int64_t prev_present = 0;        // prev_ids currently registered
    int64_t hb_not_joined = 0;       // heartbeating ids not registered

    // ---- fleet plane ----
    std::map<std::string, FleetEntry> fleet;
    std::deque<Json> anomalies;   // rise-edge anomaly ring (capped)
    int64_t anomaly_seq = 0;      // total anomalies ever (ring drops old)
    int64_t anomalies_dropped = 0;  // rise-edges evicted from the ring

    // ---- failure-evidence plane ----
    // Ring of failure signals (same discipline as the anomaly ring: capped,
    // overflow pops the oldest and bumps signals_dropped). Each entry:
    // {seq, ts_ms, replica_id, source, site, job, detail}. signal_seq is
    // the monotonic total ever recorded — consumers diff it as a cursor.
    std::deque<Json> signals;
    int64_t signal_seq = 0;
    int64_t signals_dropped = 0;
    std::map<std::string, int64_t> signal_counts;  // per-source totals
    int64_t fleet_gen = 0;  // bumped on every fleet-table mutation
    int64_t flagged = 0;    // entries with a non-empty flag set
    int64_t n_digest = 0;   // entries with a digest
    // Incremental O(log N) aggregate state, updated at digest arrival/leave.
    MedianTracker agg_rates;        // digest rates > 0
    MedianTracker agg_steps;        // digest steps (as double, like the sort)
    MedianTracker agg_gps;          // digest goodputs
    std::multiset<int64_t> agg_cfs;  // digest commit-failure streaks

    // ---- time-accounting (goodput) plane ----
    // Running per-kind badput second sums over rows whose digest carries
    // an "acct" vector — maintained at digest swap exactly like the
    // median trackers (remove old contribution, insert new), so the job
    // goodput fraction is O(1) at read time.
    double agg_badput[kNumBadputKinds] = {};
    int64_t n_acct = 0;          // rows currently contributing to agg_badput
    int64_t first_seen_ms = 0;   // first heartbeat ever (MTBF denominator)
    int64_t hard_signals = 0;    // hard-evidence rise edges (MTBF numerator)
    // ETTR episode: opened on a hard-signal rise, closed when any digest
    // advances past the fleet max step as of the fault (forward progress
    // resumed). One open episode at a time — overlapping faults extend it.
    bool ettr_open = false;
    int64_t ettr_open_ms = 0;
    int64_t ettr_open_step = 0;
    double ettr_sum_s = 0.0;
    int64_t ettr_n = 0;
    // SLO burn-rate evaluator: rise-edge slo_burn ring (same discipline
    // as the anomaly ring — monotone seq, bounded, drops counted).
    bool slo_burning = false;
    std::deque<Json> slo_burns;
    int64_t slo_seq = 0;
    int64_t slo_dropped = 0;

    // ---- per-job snapshot cache ----
    std::mutex snap_mu;     // guards snap only
    std::mutex rebuild_mu;  // single-flight rebuild
    std::shared_ptr<const FleetSnapshot> snap;
  };

  // District table kept by a ROOT lighthouse: one row per reporting district
  // lighthouse, fed by rollup-tagged heartbeat frames. Guarded by
  // districts_mu_ (never held together with a job mu).
  struct DistrictEntry {
    int64_t last_hb_ms = 0;
    int64_t epoch = 0;          // max fencing epoch seen (per-district fence)
    int64_t hb_count = 0;
    int64_t failovers = 0;      // epoch advances observed (district failover)
    int64_t stale_dropped = 0;  // rollups fenced out (old primary)
    bool lost = false;          // no rollup within heartbeat_timeout_ms
    Json rollup;                // last accepted per-job rollup
  };

  void accept_loop();
  void tick_loop();
  void district_loop();  // district -> root rollup sender
  void handle_conn(int fd);
  void handle_http(int fd);
  // `raw` (when non-null) lets the quorum path hand back the prebuilt
  // shared response bytes instead of a Json tree the caller would re-dump
  // per connection; when *raw is set the returned Json is meaningless.
  Json handle_request(const Json& req, int64_t deadline_ms,
                      std::shared_ptr<const std::string>* raw = nullptr);
  Json quorum_rpc(const Json& req, int64_t deadline_ms,
                  std::shared_ptr<const std::string>* raw = nullptr);
  std::string render_status_html();
  std::string render_metrics();
  Json status_json();

  // Job-island resolution: creates the island on first use (seeded from the
  // durable snapshot so quorum ids stay monotone across warm restarts).
  JobState& job_state(const std::string& job);
  std::vector<JobState*> all_jobs();

  // Runs one quorum evaluation for ONE job with js.mu held by the caller;
  // broadcasts (and notifies js.cv) when a quorum forms.
  void job_tick_locked(JobState& js, int64_t now);
  // O(1) gate: can a quorum POSSIBLY form for this job right now? Only a
  // pass pays the full quorum_compute; a miss defers to the periodic tick.
  bool quorum_gate_locked(const JobState& js) const;
  // Join/implicit-heartbeat bookkeeping shared by register + re-register,
  // maintaining the gate counters (js.mu held).
  void register_participant_locked(JobState& js, const QuorumMember& me);

  // All fleet_* helpers run with js.mu held by the caller.
  void fleet_note_heartbeat(JobState& js, const std::string& replica_id,
                            const Json& req, int64_t now);
  void fleet_scan_locked(JobState& js, int64_t now);  // time-based rules
  void fleet_set_flag(JobState& js, const std::string& replica_id,
                      FleetEntry& e, const std::string& kind, int64_t now,
                      Json detail);
  // Record one failure signal in the job's signal ring (js.mu held). The
  // caller decides whether to follow up with an evidence-driven
  // job_tick_locked; this only records + stamps the fleet row.
  void signal_note_locked(JobState& js, const std::string& source,
                          const std::string& replica_id,
                          const std::string& site, Json detail, int64_t now);
  // Evidence-driven hb-lapse eviction (js.mu held): drop `replica_id` from
  // the quorum tables with leave-style gate fixups but NO tombstone (a
  // relaunch rejoins normally) and keep the fleet row as forensics.
  void evidence_evict_locked(JobState& js, const std::string& replica_id,
                             int64_t now);
  void fleet_clear_flag(JobState& js, FleetEntry& e, const std::string& kind);
  void fleet_erase(JobState& js, const std::string& replica_id);
  void fleet_agg_remove(JobState& js, const FleetEntry& e);
  void fleet_agg_insert(JobState& js, const FleetEntry& e);
  int64_t fleet_jitter_budget_ms(const FleetEntry& e) const;
  Json fleet_summary_locked(JobState& js, int64_t now);  // status.json slice
  Json fleet_agg_locked(JobState& js, int64_t now);      // O(1)-ish agg dict
  Json hist_json() const;  // hot-path histograms for status

  // Per-job cached snapshot; empty job = the composite view (the default
  // job's payload extended with the cross-job summary + district table, so
  // pre-namespace consumers keep their top-level schema).
  std::shared_ptr<const FleetSnapshot> fleet_snapshot(const std::string& job,
                                                      int64_t now);

  // ---- federation (root side) ----
  Json district_note(const Json& req);     // absorb one rollup frame
  void district_scan(int64_t now);         // time-based district-loss rule
  Json districts_json(int64_t now);

  std::mutex jobs_mu_;  // guards the jobs_ map only (lookup/insert); job
                        // islands are never erased, so JobState* stay valid
  std::map<std::string, JobState> jobs_;

  std::mutex districts_mu_;
  std::map<std::string, DistrictEntry> districts_;
  int64_t district_losses_ = 0;  // districts that went silent (cumulative)

  // Hot-path latency histograms (lock-free, exported on /metrics and
  // status.json["hist"]).
  LatencyHist hist_heartbeat_;   // heartbeat RPC branch incl. lock wait
  LatencyHist hist_quorum_;      // quorum_compute inside tick
  LatencyHist hist_anomaly_;     // digest fold + anomaly rules per heartbeat
  LatencyHist hist_http_;        // whole HTTP request service
  LatencyHist hist_snapshot_;    // fleet snapshot rebuild (copy+build+dump)

  int64_t export_max_replicas_ = 64;  // TORCHFT_EXPORT_MAX_REPLICAS

  // SLO burn-rate knobs (TORCHFT_LH_SLO_*): goodput target, burn-rate
  // threshold that trips a slo_burn event, and the minimum accounted
  // seconds before the evaluator arms (startup/compile grace).
  double slo_goodput_ = 0.95;  // TORCHFT_LH_SLO_GOODPUT
  double slo_burn_ = 2.0;      // TORCHFT_LH_SLO_BURN
  double slo_min_s_ = 30.0;    // TORCHFT_LH_SLO_MIN_S

  std::string bind_host_;
  int port_;
  LighthouseOpts opts_;

  // ---- HA / fencing state (instance-global: there is ONE epoch owner per
  // lighthouse identity, shared by every job it serves) ----
  // Fencing epoch this instance stamps on quorums while active. Restored
  // from the durable snapshot on warm restart; bumped past observed_epoch_
  // on standby takeover. 0 only before a fresh active boot assigns 1.
  std::atomic<int64_t> epoch_{0};
  // Max epoch seen in manager heartbeats — the fleet's view of the current
  // owner. A standby uses it to fence its takeover epoch; an active
  // instance that observes a higher value has been superseded and demotes.
  std::atomic<int64_t> observed_epoch_{0};
  std::atomic<bool> active_{true};  // false = standby: absorb heartbeats only
  std::atomic<int64_t> takeovers_{0};   // standby -> active transitions
  std::atomic<int64_t> demotions_{0};   // active -> standby (fenced)
  // Serializes role transitions + durable saves; ordered strictly inside any
  // job mu (job mu -> persist_mu_, never the reverse).
  std::mutex persist_mu_;
  int64_t dur_quorum_id_ = 0;  // max quorum_id across jobs (persist_mu_)
  int64_t dur_gen_ = 0;        // max quorum_gen across jobs (persist_mu_)
  int64_t restored_quorum_id_ = 0;  // seeds for job islands created later
  int64_t restored_gen_ = 0;
  // Fold one job's freshly bumped ids into the durable maxima and fsync the
  // snapshot BEFORE the quorum publishes (ids stay monotone across crashes).
  void persist(int64_t job_qid, int64_t job_gen);
  void persist_locked(int64_t job_qid, int64_t job_gen);  // persist_mu_ held

  int listen_fd_ = -1;
  std::atomic<bool> running_{false};
  std::thread accept_thread_;
  std::thread tick_thread_;
  std::thread district_thread_;
  ConnTracker conns_;
};

}  // namespace tft
