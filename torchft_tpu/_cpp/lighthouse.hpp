// Lighthouse: the global quorum coordinator for torchft-tpu.
//
// Capability parity with the reference's src/lighthouse.rs:68-480:
// heartbeats + participants maps, a tick loop running quorum_compute every
// quorum_tick_ms, quorum_id bumps on membership change or commit failures,
// blocking Quorum requests answered via broadcast, an HTTP status dashboard
// served on the same port (sniffed by first bytes), and a kill endpoint that
// forwards a Kill message to a member's manager address.
//
// Wire protocol: length-prefixed JSON frames (see net.hpp). Requests:
//   {"type":"heartbeat","replica_id":...[,"digest":{...},"hb_interval_ms":N]}
//   {"type":"quorum","timeout_ms":N,"requester":{QuorumMember}}
//   {"type":"status"}
//   {"type":"fleet"}   (live fleet-health table, the framed twin of
//       GET /fleet.json: per-replica digest rows + aggregates + anomalies)
//   {"type":"kill","replica_id":...}
// HTTP: GET / or /status (dashboard), GET /fleet.json (live health table),
// GET/POST /replica/<id>/kill.
//
// Live fleet plane: heartbeats optionally carry a StepDigest (compact
// per-replica health summary built by telemetry.StepDigest). The lighthouse
// keeps a rolling per-replica fleet table, runs an online straggler/anomaly
// detector (relative step-rate slowdown vs the fleet median, heartbeat-gap
// jitter against the sender-declared cadence, commit-failure streaks), and
// serves it all at /fleet.json. Digest-driven rules evaluate at heartbeat
// ARRIVAL (same digest sequence => same anomaly sequence, so chaos replays
// reproduce alerts); only the time-based rules (open heartbeat gaps,
// staleness) live in the tick scan.
#pragma once

#include <atomic>
#include <condition_variable>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <vector>

#include "conn_tracker.hpp"
#include "quorum.hpp"

namespace tft {

// Lock-free log-bucket latency histogram: the C++ twin of
// telemetry._HIST_BOUNDS / _hist_percentile. Bucket i (i in 0..27) holds
// samples with latency <= 2^i microseconds (1 us doubling up to ~134 s);
// bucket 28 is overflow. Percentiles report the UPPER bound of the bucket
// containing the quantile, so they over-estimate within one power of two —
// identical semantics to the Python side, which keeps dashboards comparable
// across both planes.
class LatencyHist {
 public:
  static constexpr int kFinite = 28;
  static constexpr int kBuckets = kFinite + 1;

  struct Snap {
    int64_t count = 0;
    int64_t sum_us = 0;
    int64_t buckets[kBuckets] = {0};
  };

  // First bucket whose upper bound (2^i us) covers the sample; matches
  // bisect.bisect_left(_HIST_BOUNDS, dt) on the Python side.
  static int bucket_of(int64_t us) {
    if (us <= 1) return 0;
    for (int i = 1; i < kFinite; i++)
      if ((int64_t{1} << i) >= us) return i;
    return kFinite;  // overflow
  }

  void observe_us(int64_t us) {
    if (us < 0) us = 0;
    buckets_[bucket_of(us)].fetch_add(1, std::memory_order_relaxed);
    count_.fetch_add(1, std::memory_order_relaxed);
    sum_us_.fetch_add(us, std::memory_order_relaxed);
  }

  Snap snapshot() const {
    Snap s;
    s.count = count_.load(std::memory_order_relaxed);
    s.sum_us = sum_us_.load(std::memory_order_relaxed);
    for (int i = 0; i < kBuckets; i++)
      s.buckets[i] = buckets_[i].load(std::memory_order_relaxed);
    return s;
  }

  // Upper-bound quantile from bucket counts (telemetry._hist_percentile):
  // 0 with no samples; an empty bucket prefix never satisfies the target;
  // the overflow bucket reports the last finite bound.
  static int64_t percentile_us(const Snap& s, double q) {
    int64_t total = 0;
    for (int i = 0; i < kBuckets; i++) total += s.buckets[i];
    if (total == 0) return 0;
    double target = q * static_cast<double>(total);
    int64_t cum = 0;
    for (int i = 0; i < kBuckets; i++) {
      if (s.buckets[i] == 0) continue;
      cum += s.buckets[i];
      if (static_cast<double>(cum) >= target)
        return int64_t{1} << (i < kFinite ? i : kFinite - 1);
    }
    return int64_t{1} << (kFinite - 1);
  }

 private:
  std::atomic<int64_t> buckets_[kBuckets] = {};
  std::atomic<int64_t> count_{0};
  std::atomic<int64_t> sum_us_{0};
};

// Exact running median over a multiset of doubles with O(log N)
// insert/erase, replacing the per-heartbeat full-table sort. Maintains the
// same "upper median" the old fleet_median(sort) returned: lo_ holds the
// smaller floor(n/2) values, hi_ the larger ceil(n/2), so
// median() == sorted[n/2] bit-for-bit (the property tests in
// tests/test_fleet.py pin this equality against a full recompute).
class MedianTracker {
 public:
  void insert(double v) {
    if (hi_.empty() || v >= *hi_.begin())
      hi_.insert(v);
    else
      lo_.insert(v);
    rebalance();
  }

  // No-op if v is not present (defensive: an aggregate drift bug should
  // surface as a wrong median in the property test, not a crash).
  void erase(double v) {
    auto it = hi_.find(v);
    if (it != hi_.end()) {
      hi_.erase(it);
    } else {
      auto lo = lo_.find(v);
      if (lo == lo_.end()) return;
      lo_.erase(lo);
    }
    rebalance();
  }

  size_t size() const { return lo_.size() + hi_.size(); }
  double median() const { return hi_.empty() ? 0.0 : *hi_.begin(); }

 private:
  void rebalance() {
    while (hi_.size() > lo_.size() + 1) {
      lo_.insert(*hi_.begin());
      hi_.erase(hi_.begin());
    }
    while (lo_.size() > hi_.size()) {
      auto it = std::prev(lo_.end());
      hi_.insert(*it);
      lo_.erase(it);
    }
  }

  std::multiset<double> lo_, hi_;
};

class Lighthouse {
 public:
  Lighthouse(const std::string& bind_host, int port, LighthouseOpts opts);
  ~Lighthouse();

  // Starts listener + tick threads. Returns false if bind failed.
  bool start();
  void stop();

  int port() const { return port_; }
  std::string address() const;

  // Exposed for tests: runs one tick synchronously.
  void tick();

 private:
  void accept_loop();
  void tick_loop();
  void handle_conn(int fd);
  void handle_frame_conn(int fd, const std::string& first_payload);
  void handle_http(int fd);
  Json handle_request(const Json& req, int64_t deadline_ms);
  Json quorum_rpc(const Json& req, int64_t deadline_ms);
  std::string render_status_html();
  std::string render_metrics();
  Json status_json();

  // ---- live fleet health plane ----
  struct FleetEntry {
    Json digest;                     // last StepDigest wire dict
    bool has_digest = false;
    int64_t digest_ms = 0;           // arrival time of that digest
    int64_t last_hb_ms = 0;          // last heartbeat arrival
    int64_t hb_interval_ms = 0;      // sender-declared cadence (0 = unknown)
    double hb_gap_ewma_ms = 0.0;     // inter-arrival EWMA (old-client fallback)
    int64_t hb_count = 0;
    int64_t last_jitter_ms = 0;      // when a closed gap last blew the budget
    std::set<std::string> flags;     // active anomaly flags
    int64_t straggler_until_ms = 0;  // sticky display flag
  };
  // All fleet_* helpers run with mu_ held by the caller.
  void fleet_note_heartbeat(const std::string& replica_id, const Json& req,
                            int64_t now);
  void fleet_scan_locked(int64_t now);  // time-based rules (gaps, staleness)
  void fleet_set_flag(const std::string& replica_id, FleetEntry& e,
                      const std::string& kind, int64_t now, Json detail);
  void fleet_clear_flag(FleetEntry& e, const std::string& kind);
  void fleet_erase(const std::string& replica_id);
  void fleet_agg_remove(const FleetEntry& e);  // retire e.digest from aggs
  void fleet_agg_insert(const FleetEntry& e);  // fold e.digest into aggs
  int64_t fleet_jitter_budget_ms(const FleetEntry& e) const;
  Json fleet_summary_locked(int64_t now);  // the slice merged into status.json
  Json fleet_agg_locked(int64_t now);      // O(1)-ish agg dict from trackers
  Json hist_json() const;                  // hot-path histograms for status

  // Generation-tagged cached fleet snapshot. The full /fleet.json payload is
  // only O(N)-rebuilt when the cached copy is older than fleet_snap_ms; the
  // rebuild copies raw rows under mu_ (cheap) and does the JSON build + dump
  // OFF the hot lock, so heartbeats never wait behind serialization.
  struct FleetSnapshot {
    int64_t gen = -1;       // fleet_gen_ at build
    int64_t built_ms = 0;   // wall time at build (== payload ts_ms)
    Json json;              // the /fleet.json object
    std::string body;       // pre-dumped body served verbatim over HTTP
  };
  std::shared_ptr<const FleetSnapshot> fleet_snapshot(int64_t now);

  std::map<std::string, FleetEntry> fleet_;
  std::deque<Json> anomalies_;  // rise-edge anomaly ring (capped)
  int64_t anomaly_seq_ = 0;     // total anomalies ever (ring drops old ones)
  int64_t anomalies_dropped_ = 0;  // rise-edges evicted from the ring
  int64_t fleet_gen_ = 0;  // bumped on every fleet-table mutation (mu_)
  int64_t flagged_ = 0;    // entries with a non-empty flag set (mu_)
  int64_t n_digest_ = 0;   // entries with a digest (mu_)
  // Incremental O(log N) aggregate state, updated at digest arrival/leave —
  // replaces the full-table rescans that made /fleet.json and the anomaly
  // rules O(N) per heartbeat (all guarded by mu_).
  MedianTracker agg_rates_;       // digest rates > 0
  MedianTracker agg_steps_;       // digest steps (as double, like the sort)
  MedianTracker agg_gps_;         // digest goodputs
  std::multiset<int64_t> agg_cfs_;  // digest commit-failure streaks

  std::mutex snap_mu_;  // guards snap_ only; never held together with mu_
  // Serializes snapshot rebuilds (single-flight); ordered strictly outside
  // snap_mu_ and mu_, never acquired while either is held.
  std::mutex rebuild_mu_;
  std::shared_ptr<const FleetSnapshot> snap_;

  // Hot-path latency histograms (lock-free, exported on /metrics and
  // status.json["hist"]).
  LatencyHist hist_heartbeat_;   // heartbeat RPC branch incl. mu_ wait
  LatencyHist hist_quorum_;      // quorum_compute inside tick
  LatencyHist hist_anomaly_;     // digest fold + anomaly rules per heartbeat
  LatencyHist hist_http_;        // whole HTTP request service
  LatencyHist hist_snapshot_;    // fleet snapshot rebuild (copy+build+dump)

  int64_t export_max_replicas_ = 64;  // TORCHFT_EXPORT_MAX_REPLICAS

  std::string bind_host_;
  int port_;
  LighthouseOpts opts_;

  std::mutex mu_;
  std::condition_variable cv_;
  LighthouseState state_;
  std::optional<Quorum> last_quorum_;  // most recently broadcast quorum
  int64_t quorum_gen_ = 0;             // bumped on every broadcast
  int64_t joins_total_ = 0;   // members added across quorum transitions
  int64_t leaves_total_ = 0;  // members gone across quorum transitions
  std::string last_reason_;            // why no quorum yet (for status page)

  // ---- HA / fencing state (guarded by mu_ unless noted) ----
  // Fencing epoch this instance stamps on quorums while active. Restored
  // from the durable snapshot on warm restart; bumped past observed_epoch_
  // on standby takeover. 0 only before a fresh active boot assigns 1.
  int64_t epoch_ = 0;
  // Max epoch seen in manager heartbeats — the fleet's view of the current
  // owner. A standby uses it to fence its takeover epoch; an active
  // instance that observes a higher value has been superseded and demotes.
  int64_t observed_epoch_ = 0;
  // Max quorum_id seen in manager heartbeats. A standby resumes numbering
  // above it on takeover so quorum ids stay strictly monotone across
  // failover (a standby has no disk state from the old primary to restore).
  int64_t observed_quorum_id_ = 0;
  bool active_ = true;        // false = standby: absorb heartbeats only
  int64_t takeovers_ = 0;     // standby -> active transitions
  int64_t demotions_ = 0;     // active -> standby (fenced by higher epoch)
  // Persist {epoch_, state_.quorum_id, quorum_gen_} with mu_ held; called
  // before a new quorum is published so ids stay monotone across crashes.
  void persist_locked();

  int listen_fd_ = -1;
  std::atomic<bool> running_{false};
  std::thread accept_thread_;
  std::thread tick_thread_;
  ConnTracker conns_;
};

}  // namespace tft
