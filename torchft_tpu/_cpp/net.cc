#include "net.hpp"

#include <arpa/inet.h>
#include "chaos.hpp"
#include <errno.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <string.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

#include <chrono>
#include <thread>

namespace tft {

int64_t now_ms() {
  return std::chrono::duration_cast<std::chrono::milliseconds>(
             std::chrono::system_clock::now().time_since_epoch())
      .count();
}

uint64_t now_realtime_ns() {
  timespec ts{};
  clock_gettime(CLOCK_REALTIME, &ts);
  return static_cast<uint64_t>(ts.tv_sec) * 1000000000ull +
         static_cast<uint64_t>(ts.tv_nsec);
}

// Thread-local: a stripe job deltas this around one transfer; no other
// thread's misses can leak into the reading.
static thread_local uint64_t g_spin_count = 0;

uint64_t net_spin_count() { return g_spin_count; }

void sleep_ms(int64_t ms) {
  std::this_thread::sleep_for(std::chrono::milliseconds(ms));
}

static void set_nonblocking(int fd, bool nb) {
  int flags = fcntl(fd, F_GETFL, 0);
  if (nb)
    fcntl(fd, F_SETFL, flags | O_NONBLOCK);
  else
    fcntl(fd, F_SETFL, flags & ~O_NONBLOCK);
}

static void set_common_opts(int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  // TCP keep-alives stand in for the reference's HTTP2 keep-alives
  // (net.rs:13-18: 60s interval / 20s timeout).
  setsockopt(fd, SOL_SOCKET, SO_KEEPALIVE, &one, sizeof(one));
#ifdef TCP_KEEPIDLE
  int idle = 60, intvl = 20, cnt = 3;
  setsockopt(fd, IPPROTO_TCP, TCP_KEEPIDLE, &idle, sizeof(idle));
  setsockopt(fd, IPPROTO_TCP, TCP_KEEPINTVL, &intvl, sizeof(intvl));
  setsockopt(fd, IPPROTO_TCP, TCP_KEEPCNT, &cnt, sizeof(cnt));
#endif
}

int tcp_listen(const std::string& host, int port, int backlog) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (host.empty() || host == "0.0.0.0" || host == "::") {
    addr.sin_addr.s_addr = INADDR_ANY;
  } else if (inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1) {
    // Resolve hostname.
    addrinfo hints{}, *res = nullptr;
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    if (getaddrinfo(host.c_str(), nullptr, &hints, &res) != 0 || !res) {
      close(fd);
      return -1;
    }
    addr.sin_addr = reinterpret_cast<sockaddr_in*>(res->ai_addr)->sin_addr;
    freeaddrinfo(res);
  }
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      listen(fd, backlog) != 0) {
    close(fd);
    return -1;
  }
  return fd;
}

int bound_port(int fd) {
  sockaddr_in addr{};
  socklen_t len = sizeof(addr);
  if (getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &len) != 0) return -1;
  return ntohs(addr.sin_port);
}

int tcp_accept(int listen_fd, int timeout_ms) {
  pollfd pfd{listen_fd, POLLIN, 0};
  int rc = poll(&pfd, 1, timeout_ms);
  if (rc <= 0) return -1;
  int fd = accept(listen_fd, nullptr, nullptr);
  if (fd >= 0) set_common_opts(fd);
  return fd;
}

int tcp_connect(const std::string& host, int port, int64_t timeout_ms) {
  if (chaos::armed() && chaos::on_connect(host, port)) return -1;
  addrinfo hints{}, *res = nullptr;
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  std::string h = host.empty() ? "127.0.0.1" : host;
  if (h == "0.0.0.0" || h == "::") h = "127.0.0.1";
  if (getaddrinfo(h.c_str(), std::to_string(port).c_str(), &hints, &res) != 0 ||
      !res)
    return -1;
  int fd = socket(res->ai_family, res->ai_socktype, res->ai_protocol);
  if (fd < 0) {
    freeaddrinfo(res);
    return -1;
  }
  set_nonblocking(fd, true);
  int rc = connect(fd, res->ai_addr, res->ai_addrlen);
  freeaddrinfo(res);
  if (rc != 0 && errno != EINPROGRESS) {
    close(fd);
    return -1;
  }
  if (rc != 0) {
    pollfd pfd{fd, POLLOUT, 0};
    rc = poll(&pfd, 1, static_cast<int>(timeout_ms));
    if (rc <= 0) {
      close(fd);
      return -1;
    }
    int err = 0;
    socklen_t len = sizeof(err);
    getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &len);
    if (err != 0) {
      close(fd);
      return -1;
    }
  }
  set_nonblocking(fd, false);
  set_common_opts(fd);
  return fd;
}

int tcp_connect_retry(const std::string& host, int port, int64_t timeout_ms,
                      int64_t attempt_ms) {
  // Exponential backoff mirroring reference net.rs/retry.rs:
  // 100ms initial, x1.5 multiplier, 10s max interval, until deadline.
  // Full jitter (seeded, deterministic per (host:port, attempt) — see
  // chaos::backoff_unit) keeps a fleet of reconnecting peers from retrying
  // in lockstep after a partition heals.
  int64_t deadline = now_ms() + timeout_ms;
  int64_t backoff = 100;
  if (attempt_ms <= 0) attempt_ms = 5000;
  const std::string key = host + ":" + std::to_string(port);
  uint64_t attempt = 0;
  while (true) {
    int64_t remaining = deadline - now_ms();
    if (remaining <= 0) return -1;
    int fd = tcp_connect(host, port, std::min<int64_t>(remaining, attempt_ms));
    if (fd >= 0) return fd;
    remaining = deadline - now_ms();
    if (remaining <= 0) return -1;
    const int64_t cap = std::min(backoff, remaining);
    const int64_t jittered = std::max<int64_t>(
        10, static_cast<int64_t>(chaos::backoff_unit(key, attempt) *
                                 static_cast<double>(cap)));
    sleep_ms(std::min(jittered, remaining));
    backoff = std::min<int64_t>(static_cast<int64_t>(backoff * 1.5), 10000);
    ++attempt;
  }
}

bool split_host_port(const std::string& addr_in, std::string* host, int* port) {
  // Accept scheme-prefixed URLs (the reference's TORCHFT_LIGHTHOUSE is
  // e.g. http://host:29510) and trailing slashes.
  std::string addr = addr_in;
  size_t scheme = addr.find("://");
  if (scheme != std::string::npos) addr = addr.substr(scheme + 3);
  if (!addr.empty() && addr[0] != '[') {  // keep [v6] brackets intact
    size_t slash = addr.find('/');
    if (slash != std::string::npos) addr = addr.substr(0, slash);
  }
  while (!addr.empty() && addr.back() == '/') addr.pop_back();
  if (addr.empty()) return false;
  size_t colon;
  if (addr[0] == '[') {  // [v6]:port
    size_t close_b = addr.find(']');
    if (close_b == std::string::npos || close_b + 1 >= addr.size() ||
        addr[close_b + 1] != ':')
      return false;
    *host = addr.substr(1, close_b - 1);
    colon = close_b + 1;
  } else {
    colon = addr.rfind(':');
    if (colon == std::string::npos) return false;
    *host = addr.substr(0, colon);
  }
  try {
    *port = std::stoi(addr.substr(colon + 1));
  } catch (...) {
    return false;
  }
  if (*host == "::" || host->empty()) *host = "127.0.0.1";
  return true;
}

static bool wait_fd(int fd, short events, int64_t deadline) {
  int64_t remaining = deadline - now_ms();
  if (remaining < 0) remaining = 0;
  pollfd pfd{fd, events, 0};
  int rc = poll(&pfd, 1, static_cast<int>(remaining));
  return rc > 0 && (pfd.revents & (events | POLLHUP | POLLERR));
}

static bool write_all_inner(int fd, const char* data, size_t len,
                            int64_t deadline) {
  size_t off = 0;
  while (off < len) {
    // Optimistic fast path: MSG_DONTWAIT keeps the call non-blocking on a
    // blocking fd, so we only pay a poll() when the socket buffer is full.
    ssize_t n = send(fd, data + off, len - off, MSG_NOSIGNAL | MSG_DONTWAIT);
    if (n < 0) {
      if (errno == EINTR) continue;
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        ++g_spin_count;
        if (!wait_fd(fd, POLLOUT, deadline)) return false;
        continue;
      }
      return false;
    }
    off += static_cast<size_t>(n);
  }
  return true;
}

bool write_all(int fd, const char* data, size_t len, int64_t timeout_ms) {
  int64_t deadline = now_ms() + timeout_ms;
  if (chaos::armed()) {
    chaos::Decision d = chaos::on_write(fd, len);
    if (d.kind == chaos::kPartialWrite) {
      // Write a prefix through the REAL path, then tear the connection:
      // the peer sees a torn transfer, this side reports failure.
      size_t cut = static_cast<size_t>(static_cast<double>(len) * d.frac);
      if (cut > 0) write_all_inner(fd, data, cut, deadline);
      shutdown(fd, SHUT_RDWR);
      return false;
    }
    if (d.kind == chaos::kReset) {
      shutdown(fd, SHUT_RDWR);
      return false;
    }
  }
  return write_all_inner(fd, data, len, deadline);
}

static bool read_all(int fd, char* data, size_t len, int64_t deadline) {
  size_t off = 0;
  while (off < len) {
    ssize_t n = recv(fd, data + off, len - off, MSG_DONTWAIT);
    if (n < 0) {
      if (errno == EINTR) continue;
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        ++g_spin_count;
        if (!wait_fd(fd, POLLIN, deadline)) return false;
        continue;
      }
      return false;
    }
    if (n == 0) return false;  // peer closed
    off += static_cast<size_t>(n);
  }
  return true;
}

bool read_exact(int fd, char* data, size_t len, int64_t timeout_ms) {
  int64_t deadline = now_ms() + timeout_ms;
  if (chaos::armed()) {
    chaos::Decision d = chaos::on_read(fd, len);
    if (d.kind == chaos::kReset) {
      shutdown(fd, SHUT_RDWR);
      return false;
    }
  }
  return read_all(fd, data, len, deadline);
}

bool send_frame(int fd, const std::string& payload, int64_t timeout_ms) {
  uint32_t len = htonl(static_cast<uint32_t>(payload.size()));
  std::string buf(reinterpret_cast<char*>(&len), 4);
  buf += payload;
  return write_all(fd, buf.data(), buf.size(), timeout_ms);
}

bool recv_frame(int fd, std::string* out, int64_t timeout_ms) {
  int64_t deadline = now_ms() + timeout_ms;
  uint32_t len_be = 0;
  if (!read_all(fd, reinterpret_cast<char*>(&len_be), 4, deadline)) return false;
  uint32_t len = ntohl(len_be);
  if (len > (1u << 30)) return false;  // 1 GiB sanity cap
  out->resize(len);
  return read_all(fd, out->data(), len, deadline);
}

bool call_json(int fd, const Json& req, Json* resp, int64_t timeout_ms) {
  int64_t deadline = now_ms() + timeout_ms;
  if (!send_frame(fd, req.dump(), timeout_ms)) return false;
  std::string raw;
  int64_t remaining = deadline - now_ms();
  if (remaining < 1) remaining = 1;
  if (!recv_frame(fd, &raw, remaining)) return false;
  return Json::parse(raw, resp);
}

bool call_json_addr(const std::string& addr, const Json& req, Json* resp,
                    int64_t timeout_ms) {
  std::string host;
  int port = 0;
  if (!split_host_port(addr, &host, &port)) return false;
  int fd = tcp_connect(host, port, timeout_ms);
  if (fd < 0) return false;
  bool ok = call_json(fd, req, resp, timeout_ms);
  close(fd);
  return ok;
}

int peek_bytes(int fd, char* buf, int n, int timeout_ms) {
  pollfd pfd{fd, POLLIN, 0};
  if (poll(&pfd, 1, timeout_ms) <= 0) return -1;
  return static_cast<int>(recv(fd, buf, n, MSG_PEEK));
}

void watch_parent(int64_t parent_pid, std::function<void()> on_death) {
  std::thread([parent_pid, on_death = std::move(on_death)] {
    while (true) {
      if (static_cast<int64_t>(getppid()) != parent_pid) {
        fprintf(stderr, "parent %lld died; exiting\n",
                static_cast<long long>(parent_pid));
        if (on_death) on_death();
        _exit(2);
      }
      sleep_ms(500);
    }
  }).detach();
}

std::string read_http_request(int fd, int timeout_ms) {
  // Reads headers up to the blank line (control-plane GET/POSTs carry no body
  // we care about).
  int64_t deadline = now_ms() + timeout_ms;
  std::string req;
  char c;
  while (req.size() < 65536) {
    if (!wait_fd(fd, POLLIN, deadline)) break;
    ssize_t n = recv(fd, &c, 1, 0);
    if (n <= 0) break;
    req += c;
    if (req.size() >= 4 && req.compare(req.size() - 4, 4, "\r\n\r\n") == 0)
      break;
    if (req.size() >= 2 && req.compare(req.size() - 2, 2, "\n\n") == 0) break;
  }
  return req;
}

}  // namespace tft
