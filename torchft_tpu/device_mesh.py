"""ManagedMesh: splice the fault-tolerant replica axis onto a JAX mesh.

Reference: ``torchft/device_mesh.py:50-336`` (``ManagedDeviceMesh`` /
``ft_init_device_mesh``) splices a ``ManagedProcessGroup`` replica dimension
into a torch ``DeviceMesh`` so HSDP/FSDP2+TP see a resizable replicate dim.

TPU-first translation: XLA SPMD compiles for a *fixed* topology, so the
replica axis must never be a compiled mesh axis (SURVEY.md hard-part #1).
``ManagedMesh`` therefore pairs:

- an inner ``jax.sharding.Mesh`` over this replica group's chips — its axes
  (dp/fsdp/sp/tp) are static, compiled, and ride ICI; and
- the Manager's dynamic replica axis — host-driven over DCN, sized by the
  live quorum (``num_participants``), contributing the outer gradient (or
  pseudogradient) average.

The object answers the same questions the reference's mesh answers (axis
sizes incl. the dynamic replicate dim, ranks/coordinates, sub-axis lookup)
and carries the outer collective (``allreduce_grads``) so trainers write
mesh-relative code without touching the Manager directly.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple, Union

from jax.sharding import Mesh

from torchft_tpu.ddp import DistributedDataParallel
from torchft_tpu.manager import Manager


class MeshView:
    """A named-axis selection (or flattening) of a :class:`ManagedMesh` —
    the jax translation of the reference's sub-mesh objects
    (``ManagedDeviceMesh.__getitem__`` / ``_FlattenDeviceMesh``,
    reference device_mesh.py:92-236).

    XLA needs no sub-mesh to RUN collectives (axis names in a
    ``PartitionSpec``/``shard_map`` are enough), so a view answers the
    questions trainers hold a torch submesh for — sizes, coordinates,
    composite rank — and, when the managed replica axis is part of the
    selection, carries the outer ``allreduce_grads``.  Views are cheap,
    immutable, and never copies of device arrays."""

    def __init__(
        self,
        parent: "ManagedMesh",
        names: Tuple[str, ...],
        flat_name: Optional[str] = None,
    ) -> None:
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate axis in view selection {names}")
        for n in names:
            if n != ManagedMesh.REPLICA_AXIS and n not in parent.mesh.shape:
                raise KeyError(
                    f"axis {n!r} not in {parent.axis_names} "
                    "(flattened names resolve via mesh[name], not views)"
                )
        self._parent = parent
        self.names = tuple(names)
        self.flat_name = flat_name

    # -- shape ------------------------------------------------------------

    @property
    def has_replica(self) -> bool:
        return ManagedMesh.REPLICA_AXIS in self.names

    def _axis_size(self, name: str) -> int:
        if name == ManagedMesh.REPLICA_AXIS:
            return self._parent.replica_size()
        return self._parent.mesh.shape[name]

    def size(self, axis: Optional[str] = None) -> int:
        """Product over the view's axes (or one axis's extent).  A
        flattened view's total is exactly this product — the reference's
        ``_FlattenDeviceMesh.size`` contract."""
        if axis is not None:
            if axis not in self.names:
                raise KeyError(f"axis {axis!r} not in view {self.names}")
            return self._axis_size(axis)
        n = 1
        for name in self.names:
            n *= self._axis_size(name)
        return n

    def shape(self) -> Dict[str, int]:
        return {n: self._axis_size(n) for n in self.names}

    # -- coordinates ------------------------------------------------------

    def coordinate(self, device: Any = None) -> Dict[str, Optional[int]]:
        """Per-axis coordinate: the replica axis reads the manager's live
        participating rank (None while healing/spare); inner axes read
        ``device``'s position in the mesh (default: this process's first
        local device in the mesh)."""
        coords: Dict[str, Optional[int]] = {}
        inner = [n for n in self.names if n != ManagedMesh.REPLICA_AXIS]
        inner_coords = (
            self._parent.device_coordinate(device) if inner else {}
        )
        for n in self.names:
            if n == ManagedMesh.REPLICA_AXIS:
                coords[n] = self._parent.replica_rank()
            else:
                coords[n] = inner_coords[n]
        return coords

    def rank(self, device: Any = None) -> Optional[int]:
        """Row-major composite rank over the view's axes (replica axis
        included when selected — with names ``(replica, *inner)`` this is
        the reference's ``get_local_rank(None)`` formula
        ``inner_size * replica_rank + inner_rank``).  None while this
        group is healing/spare (no replica rank yet)."""
        coords = self.coordinate(device)
        rank = 0
        for n in self.names:
            c = coords[n]
            if c is None:
                return None
            rank = rank * self._axis_size(n) + int(c)
        return rank

    # -- jax-side helpers --------------------------------------------------

    def partition_spec(self) -> Any:
        """``PartitionSpec`` over the view's INNER axes in order (the
        replica axis is never a compiled mesh axis — SURVEY hard-part #1
        — so it never appears in a sharding)."""
        from jax.sharding import PartitionSpec

        return PartitionSpec(
            *[n for n in self.names if n != ManagedMesh.REPLICA_AXIS]
        )

    # -- collectives -------------------------------------------------------

    def allreduce_grads(
        self,
        grads: Any,
        should_quantize: bool = False,
        quantize_bits: int = 8,
    ) -> Any:
        if not self.has_replica:
            raise ValueError(
                f"view {self.names} has no managed axis; inner-axis "
                "reductions are XLA collectives (psum over the axis name "
                "inside the compiled step), not manager collectives"
            )
        return self._parent.allreduce_grads(
            grads,
            should_quantize=should_quantize,
            quantize_bits=quantize_bits,
        )

    def __repr__(self) -> str:
        label = f" as {self.flat_name!r}" if self.flat_name else ""
        return f"MeshView({self.names}{label}, shape={self.shape()})"


class ManagedMesh:
    """An inner SPMD mesh + the managed (fault-tolerant) replica axis.

    ``size()`` of the replica axis is dynamic — it reflects the current
    quorum (clamped >= 1 like the reference's ``ManagedDeviceMesh.size``,
    device_mesh.py:165-180); all other axes are the static jax mesh sizes.
    """

    REPLICA_AXIS = "replica"

    def __init__(
        self,
        manager: Manager,
        mesh: Mesh,
        bucket_cap_mb: float = 32.0,
    ) -> None:
        self.manager = manager
        self.mesh = mesh
        self._ddp = DistributedDataParallel(manager, bucket_cap_mb=bucket_cap_mb)
        self._flattened: Dict[str, MeshView] = {}
        self._coord_cache: Dict[Any, Dict[str, int]] = {}

    # -- shape ------------------------------------------------------------

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return (self.REPLICA_AXIS,) + tuple(self.mesh.axis_names)

    def size(self, axis: Optional[str] = None) -> int:
        if axis is None:
            return self.replica_size() * self.inner_size()
        if axis == self.REPLICA_AXIS:
            return self.replica_size()
        return self.mesh.shape[axis]

    def replica_size(self) -> int:
        """Live replica-group count (>=1 even before the first quorum)."""
        return max(self.manager.num_participants(), 1)

    def inner_size(self) -> int:
        n = 1
        for s in self.mesh.shape.values():
            n *= s
        return n

    def shape(self) -> Dict[str, int]:
        out = {self.REPLICA_AXIS: self.replica_size()}
        out.update(self.mesh.shape)
        return out

    @property
    def ndim(self) -> int:
        """Inner axes + the managed replica axis (reference: ndim)."""
        return len(self.mesh.axis_names) + 1

    # -- selection / flattening (reference device_mesh.py:92-236) ---------

    def __getitem__(
        self, names: Union[str, Tuple[str, ...]]
    ) -> MeshView:
        """Sub-mesh selection by axis name(s), including the replica axis
        and names registered by :meth:`flatten` — the reference's
        ``ManagedDeviceMesh.__getitem__``."""
        if isinstance(names, str):
            if names in self._flattened:
                return self._flattened[names]
            names = (names,)
        return MeshView(self, tuple(names))

    def flatten(
        self,
        names: Optional[Sequence[str]] = None,
        *,
        name: str,
    ) -> MeshView:
        """Registers (and returns) a flattened view over ``names``
        (default: every axis, replica first) addressable as
        ``mesh[name]`` — the reference's ``_flatten``.  The flattened
        size is the axes' product; the flattened rank is the row-major
        composite (dynamic on the replica axis)."""
        if names is None:
            names = self.axis_names
        if name in self.axis_names:
            raise ValueError(
                f"flatten name {name!r} would shadow a real axis "
                f"({self.axis_names}) in __getitem__"
            )
        prior = self._flattened.get(name)
        if prior is not None:
            if prior.names == tuple(names):
                return prior  # idempotent re-register
            raise ValueError(
                f"flatten name {name!r} already registered over "
                f"{prior.names}; pick a distinct name"
            )
        view = MeshView(self, tuple(names), flat_name=name)
        self._flattened[name] = view
        return view

    # -- coordinates ------------------------------------------------------

    def replica_rank(self) -> Optional[int]:
        """This group's rank on the replica axis (None while healing/spare —
        reference: participating_rank)."""
        return self.manager.participating_rank()

    def device_coordinate(self, device: Any = None) -> Dict[str, int]:
        """``device``'s per-axis position in the inner mesh (default:
        this process's first local device that is in the mesh — an
        error, not a fabricated (0,...), when none is: a silent
        fallback would collide composite ranks across hosts).  The
        inner-axis half of the reference's ``get_coordinate``.
        Memoized: a device's mesh position is static."""
        cached = self._coord_cache.get(device)
        if cached is not None:
            return dict(cached)
        import numpy as np

        devs = self.mesh.devices
        key = device
        if device is None:
            import jax

            local = set(jax.local_devices())
            device = next((d for d in devs.flat if d in local), None)
            if device is None:
                raise ValueError(
                    "none of this process's local devices are in the "
                    f"mesh {self.mesh}; pass the device explicitly"
                )
        pos = np.argwhere(devs == device)
        if len(pos) != 1:
            raise ValueError(f"device {device} not in mesh {self.mesh}")
        coords = {
            a: int(i) for a, i in zip(self.mesh.axis_names, pos[0])
        }
        self._coord_cache[key] = coords
        return dict(coords)

    def coordinate(self, device: Any = None) -> Dict[str, Any]:
        """Full per-axis coordinate: live replica rank (None while
        healing/spare) + the device's inner-mesh position (reference:
        get_coordinate, device_mesh.py:219-233)."""
        return {
            self.REPLICA_AXIS: self.replica_rank(),
            **self.device_coordinate(device),
        }

    # -- collectives ------------------------------------------------------

    def allreduce_grads(
        self,
        grads: Any,
        should_quantize: bool = False,
        quantize_bits: int = 8,
    ) -> Any:
        """Average a gradient pytree across the replica axis (the managed
        dim's allreduce — what ManagedProcessGroup.allreduce is to DDP in the
        reference, process_group.py:1205-1238)."""
        return self._ddp.allreduce_grads(
            grads,
            should_quantize=should_quantize,
            quantize_bits=quantize_bits,
        )

    def __repr__(self) -> str:
        return (
            f"ManagedMesh(replica~{self.replica_size()}, "
            f"inner={dict(self.mesh.shape)})"
        )


def ft_init_device_mesh(
    manager: Manager,
    *,
    dp: int = 1,
    fsdp: int = 1,
    sp: int = 1,
    tp: int = 1,
    devices: Any = None,
    mesh: Optional[Mesh] = None,
) -> ManagedMesh:
    """Builds the inner mesh and wraps it with the managed replica axis
    (reference: ft_init_device_mesh, device_mesh.py:303-336)."""
    if mesh is None:
        # Imported lazily: the FT control plane must not require the model
        # stack (flax/optax via torchft_tpu.parallel) at import time.
        from torchft_tpu.parallel.mesh import auto_mesh, make_mesh

        if dp == fsdp == sp == tp == 1 and devices is None:
            mesh = auto_mesh()
        else:
            mesh = make_mesh(dp=dp, fsdp=fsdp, sp=sp, tp=tp, devices=devices)
    return ManagedMesh(manager, mesh)
