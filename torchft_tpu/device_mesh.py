"""ManagedMesh: splice the fault-tolerant replica axis onto a JAX mesh.

Reference: ``torchft/device_mesh.py:50-336`` (``ManagedDeviceMesh`` /
``ft_init_device_mesh``) splices a ``ManagedProcessGroup`` replica dimension
into a torch ``DeviceMesh`` so HSDP/FSDP2+TP see a resizable replicate dim.

TPU-first translation: XLA SPMD compiles for a *fixed* topology, so the
replica axis must never be a compiled mesh axis (SURVEY.md hard-part #1).
``ManagedMesh`` therefore pairs:

- an inner ``jax.sharding.Mesh`` over this replica group's chips — its axes
  (dp/fsdp/sp/tp) are static, compiled, and ride ICI; and
- the Manager's dynamic replica axis — host-driven over DCN, sized by the
  live quorum (``num_participants``), contributing the outer gradient (or
  pseudogradient) average.

The object answers the same questions the reference's mesh answers (axis
sizes incl. the dynamic replicate dim, ranks/coordinates, sub-axis lookup)
and carries the outer collective (``allreduce_grads``) so trainers write
mesh-relative code without touching the Manager directly.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

from jax.sharding import Mesh

from torchft_tpu.ddp import DistributedDataParallel
from torchft_tpu.manager import Manager


class ManagedMesh:
    """An inner SPMD mesh + the managed (fault-tolerant) replica axis.

    ``size()`` of the replica axis is dynamic — it reflects the current
    quorum (clamped >= 1 like the reference's ``ManagedDeviceMesh.size``,
    device_mesh.py:165-180); all other axes are the static jax mesh sizes.
    """

    REPLICA_AXIS = "replica"

    def __init__(
        self,
        manager: Manager,
        mesh: Mesh,
        bucket_cap_mb: float = 32.0,
    ) -> None:
        self.manager = manager
        self.mesh = mesh
        self._ddp = DistributedDataParallel(manager, bucket_cap_mb=bucket_cap_mb)

    # -- shape ------------------------------------------------------------

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return (self.REPLICA_AXIS,) + tuple(self.mesh.axis_names)

    def size(self, axis: Optional[str] = None) -> int:
        if axis is None:
            return self.replica_size() * self.inner_size()
        if axis == self.REPLICA_AXIS:
            return self.replica_size()
        return self.mesh.shape[axis]

    def replica_size(self) -> int:
        """Live replica-group count (>=1 even before the first quorum)."""
        return max(self.manager.num_participants(), 1)

    def inner_size(self) -> int:
        n = 1
        for s in self.mesh.shape.values():
            n *= s
        return n

    def shape(self) -> Dict[str, int]:
        out = {self.REPLICA_AXIS: self.replica_size()}
        out.update(self.mesh.shape)
        return out

    # -- coordinates ------------------------------------------------------

    def replica_rank(self) -> Optional[int]:
        """This group's rank on the replica axis (None while healing/spare —
        reference: participating_rank)."""
        return self.manager.participating_rank()

    def coordinate(self) -> Dict[str, Any]:
        return {self.REPLICA_AXIS: self.replica_rank(), **{
            a: None for a in self.mesh.axis_names
        }}

    # -- collectives ------------------------------------------------------

    def allreduce_grads(
        self,
        grads: Any,
        should_quantize: bool = False,
        quantize_bits: int = 8,
    ) -> Any:
        """Average a gradient pytree across the replica axis (the managed
        dim's allreduce — what ManagedProcessGroup.allreduce is to DDP in the
        reference, process_group.py:1205-1238)."""
        return self._ddp.allreduce_grads(
            grads,
            should_quantize=should_quantize,
            quantize_bits=quantize_bits,
        )

    def __repr__(self) -> str:
        return (
            f"ManagedMesh(replica~{self.replica_size()}, "
            f"inner={dict(self.mesh.shape)})"
        )


def ft_init_device_mesh(
    manager: Manager,
    *,
    dp: int = 1,
    fsdp: int = 1,
    sp: int = 1,
    tp: int = 1,
    devices: Any = None,
    mesh: Optional[Mesh] = None,
) -> ManagedMesh:
    """Builds the inner mesh and wraps it with the managed replica axis
    (reference: ft_init_device_mesh, device_mesh.py:303-336)."""
    if mesh is None:
        # Imported lazily: the FT control plane must not require the model
        # stack (flax/optax via torchft_tpu.parallel) at import time.
        from torchft_tpu.parallel.mesh import auto_mesh, make_mesh

        if dp == fsdp == sp == tp == 1 and devices is None:
            mesh = auto_mesh()
        else:
            mesh = make_mesh(dp=dp, fsdp=fsdp, sp=sp, tp=tp, devices=devices)
    return ManagedMesh(manager, mesh)
