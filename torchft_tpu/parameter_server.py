"""Prototype fault-tolerant parameter server (no Lighthouse involved).

Reference: ``torchft/parameter_server.py:31-195`` — an HTTP endpoint
``/new_session`` hands out a session id + store address; server and client
then each ``configure`` a fresh 2-rank process group (server rank 0) and the
per-session handler thread serves the user's ``forward`` over pg send/recv.

Here sessions run over :class:`ProcessGroupSocket`; payloads are numpy
pytrees moved with the process-group send/recv primitives. Subclass and
implement :meth:`forward`.
"""

from __future__ import annotations

import concurrent.futures as _futures
import json
import threading
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, List, Optional

import numpy as np

from torchft_tpu.process_group import ProcessGroupSocket
from torchft_tpu.store import TCPStoreServer

_SESSION_PREFIX = "ps_session"


class ParameterServer:
    """Serves parameters / computation to dynamically-joining clients."""

    def __init__(self, port: int = 0, timeout: float = 30.0) -> None:
        self._timeout = timeout
        self._store = TCPStoreServer()
        ps = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self) -> None:  # noqa: N802 (http.server API)
                if self.path != "/new_session":
                    self.send_error(404)
                    return
                session_id = str(uuid.uuid4())
                thread = threading.Thread(
                    target=ps._serve_session,
                    args=(session_id,),
                    name=f"ps-session-{session_id[:8]}",
                    daemon=True,
                )
                thread.start()
                body = json.dumps(
                    {
                        "session_id": session_id,
                        "store_addr": ps._store.address(),
                    }
                ).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt: str, *args: Any) -> None:
                pass

        self._http = ThreadingHTTPServer(("0.0.0.0", port), Handler)
        self._http_thread = threading.Thread(
            target=self._http.serve_forever, daemon=True
        )
        self._http_thread.start()

    def address(self) -> str:
        from torchft_tpu.coordination import advertise_host

        port = self._http.server_address[1]
        # Advertise a host remote clients can actually reach (the wildcard
        # bind accepts them; TORCHFT_HOST_ADDR overrides for multi-host).
        return f"http://{advertise_host()}:{port}"

    # -- session plumbing --------------------------------------------------

    def _session_store(self, session_id: str) -> str:
        return f"{self._store.address()}/{_SESSION_PREFIX}/{session_id}"

    def _serve_session(self, session_id: str) -> None:
        pg = ProcessGroupSocket(timeout=self._timeout)
        try:
            pg.configure(self._session_store(session_id), rank=0, world_size=2)
            # An idle-but-live session must not trip the per-tag collective
            # timeout: the first INNER recv timeout would latch
            # pg.errored(), after which every re-issued recv fails
            # instantly — a busy-spin that never serves the client's next
            # request. Keep the short timeout for the rendezvous above,
            # then widen it and poll the SAME pending recv in _timeout
            # slices; a dead client's connection EOF fails that recv
            # promptly via the peer-death fast path, ending the session.
            pg.set_timeout(365 * 86400.0)
            while True:
                work = pg.recv(src=1, tag="ps.req")
                while True:
                    try:
                        (request,) = work.wait(self._timeout)
                        break
                    # concurrent.futures.TimeoutError spelled explicitly:
                    # it only became an alias of the builtin in 3.11, and
                    # this package supports 3.10 — the bare builtin would
                    # fall through to the session-over branch there.
                    except (TimeoutError, _futures.TimeoutError):
                        continue  # idle-but-live: keep the session open
                    # CancelledError descends from BaseException (3.8+), so
                    # a bare Exception clause misses it: an aborted pg
                    # (executor shutdown with cancel_futures=True) would
                    # crash the session thread instead of ending cleanly.
                    except (_futures.CancelledError, Exception):
                        return  # connection closed/aborted: session over
                response = self.forward(session_id, request)
                pg.send([np.asarray(response)], dst=1, tag="ps.resp").wait(
                    self._timeout
                )
        finally:
            pg.shutdown()

    # -- override me -------------------------------------------------------

    def forward(self, session_id: str, request: np.ndarray) -> np.ndarray:
        """Handles one request tensor; override in subclasses (reference:
        parameter_server.py:107-195 example echoes/updates params)."""
        raise NotImplementedError

    def shutdown(self) -> None:
        self._http.shutdown()
        self._store.shutdown()


class ParameterServerClient:
    """Client side: POST /new_session, then exchange tensors over the pg."""

    def __init__(self, server_url: str, timeout: float = 30.0) -> None:
        import urllib.request

        self._timeout = timeout
        with urllib.request.urlopen(
            urllib.request.Request(f"{server_url}/new_session", method="POST"),
            timeout=timeout,
        ) as resp:
            info = json.loads(resp.read())
        self._pg = ProcessGroupSocket(timeout=timeout)
        self._pg.configure(
            f"{info['store_addr']}/{_SESSION_PREFIX}/{info['session_id']}",
            rank=1,
            world_size=2,
        )

    def call(self, request: np.ndarray) -> np.ndarray:
        self._pg.send([np.asarray(request)], dst=0, tag="ps.req").wait(
            self._timeout
        )
        (resp,) = self._pg.recv(src=0, tag="ps.resp").wait(self._timeout)
        return resp

    def close(self) -> None:
        self._pg.shutdown()
