"""Work: the async handle returned by process-group collectives.

Analog of torch.distributed's ``Work`` as used by the reference
(torchft/work.py:9-20, torchft/process_group.py): a future-like object with
``wait``/``done``/``exception`` plus callback chaining. Backed by
``concurrent.futures.Future`` — JAX has no exposed stream objects, so
completion is host-side (the device-side analog is JAX async dispatch; see
manager._ManagedWork for the divide-by-N callback chaining).
"""

from __future__ import annotations

import concurrent.futures
from typing import Any, Callable, Optional


class Work:
    """Base async work handle."""

    def wait(self, timeout: Optional[float] = None) -> Any:
        raise NotImplementedError

    def done(self) -> bool:
        raise NotImplementedError

    def exception(self) -> Optional[BaseException]:
        raise NotImplementedError

    def result(self, timeout: Optional[float] = None) -> Any:
        return self.wait(timeout)

    def add_done_callback(self, fn: Callable[["Work"], None]) -> None:
        raise NotImplementedError


class DummyWork(Work):
    """Already-completed work with a preset result (reference: _DummyWork,
    torchft/work.py:9-20). Returned when a rank doesn't participate or after
    an error has been latched."""

    def __init__(self, result: Any = None) -> None:
        self._result = result

    def wait(self, timeout: Optional[float] = None) -> Any:
        return self._result

    def done(self) -> bool:
        return True

    def exception(self) -> Optional[BaseException]:
        return None

    def add_done_callback(self, fn: Callable[[Work], None]) -> None:
        fn(self)


class FutureWork(Work):
    """Work wrapping a concurrent.futures.Future."""

    def __init__(self, future: concurrent.futures.Future) -> None:
        self._future = future

    @property
    def future(self) -> concurrent.futures.Future:
        return self._future

    def wait(self, timeout: Optional[float] = None) -> Any:
        return self._future.result(timeout)

    def done(self) -> bool:
        return self._future.done()

    def exception(self) -> Optional[BaseException]:
        if not self._future.done():
            return None
        return self._future.exception()

    def add_done_callback(self, fn: Callable[[Work], None]) -> None:
        self._future.add_done_callback(lambda _f: fn(self))


class ErrorWork(Work):
    """Already-failed work carrying an exception."""

    def __init__(self, exc: BaseException) -> None:
        self._exc = exc

    def wait(self, timeout: Optional[float] = None) -> Any:
        raise self._exc

    def done(self) -> bool:
        return True

    def exception(self) -> Optional[BaseException]:
        return self._exc

    def add_done_callback(self, fn: Callable[[Work], None]) -> None:
        fn(self)
