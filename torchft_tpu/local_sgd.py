"""LocalSGD and (Streaming) DiLoCo: communication-reducing fault-tolerant
data parallelism over the replica axis.

Capability parity with the reference's ``torchft/local_sgd.py``:
- ``LocalSGD`` (local_sgd.py:43-170): run ``sync_every`` local optimizer
  steps, then average parameters across replica groups and commit iff the
  quorum agrees.
- ``DiLoCo`` / Streaming DiLoCo (local_sgd.py:173-789): keep a backup of the
  last globally-agreed parameters; every ``sync_every`` steps compute
  *pseudogradients* (backup - local), allreduce them across groups, feed
  them to an **outer optimizer** on the backup params, and lerp the result
  into the local params with ``fragment_update_alpha``. Streaming splits the
  model into fragments whose syncs are staggered (offset round-robin) and
  overlapped with ``fragment_sync_delay`` inner steps of compute.

TPU-first design: parameters live as sharded jax arrays on device; the
outer allreduce crosses pods over DCN, so pseudogradients are pulled to
host exactly once per fragment sync (amortized over ``sync_every`` inner
steps — this is why DiLoCo is the flagship cross-pod config,
BASELINE.json #5). The inner optimizer/step function is arbitrary jitted
user code; this layer never enters jit.

Fault semantics mirror the reference (local_sgd.py:444-451): a failed sync
restores the fragment to the last global (backup) state, so every replica
that commits step N has bitwise-identical global state.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import numpy as np
import optax

from torchft_tpu.manager import Manager
from torchft_tpu.telemetry import get_event_log, traced
from torchft_tpu.work import Work

logger = logging.getLogger(__name__)


def _to_host(tree: Any) -> Any:
    """Device pytree -> host numpy pytree (one transfer per leaf)."""
    return jax.tree_util.tree_map(lambda x: np.asarray(x), tree)


def _leaves(tree: Any) -> List[Any]:
    return jax.tree_util.tree_leaves(tree)


class LocalSGD:
    """Averages full parameters across replica groups every ``sync_every``
    local steps (reference: local_sgd.py:43-170).

    Usage::

        local_sgd = LocalSGD(manager, get_params, set_params, sync_every=32)
        for batch in data:
            params = train_step(params, batch)     # jitted, on device
            local_sgd.step()                       # counts; syncs on schedule

    ``get_params``/``set_params`` bridge to the caller's (possibly sharded)
    param pytree; this class never holds device state itself.
    """

    def __init__(
        self,
        manager: Manager,
        get_params: Callable[[], Any],
        set_params: Callable[[Any], None],
        sync_every: int,
        should_quantize: bool = False,
        quantize_bits: int = 8,
    ) -> None:
        assert sync_every >= 1
        if should_quantize and quantize_bits < 8:
            # LocalSGD quantizes ABSOLUTE parameter values (error is
            # O(param), recurring every sync, with nothing to cancel it);
            # that is tolerable at int8 but not below. Sub-8-bit syncs
            # belong to DiLoCo, whose pseudograd deltas + error_feedback
            # exist exactly for that regime.
            raise ValueError(
                "LocalSGD supports quantize_bits=8 only; for 4-bit syncs "
                "use DiLoCo(should_quantize=True, quantize_bits=4, "
                "error_feedback=True)"
            )
        self._manager = manager
        self._get = get_params
        self._set = set_params
        self._sync_every = sync_every
        self._should_quantize = should_quantize
        self._quantize_bits = quantize_bits
        self._local_step = 0
        manager.register_state_dict_fn(
            "LocalSGD",
            lambda: _to_host(self._get()),
            lambda state: self._set(state),
        )

    def step(self) -> Optional[bool]:
        """Counts one local step; returns the commit decision on sync steps,
        None otherwise."""
        self._local_step += 1
        if self._local_step < self._sync_every:
            return None
        self._local_step = 0
        return self.sync()

    @traced("torchft::local_sgd::sync")
    def sync(self) -> bool:
        """Quorum + parameter average + conditional commit (reference:
        local_sgd.py:126-155)."""
        manager = self._manager
        log = get_event_log()
        if log is not None:
            log.emit(
                "local_sgd_sync",
                step=manager.current_step(),
                sync_every=self._sync_every,
            )
        manager.start_quorum()
        params = self._get()
        # Leaves go to the manager AS-IS: Manager.allreduce itself routes
        # all-jax quantized inputs to the on-device Pallas quantize path
        # (int8+scales across PCIe) and hosts everything else — pulling to
        # host here would demote quantized syncs to fp32-over-PCIe and
        # duplicate the manager's dispatch condition.
        leaves, treedef = jax.tree_util.tree_flatten(params)
        work = manager.allreduce(
            list(leaves),
            should_quantize=self._should_quantize,
            quantize_bits=self._quantize_bits,
        )
        averaged = work.wait()
        # Fenced: LocalSGD allows async quorum, so a concurrent checkpoint
        # send must not snapshot the bumped step with pre-merge params.
        with manager.fenced_state_dict():
            if manager.should_commit():
                self._set(
                    jax.tree_util.tree_unflatten(treedef, list(averaged))
                )
                return True
        return False


class _Fragment:
    """One model fragment's DiLoCo state machine (reference:
    _StreamingDiLoCoFragment, local_sgd.py:173-560).

    Keeps ``backup`` = the last globally-committed values of this fragment's
    params (host-side — the reference offers CPU backup too, 235-247);
    ``prepare_sync`` snapshots pseudograds and launches the outer allreduce;
    ``perform_sync`` votes, steps the outer optimizer on the backup, and
    lerps the result into the live params.
    """

    def __init__(
        self,
        index: int,
        manager: Manager,
        keys: Sequence[str],
        get_fragment: Callable[[], Any],
        set_fragment: Callable[[Any], None],
        outer_optimizer: optax.GradientTransformation,
        fragment_update_alpha: float,
        should_quantize: bool,
        bucket_cap_mb: float = 32.0,
        quantize_bits: int = 8,
        error_feedback: bool = False,
    ) -> None:
        self.index = index
        self._manager = manager
        self.keys = list(keys)
        self._get = get_fragment
        self._set = set_fragment
        self._opt = outer_optimizer
        self._alpha = fragment_update_alpha
        self._should_quantize = should_quantize
        self._quantize_bits = quantize_bits
        self._error_feedback = error_feedback
        from torchft_tpu.collectives import ErrorFeedback

        self._residuals = ErrorFeedback(quantize_bits)
        self._bucket_cap = int(bucket_cap_mb * 1024 * 1024)

        self._backup = _to_host(get_fragment())
        self._opt_state = self._opt.init(self._backup)
        self._pending: List[tuple] = []
        self._pending_leaves: List[Any] = []
        self._pending_treedef = None

        # Healed replicas must receive the *global* state: backup + outer
        # optimizer state (reference registers fragments as
        # "StreamingDiLoCoFragment_{i}", local_sgd.py:249-275).
        manager.register_state_dict_fn(
            f"DiLoCoFragment_{index}",
            self._state_dict,
            self._load_state_dict,
        )

    def _state_dict(self) -> Dict[str, Any]:
        return {"backup": self._backup, "opt_state": self._opt_state}

    def _load_state_dict(self, state: Dict[str, Any]) -> None:
        self._backup = state["backup"]
        self._opt_state = state["opt_state"]
        # The healed local params restart from the global state; the
        # error-feedback residuals tracked the PRE-heal local stream, so
        # they reset too (the documented heal contract: at most one
        # sync's worth of this replica's own quantization error is lost).
        # clear() also invalidates the hooks of any allreduce still in
        # flight from before the heal, so the collective thread can't
        # re-insert a stale pre-heal residual after this reset.
        self._residuals.clear()
        self._set(self._backup)

    @traced("torchft::local_sgd::prepare_sync")
    def prepare_sync(self) -> None:
        """Pseudograd = backup - local, launched as an async outer allreduce
        (reference: local_sgd.py:313-326, 390-409)."""
        current = self._get()
        dev_leaves = [
            x
            for x in jax.tree_util.tree_leaves(current)
            if isinstance(x, jax.Array)
        ]
        if dev_leaves:
            # Guard the device->host pseudograd pull (see ddp.allreduce_grads).
            from torchft_tpu import futures as ft_futures

            manager = self._manager

            def on_stall() -> None:
                manager.report_error(
                    TimeoutError("pseudograd device->host pull stalled")
                )
                abort = getattr(manager, "_abort_pg_on_stall", None)
                if abort is not None:
                    abort()

            ft_futures.array_timeout(
                dev_leaves, on_stall, getattr(manager, "_timeout", 60.0)
            )
        local = _to_host(current)
        pseudograd = jax.tree_util.tree_map(
            lambda b, l: (np.asarray(b, np.float32) - np.asarray(l, np.float32)),
            self._backup,
            local,
        )
        leaves, treedef = jax.tree_util.tree_flatten(pseudograd)
        self._pending_treedef = treedef
        # Streaming buckets: <=32 MiB flat buffers per dtype, one async
        # allreduce each, unpacked at perform_sync (reference bucketized
        # fragment sync, local_sgd.py:466-560).
        from torchft_tpu.collectives import bucketize

        buckets = bucketize(leaves, self._bucket_cap)
        self._pending = []
        for b_idx, idx_list in enumerate(buckets):
            flat = np.concatenate([leaves[i].reshape(-1) for i in idx_list])
            on_quantized = None
            if self._error_feedback and self._should_quantize:
                # Residual (error-feedback) compensation: add the part of
                # the previous syncs' pseudograds this replica's quantizer
                # dropped, then store what THIS quantization drops
                # (collectives.ErrorFeedback; replica-local, preserves
                # cross-replica bitwise equality, reset on heal).
                # Standard for <=4-bit outer syncs, where bare
                # quantization bias accumulates across rounds.
                #
                # The residual math runs on the COLLECTIVE thread via the
                # on_local_quantized hook (one quantize pass total, and
                # prepare_sync stays dispatch-cheap); the write is ordered
                # before the next prepare_sync by perform_sync's wait().
                flat = self._residuals.compensate(b_idx, flat)
                on_quantized = self._residuals.make_hook(b_idx)

            work = self._manager.allreduce(
                flat,
                should_quantize=self._should_quantize,
                quantize_bits=self._quantize_bits,
                on_local_quantized=on_quantized,
            )
            self._pending.append((work, idx_list))
        self._pending_leaves = leaves
        log = get_event_log()
        if log is not None:
            log.emit(
                "fragment_prepare_sync",
                step=self._manager.current_step(),
                fragment=self.index,
                buckets=len(buckets),
            )

    @traced("torchft::local_sgd::perform_sync")
    def perform_sync(self) -> bool:
        """Waits the bucket allreduces, votes, and merges (reference:
        local_sgd.py:411-464). Returns the commit decision."""
        if not self._pending:
            return self._manager.should_commit()
        # Unpack-on-wait: rebuild leaves from each bucket's reduced flat.
        out: List[Any] = [None] * len(self._pending_leaves)
        for work, idx_list in self._pending:
            (reduced,) = work.wait()
            offset = 0
            for i in idx_list:
                leaf = self._pending_leaves[i]
                out[i] = np.asarray(
                    reduced[offset : offset + leaf.size]
                ).reshape(leaf.shape)
                offset += leaf.size
        self._pending = []
        pseudograd = jax.tree_util.tree_unflatten(
            self._pending_treedef, out
        )
        log = get_event_log()
        if log is not None:
            log.emit(
                "fragment_perform_sync",
                step=self._manager.current_step(),
                fragment=self.index,
            )

        # Fenced: the commit decision (step bump) and the backup/param
        # merge must be one critical section vs checkpoint-send reads
        # (the backup IS the checkpointed fragment state).
        with self._manager.fenced_state_dict():
            if self._manager.should_commit():
                updates, self._opt_state = self._opt.update(
                    pseudograd, self._opt_state, self._backup
                )
                new_global = optax.apply_updates(self._backup, updates)
                self._backup = jax.tree_util.tree_map(np.asarray, new_global)
                if self._alpha <= 0.0:
                    merged = self._backup
                else:
                    # alpha = weight of the LOCAL params (reference lerp
                    # convention, local_sgd.py:355-373):
                    # local' = (1-alpha) * global + alpha * local
                    local = _to_host(self._get())
                    merged = jax.tree_util.tree_map(
                        lambda g, l: (1.0 - self._alpha)
                        * np.asarray(g, np.float32)
                        + self._alpha * np.asarray(l, np.float32),
                        self._backup,
                        local,
                    )
                self._set(merged)
                return True
            # Failed sync: reset to the last global state so all committed
            # replicas stay bitwise-identical (reference:
            # local_sgd.py:444-451).
            self._set(self._backup)
            return False


class DiLoCo:
    """(Streaming) DiLoCo driver (reference: DiLoCo, local_sgd.py:563-789).

    ``fragments`` is a list of (keys, get_fn, set_fn) triples partitioning
    the model; with one fragment this is classic DiLoCo. Each inner step::

        diloco.step()

    drives the schedule: one sync round happens every
    ``sync_every // n_fragments`` inner steps with fragments taking turns
    round-robin by ``manager.current_step() % n_fragments``, so every
    fragment completes exactly one sync per ``sync_every`` inner steps
    (reference interval: local_sgd.py:629,732-767). Within a round the
    pseudograd allreduce launches ``fragment_sync_delay`` steps early,
    overlapping that much inner compute.

    ``fragment_update_alpha`` is the weight of the LOCAL params in the
    post-commit merge (``local' = (1-alpha)*global + alpha*local``); the
    default 0.0 snaps local params to the new global state, matching the
    reference's lerp convention (local_sgd.py:355-373).
    """

    def __init__(
        self,
        manager: Manager,
        fragments: Sequence[tuple],
        sync_every: int,
        outer_optimizer: Optional[optax.GradientTransformation] = None,
        fragment_sync_delay: int = 0,
        fragment_update_alpha: float = 0.0,
        should_quantize: bool = False,
        bucket_cap_mb: float = 32.0,
        quantize_bits: int = 8,
        error_feedback: bool = False,
    ) -> None:
        n = len(fragments)
        assert n >= 1, "need at least one fragment"
        # Validation mirrors local_sgd.py:616-632.
        if getattr(manager, "use_async_quorum", False):
            raise ValueError(
                "DiLoCo requires a Manager with use_async_quorum=False: an "
                "async quorum can heal (overwrite params) mid-inner-step "
                "(reference: local_sgd.py:616-620)"
            )
        if sync_every % n != 0:
            raise ValueError(f"sync_every={sync_every} % n_fragments={n} != 0")
        if fragment_sync_delay >= sync_every // n:
            raise ValueError(
                f"fragment_sync_delay={fragment_sync_delay} must be < "
                f"sync_every/n_fragments={sync_every // n}"
            )
        if not 0.0 <= fragment_update_alpha <= 1.0:
            raise ValueError("fragment_update_alpha must be in [0, 1]")

        self._manager = manager
        self._sync_every = sync_every
        # One fragment syncs per interval; with round-robin selection every
        # fragment completes one sync per `sync_every` inner steps
        # (reference: local_sgd.py:629).
        self._interval = sync_every // n
        self._delay = fragment_sync_delay
        outer_optimizer = outer_optimizer or optax.sgd(0.7, momentum=0.9, nesterov=True)
        self._fragments = [
            _Fragment(
                i,
                manager,
                keys,
                get_fn,
                set_fn,
                outer_optimizer,
                fragment_update_alpha,
                should_quantize,
                bucket_cap_mb,
                quantize_bits,
                error_feedback,
            )
            for i, (keys, get_fn, set_fn) in enumerate(fragments)
        ]
        self._local_step = 0
        self._prepared: Optional[_Fragment] = None

    @property
    def fragments(self) -> List[_Fragment]:
        return self._fragments

    @property
    def sync_in_flight(self) -> bool:
        """True while a fragment sync is prepared but not yet performed
        (the ``fragment_sync_delay`` overlap window). A drain must NOT
        leave here — peers are counting on this collective — but equally
        must not WAIT for a future sync to drain: that sync needs a
        quorum the departing peers may never form again."""
        return self._prepared is not None

    def state_dict(self) -> Dict[str, Any]:
        """The GLOBAL state as a host pytree: per-fragment backup + outer
        optimizer state — exactly what a healed replica receives
        (``DiLoCoFragment_{i}`` registrations). For durable snapshots:
        this plus the caller's inner params/optimizer is a full resume
        point after total job loss."""
        return {
            f"fragment_{f.index}": f._state_dict() for f in self._fragments
        }

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        """Restores the global state into every fragment (resetting local
        params to it, same as the heal path). Must be called at an outer
        boundary — no sync may be in flight.

        The outer optimizer state is re-hung on the live structure by
        flattened-leaf order (``DurableCheckpointer.rehang_like``), so
        the restore tolerates container-type drift through serialization
        (orbax round-trips NamedTuples as plain containers)."""
        from torchft_tpu.checkpointing.durable import DurableCheckpointer

        assert self._prepared is None, "load_state_dict during a sync"
        for f in self._fragments:
            s = state[f"fragment_{f.index}"]
            f._load_state_dict(
                {
                    "backup": jax.tree_util.tree_map(
                        np.asarray, s["backup"]
                    ),
                    "opt_state": DurableCheckpointer.rehang_like(
                        f._opt_state, s["opt_state"]
                    ),
                }
            )
        self._local_step = 0

    def _current_fragment(self) -> _Fragment:
        step = self._manager.current_step()
        return self._fragments[step % len(self._fragments)]

    def step(self) -> Optional[bool]:
        """One inner step tick; returns commit decision when a sync
        completes, else None (reference: _step_post_hook,
        local_sgd.py:739-785)."""
        self._local_step += 1
        result: Optional[bool] = None
        if self._local_step == self._interval - self._delay:
            # Quorum overlaps the remaining `delay` inner steps.
            frag = self._current_fragment()
            self._manager.start_quorum()
            frag.prepare_sync()
            self._prepared = frag
            if self._delay == 0:
                result = self._finish_sync()
        elif self._local_step >= self._interval:
            result = self._finish_sync()
        return result

    def _finish_sync(self) -> bool:
        frag = self._prepared
        assert frag is not None, "sync finished without prepare"
        self._prepared = None
        self._local_step = 0
        committed = frag.perform_sync()
        if not committed:
            logger.warning(
                "DiLoCo sync of fragment %d failed; params reset to last "
                "global state",
                frag.index,
            )
        return committed


def partition_fragments(
    params: Any, n_fragments: int
) -> List[List[str]]:
    """Splits a flat-dict-of-pytrees param container into exactly
    ``n_fragments`` contiguous, NON-empty key groups of roughly equal byte
    size (the reference splits via torch.distributed.pipelining; here
    top-level keys are the unit). Raises if there are fewer keys than
    fragments — an empty fragment would silently skew the sync cadence."""
    keys = list(params.keys())
    if n_fragments < 1:
        raise ValueError("n_fragments must be >= 1")
    if len(keys) < n_fragments:
        raise ValueError(
            f"cannot split {len(keys)} top-level params into "
            f"{n_fragments} fragments"
        )
    sizes = {
        k: sum(
            int(np.prod(x.shape)) * x.dtype.itemsize
            for x in jax.tree_util.tree_leaves(params[k])
        )
        for k in keys
    }
    target = sum(sizes.values()) / n_fragments
    groups: List[List[str]] = [[] for _ in range(n_fragments)]
    gi = 0
    acc = 0
    for j, k in enumerate(keys):
        keys_left = len(keys) - j
        groups_after = n_fragments - gi - 1
        # Advance when the current group is full — or must, so every
        # remaining group still gets at least one key.
        if groups[gi] and gi < n_fragments - 1 and (
            acc >= target or keys_left <= groups_after
        ):
            gi += 1
            acc = 0
        groups[gi].append(k)
        acc += sizes[k]
    return groups
