"""Deterministic, seeded fault injection across the control and data planes.

The drills in ``tools/drills.py`` can only kill whole processes; the failure
modes that dominate DCN training — flaky links, slow peers, partial writes,
torn RPCs mid-heal — need *surgical* faults at the socket layer, and a drill
failure is only debuggable if it replays bit-for-bit. This module is the
single source of truth for what gets injected where:

- One env knob drives everything::

      TORCHFT_CHAOS="seed:<uint64>,spec:<rule>[;<rule>...]"
      rule = <kind>@<plane>[:<param>=<value>]...

  Kinds: ``connect_refuse``, ``reset``, ``stall``, ``partial_write``,
  ``rpc_delay``, ``rpc_drop``, ``abort_heal``, ``ckpt_truncate``,
  ``throttle``, ``preempt``.
  Planes: ``ctrl`` (framed-RPC client/server path), ``data`` (process-group
  send/recv, both socket and native backends), ``heal`` (checkpoint
  transport), or ``any``.
  Params (all optional): ``peer=<substr>``, ``match=<substr>`` (RPC type or
  collective tag), ``link=<class>`` (only peers whose registered link class
  — see :func:`set_link_class` — equals this, e.g. ``wan``),
  ``step=<a>-<b>`` (inclusive window; see :func:`set_step`),
  ``p=<float>`` (per-visit probability, default 1), ``after=<n>`` (skip the
  first n eligible visits), ``every=<n>`` (then fire each n-th, default 1),
  ``count=<n>`` (max fires, default unlimited), ``ms=<int>`` (stall/delay
  duration, default 100), ``frac=<float>`` (fraction written before the cut,
  default 0.5), ``rate=<bytes/s>`` + ``bucket=<bytes>`` (throttle token
  bucket: sustained rate and burst size, defaults 1 MiB/s and 64 KiB),
  ``grace=<ms>`` (preempt grace window before hard kill; 0 = defer to the
  ``TORCHFT_DRAIN_GRACE_S`` knob).

  ``preempt`` models a spot/preemptible eviction notice: the seeded
  decision picks *which* visits of a preemption site deliver a SIGTERM,
  and ``grace`` bounds the drain window the victim gets before SIGKILL —
  the same budget k8s grants via ``terminationGracePeriodSeconds``. The
  decision is pure hash like every other kind; the actual signal delivery
  is the caller's job (see ``tools/elastic_drill.py``), keeping the replay
  multiset exact.

  ``throttle`` is special: the seeded decision (after/every/p/count, per
  visit) picks *when a site's bandwidth cap switches on*; from that visit on
  the site is paced by a token bucket without further decisions, so one
  ``chaos_inject`` journal line marks the activation rather than one per
  sub-transfer. Pacing sleeps are wall-clock (like ``stall``); which visits
  activate is hash-only and replays exactly.

  Example — reset the 3rd+ quorum RPC and stall data sends to peer 1::

      TORCHFT_CHAOS="seed:7,spec:reset@ctrl:match=quorum:after=2:count=1;\\
      stall@data:peer=1:ms=250:every=4"

- **Determinism.** Each (rule, site) pair keeps a visit counter; whether a
  visit fires depends only on ``(seed, rule index, site key, visit number)``
  via an FNV-1a-64 site hash folded through splitmix64 — never on wall
  clock, thread interleaving, or a shared RNG stream. Two runs whose sites
  perform the same operation sequence inject the identical fault sequence.
  The C++ mirror (``_cpp/chaos.hpp``) implements the same hash bit-for-bit,
  so engine-side decisions replay too.

- **Zero overhead when off.** ``TORCHFT_CHAOS`` unset parses to a module
  global of ``None``; every hook is a single attribute load + ``is None``
  test.

- **Every injection is journaled** as a ``chaos_inject`` event (kind, plane,
  site, rule, visit, seq) so ``obs_trace.py`` timelines show exactly what
  was injected where, and ``tools/chaos_soak.py`` can compare the sequence
  across same-seed runs.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from torchft_tpu import knobs

__all__ = [
    "ChaosError",
    "ChaosSpecError",
    "Injection",
    "Rule",
    "Chaos",
    "active",
    "init_from_env",
    "reset",
    "set_step",
    "current_step",
    "on_step_change",
    "scope",
    "maybe",
    "maybe_stall",
    "maybe_throttle",
    "check_connect",
    "set_link_class",
    "link_class",
    "backoff_jitter",
]

_M64 = (1 << 64) - 1

KINDS = (
    "connect_refuse",
    "reset",
    "stall",
    "partial_write",
    "rpc_delay",
    "rpc_drop",
    "abort_heal",
    "ckpt_truncate",
    "throttle",
    "preempt",
)

PLANES = ("ctrl", "data", "heal", "srv", "any")


class ChaosError(RuntimeError):
    """Raised *by* an injected fault (e.g. abort_heal). Carries the
    injection so handlers/journals can attribute the failure."""


class ChaosSpecError(ValueError):
    """Malformed TORCHFT_CHAOS value. Raised eagerly at init so a typo'd
    schedule fails the run instead of silently injecting nothing."""


# ----------------------------------------------------------------------
# Deterministic decision hash (mirrored bit-for-bit by _cpp/chaos.hpp)
# ----------------------------------------------------------------------


def fnv1a64(s: str) -> int:
    h = 0xCBF29CE484222325
    for b in s.encode("utf-8", errors="replace"):
        h ^= b
        h = (h * 0x100000001B3) & _M64
    return h


def splitmix64(x: int) -> int:
    z = (x + 0x9E3779B97F4A7C15) & _M64
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _M64
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _M64
    return z ^ (z >> 31)


def decision_hash(seed: int, rule_idx: int, site_hash: int, visit: int) -> int:
    x = (
        seed
        ^ site_hash
        ^ ((rule_idx * 0x9E3779B97F4A7C15) & _M64)
        ^ ((visit * 0xBF58476D1CE4E5B9) & _M64)
    )
    return splitmix64(x & _M64)


def _hash_unit(h: int) -> float:
    """Top 53 bits of the hash as a float in [0, 1)."""
    return (h >> 11) / float(1 << 53)


# ----------------------------------------------------------------------
# Spec grammar
# ----------------------------------------------------------------------


@dataclass
class Rule:
    kind: str
    plane: str
    index: int = 0
    peer: Optional[str] = None
    match: Optional[str] = None
    link: Optional[str] = None
    step_lo: int = -1
    step_hi: int = 1 << 62
    p: float = 1.0
    after: int = 0
    every: int = 1
    count: Optional[int] = None
    ms: int = 100
    frac: float = 0.5
    rate: int = 1 << 20
    bucket: int = 1 << 16
    grace: int = 0

    def spec(self) -> str:
        """Round-trip the rule back to grammar form (for CHAOS_SOAK.json)."""
        parts = [f"{self.kind}@{self.plane}"]
        if self.peer is not None:
            parts.append(f"peer={self.peer}")
        if self.match is not None:
            parts.append(f"match={self.match}")
        if self.link is not None:
            parts.append(f"link={self.link}")
        if self.step_lo >= 0 or self.step_hi < (1 << 62):
            hi = self.step_hi if self.step_hi < (1 << 62) else ""
            parts.append(f"step={self.step_lo}-{hi}")
        if self.p < 1.0:
            parts.append(f"p={self.p}")
        if self.after:
            parts.append(f"after={self.after}")
        if self.every != 1:
            parts.append(f"every={self.every}")
        if self.count is not None:
            parts.append(f"count={self.count}")
        if self.kind in ("stall", "rpc_delay") or self.ms != 100:
            parts.append(f"ms={self.ms}")
        if self.kind in ("partial_write", "ckpt_truncate") or self.frac != 0.5:
            parts.append(f"frac={self.frac}")
        if self.kind == "throttle" or self.rate != (1 << 20):
            parts.append(f"rate={self.rate}")
        if self.kind == "throttle" or self.bucket != (1 << 16):
            parts.append(f"bucket={self.bucket}")
        if self.grace != 0:
            parts.append(f"grace={self.grace}")
        return ":".join(parts)


def parse_rule(text: str, index: int) -> Rule:
    head, *params = [p for p in text.strip().split(":") if p != ""]
    if "@" not in head:
        raise ChaosSpecError(f"rule '{text}': expected <kind>@<plane>")
    kind, _, plane = head.partition("@")
    if kind not in KINDS:
        raise ChaosSpecError(f"rule '{text}': unknown kind '{kind}' (have {KINDS})")
    if plane not in PLANES:
        raise ChaosSpecError(f"rule '{text}': unknown plane '{plane}' (have {PLANES})")
    r = Rule(kind=kind, plane=plane, index=index)
    for p in params:
        if "=" not in p:
            raise ChaosSpecError(f"rule '{text}': bad param '{p}' (expected k=v)")
        k, _, v = p.partition("=")
        try:
            if k == "peer":
                r.peer = v
            elif k == "match":
                r.match = v
            elif k == "link":
                r.link = v
            elif k == "step":
                lo, _, hi = v.partition("-")
                r.step_lo = int(lo) if lo else 0
                r.step_hi = int(hi) if hi else (1 << 62)
            elif k == "p":
                r.p = float(v)
                if not (0.0 <= r.p <= 1.0):
                    raise ValueError("p outside [0,1]")
            elif k == "after":
                r.after = int(v)
            elif k == "every":
                r.every = max(1, int(v))
            elif k == "count":
                r.count = int(v)
            elif k == "ms":
                r.ms = int(v)
            elif k == "frac":
                r.frac = float(v)
                if not (0.0 <= r.frac <= 1.0):
                    raise ValueError("frac outside [0,1]")
            elif k == "rate":
                r.rate = int(v)
                if r.rate <= 0:
                    raise ValueError("rate must be > 0")
            elif k == "bucket":
                r.bucket = int(v)
                if r.bucket <= 0:
                    raise ValueError("bucket must be > 0")
            elif k == "grace":
                r.grace = int(v)
                if r.grace < 0:
                    raise ValueError("grace must be >= 0")
            else:
                raise ValueError(f"unknown param '{k}'")
        except ChaosSpecError:
            raise
        except Exception as e:
            raise ChaosSpecError(f"rule '{text}': param '{p}': {e}") from e
    return r


def parse_spec(value: str) -> Tuple[int, List[Rule]]:
    """Parses a full ``TORCHFT_CHAOS`` value into (seed, rules)."""
    value = value.strip()
    if not value.startswith("seed:"):
        raise ChaosSpecError("TORCHFT_CHAOS must start with 'seed:<int>,spec:'")
    rest = value[len("seed:"):]
    seed_str, sep, spec = rest.partition(",")
    if not sep or not spec.startswith("spec:"):
        raise ChaosSpecError("TORCHFT_CHAOS must be 'seed:<int>,spec:<rules>'")
    try:
        seed = int(seed_str) & _M64
    except ValueError as e:
        raise ChaosSpecError(f"bad seed '{seed_str}'") from e
    spec = spec[len("spec:"):]
    rules = []
    for i, rtext in enumerate(t for t in spec.split(";") if t.strip()):
        rules.append(parse_rule(rtext, i))
    if not rules:
        raise ChaosSpecError("TORCHFT_CHAOS spec has no rules")
    return seed, rules


# ----------------------------------------------------------------------
# Runtime state
# ----------------------------------------------------------------------


@dataclass(frozen=True)
class Injection:
    """What a hook should do; returned by :func:`maybe` when a rule fires."""

    kind: str
    plane: str
    site: str
    rule: int
    visit: int
    seq: int
    ms: int
    frac: float
    rate: int = 0
    bucket: int = 0
    grace: int = 0

    def __str__(self) -> str:
        return (
            f"chaos[{self.seq}] {self.kind}@{self.plane} site={self.site} "
            f"rule={self.rule} visit={self.visit}"
        )


class _TokenBucket:
    """Wall-clock token bucket pacing an activated throttle site. Lives in
    the hook layer, not the decision layer: *which* visit activates a
    throttle is hash-only, *how long* a paced write sleeps is not part of
    the replayed injection sequence (like a stall's sleep duration)."""

    # Cap per-call sleeps so one huge buffered write can't wedge a
    # deadline-driven transfer for longer than a stall rule could.
    MAX_SLEEP_S = 2.0

    def __init__(self, rate: int, bucket: int) -> None:
        self.rate = max(1, int(rate))  # bytes/second sustained
        self.cap = max(1, int(bucket))  # burst bytes
        self._tokens = float(self.cap)
        self._t_last = time.monotonic()
        self._lock = threading.Lock()

    def consume(self, nbytes: int) -> float:
        """Takes ``nbytes`` tokens; returns seconds the caller must sleep."""
        with self._lock:
            now = time.monotonic()
            self._tokens = min(
                float(self.cap), self._tokens + (now - self._t_last) * self.rate
            )
            self._t_last = now
            self._tokens -= float(nbytes)
            if self._tokens >= 0.0:
                return 0.0
            return min(-self._tokens / self.rate, self.MAX_SLEEP_S)


class Chaos:
    """Seeded schedule state: per-(rule, site) visit counters + fire log."""

    def __init__(self, seed: int, rules: List[Rule]) -> None:
        self.seed = seed & _M64
        self.rules = rules
        self._lock = threading.Lock()
        self._visits: Dict[Tuple[int, str], int] = {}
        self._fired: Dict[int, int] = {}
        self._seq = 0
        self._site_hash: Dict[str, int] = {}
        self._buckets: Dict[str, _TokenBucket] = {}  # site -> active throttle
        # Serializes throttle activation (check + pick + create) per process
        # so concurrent hooks at one site produce a deterministic number of
        # activation visits — the journal replays bit-for-bit.
        self._throttle_lock = threading.Lock()

    def spec(self) -> str:
        body = ";".join(r.spec() for r in self.rules)
        return f"seed:{self.seed},spec:{body}"

    def _rule_fires(self, r: Rule, site: str, visit: int) -> bool:
        if visit < r.after:
            return False
        k = visit - r.after
        if k % r.every != 0:
            return False
        if r.count is not None and self._fired.get(r.index, 0) >= r.count:
            return False
        if r.p < 1.0:
            sh = self._site_hash.get(site)
            if sh is None:
                sh = self._site_hash[site] = fnv1a64(site)
            h = decision_hash(self.seed, r.index, sh, visit)
            if _hash_unit(h) >= r.p:
                return False
        return True

    def pick(
        self,
        kind: str,
        plane: str,
        site: str,
        peer: Optional[str] = None,
        match: Optional[str] = None,
        step: Optional[int] = None,
    ) -> Optional[Injection]:
        """One eligible visit at ``site``: bumps the visit counter of every
        rule matching (kind, plane, peer, match, step) and returns an
        :class:`Injection` for the first rule that fires, else None."""
        if step is None:
            step = current_step()
        inj: Optional[Injection] = None
        # Lock-free pre-scan (rules are immutable once installed): a visit
        # no rule can match moves no counters, so skip the lock — armed
        # schedules scoped to one peer/RPC stay free for everything else.
        if not any(
            r.kind == kind
            and (r.plane == "any" or r.plane == plane)
            and (r.peer is None or (peer is not None and r.peer in peer))
            and (r.match is None or (match is not None and r.match in match))
            and (
                r.link is None
                or (peer is not None and _LINK_CLASSES.get(peer) == r.link)
            )
            and (
                r.step_lo < 0
                or (step is not None and r.step_lo <= step <= r.step_hi)
            )
            for r in self.rules
        ):
            return None
        with self._lock:
            for r in self.rules:
                if r.kind != kind:
                    continue
                if r.plane != "any" and r.plane != plane:
                    continue
                if r.peer is not None and (peer is None or r.peer not in peer):
                    continue
                if r.match is not None and (match is None or r.match not in match):
                    continue
                if r.link is not None and (
                    peer is None or _LINK_CLASSES.get(peer) != r.link
                ):
                    continue
                if r.step_lo >= 0:  # windowed rule: needs a known step
                    if step is None or not (r.step_lo <= step <= r.step_hi):
                        continue
                key = (r.index, site)
                visit = self._visits.get(key, 0)
                self._visits[key] = visit + 1
                if inj is None and self._rule_fires(r, site, visit):
                    self._fired[r.index] = self._fired.get(r.index, 0) + 1
                    self._seq += 1
                    inj = Injection(
                        kind=kind,
                        plane=plane,
                        site=site,
                        rule=r.index,
                        visit=visit,
                        seq=self._seq,
                        ms=r.ms,
                        frac=r.frac,
                        rate=r.rate if r.kind == "throttle" else 0,
                        bucket=r.bucket if r.kind == "throttle" else 0,
                        grace=r.grace if r.kind == "preempt" else 0,
                    )
        if inj is not None:
            self._journal(inj, peer=peer, match=match, step=step)
        return inj

    def _journal(
        self,
        inj: Injection,
        peer: Optional[str],
        match: Optional[str],
        step: Optional[int],
    ) -> None:
        try:
            from . import telemetry

            log = telemetry.get_event_log()
            if log is not None:
                log.emit(
                    "chaos_inject",
                    step=step,
                    kind=inj.kind,
                    plane=inj.plane,
                    site=inj.site,
                    rule=inj.rule,
                    visit=inj.visit,
                    seq=inj.seq,
                    ms=inj.ms,
                    frac=inj.frac,
                    rate=inj.rate,
                    bucket=inj.bucket,
                    grace=inj.grace,
                    peer=peer,
                    match=match,
                )
        except Exception:
            pass  # chaos must never break the path it injects into

    def throttle_delay(
        self,
        plane: str,
        site: str,
        nbytes: int,
        peer: Optional[str] = None,
        match: Optional[str] = None,
        step: Optional[int] = None,
    ) -> float:
        """Seconds this I/O must sleep under an active throttle (0 when the
        site has no active bucket and no throttle rule fires this visit)."""
        b = self._buckets.get(site)
        if b is None:
            with self._throttle_lock:
                b = self._buckets.get(site)
                if b is None:
                    inj = self.pick(
                        "throttle", plane, site, peer=peer, match=match,
                        step=step,
                    )
                    if inj is None:
                        return 0.0
                    b = self._buckets[site] = _TokenBucket(
                        inj.rate, inj.bucket
                    )
        return b.consume(nbytes)

    def injections_fired(self) -> int:
        with self._lock:
            return self._seq


# Module global consulted by every hook: None == chaos off (the fast path).
_STATE: Optional[Chaos] = None
_INIT_LOCK = threading.Lock()
_INITED = False

# Peer -> link class ("local"/"dcn"/"wan"), fed by the process group from
# TORCHFT_LINKS so `link=<class>` rules can scope faults to a whole class of
# links without enumerating peers. Plain dict: writes happen at configure
# time, reads are GIL-atomic lookups on the hook path.
_LINK_CLASSES: Dict[str, str] = {}

_GLOBAL_STEP: Optional[int] = None
_STEP_LISTENERS: List[Callable[[int], None]] = []

_TLS = threading.local()  # .ctx: (plane, peer, match) for _net-level hooks


def init_from_env(force: bool = False) -> Optional[Chaos]:
    """Parses ``TORCHFT_CHAOS`` once and installs the module state.
    Subsequent calls are no-ops unless ``force``."""
    global _STATE, _INITED
    with _INIT_LOCK:
        if _INITED and not force:
            return _STATE
        value = knobs.get_str("TORCHFT_CHAOS")
        if value:
            seed, rules = parse_spec(value)
            _STATE = Chaos(seed, rules)
        else:
            _STATE = None
        _INITED = True
        return _STATE


def active() -> Optional[Chaos]:
    """The installed schedule, initialising from env on first call.
    Hot paths read ``chaos._STATE`` directly after the first call."""
    if not _INITED:
        return init_from_env()
    return _STATE


def reset() -> None:
    """Forgets the installed schedule, step and link classes (tests)."""
    global _STATE, _INITED, _GLOBAL_STEP
    with _INIT_LOCK:
        _STATE = None
        _INITED = False
        _GLOBAL_STEP = None
        _STEP_LISTENERS.clear()
        _LINK_CLASSES.clear()


def install(seed: int, rules: List[Rule]) -> Chaos:
    """Installs a schedule programmatically (tests)."""
    global _STATE, _INITED
    with _INIT_LOCK:
        _STATE = Chaos(seed, rules)
        _INITED = True
        return _STATE


# ----------------------------------------------------------------------
# Step scoping
# ----------------------------------------------------------------------


def set_step(step: int) -> None:
    """Pins the current training step for ``step=a-b`` rule windows. Called
    by the Manager at quorum compute; listeners (the native engine mirror)
    are notified so C++-side rules stay in the same window."""
    global _GLOBAL_STEP
    _GLOBAL_STEP = int(step)
    for cb in list(_STEP_LISTENERS):
        try:
            cb(_GLOBAL_STEP)
        except Exception:
            pass


def current_step() -> Optional[int]:
    return _GLOBAL_STEP


def on_step_change(cb: Callable[[int], None]) -> None:
    """Registers a listener invoked from :func:`set_step` (e.g.
    ProcessGroupNative forwarding the step into the C++ chaos mirror)."""
    if cb not in _STEP_LISTENERS:
        _STEP_LISTENERS.append(cb)


# ----------------------------------------------------------------------
# Link classes (TORCHFT_LINKS -> `link=<class>` rule scoping)
# ----------------------------------------------------------------------


def set_link_class(peer: str, cls: str) -> None:
    """Tags ``peer`` (rank string or "host:port") with a link class so
    ``link=<class>`` rules apply to it. The process group calls this from
    its TORCHFT_LINKS policy at configure time; the native mirror is fed
    separately through ``tft_chaos_set_link``."""
    _LINK_CLASSES[str(peer)] = str(cls)


def link_class(peer: str) -> Optional[str]:
    return _LINK_CLASSES.get(str(peer))


# ----------------------------------------------------------------------
# TLS scope for _net.py-level hooks
# ----------------------------------------------------------------------


@contextlib.contextmanager
def scope(
    plane: str, peer: Optional[str] = None, match: Optional[str] = None
) -> Iterator[None]:
    """Attributes low-level ``_net`` I/O inside the block to (plane, peer,
    match) — lets ``_net.connect``/``send_frame`` consult chaos without
    changing their signatures. No-scope I/O is never injected."""
    prev = getattr(_TLS, "ctx", None)
    _TLS.ctx = (plane, peer, match)
    try:
        yield
    finally:
        _TLS.ctx = prev


def _scope_ctx() -> Optional[Tuple[str, Optional[str], Optional[str]]]:
    return getattr(_TLS, "ctx", None)


# ----------------------------------------------------------------------
# Hook helpers
# ----------------------------------------------------------------------


def maybe(
    kind: str,
    plane: str,
    site: str,
    peer: Optional[str] = None,
    match: Optional[str] = None,
    step: Optional[int] = None,
) -> Optional[Injection]:
    """The universal hook: None when chaos is off or no rule fires."""
    st = active()
    if st is None:
        return None
    return st.pick(kind, plane, site, peer=peer, match=match, step=step)


def maybe_stall(
    plane: str,
    site: str,
    peer: Optional[str] = None,
    match: Optional[str] = None,
) -> Optional[Injection]:
    """Stall hook: sleeps ``ms`` when a stall rule fires."""
    inj = maybe("stall", plane, site, peer=peer, match=match)
    if inj is not None:
        time.sleep(inj.ms / 1000.0)
    return inj


def maybe_throttle(
    plane: str,
    site: str,
    nbytes: int,
    peer: Optional[str] = None,
    match: Optional[str] = None,
) -> None:
    """Throttle hook: paces ``nbytes`` of I/O at ``site`` when a throttle
    rule has activated a token bucket there (sleeping as needed)."""
    st = active()
    if st is None:
        return
    delay = st.throttle_delay(plane, site, nbytes, peer=peer, match=match)
    if delay > 0.0:
        time.sleep(delay)


def check_connect(plane: str, peer: str) -> None:
    """Connect hook: raises ConnectionRefusedError when a connect_refuse
    rule fires for this peer."""
    inj = maybe("connect_refuse", plane, f"connect:{peer}", peer=peer)
    if inj is not None:
        raise ConnectionRefusedError(f"[chaos] connection refused: {inj}")


def backoff_jitter(key: str, attempt: int, cap_s: float) -> float:
    """Seeded full-jitter backoff delay in ``[0, cap_s)``.

    Deterministic in ``(chaos seed, key, attempt)`` via the same
    splitmix64/FNV-1a fold as the decision hash (seed 0 when no schedule is
    installed), so mass reconnects after a partition heal de-stampede
    without breaking same-seed chaos replay. Mirrored bit-for-bit by
    ``backoff_unit`` in ``_cpp/chaos.cc``."""
    st = active()
    seed = st.seed if st is not None else 0
    h = splitmix64(
        (seed ^ fnv1a64(key) ^ ((attempt * 0x9E3779B97F4A7C15) & _M64)) & _M64
    )
    return _hash_unit(h) * cap_s
