"""Fault-tolerant data parallelism across replica groups.

Reference: ``torchft/ddp.py:32-105`` routes each gradient bucket through
``manager.allreduce`` via a DDP comm hook. The JAX equivalent: the *inner*
data-parallel axis (within a replica group / pod) is a mesh axis whose
gradient psum is compiled into the step function and rides ICI; this module
averages the resulting gradients *across replica groups* over DCN, bucketed
into flat host buffers with async overlap (bucket N+1 transfers while N is
in flight — the comm-hook overlap analog).
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

import jax
import numpy as np

from torchft_tpu.manager import Manager


class DistributedDataParallel:
    """Averages gradient pytrees across the fault-tolerant replica axis.

    Usage::

        ddp = DistributedDataParallel(manager)
        grads = grad_fn(params, batch)          # inner-axis psum inside jit
        grads = ddp.allreduce_grads(grads)      # outer-axis average over DCN
    """

    def __init__(self, manager: Manager, bucket_cap_mb: float = 32.0) -> None:
        self._manager = manager
        self._bucket_cap = int(bucket_cap_mb * 1024 * 1024)

    def allreduce_grads(
        self,
        grads: Any,
        should_quantize: bool = False,
        quantize_bits: int = 8,
    ) -> Any:
        """Flattens ``grads`` into <=bucket_cap flat buffers per dtype, issues
        async manager allreduces for all buckets, waits, and rebuilds the
        pytree (values averaged over live participants)."""
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        dev_leaves = [x for x in leaves if isinstance(x, jax.Array)]
        if dev_leaves:
            # Guard the device->host pull: if the device computation feeding
            # the grads never completes (wedged inner-mesh collective), the
            # timeout engine latches an error and aborts the outer pg so the
            # step fails fast instead of wedging the trainer (the reference
            # arms stream_timeout on every wrapped future, manager.py:473-515).
            from torchft_tpu import futures as ft_futures

            manager = self._manager

            def on_stall() -> None:
                manager.report_error(
                    TimeoutError("gradient device->host pull stalled")
                )
                abort = getattr(manager, "_abort_pg_on_stall", None)
                if abort is not None:
                    abort()

            ft_futures.array_timeout(
                dev_leaves, on_stall, getattr(manager, "_timeout", 60.0)
            )
        host: List[np.ndarray] = [np.asarray(x) for x in leaves]

        buckets = self._bucketize(host)
        works: List[Tuple[Any, np.ndarray, List[int]]] = []
        for idx_list in buckets:
            flat = np.concatenate([host[i].reshape(-1) for i in idx_list])
            work = self._manager.allreduce(
                flat,
                should_quantize=should_quantize,
                quantize_bits=quantize_bits,
            )
            works.append((work, flat, idx_list))

        out: List[Optional[np.ndarray]] = [None] * len(host)
        for work, flat, idx_list in works:
            (reduced,) = work.wait()
            offset = 0
            for i in idx_list:
                n = host[i].size
                out[i] = reduced[offset : offset + n].reshape(host[i].shape)
                offset += n
        return jax.tree_util.tree_unflatten(treedef, out)

    def _bucketize(self, arrays: List[np.ndarray]) -> List[List[int]]:
        from torchft_tpu.collectives import bucketize

        return bucketize(arrays, self._bucket_cap)


class PureDistributedDataParallel:
    """Naive per-leaf variant (reference: ddp.py:82-105) — one allreduce per
    gradient leaf, no bucketing. Useful for debugging numerics."""

    def __init__(self, manager: Manager) -> None:
        self._manager = manager

    def allreduce_grads(self, grads: Any) -> Any:
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        works = [self._manager.allreduce(np.asarray(g)) for g in leaves]
        out = [w.wait()[0] for w in works]
        return jax.tree_util.tree_unflatten(treedef, out)
