"""Fault-tolerant data parallelism across replica groups.

Reference: ``torchft/ddp.py:32-105`` routes each gradient bucket through
``manager.allreduce`` via a DDP comm hook. The JAX equivalent: the *inner*
data-parallel axis (within a replica group / pod) is a mesh axis whose
gradient psum is compiled into the step function and rides ICI; this module
averages the resulting gradients *across replica groups* over DCN, bucketed
into flat host buffers with async overlap (bucket N+1 transfers while N is
in flight — the comm-hook overlap analog).
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

import jax
import numpy as np

from torchft_tpu.manager import Manager


class DistributedDataParallel:
    """Averages gradient pytrees across the fault-tolerant replica axis.

    Usage::

        ddp = DistributedDataParallel(manager)
        grads = grad_fn(params, batch)          # inner-axis psum inside jit
        grads = ddp.allreduce_grads(grads)      # outer-axis average over DCN
    """

    def __init__(
        self,
        manager: Manager,
        bucket_cap_mb: float = 32.0,
        error_feedback: bool = False,
        quantize_bits: int = 8,
    ) -> None:
        self._manager = manager
        self._bucket_cap = int(bucket_cap_mb * 1024 * 1024)
        self._error_feedback = error_feedback
        self._quantize_bits = quantize_bits
        from torchft_tpu.collectives import ErrorFeedback

        self._residuals = ErrorFeedback(quantize_bits)

    def allreduce_grads(
        self,
        grads: Any,
        should_quantize: bool = False,
        quantize_bits: Optional[int] = None,
    ) -> Any:
        """Flattens ``grads`` into <=bucket_cap flat buffers per dtype, issues
        async manager allreduces for all buckets, waits, and rebuilds the
        pytree (values averaged over live participants).

        With ``should_quantize=True``:

        - device-array grads on TPU ride the manager's DEVICE quantize
          path (Pallas kernels shrink the payload to int8/int4 *before*
          the device->host pull, so PCIe/tunnel bytes drop 4-8x along
          with the wire) — but only when ``error_feedback`` is off: the
          device path has no host-side quantize moment to hook, so an
          EF-enabled DDP takes the host path everywhere rather than
          silently dropping the residual compensation the caller asked
          for;
        - otherwise the host path quantizes the flat buckets, and
          ``error_feedback=True`` (ctor) compensates each bucket with the
          residual the previous step's quantizer dropped
          (collectives.ErrorFeedback) — what makes a 4-bit per-step grad
          wire usable without accumulating bias.  DDP residuals are NOT
          cleared on heal: they compensate the very next step's payload
          and carry at most one step's replica-local quantization error,
          unlike DiLoCo's residuals which track a whole discarded local
          stream.
        """
        if quantize_bits is None:
            quantize_bits = self._quantize_bits
        elif (
            should_quantize
            and self._error_feedback
            and quantize_bits != self._quantize_bits
        ):
            # The residual hook decodes the wire payload with the CTOR
            # width; a divergent per-call width would mis-decode it.
            raise ValueError(
                f"quantize_bits={quantize_bits} differs from the "
                f"error-feedback width {self._quantize_bits} pinned at "
                "construction; pass the width once, in the ctor"
            )
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        if (
            should_quantize
            and not self._error_feedback
            and leaves
            and all(isinstance(x, jax.Array) for x in leaves)
            and jax.default_backend() == "tpu"
        ):
            # Same bucket layout as the host path below (bucketize keys
            # on dtype/nbytes, which jax arrays expose identically), so a
            # device-path replica stays collective-for-collective
            # symmetric with host-path replicas — the socket PG pairs
            # ops in issue order, and a single whole-pytree allreduce
            # against a peer's per-bucket ones would desync the wire.
            # Each bucket's leaves go down as a list: the quantized jax
            # collective concatenates them on device, matching the host
            # path's flat bucket payload byte-for-byte.
            buckets = self._bucketize(leaves)
            works = [
                (
                    self._manager.allreduce(
                        [leaves[i] for i in idx_list],
                        should_quantize=True,
                        quantize_bits=quantize_bits,
                    ),
                    idx_list,
                )
                for idx_list in buckets
            ]
            out: List[Optional[Any]] = [None] * len(leaves)
            for work, idx_list in works:
                reduced = work.wait()
                for i, r in zip(idx_list, reduced):
                    out[i] = r
            return jax.tree_util.tree_unflatten(treedef, out)
        dev_leaves = [x for x in leaves if isinstance(x, jax.Array)]
        if dev_leaves:
            # Guard the device->host pull: if the device computation feeding
            # the grads never completes (wedged inner-mesh collective), the
            # timeout engine latches an error and aborts the outer pg so the
            # step fails fast instead of wedging the trainer (the reference
            # arms stream_timeout on every wrapped future, manager.py:473-515).
            from torchft_tpu import futures as ft_futures

            manager = self._manager

            def on_stall() -> None:
                manager.report_error(
                    TimeoutError("gradient device->host pull stalled")
                )
                abort = getattr(manager, "_abort_pg_on_stall", None)
                if abort is not None:
                    abort()

            ft_futures.array_timeout(
                dev_leaves, on_stall, getattr(manager, "_timeout", 60.0)
            )
        host: List[np.ndarray] = [np.asarray(x) for x in leaves]

        buckets = self._bucketize(host)
        works: List[Tuple[Any, np.ndarray, List[int]]] = []
        for b_idx, idx_list in enumerate(buckets):
            flat = np.concatenate([host[i].reshape(-1) for i in idx_list])
            on_quantized = None
            if should_quantize and self._error_feedback:
                flat = self._residuals.compensate(b_idx, flat)
                on_quantized = self._residuals.make_hook(b_idx)
            work = self._manager.allreduce(
                flat,
                should_quantize=should_quantize,
                quantize_bits=quantize_bits,
                on_local_quantized=on_quantized,
            )
            works.append((work, flat, idx_list))

        out: List[Optional[np.ndarray]] = [None] * len(host)
        for work, flat, idx_list in works:
            (reduced,) = work.wait()
            offset = 0
            for i in idx_list:
                n = host[i].size
                out[i] = reduced[offset : offset + n].reshape(host[i].shape)
                offset += n
        return jax.tree_util.tree_unflatten(treedef, out)

    def _bucketize(self, arrays: List[np.ndarray]) -> List[List[int]]:
        from torchft_tpu.collectives import bucketize

        return bucketize(arrays, self._bucket_cap)


class PureDistributedDataParallel:
    """Naive per-leaf variant (reference: ddp.py:82-105) — one allreduce per
    gradient leaf, no bucketing. Useful for debugging numerics."""

    def __init__(self, manager: Manager) -> None:
        self._manager = manager

    def allreduce_grads(self, grads: Any) -> Any:
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        works = [self._manager.allreduce(np.asarray(g)) for g in leaves]
        out = [w.wait()[0] for w in works]
        return jax.tree_util.tree_unflatten(treedef, out)
