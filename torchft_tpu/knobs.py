"""Central registry of ``TORCHFT_*`` environment knobs.

Every environment variable the framework reads is declared here ONCE,
with its type, default, and a one-line doc.  All reads in
``torchft_tpu/`` and ``tools/`` go through the typed accessors below —
``tools/tft_lint.py`` (rule ``env-knob-registry``) rejects any direct
``os.environ`` / ``os.getenv`` read of a ``TORCHFT_*`` name outside
this module, and rejects accessor calls that name an unregistered
knob.  ``docs/KNOBS.md`` is generated verbatim from this registry
(``python tools/tft_lint.py --gen-knob-docs``), so a knob cannot be
read-but-undocumented or documented-but-dead.

Scope tells the linter (and the reader) where the knob is consumed:

- ``py``    read by Python code in ``torchft_tpu/`` or ``tools/``
- ``cpp``   read by the C++ side (``getenv`` in ``_cpp/*.cc``)
- ``both``  read on both sides (the contract must match bit-for-bit)
- ``entry`` read by the repo-root entry script (``__graft_entry__.py``),
  outside the package; registered for documentation only

Accessor semantics (kept bit-compatible with the pre-registry call
sites):

- ``get_raw``   the raw string, or the registered default when unset
- ``get_str``   like ``get_raw`` but never ``None`` (falls back to "")
- ``get_int`` / ``get_float``  parse the raw value; unset -> default;
  a set-but-malformed value raises ``ValueError`` exactly as the old
  inline ``int(os.environ.get(...))`` did
- ``get_bool``  truthy iff the value is one of ``1/true/yes/on``
  (case-insensitive) — the journal flight-recorder gate's exact set
- ``require``   the raw string; raises ``KeyError(name)`` when unset,
  matching ``os.environ[name]``

Internal child-process plumbing variables (prefix ``_TORCHFT_``) are
deliberately NOT registered: the leading underscore marks them as
private wire between a launcher and the child it just spawned, not
user-facing configuration.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Optional, Union

__all__ = [
    "Knob",
    "KNOBS",
    "get_raw",
    "get_str",
    "get_int",
    "get_float",
    "get_bool",
    "require",
    "generate_doc",
]

_TRUTHY = ("1", "true", "yes", "on")


@dataclasses.dataclass(frozen=True)
class Knob:
    name: str
    type: str  # "str" | "int" | "float" | "bool" | "spec"
    default: Optional[str]  # raw default string; None = unset
    doc: str  # ONE line; becomes the docs/KNOBS.md table row
    scope: str = "py"  # "py" | "cpp" | "both" | "entry"


def _k(
    name: str,
    type: str,
    default: Optional[str],
    doc: str,
    scope: str = "py",
) -> Knob:
    assert name.startswith("TORCHFT_"), name
    assert "\n" not in doc, name
    return Knob(name=name, type=type, default=default, doc=doc, scope=scope)


_ALL = [
    # -- chaos plane -------------------------------------------------------
    _k(
        "TORCHFT_CHAOS",
        "spec",
        None,
        "Seeded fault-injection spec, `seed:<u64>,spec:<kind>@<plane>[:k=v]...[;...]`; parsed identically by chaos.py and _cpp/chaos.cc.",
        scope="both",
    ),
    # -- journal / telemetry ----------------------------------------------
    _k(
        "TORCHFT_JOURNAL_FILE",
        "str",
        None,
        "Append JSONL event-journal records to this exact path (wins over TORCHFT_JOURNAL_DIR).",
    ),
    _k(
        "TORCHFT_JOURNAL_DIR",
        "str",
        None,
        "Directory for per-replica event journals (`events_<replica>.jsonl`); each process rotates its own file.",
    ),
    _k(
        "TORCHFT_JOURNAL_MAX_MB",
        "float",
        "0",
        "Rotate the journal after this many MiB (0 or unset = no cap); only safe with per-process journal paths.",
    ),
    _k(
        "TORCHFT_METRICS_FILE",
        "str",
        None,
        "Append JSONL per-step metrics records to this path; empty/unset disables the metrics logger.",
    ),
    _k(
        "TORCHFT_REPLICA_ID",
        "str",
        None,
        "Replica id stamped on journal events and step digests; falls back to REPLICA_GROUP_ID, then `pid<pid>`.",
    ),
    # -- perf attribution -------------------------------------------------
    _k(
        "TORCHFT_PERF",
        "bool",
        None,
        "Truthy: trainers record per-jitted-step FLOPs/bytes from XLA cost analysis at compile time (one `perf_model` journal event) and append MFU/roofline to step logs; unset costs nothing.",
    ),
    _k(
        "TORCHFT_PERF_LEDGER",
        "str",
        None,
        "Override the benchmark ledger path tools/perf_ledger.py appends to (default `<repo>/BENCH_LEDGER.jsonl`).",
    ),
    # -- flight recorder / tracing ----------------------------------------
    _k(
        "TORCHFT_TRACE_DIR",
        "str",
        None,
        "Enable jax.profiler step-window traces, written under this directory; unset disables tracing.",
    ),
    _k(
        "TORCHFT_TRACE_START",
        "int",
        "5",
        "First step (inclusive) of the profiler trace window.",
    ),
    _k(
        "TORCHFT_TRACE_COUNT",
        "int",
        "3",
        "Number of steps the profiler trace window spans.",
    ),
    _k(
        "TORCHFT_TRIGGER_FR_ON_ABORT",
        "bool",
        None,
        "Truthy (1/true/yes/on): dump the native flight-recorder ring to a JSON file when a collective aborts.",
    ),
    _k(
        "TORCHFT_FR_DIR",
        "str",
        "/tmp",
        "Directory for on-abort flight-recorder dumps (`fr_<replica>_<reason>_<ts>.json`).",
    ),
    # -- manager / coordination -------------------------------------------
    _k(
        "TORCHFT_LIGHTHOUSE",
        "str",
        None,
        "Lighthouse address list `host:port[,host:port...]` (first entry = primary, rest = warm standbys, failover in order); required by Manager when no address argument is given, optional default for obs tools.",
    ),
    _k(
        "TORCHFT_LH_LEASE_MS",
        "int",
        "3000",
        "Manager's lease on the active lighthouse: no heartbeat ack for this long fails over to the next address in the TORCHFT_LIGHTHOUSE list.",
        scope="cpp",
    ),
    _k(
        "TORCHFT_LH_STATE_DIR",
        "str",
        None,
        "Lighthouse durable-state directory (fsync'd epoch/quorum-id snapshot, survives crash/restart so quorum ids stay monotone); unset = volatile pre-HA behavior.",
        scope="cpp",
    ),
    _k(
        "TORCHFT_JOB",
        "str",
        "default",
        "Job namespace stamped on every heartbeat/quorum/leave frame; the lighthouse keeps fully isolated per-job membership, quorum numbering, fleet tables, and anomaly rings. `default` matches the pre-namespace wire behavior.",
        scope="both",
    ),
    _k(
        "TORCHFT_LH_DISTRICT",
        "str",
        None,
        "District name for a federated lighthouse; with TORCHFT_LH_ROOT set, the active instance piggybacks per-job fleet rollups upward on the heartbeat channel. The --district flag wins over the env.",
        scope="cpp",
    ),
    _k(
        "TORCHFT_LH_ROOT",
        "str",
        None,
        "Root lighthouse address `host:port` a district lighthouse reports its per-job rollup digests to; unset = federation off. The --root flag wins over the env.",
        scope="cpp",
    ),
    # -- failure-evidence plane -------------------------------------------
    _k(
        "TORCHFT_LH_EVIDENCE",
        "bool",
        "1",
        "Lighthouse evidence-driven REACTION (cadence-aware hb-lapse eviction + signal-triggered quorum re-evaluation). Signals are always collected/journaled/exported; `0` only stops acting on them.",
        scope="cpp",
    ),
    _k(
        "TORCHFT_LH_EVICT_MULT",
        "int",
        "12",
        "hb-lapse eviction budget multiplier: a replica whose open heartbeat gap exceeds max(TORCHFT_LH_EVICT_FLOOR_MS, mult x its declared cadence) is evicted from the quorum tables on evidence instead of waiting out heartbeat_timeout_ms. Replicas that never declared a cadence are never evicted early.",
        scope="cpp",
    ),
    _k(
        "TORCHFT_LH_EVICT_FLOOR_MS",
        "int",
        "1000",
        "Floor (ms) of the cadence-aware hb-lapse eviction budget, so very fast heartbeaters keep a sane grace window.",
        scope="cpp",
    ),
    _k(
        "TORCHFT_MGR_EVIDENCE_STREAK",
        "int",
        "3",
        "Manager hard-evidence lighthouse failover: this many CONSECUTIVE transport failures (connect refused/reset) on the active entry fails over immediately instead of waiting out the full TORCHFT_LH_LEASE_MS lease. `0` = lease lapse only. The --evidence-streak flag wins over the env.",
        scope="cpp",
    ),
    _k(
        "TORCHFT_EVIDENCE_WATCH",
        "bool",
        "1",
        "Trainer-side evidence watcher: while blocked on a managed collective, poll the local manager's evidence_status (~TORCHFT_EVIDENCE_POLL_S cadence) and abort the wedged process group on first hard peer-failure evidence (native_abort / proc_death / hb_lapse) instead of waiting out the collective timeout.",
    ),
    _k(
        "TORCHFT_EVIDENCE_POLL_S",
        "float",
        "0.1",
        "Poll cadence (seconds) of the trainer-side evidence watcher.",
    ),
    _k(
        "TORCHFT_TIMEOUT_SEC",
        "float",
        None,
        "Override Manager per-RPC timeout (seconds); default comes from the Manager(timeout=...) argument.",
    ),
    _k(
        "TORCHFT_QUORUM_TIMEOUT_SEC",
        "float",
        None,
        "Override Manager quorum timeout (seconds); default comes from the Manager(quorum_timeout=...) argument.",
    ),
    _k(
        "TORCHFT_CONNECT_TIMEOUT_SEC",
        "float",
        None,
        "Override Manager connect timeout (seconds); default comes from the Manager(connect_timeout=...) argument.",
    ),
    _k(
        "TORCHFT_QUORUM_RETRIES",
        "int",
        "0",
        "Extra quorum attempts after an ordinary quorum failure before giving up.",
    ),
    _k(
        "TORCHFT_DIGEST",
        "bool",
        "1",
        "Step-digest piggyback on heartbeats; any value but `0` keeps it on.",
    ),
    _k(
        "TORCHFT_DIGEST_INTERVAL_S",
        "float",
        "1.0",
        "Minimum seconds between refreshed step digests handed to the heartbeat loop.",
    ),
    _k(
        "TORCHFT_RPC_RETRIES",
        "int",
        "3",
        "Attempts per idempotent control-plane RPC before the error propagates.",
    ),
    _k(
        "TORCHFT_RPC_BACKOFF_BASE_S",
        "float",
        "0.05",
        "Base of the exponential RPC retry backoff (seconds).",
    ),
    _k(
        "TORCHFT_RPC_BACKOFF_MAX_S",
        "float",
        "1.0",
        "Cap on the exponential RPC retry backoff (seconds).",
    ),
    _k(
        "TORCHFT_HOST_ADDR",
        "str",
        None,
        "Address to advertise for this host's servers instead of the auto-detected outbound interface.",
    ),
    # -- process group / native data plane --------------------------------
    _k(
        "TORCHFT_PG",
        "str",
        "socket",
        "Data-plane backend for ProcessGroup selection: `socket` (pure Python) or `native` (C++ engine).",
    ),
    _k(
        "TORCHFT_PG_WIRE",
        "str",
        "fp32",
        "Wire format for allreduce payloads: `fp32` or `q8` (int8 quantized).",
    ),
    _k(
        "TORCHFT_NATIVE_STREAMS",
        "int",
        "4",
        "Socket streams per peer link in the native collective engine.",
    ),
    _k(
        "TORCHFT_LINKS",
        "spec",
        None,
        "Per-peer link policy, `<peer>=<class>[,k=v]...[;...]` with classes `local`/`dcn`/`wan` and keys `connect_ms`/`io_ms`/`streams`/`q8`; `*` sets the default. Must be symmetric across ranks. Parsed in Python; the native engine receives the resolved policies via `tft_coll_set_link`, the chaos plane via `tft_chaos_set_link`.",
        scope="py",
    ),
    _k(
        "TORCHFT_NATIVE_PIPELINE_BYTES",
        "int",
        str(1 << 20),
        "Pipeline chunk size (bytes) for the native engine's chunked ring collectives.",
    ),
    _k(
        "TORCHFT_NATIVE_FR_RING",
        "int",
        "256",
        "Flight-recorder ring capacity (entries) in the native engine.",
    ),
    # -- futures / watchdog ------------------------------------------------
    _k(
        "TORCHFT_WATCHDOG_TIMEOUT_SEC",
        "float",
        "30",
        "Default watchdog timeout (seconds) for future completion before the context aborts.",
    ),
    # -- runner / orchestration -------------------------------------------
    _k(
        "TORCHFT_RUNNER_PDEATHSIG",
        "bool",
        "1",
        "Deliver SIGKILL to replica children when the runner dies; any value but `0` keeps it on (Linux only).",
    ),
    _k(
        "TORCHFT_DRAIN_GRACE_S",
        "float",
        "120",
        "Preemption drain grace window (seconds) shared by every layer that budgets a SIGTERM->SIGKILL gap: orchestration/k8s.py renders it as `terminationGracePeriodSeconds`, the chaos `preempt` kind defaults its `grace=` param to it, and tools/elastic_drill.py waits this long for a drained exit before hard-killing.",
    ),
    # -- backend probe / collectives --------------------------------------
    _k(
        "TORCHFT_PROBE_TIMEOUT",
        "float",
        None,
        "Override the TPU backend-probe timeout (seconds).",
    ),
    _k(
        "TORCHFT_PROBE_NO_CACHE",
        "bool",
        None,
        "Truthy: ignore the cached backend-probe verdict and probe fresh.",
    ),
    _k(
        "TORCHFT_FORCE_DEVICE_QUANT",
        "bool",
        None,
        "Truthy: force the on-device (Pallas) quantization path even off-TPU (interpreter; test use only).",
    ),
    _k(
        "TORCHFT_LOSS_CHUNK",
        "int",
        "128",
        "Per-shard microbatch chunk size used when computing loss without materializing full logits.",
    ),
    _k(
        "TORCHFT_TTR_BUDGET_S",
        "float",
        "60",
        "Recovery time-to-restore budget (seconds): tools/obs_top.py flags any replica whose heal p95 exceeds it, and docs/FAULT_MODEL.md's TTR table is written against it.",
    ),
    _k(
        "TORCHFT_EXPORT_MAX_REPLICAS",
        "int",
        "64",
        "Per-replica series cardinality cap shared by the lighthouse /metrics endpoint and tools/obs_export.py: above this many fleet replicas, only aggregates plus anomalous/straggler replicas get per-replica series.",
        scope="both",
    ),
    _k(
        "TORCHFT_EXPORT_MAX_JOBS",
        "int",
        "64",
        "Per-job series cardinality cap in tools/obs_export.py: above this many job namespaces in the composite fleet payload, only jobs with stragglers or anomalies get per-job rollup series (plus a suppressed-count gauge).",
        scope="py",
    ),
    # -- SLO burn-rate evaluator (lighthouse goodput plane) ---------------
    _k(
        "TORCHFT_LH_SLO_GOODPUT",
        "float",
        "0.95",
        "Per-job goodput-fraction SLO target the lighthouse burn-rate evaluator compares against (compute share of all accounted replica-seconds). >= 1.0 disarms the evaluator (no error budget).",
        scope="cpp",
    ),
    _k(
        "TORCHFT_LH_SLO_BURN",
        "float",
        "2.0",
        "Burn-rate threshold that trips a rise-edge slo_burn event: burn = (1 - goodput) / (1 - TORCHFT_LH_SLO_GOODPUT), i.e. how many times faster than allotted the job spends its error budget.",
        scope="cpp",
    ),
    _k(
        "TORCHFT_LH_SLO_MIN_S",
        "float",
        "30.0",
        "Minimum accounted replica-seconds before the SLO evaluator arms, so startup/compile windows cannot page.",
        scope="cpp",
    ),
    # -- C++-only ----------------------------------------------------------
    _k(
        "TORCHFT_LH_DEBUG",
        "bool",
        None,
        "Set (any value): the C++ lighthouse logs per-RPC debug lines to stderr.",
        scope="cpp",
    ),
    _k(
        "TORCHFT_FLEET_SNAP_MS",
        "int",
        "100",
        "/fleet.json staleness bound for the lighthouse binary's cached snapshot (ms); 0 rebuilds the payload on every request. The --fleet-snap-ms flag wins over the env.",
        scope="cpp",
    ),
    # -- repo-root entry script (documented here, read outside the pkg) ---
    _k(
        "TORCHFT_XLA_CACHE_DIR",
        "str",
        None,
        "Override the XLA compilation-cache directory used by the TPU dry-run entry script.",
        scope="entry",
    ),
    _k(
        "TORCHFT_DRYRUN_XLA_FLAGS",
        "str",
        None,
        "Extra XLA_FLAGS appended for the TPU dry-run child process.",
        scope="entry",
    ),
    _k(
        "TORCHFT_DRYRUN_ALL_LEGS",
        "bool",
        None,
        "`1`: the TPU dry-run exercises every leg instead of stopping at the first failure.",
        scope="entry",
    ),
]

KNOBS: Dict[str, Knob] = {k.name: k for k in _ALL}
assert len(KNOBS) == len(_ALL), "duplicate knob registration"

_UNSET = object()


def _knob(name: str) -> Knob:
    try:
        return KNOBS[name]
    except KeyError:
        raise KeyError(
            f"unregistered env knob {name!r}: declare it in torchft_tpu/knobs.py"
        ) from None


def get_raw(name: str, default: object = _UNSET) -> Optional[str]:
    """Raw env value; unset -> call-site default, else registered default."""
    knob = _knob(name)
    raw = os.environ.get(name)
    if raw is not None:
        return raw
    if default is not _UNSET:
        return default  # type: ignore[return-value]
    return knob.default


def get_str(name: str, default: Optional[str] = None) -> str:
    raw = get_raw(name, default if default is not None else _UNSET)
    return "" if raw is None else str(raw)


def get_int(name: str, default: Optional[Union[int, str]] = None) -> int:
    raw = get_raw(name, default if default is not None else _UNSET)
    if raw is None:
        raise ValueError(f"env knob {name} is unset and has no default")
    return int(raw)


def get_float(
    name: str, default: Optional[Union[float, str]] = None
) -> float:
    raw = get_raw(name, default if default is not None else _UNSET)
    if raw is None:
        raise ValueError(f"env knob {name} is unset and has no default")
    return float(raw)


def get_bool(name: str, default: Optional[str] = None) -> bool:
    raw = get_raw(name, default if default is not None else _UNSET)
    return str(raw).strip().lower() in _TRUTHY


def require(name: str) -> str:
    """Like ``os.environ[name]`` (raises ``KeyError(name)`` when unset)."""
    _knob(name)
    raw = os.environ.get(name)
    if raw is None:
        raise KeyError(name)
    return raw


_SCOPE_TITLE = {
    "py": "Python (`torchft_tpu/`, `tools/`)",
    "cpp": "C++ (`torchft_tpu/_cpp/`)",
    "both": "Python + C++ (dual-language contract)",
    "entry": "Repo-root entry script",
}


def generate_doc() -> str:
    """The full ``docs/KNOBS.md`` body, generated from the registry."""
    lines = [
        "# Environment knobs",
        "",
        "<!-- GENERATED FILE — do not edit by hand. -->",
        "<!-- Source of truth: torchft_tpu/knobs.py.  Regenerate with -->",
        "<!--   python tools/tft_lint.py --gen-knob-docs -->",
        "",
        "Every `TORCHFT_*` environment variable the framework reads, from",
        "the single registry in `torchft_tpu/knobs.py`.  The contract",
        "linter (`tools/tft_lint.py`, rule `env-knob-registry`) keeps this",
        "file, the registry, and the actual reads in sync: a knob cannot",
        "be read but undocumented, or documented but dead.",
        "",
    ]
    order = ["both", "py", "cpp", "entry"]
    for scope in order:
        knobs = [k for k in _ALL if k.scope == scope]
        if not knobs:
            continue
        lines += [f"## {_SCOPE_TITLE[scope]}", ""]
        lines += ["| Name | Type | Default | Description |"]
        lines += ["| --- | --- | --- | --- |"]
        for k in knobs:
            default = "*(unset)*" if k.default is None else f"`{k.default}`"
            lines.append(
                f"| `{k.name}` | {k.type} | {default} | {k.doc} |"
            )
        lines.append("")
    return "\n".join(lines)
