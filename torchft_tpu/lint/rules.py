"""Rule implementations for the contract linter.

Each rule is a pure function ``(root: str) -> List[Finding]`` over the
extractors in ``extract.py``.  A rule FIRES (returns findings) only on
contract drift; an empty list means the contract holds.  Rules are
registered in ``RULES`` — the report counts a rule class as "active"
when it ran to completion, found drift or not.
"""

from __future__ import annotations

import dataclasses
import os
import re
import subprocess
from typing import Callable, Dict, List, Optional, Set, Tuple

from torchft_tpu.lint import extract as ex

# ----------------------------------------------------------------------


@dataclasses.dataclass
class Finding:
    rule: str
    message: str
    file: str = ""
    line: int = 0

    def format(self) -> str:
        loc = f"{self.file}:{self.line}: " if self.file else ""
        return f"[{self.rule}] {loc}{self.message}"

    def to_json(self) -> Dict[str, object]:
        return dataclasses.asdict(self)


# Contract source locations, relative to the repo root.
CHAOS_PY = "torchft_tpu/chaos.py"
CHAOS_CC = "torchft_tpu/_cpp/chaos.cc"
CHAOS_HPP = "torchft_tpu/_cpp/chaos.hpp"
NATIVE_PY = "torchft_tpu/_native.py"
COLLECTIVES_HPP = "torchft_tpu/_cpp/collectives.hpp"
COORD_PY = "torchft_tpu/coordination.py"
TELEMETRY_PY = "torchft_tpu/telemetry.py"
KNOBS_PY = "torchft_tpu/knobs.py"
LIGHTHOUSE_CC = "torchft_tpu/_cpp/lighthouse.cc"
MANAGER_CC = "torchft_tpu/_cpp/manager_server.cc"
KNOBS_DOC = "docs/KNOBS.md"


def _p(root: str, rel: str) -> str:
    return os.path.join(root, rel)


def _py_files(root: str) -> List[str]:
    """Every Python source the package-wide rules scan: the package and
    the tools dir (tests are exempt — they emit throwaway event kinds
    and poke env vars on purpose)."""
    out: List[str] = []
    for sub in ("torchft_tpu", "tools"):
        base = _p(root, sub)
        for dirpath, _dirnames, filenames in os.walk(base):
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    return out


def _rel(root: str, path: str) -> str:
    try:
        return os.path.relpath(path, root)
    except ValueError:
        return path


# ----------------------------------------------------------------------
# 1. golden-constants
# ----------------------------------------------------------------------


def rule_golden_constants(root: str) -> List[Finding]:
    R = "golden-constants"
    out: List[Finding] = []
    py = ex.py_hash_constants(_p(root, CHAOS_PY))
    cc = ex.cc_hash_constants(_p(root, CHAOS_CC))
    for fn in ex.HASH_FUNCS:
        p, c = py.get(fn, {}), cc.get(fn, {})
        if p.get("missing"):
            out.append(Finding(R, f"{fn}() missing", CHAOS_PY))
            continue
        if c.get("missing"):
            out.append(Finding(R, f"{fn}() missing", CHAOS_CC))
            continue
        if p["big_ints"] != c["big_ints"]:
            only_py = {hex(v) for v in p["big_ints"] - c["big_ints"]}
            only_cc = {hex(v) for v in c["big_ints"] - p["big_ints"]}
            out.append(
                Finding(
                    R,
                    f"{fn}(): golden constants drifted "
                    f"(py-only={sorted(only_py)} cc-only={sorted(only_cc)})",
                    CHAOS_CC,
                )
            )
        if p["shifts"] != c["shifts"]:
            out.append(
                Finding(
                    R,
                    f"{fn}(): shift amounts drifted "
                    f"(py={p['shifts']} cc={c['shifts']})",
                    CHAOS_CC,
                )
            )
    pu = ex.py_hash_unit(_p(root, CHAOS_PY))
    cu = ex.cc_hash_unit(_p(root, CHAOS_CC))
    if pu["shift"] is None or pu["divisor"] is None:
        out.append(Finding(R, "_hash_unit() not extractable", CHAOS_PY))
    elif cu["shift"] is None:
        out.append(
            Finding(R, "unit-float expression not found", CHAOS_CC)
        )
    else:
        if (pu["shift"], pu["divisor"]) != (cu["shift"], cu["divisor"]):
            out.append(
                Finding(
                    R,
                    "hash-unit drifted: "
                    f"py >>({pu['shift']})/{pu['divisor']} vs "
                    f"cc >>({cu['shift']})/{cu['divisor']}",
                    CHAOS_CC,
                )
            )
    sent_py = ex.py_step_sentinel(_p(root, CHAOS_PY))
    sent_cc = ex.cc_step_sentinel(_p(root, CHAOS_CC))
    if sent_cc is None:
        out.append(Finding(R, "kStepMax not found", CHAOS_CC))
    elif sent_cc not in sent_py:
        out.append(
            Finding(
                R,
                f"step sentinel drifted: cc kStepMax=2^{sent_cc.bit_length() - 1}"
                f" not among py sentinels {sorted(v.bit_length() - 1 for v in sent_py)}",
                CHAOS_CC,
            )
        )
    return out


# ----------------------------------------------------------------------
# 2. chaos-enums
# ----------------------------------------------------------------------


def rule_chaos_enums(root: str) -> List[Finding]:
    R = "chaos-enums"
    out: List[Finding] = []
    kinds_py = ex.py_tuple_of_strings(_p(root, CHAOS_PY), "KINDS")
    planes_py = ex.py_tuple_of_strings(_p(root, CHAOS_PY), "PLANES")
    kinds_cc = ex.cc_kind_names(_p(root, CHAOS_CC))
    planes_cc = ex.cc_planes(_p(root, CHAOS_CC))
    nkinds_cc = ex.cc_num_kinds(_p(root, CHAOS_CC))
    if kinds_py is None:
        out.append(Finding(R, "KINDS tuple not found", CHAOS_PY))
    if kinds_cc is None:
        out.append(Finding(R, "kKindNames[] not found", CHAOS_CC))
    if kinds_py and kinds_cc and kinds_py != kinds_cc:
        out.append(
            Finding(
                R,
                f"fault kinds drifted (ordered): py={list(kinds_py)} "
                f"cc={list(kinds_cc)}",
                CHAOS_CC,
            )
        )
    if kinds_cc and nkinds_cc is not None and nkinds_cc != len(kinds_cc):
        out.append(
            Finding(
                R,
                f"kNumKinds={nkinds_cc} but kKindNames has "
                f"{len(kinds_cc)} entries",
                CHAOS_CC,
            )
        )
    if planes_py is None:
        out.append(Finding(R, "PLANES tuple not found", CHAOS_PY))
    if planes_cc is None:
        out.append(Finding(R, "valid_plane() not found", CHAOS_CC))
    if planes_py and planes_cc and set(planes_py) != set(planes_cc):
        out.append(
            Finding(
                R,
                f"planes drifted: py={sorted(planes_py)} "
                f"cc={sorted(planes_cc)}",
                CHAOS_CC,
            )
        )
    enum = ex.hpp_kind_enum(_p(root, CHAOS_HPP))
    if enum is None:
        out.append(Finding(R, "enum class Kind not found", CHAOS_HPP))
    elif kinds_py:
        expected = [ex.kind_to_enum_name(k) for k in kinds_py]
        names = [n for n, _v in enum]
        if names != expected:
            out.append(
                Finding(
                    R,
                    f"Kind enum names drifted: hpp={names} "
                    f"expected={expected}",
                    CHAOS_HPP,
                )
            )
        for i, (n, v) in enumerate(enum):
            if v is not None and v != i:
                out.append(
                    Finding(
                        R,
                        f"Kind enum {n}={v} breaks the positional "
                        f"contract (expected {i})",
                        CHAOS_HPP,
                    )
                )
    return out


# ----------------------------------------------------------------------
# 3. chaos-grammar
# ----------------------------------------------------------------------


def rule_chaos_grammar(root: str) -> List[Finding]:
    R = "chaos-grammar"
    out: List[Finding] = []
    py = ex.py_grammar_params(_p(root, CHAOS_PY))
    cc = ex.cc_grammar_params(_p(root, CHAOS_CC))
    if not py:
        out.append(
            Finding(R, "parse_rule param ladder not found", CHAOS_PY)
        )
    if not cc:
        out.append(
            Finding(R, "parse_rule param ladder not found", CHAOS_CC)
        )
    if py and cc and py != cc:
        out.append(
            Finding(
                R,
                f"grammar param keys drifted: py-only={sorted(py - cc)} "
                f"cc-only={sorted(cc - py)}",
                CHAOS_CC,
            )
        )
    return out


# ----------------------------------------------------------------------
# 4. c-abi
# ----------------------------------------------------------------------


def rule_c_abi(root: str) -> List[Finding]:
    R = "c-abi"
    out: List[Finding] = []
    py = ex.py_abi(_p(root, NATIVE_PY))
    cc: Dict[str, Dict[str, object]] = {}
    cc.update(ex.cc_abi(_p(root, COLLECTIVES_HPP)))
    cc.update(ex.cc_abi(_p(root, CHAOS_HPP)))
    if not py:
        out.append(Finding(R, "_declare() not extractable", NATIVE_PY))
        return out
    if not cc:
        out.append(
            Finding(R, 'extern "C" block not found', COLLECTIVES_HPP)
        )
        return out
    for fn in sorted(set(py) - set(cc)):
        out.append(
            Finding(
                R,
                f"{fn} declared in _declare() but missing from the "
                'extern "C" headers',
                NATIVE_PY,
            )
        )
    for fn in sorted(set(cc) - set(py)):
        out.append(
            Finding(
                R,
                f'{fn} exported by extern "C" but not declared in '
                "_declare() (ctypes would guess int-returning varargs)",
                COLLECTIVES_HPP,
            )
        )
    for fn in sorted(set(py) & set(cc)):
        p, c = py[fn], cc[fn]
        if p.get("nargs") != c.get("nargs"):
            out.append(
                Finding(
                    R,
                    f"{fn}: argtypes arity {p.get('nargs')} != header "
                    f"arity {c.get('nargs')}",
                    NATIVE_PY,
                )
            )
        if p.get("void") != c.get("void"):
            out.append(
                Finding(
                    R,
                    f"{fn}: restype void-ness {p.get('void')} != header "
                    f"{c.get('void')}",
                    NATIVE_PY,
                )
            )
    dt_py = ex.py_dtype_codes(_p(root, NATIVE_PY))
    dt_cc = ex.cc_dtype_codes(_p(root, COLLECTIVES_HPP))
    if dt_py is None:
        out.append(Finding(R, "DTYPE_CODES not found", NATIVE_PY))
    elif dt_py != dt_cc:
        out.append(
            Finding(
                R,
                f"dtype codes drifted: py={dt_py} cc={dt_cc}",
                NATIVE_PY,
            )
        )
    op_py = ex.py_op_codes(_p(root, NATIVE_PY))
    op_cc = ex.cc_op_codes(_p(root, COLLECTIVES_HPP))
    if op_py is None:
        out.append(Finding(R, "OP_* codes not found", NATIVE_PY))
    elif op_py != op_cc:
        out.append(
            Finding(
                R, f"op codes drifted: py={op_py} cc={op_cc}", NATIVE_PY
            )
        )
    return out


# ----------------------------------------------------------------------
# 5. rpc-methods
# ----------------------------------------------------------------------

_CLIENT_SERVER = {
    "LighthouseClient": LIGHTHOUSE_CC,
    "ManagerClient": MANAGER_CC,
}


def rule_rpc_methods(root: str) -> List[Finding]:
    R = "rpc-methods"
    out: List[Finding] = []
    clients = ex.py_rpc_clients(_p(root, COORD_PY))
    disp = {
        rel: ex.cc_dispatch_types(_p(root, rel))
        for rel in (LIGHTHOUSE_CC, MANAGER_CC)
    }
    sent_cc = {
        rel: ex.cc_sent_types(_p(root, rel))
        for rel in (LIGHTHOUSE_CC, MANAGER_CC)
    }
    for cls, server in _CLIENT_SERVER.items():
        if cls not in clients:
            out.append(Finding(R, f"client class {cls} not found",
                               COORD_PY))
            continue
        for t in sorted(clients[cls]["types"] - disp[server]):
            out.append(
                Finding(
                    R,
                    f'{cls} sends type "{t}" but {server} never '
                    f"dispatches it",
                    COORD_PY,
                )
            )
    # C++-originated requests (heartbeats, quorum forwards, drain fan-out)
    # must land on a dispatched type of SOME server.
    all_disp = disp[LIGHTHOUSE_CC] | disp[MANAGER_CC]
    for rel, types in sent_cc.items():
        for t in sorted(types - all_disp):
            out.append(
                Finding(
                    R,
                    f'{rel} originates type "{t}" but no server '
                    f"dispatches it",
                    rel,
                )
            )
    # Reverse direction: a dispatched type nobody can send is dead
    # protocol surface (or a renamed sender).
    py_types: Set[str] = set()
    for cls in clients:
        py_types |= clients[cls]["types"]
    all_sent = py_types | sent_cc[LIGHTHOUSE_CC] | sent_cc[MANAGER_CC]
    for rel in (LIGHTHOUSE_CC, MANAGER_CC):
        for t in sorted(disp[rel] - all_sent):
            out.append(
                Finding(
                    R,
                    f'{rel} dispatches type "{t}" but no client or '
                    f"server ever sends it",
                    rel,
                )
            )
    return out


# ----------------------------------------------------------------------
# 6. rpc-keys
# ----------------------------------------------------------------------


def rule_rpc_keys(root: str) -> List[Finding]:
    R = "rpc-keys"
    out: List[Finding] = []
    clients = ex.py_rpc_clients(_p(root, COORD_PY))
    lh_keys = clients.get("LighthouseClient", {}).get("keys", set())
    mgr_keys = clients.get("ManagerClient", {}).get("keys", set())
    member_json = ex.py_method_dict_keys(
        _p(root, COORD_PY), "QuorumMember.to_json"
    )
    # Keys a server reads from requests must be sendable by its clients:
    # the Python client class, or the other C++ server's request builders.
    reads_lh = ex.cc_req_keys(_p(root, LIGHTHOUSE_CC))
    senders_lh = (
        lh_keys
        | ex.cc_assigned_keys(_p(root, MANAGER_CC))
        | ex.cc_assigned_keys(_p(root, LIGHTHOUSE_CC))  # self HTTP fwd
    )
    for k in sorted(reads_lh - senders_lh):
        out.append(
            Finding(
                R,
                f'lighthouse reads request key "{k}" that no sender '
                f"includes",
                LIGHTHOUSE_CC,
            )
        )
    reads_mgr = ex.cc_req_keys(_p(root, MANAGER_CC))
    senders_mgr = mgr_keys | ex.cc_assigned_keys(_p(root, LIGHTHOUSE_CC))
    for k in sorted(reads_mgr - senders_mgr):
        out.append(
            Finding(
                R,
                f'manager server reads request key "{k}" that no '
                f"sender includes",
                MANAGER_CC,
            )
        )
    # Quorum-member parse keys come from QuorumMember.to_json.
    member_cc = ex.cc_member_keys(_p(root, LIGHTHOUSE_CC))
    for k in sorted(member_cc - member_json):
        out.append(
            Finding(
                R,
                f'lighthouse parses member key "{k}" absent from '
                f"QuorumMember.to_json()",
                LIGHTHOUSE_CC,
            )
        )
    # PR-5 heartbeat digest: wire keys + the ≤512 B budget fields.
    wire = ex.py_method_dict_keys(
        _p(root, TELEMETRY_PY), "StepDigest.to_wire"
    )
    if not wire:
        out.append(
            Finding(R, "StepDigest.to_wire() not found", TELEMETRY_PY)
        )
    digest_cc = ex.cc_digest_keys(_p(root, LIGHTHOUSE_CC))
    for k in sorted(digest_cc - wire):
        out.append(
            Finding(
                R,
                f'lighthouse reads digest key "{k}" absent from '
                f"StepDigest.to_wire()",
                LIGHTHOUSE_CC,
            )
        )
    budget = ex.py_class_int_attr(
        _p(root, TELEMETRY_PY), "StepDigest", "MAX_WIRE_BYTES"
    )
    if budget != 512:
        out.append(
            Finding(
                R,
                f"StepDigest.MAX_WIRE_BYTES={budget} != 512 (the "
                f"heartbeat-budget contract in docs/FAULT_MODEL.md)",
                TELEMETRY_PY,
            )
        )
    peers = ex.py_class_int_attr(
        _p(root, TELEMETRY_PY), "StepDigest", "MAX_PEERS"
    )
    if peers != 8:
        out.append(
            Finding(
                R,
                f"StepDigest.MAX_PEERS={peers} != 8 (bw map cap that "
                f"keeps the digest inside the budget)",
                TELEMETRY_PY,
            )
        )
    return out


# ----------------------------------------------------------------------
# 7. event-kind-registry
# ----------------------------------------------------------------------


def rule_event_kinds(root: str) -> List[Finding]:
    R = "event-kind-registry"
    out: List[Finding] = []
    registry = ex.py_event_kinds_registry(_p(root, TELEMETRY_PY))
    if registry is None:
        out.append(
            Finding(R, "EVENT_KINDS registry not found", TELEMETRY_PY)
        )
        return out
    emitted = ex.py_emitted_kinds(_py_files(root))
    for kind in sorted(set(emitted) - set(registry)):
        path, line = emitted[kind][0]
        out.append(
            Finding(
                R,
                f'journal event kind "{kind}" is emitted but not '
                f"registered in telemetry.EVENT_KINDS",
                _rel(root, path),
                line,
            )
        )
    for kind in sorted(set(registry) - set(emitted)):
        out.append(
            Finding(
                R,
                f'EVENT_KINDS entry "{kind}" is never emitted '
                f"(dead registry entry or renamed call site)",
                TELEMETRY_PY,
            )
        )
    return out


# ----------------------------------------------------------------------
# 8. env-knob-registry
# ----------------------------------------------------------------------


def rule_env_knobs(root: str) -> List[Finding]:
    R = "env-knob-registry"
    out: List[Finding] = []
    knobs_path = _p(root, KNOBS_PY)
    registry = ex.py_knob_registry(knobs_path)
    if registry is None:
        out.append(Finding(R, "knob registry not found", KNOBS_PY))
        return out
    py_files = [
        f
        for f in _py_files(root)
        if os.path.abspath(f) != os.path.abspath(knobs_path)
    ]
    for path, line, name in ex.py_raw_env_reads(py_files):
        out.append(
            Finding(
                R,
                f"raw os.environ read of {name}: go through "
                f"torchft_tpu.knobs accessors",
                _rel(root, path),
                line,
            )
        )
    accessed: Set[str] = set()
    for path, line, name in ex.py_knob_accessor_calls(_py_files(root)):
        accessed.add(name)
        if name not in registry:
            out.append(
                Finding(
                    R,
                    f"knobs accessor call names unregistered knob "
                    f"{name}",
                    _rel(root, path),
                    line,
                )
            )
    cc_files: List[str] = []
    cpp_dir = _p(root, "torchft_tpu/_cpp")
    if os.path.isdir(cpp_dir):
        for fn in sorted(os.listdir(cpp_dir)):
            if fn.endswith((".cc", ".hpp", ".h")):
                cc_files.append(os.path.join(cpp_dir, fn))
    cc_reads = ex.cc_env_reads(cc_files)
    for name in sorted(cc_reads):
        scope = registry.get(name, {}).get("scope")
        if scope is None:
            out.append(
                Finding(
                    R,
                    f"C++ getenv({name}) is unregistered — add it to "
                    f"knobs.py with scope 'cpp' or 'both'",
                    KNOBS_PY,
                )
            )
        elif scope not in ("cpp", "both"):
            out.append(
                Finding(
                    R,
                    f"{name} is read by C++ but registered with scope "
                    f"'{scope}'",
                    KNOBS_PY,
                )
            )
    for name, meta in sorted(registry.items()):
        scope = meta["scope"]
        if scope in ("py", "both") and name not in accessed:
            out.append(
                Finding(
                    R,
                    f"{name} is registered (scope '{scope}') but never "
                    f"read via knobs accessors — dead knob or missed "
                    f"migration",
                    KNOBS_PY,
                )
            )
        if scope in ("cpp", "both") and name not in cc_reads:
            out.append(
                Finding(
                    R,
                    f"{name} is registered with scope '{scope}' but no "
                    f"C++ getenv reads it",
                    KNOBS_PY,
                )
            )
    # docs/KNOBS.md must match the generated form byte-for-byte.
    doc_path = _p(root, KNOBS_DOC)
    gen = _generated_knob_doc(knobs_path)
    if gen is None:
        out.append(
            Finding(R, "could not load knobs.py to generate docs",
                    KNOBS_PY)
        )
    elif not os.path.exists(doc_path):
        out.append(
            Finding(
                R,
                "docs/KNOBS.md missing — run "
                "`python tools/tft_lint.py --gen-knob-docs`",
                KNOBS_DOC,
            )
        )
    else:
        have = open(doc_path).read()
        if have.strip() != gen.strip():
            out.append(
                Finding(
                    R,
                    "docs/KNOBS.md is stale — regenerate with "
                    "`python tools/tft_lint.py --gen-knob-docs`",
                    KNOBS_DOC,
                )
            )
    return out


def _generated_knob_doc(knobs_path: str) -> Optional[str]:
    """Loads ``knobs.py`` from the tree under lint (not the installed
    package — fixture trees in tests carry their own registry) and
    returns ``generate_doc()``."""
    import importlib.util
    import sys

    try:
        spec = importlib.util.spec_from_file_location(
            "_tft_lint_knobs", knobs_path
        )
        assert spec is not None and spec.loader is not None
        mod = importlib.util.module_from_spec(spec)
        # dataclass field introspection resolves annotations through
        # sys.modules[cls.__module__]; register before exec.
        sys.modules["_tft_lint_knobs"] = mod
        try:
            spec.loader.exec_module(mod)
            return mod.generate_doc()
        finally:
            sys.modules.pop("_tft_lint_knobs", None)
    except Exception:
        return None


# ----------------------------------------------------------------------
# 9. wallclock-free-chaos
# ----------------------------------------------------------------------


def rule_wallclock_free(root: str) -> List[Finding]:
    R = "wallclock-free-chaos"
    out: List[Finding] = []
    for func, line, call in ex.py_wallclock_calls(_p(root, CHAOS_PY)):
        if call == "<function missing>":
            out.append(
                Finding(
                    R,
                    f"decision-path function {func} not found",
                    CHAOS_PY,
                )
            )
        else:
            out.append(
                Finding(
                    R,
                    f"{func}() calls {call} — the chaos decision path "
                    f"must be wall-clock/RNG free for seeded replay",
                    CHAOS_PY,
                    line,
                )
            )
    return out


# ----------------------------------------------------------------------
# 10. artifact-hygiene
# ----------------------------------------------------------------------

_ARTIFACT_SUFFIXES = (".o", ".so", ".a", ".d")


def rule_artifact_hygiene(root: str) -> List[Finding]:
    R = "artifact-hygiene"
    out: List[Finding] = []
    if not os.path.isdir(_p(root, ".git")):
        return out  # fixture tree: nothing tracked to police
    try:
        tracked = subprocess.run(
            ["git", "-C", root, "ls-files"],
            capture_output=True,
            text=True,
            timeout=30,
            check=True,
        ).stdout.splitlines()
    except Exception as e:  # git missing/broken: report, don't crash
        return [Finding(R, f"git ls-files failed: {e}", ".git")]
    for path in tracked:
        if path.startswith("torchft_tpu/_cpp/bin/") or path.endswith(
            _ARTIFACT_SUFFIXES
        ):
            out.append(
                Finding(
                    R,
                    f"build artifact tracked in git: {path} (the lint "
                    f"pass scans sources only; make rebuilds bin/)",
                    path,
                )
            )
    gi_path = _p(root, ".gitignore")
    if os.path.exists(gi_path):
        gi = open(gi_path).read()
        if "torchft_tpu/_cpp/bin" not in gi:
            out.append(
                Finding(
                    R,
                    ".gitignore does not exclude torchft_tpu/_cpp/bin/",
                    ".gitignore",
                )
            )
    return out


# ----------------------------------------------------------------------
# fleet-keys: the /fleet.json payload contract.
#
# The lighthouse builds the fleet snapshot in C++ (fleet_snapshot /
# fleet_agg_locked); obs_top.py and obs_export.py consume it in Python.
# The golden sets below ARE the contract: the C++ builder must write
# exactly these keys, and every key the Python consumers read at the
# fleet/row/agg level must be one the builder writes.

FLEET_TOP_KEYS = {
    "ts_ms", "gen", "snap_ms", "replicas", "agg", "anomalies",
    "anomaly_seq",
    # Namespace plane: every payload names its job island; the composite
    # (unfiltered) payload adds per-job summary rollups and the root
    # lighthouse's district table.
    "job", "jobs", "districts",
    # Failure-evidence plane: the island's signal ring, its monotone seq
    # cursor, and per-source totals.
    "signals", "signal_seq", "signal_counts",
    # Goodput plane: the SLO burn-rate rise-edge ring + its seq cursor.
    "slo_burns", "slo_seq",
}
FLEET_ROW_KEYS = {
    "last_hb_age_ms", "hb_interval_ms", "digest", "digest_age_ms",
    "flags", "straggler",
    # Last failure signal naming this replica as subject (null if none).
    "signal", "signal_age_ms",
}
FLEET_AGG_KEYS = {
    "n", "n_digest", "stragglers", "median_rate", "median_step",
    "median_goodput", "max_commit_failures", "anomalies_dropped",
    "quorum_world", "joins_total", "leaves_total", "epoch",
    "signals_dropped",
    # Goodput plane: per-kind badput sums (closed BADPUT_KINDS object, or
    # null before any acct digest), the job goodput fraction, MTBF/ETTR
    # from the evidence plane, and the SLO evaluator state.
    "badput_s", "goodput_frac", "mtbf_s", "ettr_s", "slo_burning",
    "slo_dropped",
}

# Consumer read sites: variable name -> which key level it addresses.
# obs_top/obs_export bind `fleet` to the parsed payload, `agg` to
# fleet["agg"], and iterate rows as `r` or index `replicas[rid]`.
_FLEET_READ_PATTERNS: List[Tuple[str, str]] = [
    (r"\bfleet\.get\(\s*(['\"])([^'\"]+)\1", "top"),
    (r"\bagg\.get\(\s*(['\"])([^'\"]+)\1", "agg"),
    (r"\br\.get\(\s*(['\"])([^'\"]+)\1", "row"),
    (r"\breplicas\[rid\]\.get\(\s*(['\"])([^'\"]+)\1", "row"),
]
_FLEET_CONSUMERS = ("tools/obs_top.py", "tools/obs_export.py")


def rule_fleet_keys(root: str) -> List[Finding]:
    R = "fleet-keys"
    out: List[Finding] = []
    cc_path = _p(root, LIGHTHOUSE_CC)
    if not os.path.exists(cc_path):
        return out  # fixture tree without the C++ plane
    text = ex.strip_cc_comments(open(cc_path).read())

    def assigned(body: str, var: str) -> Set[str]:
        return set(re.findall(rf'\b{var}\["([^"]+)"\]\s*=', body))

    snap = ex.cc_function_body(text, "fleet_snapshot")
    agg_fn = ex.cc_function_body(text, "fleet_agg_locked")
    if not snap or not agg_fn:
        return [
            Finding(
                R,
                "could not extract fleet_snapshot/fleet_agg_locked "
                "bodies from lighthouse.cc",
                LIGHTHOUSE_CC,
            )
        ]
    produced = {
        "top": assigned(snap, "f"),
        "row": assigned(snap, "r"),
        "agg": assigned(agg_fn, "agg"),
    }
    golden = {
        "top": FLEET_TOP_KEYS,
        "row": FLEET_ROW_KEYS,
        "agg": FLEET_AGG_KEYS,
    }
    for level in ("top", "row", "agg"):
        for k in sorted(produced[level] - golden[level]):
            out.append(
                Finding(
                    R,
                    f"lighthouse writes undeclared fleet.json {level} "
                    f"key {k!r} (add it to the golden set and teach "
                    f"the consumers)",
                    LIGHTHOUSE_CC,
                )
            )
        for k in sorted(golden[level] - produced[level]):
            out.append(
                Finding(
                    R,
                    f"declared fleet.json {level} key {k!r} is no "
                    f"longer written by fleet_snapshot/fleet_agg_locked",
                    LIGHTHOUSE_CC,
                )
            )

    # Consumers may read a subset, but never a key the builder does
    # not produce (a typo'd .get() silently reads None forever).
    for rel in _FLEET_CONSUMERS:
        path = _p(root, rel)
        if not os.path.exists(path):
            continue
        src = open(path).read()
        for pat, level in _FLEET_READ_PATTERNS:
            for _q, key in re.findall(pat, src):
                if key not in golden[level]:
                    out.append(
                        Finding(
                            R,
                            f"reads fleet.json {level} key {key!r} "
                            f"that the lighthouse never writes",
                            rel,
                        )
                    )
    return out


# ----------------------------------------------------------------------
# signal-sources: the failure-evidence plane's source enum.
#
# telemetry.SIGNAL_SOURCES (python emitters, detect/report tooling) and
# lighthouse.cc kSignalSourceNames (the ingest filter) must agree
# POSITIONALLY — the lighthouse silently drops signals whose source it
# does not know, so a drifted entry loses evidence with no error anywhere.


def rule_signal_sources(root: str) -> List[Finding]:
    R = "signal-sources"
    out: List[Finding] = []
    cc_path = _p(root, LIGHTHOUSE_CC)
    if not os.path.exists(cc_path):
        return out  # fixture tree without the C++ plane
    py = ex.py_tuple_of_strings(_p(root, TELEMETRY_PY), "SIGNAL_SOURCES")
    cc = ex.cc_string_array(cc_path, "kSignalSourceNames")
    if py is None:
        out.append(Finding(R, "SIGNAL_SOURCES tuple not found", TELEMETRY_PY))
    if cc is None:
        out.append(Finding(R, "kSignalSourceNames[] not found", LIGHTHOUSE_CC))
    if py and cc and py != cc:
        out.append(
            Finding(
                R,
                f"signal sources drifted (ordered): py={list(py)} "
                f"cc={list(cc)}",
                LIGHTHOUSE_CC,
            )
        )
    # Every source a python emitter uses must be declared. Emit sites all
    # funnel through journal events / the "signal" RPC with a literal
    # source string: catch the literals.
    if py:
        emitters = (
            "torchft_tpu/manager.py",
            "torchft_tpu/coordination.py",
            "torchft_tpu/orchestration/runner.py",
        )
        pat = re.compile(
            r"(?:source\s*=\s*|_signal\(\s*|\.signal\(\s*)(['\"])([a-z_]+)\1"
        )
        for rel in emitters:
            path = _p(root, rel)
            if not os.path.exists(path):
                continue
            src = open(path).read()
            for _q, source in pat.findall(src):
                if source not in py:
                    out.append(
                        Finding(
                            R,
                            f"emits undeclared signal source {source!r} "
                            f"(the lighthouse will drop it): add it to "
                            f"SIGNAL_SOURCES + kSignalSourceNames",
                            rel,
                        )
                    )
    return out


# ----------------------------------------------------------------------
# badput-kinds: the time-accounting plane's closed taxonomy.
#
# telemetry.BADPUT_KINDS (the ledger + the digest's positional "acct"
# array) and lighthouse.cc kBadputKindNames (the aggregation index) must
# agree POSITIONALLY — a drifted entry silently mis-bills seconds to the
# wrong kind on one side with no error anywhere. FAULT_BADPUT_KINDS (the
# headline goodput-retention numerator) must stay a subset.


def rule_badput_kinds(root: str) -> List[Finding]:
    R = "badput-kinds"
    out: List[Finding] = []
    py = ex.py_tuple_of_strings(_p(root, TELEMETRY_PY), "BADPUT_KINDS")
    if py is None:
        out.append(Finding(R, "BADPUT_KINDS tuple not found", TELEMETRY_PY))
        return out
    cc_path = _p(root, LIGHTHOUSE_CC)
    if os.path.exists(cc_path):
        cc = ex.cc_string_array(cc_path, "kBadputKindNames")
        if cc is None:
            out.append(
                Finding(R, "kBadputKindNames[] not found", LIGHTHOUSE_CC)
            )
        elif py != cc:
            out.append(
                Finding(
                    R,
                    f"badput kinds drifted (ordered): py={list(py)} "
                    f"cc={list(cc)}",
                    LIGHTHOUSE_CC,
                )
            )
    fault = ex.py_tuple_of_strings(
        _p(root, TELEMETRY_PY), "FAULT_BADPUT_KINDS"
    )
    if fault is None:
        out.append(
            Finding(R, "FAULT_BADPUT_KINDS tuple not found", TELEMETRY_PY)
        )
    else:
        for k in fault:
            if k not in py:
                out.append(
                    Finding(
                        R,
                        f"FAULT_BADPUT_KINDS entry {k!r} is not a "
                        f"declared BADPUT_KINDS member",
                        TELEMETRY_PY,
                    )
                )
    return out


# ----------------------------------------------------------------------

RULES: List[Tuple[str, Callable[[str], List[Finding]]]] = [
    ("golden-constants", rule_golden_constants),
    ("chaos-enums", rule_chaos_enums),
    ("chaos-grammar", rule_chaos_grammar),
    ("c-abi", rule_c_abi),
    ("rpc-methods", rule_rpc_methods),
    ("rpc-keys", rule_rpc_keys),
    ("event-kind-registry", rule_event_kinds),
    ("env-knob-registry", rule_env_knobs),
    ("wallclock-free-chaos", rule_wallclock_free),
    ("artifact-hygiene", rule_artifact_hygiene),
    ("fleet-keys", rule_fleet_keys),
    ("signal-sources", rule_signal_sources),
    ("badput-kinds", rule_badput_kinds),
]


def run_all(
    root: str, only: Optional[Set[str]] = None
) -> Tuple[List[Finding], List[str]]:
    """Runs every rule against the tree at ``root``.  Returns
    ``(findings, rule names that ran)``.  A rule that crashes reports
    itself as a finding rather than killing the run — a linter that
    dies on a parse error hides every other contract."""
    findings: List[Finding] = []
    ran: List[str] = []
    for name, fn in RULES:
        if only is not None and name not in only:
            continue
        try:
            findings.extend(fn(root))
        except Exception as e:
            findings.append(
                Finding(name, f"rule crashed: {type(e).__name__}: {e}")
            )
        ran.append(name)
    return findings, ran
