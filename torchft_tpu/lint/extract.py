"""Source extractors for the contract linter.

Python sides are parsed with ``ast`` (no imports of the target modules:
the linter must work on a broken tree).  C++ sides are parsed from
comment-stripped text with regexes plus brace-matched function slicing —
deliberately shallow, anchored on the stable surface forms (an enum
table, an ``extern "C"`` block, a ``k == "param"`` ladder) rather than a
real C++ grammar.  Every extractor returns plain data (sets/dicts/ints)
so the rules in ``rules.py`` stay pure comparisons.
"""

from __future__ import annotations

import ast
import re
from typing import Any, Dict, List, Optional, Set, Tuple

# ----------------------------------------------------------------------
# generic helpers
# ----------------------------------------------------------------------


def strip_cc_comments(text: str) -> str:
    """Removes ``//`` and ``/* */`` comments, preserving string literals
    and line numbers (block comments are replaced by equivalent
    newlines)."""
    out: List[str] = []
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if c == '"' or c == "'":
            quote = c
            out.append(c)
            i += 1
            while i < n:
                out.append(text[i])
                if text[i] == "\\":
                    if i + 1 < n:
                        out.append(text[i + 1])
                        i += 2
                        continue
                elif text[i] == quote:
                    i += 1
                    break
                i += 1
            continue
        if c == "/" and i + 1 < n and text[i + 1] == "/":
            while i < n and text[i] != "\n":
                i += 1
            continue
        if c == "/" and i + 1 < n and text[i + 1] == "*":
            j = text.find("*/", i + 2)
            j = n if j < 0 else j + 2
            out.append("\n" * text.count("\n", i, j))
            i = j
            continue
        out.append(c)
        i += 1
    return "".join(out)


def cc_function_body(text: str, name: str) -> Optional[str]:
    """The brace-matched body of function ``name`` in comment-stripped
    C++ ``text`` (first definition wins), or None."""
    for m in re.finditer(rf"\b{re.escape(name)}\s*\(", text):
        # Definition, not a call: find the '{' after the parameter list,
        # allowing only whitespace/identifiers between ')' and '{'.
        depth = 1
        i = m.end()
        while i < len(text) and depth:
            if text[i] == "(":
                depth += 1
            elif text[i] == ")":
                depth -= 1
            i += 1
        rest = text[i:]
        m2 = re.match(r"\s*(const|noexcept|override)?\s*\{", rest)
        if not m2:
            continue
        start = i + m2.end()
        depth = 1
        j = start
        while j < len(text) and depth:
            if text[j] == "{":
                depth += 1
            elif text[j] == "}":
                depth -= 1
            j += 1
        return text[start : j - 1]
    return None


def _fold_int(node: ast.AST) -> Optional[int]:
    """Constant int, or a constant ``a << b`` / ``a * b`` fold."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return node.value
    if isinstance(node, ast.BinOp):
        left, right = _fold_int(node.left), _fold_int(node.right)
        if left is None or right is None:
            return None
        if isinstance(node.op, ast.LShift):
            return left << right
        if isinstance(node.op, ast.Mult):
            return left * right
        if isinstance(node.op, ast.Sub):
            return left - right
    return None


def _parse(path: str) -> ast.Module:
    with open(path, "r") as f:
        return ast.parse(f.read(), filename=path)


def _func(tree: ast.Module, name: str) -> Optional[ast.FunctionDef]:
    """Module-level or method function def named ``name`` (dotted
    ``Class.method`` form supported)."""
    if "." in name:
        cls_name, meth = name.split(".", 1)
        for node in tree.body:
            if isinstance(node, ast.ClassDef) and node.name == cls_name:
                for sub in node.body:
                    if (
                        isinstance(sub, ast.FunctionDef)
                        and sub.name == meth
                    ):
                        return sub
        return None
    for node in tree.body:
        if isinstance(node, ast.FunctionDef) and node.name == name:
            return node
    return None


# ----------------------------------------------------------------------
# golden constants (chaos.py vs chaos.cc)
# ----------------------------------------------------------------------

# Decision-hash functions mirrored bit-for-bit across the two languages.
HASH_FUNCS = ("fnv1a64", "splitmix64", "decision_hash")


def py_hash_constants(path: str) -> Dict[str, Dict[str, Any]]:
    """Per decision function: the big integer constants (>= 256, i.e.
    the golden multipliers/offsets) and the right-shift amounts."""
    tree = _parse(path)
    out: Dict[str, Dict[str, Any]] = {}
    for name in HASH_FUNCS:
        fn = _func(tree, name)
        if fn is None:
            out[name] = {"missing": True}
            continue
        big: Set[int] = set()
        shifts: List[int] = []
        for node in ast.walk(fn):
            if isinstance(node, ast.Constant) and isinstance(
                node.value, int
            ):
                if node.value >= 256:
                    big.add(node.value)
            if isinstance(node, ast.BinOp) and isinstance(
                node.op, ast.RShift
            ):
                amt = _fold_int(node.right)
                if amt is not None:
                    shifts.append(amt)
        out[name] = {"big_ints": big, "shifts": sorted(shifts)}
    return out


def py_hash_unit(path: str) -> Dict[str, Optional[int]]:
    """``_hash_unit``: (right-shift amount, divisor) — top-53-bit unit
    float contract."""
    tree = _parse(path)
    fn = _func(tree, "_hash_unit")
    if fn is None:
        return {"shift": None, "divisor": None}
    shift = divisor = None
    for node in ast.walk(fn):
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.RShift):
            shift = _fold_int(node.right)
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.LShift):
            divisor = _fold_int(node)
    return {"shift": shift, "divisor": divisor}


def py_step_sentinel(path: str) -> Set[int]:
    """All distinct ``1 << N`` folds with N >= 32 in chaos.py — the
    step-window sentinel(s)."""
    tree = _parse(path)
    out: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.LShift):
            v = _fold_int(node)
            if v is not None and v >= (1 << 32):
                out.add(v)
    return out


_HEX = re.compile(r"0[xX][0-9a-fA-F]+")
_RSHIFT = re.compile(r">>\s*(\d+)")


def cc_hash_constants(path: str) -> Dict[str, Dict[str, Any]]:
    text = strip_cc_comments(open(path).read())
    out: Dict[str, Dict[str, Any]] = {}
    for name in HASH_FUNCS:
        body = cc_function_body(text, name)
        if body is None:
            out[name] = {"missing": True}
            continue
        big = {
            int(h, 16) for h in _HEX.findall(body) if int(h, 16) >= 256
        }
        shifts = sorted(int(s) for s in _RSHIFT.findall(body))
        out[name] = {"big_ints": big, "shifts": shifts}
    return out


def cc_hash_unit(path: str) -> Dict[str, Optional[int]]:
    """The ``(h >> S) / D.0`` unit-float expression in chaos.cc."""
    text = strip_cc_comments(open(path).read())
    m = re.search(r">>\s*(\d+)\)\s*/\s*(\d+)\.0", text)
    if not m:
        return {"shift": None, "divisor": None}
    return {"shift": int(m.group(1)), "divisor": int(m.group(2))}


def cc_step_sentinel(path: str) -> Optional[int]:
    text = strip_cc_comments(open(path).read())
    m = re.search(r"kStepMax\s*=\s*int64_t\(1\)\s*<<\s*(\d+)", text)
    return (1 << int(m.group(1))) if m else None


# ----------------------------------------------------------------------
# chaos enums (kinds / planes) and grammar param keys
# ----------------------------------------------------------------------


def py_tuple_of_strings(path: str, name: str) -> Optional[Tuple[str, ...]]:
    tree = _parse(path)
    for node in tree.body:
        # Plain and annotated (``X: tuple = (...)``) module-level assigns.
        targets: List[ast.expr] = []
        value: Optional[ast.expr] = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        for tgt in targets:
            if isinstance(tgt, ast.Name) and tgt.id == name:
                if isinstance(value, (ast.Tuple, ast.List)):
                    vals = []
                    for elt in value.elts:
                        if isinstance(
                            elt, ast.Constant
                        ) and isinstance(elt.value, str):
                            vals.append(elt.value)
                    return tuple(vals)
    return None


def cc_kind_names(path: str) -> Optional[Tuple[str, ...]]:
    text = strip_cc_comments(open(path).read())
    m = re.search(r"kKindNames\[\]\s*=\s*\{([^}]*)\}", text)
    if not m:
        return None
    return tuple(re.findall(r'"([^"]+)"', m.group(1)))


def cc_string_array(path: str, name: str) -> Optional[Tuple[str, ...]]:
    """Entries of a ``const char* <name>[] = {"a", "b", ...}`` array."""
    text = strip_cc_comments(open(path).read())
    m = re.search(rf"{re.escape(name)}\[\]\s*=\s*\{{([^}}]*)\}}", text)
    if not m:
        return None
    return tuple(re.findall(r'"([^"]+)"', m.group(1)))


def cc_num_kinds(path: str) -> Optional[int]:
    text = strip_cc_comments(open(path).read())
    m = re.search(r"kNumKinds\s*=\s*(\d+)", text)
    return int(m.group(1)) if m else None


def cc_planes(path: str) -> Optional[Tuple[str, ...]]:
    text = strip_cc_comments(open(path).read())
    body = cc_function_body(text, "valid_plane")
    if body is None:
        return None
    return tuple(re.findall(r'==\s*"([^"]+)"', body))


def hpp_kind_enum(path: str) -> Optional[List[Tuple[str, Optional[int]]]]:
    """``enum [class] Kind`` entries as (name, explicit value or None)."""
    text = strip_cc_comments(open(path).read())
    m = re.search(r"enum\s+(?:class\s+)?Kind[^{]*\{([^}]*)\}", text)
    if not m:
        return None
    out: List[Tuple[str, Optional[int]]] = []
    for entry in m.group(1).split(","):
        entry = entry.strip()
        if not entry:
            continue
        em = re.match(r"(\w+)(?:\s*=\s*(\d+))?", entry)
        if em:
            out.append(
                (em.group(1), int(em.group(2)) if em.group(2) else None)
            )
    return out


def kind_to_enum_name(kind: str) -> str:
    """``connect_refuse`` -> ``kConnectRefuse`` (the naming convention
    the C++ enum follows)."""
    return "k" + "".join(w.capitalize() for w in kind.split("_"))


def py_grammar_params(path: str) -> Set[str]:
    """Param keys handled by chaos.py ``parse_rule`` (the
    ``k == "peer"`` ladder)."""
    tree = _parse(path)
    fn = _func(tree, "parse_rule")
    if fn is None:
        return set()
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Compare) and len(node.ops) == 1:
            if (
                isinstance(node.left, ast.Name)
                and node.left.id == "k"
                and isinstance(node.ops[0], ast.Eq)
                and isinstance(node.comparators[0], ast.Constant)
                and isinstance(node.comparators[0].value, str)
            ):
                out.add(node.comparators[0].value)
    return out


def cc_grammar_params(path: str) -> Set[str]:
    text = strip_cc_comments(open(path).read())
    body = cc_function_body(text, "parse_rule")
    if body is None:
        return set()
    return set(re.findall(r'\bk\s*==\s*"(\w+)"', body))


# ----------------------------------------------------------------------
# C ABI (_native.py _declare vs extern "C" prototypes)
# ----------------------------------------------------------------------


def py_abi(path: str) -> Dict[str, Dict[str, Any]]:
    """``{fn: {"nargs": int, "void": bool}}`` from ``_declare``'s
    ``lib.<fn>.restype/.argtypes`` assignments."""
    tree = _parse(path)
    fn = _func(tree, "_declare")
    out: Dict[str, Dict[str, Any]] = {}
    if fn is None:
        return out
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if not (
            isinstance(tgt, ast.Attribute)
            and isinstance(tgt.value, ast.Attribute)
            and isinstance(tgt.value.value, ast.Name)
            and tgt.value.value.id == "lib"
        ):
            continue
        fname, field = tgt.value.attr, tgt.attr
        entry = out.setdefault(fname, {})
        if field == "restype":
            entry["void"] = (
                isinstance(node.value, ast.Constant)
                and node.value.value is None
            )
        elif field == "argtypes":
            if isinstance(node.value, (ast.List, ast.Tuple)):
                entry["nargs"] = len(node.value.elts)
    return out


def cc_abi(path: str) -> Dict[str, Dict[str, Any]]:
    """Same shape from a header's ``extern "C" { ... }`` block(s)."""
    text = strip_cc_comments(open(path).read())
    out: Dict[str, Dict[str, Any]] = {}
    for m in re.finditer(r'extern\s+"C"\s*\{', text):
        depth = 1
        i = m.end()
        while i < len(text) and depth:
            if text[i] == "{":
                depth += 1
            elif text[i] == "}":
                depth -= 1
            i += 1
        block = text[m.end() : i - 1]
        for proto in block.split(";"):
            proto = " ".join(proto.split())
            pm = re.match(
                r"(?P<ret>[\w:<>]+(?:\s*\*+)?)\s+(?P<name>tft_\w+)\s*"
                r"\((?P<args>[^)]*)\)$",
                proto,
            )
            if not pm:
                continue
            args = pm.group("args").strip()
            nargs = (
                0
                if args in ("", "void")
                else len(re.split(r",", args))
            )
            out[pm.group("name")] = {
                "nargs": nargs,
                "void": pm.group("ret").strip() == "void",
            }
    return out


def py_dtype_codes(path: str) -> Optional[Dict[str, int]]:
    tree = _parse(path)
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == "DTYPE_CODES":
                    if isinstance(node.value, ast.Dict):
                        return {
                            k.value: v.value
                            for k, v in zip(
                                node.value.keys, node.value.values
                            )
                            if isinstance(k, ast.Constant)
                            and isinstance(v, ast.Constant)
                        }
    return None


def py_op_codes(path: str) -> Optional[Dict[str, int]]:
    """``OP_SUM, OP_MAX, OP_MIN = 0, 1, 2`` -> {"SUM": 0, ...}."""
    tree = _parse(path)
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and isinstance(node.targets[0], ast.Tuple)
            and isinstance(node.value, ast.Tuple)
        ):
            names = [
                t.id
                for t in node.targets[0].elts
                if isinstance(t, ast.Name)
            ]
            if names and all(n.startswith("OP_") for n in names):
                vals = [
                    v.value
                    for v in node.value.elts
                    if isinstance(v, ast.Constant)
                ]
                if len(vals) == len(names):
                    return {
                        n[len("OP_") :]: v for n, v in zip(names, vals)
                    }
    return None


_CC_DT_NAMES = {"F32": "float32", "F64": "float64", "I32": "int32",
                "I64": "int64"}


def cc_dtype_codes(path: str) -> Dict[str, int]:
    text = strip_cc_comments(open(path).read())
    out: Dict[str, int] = {}
    for m in re.finditer(r"TFT_DT_(\w+)\s*=\s*(\d+)", text):
        name = _CC_DT_NAMES.get(m.group(1), m.group(1))
        out[name] = int(m.group(2))
    return out


def cc_op_codes(path: str) -> Dict[str, int]:
    text = strip_cc_comments(open(path).read())
    return {
        m.group(1): int(m.group(2))
        for m in re.finditer(r"TFT_OP_(\w+)\s*=\s*(\d+)", text)
    }


# ----------------------------------------------------------------------
# RPC methods and JSON keys
# ----------------------------------------------------------------------


def py_rpc_clients(path: str) -> Dict[str, Dict[str, Set[str]]]:
    """Per client class in coordination.py:
    ``{"types": RPC type values sent, "keys": all request keys sent}``.
    Keys come from dict literals that contain a ``"type"`` entry plus
    any ``var["key"] = ...`` subscript assignment in the same class."""
    tree = _parse(path)
    out: Dict[str, Dict[str, Set[str]]] = {}
    for node in tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        types: Set[str] = set()
        keys: Set[str] = set()
        dict_vars: Set[str] = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Dict):
                entry_keys = [
                    k.value
                    for k in sub.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)
                ]
                if "type" in entry_keys:
                    keys.update(entry_keys)
                    for k, v in zip(sub.keys, sub.values):
                        if (
                            isinstance(k, ast.Constant)
                            and k.value == "type"
                            and isinstance(v, ast.Constant)
                            and isinstance(v.value, str)
                        ):
                            types.add(v.value)
        # second pass: subscript assignments onto request dicts
        # (req["digest"] = ..., req["hb_interval_ms"] = ...)
        for sub in ast.walk(node):
            if (
                isinstance(sub, ast.Assign)
                and len(sub.targets) == 1
                and isinstance(sub.targets[0], ast.Subscript)
            ):
                sl = sub.targets[0].slice
                if isinstance(sl, ast.Constant) and isinstance(
                    sl.value, str
                ):
                    keys.add(sl.value)
        if types:
            out[node.name] = {"types": types, "keys": keys,
                              "dict_vars": dict_vars}
    return out


def py_method_dict_keys(path: str, qualname: str) -> Set[str]:
    """Constant string keys of dict literals (plus ``x["k"] =``
    assignments) inside one function/method — e.g.
    ``QuorumMember.to_json`` or ``StepDigest.to_wire``."""
    tree = _parse(path)
    fn = _func(tree, qualname)
    if fn is None:
        return set()
    keys: Set[str] = set()
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Dict):
            for k in sub.keys:
                if isinstance(k, ast.Constant) and isinstance(
                    k.value, str
                ):
                    keys.add(k.value)
        if (
            isinstance(sub, ast.Assign)
            and len(sub.targets) == 1
            and isinstance(sub.targets[0], ast.Subscript)
        ):
            sl = sub.targets[0].slice
            if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                keys.add(sl.value)
    return keys


def py_class_int_attr(
    path: str, cls: str, attr: str
) -> Optional[int]:
    tree = _parse(path)
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name == cls:
            for sub in node.body:
                if isinstance(sub, ast.Assign):
                    for tgt in sub.targets:
                        if (
                            isinstance(tgt, ast.Name)
                            and tgt.id == attr
                        ):
                            return _fold_int(sub.value)
    return None


def cc_dispatch_types(path: str) -> Set[str]:
    """RPC types a C++ server dispatches (``type == "X"`` ladders)."""
    text = strip_cc_comments(open(path).read())
    return set(re.findall(r'\btype\s*==\s*"(\w+)"', text))


def cc_sent_types(path: str) -> Set[str]:
    """RPC types a C++ file originates: every string literal on the RHS
    of a ``...["type"] = Json::of(...)`` assignment (covers the ternary
    form too)."""
    text = strip_cc_comments(open(path).read())
    out: Set[str] = set()
    for m in re.finditer(r'\["type"\]\s*=\s*Json::of\(([^)]*)\)', text):
        out.update(re.findall(r'"(\w+)"', m.group(1)))
    return out


def cc_req_keys(path: str) -> Set[str]:
    """Request keys a C++ server reads (``req.get("K")``)."""
    text = strip_cc_comments(open(path).read())
    return set(re.findall(r'\breq\.get\("(\w+)"\)', text))


def cc_assigned_keys(path: str) -> Set[str]:
    """All JSON keys a C++ file assigns (``x["k"] = ...``) — requests it
    builds and responses it fills."""
    text = strip_cc_comments(open(path).read())
    return set(re.findall(r'\["(\w+)"\]\s*=', text))


def cc_digest_keys(path: str) -> Set[str]:
    """Digest wire keys the lighthouse reads
    (``<expr>digest.get("K")``)."""
    text = strip_cc_comments(open(path).read())
    return set(re.findall(r'digest\.get\("(\w+)"\)', text))


def cc_member_keys(path: str) -> Set[str]:
    """Quorum-member keys lighthouse.cc parses (``p.get("K")`` in its
    member-parsing loop)."""
    text = strip_cc_comments(open(path).read())
    return set(re.findall(r'\bp\.get\("(\w+)"\)', text))


# ----------------------------------------------------------------------
# journal event kinds
# ----------------------------------------------------------------------


def py_event_kinds_registry(path: str) -> Optional[Dict[str, str]]:
    """The ``EVENT_KINDS`` dict literal in telemetry.py."""
    tree = _parse(path)
    for node in tree.body:
        targets: List[ast.expr] = []
        value: Optional[ast.expr] = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        for tgt in targets:
            if (
                isinstance(tgt, ast.Name)
                and tgt.id == "EVENT_KINDS"
                and isinstance(value, ast.Dict)
            ):
                return {
                    k.value: v.value
                    for k, v in zip(value.keys, value.values)
                    if isinstance(k, ast.Constant)
                    and isinstance(v, ast.Constant)
                }
    return None


def py_emitted_kinds(paths: List[str]) -> Dict[str, List[Tuple[str, int]]]:
    """``{kind: [(file, line), ...]}`` for every ``emit(...)`` /
    ``_journal(...)`` call with a string-literal first argument."""
    out: Dict[str, List[Tuple[str, int]]] = {}
    for path in paths:
        try:
            tree = _parse(path)
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            fn = node.func
            name = (
                fn.attr
                if isinstance(fn, ast.Attribute)
                else (fn.id if isinstance(fn, ast.Name) else None)
            )
            if name not in ("emit", "_journal"):
                continue
            arg0 = node.args[0]
            if isinstance(arg0, ast.Constant) and isinstance(
                arg0.value, str
            ):
                out.setdefault(arg0.value, []).append(
                    (path, node.lineno)
                )
    return out


# ----------------------------------------------------------------------
# env knobs
# ----------------------------------------------------------------------


def py_knob_registry(path: str) -> Optional[Dict[str, Dict[str, Any]]]:
    """The ``_k("NAME", type, default, doc, scope=...)`` entries in
    knobs.py, without importing it."""
    tree = _parse(path)
    out: Dict[str, Dict[str, Any]] = {}
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "_k"
            and node.args
            and isinstance(node.args[0], ast.Constant)
        ):
            name = node.args[0].value
            scope = "py"
            for kw in node.keywords:
                if kw.arg == "scope" and isinstance(
                    kw.value, ast.Constant
                ):
                    scope = kw.value.value
            if len(node.args) >= 4 and isinstance(
                node.args[3], ast.Constant
            ):
                pass
            out[name] = {"scope": scope}
    return out or None


def py_raw_env_reads(
    paths: List[str], prefix: str = "TORCHFT_"
) -> List[Tuple[str, int, str]]:
    """Direct ``os.environ``/``os.getenv`` READS of ``TORCHFT_*`` names:
    ``environ.get(X)``, ``environ[X]`` loads, ``getenv(X)``.  Writes,
    ``pop``/``del``, and ``"... in os.environ"`` checks are allowed
    (launchers set child env all the time)."""
    found: List[Tuple[str, int, str]] = []
    for path in paths:
        try:
            tree = _parse(path)
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            # os.environ.get("X") / os.getenv("X")
            if isinstance(node, ast.Call):
                fn = node.func
                attr = fn.attr if isinstance(fn, ast.Attribute) else None
                is_environ_get = (
                    attr == "get"
                    and isinstance(fn.value, ast.Attribute)
                    and fn.value.attr == "environ"
                )
                is_getenv = attr == "getenv" or (
                    isinstance(fn, ast.Name) and fn.id == "getenv"
                )
                if (is_environ_get or is_getenv) and node.args:
                    arg0 = node.args[0]
                    if isinstance(arg0, ast.Constant) and isinstance(
                        arg0.value, str
                    ):
                        if arg0.value.startswith(prefix):
                            found.append(
                                (path, node.lineno, arg0.value)
                            )
            # os.environ["X"] as a LOAD (writes have Store ctx)
            if (
                isinstance(node, ast.Subscript)
                and isinstance(node.ctx, ast.Load)
                and isinstance(node.value, ast.Attribute)
                and node.value.attr == "environ"
                and isinstance(node.slice, ast.Constant)
                and isinstance(node.slice.value, str)
                and node.slice.value.startswith(prefix)
            ):
                found.append((path, node.lineno, node.slice.value))
    return found


def py_knob_accessor_calls(
    paths: List[str],
) -> List[Tuple[str, int, str]]:
    """Every ``knobs.get_*("NAME")`` / ``knobs.require("NAME")`` call."""
    accessors = {
        "get_raw", "get_str", "get_int", "get_float", "get_bool",
        "require",
    }
    found: List[Tuple[str, int, str]] = []
    for path in paths:
        try:
            tree = _parse(path)
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            fn = node.func
            if not (
                isinstance(fn, ast.Attribute)
                and fn.attr in accessors
                and isinstance(fn.value, ast.Name)
                and "knobs" in fn.value.id
            ):
                continue
            arg0 = node.args[0]
            if isinstance(arg0, ast.Constant) and isinstance(
                arg0.value, str
            ):
                found.append((path, node.lineno, arg0.value))
    return found


def cc_env_reads(paths: List[str], prefix: str = "TORCHFT_") -> Set[str]:
    """``getenv("TORCHFT_X")`` names across the C++ sources."""
    out: Set[str] = set()
    for path in paths:
        text = strip_cc_comments(open(path).read())
        out.update(
            n
            for n in re.findall(r'getenv\("(\w+)"\)', text)
            if n.startswith(prefix)
        )
    return out


# ----------------------------------------------------------------------
# wall-clock-free chaos decision path
# ----------------------------------------------------------------------

# Functions forming the deterministic decision path: same (seed, spec,
# visit sequence) must produce the same injections on any host at any
# time — so no clocks, no RNG, no PIDs in here.
DECISION_FUNCS = (
    "fnv1a64",
    "splitmix64",
    "decision_hash",
    "_hash_unit",
    "parse_rule",
    "parse_spec",
    "Chaos._rule_fires",
    "Chaos.pick",
)

_FORBIDDEN_MODULES = {"time", "random", "datetime", "os", "uuid"}


def py_wallclock_calls(path: str) -> List[Tuple[str, int, str]]:
    """Calls to time/random/datetime/os/uuid inside the decision path
    (``(func, line, offending call)``)."""
    tree = _parse(path)
    bad: List[Tuple[str, int, str]] = []
    for qual in DECISION_FUNCS:
        fn = _func(tree, qual)
        if fn is None:
            bad.append((qual, 0, "<function missing>"))
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if (
                isinstance(f, ast.Attribute)
                and isinstance(f.value, ast.Name)
                and f.value.id in _FORBIDDEN_MODULES
            ):
                bad.append(
                    (qual, node.lineno, f"{f.value.id}.{f.attr}")
                )
    return bad
