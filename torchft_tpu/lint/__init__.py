"""Repo-specific contract linter: statically proves the dual-language
invariants the chaos/fleet planes only test dynamically.

The framework implements several contracts TWICE — once in Python, once
in C++ — and more that hold only by convention (journal event kinds,
env knobs, RPC JSON keys).  This package parses both sides of each
contract from SOURCE (Python via ``ast``, C++ via comment-stripped
regex/brace slicing — never compiled artifacts) and cross-checks them:

======================  ==============================================
rule class              contract
======================  ==============================================
golden-constants        FNV-1a/splitmix64 constants, hash-unit
                        divisor, step-window sentinel:
                        ``chaos.py`` vs ``_cpp/chaos.cc``
chaos-enums             fault kinds + planes: ``chaos.py`` vs
                        ``chaos.cc``/``chaos.hpp``
chaos-grammar           ``TORCHFT_CHAOS`` rule param keys, both parsers
c-abi                   ``_native.py`` ctypes declarations vs the
                        ``extern "C"`` prototypes, dtype/op codes
rpc-methods             RPC ``type`` values sent vs dispatched,
                        both directions, both servers
rpc-keys                request JSON keys read by a server exist in
                        what its clients send (incl. quorum member
                        and ≤512 B heartbeat digest wire keys)
event-kind-registry     every ``EventLog.emit``/``_journal`` kind is
                        registered in ``telemetry.EVENT_KINDS`` (and
                        no registered kind is dead)
env-knob-registry       every ``TORCHFT_*`` env read goes through
                        ``torchft_tpu/knobs.py``; registry matches
                        actual reads (both languages) and
                        ``docs/KNOBS.md``
wallclock-free-chaos    no wall-clock/random calls inside the chaos
                        decision path (replay determinism)
artifact-hygiene        no build artifacts tracked in git; lint scans
                        sources only
fleet-keys              ``/fleet.json`` payload keys written by
                        ``fleet_snapshot``/``fleet_agg_locked`` match
                        the golden top/row/agg sets; ``obs_top``/
                        ``obs_export`` never read an unwritten key
======================  ==============================================

Run ``python tools/tft_lint.py --check`` (the ``suite_gate.sh lint``
lane).  See ``docs/STATIC_ANALYSIS.md`` for the contract model and how
to add a new contract.
"""

from torchft_tpu.lint.rules import (  # noqa: F401
    Finding,
    RULES,
    run_all,
)
