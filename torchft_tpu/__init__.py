"""torchft_tpu: per-step fault-tolerant training for TPU (JAX/XLA) clusters.

A TPU-native framework with the capabilities of torchft
(github.com/pytorch/torchft): a C++ Lighthouse computes a quorum of healthy
replica groups each step; a per-group Manager reconfigures a resizable
collective layer, live-heals recovering replicas by streaming checkpoints from
a healthy peer, and gates optimizer commits with a distributed should-commit
vote. Inner parallelism (FSDP/TP/SP) stays native XLA SPMD over ICI; the
fault-tolerant replica axis runs host-driven over DCN.
"""

__version__ = "0.1.0"

__all__ = []  # populated as runtime modules land; see torchft_tpu.manager etc.
