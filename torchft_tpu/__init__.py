"""torchft_tpu: per-step fault-tolerant training for TPU (JAX/XLA) clusters.

A TPU-native framework with the capabilities of torchft
(github.com/pytorch/torchft): a C++ Lighthouse computes a quorum of healthy
replica groups each step; a per-group Manager reconfigures a resizable
collective layer, live-heals recovering replicas by streaming checkpoints from
a healthy peer, and gates optimizer commits with a distributed should-commit
vote. Inner parallelism (FSDP/TP/SP) stays native XLA SPMD over ICI; the
fault-tolerant replica axis runs host-driven over DCN.
"""

__version__ = "0.1.0"

from torchft_tpu.baby import ProcessGroupBabySocket  # noqa: E402,F401
from torchft_tpu.data import DistributedSampler  # noqa: E402,F401
from torchft_tpu.ddp import (  # noqa: E402,F401
    DistributedDataParallel,
    PureDistributedDataParallel,
)
from torchft_tpu.device_mesh import (  # noqa: E402,F401
    ManagedMesh,
    ft_init_device_mesh,
)
from torchft_tpu.local_sgd import DiLoCo, LocalSGD  # noqa: E402,F401
from torchft_tpu.manager import Manager, WorldSizeMode  # noqa: E402,F401
from torchft_tpu.optim import OptimizerWrapper  # noqa: E402,F401
from torchft_tpu.process_group import (  # noqa: E402,F401
    ManagedProcessGroup,
    ProcessGroup,
    ProcessGroupDummy,
    ProcessGroupSocket,
    ReduceOp,
)
from torchft_tpu.telemetry import (  # noqa: E402,F401
    EventLog,
    MetricsLogger,
    flight_recorder,
    get_event_log,
    span_percentiles,
    span_stats,
    timeit,
    trace_span,
)

__all__ = [
    "DiLoCo",
    "DistributedDataParallel",
    "DistributedSampler",
    "LocalSGD",
    "ManagedMesh",
    "ManagedProcessGroup",
    "Manager",
    "EventLog",
    "MetricsLogger",
    "get_event_log",
    "span_percentiles",
    "OptimizerWrapper",
    "ProcessGroup",
    "ProcessGroupBabySocket",
    "ProcessGroupDummy",
    "ProcessGroupSocket",
    "PureDistributedDataParallel",
    "ReduceOp",
    "WorldSizeMode",
    "flight_recorder",
    "ft_init_device_mesh",
    "span_stats",
    "timeit",
    "trace_span",
    "__version__",
]
