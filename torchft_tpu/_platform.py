"""Backend-platform pinning shared by the train-script entry points.

The container may pre-pin an accelerator platform via ``jax.config`` at
interpreter startup (sitecustomize), where the ``JAX_PLATFORMS`` env var
alone is silently ignored — every trainer must re-pin through
``jax.config`` BEFORE any backend initializes.  One helper so the next
platform quirk is fixed in one place, not per-script."""

from __future__ import annotations

import os


def maybe_pin_cpu() -> None:
    """Honors ``JAX_PLATFORMS=cpu`` even when an accelerator platform was
    pre-pinned via jax.config.  Safe to call any time before first device
    use (backends initialize lazily)."""
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
