"""A small TCP key-value store used for rendezvous.

Plays the role torch's ``TCPStore``/``PrefixStore`` play in the reference
(torchft/process_group.py:111-130, torchft/manager.py:271-314): every replica
group runs one store server; process groups rendezvous against unique prefixes
``{store}/torchft/{quorum_id}/{group_rank}``; the manager address is published
under a well-known key. Values are bytes; ``wait``/``get`` block until a key
exists (with timeout).

Protocol: length-prefixed JSON frames (see torchft_tpu/_net.py); values are
latin-1-encoded in JSON (control-plane values are tiny).
"""

from __future__ import annotations

import socket
import socketserver
import threading
from typing import Dict, Optional

from torchft_tpu import _net


class _StoreState:
    def __init__(self) -> None:
        self.data: Dict[str, str] = {}
        self.cond = threading.Condition()


class _StoreHandler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        state: _StoreState = self.server.state  # type: ignore[attr-defined]
        sock = self.request
        try:
            while True:
                req = _net.recv_json(sock)
                op = req.get("op")
                resp = {"ok": True}
                if op == "set":
                    with state.cond:
                        state.data[req["key"]] = req["value"]
                        state.cond.notify_all()
                elif op == "get":
                    timeout = req.get("timeout", 0.0)
                    with state.cond:
                        ok = state.cond.wait_for(
                            lambda: req["key"] in state.data, timeout=timeout
                        )
                        if ok:
                            resp["value"] = state.data[req["key"]]
                        else:
                            resp = {"ok": False, "timeout": True,
                                    "error": f"key {req['key']} not set"}
                elif op == "check":
                    with state.cond:
                        resp["exists"] = req["key"] in state.data
                elif op == "delete":
                    with state.cond:
                        resp["deleted"] = state.data.pop(req["key"], None) is not None
                elif op == "add":
                    # Atomic counter add; returns the new value.
                    with state.cond:
                        try:
                            cur = int(state.data.get(req["key"], "0"))
                            cur += int(req["amount"])
                        except ValueError as e:
                            resp = {"ok": False,
                                    "error": f"add on non-integer key "
                                             f"{req['key']!r}: {e}"}
                        else:
                            state.data[req["key"]] = str(cur)
                            state.cond.notify_all()
                            resp["value"] = str(cur)
                elif op == "list":
                    with state.cond:
                        resp["keys"] = sorted(state.data.keys())
                else:
                    resp = {"ok": False, "error": f"unknown op {op!r}"}
                _net.send_json(sock, resp)
        except (_net.FrameError, OSError):
            pass  # client disconnected


class _ThreadingTCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class TCPStoreServer:
    """In-process store server. One per replica group (hosted by group rank 0)."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0) -> None:
        self._server = _ThreadingTCPServer((host, port), _StoreHandler)
        self._server.state = _StoreState()  # type: ignore[attr-defined]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="tcp-store", daemon=True
        )
        self._thread.start()
        self.port = self._server.server_address[1]

    def address(self) -> str:
        from torchft_tpu.coordination import advertise_host

        return f"{advertise_host()}:{self.port}"

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()


class StoreClient:
    """Client with an optional key prefix (the ``PrefixStore`` analog)."""

    def __init__(self, addr: str, prefix: str = "", timeout: float = 60.0) -> None:
        self._addr = addr
        self._prefix = prefix
        self._timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()

    def with_prefix(self, prefix: str) -> "StoreClient":
        joined = f"{self._prefix}/{prefix}" if self._prefix else prefix
        return StoreClient(self._addr, joined, self._timeout)

    def _key(self, key: str) -> str:
        return f"{self._prefix}/{key}" if self._prefix else key

    def _call(self, req: dict, timeout: float, retry: bool = True) -> dict:
        with self._lock:
            if self._sock is None:
                self._sock = _net.connect(self._addr, self._timeout)
            try:
                resp = _net.call_json(self._sock, req, timeout + 5.0)
            except TimeoutError:
                # Never blind-retry a timed-out request: the server may have
                # applied it (matters for non-idempotent ops like `add`).
                self.close()
                raise
            except (OSError, _net.FrameError):
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None
                if not retry:
                    raise
                # One reconnect attempt (idempotent ops only).
                self._sock = _net.connect(self._addr, self._timeout)
                resp = _net.call_json(self._sock, req, timeout + 5.0)
        if not resp.get("ok", False):
            if resp.get("timeout"):
                raise TimeoutError(resp.get("error"))
            raise RuntimeError(f"store op failed: {resp.get('error')}")
        return resp

    def set(self, key: str, value: bytes | str) -> None:
        if isinstance(value, bytes):
            value = value.decode("latin-1")
        self._call({"op": "set", "key": self._key(key), "value": value}, 10.0)

    def get(self, key: str, timeout: Optional[float] = None) -> bytes:
        timeout = self._timeout if timeout is None else timeout
        resp = self._call(
            {"op": "get", "key": self._key(key), "timeout": timeout}, timeout
        )
        return resp["value"].encode("latin-1")

    def get_str(self, key: str, timeout: Optional[float] = None) -> str:
        return self.get(key, timeout).decode("latin-1")

    def check(self, key: str) -> bool:
        return self._call({"op": "check", "key": self._key(key)}, 10.0)["exists"]

    def delete(self, key: str) -> bool:
        return self._call({"op": "delete", "key": self._key(key)}, 10.0)["deleted"]

    def add(self, key: str, amount: int) -> int:
        # retry=False: a reconnect-resend could double-apply the increment.
        resp = self._call(
            {"op": "add", "key": self._key(key), "amount": amount}, 10.0,
            retry=False,
        )
        return int(resp["value"])

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                finally:
                    self._sock = None
