"""Kubernetes/GKE manifest rendering for replica-group jobs.

The scheduler-facing half of the torchx analog (reference:
torchft/torchx.py:11-83 renders roles for a scheduler; the slurm example
runner keeps N sbatch jobs alive, examples/slurm/runner.py). Here the
same topology the local launcher renders (launcher.py) is emitted as
Kubernetes manifests — one Job per replica group plus a lighthouse
Deployment+Service — so the cluster's own controller provides the
keep-alive restarts (`backoffLimit`) that runner.py provides locally.

Pure text generation (no kubernetes client): render, `kubectl apply -f -`.
TPU specifics: a `google.com/tpu` resource request and a
`cloud.google.com/gke-tpu-topology` node selector per group, so each
replica group lands on its own slice; the FT replica axis rides the
cluster network (DCN) exactly as the socket PG expects.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from torchft_tpu import knobs


def _env_list(env: Dict[str, str]) -> List[Dict[str, str]]:
    return [{"name": k, "value": str(v)} for k, v in sorted(env.items())]


def render_lighthouse(
    name: str = "torchft-lighthouse",
    image: str = "torchft-tpu:latest",
    min_replicas: int = 1,
    port: int = 29510,
    join_timeout_ms: int = 60000,
    namespace: str = "default",
) -> List[dict]:
    """Deployment + stable Service for the lighthouse (the quorum leader
    needs a stable DNS name; replicas point TORCHFT_LIGHTHOUSE at it)."""
    labels = {"app": name}
    deployment = {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": name, "namespace": namespace},
        "spec": {
            "replicas": 1,
            "selector": {"matchLabels": labels},
            "template": {
                "metadata": {"labels": labels},
                "spec": {
                    "containers": [
                        {
                            "name": "lighthouse",
                            "image": image,
                            "command": [
                                "torchft_tpu_lighthouse",
                                "--min-replicas", str(min_replicas),
                                "--port", str(port),
                                "--join-timeout-ms", str(join_timeout_ms),
                            ],
                            "ports": [{"containerPort": port}],
                        }
                    ]
                },
            },
        },
    }
    service = {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": name, "namespace": namespace},
        "spec": {
            "selector": labels,
            "ports": [{"port": port, "targetPort": port}],
        },
    }
    return [deployment, service]


def render_replica_groups(
    cmd: Sequence[str],
    num_replica_groups: int,
    lighthouse_addr: str,
    image: str = "torchft-tpu:latest",
    name: str = "torchft-trainer",
    namespace: str = "default",
    env: Optional[Dict[str, str]] = None,
    tpu_topology: Optional[str] = None,
    tpu_chips: int = 0,
    max_restarts: int = 100,
    timeout_sec: Optional[float] = None,
    quorum_timeout_sec: Optional[float] = None,
    termination_grace_period_sec: Optional[int] = None,
) -> List[dict]:
    """One Kubernetes Job per replica group (the reference's torchx role
    per group, torchx.py:41-76). The cluster restarts failed pods up to
    ``max_restarts`` (the runner.py keep-alive loop, scheduler-side);
    a restarted pod rejoins the quorum and live-heals.

    ``termination_grace_period_sec``: pod deletion / node drain delivers
    SIGTERM, the trainers' ``--drain-on-sigterm`` path finishes the
    step, leaves the quorum, and (with ``--durable-dir``) writes a final
    durable snapshot — the default comes from the registered
    ``TORCHFT_DRAIN_GRACE_S`` knob (120 s vs k8s's 30 s) so the renderer,
    the chaos ``preempt`` kind, and the SIGTERM drain path all budget the
    SAME SIGTERM->SIGKILL gap; the snapshot must fit inside it on large
    models.

    The FT env contract is OWNED by launcher.render_topology — this
    renderer just re-emits its ProcessSpecs as Jobs, so the two launch
    paths can never drift.
    """
    from torchft_tpu.orchestration.launcher import render_topology

    if termination_grace_period_sec is None:
        termination_grace_period_sec = int(
            knobs.get_float("TORCHFT_DRAIN_GRACE_S")
        )
    specs = render_topology(
        cmd,
        num_replica_groups=num_replica_groups,
        lighthouse_addr=lighthouse_addr,
        workers_per_replica=1,  # one pod per group; in-pod ranks are the
        # inner XLA mesh, not separate processes
        env=env,
        timeout_sec=timeout_sec,
        quorum_timeout_sec=quorum_timeout_sec,
    )
    jobs: List[dict] = []
    for spec in specs:
        group = spec.replica_group
        container: dict = {
            "name": "trainer",
            "image": image,
            "command": list(spec.cmd),
            "env": _env_list(spec.env),
        }
        pod_spec: dict = {
            "restartPolicy": "Never",  # the Job controller restarts
            "terminationGracePeriodSeconds": termination_grace_period_sec,
            "containers": [container],
        }
        if tpu_chips > 0:
            container["resources"] = {
                "limits": {"google.com/tpu": str(tpu_chips)}
            }
        if tpu_topology:
            pod_spec["nodeSelector"] = {
                "cloud.google.com/gke-tpu-topology": tpu_topology
            }
        jobs.append(
            {
                "apiVersion": "batch/v1",
                "kind": "Job",
                "metadata": {
                    "name": f"{name}-group{group}",
                    "namespace": namespace,
                    "labels": {"app": name, "replica-group": str(group)},
                },
                "spec": {
                    "backoffLimit": max_restarts,
                    "template": {
                        "metadata": {
                            "labels": {
                                "app": name,
                                "replica-group": str(group),
                            }
                        },
                        "spec": pod_spec,
                    },
                },
            }
        )
    return jobs


def render_yaml(manifests: List[dict]) -> str:
    """Multi-document YAML without external deps (the manifest trees use
    only dicts/lists/strs/ints, which this emitter covers)."""

    def emit(obj, indent: int = 0) -> List[str]:
        pad = "  " * indent
        lines: List[str] = []
        if isinstance(obj, dict):
            for k, v in obj.items():
                if isinstance(v, (dict, list)) and v:
                    lines.append(f"{pad}{k}:")
                    lines.extend(emit(v, indent + 1))
                else:
                    lines.append(f"{pad}{k}: {_scalar(v)}")
        elif isinstance(obj, list):
            for item in obj:
                if isinstance(item, (dict, list)) and item:
                    sub = emit(item, indent + 1)
                    first = sub[0].lstrip()
                    lines.append(f"{pad}- {first}")
                    lines.extend(sub[1:])
                else:
                    lines.append(f"{pad}- {_scalar(item)}")
        return lines

    import re

    # Unquoted only for strings that can't be misread as any other YAML
    # type: plain identifier-ish tokens that aren't numeric (incl. YAML 1.1
    # hex/binary/octal lexemes) or boolean-ish words. Everything else goes
    # double-quoted with control characters escaped.
    _plain = re.compile(r"^[A-Za-z][A-Za-z0-9._/-]*$")
    _booly = {"true", "false", "null", "yes", "no", "on", "off", "y", "n"}

    def _scalar(v) -> str:
        if isinstance(v, bool):
            return "true" if v else "false"
        if v is None:
            return "null"
        if v == {} and isinstance(v, dict):
            return "{}"
        if v == [] and isinstance(v, list):
            return "[]"
        s = str(v)
        if isinstance(v, str):
            if not _plain.match(s) or s.lower() in _booly:
                s = (
                    s.replace("\\", "\\\\")
                    .replace('"', '\\"')
                    .replace("\n", "\\n")
                    .replace("\r", "\\r")
                    .replace("\t", "\\t")
                )
                return f'"{s}"'
        return s

    docs = ["\n".join(emit(m)) for m in manifests]
    return "---\n" + "\n---\n".join(docs) + "\n"


def main(argv: Optional[Sequence[str]] = None) -> int:
    """CLI: render the full job (lighthouse + N replica-group Jobs) as
    multi-document YAML on stdout, ready for `kubectl apply -f -`."""
    import argparse

    p = argparse.ArgumentParser(
        description="Render GKE/Kubernetes manifests for a fault-tolerant "
        "replica-group training job."
    )
    p.add_argument("--replicas", type=int, required=True)
    p.add_argument("--image", default="torchft-tpu:latest")
    p.add_argument("--lighthouse-port", type=int, default=29510)
    p.add_argument("--min-replicas", type=int, default=1)
    p.add_argument("--tpu-topology", default=None)
    p.add_argument("--tpu-chips", type=int, default=0)
    p.add_argument("--namespace", default="default")
    p.add_argument("cmd", nargs=argparse.REMAINDER,
                   help="trainer command (after --)")
    args = p.parse_args(argv)
    cmd = list(args.cmd)
    if "--" in cmd:
        cmd.remove("--")  # drop only the argparse separator, not the
        # trainer's own "--" tokens
    cmd = cmd or ["python", "train_hsdp.py", "--model", "small"]
    manifests = render_lighthouse(
        image=args.image,
        min_replicas=args.min_replicas,
        port=args.lighthouse_port,
        namespace=args.namespace,
    ) + render_replica_groups(
        cmd,
        num_replica_groups=args.replicas,
        lighthouse_addr=f"torchft-lighthouse:{args.lighthouse_port}",
        image=args.image,
        namespace=args.namespace,
        tpu_topology=args.tpu_topology,
        tpu_chips=args.tpu_chips,
    )
    print(render_yaml(manifests), end="")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
