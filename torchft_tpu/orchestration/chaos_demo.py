"""One-command chaos demo: N replica groups + keep-alive runner + punisher.

Starts an in-proc lighthouse, launches ``--replicas`` demo trainers under
the keep-alive runner, SIGKILLs random groups on an MTBF schedule while
they train ``--steps`` steps, and verifies every group's final parameters
are bitwise identical — the north-star fault story
(reference: examples/slurm/runner.py + punisher.py, run as one command).

    python -m torchft_tpu.orchestration.chaos_demo --replicas 3 --steps 200
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import tempfile

import numpy as np

from torchft_tpu.coordination import LighthouseServer
from torchft_tpu.orchestration.launcher import render_topology
from torchft_tpu.orchestration.punisher import Punisher
from torchft_tpu.orchestration.runner import ReplicaGroupRunner

logger = logging.getLogger(__name__)


def run_demo(
    replicas: int = 3,
    steps: int = 200,
    mtbf_secs: float = 10.0,
    step_sleep: float = 0.01,
    timeout: float = 600.0,
    max_kills: int = 3,
    seed: int = 0,
    result_dir: str | None = None,
) -> dict:
    """Runs the demo; returns {"ok", "kills", "restarts", "results"}."""
    own_dir = result_dir is None
    if own_dir:
        result_dir = tempfile.mkdtemp(prefix="torchft_chaos_")
    lighthouse = LighthouseServer(
        bind="127.0.0.1:0",
        min_replicas=min(2, replicas),
        join_timeout_ms=10000,
        quorum_tick_ms=50,
        heartbeat_timeout_ms=3000,
    )
    punisher = None
    runner = None
    try:
        specs = render_topology(
            [
                sys.executable, "-m",
                "torchft_tpu.orchestration.demo_trainer",
                "--steps", str(steps),
                "--result-dir", result_dir,
                "--step-sleep", str(step_sleep),
            ],
            num_replica_groups=replicas,
            lighthouse_addr=lighthouse.address(),
        )
        runner = ReplicaGroupRunner(
            specs, max_restarts=20, log_dir=os.path.join(result_dir, "logs")
        )
        runner.start()
        punisher = Punisher(
            runner,
            mtbf_secs=mtbf_secs,
            interval_secs=0.5,
            seed=seed,
            max_kills=max_kills,
        )
        punisher.start()
        ok = runner.run_until_done(timeout)
        punisher.stop()

        results = {}
        for g in range(replicas):
            path = os.path.join(result_dir, f"group{g}.json")
            with open(path) as f:
                results[g] = json.load(f)
        ws = [np.asarray(r["w"], np.float32) for r in results.values()]
        equal = all(np.array_equal(ws[0], w) for w in ws[1:])
        return {
            "ok": ok and equal,
            "state_equal": equal,
            "kills": punisher.kills,
            "restarts": runner.restarts,
            "results": results,
        }
    finally:
        if punisher is not None:
            punisher.stop()
        if runner is not None:
            runner.stop()
        lighthouse.shutdown()


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--replicas", type=int, default=3)
    parser.add_argument("--steps", type=int, default=200)
    parser.add_argument("--mtbf", type=float, default=10.0)
    parser.add_argument("--step-sleep", type=float, default=0.01)
    parser.add_argument("--max-kills", type=int, default=3)
    parser.add_argument("--timeout", type=float, default=600.0)
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)

    out = run_demo(
        replicas=args.replicas,
        steps=args.steps,
        mtbf_secs=args.mtbf,
        step_sleep=args.step_sleep,
        timeout=args.timeout,
        max_kills=args.max_kills,
        seed=args.seed,
    )
    sps = [r["steps_per_sec"] for r in out["results"].values()]
    print(
        json.dumps(
            {
                "ok": out["ok"],
                "state_equal": out["state_equal"],
                "kills": out["kills"],
                "restarts": sum(out["restarts"].values()),
                "steps_per_sec_min": round(min(sps), 2),
                "steps_per_sec_max": round(max(sps), 2),
            }
        )
    )
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
