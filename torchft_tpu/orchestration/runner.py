"""Keep-alive supervisor for replica-group trainer processes (reference:
examples/slurm/runner.py:112-211 monitor/relaunch loop).

``ReplicaGroupRunner`` launches every ProcessSpec as a subprocess and
monitors them: a process that dies (crash, chaos kill, lighthouse Kill RPC)
is relaunched — the process-level half of fault tolerance that torchelastic
``max_restarts`` provides in the reference (torchx.py:56). The in-job half
(quorum shrink, heal-on-rejoin) is the Manager's.

CLI::

    python -m torchft_tpu.orchestration.runner \
        --replicas 3 --lighthouse 127.0.0.1:29510 -- python train_ddp.py
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

from torchft_tpu import knobs
from torchft_tpu.orchestration.launcher import ProcessSpec, render_topology

logger = logging.getLogger(__name__)


# libc handle resolved in the PARENT at import time: preexec_fn runs in
# the forked child before exec, where importing/loading modules can
# deadlock or fail silently (verified: a ctypes.CDLL inside the hook
# left the child without its pdeathsig).
try:
    import ctypes as _ctypes

    _LIBC = _ctypes.CDLL(None, use_errno=True)
    _LIBC.prctl  # resolve the symbol now
except Exception:  # noqa: BLE001 - non-linux fallback
    _LIBC = None

_PR_SET_PDEATHSIG = 1


def _pdeathsig_preexec() -> None:
    """Child-side (post-fork, pre-exec): request SIGKILL when the parent
    (the runner) dies.  Linux-only (prctl); elsewhere a no-op — the C++
    servers' own parent-death watchdog (net.hpp) still covers the next
    tier down.  Only async-signal-safe-ish work here: the libc handle
    was resolved in the parent."""
    if _LIBC is not None:
        try:
            _LIBC.prctl(_PR_SET_PDEATHSIG, int(signal.SIGKILL), 0, 0, 0)
        except Exception:  # noqa: BLE001 - supervision hint only
            pass


class ReplicaGroupRunner:
    def __init__(
        self,
        specs: List[ProcessSpec],
        max_restarts: int = 10,
        poll_interval: float = 0.5,
        log_dir: Optional[str] = None,
    ) -> None:
        self._specs = specs
        self._max_restarts = max_restarts
        self._poll = poll_interval
        self._log_dir = log_dir
        self._procs: Dict[int, subprocess.Popen] = {}
        self._restarts: Dict[int, int] = {i: 0 for i in range(len(specs))}
        self._clean_exit: Dict[int, bool] = {}
        self._lock = threading.Lock()
        self._stopping = False
        self._retired: set = set()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        for i in range(len(self._specs)):
            self._launch(i)

    def _launch(self, idx: int) -> None:
        spec = self._specs[idx]
        env = dict(os.environ)
        env.update(spec.env)
        stdout = None
        if self._log_dir:
            os.makedirs(self._log_dir, exist_ok=True)
            path = os.path.join(
                self._log_dir,
                f"{spec.name.replace('/', '_')}.r{self._restarts[idx]}.log",
            )
            stdout = open(path, "w")
        proc = subprocess.Popen(
            spec.cmd,
            env=env,
            stdout=stdout,
            stderr=subprocess.STDOUT if stdout else None,
            # Die with the supervisor: a runner killed without reaching
            # stop() leaves orphaned trainers spinning on quorum retries,
            # stealing the box's one core for hours (observed r5: two
            # strays + their manager servers degraded every later suite
            # run ~2x and flaked quorum-timing tests).  BEST-EFFORT:
            # pdeathsig delivery is not honored in every container
            # (verified undelivered on this sandboxed box despite
            # PR_GET_PDEATHSIG reading back 9), so the primary defense
            # is the SIGTERM->clean-unwind handler in the harness entry
            # points (tools/drills.py, tests/conftest.py), which runs
            # stop() and reaps the tree; the C++ servers' own
            # getppid-polling watchdog (net.hpp) covers the tier below.
            # TORCHFT_RUNNER_PDEATHSIG=0 disables the hook: preexec_fn
            # forces fork-not-posix_spawn, which in a jax-threaded
            # parent carries a small fork-lock deadlock risk (Python
            # 3.12 warns) — the test suite opts out (conftest) since
            # delivery doesn't work in its container anyway.
            preexec_fn=(
                _pdeathsig_preexec
                if knobs.get_raw("TORCHFT_RUNNER_PDEATHSIG") != "0"
                else None
            ),
        )
        if stdout is not None:
            stdout.close()  # the child owns the fd now
        with self._lock:
            self._procs[idx] = proc
        logger.info("launched %s (pid %d)", spec.name, proc.pid)

    def monitor_once(self) -> bool:
        """One supervision pass; returns True while anything is running or
        restartable."""
        alive = False
        for idx, spec in enumerate(self._specs):
            proc = self._procs.get(idx)
            if proc is None:
                continue
            rc = proc.poll()
            if rc is None:
                alive = True
                continue
            if idx in self._clean_exit:
                continue
            if rc == 0:
                self._clean_exit[idx] = True
                logger.info("%s exited cleanly", spec.name)
                continue
            if self._stopping:
                continue
            # Runner-observed process death: the supervisor is the first
            # (sometimes the only) observer of an abrupt trainer death —
            # journal it as failure evidence so detection-latency reports
            # can attribute the proc_death signal path.
            self._journal_proc_death(spec.name, rc)
            if idx in self._retired:
                # Deliberate scale-down: the exit is final, clean or not —
                # a retired group must never resurrect (a relaunch would
                # silently undo the resize).
                logger.info(
                    "%s retired; not relaunching (rc=%d)", spec.name, rc
                )
                self._clean_exit[idx] = False
                continue
            if self._restarts[idx] >= self._max_restarts:
                logger.error(
                    "%s died (rc=%d) and exhausted %d restarts",
                    spec.name, rc, self._max_restarts,
                )
                self._clean_exit[idx] = False
                continue
            self._restarts[idx] += 1
            logger.warning(
                "%s died (rc=%d); relaunching (restart %d/%d)",
                spec.name, rc, self._restarts[idx], self._max_restarts,
            )
            self._launch(idx)
            alive = True
        return alive

    def _journal_proc_death(self, name: str, rc: int) -> None:
        from torchft_tpu.telemetry import get_event_log

        log = get_event_log()
        if log is not None:
            log.emit(
                "failure_signal",
                source="proc_death",
                subject=name,
                site="runner.monitor",
                detail=f"rc={rc}",
            )

    def run_until_done(self, timeout: float) -> bool:
        """Supervises until every process exited cleanly (True) or the
        deadline passes / a process exhausts restarts (False)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            running = self.monitor_once()
            done = len(self._clean_exit) == len(self._specs)
            if done or not running:
                return all(self._clean_exit.get(i) for i in range(len(self._specs)))
            time.sleep(self._poll)
        return False

    def stop(self) -> None:
        self._stopping = True
        with self._lock:
            procs = list(self._procs.values())
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()

    # -- chaos interface (used by the punisher) ----------------------------

    def live_pids(self) -> Dict[int, int]:
        """spec index -> pid of currently-running processes."""
        with self._lock:
            return {
                i: p.pid
                for i, p in self._procs.items()
                if p.poll() is None and i not in self._clean_exit
            }

    def kill_group(self, idx: int, sig: int = signal.SIGKILL) -> bool:
        """SIGKILLs one replica group's process (chaos); the monitor loop
        relaunches it."""
        with self._lock:
            proc = self._procs.get(idx)
        if proc is None or proc.poll() is not None:
            return False
        logger.warning(
            "chaos: killing %s (pid %d)", self._specs[idx].name, proc.pid
        )
        proc.send_signal(sig)
        return True

    def retire_group(self, idx: int) -> None:
        """Marks one group as deliberately scaled down: its NEXT exit is
        final (no relaunch, however it dies). Call before delivering a
        preemption SIGTERM — a drain that overruns its grace window and
        eats a SIGKILL must stay gone, not resurrect via the restart
        budget and silently undo the resize. A clean (rc 0) drained exit
        still counts as clean; any other exit of a retired group marks it
        failed-final."""
        with self._lock:
            self._retired.add(idx)

    def clean_exit(self, idx: int) -> bool:
        """Whether spec ``idx`` has exited with rc 0 (False while running,
        crashed, or restart-exhausted)."""
        return bool(self._clean_exit.get(idx))

    @property
    def restarts(self) -> Dict[int, int]:
        return dict(self._restarts)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--replicas", type=int, required=True)
    parser.add_argument("--workers-per-replica", type=int, default=1)
    parser.add_argument("--lighthouse", type=str, required=True)
    parser.add_argument("--max-restarts", type=int, default=10)
    parser.add_argument("--timeout", type=float, default=3600.0)
    parser.add_argument("--log-dir", type=str, default=None)
    parser.add_argument("cmd", nargs=argparse.REMAINDER,
                        help="trainer command after --")
    args = parser.parse_args(argv)
    cmd = args.cmd[1:] if args.cmd and args.cmd[0] == "--" else args.cmd
    if not cmd:
        parser.error("missing trainer command")
    logging.basicConfig(level=logging.INFO)

    specs = render_topology(
        cmd,
        num_replica_groups=args.replicas,
        workers_per_replica=args.workers_per_replica,
        lighthouse_addr=args.lighthouse,
    )
    runner = ReplicaGroupRunner(
        specs, max_restarts=args.max_restarts, log_dir=args.log_dir
    )
    runner.start()
    try:
        ok = runner.run_until_done(args.timeout)
    finally:
        runner.stop()
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
