"""Topology rendering: the torchx-component analog (reference:
torchft/torchx.py:11-83 hsdp()).

Renders an N-replica-group x workers_per_replica job into per-process
launch specs carrying the full FT environment (``REPLICA_GROUP_ID``,
``NUM_REPLICA_GROUPS``, ``TORCHFT_LIGHTHOUSE``, and per-group
``RANK``/``WORLD_SIZE``/``MASTER_ADDR``/``MASTER_PORT`` so multi-rank
groups rendezvous on their group store). The runner (runner.py) consumes
these specs locally; a k8s/slurm integration renders the same specs into
its own job descriptions.
"""

from __future__ import annotations

import dataclasses
import socket
from typing import Dict, List, Optional, Sequence


@dataclasses.dataclass
class ProcessSpec:
    """One OS process of the job."""

    replica_group: int
    group_rank: int
    cmd: List[str]
    env: Dict[str, str]

    @property
    def name(self) -> str:
        return f"replica{self.replica_group}/rank{self.group_rank}"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def render_topology(
    cmd: Sequence[str],
    num_replica_groups: int,
    lighthouse_addr: str,
    workers_per_replica: int = 1,
    env: Optional[Dict[str, str]] = None,
    timeout_sec: Optional[float] = None,
    quorum_timeout_sec: Optional[float] = None,
    journal_dir: Optional[str] = None,
) -> List[ProcessSpec]:
    """Returns one ProcessSpec per (replica_group, group_rank).

    ``cmd`` is the trainer command (e.g. ``[sys.executable, "train_ddp.py"]``);
    the FT topology is injected purely through env vars, like the reference's
    torchrun roles (torchx.py:70-74).

    ``journal_dir`` wires the step-event journal (telemetry.EventLog): each
    process gets a distinct ``TORCHFT_JOURNAL_FILE`` under the dir so a run
    produces per-replica journals that ``tools/obs_report.py`` can merge.
    Relaunches of the same slot append to the same file — the timeline of a
    replica that died and came back belongs in one journal.
    """
    specs: List[ProcessSpec] = []
    for group in range(num_replica_groups):
        master_port = _free_port() if workers_per_replica > 1 else None
        for rank in range(workers_per_replica):
            e: Dict[str, str] = dict(env or {})
            e.update(
                {
                    "REPLICA_GROUP_ID": str(group),
                    "NUM_REPLICA_GROUPS": str(num_replica_groups),
                    "TORCHFT_LIGHTHOUSE": lighthouse_addr,
                    "RANK": str(rank),
                    "WORLD_SIZE": str(workers_per_replica),
                }
            )
            if master_port is not None:
                e["MASTER_ADDR"] = "127.0.0.1"
                e["MASTER_PORT"] = str(master_port)
            if journal_dir is not None:
                e["TORCHFT_JOURNAL_FILE"] = (
                    f"{journal_dir}/journal_replica{group}_rank{rank}.jsonl"
                )
            if timeout_sec is not None:
                e["TORCHFT_TIMEOUT_SEC"] = str(timeout_sec)
            if quorum_timeout_sec is not None:
                e["TORCHFT_QUORUM_TIMEOUT_SEC"] = str(quorum_timeout_sec)
            specs.append(
                ProcessSpec(
                    replica_group=group,
                    group_rank=rank,
                    cmd=list(cmd),
                    env=e,
                )
            )
    return specs
