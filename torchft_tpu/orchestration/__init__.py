"""Process-level orchestration: topology rendering, keep-alive supervision,
and chaos injection (reference: torchft/torchx.py, examples/slurm/runner.py,
examples/slurm/punisher.py)."""

from torchft_tpu.orchestration.k8s import (
    render_lighthouse,
    render_replica_groups,
    render_yaml,
)
from torchft_tpu.orchestration.launcher import ProcessSpec, render_topology
from torchft_tpu.orchestration.punisher import Punisher, kill_via_lighthouse
from torchft_tpu.orchestration.runner import ReplicaGroupRunner

__all__ = [
    "ProcessSpec",
    "render_topology",
    "ReplicaGroupRunner",
    "Punisher",
    "kill_via_lighthouse",
]
