"""Chaos monkey (reference: examples/slurm/punisher.py:15-89).

Two kill mechanisms:
- process-level: SIGKILL a random live replica-group process managed by a
  ``ReplicaGroupRunner`` (``kill_one`` / the ``Punisher`` MTBF loop,
  reference kill_one/kill_loop punisher.py:25-45);
- control-plane: the lighthouse ``POST /replica/{id}/kill`` RPC, which makes
  the target's manager server ``exit(1)`` (reference: lighthouse dashboard
  Kill button, lighthouse.rs:454-479).
"""

from __future__ import annotations

import logging
import random
import threading
import urllib.request
from typing import Optional

from torchft_tpu.orchestration.runner import ReplicaGroupRunner

logger = logging.getLogger(__name__)


def kill_one(
    runner: ReplicaGroupRunner,
    rng: Optional[random.Random] = None,
    spare_group_zero: bool = True,
) -> Optional[int]:
    """SIGKILLs one random live replica group; returns the killed spec index
    (None if nothing killable). ``spare_group_zero`` mirrors the reference's
    never-kill-replica-0 rule (punisher.py:25-33) so at least one healthy
    checkpoint source always survives."""
    rng = rng or random.Random()
    candidates = [
        idx for idx in runner.live_pids() if not (spare_group_zero and idx == 0)
    ]
    if not candidates:
        return None
    victim = rng.choice(candidates)
    return victim if runner.kill_group(victim) else None


class Punisher:
    """Background kill loop with MTBF pacing (reference: kill_loop,
    punisher.py:36-45): every tick, kill one random group with probability
    interval/mtbf."""

    def __init__(
        self,
        runner: ReplicaGroupRunner,
        mtbf_secs: float,
        interval_secs: float = 1.0,
        spare_group_zero: bool = True,
        seed: Optional[int] = None,
        max_kills: Optional[int] = None,
    ) -> None:
        self._runner = runner
        self._mtbf = mtbf_secs
        self._interval = interval_secs
        self._spare0 = spare_group_zero
        self._rng = random.Random(seed)
        self._max_kills = max_kills
        self.kills = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, name="punisher", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        p_kill = min(self._interval / self._mtbf, 1.0)
        while not self._stop.wait(self._interval):
            if self._max_kills is not None and self.kills >= self._max_kills:
                return
            if self._rng.random() < p_kill:
                victim = kill_one(
                    self._runner, self._rng, spare_group_zero=self._spare0
                )
                if victim is not None:
                    self.kills += 1
                    logger.warning(
                        "punisher: killed group %d (%d kills so far)",
                        victim, self.kills,
                    )

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)


def kill_via_lighthouse(
    lighthouse_addr: str, replica_id: str, timeout: float = 5.0
) -> bool:
    """Control-plane kill: POST /replica/{id}/kill on the lighthouse HTTP
    dashboard port — the target replica's manager server exits(1), taking
    the trainer's quorum with it."""
    url = f"http://{lighthouse_addr}/replica/{replica_id}/kill"
    req = urllib.request.Request(url, method="POST", data=b"")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return 200 <= resp.status < 300
    except Exception as e:  # noqa: BLE001 - chaos tooling reports, not raises
        logger.warning("lighthouse kill of %r failed: %s", replica_id, e)
        return False
