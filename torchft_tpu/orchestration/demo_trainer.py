"""Minimal deterministic FT trainer used by the chaos demo and tests.

Numpy-only data plane (no accelerator is touched, so any number of these
can run as subprocesses on one machine): each replica group trains a small
parameter vector with gradients that are a pure function of the committed
step, so EVERY replica group that reaches step N — regardless of how many
times it was killed, restarted, and healed — must hold bitwise-identical
parameters. That is the north-star fault-tolerance contract
(reference: manager_integ_test state-equality asserts; BASELINE.md).

Run under the keep-alive runner with a punisher to demonstrate it::

    python -m torchft_tpu.orchestration.chaos_demo
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import time
from typing import Dict

import numpy as np

from torchft_tpu.manager import Manager
from torchft_tpu.process_group import make_process_group


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=200)
    parser.add_argument("--dim", type=int, default=64)
    parser.add_argument("--min-replicas", type=int, default=1)
    parser.add_argument("--result-dir", type=str, default=None)
    parser.add_argument("--step-sleep", type=float, default=0.0,
                        help="artificial per-step compute time")
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)

    group = os.environ.get("REPLICA_GROUP_ID", "0")
    params: Dict[str, np.ndarray] = {
        "w": np.zeros(args.dim, np.float32),
    }

    manager = Manager(
        pg=make_process_group(timeout=15.0),
        state_dict=lambda: {k: v.copy() for k, v in params.items()},
        load_state_dict=lambda s: params.update(
            {k: np.asarray(v) for k, v in s.items()}
        ),
        min_replica_size=args.min_replicas,
        use_async_quorum=True,
        timeout=15.0,
        quorum_timeout=30.0,
        connect_timeout=15.0,
        max_retries=20,
    )
    t0 = time.monotonic()
    committed = 0
    try:
        while manager.current_step() < args.steps:
            step = manager.current_step()
            manager.start_quorum()
            if args.step_sleep:
                time.sleep(args.step_sleep)
            # Gradient = pure function of the committed step: replicas that
            # commit the same steps compute identical params, bitwise.
            grad = np.full(
                args.dim, np.float32(1.0 + (step % 7) * 0.5), np.float32
            )
            out = manager.allreduce(grad).wait(timeout=30)[0]
            if manager.should_commit():
                params["w"] -= np.float32(0.01) * out
                committed += 1
        wall = time.monotonic() - t0
        if args.result_dir:
            os.makedirs(args.result_dir, exist_ok=True)
            path = os.path.join(args.result_dir, f"group{group}.json")
            with open(path, "w") as f:
                json.dump(
                    {
                        "group": group,
                        "w": [float(x) for x in params["w"]],
                        "final_step": manager.current_step(),
                        "committed_this_life": committed,
                        "wall_secs": wall,
                        "steps_per_sec": args.steps / wall if wall > 0 else 0,
                    },
                    f,
                )
        logging.info(
            "group %s done: step=%d committed_this_life=%d wall=%.1fs",
            group, manager.current_step(), committed, wall,
        )
        return 0
    finally:
        manager.shutdown()


if __name__ == "__main__":
    sys.exit(main())
