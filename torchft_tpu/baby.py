"""Subprocess-isolated process group ("Baby PG").

Runs the real collective backend (:class:`ProcessGroupSocket`) in a spawned
child process so a wedged or crashed collective layer can be SIGKILLed and
respawned without taking down the trainer — the capability of the
reference's ``ProcessGroupBaby*`` family (reference: process_group.py
1241-1798), rebuilt for the TPU replica axis:

- the parent never blocks on the child: ops are issued over a command pipe
  and resolved by a future-handler thread reading a result pipe, so
  ``wait(timeout)`` is always interruptible;
- in-place collectives (allreduce, broadcast) move payloads through POSIX
  shared memory, written through by the child — no pickling of gradient
  buffers on the hot path (the analog of the reference's
  ``_maybe_share_tensors``, process_group.py:1310-1321);
- ``configure`` kills (SIGKILL) and respawns the child (reference:
  process_group.py:1386-1431), ``abort`` kills it and fails all in-flight
  work, and a child death detected on the pipe fails pending work instead
  of wedging the trainer;
- ``num_active_work`` introspection (reference: process_group.py:1790-1795).

The trainer process stays alive through any of: child crash, child wedge
(killed via ``abort`` after a ``wait`` timeout), or peer death surfacing as
a collective error in the child.
"""

from __future__ import annotations

import multiprocessing as mp
import os
import pickle
import threading
import time
from multiprocessing import shared_memory
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from torchft_tpu.process_group import ProcessGroup, ReduceOp, _as_list
from torchft_tpu.work import ErrorWork, Work

import logging

logger = logging.getLogger(__name__)

# Arrays at or above this size ride shared memory; smaller ones are pickled
# through the pipe (a 4 KiB control tensor isn't worth an shm segment).
_SHM_THRESHOLD = 1 << 16


def _release_shms(shms: List[shared_memory.SharedMemory]) -> None:
    """Close + unlink, tolerating segments already gone (a dying child's
    resource tracker can unlink first)."""
    for shm in shms:
        try:
            shm.close()
        except OSError:
            pass
        try:
            shm.unlink()
        except (OSError, FileNotFoundError):
            pass


def _encode_arrays(
    arrays: List[np.ndarray], shms: List[shared_memory.SharedMemory]
) -> List[Tuple]:
    """Parent-side: stage arrays for the child. Large arrays are copied into
    fresh shm segments (appended to ``shms``); small ones inlined."""
    meta: List[Tuple] = []
    for a in arrays:
        a = np.ascontiguousarray(a)
        if a.nbytes >= _SHM_THRESHOLD:
            shm = shared_memory.SharedMemory(create=True, size=a.nbytes)
            np.ndarray(a.shape, a.dtype, buffer=shm.buf)[...] = a
            shms.append(shm)
            meta.append(("shm", shm.name, str(a.dtype), a.shape))
        else:
            meta.append(("inline", a.tobytes(), str(a.dtype), a.shape))
    return meta


def _decode_arrays(
    meta: List[Tuple], shms: List[shared_memory.SharedMemory]
) -> List[np.ndarray]:
    """Child-side: reconstruct arrays. shm-backed ones write through."""
    out: List[np.ndarray] = []
    for kind, payload, dtype, shape in meta:
        if kind == "shm":
            shm = shared_memory.SharedMemory(name=payload)
            # The parent owns these segments' lifetime. On Python <= 3.12
            # attaching registers with THIS process's resource tracker,
            # which would unlink them when the child exits/dies — racing
            # the parent's own cleanup. Unregister to disown.
            try:
                from multiprocessing import resource_tracker

                resource_tracker.unregister(shm._name, "shared_memory")
            except Exception:  # noqa: BLE001 - tracker API is private-ish
                pass
            shms.append(shm)
            out.append(np.ndarray(shape, np.dtype(dtype), buffer=shm.buf))
        else:
            out.append(
                np.frombuffer(bytearray(payload), dtype=np.dtype(dtype)).reshape(
                    shape
                )
            )
    return out


def _baby_worker(
    cmd_conn, res_conn, store_addr: str, rank: int, world_size: int,
    timeout: float,
) -> None:
    """Child main: configure a real socket PG, then replay ops from the
    command pipe in issue order (reference worker loop:
    process_group.py:1441-1605). Runs until "exit" or SIGKILL."""
    from torchft_tpu.process_group import make_process_group

    # Factory, not a hardcoded class: TORCHFT_PG is inherited across the
    # process boundary, so baby groups ride the same backend as the parent.
    pg = make_process_group(timeout=timeout)
    try:
        pg.configure(store_addr, rank, world_size)
    except Exception as e:  # noqa: BLE001 - parent maps this to configure fail
        res_conn.send(("boot_error", repr(e)))
        return
    res_conn.send(("ready",))

    open_shms: List[shared_memory.SharedMemory] = []
    try:
        while True:
            try:
                msg = cmd_conn.recv()
            except (EOFError, OSError):
                break
            kind = msg[0]
            if kind == "exit":
                break
            if kind == "set_timeout":
                timeout = float(msg[1])
                pg.set_timeout(timeout)
                continue
            if kind == "stall":
                # Test-only wedge injection: simulates a hung collective
                # layer (the scenario Baby PG exists for).
                time.sleep(msg[1])
                continue
            assert kind == "func", kind
            _, op_id, name, arg_meta, kwargs = msg
            del open_shms[:]
            try:
                arrays = _decode_arrays(arg_meta, open_shms)
                result = _run_op(pg, name, arrays, kwargs, timeout)
                # In-place ops already wrote through shm; anything inlined
                # (or op-produced) goes back over the pipe.
                res_conn.send(("done", op_id, _pickle_result(name, result, arrays, arg_meta)))
            except Exception as e:  # noqa: BLE001 - report, keep serving
                res_conn.send(("error", op_id, repr(e)))
            finally:
                for shm in open_shms:
                    shm.close()
                del open_shms[:]
    finally:
        pg.shutdown()
        try:
            res_conn.close()
        except OSError:
            pass


def _run_op(pg, name: str, arrays, kwargs: Dict[str, Any], timeout: float):
    if name == "allreduce":
        return pg.allreduce(arrays, ReduceOp(kwargs["op"])).wait(timeout)
    if name == "allgather":
        return pg.allgather(arrays).wait(timeout)
    if name == "broadcast":
        return pg.broadcast(arrays, root=kwargs["root"]).wait(timeout)
    if name == "reduce_scatter":
        return pg.reduce_scatter(arrays, ReduceOp(kwargs["op"])).wait(timeout)
    if name == "alltoall":
        return pg.alltoall(arrays).wait(timeout)
    if name == "barrier":
        return pg.barrier().wait(timeout)
    if name == "send":
        return pg.send(arrays, dst=kwargs["dst"], tag=kwargs["tag"]).wait(timeout)
    if name == "recv":
        return pg.recv(
            src=kwargs["src"], tag=kwargs["tag"],
            num_tensors=kwargs["num_tensors"],
        ).wait(timeout)
    raise ValueError(f"unknown op {name!r}")


def _pickle_result(name, result, arrays, arg_meta):
    """Results for in-place ops whose inputs rode shm need no payload: the
    child already wrote through. Everything else is pickled."""
    if name in ("allreduce", "broadcast"):
        # Write back any *inlined* inputs (too small for shm) explicitly.
        inline_payloads = [
            a.tobytes() if m[0] == "inline" else None
            for a, m in zip(arrays, arg_meta)
        ]
        return ("inplace", inline_payloads)
    return ("value", pickle.dumps(result, protocol=pickle.HIGHEST_PROTOCOL))


class _BabyWork(Work):
    """Parent-side handle; resolved by the future-handler thread."""

    def __init__(self, op_id: int) -> None:
        self._op_id = op_id
        self._event = threading.Event()
        self._result: Any = None
        self._exc: Optional[BaseException] = None
        self._callbacks: List[Any] = []
        self._cb_lock = threading.Lock()

    def _complete(self, result: Any = None, exc: Optional[BaseException] = None):
        with self._cb_lock:
            if self._event.is_set():
                return  # first completion wins (e.g. abort vs late result)
            self._result = result
            self._exc = exc
            self._event.set()
            callbacks, self._callbacks = self._callbacks, []
        for fn in callbacks:
            try:
                fn(self)
            except Exception:  # noqa: BLE001
                logger.exception("baby work callback failed")

    def wait(self, timeout: Optional[float] = None) -> Any:
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"baby pg op {self._op_id} timed out after {timeout}s "
                "(child may be wedged: call abort() to kill it)"
            )
        if self._exc is not None:
            raise self._exc
        return self._result

    def done(self) -> bool:
        return self._event.is_set()

    def exception(self) -> Optional[BaseException]:
        return self._exc if self._event.is_set() else None

    def add_done_callback(self, fn) -> None:
        with self._cb_lock:
            if not self._event.is_set():
                self._callbacks.append(fn)
                return
        fn(self)


class ProcessGroupBabySocket(ProcessGroup):
    """Socket process group running in a kill-safe subprocess.

    Drop-in for :class:`ProcessGroupSocket` wherever the ``ProcessGroup``
    ABC is accepted (Manager, DDP, transports). The reference equivalent is
    ``ProcessGroupBabyGloo`` (process_group.py:1853-1899).
    """

    def __init__(self, timeout: float = 60.0) -> None:
        self._timeout = timeout
        self._rank = -1
        self._world = 0
        self._child: Optional[mp.process.BaseProcess] = None
        self._cmd_conn = None
        self._res_conn = None
        self._handler: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        # Serializes issue order (op-id allocation -> pipe send) WITHOUT
        # blocking abort(): a full cmd pipe under a wedged child blocks the
        # sender on this lock only, so abort() can still take self._lock,
        # SIGKILL the child, and break the pipe out from under the send.
        self._send_lock = threading.Lock()
        self._errored: Optional[Exception] = None
        self._next_op = 0
        self._pending: Dict[int, Tuple[_BabyWork, List, List]] = {}
        self._generation = 0

    # -- lifecycle ---------------------------------------------------------

    def configure(self, store_addr: str, rank: int, world_size: int) -> None:
        failed: List[Tuple[_BabyWork, Exception]] = []
        try:
            self._configure_inner(store_addr, rank, world_size, failed)
        finally:
            for work, err in failed:
                work._complete(exc=err)

    def _configure_inner(
        self, store_addr: str, rank: int, world_size: int, failed: List
    ) -> None:
        with self._lock:
            failed.extend(self._kill_child_locked())
            self._errored = None
            self._rank = rank
            self._world = world_size
            self._generation += 1
            generation = self._generation

        # Spawn + ready-wait OUTSIDE the lock: both can take seconds (fresh
        # interpreter + rendezvous), and abort() must be able to interrupt a
        # wedged reconfigure (the Manager arms a context_timeout around
        # pg.configure for exactly that).
        ctx = mp.get_context("spawn")
        parent_cmd, child_cmd = ctx.Pipe()
        parent_res, child_res = ctx.Pipe(duplex=False)
        proc = ctx.Process(
            target=_baby_worker,
            args=(
                child_cmd, child_res, store_addr, rank, world_size,
                self._timeout,
            ),
            daemon=True,
            name=f"baby-pg-{rank}",
        )
        proc.start()
        child_cmd.close()
        child_res.close()
        try:
            deadline = time.monotonic() + self._timeout + 30.0
            ready = False
            while time.monotonic() < deadline:
                # Short poll slices so an abort() (which latches _errored)
                # cancels the wait promptly.
                if parent_res.poll(0.2):
                    ready = True
                    break
                with self._lock:
                    if self._errored is not None or self._generation != generation:
                        raise RuntimeError(
                            "baby pg aborted/reconfigured during configure"
                        )
            if not ready:
                raise RuntimeError(
                    f"baby pg rank {rank}: child did not become ready"
                )
            try:
                msg = parent_res.recv()
            except (EOFError, OSError) as e:
                raise RuntimeError(
                    f"baby pg rank {rank}: child died during boot "
                    f"(before reporting ready): {e!r}"
                ) from e
            if msg[0] != "ready":
                raise RuntimeError(
                    f"baby pg rank {rank}: child failed to configure: {msg[1]}"
                )
            with self._lock:
                if self._errored is not None or self._generation != generation:
                    raise RuntimeError(
                        "baby pg aborted/reconfigured during configure"
                    )
                self._child = proc
                self._cmd_conn = parent_cmd
                self._res_conn = parent_res
                handler = threading.Thread(
                    target=self._future_handler,
                    args=(parent_res, generation),
                    name=f"baby-pg-futures-{rank}",
                    daemon=True,
                )
                self._handler = handler
                handler.start()
        except Exception:
            proc.kill()
            proc.join(timeout=10.0)
            for conn in (parent_cmd, parent_res):
                try:
                    conn.close()
                except OSError:
                    pass
            raise

    def _future_handler(self, res_conn, generation: int) -> None:
        """Drains the child's result pipe, resolving works (reference:
        _future_handler thread, process_group.py:1539-1605). Child death
        (pipe EOF) fails everything pending."""
        while True:
            try:
                msg = res_conn.recv()
            except (EOFError, OSError):
                with self._lock:
                    if self._generation != generation:
                        return  # superseded by a reconfigure
                    err = self._errored or RuntimeError(
                        "baby pg child process died"
                    )
                    self._errored = err
                    pending = list(self._pending.values())
                    self._pending.clear()
                for work, _, shms in pending:
                    _release_shms(shms)
                    work._complete(exc=err)
                return
            kind, op_id = msg[0], msg[1]
            with self._lock:
                entry = self._pending.pop(op_id, None)
            if entry is None:
                continue
            work, arrays, shms = entry
            # Any failure resolving THIS op must not kill the handler
            # thread — every later op would then hang to timeout.
            exc: Optional[BaseException] = None
            result = None
            if kind == "error":
                exc = RuntimeError(f"baby pg op failed in child: {msg[2]}")
            else:
                try:
                    result = self._decode_result(msg[2], arrays, shms)
                except Exception as e:  # noqa: BLE001 - e.g. read-only input
                    exc = e
            _release_shms(shms)
            work._complete(result=result, exc=exc)

    def _decode_result(self, payload, arrays: List[np.ndarray], shms) -> Any:
        kind, body = payload
        if kind == "inplace":
            # shm-staged inputs: copy the child's reduced bytes back into
            # the caller's arrays; inlined ones come back over the pipe.
            shm_i = 0
            for a, inline in zip(arrays, body):
                if inline is None:
                    shm = shms[shm_i]
                    shm_i += 1
                    a[...] = np.ndarray(a.shape, a.dtype, buffer=shm.buf)
                else:
                    a[...] = np.frombuffer(inline, dtype=a.dtype).reshape(
                        a.shape
                    )
            return arrays
        return pickle.loads(body)

    def _kill_child_locked(self) -> List[Tuple[_BabyWork, Exception]]:
        """Kills the child and collects pending works; the CALLER must
        complete them after releasing the lock (completion runs user
        callbacks, which may re-enter this pg)."""
        # Supersede the future-handler generation FIRST: the pipe EOF the
        # kill produces must read as intentional teardown, not latch a
        # phantom "child died" error after a clean shutdown/reconfigure.
        self._generation += 1
        if self._child is not None:
            self._child.kill()
            self._child.join(timeout=10.0)
            self._child = None
        for conn in (self._cmd_conn, self._res_conn):
            if conn is not None:
                try:
                    conn.close()
                except OSError:
                    pass
        self._cmd_conn = self._res_conn = None
        pending = list(self._pending.values())
        self._pending.clear()
        err = self._errored or RuntimeError("baby pg child killed")
        failed = []
        for work, _, shms in pending:
            _release_shms(shms)
            failed.append((work, err))
        return failed

    def abort(self) -> None:
        with self._lock:
            if self._errored is None:
                self._errored = RuntimeError("baby pg aborted")
            failed = self._kill_child_locked()
        for work, err in failed:
            work._complete(exc=err)

    def shutdown(self) -> None:
        # Politely ask the child to exit, serialized against in-flight
        # func sends (_send_lock, same order as _issue) — but with a
        # BOUNDED wait: a wedged child can leave _issue blocked mid-send
        # holding _send_lock forever, and shutdown must still reach the
        # kill below (the hang-wedge domain this class exists for).  If
        # the lock can't be had, skip the polite exit; the kill makes the
        # interleaving question moot.
        polite = self._send_lock.acquire(timeout=1.0)
        try:
            if polite:
                with self._lock:
                    if self._cmd_conn is not None:
                        try:
                            self._cmd_conn.send(("exit",))
                        except (OSError, BrokenPipeError):
                            pass
        finally:
            if polite:
                self._send_lock.release()
        with self._lock:
            if polite and self._child is not None:
                self._child.join(timeout=5.0)
            failed = self._kill_child_locked()
        for work, err in failed:
            work._complete(exc=err)

    def errored(self) -> Optional[Exception]:
        return self._errored

    def set_timeout(self, timeout: float) -> None:
        self._timeout = timeout
        # Forward to the live child so its op waits and socket deadlines
        # update immediately (not only after the next configure).
        # _send_lock serializes against _issue's func sends: Connection is
        # not thread-safe, and a near-64KiB inline payload is written in
        # multiple syscalls, so an unserialized send here could interleave
        # and corrupt the child's command stream.  Lock order matches
        # _issue: _send_lock, then _lock.
        with self._send_lock, self._lock:
            if self._cmd_conn is not None:
                try:
                    self._cmd_conn.send(("set_timeout", float(timeout)))
                except (OSError, BrokenPipeError, ValueError):
                    pass  # dead child: next configure applies it anyway

    def size(self) -> int:
        return self._world

    def rank(self) -> int:
        return self._rank

    def getBackendName(self) -> str:
        return "torchft-baby-socket"

    def num_active_work(self) -> int:
        """In-flight op count (reference: process_group.py:1790-1795)."""
        with self._lock:
            return len(self._pending)

    # -- test hooks --------------------------------------------------------

    def _inject_stall(self, seconds: float = 3600.0) -> None:
        """Makes the child sleep before its next op — a deterministic wedge
        for resiliency tests (the scenario this class exists to survive)."""
        # Same cmd-pipe serialization + lock order as set_timeout.
        with self._send_lock, self._lock:
            if self._cmd_conn is None:
                raise RuntimeError("not configured")
            self._cmd_conn.send(("stall", seconds))

    def child_pid(self) -> Optional[int]:
        with self._lock:
            return self._child.pid if self._child is not None else None

    # -- op issue ----------------------------------------------------------

    def _issue(self, name: str, arrays: List[np.ndarray], **kwargs) -> Work:
        with self._send_lock:
            with self._lock:
                if self._errored is not None:
                    return ErrorWork(self._errored)
                conn = self._cmd_conn
                if conn is None:
                    return ErrorWork(RuntimeError("baby pg not configured"))
                op_id = self._next_op
                self._next_op += 1
            # Staging (shm alloc + memcpy) and the pipe send happen OUTSIDE
            # self._lock: both can block, and abort() must stay reachable.
            shms: List[shared_memory.SharedMemory] = []
            try:
                meta = _encode_arrays(arrays, shms)
            except Exception as e:  # noqa: BLE001 - e.g. /dev/shm exhausted
                _release_shms(shms)
                return ErrorWork(e)
            work = _BabyWork(op_id)
            with self._lock:
                if self._errored is not None or self._cmd_conn is not conn:
                    _release_shms(shms)  # aborted/reconfigured meanwhile
                    return ErrorWork(
                        self._errored or RuntimeError("baby pg reconfigured")
                    )
                self._pending[op_id] = (work, arrays, shms)
            try:
                conn.send(("func", op_id, name, meta, kwargs))
            except (OSError, BrokenPipeError, ValueError) as e:
                with self._lock:
                    entry = self._pending.pop(op_id, None)
                    err = self._errored = self._errored or RuntimeError(
                        f"baby pg child pipe broken: {e}"
                    )
                if entry is not None:
                    _release_shms(shms)
                return ErrorWork(err)
            return work

    # -- collectives -------------------------------------------------------

    def allreduce(self, tensors: Any, op: ReduceOp = ReduceOp.SUM) -> Work:
        return self._issue("allreduce", _as_list(tensors), op=op.value)

    def allgather(self, tensors: Any) -> Work:
        return self._issue("allgather", _as_list(tensors))

    def broadcast(self, tensors: Any, root: int = 0) -> Work:
        return self._issue("broadcast", _as_list(tensors), root=root)

    def reduce_scatter(
        self, inputs: Sequence[Any], op: ReduceOp = ReduceOp.SUM
    ) -> Work:
        return self._issue("reduce_scatter", _as_list(inputs), op=op.value)

    def alltoall(self, inputs: Sequence[Any]) -> Work:
        return self._issue("alltoall", _as_list(inputs))

    def barrier(self) -> Work:
        return self._issue("barrier", [])

    def send(self, tensors: Any, dst: int, tag: str = "") -> Work:
        return self._issue("send", _as_list(tensors), dst=dst, tag=tag)

    def recv(self, src: int, tag: str = "", num_tensors: int = 1) -> Work:
        return self._issue(
            "recv", [], src=src, tag=tag, num_tensors=num_tensors
        )
