"""Parameter-path sharding rules for the Llama family.

The model stays mesh-agnostic; these rules map each parameter to a
PartitionSpec over the (dp, fsdp, sp, tp) mesh. The scan-stacked layer dim
(leading axis of every ``layers/*`` param) is unsharded — XLA scans over it.

Layout (standard HSDP+TP recipe, cf. the public scaling playbook):
- contraction-input dims shard over ``fsdp`` (all-gathered per layer),
- head/feature output dims shard over ``tp`` (ICI-adjacent),
- norms replicate; activations shard batch over (dp, fsdp) and sequence
  over ``sp``.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# name of innermost param container -> spec for the trailing dims
_RULES: Dict[Tuple[str, str], Tuple[Any, ...]] = {
    ("embed", "embedding"): ("tp", "fsdp"),
    ("wq", "kernel"): ("fsdp", "tp", None),
    ("wk", "kernel"): ("fsdp", "tp", None),
    ("wv", "kernel"): ("fsdp", "tp", None),
    ("wo", "kernel"): ("tp", None, "fsdp"),
    ("gate", "kernel"): ("fsdp", "tp"),
    ("up", "kernel"): ("fsdp", "tp"),
    ("down", "kernel"): ("tp", "fsdp"),
    ("lm_head", "kernel"): ("fsdp", "tp"),
    # MoE: experts shard over 'ep'; within an expert the FFN shards like
    # the dense MLP. The fp32 router's [H, E] kernel shards H over fsdp
    # (gathered with the rest of the layer) and keeps E whole.
    ("mlp", "experts_gate"): ("ep", "fsdp", "tp"),
    ("mlp", "experts_up"): ("ep", "fsdp", "tp"),
    ("mlp", "experts_down"): ("ep", "tp", "fsdp"),
    ("router", "kernel"): ("fsdp", None),
}


def _spec_for(path: Tuple[str, ...], ndim: int) -> P:
    key = tuple(path[-2:]) if len(path) >= 2 else tuple(path)
    rule = _RULES.get(key)  # type: ignore[arg-type]
    if rule is None:
        return P()  # norms / scalars: replicated
    pad = ndim - len(rule)
    return P(*((None,) * pad + tuple(rule)))


def _path_keys(path) -> Tuple[str, ...]:
    keys = []
    for entry in path:
        if hasattr(entry, "key"):
            keys.append(str(entry.key))
        elif hasattr(entry, "idx"):
            keys.append(str(entry.idx))
        else:
            keys.append(str(entry))
    return tuple(keys)


def param_specs(params: Any) -> Any:
    """Pytree of PartitionSpec matching ``params`` (works on real arrays or
    ShapeDtypeStructs)."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: _spec_for(_path_keys(path), leaf.ndim), params
    )


def param_shardings(params: Any, mesh: Mesh) -> Any:
    return jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec), param_specs(params)
    )


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """[B, S]-shaped token batches: batch over (dp, fsdp), seq over sp."""
    return NamedSharding(mesh, P(("dp", "fsdp"), "sp"))


def tree_specs_like(tree: Any, params_spec_by_path: Dict[Tuple[str, ...], P]) -> Any:
    """Specs for an arbitrary pytree (e.g. optax state) whose leaves mirror
    parameter subtrees: a leaf whose path *ends with* a known param path gets
    that param's spec; everything else (counts, scalars) replicates."""

    def lookup(path, leaf):
        keys = _path_keys(path)
        for start in range(len(keys)):
            suffix = keys[start:]
            if suffix in params_spec_by_path:
                return params_spec_by_path[suffix]
        return P()

    return jax.tree_util.tree_map_with_path(lookup, tree)


def params_spec_dict(params: Any) -> Dict[Tuple[str, ...], P]:
    out: Dict[Tuple[str, ...], P] = {}

    def record(path, leaf):
        out[_path_keys(path)] = _spec_for(_path_keys(path), leaf.ndim)
        return leaf

    jax.tree_util.tree_map_with_path(record, params)
    return out
