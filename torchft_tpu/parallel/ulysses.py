"""Ulysses-style all-to-all sequence (context) parallelism.

The second context-parallel mode next to ring attention
(``parallel/ring_attention.py``): instead of streaming k/v blocks around
the ICI ring, two ``all_to_all`` collectives re-shard the activations from
sequence-sharded to head-sharded and back:

    [B, S/sp, H, D]  --all_to_all-->  [B, S, H/sp, D]
        (attention over the FULL sequence, H/sp heads per chip)
    [B, S, H/sp, D]  --all_to_all-->  [B, S/sp, H, D]

Each chip then runs ordinary (flash) attention over the full sequence for
its head subset — no per-block online-softmax folding, and the Pallas
flash kernel applies unmodified. Communication volume is 2 all_to_alls of
the qkv/out activations, independent of the number of ring steps, which
wins over the ring when heads are plentiful and sequence shards are small;
the ring wins when H/sp < 1 would be needed or activations dominate.

The reference has no context parallelism at all (SURVEY.md §2.3: CP
delegated to the consuming trainer); both modes here are TPU-first
designs over a mesh axis.

GQA note: k/v heads are repeated up to the smallest multiple that (a)
divides evenly over the ``sp`` axis and (b) divides the q-head count, so
grouped-query models work at any (heads, kv_heads, sp) combination at the
cost of the minimal kv duplication.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from torchft_tpu.parallel.ring_attention import shard_map


def _kv_expand_factor(h_q: int, h_kv: int, sp: int) -> int:
    """Smallest r such that sp divides h_kv*r and h_kv*r divides h_q
    (falls back to full MHA expansion r = h_q/h_kv)."""
    for r in range(1, h_q // h_kv + 1):
        hk = h_kv * r
        if h_q % hk == 0 and hk % sp == 0:
            return r
    return h_q // h_kv


def ulysses_attention_shard(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "sp",
    causal: bool = True,
    use_flash: Optional[bool] = None,
) -> jax.Array:
    """Per-shard Ulysses body (inside shard_map): q/k/v are the LOCAL
    sequence shards [b, S/sp, h, D]; returns the local output shard."""
    from torchft_tpu.models.llama import dense_attention
    from torchft_tpu.ops.flash_attention import flash_attention, supports

    # jax.lax.psum(1, axis) is the portable axis-size spelling (same idiom
    # as ring_attention.py); jax.lax.axis_size is not present in all
    # supported jax versions.
    sp = int(jax.lax.psum(1, axis_name))
    if sp == 1:
        # Degenerate axis: same auto-flash heuristic as the sp>1 branch,
        # so an sp=1 mesh doesn't silently materialize S^2 dense scores.
        flash1 = use_flash
        if flash1 is None:
            flash1 = causal and q.shape[1] >= 1024 and supports(q.shape[1])
        if flash1 and supports(q.shape[1]):
            return flash_attention(q, k, v, causal=causal)
        return dense_attention(q, k, v, causal=causal)

    h_q, h_kv = q.shape[2], k.shape[2]
    assert h_q % sp == 0, (
        f"Ulysses needs heads ({h_q}) divisible by the {axis_name} axis "
        f"({sp}); use ring attention otherwise"
    )
    r = _kv_expand_factor(h_q, h_kv, sp)
    if r > 1:
        k = jnp.repeat(k, r, axis=2)
        v = jnp.repeat(v, r, axis=2)

    # seq-sharded -> head-sharded: split heads (axis 2), gather seq (axis 1).
    a2a = partial(
        jax.lax.all_to_all,
        axis_name=axis_name,
        split_axis=2,
        concat_axis=1,
        tiled=True,
    )
    qg, kg, vg = a2a(q), a2a(k), a2a(v)

    s_full = qg.shape[1]
    flash = use_flash
    if flash is None:
        flash = causal and s_full >= 1024
    # Same guard as the sp==1 branch: an explicit use_flash=True on an
    # unsupported full-sequence length (e.g. not block-aligned) falls
    # back to dense instead of failing inside the kernel.
    if flash and supports(s_full):
        out = flash_attention(qg, kg, vg, causal=causal)
    else:
        out = dense_attention(qg, kg, vg, causal=causal)

    # head-sharded -> seq-sharded: split seq (axis 1), gather heads (axis 2).
    return jax.lax.all_to_all(
        out.astype(q.dtype),
        axis_name=axis_name,
        split_axis=1,
        concat_axis=2,
        tiled=True,
    )


def make_ulysses_attention(
    mesh: Mesh,
    *,
    batch_axes: Tuple[str, ...] = ("dp", "fsdp"),
    seq_axis: str = "sp",
    head_axis: Optional[str] = "tp",
    causal: bool = True,
    use_flash: Optional[bool] = None,
):
    """Returns attn_fn(q, k, v) usable inside a pjit'd program — the
    all-to-all counterpart of :func:`make_ring_attention`, same sharding
    contract: [B, S, H, Dh] with batch over ``batch_axes``, sequence over
    ``seq_axis``, heads over ``head_axis``."""
    spec = P(batch_axes, seq_axis, head_axis, None)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    def attn_fn(q, k, v):
        return ulysses_attention_shard(
            q, k, v, axis_name=seq_axis, causal=causal, use_flash=use_flash
        )

    return attn_fn
