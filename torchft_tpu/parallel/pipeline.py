"""Pipeline parallelism over a ``pp`` mesh axis (GPipe schedule).

The reference has NO pipeline engine — it only uses torch's pipelining
helper to *split* a model into DiLoCo fragments (reference:
train_diloco.py:162-165, SURVEY.md §2.3); actual PP is delegated to the
consuming trainer. This module exceeds that with a real TPU-native
schedule, designed the SPMD way rather than as a runtime of stage workers:

- the scan-stacked layer dim of the Transformer's params (leading
  ``[num_layers]`` axis, models/llama.py nn.scan) is sharded over ``pp``,
  so each stage device holds ``num_layers / pp`` layers — no parameter
  tree surgery, and FSDP-style rules still apply to the trailing dims;
- the schedule itself is a ``lax.scan`` over ticks inside ``shard_map``:
  each tick every stage applies its layer slice to its current microbatch
  and ``ppermute``\\ s the activation to the next stage. Reverse-mode AD
  through the loop IS pipeline backward (the transpose of ppermute is the
  reverse rotation), so one ``jax.grad`` gives the full bwd schedule with
  the same bubble;
- bubble fraction = (pp - 1) / (n_micro + pp - 1); activations of all
  in-flight ticks are the GPipe memory profile, reduced per-layer with
  ``jax.checkpoint`` when ``cfg.remat`` is set.

Stage-0 embedding and last-stage head/loss run on every pp rank (their
inputs are replicated; only the owning rank's result is consumed) — that
redundancy costs a few percent of FLOPs and keeps every collective a
static-shape ppermute XLA can schedule on ICI.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
# version-compat wrapper (check_rep/check_vma) shared with ring attention
from torchft_tpu.parallel.ring_attention import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torchft_tpu.models.llama import (
    Block,
    LlamaConfig,
    RMSNorm,
    Transformer,
    rope_table,
)
from torchft_tpu.parallel.sharding import _path_keys, tree_specs_like
from torchft_tpu.parallel.train import TrainState, default_optimizer


def gpipe_loop(
    stage_fn: Callable[[jax.Array], jax.Array],
    x_all: jax.Array,
    axis: str = "pp",
) -> jax.Array:
    """The per-device GPipe tick loop; call INSIDE shard_map.

    ``x_all``: [n_micro, mb, ...] stage-0 inputs (replicated across the
    axis; only rank 0 consumes them). ``stage_fn`` must be shape-preserving
    (a homogeneous trunk). Returns [n_micro, mb, ...] outputs — valid on
    the LAST stage only; other ranks hold zeros/garbage.
    """
    n_micro = x_all.shape[0]
    n_stages = jax.lax.psum(1, axis)
    stage = jax.lax.axis_index(axis)
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(carry, t):
        x_recv, out = carry
        feed = jax.lax.dynamic_index_in_dim(
            x_all, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False
        )
        x_in = jnp.where(stage == 0, feed, x_recv)
        y = stage_fn(x_in)
        slot = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
        write = (stage == n_stages - 1) & (t >= n_stages - 1)
        cur = jax.lax.dynamic_index_in_dim(out, slot, 0, keepdims=False)
        out = jax.lax.dynamic_update_index_in_dim(
            out, jnp.where(write, y, cur), slot, 0
        )
        x_send = jax.lax.ppermute(y, axis, perm)
        return (x_send, out), None

    init = (jnp.zeros_like(x_all[0]), jnp.zeros_like(x_all))
    (_, out), _ = jax.lax.scan(
        tick, init, jnp.arange(n_micro + n_stages - 1)
    )
    return out


def pipeline_param_specs(params: Any) -> Any:
    """P('pp') on the stacked layer dim; everything else replicated (the
    pipeline composes with dp on the batch, not with fsdp/tp, in this v1)."""

    def spec(path, leaf):
        keys = _path_keys(path)
        if "layers" in keys:
            return P(*(("pp",) + (None,) * (leaf.ndim - 1)))
        return P()

    return jax.tree_util.tree_map_with_path(spec, params)


def _check_cfg(cfg: LlamaConfig, n_stages: int) -> None:
    if cfg.num_layers % n_stages != 0:
        raise ValueError(
            f"num_layers {cfg.num_layers} not divisible by pp={n_stages}"
        )
    if cfg.tie_embeddings:
        raise ValueError("pipeline: tie_embeddings unsupported (head lives "
                         "on the last stage, embed on the first)")
    if cfg.num_experts > 0:
        raise ValueError("pipeline: MoE aux-loss sow is not plumbed "
                         "through shard_map; use the ep axis instead")
    if cfg.attn_impl in ("ring", "ulysses"):
        raise ValueError("pipeline: compose with sp later; use dense/flash")


def make_pipeline_loss(
    cfg: LlamaConfig, mesh: Mesh, n_micro: int
) -> Callable[[Any, Any], jax.Array]:
    """Returns loss(params, batch) where the layer stack is pipelined over
    mesh axis 'pp' and the batch is sharded over 'dp'. ``params`` is the
    standard Transformer param tree (layers stacked [num_layers, ...])."""
    n_stages = mesh.shape["pp"]
    _check_cfg(cfg, n_stages)
    block = Block(cfg)
    norm = RMSNorm(cfg.norm_eps, cfg.param_dtype)

    def device_fn(params, inputs, targets, mask):
        # params["layers"]: local [num_layers/pp, ...] slice.
        layers_local = params["layers"]
        B_loc, S = inputs.shape
        if B_loc % n_micro != 0:
            raise ValueError(
                f"local batch {B_loc} not divisible by n_micro {n_micro}"
            )
        mb = B_loc // n_micro

        embed_tab = params["embed"]["embedding"]  # [V, H] param_dtype
        x = jnp.take(embed_tab, inputs, axis=0).astype(cfg.dtype)
        x_all = x.reshape(n_micro, mb, S, cfg.hidden_size)
        positions = jnp.broadcast_to(jnp.arange(S), (mb, S))
        cos, sin = rope_table(
            positions, cfg.head_dim, cfg.rope_theta, cfg.dtype
        )

        def layer_step(h, layer_p):
            return block.apply({"params": layer_p}, h, cos, sin), None

        if cfg.remat:
            layer_step = jax.checkpoint(layer_step, prevent_cse=False)

        def stage_fn(h):
            out, _ = jax.lax.scan(layer_step, h, layers_local)
            return out

        h_all = gpipe_loop(stage_fn, x_all, axis="pp")  # last stage only

        # Head + loss on every rank; only the last stage's input is real.
        h = norm.apply(
            {"params": params["final_norm"]},
            h_all.reshape(B_loc, S, cfg.hidden_size),
        )
        w = params["lm_head"]["kernel"].astype(cfg.dtype)
        logits = jnp.dot(
            h.astype(cfg.dtype), w, preferred_element_type=jnp.float32
        )
        losses = optax.softmax_cross_entropy_with_integer_labels(
            logits, targets
        )
        mask_f = mask.astype(jnp.float32)
        stage = jax.lax.axis_index("pp")
        is_last = (stage == n_stages - 1).astype(jnp.float32)
        loss_sum = jax.lax.psum(
            jax.lax.psum((losses * mask_f).sum() * is_last, "pp"), "dp"
        )
        denom = jnp.maximum(jax.lax.psum(mask_f.sum(), "dp"), 1.0)
        return loss_sum / denom

    sharded = shard_map(
        device_fn,
        mesh=mesh,
        in_specs=(
            pipeline_param_specs_struct(cfg),
            P("dp", None),
            P("dp", None),
            P("dp", None),
        ),
        out_specs=P(),
    )

    def loss_fn(params, batch):
        return sharded(
            params, batch["inputs"], batch["targets"], batch["mask"]
        )

    return loss_fn


def pipeline_param_specs_struct(cfg: LlamaConfig) -> Any:
    """Spec pytree for the Transformer param structure (via eval_shape, so
    no FLOPs)."""
    model = Transformer(cfg)
    tokens = jnp.zeros((1, 8), jnp.int32)
    shape = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), tokens)["params"]
    )
    return pipeline_param_specs(shape)


def init_pipeline_state(
    cfg: LlamaConfig,
    mesh: Mesh,
    rng: jax.Array,
    sample_tokens_shape: Tuple[int, int],
    optimizer: Optional[optax.GradientTransformation] = None,
) -> Tuple[TrainState, TrainState]:
    """Born-sharded init: layers sharded over 'pp', rest replicated.
    Returns (state, shardings)."""
    optimizer = optimizer or default_optimizer()
    _check_cfg(cfg, mesh.shape["pp"])
    model = Transformer(cfg)

    def init_fn(rng):
        tokens = jnp.zeros(sample_tokens_shape, jnp.int32)
        params = model.init(rng, tokens)["params"]
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=optimizer.init(params),
        )

    shape = jax.eval_shape(init_fn, rng)
    p_specs = pipeline_param_specs(shape.params)
    # Path->spec dict so optimizer-state leaves (mu/nu mirror the params)
    # inherit their param's spec.
    spec_dict = {}

    def record(path, spec):
        spec_dict[_path_keys(path)] = spec

    jax.tree_util.tree_map_with_path(
        record, p_specs, is_leaf=lambda x: isinstance(x, P)
    )
    opt_specs = tree_specs_like(shape.opt_state, spec_dict)
    to_sh = lambda s: NamedSharding(mesh, s)  # noqa: E731
    shardings = TrainState(
        step=to_sh(P()),
        params=jax.tree_util.tree_map(to_sh, p_specs),
        opt_state=jax.tree_util.tree_map(
            to_sh, opt_specs, is_leaf=lambda x: isinstance(x, P)
        ),
    )
    state = jax.jit(init_fn, out_shardings=shardings)(rng)
    return state, shardings


def make_pipeline_train_step(
    cfg: LlamaConfig,
    mesh: Mesh,
    shardings: TrainState,
    n_micro: int,
    optimizer: Optional[optax.GradientTransformation] = None,
):
    """Jitted (state, batch) -> (state, metrics) with the trunk pipelined
    over 'pp' and batch data-parallel over 'dp'."""
    optimizer = optimizer or default_optimizer()
    loss_fn = make_pipeline_loss(cfg, mesh, n_micro)
    batch_sh = NamedSharding(mesh, P("dp", None))

    def step_fn(state: TrainState, batch):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params
        )
        params = optax.apply_updates(state.params, updates)
        return (
            TrainState(
                step=state.step + 1, params=params, opt_state=opt_state
            ),
            {"loss": loss, "grad_norm": optax.global_norm(grads)},
        )

    return jax.jit(
        step_fn,
        in_shardings=(
            shardings,
            {"inputs": batch_sh, "targets": batch_sh, "mask": batch_sh},
        ),
        out_shardings=(shardings, None),
        donate_argnums=(0,),
    )
