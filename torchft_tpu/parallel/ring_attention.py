"""Ring attention: exact causal attention with the sequence sharded over a
mesh axis (context parallelism for long sequences).

Each chip holds one query block and streams every key/value block past it on
the ICI ring via ``ppermute``, folding each block into a numerically-stable
online softmax (flash-attention accumulation in fp32). Communication
overlaps compute — XLA schedules the ppermute DMA of block i+1 against the
matmuls of block i.

The reference has no long-context code (SURVEY.md §2.3: CP/ring absent —
delegated to torchtitan); here it is first-class because the TPU design
treats sequence as just another mesh axis.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax>=0.4.35 moved shard_map to the public namespace
    from jax import shard_map as _shard_map  # type: ignore[attr-defined]
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map


def shard_map(f, *, mesh, in_specs, out_specs):
    # The replication-check kwarg was renamed check_rep -> check_vma across
    # jax versions; we need it off (ppermute inside fori_loop).
    try:
        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
        )
    except TypeError:
        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
        )


def _flash_fold_supported(sq: int, skv: int) -> bool:
    from torchft_tpu.ops.flash_attention import supports

    # The pallas fold needs block-divisible shard lengths; tiny shards
    # (tests, debug models) stay on the fused-XLA dense fold.
    return sq >= 256 and skv >= 256 and supports(sq) and supports(skv)


def ring_attention_shard_flash(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "sp",
) -> jax.Array:
    """Pallas-accelerated per-shard ring body: each streamed k/v block is
    folded with :func:`ops.flash_attention.flash_attention_block` (on-chip
    blocked attention at GLOBAL positions) and merged via the online-softmax
    combine. Same semantics as :func:`ring_attention_shard` with
    ``causal=True``; preferred for production shard sizes (the dense fold
    materializes [B,H,Sq,Skv] fp32 scores per step)."""
    from torchft_tpu.ops.flash_attention import flash_attention_block

    axis_size = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, sq, hq, dh = q.shape
    skv = k.shape[1]
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
    q_off = idx * sq

    out0 = jnp.zeros((b, sq, hq, dh), jnp.float32)
    lse0 = jnp.full((b, hq, sq), -jnp.inf, jnp.float32)

    def fold(i, k_blk, v_blk, out, lse):
        src = (idx - i) % axis_size
        o_blk, lse_blk = flash_attention_block(
            q, k_blk, v_blk, q_off, src * skv
        )
        new_lse = jnp.logaddexp(lse, lse_blk)
        safe = jnp.where(jnp.isfinite(new_lse), new_lse, 0.0)
        w_old = jnp.where(jnp.isfinite(lse), jnp.exp(lse - safe), 0.0)
        w_new = jnp.where(jnp.isfinite(lse_blk), jnp.exp(lse_blk - safe), 0.0)
        wt = lambda w: jnp.swapaxes(w, 1, 2)[..., None]  # noqa: E731
        out = out * wt(w_old) + o_blk.astype(jnp.float32) * wt(w_new)
        return out, new_lse

    def body(i, carry):
        k_blk, v_blk, out, lse = carry
        out, lse = fold(i, k_blk, v_blk, out, lse)
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return k_blk, v_blk, out, lse

    k_blk, v_blk, out, lse = jax.lax.fori_loop(
        0, axis_size - 1, body, (k, v, out0, lse0)
    )
    out, _ = fold(axis_size - 1, k_blk, v_blk, out, lse)
    return out.astype(q.dtype)


def ring_attention_shard(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "sp",
    causal: bool = True,
) -> jax.Array:
    """Per-shard body (run under shard_map). q: [B, Sq, Hq, Dh] local block;
    k/v: [B, Skv, Hkv, Dh] local block. Returns [B, Sq, Hq, Dh]."""
    axis_size = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, sq, hq, dh = q.shape
    skv, hkv = k.shape[1], k.shape[2]
    g = hq // hkv
    scale = dh**-0.5
    qg = q.reshape(b, sq, hkv, g, dh).astype(jnp.float32) * scale
    q_pos = idx * sq + jnp.arange(sq)

    m0 = jnp.full((b, hkv, g, sq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, hkv, g, sq), jnp.float32)
    acc0 = jnp.zeros((b, hkv, g, sq, dh), jnp.float32)
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    def fold(i, k_blk, v_blk, m, l, acc):
        # After i forward rotations this chip holds the block that started
        # on chip (idx - i) mod axis_size.
        src = (idx - i) % axis_size
        scores = jnp.einsum(
            "bqkgd,bskd->bkgqs", qg, k_blk.astype(jnp.float32)
        )
        if causal:
            k_pos = src * skv + jnp.arange(skv)
            mask = q_pos[:, None] >= k_pos[None, :]
            scores = jnp.where(mask[None, None, None], scores, -jnp.inf)
        blk_max = jnp.max(scores, axis=-1)
        new_m = jnp.maximum(m, blk_max)
        safe_m = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
        correction = jnp.where(
            jnp.isfinite(m), jnp.exp(m - safe_m), 0.0
        )
        probs = jnp.exp(scores - safe_m[..., None])  # masked -> exp(-inf)=0
        l = l * correction + probs.sum(axis=-1)
        acc = acc * correction[..., None] + jnp.einsum(
            "bkgqs,bskd->bkgqd", probs, v_blk.astype(jnp.float32)
        )
        return new_m, l, acc

    def body(i, carry):
        k_blk, v_blk, m, l, acc = carry
        m, l, acc = fold(i, k_blk, v_blk, m, l, acc)
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return k_blk, v_blk, m, l, acc

    # Rotate only axis_size-1 times; the last block folds outside the loop
    # so its ppermute (whose result would be discarded) is never issued.
    k_blk, v_blk, m, l, acc = jax.lax.fori_loop(
        0, axis_size - 1, body, (k, v, m0, l0, acc0)
    )
    _, l, acc = fold(axis_size - 1, k_blk, v_blk, m, l, acc)
    out = acc / jnp.where(l == 0.0, 1.0, l)[..., None]
    out = out.transpose(0, 3, 1, 2, 4).reshape(b, sq, hq, dh)
    return out.astype(q.dtype)


def make_ring_attention(
    mesh: Mesh,
    *,
    batch_axes: Tuple[str, ...] = ("dp", "fsdp"),
    seq_axis: str = "sp",
    head_axis: Optional[str] = "tp",
    causal: bool = True,
    use_flash: Optional[bool] = None,
):
    """Returns attn_fn(q, k, v) usable inside a pjit'd program: shards
    [B, S, H, Dh] with batch over ``batch_axes``, sequence over ``seq_axis``,
    heads over ``head_axis``, and runs the ring per shard.

    ``use_flash``: fold each streamed block with the Pallas kernel
    (ops/flash_attention.py) instead of the dense einsum. Default (None)
    auto-selects it for causal rings with production-sized shards."""
    spec = P(batch_axes, seq_axis, head_axis, None)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    def attn_fn(q, k, v):
        sq, skv = q.shape[1], k.shape[1]
        flash = use_flash
        if flash is None:
            flash = causal and _flash_fold_supported(sq, skv)
        if flash:
            assert causal, "flash ring fold is causal-only"
            return ring_attention_shard_flash(q, k, v, axis_name=seq_axis)
        return ring_attention_shard(q, k, v, axis_name=seq_axis, causal=causal)

    return attn_fn
