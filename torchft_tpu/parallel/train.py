"""Sharded training step: init, loss, grads, optimizer update — all compiled
as one pjit program over the (dp, fsdp, sp, tp) mesh.

This is the inner (per-replica-group) step of the fault-tolerant trainer:
everything here rides ICI via XLA collectives; the outer replica-axis
gradient/pseudograd averaging is host-driven by the Manager (DDP: per-step;
DiLoCo: per-outer-step). Reference analog: the torchtitan train step the
reference composes with (SURVEY.md §2.3) — here it is in-repo.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torchft_tpu.models.llama import LlamaConfig, Transformer
from torchft_tpu.parallel.ring_attention import make_ring_attention
from torchft_tpu.parallel.sharding import (
    batch_sharding,
    param_specs,
    params_spec_dict,
    tree_specs_like,
)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    step: jax.Array
    params: Any
    opt_state: Any


def build_model(cfg: LlamaConfig, mesh: Optional[Mesh]) -> Transformer:
    """Binds the mesh-bound context-parallel attention when requested:
    ``ring`` (ppermute k/v streaming) or ``ulysses`` (all-to-all
    seq<->head re-shard; parallel/ulysses.py)."""
    if cfg.attn_impl == "ring":
        assert mesh is not None, "ring attention requires a mesh"
        cfg = dataclasses.replace(cfg, attn_fn=make_ring_attention(mesh))
    elif cfg.attn_impl == "ulysses":
        from torchft_tpu.parallel.ulysses import make_ulysses_attention

        assert mesh is not None, "ulysses attention requires a mesh"
        cfg = dataclasses.replace(cfg, attn_fn=make_ulysses_attention(mesh))
    return Transformer(cfg)


def state_shardings(
    model: Transformer,
    mesh: Mesh,
    sample_tokens_shape: Tuple[int, int],
    optimizer: Optional[optax.GradientTransformation] = None,
) -> TrainState:
    """TrainState-of-NamedShardings, derived from abstract init (no FLOPs)."""
    optimizer = optimizer or _DEFAULT_OPT

    def abstract_init():
        tokens = jnp.zeros(sample_tokens_shape, jnp.int32)
        params = model.init(jax.random.PRNGKey(0), tokens)["params"]
        return params

    params_shape = jax.eval_shape(abstract_init)
    specs = param_specs(params_shape)
    spec_dict = params_spec_dict(params_shape)
    opt_shape = jax.eval_shape(lambda p: optimizer.init(p), params_shape)
    opt_specs = tree_specs_like(opt_shape, spec_dict)
    to_sharding = lambda s: NamedSharding(mesh, s)  # noqa: E731
    return TrainState(
        step=to_sharding(P()),
        params=jax.tree_util.tree_map(to_sharding, specs),
        opt_state=jax.tree_util.tree_map(
            to_sharding, opt_specs, is_leaf=lambda x: isinstance(x, P)
        ),
    )


_DEFAULT_OPT = optax.adamw(3e-4, b1=0.9, b2=0.95, weight_decay=0.1)


def default_optimizer() -> optax.GradientTransformation:
    """The optimizer init_train_state uses when none is given; callers that
    later apply updates to that opt_state must use this same transform."""
    return _DEFAULT_OPT


def init_train_state(
    model: Transformer,
    mesh: Mesh,
    rng: jax.Array,
    sample_tokens_shape: Tuple[int, int],
    optimizer: Optional[optax.GradientTransformation] = None,
) -> Tuple[TrainState, TrainState]:
    """Initializes the state *born sharded* (out_shardings on init — no
    host-side full copy, required at 8B scale). Returns (state, shardings)."""
    optimizer = optimizer or _DEFAULT_OPT
    shardings = state_shardings(model, mesh, sample_tokens_shape, optimizer)

    def init_fn(rng):
        tokens = jnp.zeros(sample_tokens_shape, jnp.int32)
        params = model.init(rng, tokens)["params"]
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=optimizer.init(params),
        )

    state = jax.jit(init_fn, out_shardings=shardings)(rng)
    return state, shardings


# Tokens per chunked-loss slice. The [B,S,V] fp32 logits of a 32k-vocab
# model at B=8,S=1024 are >1 GB and their log_softmax + backward dlogits
# multiply that — the dominant HBM transient of the whole step. Chunking
# bounds it at [B,_LOSS_CHUNK,V] (~130 MB) with jax.checkpoint recompute.
# Env-tunable (TORCHFT_LOSS_CHUNK) so the on-chip MFU sweep can A/B chunk
# sizes without code edits — larger chunks = fewer scan iterations and
# bigger head matmuls at proportionally more transient HBM.
from torchft_tpu import knobs as _knobs

_LOSS_CHUNK = _knobs.get_int("TORCHFT_LOSS_CHUNK")


def _lm_head_projection(model: Transformer, params):
    """The vocab projection [H, V] straight from the param pytree — same
    tensors as the model's own head. Both head forms compute in cfg.dtype:
    flax's Dense casts input+kernel to ``dtype``, and Embed.attend promotes
    query AND embedding to ``dtype`` too (so the model's
    ``attend(x.astype(param_dtype))`` still multiplies in cfg.dtype)."""
    cfg = model.cfg
    if cfg.tie_embeddings:
        return params["embed"]["embedding"].T, cfg.dtype
    return params["lm_head"]["kernel"], cfg.dtype


def _apply_with_aux(model: Transformer, params, inputs, **kw):
    """model.apply + the MoE router load-balancing aux term (mean of the
    per-layer Switch aux values MoEMLP sows; 0.0 for dense models)."""
    if model.cfg.num_experts <= 0:
        return model.apply({"params": params}, inputs, **kw), jnp.zeros(())
    out, inter = model.apply(
        {"params": params}, inputs, mutable=["intermediates"], **kw
    )
    vals = [
        jnp.ravel(leaf)
        for leaf in jax.tree_util.tree_leaves(inter)
    ]
    aux = (
        jnp.concatenate(vals).mean() if vals else jnp.zeros(())
    )
    return out, aux


def _loss_fn(model: Transformer, params, inputs, targets, mask):
    B, S = inputs.shape
    C = min(_LOSS_CHUNK, S)
    mask_f = mask.astype(jnp.float32)
    denom = jnp.maximum(mask_f.sum(), 1.0)
    aux_coef = getattr(model.cfg, "router_aux_coef", 0.0)
    if S % C != 0:  # odd seq len: the plain full-logits path
        logits, aux = _apply_with_aux(model, params, inputs)
        losses = optax.softmax_cross_entropy_with_integer_labels(
            logits, targets
        )
        return (losses * mask_f).sum() / denom + aux_coef * aux

    h, aux = _apply_with_aux(model, params, inputs, return_hidden=True)
    w, head_dtype = _lm_head_projection(model, params)
    w = w.astype(head_dtype)
    n = S // C
    h_r = jnp.moveaxis(h.reshape(B, n, C, h.shape[-1]), 1, 0)  # [n,B,C,H]
    t_r = jnp.moveaxis(targets.reshape(B, n, C), 1, 0)
    m_r = jnp.moveaxis(mask_f.reshape(B, n, C), 1, 0)

    # A hand-written VJP for this scan (saved-lse + bf16 dlogits) is 2x
    # faster in isolation but 8% slower composed into the full step (XLA
    # overlaps this checkpointed scan's backward with the trunk backward;
    # a custom_vjp boundary defeats that) — measured on v5e, B=8 S=1024.
    def chunk(acc, xs):
        hc, tc, mc = xs
        logits = jnp.dot(
            hc.astype(head_dtype), w, preferred_element_type=jnp.float32
        )  # [B,C,V] fp32, exists only inside this chunk
        losses = optax.softmax_cross_entropy_with_integer_labels(logits, tc)
        return acc + (losses * mc).sum(), None

    total, _ = jax.lax.scan(
        jax.checkpoint(chunk), jnp.zeros((), jnp.float32), (h_r, t_r, m_r)
    )
    return total / denom + aux_coef * aux


def make_train_step(
    model: Transformer,
    mesh: Mesh,
    shardings: TrainState,
    optimizer: Optional[optax.GradientTransformation] = None,
    donate: bool = True,
    accum_steps: int = 1,
) -> Callable[[TrainState, Any], Tuple[TrainState, Any]]:
    """batch = {"inputs": [B,S] i32, "targets": [B,S] i32, "mask": [B,S]}.
    Returns jitted (state, batch) -> (state, metrics).

    ``accum_steps > 1`` runs gradient accumulation: the global batch is
    split into ``accum_steps`` microbatches along the batch dim and
    swept with ``lax.scan`` (ONE compiled microstep body — compile time
    and activation HBM stay those of a microbatch, which is how a large
    global batch fits a chip), accumulating fp32 gradients and applying
    the optimizer once.  Per-microbatch losses are normalized by their
    own mask counts and averaged, so with equal token counts per
    microbatch the result matches the unaccumulated step exactly (the
    usual data-parallel convention).  Requires B % accum_steps == 0.
    """
    optimizer = optimizer or _DEFAULT_OPT
    bsh = batch_sharding(mesh)
    batch_sh = {"inputs": bsh, "targets": bsh, "mask": bsh}

    def grads_and_loss(params, batch):
        inputs = jax.lax.with_sharding_constraint(batch["inputs"], bsh)
        loss, grads = jax.value_and_grad(
            lambda p: _loss_fn(
                model, p, inputs, batch["targets"], batch["mask"]
            )
        )(params)
        return loss, grads

    def step_fn(state: TrainState, batch) -> Tuple[TrainState, Any]:
        if accum_steps <= 1:
            loss, grads = grads_and_loss(state.params, batch)
        else:
            B = batch["inputs"].shape[0]
            if B % accum_steps != 0:
                raise ValueError(
                    f"batch size {B} not divisible by "
                    f"accum_steps={accum_steps}"
                )
            # INTERLEAVED split (microbatch k = rows k::accum_steps):
            # under the contiguous (dp, fsdp) row sharding every shard
            # contributes the same fraction of each microbatch and the
            # rows land exactly where the microbatch sharding wants them
            # — a contiguous block split would leave each microbatch on
            # 1/accum_steps of the shards and force a cross-device
            # redistribution every scan iteration.
            micro = {
                k: jnp.moveaxis(
                    v.reshape(
                        B // accum_steps, accum_steps, *v.shape[1:]
                    ),
                    1,
                    0,
                )
                for k, v in batch.items()
            }
            g0 = jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), state.params
            )

            def body(carry, mb):
                acc_g, acc_loss = carry
                loss, grads = grads_and_loss(state.params, mb)
                acc_g = jax.tree_util.tree_map(
                    lambda a, g: a + g.astype(jnp.float32), acc_g, grads
                )
                return (acc_g, acc_loss + loss), None

            (gsum, loss_sum), _ = jax.lax.scan(
                body, (g0, jnp.zeros((), jnp.float32)), micro
            )
            inv = 1.0 / accum_steps
            grads = jax.tree_util.tree_map(lambda g: g * inv, gsum)
            loss = loss_sum * inv
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params
        )
        params = optax.apply_updates(state.params, updates)
        gnorm = optax.global_norm(grads)
        new_state = TrainState(
            step=state.step + 1, params=params, opt_state=opt_state
        )
        return new_state, {"loss": loss, "grad_norm": gnorm}

    return jax.jit(
        step_fn,
        in_shardings=(shardings, batch_sh),
        out_shardings=(shardings, None),
        donate_argnums=(0,) if donate else (),
    )


def make_grad_step(
    model: Transformer,
    mesh: Mesh,
    shardings: TrainState,
) -> Callable[[Any, Any], Tuple[jax.Array, Any]]:
    """(params, batch) -> (loss, grads): the DDP variant where the optimizer
    update is applied *after* the Manager's outer-axis gradient allreduce."""
    bsh = batch_sharding(mesh)
    batch_sh = {"inputs": bsh, "targets": bsh, "mask": bsh}

    def fn(params, batch):
        return jax.value_and_grad(
            lambda p: _loss_fn(
                model, p, batch["inputs"], batch["targets"], batch["mask"]
            )
        )(params)

    return jax.jit(
        fn,
        in_shardings=(shardings.params, batch_sh),
        out_shardings=(None, shardings.params),
    )


def make_eval_step(model: Transformer, mesh: Mesh, shardings: TrainState):
    bsh = batch_sharding(mesh)
    batch_sh = {"inputs": bsh, "targets": bsh, "mask": bsh}

    def fn(params, batch):
        return _loss_fn(
            model, params, batch["inputs"], batch["targets"], batch["mask"]
        )

    return jax.jit(fn, in_shardings=(shardings.params, batch_sh))
