"""Parallelism layer: device meshes, sharding rules, ring attention, and the
sharded train step.

TPU-first equivalent of the reference's composition story (SURVEY.md §2.3):
inner axes (data/FSDP/TP/sequence) are native ``jax.sharding.Mesh`` axes —
XLA inserts the ICI collectives; the fault-tolerant *replica* axis stays
outside the compiled program and is carried by the Manager over DCN
(reference: torchft/device_mesh.py:50-336 splices a ManagedProcessGroup into
a torch DeviceMesh; here the managed axis wraps the jax mesh instead).
"""

from torchft_tpu.parallel.mesh import (  # noqa: F401
    MESH_AXES,
    auto_mesh,
    make_mesh,
    make_multislice_mesh,
)
from torchft_tpu.parallel.sharding import (  # noqa: F401
    batch_sharding,
    param_shardings,
    param_specs,
)
from torchft_tpu.parallel.pipeline import (  # noqa: F401
    gpipe_loop,
    init_pipeline_state,
    make_pipeline_loss,
    make_pipeline_train_step,
)
from torchft_tpu.parallel.ring_attention import (  # noqa: F401
    make_ring_attention,
    ring_attention_shard,
)
from torchft_tpu.parallel.ulysses import (  # noqa: F401
    make_ulysses_attention,
)
from torchft_tpu.parallel.train import (  # noqa: F401
    TrainState,
    init_train_state,
    make_eval_step,
    make_train_step,
)
