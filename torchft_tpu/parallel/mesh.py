"""Mesh construction for the inner (SPMD) axes.

Axes, in physical-locality order (outermost = slowest-varying over the
device order, so ``tp``/``sp`` land on ICI-adjacent chips):

- ``dp``   pure data parallelism (gradients all-reduced by XLA),
- ``pp``   pipeline parallelism (the stacked layer dim sharded stage-wise;
           activations ppermute stage-to-stage — parallel/pipeline.py),
- ``fsdp`` sharded data parallelism (params/opt state sharded, all-gathered
           per layer by XLA — the HSDP inner axis of BASELINE config #4),
- ``ep``   expert parallelism (MoE experts sharded over this axis; XLA
           inserts the dispatch/combine collectives from the shardings),
- ``sp``   sequence/context parallelism (ring attention over this axis),
- ``tp``   tensor parallelism (innermost: highest-bandwidth neighbors).

The fault-tolerant replica axis is deliberately NOT a mesh axis — it is the
Manager's host-side axis over DCN (see torchft_tpu/device_mesh.py).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

MESH_AXES = ("dp", "pp", "fsdp", "ep", "sp", "tp")


def make_mesh(
    dp: int = 1,
    fsdp: int = 1,
    sp: int = 1,
    tp: int = 1,
    ep: int = 1,
    pp: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    if devices is None:
        devices = jax.devices()
    n = dp * pp * fsdp * ep * sp * tp
    if len(devices) < n:
        raise ValueError(f"need {n} devices, have {len(devices)}")
    arr = np.asarray(devices[:n]).reshape(dp, pp, fsdp, ep, sp, tp)
    return Mesh(arr, MESH_AXES)


def auto_mesh(
    n_devices: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Factor ``n_devices`` into a (dp, fsdp, sp, tp) mesh that exercises
    every axis it can: hands out prime factors largest-first, each to the
    currently-smallest axis, preferring fsdp > tp > sp > dp on ties
    (matches the HSDP flagship config where fsdp carries most of the
    scaling and tp/sp stay within ICI reach)."""
    if devices is None:
        devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    sizes = {"dp": 1, "fsdp": 1, "sp": 1, "tp": 1}  # ep stays 1 here:
    # dense flagship doesn't use experts; MoE runs build make_mesh(ep=...)
    priority = ("fsdp", "tp", "sp", "dp")

    def prime_factors(n: int) -> list:
        out, d = [], 2
        while d * d <= n:
            while n % d == 0:
                out.append(d)
                n //= d
            d += 1
        if n > 1:
            out.append(n)
        return sorted(out, reverse=True)

    for f in prime_factors(n_devices):
        target = min(priority, key=lambda a: (sizes[a], priority.index(a)))
        sizes[target] *= f
    return make_mesh(
        dp=sizes["dp"],
        fsdp=sizes["fsdp"],
        sp=sizes["sp"],
        tp=sizes["tp"],
        devices=devices,
    )
