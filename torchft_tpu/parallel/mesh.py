"""Mesh construction for the inner (SPMD) axes.

Axes, in physical-locality order (outermost = slowest-varying over the
device order, so ``tp``/``sp`` land on ICI-adjacent chips):

- ``dp``   pure data parallelism (gradients all-reduced by XLA),
- ``pp``   pipeline parallelism (the stacked layer dim sharded stage-wise;
           activations ppermute stage-to-stage — parallel/pipeline.py),
- ``fsdp`` sharded data parallelism (params/opt state sharded, all-gathered
           per layer by XLA — the HSDP inner axis of BASELINE config #4),
- ``ep``   expert parallelism (MoE experts sharded over this axis; XLA
           inserts the dispatch/combine collectives from the shardings),
- ``sp``   sequence/context parallelism (ring attention over this axis),
- ``tp``   tensor parallelism (innermost: highest-bandwidth neighbors).

The fault-tolerant replica axis is deliberately NOT a mesh axis — it is the
Manager's host-side axis over DCN (see torchft_tpu/device_mesh.py).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

MESH_AXES = ("dp", "pp", "fsdp", "ep", "sp", "tp")


def make_mesh(
    dp: int = 1,
    fsdp: int = 1,
    sp: int = 1,
    tp: int = 1,
    ep: int = 1,
    pp: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    if devices is None:
        devices = jax.devices()
    n = dp * pp * fsdp * ep * sp * tp
    if len(devices) < n:
        raise ValueError(f"need {n} devices, have {len(devices)}")
    arr = np.asarray(devices[:n]).reshape(dp, pp, fsdp, ep, sp, tp)
    return Mesh(arr, MESH_AXES)


def make_multislice_mesh(
    num_slices: int,
    dp: int = 1,
    fsdp: int = 1,
    sp: int = 1,
    tp: int = 1,
    ep: int = 1,
    pp: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Mesh spanning ``num_slices`` TPU slices connected over DCN (the
    multi-pod scaling shape): the slice dimension folds into the
    OUTERMOST ``dp`` coordinate, so the only cross-slice collective XLA
    emits is the dp gradient all-reduce (which it performs
    hierarchically: reduce inside each slice over ICI, one exchange over
    DCN, broadcast back) — model axes (fsdp/ep/sp/tp/pp) never leave a
    slice's ICI domain.  ``dp`` is the per-slice data-parallel factor;
    the resulting mesh has ``dp_total = num_slices * dp``.

    On real multislice hardware devices are grouped by
    ``device.slice_index``; on a single slice or a virtual CPU platform
    (tests, dryrun) contiguous equal blocks stand in for slices.  No
    sharding rule changes: everything keyed on "dp" transparently spans
    the DCN axis.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    per = dp * pp * fsdp * ep * sp * tp
    need = num_slices * per
    if len(devices) < need:
        raise ValueError(f"need {need} devices, have {len(devices)}")
    # Group by slice BEFORE any truncation: real slices usually hold
    # more devices than ``per``, and truncating first would collapse the
    # visible slice set to one (jax.devices() orders by slice) — the
    # "multislice" mesh would then silently live inside a single slice.
    by_slice: dict = {}
    if all(
        getattr(d, "slice_index", None) is not None for d in devices
    ) and len({d.slice_index for d in devices}) >= num_slices:
        for d in devices:
            by_slice.setdefault(d.slice_index, []).append(d)
        groups = [
            sorted(v, key=lambda d: d.id)
            for _, v in sorted(by_slice.items())
        ][:num_slices]
        short = [i for i, g in enumerate(groups) if len(g) < per]
        if short:
            raise ValueError(
                f"slice(s) {short} have fewer than {per} devices"
            )
        groups = [g[:per] for g in groups]
    else:
        groups = [
            devices[i * per:(i + 1) * per] for i in range(num_slices)
        ]
    arr = np.stack(
        [
            np.asarray(g[:per]).reshape(dp, pp, fsdp, ep, sp, tp)
            for g in groups
        ]
    ).reshape(num_slices * dp, pp, fsdp, ep, sp, tp)
    return Mesh(arr, MESH_AXES)


def auto_mesh(
    n_devices: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Factor ``n_devices`` into a (dp, fsdp, sp, tp) mesh that exercises
    every axis it can: hands out prime factors largest-first, each to the
    currently-smallest axis, preferring fsdp > tp > sp > dp on ties
    (matches the HSDP flagship config where fsdp carries most of the
    scaling and tp/sp stay within ICI reach)."""
    if devices is None:
        devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    sizes = {"dp": 1, "fsdp": 1, "sp": 1, "tp": 1}  # ep stays 1 here:
    # dense flagship doesn't use experts; MoE runs build make_mesh(ep=...)
    priority = ("fsdp", "tp", "sp", "dp")

    def prime_factors(n: int) -> list:
        out, d = [], 2
        while d * d <= n:
            while n % d == 0:
                out.append(d)
                n //= d
            d += 1
        if n > 1:
            out.append(n)
        return sorted(out, reverse=True)

    for f in prime_factors(n_devices):
        target = min(priority, key=lambda a: (sizes[a], priority.index(a)))
        sizes[target] *= f
    return make_mesh(
        dp=sizes["dp"],
        fsdp=sizes["fsdp"],
        sp=sizes["sp"],
        tp=sizes["tp"],
        devices=devices,
    )
