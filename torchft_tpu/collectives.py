"""Quantized collectives for the replica (DCN) axis.

Capability parity with the reference's ``torchft/collectives.py:159-415``:
``allreduce_quantized`` cuts outer-axis gradient traffic ~4x by sending
block-quantized int8 with per-block float scales instead of float32, using
the same alltoall -> local-reduce-in-full-precision -> allgather pipeline
(sums are computed in float32, so quantization error does not accumulate
across ranks; only one quantize->dequantize round trip per value).

The reference quantizes with Triton fp8 kernels on CUDA; here the host path
is vectorized numpy int8 (DCN transfers are host-driven), and
``torchft_tpu/ops/quantization.py`` provides Pallas TPU kernels for
quantizing on-device before the device->host pull.
"""

from __future__ import annotations

import threading
from typing import List, Sequence, Tuple

import numpy as np

from torchft_tpu.process_group import ProcessGroup, ReduceOp
from torchft_tpu.work import DummyWork, FutureWork, Work

BLOCK = 512  # values per quantization scale


def _spawn_collective(fn) -> "concurrent.futures.Future":
    """One daemon thread per in-flight quantized collective. A bounded pool
    would deadlock when several ranks live in one process (tests, parameter
    server): every rank's pipeline must make progress concurrently for any
    alltoall to complete."""
    import concurrent.futures

    fut: concurrent.futures.Future = concurrent.futures.Future()

    def run() -> None:
        if not fut.set_running_or_notify_cancel():
            return
        try:
            fut.set_result(fn())
        except BaseException as e:  # noqa: BLE001 - delivered via the future
            fut.set_exception(e)

    threading.Thread(target=run, daemon=True, name="quant-collective").start()
    return fut


def quantize_blockwise(flat: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """int8-quantizes a 1-D float array with one float32 scale per BLOCK
    values (the rowwise-fp8 analog of quantization.py:44-162). Returns
    (int8 values, float32 scales)."""
    n = flat.size
    blocks = (n + BLOCK - 1) // BLOCK
    padded = np.zeros(blocks * BLOCK, dtype=np.float32)
    padded[:n] = flat
    mat = padded.reshape(blocks, BLOCK)
    scales = np.abs(mat).max(axis=1) / 127.0
    scales = np.where(scales == 0, 1.0, scales).astype(np.float32)
    q = np.clip(np.rint(mat / scales[:, None]), -127, 127).astype(np.int8)
    return q.reshape(-1), scales


def dequantize_blockwise(
    q: np.ndarray, scales: np.ndarray, n: int
) -> np.ndarray:
    mat = q.astype(np.float32).reshape(-1, BLOCK) * scales[:, None]
    return mat.reshape(-1)[:n]


def _flatten(arrays: Sequence[np.ndarray]) -> Tuple[np.ndarray, List[int]]:
    sizes = [a.size for a in arrays]
    flat = np.concatenate([a.reshape(-1).astype(np.float32) for a in arrays])
    return flat, sizes


def _unflatten_into(
    arrays: Sequence[np.ndarray], flat: np.ndarray, sizes: List[int]
) -> None:
    offset = 0
    for a, n in zip(arrays, sizes):
        a[...] = flat[offset : offset + n].reshape(a.shape).astype(
            a.dtype, copy=False
        )
        offset += n


def allreduce_quantized_jax(
    pg: ProcessGroup,
    arrays: Sequence["jax.Array"],  # noqa: F821 - imported lazily
    op: ReduceOp = ReduceOp.SUM,
    scale: float = 1.0,
) -> Work:
    """Quantized allreduce for jax device arrays: quantize ON DEVICE with the
    Pallas kernels, pull int8 + per-block scales to host (~4x fewer bytes
    than fp32 across PCIe and then DCN), run the alltoall -> fp32 local
    reduce -> allgather wire pipeline on the quantized payload, and
    dequantize ON DEVICE (reference: collectives.py:297-415, with the
    device-side quantize the Triton kernels provide there).

    Returns Work whose result is a list of NEW jax arrays (original
    shapes/dtypes), scaled by ``scale`` on device. The inputs are not
    mutated (jax arrays are immutable).
    """
    import jax
    import jax.numpy as jnp

    from torchft_tpu.ops import quantization as Q

    if op not in (ReduceOp.SUM, ReduceOp.AVG):
        raise ValueError(f"allreduce_quantized supports SUM/AVG, got {op}")
    arrays = list(arrays)
    shapes = [a.shape for a in arrays]
    dtypes = [a.dtype for a in arrays]
    sizes = [a.size for a in arrays]

    def rebuild(flat: "jax.Array") -> List["jax.Array"]:
        outs = []
        offset = 0
        for shape, dtype, size in zip(shapes, dtypes, sizes):
            outs.append(
                flat[offset : offset + size].reshape(shape).astype(dtype)
            )
            offset += size
        return outs

    flat = (
        jnp.concatenate([jnp.ravel(a).astype(jnp.float32) for a in arrays])
        if len(arrays) > 1
        else jnp.ravel(arrays[0]).astype(jnp.float32)
    )
    ws = pg.size()
    if ws <= 1:
        return DummyWork(rebuild(flat * scale) if scale != 1.0 else arrays)

    from torchft_tpu.telemetry import trace_span

    # Device quantize + int8 host pull happen on the caller's thread so the
    # payload is snapshotted before the caller mutates params further.
    with trace_span("torchft::collectives::quantize_pull"):
        q_host, s_host, n = Q.quantize_for_transfer(flat)
    total_scale = scale / ws if op == ReduceOp.AVG else scale

    def run() -> List["jax.Array"]:
        with trace_span("torchft::collectives::wire"):
            reduced = _quantized_wire_pipeline(pg, q_host, s_host, n)
        with trace_span("torchft::collectives::dequant_push"):
            if isinstance(reduced, np.ndarray):
                # Tiny payload: the local reduce already produced the full
                # fp32 sum — push it straight to device, no second lossy
                # round trip.
                out = jnp.asarray(reduced)
            else:
                q_final, s_final = reduced
                # Device-side dequantize (chunked; the sum stayed fp32 on
                # the wire pipeline so only one quantize->dequantize round
                # trip of error per value).
                out = Q.dequantize_from_transfer(q_final, s_final, n)
            if total_scale != 1.0:
                out = out * total_scale
            outs = rebuild(out)
            jax.block_until_ready(outs)
        return outs

    return FutureWork(_spawn_collective(run))


def reduce_scatter_quantized(
    pg: ProcessGroup, arrays: Sequence[np.ndarray], op: ReduceOp = ReduceOp.SUM
) -> Work:
    """Quantized reduce_scatter (reference: collectives.py:159-294): the
    alltoall + local-fp32-reduce half of the allreduce pipeline, WITHOUT the
    allgather — each rank keeps only its own reduced shard (block-aligned).

    Returns Work whose result is ``(shard, (start, end))``: this rank's
    fp32 reduced values covering flat elements ``[start, end)`` of the
    concatenated input.
    """
    if op not in (ReduceOp.SUM, ReduceOp.AVG):
        raise ValueError(f"reduce_scatter_quantized supports SUM/AVG, got {op}")
    ws = pg.size()
    arrays = list(arrays)

    def run():
        flat, _sizes = _flatten(arrays)
        n = flat.size
        if ws <= 1:
            return flat, (0, n)
        q_host, s_host = quantize_blockwise(flat)
        blocks = s_host.size
        me = pg.rank()
        counts = [len(c) for c in np.array_split(np.arange(blocks), ws)]
        starts = np.concatenate([[0], np.cumsum(counts)]) * BLOCK
        start, end = int(starts[me]), int(min(starts[me + 1], n))
        if blocks < ws:
            # Tiny payload: gather-all, reduce locally, slice my range.
            gathered = pg.allgather([q_host, s_host]).wait()
            acc = np.zeros(n, np.float32)
            for g_q, g_s in gathered:
                acc += dequantize_blockwise(g_q, g_s, n)
            shard = acc[start:end]
        else:
            q_chunks, s_chunks = [], []
            off = 0
            for c in counts:
                q_chunks.append(q_host[off * BLOCK : (off + c) * BLOCK])
                s_chunks.append(s_host[off : off + c])
                off += c
            all_q = pg.alltoall(q_chunks).wait()
            all_s = pg.alltoall(s_chunks).wait()
            n_me = counts[me] * BLOCK
            acc = np.zeros(n_me, np.float32)
            for g_q, g_s in zip(all_q, all_s):
                acc += dequantize_blockwise(g_q, g_s, n_me)
            shard = acc[: end - start]
        if op == ReduceOp.AVG:
            shard = shard / ws
        return shard, (start, end)

    return FutureWork(_spawn_collective(run))


def bucketize(arrays: Sequence[np.ndarray], cap_bytes: int) -> List[List[int]]:
    """Greedy same-dtype buckets up to ``cap_bytes`` (reference: <=32 MiB
    flat buffers, local_sgd.py:466-560 / ddp bucketing). Returns index
    groups into ``arrays``."""
    by_dtype: dict = {}
    for i, a in enumerate(arrays):
        by_dtype.setdefault(a.dtype, []).append(i)
    buckets: List[List[int]] = []
    for idxs in by_dtype.values():
        cur: List[int] = []
        size = 0
        for i in idxs:
            nbytes = arrays[i].nbytes
            if cur and size + nbytes > cap_bytes:
                buckets.append(cur)
                cur, size = [], 0
            cur.append(i)
            size += nbytes
        if cur:
            buckets.append(cur)
    return buckets


def _quantized_wire_pipeline(
    pg: ProcessGroup, q_host: np.ndarray, s_host: np.ndarray, n: int
):
    """The shared quantized-allreduce wire protocol: block-aligned alltoall
    of int8 chunks + scales -> local fp32 reduce -> requantize -> allgather.
    BOTH entry points (jax-array and numpy) use this, so replicas may mix
    input types freely — the wire format never depends on the caller's local
    array type.

    Returns (q_final, s_final) int8+scales for the full buffer, or, for tiny
    payloads (fewer blocks than ranks: allgather-all fallback, no chunking),
    the fully-reduced fp32 array of length ``n`` directly.
    """
    ws = pg.size()
    blocks = s_host.size
    if blocks < ws:
        gathered = pg.allgather([q_host, s_host]).wait()
        acc = np.zeros(n, np.float32)
        for g_q, g_s in gathered:
            acc += dequantize_blockwise(g_q, g_s, n)
        return acc
    # Contiguous block-aligned chunks so each chunk owns whole scales;
    # alltoall -> rank r reduces everyone's r-th chunk.
    counts = [len(c) for c in np.array_split(np.arange(blocks), ws)]
    q_chunks, s_chunks = [], []
    off = 0
    for c in counts:
        q_chunks.append(q_host[off * BLOCK : (off + c) * BLOCK])
        s_chunks.append(s_host[off : off + c])
        off += c
    all_q = pg.alltoall(q_chunks).wait()
    all_s = pg.alltoall(s_chunks).wait()
    me = pg.rank()
    n_me = counts[me] * BLOCK
    acc = np.zeros(n_me, np.float32)
    for g_q, g_s in zip(all_q, all_s):
        acc += dequantize_blockwise(g_q, g_s, n_me)
    rq, rs = quantize_blockwise(acc)
    gathered = pg.allgather([rq, np.asarray(rs)]).wait()
    q_final = np.concatenate([g[0] for g in gathered])
    s_final = np.concatenate([g[1] for g in gathered])
    return q_final, s_final


def allreduce_quantized(
    pg: ProcessGroup, arrays: Sequence[np.ndarray], op: ReduceOp = ReduceOp.SUM
) -> Work:
    """Quantized SUM/AVG allreduce, in place (reference:
    collectives.py:297-415). Returns async Work whose result is ``arrays``."""
    if op not in (ReduceOp.SUM, ReduceOp.AVG):
        raise ValueError(f"allreduce_quantized supports SUM/AVG, got {op}")
    ws = pg.size()
    if ws <= 1:
        return DummyWork(list(arrays))

    def run() -> List[np.ndarray]:
        flat, sizes = _flatten(arrays)
        n = flat.size
        q_host, s_host = quantize_blockwise(flat)
        reduced = _quantized_wire_pipeline(pg, q_host, s_host, n)
        if isinstance(reduced, np.ndarray):
            result = reduced
        else:
            q_final, s_final = reduced
            result = dequantize_blockwise(q_final, s_final, n)
        if op == ReduceOp.AVG:
            result /= ws
        _unflatten_into(arrays, result, sizes)
        return list(arrays)

    return FutureWork(_spawn_collective(run))
