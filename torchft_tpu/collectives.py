"""Quantized collectives for the replica (DCN) axis.

Capability parity with the reference's ``torchft/collectives.py:159-415``:
``allreduce_quantized`` cuts outer-axis gradient traffic ~4x by sending
block-quantized int8 with per-block float scales instead of float32, using
the same alltoall -> local-reduce-in-full-precision -> allgather pipeline
(sums are computed in float32, so quantization error does not accumulate
across ranks; only one quantize->dequantize round trip per value).

The reference quantizes with Triton fp8 kernels on CUDA; here the host path
is vectorized numpy int8 (DCN transfers are host-driven), and
``torchft_tpu/ops/quantization.py`` provides Pallas TPU kernels for
quantizing on-device before the device->host pull.
"""

from __future__ import annotations

import os
import threading
from typing import Callable, List, Sequence, Tuple

import numpy as np

from torchft_tpu import knobs
from torchft_tpu.process_group import ProcessGroup, ReduceOp
from torchft_tpu.work import DummyWork, FutureWork, Work

BLOCK = 512  # values per quantization scale


def _spawn_collective(fn) -> "concurrent.futures.Future":
    """One daemon thread per in-flight quantized collective. A bounded pool
    would deadlock when several ranks live in one process (tests, parameter
    server): every rank's pipeline must make progress concurrently for any
    alltoall to complete."""
    import concurrent.futures

    fut: concurrent.futures.Future = concurrent.futures.Future()

    def run() -> None:
        if not fut.set_running_or_notify_cancel():
            return
        try:
            fut.set_result(fn())
        except BaseException as e:  # noqa: BLE001 - delivered via the future
            fut.set_exception(e)

    threading.Thread(target=run, daemon=True, name="quant-collective").start()
    return fut


# Host-side (de)quantize runs chunk-parallel on threads: numpy ufuncs
# release the GIL on large arrays, so this scales with cores — measured
# 125M elements: 16.3s -> ~2s single-pass in-place math across 8 threads.
# Param-sized DiLoCo pseudograds make this the peer-side critical path of
# the quantized outer allreduce.
_HOST_QUANT_CHUNK = 8 * 1024 * 1024  # elements per parallel task
_host_pool = None
_host_pool_lock = threading.Lock()


def _pool():
    global _host_pool
    with _host_pool_lock:
        if _host_pool is None:
            import concurrent.futures

            _host_pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=min(8, os.cpu_count() or 4),
                thread_name_prefix="quant-host",
            )
        return _host_pool


def _parallel_over_blocks(n_blocks: int, fn) -> None:
    """Runs fn(block_start, block_end) over block ranges in parallel."""
    blocks_per_task = max(_HOST_QUANT_CHUNK // BLOCK, 1)
    if n_blocks <= blocks_per_task:
        fn(0, n_blocks)
        return
    tasks = []
    for start in range(0, n_blocks, blocks_per_task):
        tasks.append(
            _pool().submit(fn, start, min(start + blocks_per_task, n_blocks))
        )
    for t in tasks:
        t.result()


def _qmax(bits: int) -> float:
    """Symmetric integer range: 127 for int8, 7 for int4."""
    if bits == 8:
        return 127.0
    if bits == 4:
        return 7.0
    raise ValueError(f"unsupported quantization width: {bits} bits")


def pack_nibbles(q: np.ndarray) -> np.ndarray:
    """Packs int8 values in [-7, 7] two-per-byte (two's-complement 4-bit
    nibbles; even index -> low nibble). Wire format of the ``bits=4``
    codec — halves outer-axis bytes vs int8 (the reference's fp8 is
    8-bit; 4-bit matches the Streaming-DiLoCo-style compressed outer
    sync)."""
    u = q.astype(np.uint8) & 0xF
    return (u[0::2] | (u[1::2] << 4)).view(np.int8)


def unpack_nibbles(p: np.ndarray, n_vals: int) -> np.ndarray:
    """Inverse of :func:`pack_nibbles`; returns int8 values of length
    ``n_vals`` with sign extension."""
    u = p.view(np.uint8)
    out = np.empty(u.size * 2, dtype=np.uint8)
    out[0::2] = u & 0xF
    out[1::2] = u >> 4
    # Two's-complement sign extension of the 4-bit field.
    out = ((out ^ 8).astype(np.int8) - 8)
    return out[:n_vals]


def quantize_blockwise(
    flat: np.ndarray, bits: int = 8
) -> Tuple[np.ndarray, np.ndarray]:
    """Block-quantizes a 1-D float array with one float32 scale per BLOCK
    values (the rowwise-fp8 analog of quantization.py:44-162). Returns
    (int8 payload, float32 scales); with ``bits=4`` the payload is
    nibble-packed (BLOCK/2 bytes per block)."""
    n = flat.size
    qmax = _qmax(bits)
    blocks = (n + BLOCK - 1) // BLOCK
    q = np.empty(blocks * BLOCK, dtype=np.int8)
    scales = np.empty(blocks, dtype=np.float32)
    flat = np.ascontiguousarray(flat, dtype=np.float32)

    def work(b0: int, b1: int) -> None:
        lo, hi = b0 * BLOCK, min(b1 * BLOCK, n)
        chunk = flat[lo:hi]
        pad = b1 * BLOCK - lo
        if pad != chunk.size:  # tail: pad to whole blocks
            padded = np.zeros(pad, dtype=np.float32)
            padded[: chunk.size] = chunk
            chunk = padded
        mat = chunk.reshape(b1 - b0, BLOCK)
        s = np.abs(mat).max(axis=1)
        s /= qmax
        np.copyto(s, 1.0, where=(s == 0))
        scales[b0:b1] = s
        # In-place pipeline: one fp32 temporary for the chunk only.
        buf = mat / s[:, None]
        np.rint(buf, out=buf)
        np.clip(buf, -qmax, qmax, out=buf)
        q[b0 * BLOCK : b1 * BLOCK] = buf.reshape(-1)

    _parallel_over_blocks(blocks, work)
    if bits == 4:
        return pack_nibbles(q), scales
    return q, scales


def dequantize_blockwise(
    q: np.ndarray, scales: np.ndarray, n: int, bits: int = 8
) -> np.ndarray:
    blocks = scales.size
    if bits == 4:
        q = unpack_nibbles(q, blocks * BLOCK)
    out = np.empty(blocks * BLOCK, dtype=np.float32)

    def work(b0: int, b1: int) -> None:
        mat = q[b0 * BLOCK : b1 * BLOCK].astype(np.float32).reshape(
            b1 - b0, BLOCK
        )
        mat *= scales[b0:b1, None]
        out[b0 * BLOCK : b1 * BLOCK] = mat.reshape(-1)

    _parallel_over_blocks(blocks, work)
    return out[:n]


def _flatten(arrays: Sequence[np.ndarray]) -> Tuple[np.ndarray, List[int]]:
    sizes = [a.size for a in arrays]
    flat = np.concatenate([a.reshape(-1).astype(np.float32) for a in arrays])
    return flat, sizes


def _unflatten_into(
    arrays: Sequence[np.ndarray], flat: np.ndarray, sizes: List[int]
) -> None:
    offset = 0
    for a, n in zip(arrays, sizes):
        a[...] = flat[offset : offset + n].reshape(a.shape).astype(
            a.dtype, copy=False
        )
        offset += n


def allreduce_quantized_jax(
    pg: ProcessGroup,
    arrays: Sequence["jax.Array"],  # noqa: F821 - imported lazily
    op: ReduceOp = ReduceOp.SUM,
    scale: float = 1.0,
    bits: int = 8,
) -> Work:
    """Quantized allreduce for jax device arrays: quantize ON DEVICE with the
    Pallas kernels, pull int8 + per-block scales to host (~4x fewer bytes
    than fp32 across PCIe and then DCN), run the alltoall -> fp32 local
    reduce -> allgather wire pipeline on the quantized payload, and
    dequantize ON DEVICE (reference: collectives.py:297-415, with the
    device-side quantize the Triton kernels provide there).

    Returns Work whose result is a list of NEW jax arrays (original
    shapes/dtypes), scaled by ``scale`` on device. The inputs are not
    mutated (jax arrays are immutable).
    """
    import jax
    import jax.numpy as jnp

    from torchft_tpu.ops import quantization as Q

    if op not in (ReduceOp.SUM, ReduceOp.AVG):
        raise ValueError(f"allreduce_quantized supports SUM/AVG, got {op}")
    arrays = list(arrays)
    shapes = [a.shape for a in arrays]
    dtypes = [a.dtype for a in arrays]
    sizes = [a.size for a in arrays]

    def rebuild(flat: "jax.Array") -> List["jax.Array"]:
        outs = []
        offset = 0
        for shape, dtype, size in zip(shapes, dtypes, sizes):
            outs.append(
                flat[offset : offset + size].reshape(shape).astype(dtype)
            )
            offset += size
        return outs

    if len(arrays) > 1:
        flat = jnp.concatenate(
            [jnp.ravel(a).astype(jnp.float32) for a in arrays]
        )
    else:
        flat = jnp.ravel(arrays[0]).astype(jnp.float32)
    ws = pg.size()
    if ws <= 1:
        return DummyWork(rebuild(flat * scale) if scale != 1.0 else arrays)
    a0 = arrays[0]
    if len(arrays) == 1 and a0.ndim == 1 and a0.dtype == jnp.float32:
        # ravel/astype both short-circuited, so ``flat`` aliases the
        # caller's buffer.  Parts of the pipeline touch ``flat`` after
        # this call returns (host path: the deferred host pull; device
        # path: quantize kernels already enqueued but not yet executed)
        # while the caller's next train step may DONATE this buffer
        # (make_train_step and bench.py both donate), deleting it
        # mid-use.  Materialize an independent device snapshot before
        # returning to the caller.
        # (Below the ws<=1 return: the single-replica path never defers.)
        flat = jnp.copy(flat)

    from torchft_tpu.telemetry import trace_span

    total_scale = scale / ws if op == ReduceOp.AVG else scale

    # On TPU the Pallas kernels quantize/dequantize ON DEVICE (int8 over
    # PCIe, ~4x fewer bytes).  Off-TPU those same kernels would run
    # through the Pallas INTERPRETER — a test shim, seconds per MB — so
    # the compiled-CPU deployment path is the vectorized host quantizer
    # (same wire format bit-for-bit; the bench peer already uses it for
    # exactly this reason).  TORCHFT_FORCE_DEVICE_QUANT forces the
    # device path anyway (Pallas interpreter off-TPU; a no-op on TPU,
    # where the device path is already taken): the cross-path
    # wire-equality test drives it.
    force_device = knobs.get_bool("TORCHFT_FORCE_DEVICE_QUANT")
    host_quant = jax.default_backend() != "tpu" and not force_device

    # Device path: dispatch the quantize kernels NOW, on the caller's
    # thread. Async dispatch returns immediately, but enqueues the kernels
    # right behind the compute that produced ``flat`` — BEFORE the
    # caller's next training window. The deferred host pull then overlaps
    # that window; dispatched lazily from the collective thread instead,
    # the kernels would queue behind the whole next window and the "pull"
    # would spend its time waiting on unrelated compute (measured 24 s of
    # a 3 s transfer in BENCH_TPU_r03).
    q_chunks = None
    n_elems = 0
    if not host_quant:
        q_chunks, n_elems = Q.quantize_for_transfer_async(flat, bits)
        # The enqueued kernels hold their own reference to the snapshot;
        # don't let the run() closure pin the full fp32 copy across the
        # multi-second wire pipeline too.
        flat = None

    def run() -> List["jax.Array"]:
        with trace_span("torchft::collectives::quantize_pull"):
            if host_quant:
                flat_host = np.asarray(flat, dtype=np.float32)
                n = flat_host.size
                q_host, s_host = quantize_blockwise(flat_host, bits)
            else:
                q_host, s_host, n = Q.pull_transfer_chunks(
                    q_chunks, n_elems, bits
                )
        with trace_span("torchft::collectives::wire"):
            reduced = _quantized_wire_pipeline(pg, q_host, s_host, n, bits)
        with trace_span("torchft::collectives::dequant_push"):
            if isinstance(reduced, np.ndarray):
                # Tiny payload: the local reduce already produced the full
                # fp32 sum — push it straight to device, no second lossy
                # round trip.
                out = jnp.asarray(reduced)
            else:
                q_final, s_final = reduced
                if host_quant:
                    out = jnp.asarray(
                        dequantize_blockwise(q_final, s_final, n, bits)
                    )
                else:
                    # Device-side dequantize (chunked; the sum stayed fp32
                    # on the wire pipeline so only one quantize->dequantize
                    # round trip of error per value).
                    out = Q.dequantize_from_transfer(
                        q_final, s_final, n, bits
                    )
            if total_scale != 1.0:
                out = out * total_scale
            outs = rebuild(out)
            # BOTH backends: leave the final device arrays async-dispatched.
            # On CPU the dequantize itself already ran on the host above, so
            # every real error class (wire, shape, quantize, reduce) has
            # latched by this point; the only thing a block_until_ready here
            # would add is latching execution faults of the trivial
            # elementwise rebuild ops — and on a 1-core box it DRAINS THE
            # DEVICE QUEUE through the caller's whole in-flight training
            # window (measured: a 0.05 MB fragment's "dequant_push" span at
            # 14.7 s in BENCH_r04, with a 3.1 s exposed tail in the
            # caller's wait), turning the overlapped sync into a serialized
            # one.  The r03 TPU rationale below now applies everywhere.
            #
            # TPU: leave the dequantize async-dispatched. Its execution
            # naturally queues behind whatever window the caller has in
            # flight, and wait() returning a not-yet-executed array is
            # exactly XLA's async-dispatch contract — blocking here would
            # re-serialize the window we just overlapped.
            #
            # FT error-latch boundary under async dispatch: everything
            # DISPATCH-time still raises here on the collective thread and
            # latches (shape errors, and HBM OOM — PJRT allocates output
            # buffers at dispatch, so the big fp32 allocation in
            # dequantize_from_transfer fails synchronously).  Only an
            # EXECUTION-time device fault defers to the caller's next
            # materialize, outside the latch — for static-shaped
            # elementwise kernels on TPU there is no analog of CUDA's
            # illegal-access class, so that residue is accepted as the
            # price of the overlap.
        return outs

    return FutureWork(_spawn_collective(run))


def reduce_scatter_quantized(
    pg: ProcessGroup,
    arrays: Sequence[np.ndarray],
    op: ReduceOp = ReduceOp.SUM,
    bits: int = 8,
) -> Work:
    """Quantized reduce_scatter (reference: collectives.py:159-294): the
    alltoall + local-fp32-reduce half of the allreduce pipeline, WITHOUT the
    allgather — each rank keeps only its own reduced shard (block-aligned).

    Returns Work whose result is ``(shard, (start, end))``: this rank's
    fp32 reduced values covering flat elements ``[start, end)`` of the
    concatenated input.
    """
    if op not in (ReduceOp.SUM, ReduceOp.AVG):
        raise ValueError(f"reduce_scatter_quantized supports SUM/AVG, got {op}")
    ws = pg.size()
    arrays = list(arrays)

    def run():
        flat, _sizes = _flatten(arrays)
        n = flat.size
        if ws <= 1:
            return flat, (0, n)
        q_host, s_host = quantize_blockwise(flat, bits)
        blocks = s_host.size
        me = pg.rank()
        counts = [len(c) for c in np.array_split(np.arange(blocks), ws)]
        starts = np.concatenate([[0], np.cumsum(counts)]) * BLOCK
        start, end = int(starts[me]), int(min(starts[me + 1], n))
        if blocks < ws:
            # Tiny payload: gather-all, reduce locally, slice my range.
            gathered = pg.allgather([q_host, s_host]).wait()
            acc = np.zeros(n, np.float32)
            for g_q, g_s in gathered:
                acc += dequantize_blockwise(g_q, g_s, n, bits)
            shard = acc[start:end]
        else:
            acc = _alltoall_chunk_reduce(pg, q_host, s_host, counts, bits)
            shard = acc[: end - start]
        if op == ReduceOp.AVG:
            shard = shard / ws
        return shard, (start, end)

    return FutureWork(_spawn_collective(run))


def bucketize(arrays: Sequence[np.ndarray], cap_bytes: int) -> List[List[int]]:
    """Greedy same-dtype buckets up to ``cap_bytes`` (reference: <=32 MiB
    flat buffers, local_sgd.py:466-560 / ddp bucketing). Returns index
    groups into ``arrays``."""
    by_dtype: dict = {}
    for i, a in enumerate(arrays):
        by_dtype.setdefault(a.dtype, []).append(i)
    buckets: List[List[int]] = []
    for idxs in by_dtype.values():
        cur: List[int] = []
        size = 0
        for i in idxs:
            nbytes = arrays[i].nbytes
            if cur and size + nbytes > cap_bytes:
                buckets.append(cur)
                cur, size = [], 0
            cur.append(i)
            size += nbytes
        if cur:
            buckets.append(cur)
    return buckets


class ErrorFeedback:
    """Replica-local error-feedback residual store for quantized
    collectives (host path).

    Each sync, the caller compensates its payload with the residual the
    previous sync's quantizer dropped, and the ``on_local_quantized``
    hook (running on the collective thread) records what THIS
    quantization drops.  Residuals never cross the wire — each replica
    ships its own compensated payload — so cross-replica bitwise
    equality of the reduced result is unaffected.

    Heal safety: ``clear()`` bumps a generation counter, and a hook
    created before the clear drops its write — an in-flight allreduce
    issued pre-heal cannot re-insert a stale pre-heal residual after
    the store was reset (the collective thread races the heal
    otherwise).  Reference ceiling is 8-bit fp8 with no feedback
    (torchft/collectives.py:297-415); feedback is what makes <=4-bit
    wire widths usable across many rounds.
    """

    def __init__(self, bits: int) -> None:
        self._bits = bits
        self._residuals: dict = {}
        self._generation = 0
        self._lock = threading.Lock()

    def compensate(self, key, flat: np.ndarray) -> np.ndarray:
        """Returns ``flat`` plus the stored residual for ``key`` (no-op
        when absent or shape-mismatched, e.g. after a re-bucketing)."""
        r = self._residuals.get(key)
        if r is not None and r.size == flat.size:
            return flat + r
        return flat

    def make_hook(self, key) -> Callable:
        """Builds the ``on_local_quantized(wire_flat, q, s)`` callback
        that stores the new residual, pinned to the CURRENT generation."""
        gen = self._generation

        def on_local_quantized(wire_flat, q, s):  # collective thread
            residual = wire_flat - dequantize_blockwise(
                q, s, wire_flat.size, self._bits
            )
            with self._lock:
                if self._generation == gen:
                    self._residuals[key] = residual

        return on_local_quantized

    def clear(self) -> None:
        """Drops all residuals AND invalidates in-flight hooks (heal)."""
        with self._lock:
            self._generation += 1
            self._residuals.clear()

    def __bool__(self) -> bool:
        return bool(self._residuals)


def _alltoall_chunk_reduce(
    pg: ProcessGroup,
    q_host: np.ndarray,
    s_host: np.ndarray,
    counts: "List[int]",
    bits: int,
) -> np.ndarray:
    """Shared wire step of both quantized collectives: split the payload
    into per-rank block-aligned chunks, alltoall, and dequantize-accumulate
    every peer's contribution for MY chunk in fp32. Returns the fp32 sum of
    this rank's chunk (counts[rank] * BLOCK values, padded)."""
    bpb = BLOCK // (8 // bits)  # payload bytes per block
    q_chunks, s_chunks = [], []
    off = 0
    for c in counts:
        q_chunks.append(q_host[off * bpb : (off + c) * bpb])
        s_chunks.append(s_host[off : off + c])
        off += c
    all_q = pg.alltoall(q_chunks).wait()
    all_s = pg.alltoall(s_chunks).wait()
    me = pg.rank()
    n_me = counts[me] * BLOCK
    acc = np.zeros(n_me, np.float32)
    for g_q, g_s in zip(all_q, all_s):
        acc += dequantize_blockwise(g_q, g_s, n_me, bits)
    return acc


def _quantized_wire_pipeline(
    pg: ProcessGroup,
    q_host: np.ndarray,
    s_host: np.ndarray,
    n: int,
    bits: int = 8,
):
    """The shared quantized-allreduce wire protocol: block-aligned alltoall
    of int8 chunks + scales -> local fp32 reduce -> requantize -> allgather.
    BOTH entry points (jax-array and numpy) use this, so replicas may mix
    input types freely — the wire format never depends on the caller's local
    array type.

    Returns (q_final, s_final) int8+scales for the full buffer, or, for tiny
    payloads (fewer blocks than ranks: allgather-all fallback, no chunking),
    the fully-reduced fp32 array of length ``n`` directly.
    """
    ws = pg.size()
    blocks = s_host.size
    if blocks < ws:
        gathered = pg.allgather([q_host, s_host]).wait()
        acc = np.zeros(n, np.float32)
        for g_q, g_s in gathered:
            acc += dequantize_blockwise(g_q, g_s, n, bits)
        return acc
    # Contiguous block-aligned chunks so each chunk owns whole scales;
    # alltoall -> rank r reduces everyone's r-th chunk.
    counts = [len(c) for c in np.array_split(np.arange(blocks), ws)]
    acc = _alltoall_chunk_reduce(pg, q_host, s_host, counts, bits)
    rq, rs = quantize_blockwise(acc, bits)
    gathered = pg.allgather([rq, np.asarray(rs)]).wait()
    q_final = np.concatenate([g[0] for g in gathered])
    s_final = np.concatenate([g[1] for g in gathered])
    return q_final, s_final


def allreduce_quantized(
    pg: ProcessGroup,
    arrays: Sequence[np.ndarray],
    op: ReduceOp = ReduceOp.SUM,
    bits: int = 8,
    on_local_quantized: "Callable | None" = None,
) -> Work:
    """Quantized SUM/AVG allreduce, in place (reference:
    collectives.py:297-415). Returns async Work whose result is ``arrays``.
    ``bits=4`` nibble-packs the wire payload (half the bytes of int8).

    ``on_local_quantized(flat, q, scales)`` is invoked on the collective
    thread right after THIS rank's payload is quantized — DiLoCo's
    error-feedback residual (flat - dequantize(q, s)) hooks in here, so
    the payload is quantized exactly once and the residual math stays off
    the training thread. The callback sees the flat that actually hit the
    wire (zeros on a non-participating replica)."""
    if op not in (ReduceOp.SUM, ReduceOp.AVG):
        raise ValueError(f"allreduce_quantized supports SUM/AVG, got {op}")
    ws = pg.size()
    if ws <= 1:
        return DummyWork(list(arrays))

    from torchft_tpu.telemetry import trace_span

    def run() -> List[np.ndarray]:
        # Same span names as the device (jax) path so bench/telemetry
        # consumers see one uniform phase decomposition: "quantize_pull"
        # is the host quantize here (there is no device pull), "wire" the
        # alltoall-reduce-allgather pipeline, "dequant_push" the decode +
        # write-back.
        with trace_span("torchft::collectives::quantize_pull"):
            flat, sizes = _flatten(arrays)
            n = flat.size
            q_host, s_host = quantize_blockwise(flat, bits)
            if on_local_quantized is not None:
                on_local_quantized(flat, q_host, s_host)
        with trace_span("torchft::collectives::wire"):
            reduced = _quantized_wire_pipeline(pg, q_host, s_host, n, bits)
        with trace_span("torchft::collectives::dequant_push"):
            if isinstance(reduced, np.ndarray):
                result = reduced
            else:
                q_final, s_final = reduced
                result = dequantize_blockwise(q_final, s_final, n, bits)
            if op == ReduceOp.AVG:
                result /= ws
            _unflatten_into(arrays, result, sizes)
        return list(arrays)

    return FutureWork(_spawn_collective(run))
