"""Quantized collectives for the replica (DCN) axis.

Capability parity with the reference's ``torchft/collectives.py:159-415``:
``allreduce_quantized`` cuts outer-axis gradient traffic ~4x by sending
block-quantized int8 with per-block float scales instead of float32, using
the same alltoall -> local-reduce-in-full-precision -> allgather pipeline
(sums are computed in float32, so quantization error does not accumulate
across ranks; only one quantize->dequantize round trip per value).

The reference quantizes with Triton fp8 kernels on CUDA; here the host path
is vectorized numpy int8 (DCN transfers are host-driven), and
``torchft_tpu/ops/quantization.py`` provides Pallas TPU kernels for
quantizing on-device before the device->host pull.
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional, Sequence, Tuple

import numpy as np

from torchft_tpu.process_group import ProcessGroup, ReduceOp
from torchft_tpu.work import DummyWork, FutureWork, Work

BLOCK = 512  # values per quantization scale

_EXECUTOR: Optional[ThreadPoolExecutor] = None
_EXECUTOR_LOCK = threading.Lock()


def _executor() -> ThreadPoolExecutor:
    global _EXECUTOR
    with _EXECUTOR_LOCK:
        if _EXECUTOR is None:
            _EXECUTOR = ThreadPoolExecutor(
                max_workers=2, thread_name_prefix="quant-collective"
            )
        return _EXECUTOR


def quantize_blockwise(flat: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """int8-quantizes a 1-D float array with one float32 scale per BLOCK
    values (the rowwise-fp8 analog of quantization.py:44-162). Returns
    (int8 values, float32 scales)."""
    n = flat.size
    blocks = (n + BLOCK - 1) // BLOCK
    padded = np.zeros(blocks * BLOCK, dtype=np.float32)
    padded[:n] = flat
    mat = padded.reshape(blocks, BLOCK)
    scales = np.abs(mat).max(axis=1) / 127.0
    scales = np.where(scales == 0, 1.0, scales).astype(np.float32)
    q = np.clip(np.rint(mat / scales[:, None]), -127, 127).astype(np.int8)
    return q.reshape(-1), scales


def dequantize_blockwise(
    q: np.ndarray, scales: np.ndarray, n: int
) -> np.ndarray:
    mat = q.astype(np.float32).reshape(-1, BLOCK) * scales[:, None]
    return mat.reshape(-1)[:n]


def _flatten(arrays: Sequence[np.ndarray]) -> Tuple[np.ndarray, List[int]]:
    sizes = [a.size for a in arrays]
    flat = np.concatenate([a.reshape(-1).astype(np.float32) for a in arrays])
    return flat, sizes


def _unflatten_into(
    arrays: Sequence[np.ndarray], flat: np.ndarray, sizes: List[int]
) -> None:
    offset = 0
    for a, n in zip(arrays, sizes):
        a[...] = flat[offset : offset + n].reshape(a.shape).astype(
            a.dtype, copy=False
        )
        offset += n


def allreduce_quantized(
    pg: ProcessGroup, arrays: Sequence[np.ndarray], op: ReduceOp = ReduceOp.SUM
) -> Work:
    """Quantized SUM/AVG allreduce, in place (reference:
    collectives.py:297-415). Returns async Work whose result is ``arrays``."""
    if op not in (ReduceOp.SUM, ReduceOp.AVG):
        raise ValueError(f"allreduce_quantized supports SUM/AVG, got {op}")
    ws = pg.size()
    if ws <= 1:
        return DummyWork(list(arrays))

    def run() -> List[np.ndarray]:
        flat, sizes = _flatten(arrays)
        rank_chunks = np.array_split(flat, ws)
        chunk_sizes = [c.size for c in rank_chunks]
        # Quantize my copy of every rank's chunk, alltoall so rank j gets
        # everyone's j-th chunk.
        qs, ss = zip(*(quantize_blockwise(c) for c in rank_chunks))
        all_q = pg.alltoall(list(qs)).wait()
        all_s = pg.alltoall([np.asarray(s) for s in ss]).wait()
        # Local reduce in float32 (error does not compound across ranks).
        me = pg.rank()
        n_me = chunk_sizes[me]
        acc = np.zeros(n_me, dtype=np.float32)
        for q, s in zip(all_q, all_s):
            acc += dequantize_blockwise(q, s, n_me)
        if op == ReduceOp.AVG:
            acc /= ws
        # Re-quantize the reduced chunk and allgather.
        rq, rs = quantize_blockwise(acc)
        gathered = pg.allgather([rq, np.asarray(rs)]).wait()
        pieces = [
            dequantize_blockwise(gq, gs, chunk_sizes[r])
            for r, (gq, gs) in enumerate(gathered)
        ]
        result = np.concatenate(pieces)
        _unflatten_into(arrays, result, sizes)
        return list(arrays)

    return FutureWork(_executor().submit(run))
